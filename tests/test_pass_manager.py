"""paddle_tpu.analysis.pass_manager — the uniform IR pass framework
(ROADMAP item 5): registry round-trips, dependency ordering, analysis-cache
reuse vs invalidation-after-transform, the pre/post verification bracket,
the PT700s/PT710s/PT720s static-check families (positive + negative
controls each), the opt-in DCE transform's fidelity witness, and the
executor hooks routing through the manager."""
import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.unique_name as un
from paddle_tpu import monitor
from paddle_tpu.analysis import (ALL_ANALYSIS_PASSES, VERIFY_PASSES,
                                 PassContext, PassManager,
                                 PassVerificationError,
                                 ProgramVerificationError, Severity,
                                 check_program, dce_program,
                                 default_pass_manager, get_pass_registry,
                                 register_pass, verify_program)
from paddle_tpu.analysis.pass_manager import ANALYSIS, TRANSFORM
from paddle_tpu.core import registry as op_registry


def codes_of(diags):
    return {d.code for d in diags}


def run_passes(prog, passes, fetches=(), feeds=(), verify="none"):
    return default_pass_manager().run_pipeline(
        prog, passes, feed_names=list(feeds), fetch_names=list(fetches),
        verify=verify)


def _mlp_program():
    main, startup = fluid.Program(), fluid.Program()
    with un.guard(), fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        h = fluid.layers.fc(x, 8, act="relu")
        pred = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


# ---------------------------------------------------------------------------
# registry: round-trip, duplicates, isolation
# ---------------------------------------------------------------------------

def test_builtin_passes_registered():
    names = get_pass_registry().names()
    for n in ALL_ANALYSIS_PASSES + ("auto_remat", "dce"):
        assert n in names, n
    assert tuple(VERIFY_PASSES) == tuple(
        fluid.analysis.DEFAULT_PASSES)  # the pre-manager pipeline survives


def test_register_custom_pass_roundtrip():
    seen = []

    @register_pass("pm_test_custom")
    def my_pass(program, ctx):
        seen.append(sum(len(b.ops) for b in program.blocks))
        return "custom-result"

    assert get_pass_registry().has("pm_test_custom")
    main, _, loss = _mlp_program()
    res = run_passes(main, ("pm_test_custom",), fetches=[loss.name])
    assert res.values["pm_test_custom"] == "custom-result"
    assert seen and seen[0] > 0
    # verify_program accepts registered custom pass names too
    verify_program(main, fetch_names=[loss.name],
                   passes=("schema", "pm_test_custom"))
    assert len(seen) == 2


def test_registry_snapshot_restore_drops_custom_pass():
    reg = get_pass_registry()
    snap = reg.snapshot()

    @register_pass("pm_test_leaky")
    def leaky(program, ctx):
        return None

    assert reg.has("pm_test_leaky")
    reg.restore(snap)
    assert not reg.has("pm_test_leaky")
    assert reg.has("schema")  # builtins survive the restore


def test_duplicate_registration_rejected_override_allowed():
    @register_pass("pm_test_dup")
    def first(program, ctx):
        return 1

    with pytest.raises(ValueError, match="already registered"):
        @register_pass("pm_test_dup")
        def second(program, ctx):
            return 2

    @register_pass("pm_test_dup", override=True)
    def third(program, ctx):
        return 3

    main, _, loss = _mlp_program()
    assert run_passes(main, ("pm_test_dup",)).values["pm_test_dup"] == 3


def test_unknown_pass_raises_keyerror():
    main, _, _ = _mlp_program()
    with pytest.raises(KeyError, match="unknown pass"):
        run_passes(main, ("definitely_not_registered",))
    with pytest.raises(KeyError):
        verify_program(main, passes=("nope",))


# ---------------------------------------------------------------------------
# dependency ordering + cycles
# ---------------------------------------------------------------------------

def test_dependency_ordering():
    order = []

    @register_pass("pm_test_base")
    def base(program, ctx):
        order.append("base")

    @register_pass("pm_test_mid", requires=("pm_test_base",))
    def mid(program, ctx):
        order.append("mid")

    @register_pass("pm_test_top", requires=("pm_test_mid",))
    def top(program, ctx):
        order.append("top")

    main, _, _ = _mlp_program()
    mgr = default_pass_manager()
    # requesting only the top pass pulls the chain in dependency order
    assert mgr.resolve(("pm_test_top",)) == [
        "pm_test_base", "pm_test_mid", "pm_test_top"]
    run_passes(main, ("pm_test_top",))
    assert order == ["base", "mid", "top"]
    # builtin deps: donation_race pulls liveness ahead of itself
    r = mgr.resolve(("donation_race",))
    assert r.index("liveness") < r.index("donation_race")


def test_dependency_cycle_detected():
    @register_pass("pm_test_cyc_a", requires=("pm_test_cyc_b",))
    def a(program, ctx):
        pass

    @register_pass("pm_test_cyc_b", requires=("pm_test_cyc_a",))
    def b(program, ctx):
        pass

    main, _, _ = _mlp_program()
    with pytest.raises(ValueError, match="cycle"):
        run_passes(main, ("pm_test_cyc_a",))


# ---------------------------------------------------------------------------
# analysis cache: shared across passes, dropped by transforms
# ---------------------------------------------------------------------------

def test_analysis_cache_shared_across_dependents():
    calls = []

    @register_pass("pm_test_count")
    def count(program, ctx):
        calls.append(1)
        return len(calls)

    @register_pass("pm_test_dep1", requires=("pm_test_count",))
    def dep1(program, ctx):
        return ctx.analysis("pm_test_count")

    @register_pass("pm_test_dep2", requires=("pm_test_count",))
    def dep2(program, ctx):
        return ctx.analysis("pm_test_count")

    main, _, loss = _mlp_program()
    res = run_passes(main, ("pm_test_dep1", "pm_test_dep2"),
                     fetches=[loss.name])
    assert len(calls) == 1  # one shared run serves both dependents
    assert res.values["pm_test_dep1"] == res.values["pm_test_dep2"] == 1


def test_transform_invalidates_analysis_cache():
    calls = []

    @register_pass("pm_test_count2")
    def count(program, ctx):
        calls.append(1)
        return len(calls)

    @register_pass("pm_test_clone", kind=TRANSFORM)
    def clone_t(program, ctx):
        return program.clone()

    @register_pass("pm_test_after", requires=("pm_test_count2",))
    def after(program, ctx):
        return ctx.analysis("pm_test_count2")

    main, _, loss = _mlp_program()
    res = run_passes(main, ("pm_test_count2", "pm_test_clone",
                            "pm_test_after"), fetches=[loss.name])
    # the transform swapped the program -> the cached analysis was dropped
    # and recomputed on the rebuilt program
    assert len(calls) == 2
    assert res.changed and res.program is not main


def test_transform_with_narrow_invalidation_keeps_other_analyses():
    calls = []

    @register_pass("pm_test_count3")
    def count(program, ctx):
        calls.append(1)
        return len(calls)

    @register_pass("pm_test_clone2", kind=TRANSFORM,
                   invalidates=("something_else",))
    def clone_t(program, ctx):
        return program.clone()

    @register_pass("pm_test_after3", requires=("pm_test_count3",))
    def after(program, ctx):
        return ctx.analysis("pm_test_count3")

    main, _, loss = _mlp_program()
    run_passes(main, ("pm_test_count3", "pm_test_clone2",
                      "pm_test_after3"), fetches=[loss.name])
    assert len(calls) == 1  # declared invalidation spared the cache


# ---------------------------------------------------------------------------
# pre/post verification: the pipeline invariant
# ---------------------------------------------------------------------------

def _register_corrupting_pass(name="pm_test_corrupt"):
    @register_pass(name, kind=TRANSFORM)
    def corrupt(program, ctx):
        p = program.clone()
        op = next(o for o in p.global_block.ops if o.type == "relu")
        del op.inputs["X"]  # PT101: required input slot now absent
        return p

    return name


def test_strict_verify_catches_corrupting_transform():
    main, _, loss = _mlp_program()
    name = _register_corrupting_pass()
    with pytest.raises(PassVerificationError) as ei:
        run_passes(main, (name,), fetches=[loss.name], verify="strict")
    assert ei.value.pass_name == name
    assert "PT101" in str(ei.value)
    # PassVerificationError is a ProgramVerificationError: existing
    # callers' except clauses keep working
    assert isinstance(ei.value, ProgramVerificationError)
    # without the bracket the corrupt program sails through
    res = run_passes(main, (name,), fetches=[loss.name], verify="none")
    assert res.changed


def test_check_program_level2_gates_transform_pipelines():
    from paddle_tpu.analysis.pass_manager import run_transform_pipeline

    main, _, loss = _mlp_program()
    name = _register_corrupting_pass("pm_test_corrupt2")
    prev = fluid.get_flags(["FLAGS_check_program"])
    fluid.set_flags({"FLAGS_check_program": 2})
    try:
        with pytest.raises(PassVerificationError):
            run_transform_pipeline(main, (name,), fetch_names=[loss.name])
        # level 1: pre-run verification only, no transform bracket
        fluid.set_flags({"FLAGS_check_program": 1})
        res = run_transform_pipeline(main, (name,),
                                     fetch_names=[loss.name])
        assert res.changed
    finally:
        fluid.set_flags(prev)


def test_strict_verify_survives_op_renumbering():
    """Pre-existing errors whose MESSAGES embed op indices (PT200's
    'produced later (op N)') must not look new after a transform merely
    shifts indices — the baseline compares per-code counts."""
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        blk = p.global_block
        blk.create_var(name="late", shape=[2], dtype="float32")
        early = fluid.layers.scale(blk.var("late"), scale=1.0)  # PT200
        blk.append_op("fill_constant", outputs={"Out": ["late"]},
                      attrs={"shape": [2], "dtype": "float32",
                             "value": 1.0})

    @register_pass("pm_test_prepend", kind=TRANSFORM)
    def prepend(program, ctx):
        q = program.clone()
        q.global_block.create_var(name="pm_pad", shape=[1],
                                  dtype="float32")
        q.global_block.insert_op(
            0, "fill_constant", outputs={"Out": ["pm_pad"]},
            attrs={"shape": [1], "dtype": "float32", "value": 0.0})
        return q

    # the PT200 error pre-dates the pass and its message now names a
    # different op index — still not the transform's fault
    res = run_passes(p, ("pm_test_prepend",), fetches=[early.name],
                     verify="strict")
    assert res.changed


def test_on_demand_analysis_diagnostics_not_duplicated():
    """A pass calling ctx.analysis() for an undeclared dependency must not
    double-count that analysis' findings when the pipeline also lists it."""
    @register_pass("pm_test_peek")
    def peek(program, ctx):
        return ctx.analysis("dead_code")  # on-demand, no requires=

    p, a, b, out = _dead_chain_program()
    res = run_passes(p, ("pm_test_peek", "dead_code"), fetches=[out.name])
    assert sum(d.code == "PT720" for d in res.diagnostics) == 2  # not 4


def test_strict_verify_ignores_preexisting_errors():
    """The bracket flags NEW errors only: a program already carrying an
    error finding may still run a transform that leaves it untouched."""
    main, _, loss = _mlp_program()
    op = next(o for o in main.global_block.ops if o.type == "mean")
    del op.inputs["X"]  # pre-existing PT101

    @register_pass("pm_test_noop_t", kind=TRANSFORM)
    def noop(program, ctx):
        return program.clone()

    res = run_passes(main, ("pm_test_noop_t",), fetches=[loss.name],
                     verify="strict")
    assert res.changed  # the old error did not blame the innocent pass


# ---------------------------------------------------------------------------
# the migrated pipeline: identical diagnostics, monitor timings
# ---------------------------------------------------------------------------

def test_verify_pipeline_matches_check_program():
    main, _, loss = _mlp_program()
    op = next(o for o in main.global_block.ops if o.type == "relu")
    del op.inputs["X"]
    from paddle_tpu.analysis.pass_manager import run_verify_pipeline

    with pytest.raises(ProgramVerificationError) as e1:
        check_program(main, fetch_names=[loss.name])
    with pytest.raises(ProgramVerificationError) as e2:
        run_verify_pipeline(main, fetch_names=[loss.name])
    assert ([d.code for d in e1.value.diagnostics]
            == [d.code for d in e2.value.diagnostics])


def test_executor_hook_routes_through_manager():
    """FLAGS_check_program executions show up as per-pass monitor
    counters/timings — the acceptance-visible face of the migration."""
    def runs(name):
        return monitor.metric_value(
            "pass_runs_total", 0.0,
            **{"pass": name, "kind": "analysis", "result": "run"})

    before = {n: runs(n) for n in VERIFY_PASSES}
    main, startup, loss = _mlp_program()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    fluid.set_flags({"FLAGS_check_program": 1})
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed={"x": np.zeros((2, 4), np.float32),
                            "y": np.zeros((2, 1), np.float32)},
                fetch_list=[loss.name])
    for n in VERIFY_PASSES:
        assert runs(n) > before[n], n
    hist = monitor.metric_value("pass_duration_seconds", None,
                                **{"pass": "liveness"})
    assert hist is not None and hist["count"] > 0
    # and the JSON export carries them (the CI artifact face)
    snap = monitor.snapshot()
    assert "pass_runs_total" in snap["metrics"]
    assert "pass_duration_seconds" in snap["metrics"]


def test_auto_remat_via_transform_pipeline():
    """The FLAGS_auto_recompute executor path now runs Pass 6 through the
    manager; the pipeline result carries the RematDecision."""
    from paddle_tpu.analysis.pass_manager import run_transform_pipeline

    main, startup = fluid.Program(), fluid.Program()
    with un.guard(), fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[16], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        h = x
        for _ in range(6):
            h = fluid.layers.fc(h, 16, act="relu")
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(fluid.layers.fc(h, 1), y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    res = run_transform_pipeline(main, ("auto_remat",),
                                 feed_names=["x", "y"],
                                 fetch_names=[loss.name], batch_size=8)
    dec = res.values["auto_remat"]
    assert dec.applied and dec.n_segments > 0
    assert res.program is dec.program and res.changed
    assert any(op.type == "recompute_segment"
               for op in res.program.global_block.ops)


# ---------------------------------------------------------------------------
# PT700s — whole-program dtype/shape consistency
# ---------------------------------------------------------------------------

def _clean_chain():
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        h = fluid.layers.relu(x)
        out = fluid.layers.scale(h, scale=2.0)
    return p, h, out


def test_pt700_infer_failure_under_propagation():
    if not op_registry.has_op("pm_strict_infer"):
        def strict_infer(op, block):
            v = block.var(op.inputs["X"][0])
            if v.shape is not None and tuple(v.shape)[-1] != 4:
                raise ValueError(f"pm_strict_infer wants last dim 4, "
                                 f"got {v.shape}")
            block.var(op.outputs["Out"][0]).shape = v.shape

        op_registry._OP_REGISTRY["pm_strict_infer"] = op_registry.OpDef(
            type="pm_strict_infer",
            inputs=[op_registry.IOSpec("X")],
            outputs=[op_registry.IOSpec("Out")],
            infer_shape=strict_infer, lower=lambda ctx, ins, attrs: None)
    p, h, out = _clean_chain()
    blk = p.global_block
    o = blk.create_var(name="pm_strict_out", shape=[4], dtype="float32")
    blk.append_op("pm_strict_infer", inputs={"X": [h.name]},
                  outputs={"Out": [o.name]})
    # negative control first: consistent metadata, no PT700
    res = run_passes(p, ("dtype_shape_check",), fetches=[out.name])
    assert "PT700" not in codes_of(res.diagnostics)
    # upstream producer drifts -> propagation hands the consumer a shape
    # its contract rejects
    op = next(o_ for o_ in blk.ops if o_.type == "relu")
    op.attrs["__pm_poke__"] = 1  # raw mutate: no re-infer
    blk.var(h.name).shape = (2, 9)
    res = run_passes(p, ("dtype_shape_check",), fetches=[out.name])
    assert "PT700" not in codes_of(res.diagnostics)  # recorded = replayed
    # force replay drift: relu's input metadata changes, its replay output
    # follows, and the strict consumer downstream blows up
    blk.var("x").shape = (-1, 9)
    blk.var(h.name).shape = (-1, 4)
    res = run_passes(p, ("dtype_shape_check",), fetches=[out.name])
    assert "PT700" in codes_of(res.diagnostics)


def test_pt701_shape_mismatch_at_consumer_boundary():
    p, h, out = _clean_chain()
    p.global_block.var(h.name).shape = (9, 9)  # stale recorded metadata
    res = run_passes(p, ("dtype_shape_check",), fetches=[out.name])
    found = [d for d in res.diagnostics if d.code == "PT701"]
    assert found and "scale" in found[0].message  # consumer named


def test_pt702_dtype_mismatch_at_consumer_boundary():
    p, h, out = _clean_chain()
    p.global_block.var(h.name).dtype = "int64"
    res = run_passes(p, ("dtype_shape_check",), fetches=[out.name])
    assert "PT702" in codes_of(res.diagnostics)


def test_pt703_conflicting_producers():
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        blk = p.global_block
        blk.create_var(name="v", shape=[2], dtype="float32")
        blk.append_op("fill_constant", outputs={"Out": ["v"]},
                      attrs={"shape": [2], "dtype": "float32", "value": 1.0})
        blk.append_op("fill_constant", outputs={"Out": ["v"]},
                      attrs={"shape": [3], "dtype": "int64", "value": 2.0})
        out = fluid.layers.scale(blk.var("v"), scale=1.0)
    res = run_passes(p, ("dtype_shape_check",), fetches=[out.name])
    assert "PT703" in codes_of(res.diagnostics)


def test_pt704_shapeless_consumer_boundary():
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        blk = p.global_block
        u = blk.create_var(name="u", shape=None, dtype="float32")
        out = fluid.layers.relu(u)
    res = run_passes(p, ("dtype_shape_check",), fetches=[out.name])
    assert "PT704" in codes_of(res.diagnostics)


def test_pt700s_negative_control_clean_program():
    main, startup, loss = _mlp_program()
    for prog, fetches in ((main, [loss.name]), (startup, [])):
        res = run_passes(prog, ("dtype_shape_check",), fetches=fetches)
        assert not res.diagnostics, [str(d) for d in res.diagnostics]
    # and the pass is read-only: metadata restored after the replay
    assert main.global_block.var("x").shape == (-1, 4)


# ---------------------------------------------------------------------------
# PT710s — donation/alias races
# ---------------------------------------------------------------------------

def _donation_race_program(read_after_write=True):
    """Persistable w is read into the step, updated in place, and (for the
    positive control) read AGAIN after the update — the shape the old
    state_in∩state_out heuristic donated and the PR 2 proof refuses."""
    p, sp = fluid.Program(), fluid.Program()
    with un.guard(), fluid.program_guard(p, sp):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        w = p.global_block.create_parameter("w_state", [4], "float32")
        h = fluid.layers.elementwise_add(x, w)          # read w
        fluid.layers.assign(h, output=w)                # write w in place
        if read_after_write:
            out = fluid.layers.scale(w, scale=1.0)      # read AFTER write
        else:
            out = fluid.layers.scale(h, scale=1.0)
    return p, out


def test_pt710_donated_then_read_race():
    p, out = _donation_race_program(read_after_write=True)
    res = run_passes(p, ("donation_race",), fetches=[out.name],
                     feeds=["x"])
    found = [d for d in res.diagnostics if d.code == "PT710"]
    assert found and "w_state" in found[0].message


def test_pt710_negative_control():
    p, out = _donation_race_program(read_after_write=False)
    res = run_passes(p, ("donation_race",), fetches=[out.name],
                     feeds=["x"])
    assert "PT710" not in codes_of(res.diagnostics)


def test_pt711_unordered_double_write():
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        blk = p.global_block
        blk.create_var(name="v", shape=[2], dtype="float32")
        blk.append_op("fill_constant", outputs={"Out": ["v"]},
                      attrs={"shape": [2], "dtype": "float32", "value": 1.0})
        blk.append_op("fill_constant", outputs={"Out": ["v"]},
                      attrs={"shape": [2], "dtype": "float32", "value": 2.0})
        out = fluid.layers.scale(blk.var("v"), scale=1.0)
    res = run_passes(p, ("donation_race",), fetches=[out.name])
    assert "PT711" in codes_of(res.diagnostics)


def test_pt711_negative_intervening_read_orders_writes():
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        blk = p.global_block
        blk.create_var(name="v", shape=[2], dtype="float32")
        blk.append_op("fill_constant", outputs={"Out": ["v"]},
                      attrs={"shape": [2], "dtype": "float32", "value": 1.0})
        mid = fluid.layers.scale(blk.var("v"), scale=1.0)  # read orders
        blk.append_op("fill_constant", outputs={"Out": ["v"]},
                      attrs={"shape": [2], "dtype": "float32", "value": 2.0})
        out = fluid.layers.elementwise_add(mid, blk.var("v"))
    res = run_passes(p, ("donation_race",), fetches=[out.name])
    assert "PT711" not in codes_of(res.diagnostics)


def _alias_fetch_program(view_before_update=True):
    p, sp = fluid.Program(), fluid.Program()
    with un.guard(), fluid.program_guard(p, sp):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        w = p.global_block.create_parameter("w_al", [4], "float32")
        h = fluid.layers.elementwise_add(x, w)          # read w
        if view_before_update:
            snap = fluid.layers.assign(w)               # view BEFORE update
            fluid.layers.assign(h, output=w)            # in-place update
        else:
            fluid.layers.assign(h, output=w)
            snap = fluid.layers.assign(w)               # view after: fine
    return p, snap


def test_pt712_fetch_views_donated_buffer():
    p, snap = _alias_fetch_program(view_before_update=True)
    res = run_passes(p, ("donation_race",), fetches=[snap.name],
                     feeds=["x"])
    found = [d for d in res.diagnostics if d.code == "PT712"]
    assert found and "w_al" in found[0].message


def test_pt712_negative_view_after_final_write():
    p, snap = _alias_fetch_program(view_before_update=False)
    res = run_passes(p, ("donation_race",), fetches=[snap.name],
                     feeds=["x"])
    assert "PT712" not in codes_of(res.diagnostics)


def test_pt713_write_to_feed_var():
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        blk = p.global_block
        blk.append_op("scale", inputs={"X": [x.name]},
                      outputs={"Out": [x.name]}, attrs={"scale": 2.0})
        out = fluid.layers.relu(x)
    res = run_passes(p, ("donation_race",), fetches=[out.name],
                     feeds=["x"])
    assert "PT713" in codes_of(res.diagnostics)


def test_pt710s_negative_control_clean_training_program():
    main, _, loss = _mlp_program()
    res = run_passes(main, ("donation_race",), fetches=[loss.name],
                     feeds=["x", "y"])
    bad = {d.code for d in res.diagnostics} & {"PT711", "PT712", "PT713"}
    assert not bad, [str(d) for d in res.diagnostics]


# ---------------------------------------------------------------------------
# PT720s — dead code lint + DCE
# ---------------------------------------------------------------------------

def _dead_chain_program():
    """h is live; a=scale(h) is read ONLY by b=scale(a); b is read by
    nobody — a is dead only transitively (first-order PT502 misses it)."""
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        h = fluid.layers.relu(x)
        a = fluid.layers.scale(h, scale=2.0)
        b = fluid.layers.scale(a, scale=3.0)
        out = fluid.layers.scale(h, scale=4.0)
    return p, a, b, out


def test_pt720_transitive_dead_chain():
    p, a, b, out = _dead_chain_program()
    res = run_passes(p, ("dead_code", "liveness"), fetches=[out.name])
    dead_msgs = [d for d in res.diagnostics if d.code == "PT720"]
    assert len(dead_msgs) == 2  # BOTH links of the chain
    # ...while first-order PT502 sees only the chain's tail (a IS read,
    # by the dead b) — the closure is the new information
    pt502_ops = {d.op_idx for d in res.diagnostics if d.code == "PT502"}
    pt720_ops = {d.op_idx for d in res.diagnostics if d.code == "PT720"}
    assert pt720_ops > pt502_ops


def test_pt720_negative_control():
    main, _, loss = _mlp_program()
    res = run_passes(main, ("dead_code",), fetches=[loss.name])
    assert "PT720" not in codes_of(res.diagnostics)


def test_pt721_unused_output_of_live_op():
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        acc = fluid.layers.accuracy(fluid.layers.fc(x, 4), label)
    res = run_passes(p, ("dead_code",), fetches=[acc.name])
    found = [d for d in res.diagnostics if d.code == "PT721"]
    # accuracy's Correct/Total state outputs are unused; the op is live
    assert found and all(d.op_type == "accuracy" for d in found)
    assert "PT720" not in codes_of(res.diagnostics)


def test_pt722_unreachable_sub_block():
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        out = fluid.layers.relu(x)
    p._create_block()   # orphan: no op carries sub_block=1
    p._rollback()
    res = run_passes(p, ("dead_code",), fetches=[out.name])
    assert "PT722" in codes_of(res.diagnostics)


def test_dce_removes_dead_chain_and_preserves_results():
    p, a, b, out = _dead_chain_program()
    n0 = len(p.global_block.ops)
    res = run_passes(p, ("dce",), fetches=[out.name], verify="strict")
    dec = res.values["dce"]
    assert dec.applied and dec.removed_ops == 2
    assert len(res.program.global_block.ops) == n0 - 2
    assert {a.name, b.name} & set(res.program.global_block.vars) == set()
    # the witness: identical fetches from the original and DCE'd program
    exe = fluid.Executor(fluid.CPUPlace())
    feed = {"x": np.arange(8, dtype=np.float32).reshape(2, 4)}
    with fluid.scope_guard(fluid.Scope()):
        (want,) = exe.run(p, feed=feed, fetch_list=[out.name])
    with fluid.scope_guard(fluid.Scope()):
        (got,) = exe.run(res.program, feed=feed, fetch_list=[out.name])
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))


def test_dce_refuses_on_clean_program():
    main, _, loss = _mlp_program()
    dec = dce_program(main, fetch_names=[loss.name])
    assert not dec.applied and dec.program is main
    assert "no dead ops" in dec.reason


def test_dce_never_removes_effectful_or_fetched_ops():
    if not op_registry.has_op("py_func"):
        # 'py_func' is in liveness._SIDE_EFFECT_TYPES: registering a stub
        # gives the test a schema-valid op the effect classifier pins
        op_registry.register_op("py_func", inputs=["X"], outputs=["Out"],
                                grad=None)(lambda ctx, ins, attrs: None)
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        h = fluid.layers.relu(x)          # fetched
        d = fluid.layers.scale(h, scale=2.0)  # dead value op
        blk = p.global_block
        sink = blk.create_var(name="pm_sink", shape=[4], dtype="float32")
        blk.append_op("py_func", inputs={"X": [h.name]},
                      outputs={"Out": [sink.name]})  # side effect: survives
    dec = dce_program(p, fetch_names=[h.name])
    assert dec.applied
    kept = [op.type for op in dec.program.global_block.ops]
    assert "py_func" in kept and "relu" in kept
    assert "scale" not in kept, kept


# ---------------------------------------------------------------------------
# context plumbing
# ---------------------------------------------------------------------------

def test_pass_context_options_and_batch():
    seen = {}

    @register_pass("pm_test_ctx")
    def probe(program, ctx):
        seen.update(batch=ctx.batch_size, opt=ctx.options.get("knob"),
                    feeds=ctx.feed_names, fetches=ctx.fetch_names)

    main, _, loss = _mlp_program()
    default_pass_manager().run_pipeline(
        main, ("pm_test_ctx",), feed_names=["x", "y"],
        fetch_names=[loss.name], batch_size=32, options={"knob": 7},
        verify="none")
    assert seen == {"batch": 32, "opt": 7, "feeds": ("x", "y"),
                    "fetches": (loss.name,)}


def test_pass_context_rejects_caching_transforms():
    main, _, _ = _mlp_program()
    ctx = PassContext(main)
    with pytest.raises(ValueError, match="transform"):
        ctx.analysis("dce")
