"""paddle_tpu.monitor.numwitness — the runtime half of the PT900
numerics gate (FLAGS_numerics_witness). Record/merge semantics, the
tolerance-free containment cross-check against the static intervals,
the disabled-is-a-no-op hot-path contract, and the first-offender
attribution feeding FLAGS_nan_inf_policy escalations and the flight
recorder (ISSUE 17 satellite)."""
import logging

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.unique_name as un
from paddle_tpu.analysis.numerics import static_intervals
from paddle_tpu.monitor import numwitness


@pytest.fixture
def flags_guard():
    from paddle_tpu import flags as F

    saved = dict(F._overrides)
    yield fluid.set_flags
    F._overrides.clear()
    F._overrides.update(saved)


@pytest.fixture(autouse=True)
def _fresh_witness():
    numwitness.reset_numerics_witness()
    yield
    numwitness.reset_numerics_witness()


# ---------------------------------------------------------------------------
# record/merge semantics (pure host logic)
# ---------------------------------------------------------------------------

def test_record_step_merges_ranges_across_steps():
    numwitness.record_step(["a", "b"],
                           [[2.0, -2.0, 1.0, 0.0],
                            [5.0, 0.5, 5.0, 0.0]])
    numwitness.record_step(["a", "b"],
                           [[3.0, -1.0, 3.0, 0.0],
                            [4.0, 0.1, 4.0, 2.0]])
    v = numwitness.numerics_witness_vars()
    assert v["a"] == {"absmax": 3.0, "min": -2.0, "max": 3.0,
                      "nonfinite": 0, "steps": 2}
    assert v["b"] == {"absmax": 5.0, "min": 0.1, "max": 5.0,
                      "nonfinite": 2, "steps": 2}
    rep = numwitness.numerics_witness_report()
    assert rep["nonfinite_total"] == 2


def test_all_nonfinite_var_reports_no_finite_range():
    """min/max fold nonfinite lanes away: a var that was ALL nan keeps
    min=+inf/max=-inf internally and serializes them as None."""
    numwitness.record_step(["x"], [[0.0, np.inf, -np.inf, 4.0]])
    v = numwitness.numerics_witness_vars()["x"]
    assert v["min"] is None and v["max"] is None
    assert v["nonfinite"] == 4


def test_first_offender_is_per_step_not_cumulative():
    numwitness.record_step(["a", "b", "c"],
                           [[1.0, 0.0, 1.0, 0.0],
                            [1.0, 0.0, 1.0, 3.0],
                            [1.0, 0.0, 1.0, 1.0]])
    assert numwitness.first_offender() == "b"   # first in program order
    numwitness.record_step(["a", "b", "c"],
                           [[1.0, 0.0, 1.0, 0.0],
                            [1.0, 0.0, 1.0, 0.0],
                            [1.0, 0.0, 1.0, 0.0]])
    assert numwitness.first_offender() is None  # last step was clean


def test_containment_violations_logic():
    static = {"a": (-1.0, 1.0), "b": (0.0, 10.0), "c": (0.0, 1.0)}
    observed = {
        "a": {"absmax": 0.9, "min": -0.9, "max": 0.9,
              "nonfinite": 0, "steps": 1},             # inside
        "b": {"absmax": 11.0, "min": -0.5, "max": 11.0,
              "nonfinite": 0, "steps": 1},             # both sides escape
        "d": {"absmax": 99.0, "min": -99.0, "max": 99.0,
              "nonfinite": 0, "steps": 1},             # no static side
        # c: never witnessed -> skipped
    }
    v = numwitness.containment_violations(static, observed)
    assert [(x["var"], x["bound"]) for x in v] == [("b", "lo"), ("b", "hi")]
    assert "observed min -0.5 < static lower bound 0" in v[0]["detail"]


# ---------------------------------------------------------------------------
# end-to-end: the executor's witness taps
# ---------------------------------------------------------------------------

def _bounded_net():
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[8], dtype="float32")
            t = fluid.layers.tanh(x)
            s = fluid.layers.sigmoid(t)
            out = fluid.layers.mean(fluid.layers.scale(s, scale=2.0))
    return main, startup, out


def _run(main, startup, fetch, steps=2):
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(0)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(steps):
            exe.run(main, feed={"x": rng.randn(4, 8).astype(np.float32)},
                    fetch_list=[fetch])
    return exe


def test_witness_observes_vars_and_contains_them(flags_guard):
    main, startup, out = _bounded_net()
    flags_guard({"FLAGS_numerics_witness": 1})
    _run(main, startup, out.name)
    observed = numwitness.numerics_witness_vars()
    assert observed, "witness on: float op outputs must be observed"
    static = static_intervals(main, fetch_names=[out.name])
    checked = set(static) & set(observed)
    assert checked, "bounded vars (tanh/sigmoid/...) must be witnessed"
    violations = numwitness.containment_violations(static, observed)
    assert violations == [], (
        "tolerance-free containment: any escape is an analysis "
        f"soundness bug — {violations}")


def test_witness_disabled_is_a_hot_path_no_op(flags_guard):
    """Flag off (the default): no tap is traced, nothing recorded, and
    the compiled step carries no witness metadata."""
    main, startup, out = _bounded_net()
    exe = _run(main, startup, out.name)
    assert numwitness.numerics_witness_vars() == {}
    step = next(iter(exe._cache.values()))
    assert step.num_witness_meta is None


def test_witness_flag_flips_get_separate_compiles(flags_guard):
    """The flag is part of the compile cache key: flipping it mid-session
    must not serve a step traced without taps (or vice versa)."""
    main, startup, out = _bounded_net()
    exe = _run(main, startup, out.name)
    flags_guard({"FLAGS_numerics_witness": 1})
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        exe.run(main, feed={"x": np.zeros((4, 8), np.float32)},
                fetch_list=[out.name])
    metas = [s.num_witness_meta for s in exe._cache.values()]
    assert None in metas and any(m is not None for m in metas)
    assert numwitness.numerics_witness_vars()


# ---------------------------------------------------------------------------
# attribution: the witness names the first offender for the nan/inf
# machinery (resilience.nonfinite + the flight recorder)
# ---------------------------------------------------------------------------

def _nan_net():
    """First non-finite producer in program order is the log of a
    negative constant."""
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            c = fluid.layers.fill_constant(shape=[4], dtype="float32",
                                           value=-1.0)
            bad = fluid.layers.log(c)                 # nan, first
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            out = fluid.layers.mean(fluid.layers.elementwise_add(x, bad))
    return main, startup, bad, out


def test_escalation_message_names_the_first_offender(flags_guard, caplog):
    main, startup, bad, out = _nan_net()
    flags_guard({"FLAGS_numerics_witness": 1, "FLAGS_check_nan_inf": 1,
                 "FLAGS_nan_inf_policy": "skip",
                 "FLAGS_nan_inf_max_consecutive_skips": 2})
    exe = fluid.Executor(fluid.CPUPlace())
    feed = {"x": np.zeros((1, 4), np.float32)}
    incidents_before = len([i for i in fluid.trace.incidents()
                            if i.get("kind") == "nonfinite_step"])
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        with caplog.at_level(logging.WARNING, "paddle_tpu.resilience"):
            exe.run(main, feed=feed, fetch_list=[out.name])   # skip #1
            with pytest.raises(FloatingPointError) as ei:
                exe.run(main, feed=feed, fetch_list=[out.name])  # escalate
    attribution = f"first non-finite var this step was '{bad.name}'"
    assert attribution in str(ei.value)
    assert any(attribution in r.getMessage() for r in caplog.records)
    # both dropped steps left a flight-recorder incident carrying the
    # same attribution
    incidents = [i for i in fluid.trace.incidents()
                 if i.get("kind") == "nonfinite_step"]
    assert len(incidents) == incidents_before + 2
    assert all(attribution in i.get("detail", "") for i in incidents[-2:])


def test_attribution_is_empty_without_the_witness(flags_guard):
    """The nan-check machinery works unchanged with the witness off —
    the suffix is simply absent (no stale offender leaks in)."""
    from paddle_tpu.resilience.nonfinite import witness_attribution

    main, startup, _bad, out = _nan_net()
    flags_guard({"FLAGS_check_nan_inf": 1, "FLAGS_nan_inf_policy": "skip",
                 "FLAGS_nan_inf_max_consecutive_skips": 0})
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        exe.run(main, feed={"x": np.zeros((1, 4), np.float32)},
                fetch_list=[out.name])
    assert witness_attribution() == ""


def test_observed_absmax_is_the_calibration_dict(flags_guard):
    """numerics_witness_vars()['absmax'] feeds analyze_numerics as
    calibration — the PT906 feedback loop lint_numerics --witness runs."""
    from paddle_tpu.analysis.numerics import analyze_numerics

    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            a = fluid.layers.data("a", shape=[8, 8], dtype="float32")
            b = fluid.layers.data("b", shape=[8, 8], dtype="float32")
            out = fluid.layers.matmul(a, b)
    flags_guard({"FLAGS_numerics_witness": 1})
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(1)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        exe.run(main, feed={"a": rng.randn(8, 8).astype(np.float32),
                            "b": rng.randn(8, 8).astype(np.float32)},
                fetch_list=[out.name])
    calib = {n: o["absmax"]
             for n, o in numwitness.numerics_witness_vars().items()}
    assert calib
    rep = analyze_numerics(main, fetch_names=[out.name], calibration=calib)
    (site,) = rep.quant_sites
    assert site["calibrated_absmax"], "observed abs-max reaches the site"
    assert set(site["calibrated_absmax"]) <= {"a", "b", out.name}
