"""Predictor API + StableHLO export (reference
inference/api/analysis_predictor.h, analysis_predictor_tester.cc)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.inference import (AnalysisConfig, StableHLOPredictor,
                                  create_paddle_predictor, export_stablehlo,
                                  load_stablehlo)


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    d = tmp_path_factory.mktemp("model")
    import paddle_tpu.unique_name as un

    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[8], dtype="float32")
            h = fluid.layers.fc(x, 16, act="relu")
            out = fluid.layers.fc(h, 3, act="softmax")
    main.random_seed = 5
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    xb = rng.randn(4, 8).astype(np.float32)
    with fluid.scope_guard(scope):
        exe.run(startup)
        (ref,) = exe.run(main, feed={"x": xb}, fetch_list=[out.name])
        fluid.io.save_inference_model(str(d / "m"), ["x"], [out], exe,
                                      main_program=main)
        meta = export_stablehlo(main, {"x": ((4, 8), "float32")}, [out],
                                str(d / "m.stablehlo"))
    return {"dir": str(d / "m"), "hlo": str(d / "m.stablehlo"),
            "xb": xb, "ref": np.asarray(ref), "meta": meta}


def test_predictor_run_positional(trained):
    config = AnalysisConfig(trained["dir"])
    config.disable_gpu()
    pred = create_paddle_predictor(config)
    assert pred.get_input_names() == ["x"]
    (got,) = pred.run([trained["xb"]])
    np.testing.assert_allclose(got, trained["ref"], rtol=1e-5)


def test_predictor_zero_copy_handles(trained):
    pred = create_paddle_predictor(AnalysisConfig(trained["dir"]))
    h = pred.get_input_handle("x")
    h.copy_from_cpu(trained["xb"])
    pred.zero_copy_run()
    out_name = pred.get_output_names()[0]
    got = pred.get_output_handle(out_name).copy_to_cpu()
    np.testing.assert_allclose(got, trained["ref"], rtol=1e-5)
    with pytest.raises(KeyError):
        pred.get_input_handle("nope")


def test_stablehlo_roundtrip(trained):
    """The serialized artifact runs standalone and matches; the .mlir text
    is genuine StableHLO."""
    p = load_stablehlo(trained["hlo"])
    (got,) = p.run(trained["xb"])
    np.testing.assert_allclose(got, trained["ref"], rtol=1e-5)
    txt = open(trained["hlo"] + ".mlir").read()
    assert "stablehlo." in txt and "dot_general" in txt
