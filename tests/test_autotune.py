"""paddle_tpu.tuning — the persistent autotuner: cost-database round trip,
content fingerprints, mode gating (off|use|measure), executor compile-path
feedback (best-known config in the cache key, hit/miss counters), and
staleness invalidation. The cross-process round trip (a fresh 'use'
process compiling straight to the measured best with zero re-trials) is
proven end-to-end by tools/fusion_check.py in CI."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.unique_name as un
from paddle_tpu import monitor, tuning


@pytest.fixture(autouse=True)
def _tuning_isolation(tmp_path):
    prev = fluid.get_flags(["FLAGS_autotune", "FLAGS_autotune_db",
                            "FLAGS_xla_options",
                            "FLAGS_fused_gemm_blocks"])
    fluid.set_flags({"FLAGS_autotune_db":
                     str(tmp_path / "autotune_db.json")})
    tuning.reset_database_cache()
    yield
    fluid.set_flags(prev)
    tuning.reset_database_cache()


def _program(width=64, seed=7):
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[width], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="float32")
            h = fluid.layers.fc(x, width, act="relu")
            pred = fluid.layers.fc(h, 1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    main.random_seed = seed
    return main, startup, loss


# ---------------------------------------------------------------------------
# database
# ---------------------------------------------------------------------------

def test_db_round_trip_and_best():
    db = tuning.get_database()
    c1 = tuning.TunedConfig.make({"xla_cpu_enable_fast_min_max": True})
    c2 = tuning.TunedConfig.make({}, (256, 128, 128))
    db.record("fp1", 64, "cpu", c1, 0.02)
    db.record("fp1", 64, "cpu", c2, 0.01)
    best = db.best("fp1", 64, "cpu")
    assert best["candidate"]["gemm_blocks"] == [256, 128, 128]
    # re-measuring a candidate replaces its trial, never duplicates
    db.record("fp1", 64, "cpu", c2, 0.03)
    assert db.trial_count() == 2
    assert db.best("fp1", 64, "cpu")["candidate"]["xla_options"] == {
        "xla_cpu_enable_fast_min_max": True}
    # durable: a fresh CostDatabase object reloads from disk
    db2 = tuning.CostDatabase(db.path)
    assert db2.trial_count() == 2
    assert db2.best("fp1", 64, "cpu") == db.best("fp1", 64, "cpu")


def test_db_version_staleness_invalidates():
    """Trials recorded by a different framework/jax version are invisible
    to best() — a compiler upgrade invalidates its measurements."""
    db = tuning.get_database()
    db.record("fp2", 32, "cpu", tuning.TunedConfig.make({}), 0.01)
    with db._lock:
        for e in db._load().values():
            for t in e["trials"]:
                t["jax_version"] = "0.0.0-other"
    assert db.best("fp2", 32, "cpu") is None


def test_db_corrupt_file_degrades_to_empty(tmp_path):
    p = str(tmp_path / "bad.json")
    with open(p, "w") as f:
        f.write("{not json")
    db = tuning.CostDatabase(p)
    assert db.trial_count() == 0
    db.record("fp", 1, "cpu", tuning.TunedConfig.make({}), 0.5)
    assert tuning.CostDatabase(p).trial_count() == 1


def test_shape_bucket_powers_of_two():
    assert [tuning.shape_bucket(b) for b in (1, 2, 3, 64, 65, 128)] == \
        [1, 2, 4, 64, 128, 128]


def test_content_fingerprint_stable_across_builds():
    m1, _, _ = _program()
    m2, _, _ = _program()
    m3, _, _ = _program(width=32)
    assert tuning.program_content_fingerprint(m1) == \
        tuning.program_content_fingerprint(m2)
    assert tuning.program_content_fingerprint(m1) != \
        tuning.program_content_fingerprint(m3)
    assert m1._serial != m2._serial  # serials differ; content hash doesn't


# ---------------------------------------------------------------------------
# mode gating
# ---------------------------------------------------------------------------

def test_record_requires_measure_mode():
    main, _, _ = _program()
    for mode in ("off", "use"):
        fluid.set_flags({"FLAGS_autotune": mode})
        with pytest.raises(RuntimeError, match="measure"):
            tuning.record_trial(main, 8, tuning.TunedConfig.make({}), 0.1)
    fluid.set_flags({"FLAGS_autotune": "measure"})
    tuning.record_trial(main, 8, tuning.TunedConfig.make({}), 0.1)
    assert tuning.get_database().trial_count() == 1


def test_lookup_off_mode_never_touches_db():
    main, _, _ = _program()
    fluid.set_flags({"FLAGS_autotune": "off"})
    assert tuning.lookup_best(main, 8) is None
    assert not os.path.exists(tuning.default_db_path())


# ---------------------------------------------------------------------------
# executor integration
# ---------------------------------------------------------------------------

def _seed_best(main, batch, opts):
    fluid.set_flags({"FLAGS_autotune": "measure"})
    tuning.record_trial(main, batch,
                        tuning.TunedConfig.make(opts), 0.001)
    # a worse candidate the executor must NOT pick
    tuning.record_trial(main, batch, tuning.TunedConfig.make({}), 0.5)


def test_executor_use_mode_compiles_best_config():
    main, startup, loss = _program()
    batch = 16
    best_opts = {"xla_cpu_enable_fast_min_max": True}
    _seed_best(main, batch, best_opts)
    fluid.set_flags({"FLAGS_autotune": "use"})
    monitor.reset()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(batch, 64).astype(np.float32),
            "y": rng.randn(batch, 1).astype(np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        (lv,) = exe.run(main, feed=feed, fetch_list=[loss.name])
    assert np.isfinite(lv).all()
    assert (monitor.metric_value("autotune_hits_total") or 0) >= 1
    assert (monitor.metric_value("autotune_trials_total") or 0) == 0
    ev = [e for e in monitor.recompile_events(recompiles_only=False)
          if e.components.get("xla_options")]
    assert ev, "no compile carried the tuned options"
    assert dict(ev[-1].components["xla_options"]) == best_opts


def test_explicit_flags_beat_db():
    main, startup, loss = _program()
    batch = 16
    _seed_best(main, batch, {"xla_cpu_enable_fast_min_max": True})
    fluid.set_flags({"FLAGS_autotune": "use",
                     "FLAGS_xla_options":
                     json.dumps({"xla_llvm_disable_expensive_passes":
                                 True})})
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(batch, 64).astype(np.float32),
            "y": rng.randn(batch, 1).astype(np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[loss.name])
    ev = [e for e in monitor.recompile_events(recompiles_only=False)
          if e.components.get("xla_options")]
    assert dict(ev[-1].components["xla_options"]) == {
        "xla_llvm_disable_expensive_passes": True}


def test_measure_candidates_records_and_ranks():
    main, startup, loss = _program()
    fluid.set_flags({"FLAGS_autotune": "measure"})
    monitor.reset()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(8, 64).astype(np.float32),
            "y": rng.randn(8, 1).astype(np.float32)}
    cands = [tuning.TunedConfig.make({}),
             tuning.TunedConfig.make({"xla_cpu_enable_fast_min_max": True})]
    with fluid.scope_guard(scope):
        exe.run(startup)
        rep = tuning.measure_candidates(exe, main, feed, [loss.name],
                                        scope, candidates=cands,
                                        k_short=2, k_long=4)
    ok = [t for t in rep["trials"] if t["status"] == "ok"]
    assert len(ok) == 2 and rep["best"] is not None
    assert tuning.get_database().trial_count() == 2
    assert (monitor.metric_value("autotune_trials_total") or 0) == 2
    # and a subsequent use-mode executor reuses the best with no trials
    fluid.set_flags({"FLAGS_autotune": "use"})
    exe2 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe2.run(main, feed=feed, fetch_list=[loss.name])
    assert tuning.get_database().trial_count() == 2
    assert (monitor.metric_value("autotune_hits_total") or 0) >= 1


def test_autotune_off_drops_tuned_blocks_and_no_program_stamp():
    """Turning FLAGS_autotune off drops the DB's influence entirely: the
    off-mode compile must carry gemm_blocks=None in its compile components
    (a distinct cache key — it recompiles, it does not reuse the tuned
    executable). The tuned blocks are threaded per-compile, never stamped
    on the shared Program: a stamp read lazily at jit-trace time could be
    overwritten by a concurrent compile with a different tuned config."""
    main, startup, loss = _program()
    batch = 16
    fluid.set_flags({"FLAGS_autotune": "measure"})
    tuning.record_trial(main, batch,
                        tuning.TunedConfig.make({}, (256, 128, 128)),
                        0.001)
    fluid.set_flags({"FLAGS_autotune": "use"})
    monitor.reset()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(batch, 64).astype(np.float32),
            "y": rng.randn(batch, 1).astype(np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[loss.name])
        evs = [e for e in monitor.recompile_events(recompiles_only=False)
               if "gemm_blocks" in e.components]
        assert evs[-1].components["gemm_blocks"] == (256, 128, 128)
        fluid.set_flags({"FLAGS_autotune": "off"})
        exe.run(main, feed=feed, fetch_list=[loss.name])
        evs = [e for e in monitor.recompile_events(recompiles_only=False)
               if "gemm_blocks" in e.components]
        assert evs[-1].components["gemm_blocks"] is None
    assert not hasattr(main, "_tuned_gemm_blocks")


def test_use_mode_hits_db_with_epilogue_fusion_enabled():
    """Record/lookup key consistency under fusion: trials are recorded
    under the SUBMITTED program's content fingerprint, and the executor
    must look up with that same fingerprint even though it compiles the
    fused clone (whose content — fused_gemm_epilogue ops — differs)."""
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[64], dtype="float32")
            h = fluid.layers.fc(x, 64, act="relu")
            pred = fluid.layers.fc(h, 64)
    batch = 16
    best_opts = {"xla_cpu_enable_fast_min_max": True}
    fluid.set_flags({"FLAGS_autotune": "measure"})
    tuning.record_trial(main, batch, tuning.TunedConfig.make(best_opts),
                        0.001)
    prev = fluid.get_flags(["FLAGS_epilogue_fusion"])
    fluid.set_flags({"FLAGS_autotune": "use", "FLAGS_epilogue_fusion": 1})
    monitor.reset()
    try:
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        feed = {"x": np.random.RandomState(0).randn(
            batch, 64).astype(np.float32)}
        with fluid.scope_guard(scope):
            exe.run(startup)
            exe.run(main, feed=feed, fetch_list=[pred.name])
        # the executor really swapped in a fused clone...
        assert any(op.type == "fused_gemm_epilogue"
                   for p in exe._fusion_cache.values()
                   for op in p.global_block.ops)
        # ...and the DB lookup still hit the submitted program's entry
        assert (monitor.metric_value("autotune_hits_total") or 0) >= 1
        evs = [e for e in monitor.recompile_events(recompiles_only=False)
               if e.components.get("xla_options")]
        assert evs and dict(evs[-1].components["xla_options"]) == best_opts
    finally:
        fluid.set_flags(prev)


def test_measure_trial_not_contaminated_by_db_best():
    """The in-trial guard: while measure_candidates runs a candidate, the
    executor must compile exactly that candidate's config — never fill its
    unset knobs from the DB's best-known entry, or the baseline {} trial
    would be silently measured under the tuned config and recorded as if
    the default achieved its step time."""
    main, startup, loss = _program()
    batch = 8
    fluid.set_flags({"FLAGS_autotune": "measure"})
    tuning.record_trial(main, batch,
                        tuning.TunedConfig.make(
                            {"xla_cpu_enable_fast_min_max": True}),
                        0.000001)   # an irresistibly fast best
    monitor.reset()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(batch, 64).astype(np.float32),
            "y": rng.randn(batch, 1).astype(np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        rep = tuning.measure_candidates(
            exe, main, feed, [loss.name], scope,
            candidates=[tuning.TunedConfig.make({})], k_short=2, k_long=4)
    assert [t["status"] for t in rep["trials"]] == ["ok"]
    # every compile issued during the trial carried the candidate's empty
    # options, not the DB best
    for e in monitor.recompile_events(recompiles_only=False):
        assert not dict(e.components.get("xla_options") or ()), \
            "trial compile leaked the DB's best-known xla_options"


def test_concurrent_recorders_merge_on_save(tmp_path):
    """Two DB instances sharing one file (two measure-mode processes)
    must union their trials on save, not last-writer-wins."""
    p = str(tmp_path / "shared_db.json")
    a, b = tuning.CostDatabase(p), tuning.CostDatabase(p)
    a._load()
    b._load()          # both memoize the (empty) file before either saves
    a.record("fp", 16, "cpu", tuning.TunedConfig.make({"opt_a": True}),
             0.5)
    b.record("fp", 16, "cpu", tuning.TunedConfig.make({"opt_b": True}),
             0.4)
    fresh = tuning.CostDatabase(p)
    e = fresh._load()[tuning.CostDatabase.key("fp", 16, "cpu")]
    cands = [t["candidate"]["xla_options"] for t in e["trials"]]
    assert {"opt_a": True} in cands and {"opt_b": True} in cands
