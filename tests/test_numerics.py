"""paddle_tpu.analysis.numerics — the PT900 range/precision linter
(ISSUE 17 tentpole). Transfer-rule unit tests, a positive + negative
(guarded) control per PT90x code, the PT906-superset-of-fusable-chains
acceptance assertion, the QAT x epilogue-fusion pass-order contract
(docs/ANALYSIS.md "Quantization and epilogue fusion"), and the
numerics_check pass registration."""
import importlib
import math
import os
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.unique_name as un
from paddle_tpu.analysis import ALL_ANALYSIS_PASSES, default_pass_manager
from paddle_tpu.analysis.epilogue_fusion import fuse_epilogues
from paddle_tpu.analysis.numerics import (FAKE_QUANT_TYPES, Interval,
                                          NumericsReport, QUANT_SITE_TYPES,
                                          TOP, analyze_numerics,
                                          static_intervals)
from paddle_tpu.contrib.slim.quantization import quant_aware

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "numerics")
sys.path.insert(0, FIXTURES)


def _codes(rep):
    return {d.code for d in rep.diagnostics}


def _findings(rep, code):
    return [d for d in rep.diagnostics if d.code == code]


def _fixture(modname):
    return importlib.import_module(modname)


# ---------------------------------------------------------------------------
# Interval algebra
# ---------------------------------------------------------------------------

def test_interval_algebra():
    iv = Interval(-2.0, 3.0)
    assert iv.known and not iv.is_top
    assert iv.absmax == 3.0
    assert iv.contains_zero()
    assert iv.hull(Interval(-5.0, 1.0)) == Interval(-5.0, 3.0)
    assert iv.scaled(-1.0) == Interval(-3.0, 2.0)
    assert iv.shifted(1.0) == Interval(-1.0, 4.0)
    assert TOP.is_top and not TOP.known
    assert not Interval(0.0, math.inf).is_top  # one-sided is information


# ---------------------------------------------------------------------------
# transfer rules
# ---------------------------------------------------------------------------

def test_structural_activation_bounds_are_exact():
    """relu/tanh/clip model no float arithmetic — their bounds are exact
    (the rounding slack applies only to arithmetic rules)."""
    main, startup = fluid.Program(), fluid.Program()
    with un.guard(), fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        t = fluid.layers.tanh(x)
        r = fluid.layers.relu(t)
        c = fluid.layers.clip(r, min=0.2, max=0.8)
    rep = analyze_numerics(main)
    assert rep.intervals[t.name].to_tuple() == (-1.0, 1.0)
    assert rep.intervals[r.name].to_tuple() == (0.0, 1.0)
    assert rep.intervals[c.name].to_tuple() == (0.2, 0.8)


def test_fill_constant_interval_contains_the_float32_value():
    """The rounding-slack rationale: python 1e-4 is not a float32 — the
    runtime materializes np.float32(1e-4) = 9.9999997e-05, and the
    derived interval must contain THAT value (tolerance-free witness)."""
    main, startup = fluid.Program(), fluid.Program()
    with un.guard(), fluid.program_guard(main, startup):
        c = fluid.layers.fill_constant(shape=[1], dtype="float32",
                                       value=1e-4)
    rep = analyze_numerics(main)
    lo, hi = rep.intervals[c.name].to_tuple()
    stored = float(np.float32(1e-4))
    assert lo <= stored <= hi
    assert stored < 1e-4          # the exact interval would have missed it
    assert hi - lo < 1e-9         # ...but the slack stays tiny


def test_gemm_growth_bounded_by_contraction_width():
    """|out| <= |x|max * |y|max * K for matmul, K read off the shapes."""
    main, startup = fluid.Program(), fluid.Program()
    with un.guard(), fluid.program_guard(main, startup):
        a = fluid.layers.data("a", shape=[4, 8], dtype="float32")
        b = fluid.layers.data("b", shape=[8, 5], dtype="float32")
        out = fluid.layers.matmul(fluid.layers.tanh(a),
                                  fluid.layers.tanh(b))
    rep = analyze_numerics(main)
    iv = rep.intervals[out.name]
    assert iv.known
    assert iv.absmax >= 8.0                  # K=8, both operands in [-1,1]
    assert iv.absmax <= 8.0 * (1.0 + 1e-4)   # slack stays proportionate


def test_unknown_operand_stays_top_soundly():
    """A GEMM over an unbounded parameter derives nothing — soundness
    over precision: no rule may invent a bound."""
    main, startup = fluid.Program(), fluid.Program()
    with un.guard(), fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        h = fluid.layers.fc(x, 4)            # weight interval unknown
    rep = analyze_numerics(main)
    assert not rep.intervals.get(h.name, TOP).known


def test_elementwise_and_scale_chain():
    main, startup = fluid.Program(), fluid.Program()
    with un.guard(), fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        s = fluid.layers.sigmoid(x)                    # [0, 1]
        y = fluid.layers.scale(s, scale=3.0, bias=-1.0)  # [-1, 2]
        z = fluid.layers.elementwise_add(y, s)         # [-1, 3]
    rep = analyze_numerics(main)
    lo, hi = rep.intervals[z.name].to_tuple()
    assert lo <= -1.0 <= hi and lo <= 3.0 <= hi
    assert -1.001 < lo and hi < 3.001


# ---------------------------------------------------------------------------
# positive controls: the fixtures trip their codes
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("modname", [
    "pt900_broken_pairing", "pt901_dead_scale", "pt902_overflow_cast",
    "pt903_low_precision_reduce", "pt904_amp_gap", "pt905_nonfinite",
])
def test_fixture_trips_expected_code(modname):
    with un.guard():
        mod = _fixture(modname)
        main, _startup, fetch = mod.build()
    rep = analyze_numerics(main, fetch_names=fetch)
    assert mod.EXPECTED in _codes(rep), (
        f"{modname} must trip {mod.EXPECTED}, got {_codes(rep)}")


# ---------------------------------------------------------------------------
# negative controls: a guard clears each finding
# ---------------------------------------------------------------------------

def test_pt905_cleared_by_clip_guard():
    """The fixture's hazards behind guards: clip narrows the interval and
    the finding disappears by construction, not by allowlist."""
    main, startup = fluid.Program(), fluid.Program()
    with un.guard(), fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        safe = fluid.layers.clip(x, min=0.1, max=10.0)
        lg = fluid.layers.log(safe)
        den = fluid.layers.clip(fluid.layers.tanh(x), min=0.5, max=1.0)
        q = fluid.layers.elementwise_div(x, den)
    rep = analyze_numerics(main)
    assert "PT905" not in _codes(rep)
    lo, hi = rep.intervals[lg.name].to_tuple()
    assert lo <= math.log(0.1) and hi >= math.log(10.0)
    assert not rep.intervals.get(q.name, TOP).known or True


def test_pt902_cleared_by_clip_before_cast():
    main, startup = fluid.Program(), fluid.Program()
    with un.guard(), fluid.program_guard(main, startup):
        c = fluid.layers.fill_constant(shape=[4], dtype="float32",
                                       value=1e6)
        safe = fluid.layers.clip(c, min=-100.0, max=100.0)
        fluid.layers.cast(safe, "float16")
    rep = analyze_numerics(main)
    assert "PT902" not in _codes(rep)


def test_pt903_cleared_by_float32_accumulation():
    main, startup = fluid.Program(), fluid.Program()
    with un.guard(), fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[1024], dtype="float32")
        h = fluid.layers.cast(x, "float16")
        up = fluid.layers.cast(h, "float32")     # upcast around the sum
        fluid.layers.reduce_sum(up)
    rep = analyze_numerics(main)
    assert "PT903" not in _codes(rep)


def test_pt904_cleared_by_full_unscale_coverage():
    main, startup = fluid.Program(), fluid.Program()
    with un.guard(), fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        p = fluid.layers.fc(fluid.layers.fc(x, 8, act="relu"), 1)
        loss = fluid.layers.mean(fluid.layers.square(p - y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        blk = main.global_block
        grads = sorted(n for n in blk.vars if n.endswith("@GRAD")
                       and (".w_" in n or ".b_" in n))
        scale = fluid.layers.fill_constant(shape=[1], dtype="float32",
                                           value=128.0)
        found = blk.create_var(name="found_inf", shape=(1,), dtype="bool")
        blk.append_op("check_finite_and_unscale",
                      inputs={"X": grads, "Scale": [scale.name]},
                      outputs={"Out": grads,
                               "FoundInfinite": [found.name]})
    rep = analyze_numerics(main, fetch_names=[loss.name])
    assert rep.loss_scaling_active
    assert "PT904" not in _codes(rep)


def test_quant_aware_output_is_pt900_pt901_clean():
    """The slim pass's own output honors its contract: every fake-quant
    feeds a GEMM, every moving-average scale is persistable in-place
    state."""
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[16], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="int64")
            h = fluid.layers.fc(x, 32, act="relu")
            logits = fluid.layers.fc(h, 4)
            quant_aware(main, startup)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, y))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    rep = analyze_numerics(main, fetch_names=[loss.name])
    assert rep.is_training
    assert "PT900" not in _codes(rep)
    assert "PT901" not in _codes(rep)


# ---------------------------------------------------------------------------
# PT906: the quantizability work-list
# ---------------------------------------------------------------------------

def _forward_mlp(act="relu", width=32):
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[width], dtype="float32")
            h = fluid.layers.fc(x, width, act=act)
            pred = fluid.layers.fc(h, width)
    return main, startup, pred


def test_pt906_one_site_per_forward_gemm():
    main, _startup, pred = _forward_mlp()
    rep = analyze_numerics(main, fetch_names=[pred.name])
    gemms = [i for i, op in enumerate(main.global_block.ops)
             if op.type in QUANT_SITE_TYPES]
    assert len(rep.quant_sites) == len(gemms) == 2
    for site in rep.quant_sites:
        assert site["op_idx"] in gemms
        assert site["contraction_width"] == 32
        assert site["quant_annotated"] is False
    assert len(_findings(rep, "PT906")) == 2
    assert all(d.severity == "info" for d in _findings(rep, "PT906"))


def test_pt906_sees_qat_annotations():
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[16], dtype="float32")
            h = fluid.layers.fc(x, 16, act="relu")
            fluid.layers.fc(h, 4)
            quant_aware(main, startup)
    rep = analyze_numerics(main)
    assert rep.quant_sites, "QAT program still has its GEMM sites"
    assert all(s["quant_annotated"] for s in rep.quant_sites), (
        "every input of every site is produced by a fake-quant op after "
        "quant_aware — PT906 must see the annotation")


def test_pt906_is_a_superset_of_fusable_chain_bases():
    """Acceptance: every GEMM the epilogue-fusion pass can claim as a
    chain base is in the PT906 work-list — the int8 PR never discovers a
    fusable site the numerics report missed."""
    for act in ("relu", "gelu"):
        main, _startup, pred = _forward_mlp(act=act, width=128)
        rep = analyze_numerics(main, fetch_names=[pred.name])
        site_idxs = {s["op_idx"] for s in rep.quant_sites
                     if s["block"] == 0}
        decision = fuse_epilogues(main, fetch_names=[pred.name])
        assert decision.applied and decision.n_fused == 2
        # recover the chain bases from the ORIGINAL program: the fused
        # ops' epilogue labels aside, every base op index must be a
        # PT906 site
        from paddle_tpu.analysis.liveness import block_liveness
        from paddle_tpu.analysis.epilogue_fusion import find_fusable_chains
        gb = main.global_block
        feeds = sorted(v.name for v in gb.vars.values() if v.is_data)
        live = block_liveness(gb, feeds, [pred.name])
        chains = find_fusable_chains(main, live, [pred.name])
        assert chains
        for c in chains:
            assert c.op_indices[0] in site_idxs, (
                f"fusable base op {c.op_indices[0]} missing from the "
                f"PT906 work-list {sorted(site_idxs)}")


def test_calibration_is_tracked_separately_from_proofs():
    """Observed abs-max seeds flow but never enter the proven set — the
    witness containment surface stays calibration-free."""
    main, startup = fluid.Program(), fluid.Program()
    with un.guard(), fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.scale(x, scale=2.0)
    rep = analyze_numerics(main, calibration={"x": 3.0})
    assert rep.intervals["x"].to_tuple() == (-3.0, 3.0)
    assert rep.intervals[y.name].known            # the seed propagated
    assert {"x", y.name} <= rep.calibrated        # ...but stays tainted
    assert "x" not in rep.bounded_intervals(proven_only=True)
    assert y.name not in rep.bounded_intervals(proven_only=True)
    assert "x" in rep.bounded_intervals(proven_only=False)
    # static_intervals is the proven surface: no calibration at all
    assert "x" not in static_intervals(main)
    # and the PT906 site record carries the calibrated abs-max
    with un.guard():
        m2, s2 = fluid.Program(), fluid.Program()
        with fluid.program_guard(m2, s2):
            a = fluid.layers.data("a", shape=[8, 8], dtype="float32")
            b = fluid.layers.data("b", shape=[8, 8], dtype="float32")
            fluid.layers.matmul(a, b)
    rep2 = analyze_numerics(m2, calibration={"a": 1.5})
    (site,) = rep2.quant_sites
    assert site["calibrated_absmax"] == {"a": 1.5}


# ---------------------------------------------------------------------------
# QAT x epilogue fusion: the pass-order contract (docs/ANALYSIS.md)
# ---------------------------------------------------------------------------

def test_qat_then_fusion_keeps_the_pt900_contract():
    """Legal order: quant_aware BEFORE epilogue fusion. The fused op is a
    legal fake-quant consumer (QUANT_CONSUMER_TYPES), so PT900 holds on
    the fused program too."""
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[128], dtype="float32")
            h = fluid.layers.fc(x, 128, act="relu")
            pred = fluid.layers.fc(h, 128)
            quant_aware(main, startup)
    decision = fuse_epilogues(main, fetch_names=[pred.name])
    assert decision.applied, decision.reason
    fused = decision.program
    types = [op.type for op in fused.global_block.ops]
    assert "fused_gemm_epilogue" in types
    assert any(t in FAKE_QUANT_TYPES for t in types), (
        "fusion must not swallow the fake-quant annotations")
    rep = analyze_numerics(fused, fetch_names=[pred.name])
    assert "PT900" not in _codes(rep), [
        d.message for d in _findings(rep, "PT900")]


def test_fusion_then_qat_refuses_loudly():
    """Illegal order: quantizing an already-fused program must raise —
    the QAT pass cannot annotate operands a fused op swallowed."""
    main, _startup, pred = _forward_mlp(width=128)
    decision = fuse_epilogues(main, fetch_names=[pred.name])
    assert decision.applied
    startup = fluid.Program()
    with pytest.raises(ValueError, match="BEFORE epilogue fusion"):
        quant_aware(decision.program, startup)


# ---------------------------------------------------------------------------
# pass registration
# ---------------------------------------------------------------------------

def test_numerics_check_is_a_registered_analysis_pass():
    assert "numerics_check" in ALL_ANALYSIS_PASSES
    with un.guard():
        mod = _fixture("pt905_nonfinite")
        main, _startup, fetch = mod.build()
    result = default_pass_manager().run_pipeline(
        main, ("numerics_check",), fetch_names=list(fetch), verify="none")
    assert "PT905" in {d.code for d in result.diagnostics}
    rep = result.values["numerics_check"]
    assert isinstance(rep, NumericsReport)
    # the analysis cache serves the same report object back
    assert result.context.analysis("numerics_check") is rep


def test_numerics_check_reads_calibration_option():
    main, startup = fluid.Program(), fluid.Program()
    with un.guard(), fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        fluid.layers.scale(x, scale=2.0)
    result = default_pass_manager().run_pipeline(
        main, ("numerics_check",),
        options={"numerics_calibration": {"x": 7.0}}, verify="none")
    rep = result.values["numerics_check"]
    assert rep.intervals["x"].to_tuple() == (-7.0, 7.0)
    assert "x" in rep.calibrated
