"""High-level Trainer/Inferencer + fs shim (reference contrib/trainer.py,
contrib/inferencer.py, framework/io/fs.h)."""
import os

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.unique_name as un


def _train_func():
    x = fluid.layers.data("x", shape=[13], dtype="float32")
    y = fluid.layers.data("y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(x, 1, name="fit")
    return fluid.layers.mean(fluid.layers.square_error_cost(pred, y))


def _infer_func():
    x = fluid.layers.data("x", shape=[13], dtype="float32")
    return fluid.layers.fc(x, 1, name="fit")


def test_trainer_events_checkpoints_and_inferencer(tmp_path):
    from paddle_tpu.dataset import uci_housing

    events = {"epochs": 0, "steps": 0, "losses": []}

    def handler(ev):
        if isinstance(ev, fluid.contrib.EndEpochEvent):
            events["epochs"] += 1
        elif isinstance(ev, fluid.contrib.EndStepEvent):
            events["steps"] += 1
            if ev.metrics:
                events["losses"].append(ev.metrics[0])

    ckpt = fluid.contrib.CheckpointConfig(str(tmp_path / "ckpt"),
                                          max_num_checkpoints=2,
                                          step_interval=5)
    with un.guard():
        trainer = fluid.contrib.Trainer(_train_func,
                                        lambda: fluid.optimizer.SGD(0.01),
                                        checkpoint_config=ckpt)
        reader = fluid.reader.batch(uci_housing.train(), batch_size=32,
                                    drop_last=True)
        trainer.train(num_epochs=3, event_handler=handler, reader=reader,
                      feed_order=["x", "y"])
        trainer.save_params(str(tmp_path / "params"))
    assert events["epochs"] == 3 and events["steps"] > 10
    assert events["losses"][-1] < events["losses"][0]
    # rotation kept at most 2 checkpoints
    kept = [n for n in os.listdir(str(tmp_path / "ckpt"))
            if n.startswith("checkpoint_")]
    assert 0 < len(kept) <= 2

    with un.guard():
        inf = fluid.contrib.Inferencer(_infer_func,
                                       str(tmp_path / "params"))
    xb = np.random.RandomState(0).randn(4, 13).astype(np.float32)
    out = inf.infer({"x": xb})
    assert np.asarray(out).shape == (4, 1)

    # resume: a fresh trainer on the same ckpt dir restores the step count
    with un.guard():
        t2 = fluid.contrib.Trainer(_train_func,
                                   lambda: fluid.optimizer.SGD(0.01),
                                   checkpoint_config=ckpt)
    assert t2._step > 0


def test_local_fs():
    from paddle_tpu.incubate.fleet.utils.fs import LocalFS

    fs = LocalFS()
    import tempfile

    d = tempfile.mkdtemp()
    fs.mkdirs(os.path.join(d, "a/b"))
    assert fs.is_dir(os.path.join(d, "a/b"))
    p = os.path.join(d, "a/b/f.txt")
    fs.touch(p)
    assert fs.is_file(p) and fs.ls_dir(os.path.join(d, "a/b")) == ["f.txt"]
    fs.mv(p, os.path.join(d, "a/g.txt"))
    assert fs.cat(os.path.join(d, "a/g.txt")) == ""
    fs.delete(d)
    assert not fs.is_exist(d)


def test_hdfs_client_clear_error_without_hadoop():
    from paddle_tpu.incubate.fleet.utils.fs import HDFSClient

    c = HDFSClient(hadoop_home="/nonexistent")
    with pytest.raises(RuntimeError, match="hadoop binary not found"):
        c.mkdirs("/tmp/x")
