"""Dygraph layer zoo round-5 additions (reference dygraph/nn.py:1509-2762:
GRUUnit, NCE, PRelu, BilinearTensorProduct, Conv2DTranspose, GroupNorm,
SpectralNorm, TreeConv, RowConv, SequenceConv) + dygraph LR schedulers
(dygraph/learning_rate_scheduler.py) and eager gradient clipping."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import dygraph

RNG = np.random.RandomState(42)


def _np(v):
    return np.asarray(v.value if hasattr(v, "value") else v)


def test_gru_unit_steps_and_grads():
    with dygraph.guard():
        cell = dygraph.GRUUnit(size=3 * 8)
        x = dygraph.to_variable(RNG.randn(4, 24).astype(np.float32))
        h = dygraph.to_variable(np.zeros((4, 8), np.float32))
        gate, reset, hidden = cell(x, h)
        assert _np(hidden).shape == (4, 8)
        loss = dygraph.ops.reduce_mean(dygraph.ops.square(hidden))
        loss.backward()
        assert cell.weight._grad is not None


def test_nce_layer_trains():
    with dygraph.guard():
        dygraph.seed_parameters(0)
        head = dygraph.NCE(num_total_classes=30, dim=16, num_neg_samples=5)
        opt = fluid.optimizer.SGD(learning_rate=0.3)
        rng = np.random.RandomState(0)
        W = rng.randn(16, 30)
        vals = []
        for _ in range(120):
            xb = rng.randn(32, 16).astype(np.float32)
            yb = (xb @ W).argmax(1)[:, None].astype(np.int64)
            x = dygraph.to_variable(xb)
            y = dygraph.to_variable(yb)
            cost = dygraph.ops.reduce_mean(head(x, y))
            cost.backward()
            opt.minimize(cost, parameter_list=head.parameters())
            head.clear_gradients()
            vals.append(float(_np(cost).reshape(-1)[0]))
        assert vals[-1] < 0.5 * vals[0], (vals[0], vals[-1])


def test_prelu_modes():
    with dygraph.guard():
        x = dygraph.to_variable(RNG.randn(2, 3, 4, 4).astype(np.float32))
        for mode, kw in [("all", {}), ("channel", {"channel": 3}),
                         ("element", {"input_shape": [3, 4, 4]})]:
            layer = dygraph.PRelu(mode=mode, **kw)
            y = _np(layer(x))
            xin = _np(x)
            assert y.shape == xin.shape
            np.testing.assert_allclose(y[xin > 0], xin[xin > 0], rtol=1e-6)
            np.testing.assert_allclose(y[xin < 0], 0.25 * xin[xin < 0],
                                       rtol=1e-5)


def test_bilinear_tensor_product():
    with dygraph.guard():
        layer = dygraph.BilinearTensorProduct(input1_dim=4, input2_dim=5,
                                              output_dim=3)
        x = dygraph.to_variable(RNG.randn(6, 4).astype(np.float32))
        y = dygraph.to_variable(RNG.randn(6, 5).astype(np.float32))
        out = layer(x, y)
        assert _np(out).shape == (6, 3)
        W = _np(layer.weight)
        expect = np.einsum("bi,kij,bj->bk", _np(x), W, _np(y)) \
            + _np(layer.bias)
        np.testing.assert_allclose(_np(out), expect, rtol=1e-4, atol=1e-5)


def test_conv2d_transpose_shape_and_grad():
    with dygraph.guard():
        layer = dygraph.Conv2DTranspose(num_channels=3, num_filters=5,
                                        filter_size=3, stride=2, padding=1)
        x = dygraph.to_variable(RNG.randn(2, 3, 8, 8).astype(np.float32))
        y = layer(x)
        assert _np(y).shape[:2] == (2, 5)
        loss = dygraph.ops.reduce_mean(dygraph.ops.square(y))
        loss.backward()
        assert layer.weight._grad is not None


def test_group_norm_normalizes():
    with dygraph.guard():
        layer = dygraph.GroupNorm(channels=8, groups=2)
        x = dygraph.to_variable(RNG.randn(4, 8, 5, 5).astype(np.float32))
        y = _np(layer(x))
        # per-(sample, group) statistics ~ (0, 1)
        g = y.reshape(4, 2, 4 * 5 * 5)
        np.testing.assert_allclose(g.mean(-1), 0.0, atol=1e-4)
        np.testing.assert_allclose(g.std(-1), 1.0, atol=1e-3)


def test_spectral_norm_shrinks_sigma():
    with dygraph.guard():
        w = RNG.randn(6, 10).astype(np.float32)
        layer = dygraph.SpectralNorm(weight_shape=[6, 10], power_iters=30)
        wv = dygraph.to_variable(w)
        y = _np(layer(wv))
        # sigma_max of the normalized weight must be ~1
        s = np.linalg.svd(y, compute_uv=False)[0]
        np.testing.assert_allclose(s, 1.0, rtol=1e-2)


def test_tree_conv_matches_reference_patch_semantics():
    """Single tree: 1 -> (2, 3); max_depth=2. Hand-computed patch sums via
    the reference eta formulas (tree2col.h:35-52)."""
    with dygraph.guard():
        f, out_sz, k = 2, 3, 1
        layer = dygraph.TreeConv(feature_size=f, output_size=out_sz,
                                 num_filters=k, max_depth=2, act=None)
        nodes = RNG.randn(1, 3, f).astype(np.float32)
        edges = np.array([[[1, 2], [1, 3], [0, 0]]], np.int64)
        y = _np(layer(dygraph.to_variable(nodes),
                      dygraph.to_variable(edges)))
        assert y.shape == (1, 3, out_sz, k)
        W = _np(layer.weight)  # [F, 3(l,r,t), out, k]
        M = 2.0

        def eta(depth, idx, pclen):
            et = (M - depth) / M
            tmp = 0.5 if pclen == 1 else (idx - 1) / (pclen - 1)
            el = (1 - et) * tmp
            er = (1 - et) * (1 - el)
            return el, er, et

        # patch(root=1): (1,idx1,pclen1,d0), (2,idx1,pclen2,d1),
        #                (3,idx2,pclen2,d1)
        expect = np.zeros((out_sz, k))
        for nid, idx, pclen, d in [(1, 1, 1, 0), (2, 1, 2, 1), (3, 2, 2, 1)]:
            el, er, et = eta(d, idx, pclen)
            xv = nodes[0, nid - 1]
            expect += np.einsum("f,fok->ok",
                                xv, el * W[:, 0] + er * W[:, 1]
                                + et * W[:, 2])
        np.testing.assert_allclose(y[0, 0], expect, rtol=1e-4, atol=1e-5)
        # leaves' patches are just themselves (no children): only eta_t
        for nid in (2, 3):
            el, er, et = eta(0, 1, 1)
            exp_leaf = np.einsum("f,fok->ok", nodes[0, nid - 1],
                                 el * W[:, 0] + er * W[:, 1] + et * W[:, 2])
            np.testing.assert_allclose(y[0, nid - 1], exp_leaf, rtol=1e-4,
                                       atol=1e-5)


def test_row_conv_and_sequence_conv():
    with dygraph.guard():
        x = dygraph.to_variable(RNG.randn(2, 6, 4).astype(np.float32))
        rc = dygraph.RowConv(future_context_size=2, dim=4)
        assert _np(rc(x)).shape == (2, 6, 4)
        sc = dygraph.SequenceConv(dim=4, num_filters=7, filter_size=3)
        lens = dygraph.to_variable(np.array([6, 4], np.int32))
        assert _np(sc(x, lens)).shape == (2, 6, 7)


def test_dygraph_lr_schedulers():
    sched = dygraph.ExponentialDecay(learning_rate=1.0, decay_steps=10,
                                     decay_rate=0.5, staircase=True)
    rates = [sched() for _ in range(25)]
    assert rates[0] == 1.0 and rates[9] == 1.0
    assert rates[10] == 0.5 and rates[20] == 0.25

    noam = dygraph.NoamDecay(d_model=64, warmup_steps=10)
    rs = [noam() for _ in range(30)]
    assert np.argmax(rs) == 9  # peak at warmup boundary

    pw = dygraph.PiecewiseDecay([5, 10], [1.0, 0.5, 0.1], begin=0)
    rs = [pw() for _ in range(12)]
    assert rs[0] == 1.0 and rs[5] == 0.5 and rs[11] == 0.1

    cos = dygraph.CosineDecay(1.0, step_each_epoch=2, epochs=4)
    assert abs(cos() - 1.0) < 1e-6

    poly = dygraph.PolynomialDecay(1.0, decay_steps=10,
                                   end_learning_rate=0.1)
    first = poly()
    for _ in range(20):
        last = poly()
    assert first == 1.0 and abs(last - 0.1) < 1e-6


def test_scheduler_drives_optimizer():
    with dygraph.guard():
        fc = dygraph.FC(4, 1)
        sched = dygraph.PiecewiseDecay([2], [0.5, 0.0], begin=0)
        opt = fluid.optimizer.SGD(learning_rate=sched)
        x = dygraph.to_variable(np.ones((2, 4), np.float32))
        w_before = _np(fc.weight).copy()
        for i in range(4):
            loss = dygraph.ops.reduce_mean(fc(x))
            loss.backward()
            opt.minimize(loss, parameter_list=fc.parameters())
            fc.clear_gradients()
            if i == 1:
                w_mid = _np(fc.weight).copy()
        # steps 0-1 move (lr 0.5), steps 2-3 frozen (lr 0.0)
        assert np.abs(w_mid - w_before).max() > 0
        np.testing.assert_array_equal(_np(fc.weight), w_mid)


def test_eager_gradient_clip_global_norm():
    try:
        with dygraph.guard():
            fluid.clip.set_gradient_clip(
                fluid.clip.GradientClipByGlobalNorm(clip_norm=1e-3))
            fc = dygraph.FC(4, 1)
            opt = fluid.optimizer.SGD(learning_rate=1.0)
            x = dygraph.to_variable(100 * np.ones((2, 4), np.float32))
            w0 = _np(fc.weight).copy()
            loss = dygraph.ops.reduce_mean(fc(x))
            loss.backward()
            opt.minimize(loss, parameter_list=fc.parameters())
            # update magnitude bounded by lr * clip_norm
            delta = np.abs(_np(fc.weight) - w0).max()
            assert delta <= 1e-3 + 1e-7, delta
    finally:
        fluid.clip.set_gradient_clip(None)
