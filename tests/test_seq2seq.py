"""Seq2seq + beam search e2e (VERDICT item #2 done-criterion: a seq2seq model
with beam-search decode runs; reference book/test_machine_translation.py)."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu.models.seq2seq import (build_seq2seq_infer,
                                       build_seq2seq_train)

VOCAB = 12
BATCH = 8
SLEN = 5


def _copy_batch(rng, batch):
    """Copy task: target = source; bos=0 eos=1, tokens in [2, VOCAB)."""
    src = rng.randint(2, VOCAB, (batch, SLEN)).astype(np.int64)
    tgt_in = np.concatenate([np.zeros((batch, 1), np.int64), src[:, :-1]], 1)
    return src, tgt_in, src


def test_seq2seq_trains_and_beam_decodes():
    rng = np.random.RandomState(0)
    train = build_seq2seq_train(VOCAB, VOCAB, emb_dim=16, hidden=32,
                                src_len=SLEN, tgt_len=SLEN, batch=BATCH,
                                lr=5e-3)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(train["startup"])
        losses = []
        for step in range(120):
            src, tin, tout = _copy_batch(rng, BATCH)
            losses.append(float(exe.run(
                train["main"],
                feed={"src_ids": src, "tgt_in_ids": tin, "tgt_out_ids": tout},
                fetch_list=[train["loss"]])[0]))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

        # beam decode in the SAME scope (shared parameter names)
        infer = build_seq2seq_infer(VOCAB, VOCAB, emb_dim=16, hidden=32,
                                    src_len=SLEN, batch=4, beam_size=3,
                                    max_len=SLEN)
        # params already trained; run infer startup only for missing vars
        src, _, _ = _copy_batch(rng, 4)
        ids, scores = exe.run(infer["main"], feed={"src_ids": src},
                              fetch_list=infer["fetches"])
    nbk = 4 * 3
    assert ids.shape == (SLEN + 1, nbk)
    assert scores.shape == (SLEN + 1, nbk)
    assert ids.min() >= 0 and ids.max() < VOCAB
    # scores are accumulated log-probs: non-increasing over steps for the
    # top beam of each source
    assert np.isfinite(scores).all()


def test_seq2seq_infer_program_serializes():
    infer = build_seq2seq_infer(VOCAB, VOCAB, emb_dim=8, hidden=16,
                                src_len=4, batch=2, beam_size=2, max_len=4)
    j = infer["main"].to_json()
    back = fluid.Program.from_json(j)
    # sub-blocks survive the round-trip
    assert len(back.blocks) == len(infer["main"].blocks)
    types = [op.type for op in back.global_block.ops]
    assert "while" in types and "beam_search_decode" in types


def test_beam_search_beams_diverge():
    """Round-2 advisor: identical beam slots at step 0 made search greedy —
    with the -1e9 non-first-slot init, distinct hypotheses must survive."""
    rng = np.random.RandomState(1)
    train = build_seq2seq_train(VOCAB, VOCAB, emb_dim=16, hidden=32,
                                src_len=SLEN, tgt_len=SLEN, batch=BATCH,
                                lr=5e-3)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    beam = 3
    with fluid.scope_guard(scope):
        exe.run(train["startup"])
        for _ in range(30):
            src, tin, tout = _copy_batch(rng, BATCH)
            exe.run(train["main"],
                    feed={"src_ids": src, "tgt_in_ids": tin,
                          "tgt_out_ids": tout}, fetch_list=[train["loss"]])
        infer = build_seq2seq_infer(VOCAB, VOCAB, emb_dim=16, hidden=32,
                                    src_len=SLEN, batch=4, beam_size=beam,
                                    max_len=SLEN)
        src, _, _ = _copy_batch(rng, 4)
        ids, scores = exe.run(infer["main"], feed={"src_ids": src},
                              fetch_list=infer["fetches"])
    # per source: the beam hypotheses (token sequences over time) must not
    # all be identical
    diverged = 0
    for b in range(4):
        hyps = {tuple(ids[:, b * beam + k]) for k in range(beam)}
        if len(hyps) > 1:
            diverged += 1
    assert diverged >= 2, f"beams collapsed to greedy: {diverged}/4 diverged"


def test_seq2seq_varlen_trains_across_buckets():
    """Genuinely variable-length batches (VERDICT r2 item 4 done-criterion):
    copy task with lengths 3..12, masked loss, DataFeeder bucketing; batches
    land in two buckets (8, 16) -> exactly two compiled train steps."""
    from paddle_tpu.models.seq2seq import build_seq2seq_train_varlen

    import paddle_tpu.unique_name as un

    rng = np.random.RandomState(5)
    with un.guard():
        m = build_seq2seq_train_varlen(VOCAB, VOCAB, emb_dim=16, hidden=32,
                                       lr=1e-2)
    m["main"].random_seed = 13
    feeder = fluid.DataFeeder(feed_list=m["feed_vars"], program=m["main"])
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()

    def make_batch(lo, hi, n=8):
        samples = []
        for _ in range(n):
            L = int(rng.randint(lo, hi + 1))
            s = rng.randint(2, VOCAB, L).astype(np.int64)
            tin = np.concatenate([[0], s[:-1]])
            samples.append((s, tin, s))
        return feeder.feed(samples)

    batches = [make_batch(3, 8), make_batch(9, 12)]  # buckets 8 and 16
    losses = []
    with fluid.scope_guard(scope):
        exe.run(m["startup"])
        for step in range(60):
            (lv,) = exe.run(m["main"], feed=batches[step % 2],
                            fetch_list=[m["loss"].name])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert losses[-1] < losses[0] * 0.5, losses[::12]
    # startup + one executable per bucket (8 and 16)
    assert len(exe._cache) == 3, f"got {len(exe._cache)} cache entries"


def test_varlen_loss_ignores_padding():
    """The same logical batch padded to different max_lens must give the
    same loss (padding contributes nothing) — the padded-vs-packed
    equivalence at model level."""
    from paddle_tpu.models.seq2seq import build_seq2seq_train_varlen

    import paddle_tpu.unique_name as un

    rng = np.random.RandomState(9)
    samples = []
    for _ in range(6):
        L = int(rng.randint(3, 8))
        s = rng.randint(2, VOCAB, L).astype(np.int64)
        samples.append((s, np.concatenate([[0], s[:-1]]), s))
    losses = {}
    for buckets in [(8,), (32,)]:
        with un.guard():
            m = build_seq2seq_train_varlen(VOCAB, VOCAB, emb_dim=16,
                                           hidden=32)
        m["main"].random_seed = 21
        feeder = fluid.DataFeeder(feed_list=m["feed_vars"],
                                  program=m["main"], seq_buckets=buckets)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(m["startup"])
            (lv,) = exe.run(m["main"], feed=feeder.feed(samples),
                            fetch_list=[m["loss"].name])
        losses[buckets[0]] = float(np.asarray(lv).reshape(-1)[0])
    np.testing.assert_allclose(losses[8], losses[32], rtol=1e-5)
