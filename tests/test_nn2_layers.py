"""Round-5 layer-surface tail (layers/nn2.py + ops/misc2.py): deformable
conv family, PS-ROI pooling, sampled softmax, py_func host callback,
SelectedRows utilities, sequence reshape/expand_as/scatter, lstm_unit,
and spot checks across the generic wrappers."""
import numpy as np
import pytest

import paddle_tpu as fluid

RNG = np.random.RandomState(9)


def _run(main, feed, fetch, startup=None):
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        if startup is not None:
            exe.run(startup)
        res = exe.run(main, feed=feed, fetch_list=fetch)
    return [np.asarray(r) for r in res], scope


def test_deformable_conv_zero_offset_equals_conv2d():
    """With offsets=0 and mask=1, deformable conv v2 IS standard conv —
    the exact oracle the reference kernels satisfy."""
    b, c, h, w, o, k = 2, 4, 6, 6, 3, 3
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        img = fluid.layers.data("img", shape=[c, h, w], dtype="float32")
        off = fluid.layers.data("off", shape=[2 * k * k, h, w],
                                dtype="float32")
        msk = fluid.layers.data("msk", shape=[k * k, h, w],
                                dtype="float32")
        y_def = fluid.layers.deformable_conv(
            img, off, msk, num_filters=o, filter_size=k, padding=1,
            param_attr=fluid.ParamAttr(name="w_def"), bias_attr=False)
        y_ref = fluid.layers.conv2d(
            img, o, k, padding=1,
            param_attr=fluid.ParamAttr(name="w_ref"), bias_attr=False)
        main = fluid.default_main_program()
        xb = RNG.rand(b, c, h, w).astype(np.float32)
        feed = {"img": xb,
                "off": np.zeros((b, 2 * k * k, h, w), np.float32),
                "msk": np.ones((b, k * k, h, w), np.float32)}
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            wv = RNG.randn(o, c, k, k).astype(np.float32) * 0.3
            scope.set_var("w_def", wv)
            scope.set_var("w_ref", wv)
            got, ref = [np.asarray(v) for v in exe.run(
                main, feed=feed, fetch_list=[y_def, y_ref])]
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_deformable_conv_offsets_shift_sampling():
    """An integer offset of (0, +1) everywhere shifts sampling one pixel
    right: equals conv over the shifted image (interior columns)."""
    b, c, h, w, k = 1, 2, 6, 6, 1
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        img = fluid.layers.data("img", shape=[c, h, w], dtype="float32")
        off = fluid.layers.data("off", shape=[2, h, w], dtype="float32")
        y = fluid.layers.deformable_conv(
            img, off, None, num_filters=1, filter_size=1, modulated=False,
            param_attr=fluid.ParamAttr(name="w1"), bias_attr=False)
        xb = RNG.rand(b, c, h, w).astype(np.float32)
        offb = np.zeros((b, 2, h, w), np.float32)
        offb[:, 1] = 1.0  # x-offset +1
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            wv = np.ones((1, c, 1, 1), np.float32)
            scope.set_var("w1", wv)
            got = np.asarray(exe.run(fluid.default_main_program(),
                                     feed={"img": xb, "off": offb},
                                     fetch_list=[y])[0])
    expect = xb.sum(1)[:, None, :, 1:]  # shifted left by one in x
    np.testing.assert_allclose(got[..., :-1], expect, rtol=1e-5, atol=1e-6)
    assert np.allclose(got[..., -1], 0)  # sampled outside -> zero


def test_psroi_pool_positions():
    """2x2 PS pooling with oc=1: each bin reads its own channel."""
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        xv = fluid.layers.data("x", shape=[4, 4, 4], dtype="float32")
        rois = fluid.layers.data("rois", shape=[4], dtype="float32",
                                 append_batch_size=False)
        o = fluid.layers.psroi_pool(xv, rois, output_channels=1,
                                    spatial_scale=1.0, pooled_height=2,
                                    pooled_width=2)
        xb = np.zeros((1, 4, 4, 4), np.float32)
        for ch in range(4):
            xb[0, ch] = ch + 1
        feed = {"x": xb, "rois": np.array([[0, 0, 3, 3]], np.float32)}
        got, _ = _run(fluid.default_main_program(), feed, [o])
    np.testing.assert_allclose(got[0].reshape(2, 2),
                               [[1, 2], [3, 4]], rtol=1e-5)


def test_prroi_pool_uniform_image():
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        xv = fluid.layers.data("x", shape=[2, 6, 6], dtype="float32")
        rois = fluid.layers.data("rois", shape=[4], dtype="float32",
                                 append_batch_size=False)
        o = fluid.layers.prroi_pool(xv, rois, spatial_scale=1.0,
                                    pooled_height=2, pooled_width=2)
        xb = np.full((1, 2, 6, 6), 3.5, np.float32)
        feed = {"x": xb, "rois": np.array([[1, 1, 4, 4]], np.float32)}
        got, _ = _run(fluid.default_main_program(), feed, [o])
    np.testing.assert_allclose(got[0], 3.5, rtol=1e-4)


def test_prroi_pool_batched_rois_batch_idx():
    """r5 advisor finding: prroi_pool must honor per-ROI image indices —
    with two distinct uniform images, each ROI pools its own image."""
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        xv = fluid.layers.data("x", shape=[2, 6, 6], dtype="float32")
        rois = fluid.layers.data("rois", shape=[4], dtype="float32",
                                 append_batch_size=False)
        bidx = fluid.layers.data("bidx", shape=[2], dtype="int32",
                                 append_batch_size=False)
        o = fluid.layers.prroi_pool(xv, rois, spatial_scale=1.0,
                                    pooled_height=2, pooled_width=2,
                                    rois_batch_idx=bidx)
        xb = np.stack([np.full((2, 6, 6), 1.0, np.float32),
                       np.full((2, 6, 6), 5.0, np.float32)])
        feed = {"x": xb,
                "rois": np.array([[1, 1, 4, 4], [1, 1, 4, 4]], np.float32),
                "bidx": np.array([0, 1], np.int32)}
        got, _ = _run(fluid.default_main_program(), feed, [o])
    np.testing.assert_allclose(got[0][0], 1.0, rtol=1e-4)
    np.testing.assert_allclose(got[0][1], 5.0, rtol=1e-4)


def test_prroi_pool_batch_roi_nums():
    """BatchRoINums [B] (the reference's signature): counts per image
    resolve to the same per-ROI indices."""
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        xv = fluid.layers.data("x", shape=[1, 6, 6], dtype="float32")
        rois = fluid.layers.data("rois", shape=[4], dtype="float32",
                                 append_batch_size=False)
        nums = fluid.layers.data("nums", shape=[2], dtype="int32",
                                 append_batch_size=False)
        o = fluid.layers.prroi_pool(xv, rois, spatial_scale=1.0,
                                    pooled_height=1, pooled_width=1,
                                    batch_roi_nums=nums)
        xb = np.stack([np.full((1, 6, 6), 2.0, np.float32),
                       np.full((1, 6, 6), 7.0, np.float32)])
        feed = {"x": xb,
                "rois": np.array([[1, 1, 4, 4]] * 3, np.float32),
                "nums": np.array([1, 2], np.int32)}  # img0: 1 ROI, img1: 2
        got, _ = _run(fluid.default_main_program(), feed, [o])
    np.testing.assert_allclose(got[0].reshape(-1), [2.0, 7.0, 7.0],
                               rtol=1e-4)


def test_psroi_pool_batched_rois_batch_idx():
    """psroi_pool honors per-ROI image indices like its prroi sibling."""
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        xv = fluid.layers.data("x", shape=[4, 6, 6], dtype="float32")
        rois = fluid.layers.data("rois", shape=[4], dtype="float32",
                                 append_batch_size=False)
        bidx = fluid.layers.data("bidx", shape=[2], dtype="int32",
                                 append_batch_size=False)
        o = fluid.layers.psroi_pool(xv, rois, output_channels=1,
                                    spatial_scale=1.0, pooled_height=2,
                                    pooled_width=2, rois_batch_idx=bidx)
        xb = np.stack([np.full((4, 6, 6), 2.0, np.float32),
                       np.full((4, 6, 6), 8.0, np.float32)])
        feed = {"x": xb,
                "rois": np.array([[1, 1, 4, 4], [1, 1, 4, 4]], np.float32),
                "bidx": np.array([0, 1], np.int32)}
        got, _ = _run(fluid.default_main_program(), feed, [o])
    np.testing.assert_allclose(got[0][0], 2.0, rtol=1e-4)
    np.testing.assert_allclose(got[0][1], 8.0, rtol=1e-4)


def test_psroi_pool_multibatch_without_index_refuses():
    """psroi_pool with batch > 1 and no RoisBatchIdx must raise, not pool
    every ROI from image 0 (same contract as prroi_pool)."""
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        xv = fluid.layers.data("x", shape=[4, 6, 6], dtype="float32")
        rois = fluid.layers.data("rois", shape=[4], dtype="float32",
                                 append_batch_size=False)
        o = fluid.layers.psroi_pool(xv, rois, output_channels=1,
                                    spatial_scale=1.0, pooled_height=2,
                                    pooled_width=2)
        feed = {"x": np.ones((2, 4, 6, 6), np.float32),
                "rois": np.array([[1, 1, 4, 4]], np.float32)}
        with pytest.raises(Exception, match="psroi_pool.*batch"):
            _run(fluid.default_main_program(), feed, [o])


def test_prroi_pool_multibatch_without_index_refuses():
    """Batch > 1 with no batch-index information must raise, not silently
    pool every ROI from image 0."""
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        xv = fluid.layers.data("x", shape=[2, 6, 6], dtype="float32")
        rois = fluid.layers.data("rois", shape=[4], dtype="float32",
                                 append_batch_size=False)
        o = fluid.layers.prroi_pool(xv, rois, spatial_scale=1.0,
                                    pooled_height=2, pooled_width=2)
        feed = {"x": np.ones((2, 2, 6, 6), np.float32),
                "rois": np.array([[1, 1, 4, 4]], np.float32)}
        with pytest.raises(Exception, match="prroi_pool.*batch"):
            _run(fluid.default_main_program(), feed, [o])


def test_deformable_roi_pooling_batched_rois_batch_idx():
    """deformable_psroi_pooling honors RoisBatchIdx (r5 advisor finding):
    no_trans + uniform per-image values -> each ROI reads its image."""
    gs = (1, 1)
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        xv = fluid.layers.data("x", shape=[2, 6, 6], dtype="float32")
        rois = fluid.layers.data("rois", shape=[4], dtype="float32",
                                 append_batch_size=False)
        trans = fluid.layers.data("trans", shape=[2, 2, 1, 1],
                                  dtype="float32", append_batch_size=False)
        bidx = fluid.layers.data("bidx", shape=[2], dtype="int32",
                                 append_batch_size=False)
        o = fluid.layers.deformable_roi_pooling(
            xv, rois, trans, no_trans=True, group_size=list(gs),
            pooled_height=1, pooled_width=1, sample_per_part=2,
            rois_batch_idx=bidx)
        xb = np.stack([np.full((2, 6, 6), 1.5, np.float32),
                       np.full((2, 6, 6), 4.5, np.float32)])
        feed = {"x": xb,
                "rois": np.array([[1, 1, 4, 4], [1, 1, 4, 4]], np.float32),
                "trans": np.zeros((2, 2, 1, 1), np.float32),
                "bidx": np.array([0, 1], np.int32)}
        got, _ = _run(fluid.default_main_program(), feed, [o])
    np.testing.assert_allclose(got[0][0], 1.5, rtol=1e-4)
    np.testing.assert_allclose(got[0][1], 4.5, rtol=1e-4)


def test_sampled_softmax_with_cross_entropy_trains():
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        xv = fluid.layers.data("x", shape=[8], dtype="float32")
        yv = fluid.layers.data("y", shape=[1], dtype="int64")
        logits = fluid.layers.fc(xv, 40)
        loss = fluid.layers.mean(
            fluid.layers.sampled_softmax_with_cross_entropy(
                logits, yv, num_samples=8))
        fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        rng = np.random.RandomState(0)
        W = rng.randn(8, 40)
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            vals = []
            for _ in range(60):
                xb = rng.randn(32, 8).astype(np.float32)
                yb = (xb @ W).argmax(1)[:, None].astype(np.int64)
                out = exe.run(fluid.default_main_program(),
                              feed={"x": xb, "y": yb}, fetch_list=[loss])
                vals.append(float(np.asarray(out[0]).reshape(-1)[0]))
    assert vals[-1] < 0.6 * vals[0], (vals[0], vals[-1])


def test_py_func_host_callback():
    def double_plus_one(a):
        return (2.0 * a + 1.0).astype(np.float32)

    with fluid.program_guard(fluid.Program(), fluid.Program()):
        xv = fluid.layers.data("x", shape=[3], dtype="float32",
                               append_batch_size=False)
        blk = fluid.default_main_program().global_block
        ov = blk.create_var(name="py_out", shape=(2, 3), dtype="float32")
        fluid.layers.py_func(double_plus_one, xv, ov)
        xb = RNG.rand(2, 3).astype(np.float32)
        got, _ = _run(fluid.default_main_program(), {"x": xb}, ["py_out"])
    np.testing.assert_allclose(got[0], 2 * xb + 1, rtol=1e-6)


def test_selected_rows_utility_layers():
    from paddle_tpu.core.selected_rows import SelectedRows

    with fluid.program_guard(fluid.Program(), fluid.Program()):
        ids = fluid.layers.data(name="ids", shape=[4], dtype="int64")
        emb = fluid.layers.embedding(ids, size=[20, 6], is_sparse=True,
                                     param_attr=fluid.ParamAttr(name="tw"))
        loss = fluid.layers.mean(emb)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        dense_grad = fluid.layers.get_tensor_from_selected_rows(
            fluid.layers.merge_selected_rows(
                fluid.default_main_program().global_block.var("tw@GRAD")))
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            got = np.asarray(exe.run(
                fluid.default_main_program(),
                feed={"ids": np.array([[1, 2, 2, 5]], np.int64)},
                fetch_list=[dense_grad])[0])
    assert got.shape == (20, 6)
    assert (np.abs(got[[1, 2, 5]]).sum(1) > 0).all()
    untouched = np.ones(20, bool)
    untouched[[1, 2, 5]] = False
    assert (got[untouched] == 0).all()


def test_sequence_reshape_and_expand_as_and_scatter():
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        xv = fluid.layers.data("x", shape=[4], dtype="float32",
                               lod_level=1)
        r = fluid.layers.sequence_reshape(xv, new_dim=2)
        row = fluid.layers.data("row", shape=[3], dtype="float32")
        e = fluid.layers.sequence_expand_as(row, xv)
        base = fluid.layers.data("base", shape=[6], dtype="float32")
        idx = fluid.layers.data("idx", shape=[1], dtype="int64",
                                lod_level=1)
        upd = fluid.layers.data("upd", shape=[1], dtype="float32",
                                lod_level=1)
        sc = fluid.layers.sequence_scatter(base, idx, upd)
        feed = {
            "x": RNG.rand(2, 3, 4).astype(np.float32),
            "x@LOD": np.array([3, 2], np.int32),
            "row": RNG.rand(2, 3).astype(np.float32),
            "base": np.zeros((2, 6), np.float32),
            "idx": np.array([[[0], [2], [2]], [[5], [1], [0]]], np.int64),
            "idx@LOD": np.array([3, 2], np.int32),
            "upd": np.ones((2, 3, 1), np.float32),
            "upd@LOD": np.array([3, 2], np.int32),
        }
        got, _ = _run(fluid.default_main_program(), feed, [r, e, sc])
    assert got[0].shape == (2, 6, 2)        # T*D/new_dim = 3*4/2
    assert got[1].shape == (2, 3, 3)
    assert (got[1][0, :3] == got[1][0, 0]).all()
    assert (got[1][1, 2] == 0).all()        # beyond len 2 -> zero
    np.testing.assert_allclose(got[2][0], [1, 0, 2, 0, 0, 0])
    np.testing.assert_allclose(got[2][1], [0, 1, 0, 0, 0, 1])


def test_lstm_unit_composite():
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        xt = fluid.layers.data("xt", shape=[5], dtype="float32")
        h0 = fluid.layers.data("h0", shape=[4], dtype="float32")
        c0 = fluid.layers.data("c0", shape=[4], dtype="float32")
        h1, c1 = fluid.layers.lstm_unit(xt, h0, c0, forget_bias=1.0)
        feed = {"xt": RNG.rand(3, 5).astype(np.float32),
                "h0": np.zeros((3, 4), np.float32),
                "c0": np.zeros((3, 4), np.float32)}
        got, _ = _run(fluid.default_main_program(), feed, [h1, c1],
                      startup=fluid.default_startup_program())
    assert got[0].shape == (3, 4) and got[1].shape == (3, 4)
    assert np.isfinite(got[0]).all()


def test_generic_wrapper_spot_checks():
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        xv = fluid.layers.data("x", shape=[4, 8, 8], dtype="float32")
        lbl = fluid.layers.data("y", shape=[1], dtype="int64")
        outs = {
            "maxout": fluid.layers.maxout(xv, groups=2),
            "s2d": fluid.layers.space_to_depth(xv, 2),
            "pix": fluid.layers.pixel_shuffle(xv, 2),
            "smooth": fluid.layers.label_smooth(
                fluid.layers.one_hot(lbl, 10), epsilon=0.1),
            "pool": fluid.layers.adaptive_pool2d(xv, [2, 2], "avg"),
            "sign": fluid.layers.sign(xv),
            "mse": fluid.layers.mse_loss(
                fluid.layers.flatten(xv),
                fluid.layers.flatten(xv)),
        }
        feed = {"x": RNG.randn(2, 4, 8, 8).astype(np.float32),
                "y": np.array([[3], [7]], np.int64)}
        names = list(outs)
        got, _ = _run(fluid.default_main_program(), feed,
                      [outs[n] for n in names])
    res = dict(zip(names, got))
    assert res["maxout"].shape == (2, 2, 8, 8)
    assert res["s2d"].shape == (2, 16, 4, 4)
    assert res["pix"].shape == (2, 1, 16, 16)
    np.testing.assert_allclose(res["smooth"].sum(-1), 1.0, rtol=1e-5)
    assert res["pool"].shape == (2, 4, 2, 2)
    assert set(np.unique(res["sign"])) <= {-1.0, 0.0, 1.0}
    assert res["mse"] == 0
