"""Static SPMD sharding analysis (analysis/sharding_check.py, ISSUE 12):
positive + negative controls for every PT730-PT744 code, spec propagation
over the real zoo layouts, per-chip memory plans (incl. while sub-blocks),
collective wire volumes and the comms gauges."""
import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.unique_name as un
from paddle_tpu import monitor
from paddle_tpu.analysis import default_pass_manager
from paddle_tpu.analysis.cost_model import (comms_compute_ratio,
                                            estimate_comms, estimate_cost)
from paddle_tpu.analysis.sharding_check import (propagate_sharding,
                                                spec_divisor,
                                                staging_bytes_by_op)
from paddle_tpu.parallel.sharding import extract_param_specs, zero1_spec_for


def codes(analysis):
    return {d.code for d in analysis.diagnostics}


def run(program, mesh, specs=None, fetches=(), feed_spec=None, batch=8,
        **kw):
    return propagate_sharding(program, mesh, param_specs=specs,
                              feed_spec=feed_spec, fetch_names=fetches,
                              batch_size=batch, **kw)


def _param_program(*params, builder=None):
    """Program with the given (name, shape) f32 params and an optional
    builder(block, vars) appending ops."""
    with un.guard():
        main = fluid.Program()
        gb = main.global_block
        vars_ = {}
        for name, shape in params:
            vars_[name] = gb.create_parameter(name, list(shape), "float32")
        if builder is not None:
            builder(gb, vars_)
    return main


# ---------------------------------------------------------------------------
# PT730-PT733: the input-spec contract
# ---------------------------------------------------------------------------

def test_pt730_unknown_mesh_axis():
    p = _param_program(("w", (8, 4)))
    an = run(p, {"dp": 2}, {"w": ("tp",)})
    assert "PT730" in codes(an)
    assert an.param_specs["w"] == (None, None)  # degraded, not crashed
    an2 = run(p, {"dp": 2}, {"w": ("dp",)})
    assert "PT730" not in codes(an2)


def test_pt731_spec_rank_exceeds_var_rank():
    p = _param_program(("w", (8, 4)))
    an = run(p, {"dp": 2}, {"w": ("dp", None, None)})
    assert "PT731" in codes(an)
    assert "PT731" not in codes(run(p, {"dp": 2}, {"w": ("dp", None)}))


def test_pt732_axis_reused_across_dims():
    p = _param_program(("w", (8, 4)))
    an = run(p, {"dp": 2}, {"w": ("dp", "dp")})
    assert "PT732" in codes(an)
    # first use wins, second degrades
    assert an.param_specs["w"] == ("dp", None)
    assert "PT732" not in codes(run(p, {"dp": 2}, {"w": ("dp", None)}))


def test_pt733_indivisible_static_dim():
    p = _param_program(("w", (10, 4)))
    an = run(p, {"dp": 4}, {"w": ("dp",)})
    assert "PT733" in codes(an)
    assert an.param_specs["w"] == (None, None)  # kept whole
    p2 = _param_program(("w", (8, 4)))
    assert "PT733" not in codes(run(p2, {"dp": 4}, {"w": ("dp",)}))


def test_pt733_dynamic_dim_is_runtime_contract():
    """A -1 batch dim is resolved at feed time — no static indivisibility
    error (the per-chip plan re-checks at the resolved batch)."""
    with un.guard():
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            fluid.layers.scale(x, 2.0)
    an = run(main, {"dp": 8}, batch=2)   # resolved batch NOT divisible
    assert "PT733" not in codes(an)


# ---------------------------------------------------------------------------
# PT734/PT735: inconsistent and unsatisfiable input layouts
# ---------------------------------------------------------------------------

def _add_program(spec_a, spec_b):
    def build(gb, v):
        out = gb.create_var(name="out", shape=(8, 8), dtype="float32")
        gb.append_op("elementwise_add", {"X": "a", "Y": "b"},
                     {"Out": "out"}, {"axis": -1})
    p = _param_program(("a", (8, 8)), ("b", (8, 8)), builder=build)
    return p, {"a": spec_a, "b": spec_b}


def test_pt734_conflicting_elementwise_inputs():
    p, specs = _add_program(("dp",), ("tp",))
    an = run(p, {"dp": 2, "tp": 2}, specs)
    assert "PT734" in codes(an)
    # the losing input pays a reshard
    assert any(c.kind == "reshard" for c in an.collectives)
    p2, specs2 = _add_program(("dp",), ("dp",))
    assert "PT734" not in codes(run(p2, {"dp": 2, "tp": 2}, specs2))


def _matmul_program(spec_x, spec_y):
    def build(gb, v):
        gb.create_var(name="out", shape=(4, 4), dtype="float32")
        gb.append_op("matmul", {"X": "x", "Y": "y"}, {"Out": "out"},
                     {"transpose_X": False, "transpose_Y": False})
    p = _param_program(("x", (4, 8)), ("y", (8, 4)), builder=build)
    return p, {"x": spec_x, "y": spec_y}


def test_pt735_contraction_layout_conflict():
    p, specs = _matmul_program((None, "dp"), ("tp", None))
    an = run(p, {"dp": 2, "tp": 2}, specs)
    assert "PT735" in codes(an)
    # agreeing contraction shardings are a partial sum, not a conflict
    p2, specs2 = _matmul_program((None, "dp"), ("dp", None))
    an2 = run(p2, {"dp": 2, "tp": 2}, specs2)
    assert "PT735" not in codes(an2)
    assert any(c.kind == "all_reduce" and c.var == "out"
               for c in an2.collectives)


# ---------------------------------------------------------------------------
# PT736: implicit full replication of a large tensor
# ---------------------------------------------------------------------------

def _reshape_fold_program():
    with un.guard():
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            x = fluid.layers.data("x", shape=[64, 512], dtype="float32",
                                  append_batch_size=False)
            fluid.layers.reshape(x, shape=[64 * 512])
    return main


def test_pt736_large_tensor_replicated():
    an = run(_reshape_fold_program(), {"dp": 8}, batch=64, large_bytes=1024)
    assert "PT736" in codes(an)
    # the lost batch axis costs an all-gather of the input
    assert any(c.kind == "all_gather" for c in an.collectives)
    # raising the threshold silences it (and nothing else fires)
    an2 = run(_reshape_fold_program(), {"dp": 8}, batch=64,
              large_bytes=1 << 30)
    assert "PT736" not in codes(an2)


def test_pt736_not_fired_when_collective_explains_it():
    """A DP grad all-reduce produces a replicated grad by contract — the
    recorded collective explains the replication, no PT736."""
    with un.guard():
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            x = fluid.layers.data("x", shape=[512], dtype="float32")
            y = fluid.layers.fc(x, 512, bias_attr=False, name="big")
            loss = fluid.layers.mean(y)
            fluid.optimizer.SGD(0.1).minimize(loss)
    an = run(main, {"dp": 8}, batch=64, fetches=[loss.name],
             large_bytes=1024)
    assert any(c.kind == "all_reduce" and c.var.endswith("@GRAD")
               for c in an.collectives)
    assert "PT736" not in codes(an)


# ---------------------------------------------------------------------------
# PT737/PT741: resharding inside the training loop / donation invalidated
# ---------------------------------------------------------------------------

def _state_reshard_program(p_spec):
    def build(gb, v):
        gb.create_var(name="z", shape=(8, 4), dtype="float32")
        # read w (so it is live-in and donation-eligible) ...
        gb.append_op("elementwise_add", {"X": "w", "Y": "w"}, {"Out": "z"},
                     {"axis": -1})
        # ... then overwrite it from another layout
        gb.append_op("assign", {"X": "p"}, {"Out": "w"})
    return _param_program(("w", (8, 4)), ("p", (8, 4)), builder=build), \
        {"w": ("dp",), "p": p_spec}


def test_pt737_pt741_state_layout_change():
    from paddle_tpu.analysis.liveness import _donation_analysis

    p, specs = _state_reshard_program(())          # p replicated
    cands, unsafe, _live = _donation_analysis(p.global_block, [], [])
    an = run(p, {"dp": 2}, specs,
             liveness_info={"cands": cands, "unsafe": unsafe})
    assert "PT737" in codes(an)
    assert "PT741" in codes(an)
    # same layout in and out: neither fires
    p2, specs2 = _state_reshard_program(("dp",))
    cands2, unsafe2, _ = _donation_analysis(p2.global_block, [], [])
    an2 = run(p2, {"dp": 2}, specs2,
              liveness_info={"cands": cands2, "unsafe": unsafe2})
    assert "PT737" not in codes(an2)
    assert "PT741" not in codes(an2)


# ---------------------------------------------------------------------------
# PT738/PT739/PT740: the optimizer update layouts
# ---------------------------------------------------------------------------

def _sgd_program(grad_spec):
    def build(gb, v):
        gb.create_var(name="lr", shape=(1,), dtype="float32",
                      persistable=True)
        gb.append_op("sgd", {"Param": "w", "Grad": "g",
                             "LearningRate": "lr"}, {"ParamOut": "w"})
    p = _param_program(("w", (8, 4)), ("g", (8, 4)), builder=build)
    return p, {"g": grad_spec} if grad_spec else {}


def test_pt738_grad_param_layout_disagreement():
    p, specs = _sgd_program(("dp",))
    an = run(p, {"dp": 2}, specs)
    assert "PT738" in codes(an)
    p2, specs2 = _sgd_program(None)
    assert "PT738" not in codes(run(p2, {"dp": 2}, specs2))


def _momentum_program(vel_spec):
    def build(gb, v):
        gb.create_var(name="lr", shape=(1,), dtype="float32",
                      persistable=True)
        gb.append_op("momentum",
                     {"Param": "w", "Grad": "g", "Velocity": "vel",
                      "LearningRate": "lr"},
                     {"ParamOut": "w", "VelocityOut": "vel"},
                     {"mu": 0.9})
    p = _param_program(("w", (8, 8)), ("g", (8, 8)), ("vel", (8, 8)),
                       builder=build)
    return p, {"vel": vel_spec}


def test_pt739_non_zero_state_layout():
    # dim-1 sharded state is NOT the ZeRO dim-0-over-dp pattern
    p, specs = _momentum_program((None, "dp"))
    an = run(p, {"dp": 2}, specs)
    assert "PT739" in codes(an)
    assert "PT740" not in codes(an)


def test_pt740_zero_layout_recognized():
    p, specs = _momentum_program(("dp",))
    an = run(p, {"dp": 2}, specs)
    assert "PT740" in codes(an)
    assert "PT739" not in codes(an)
    kinds = {c.kind for c in an.collectives}
    assert "reduce_scatter" in kinds and "all_gather" in kinds


def test_pt740_zero_rewrites_grad_all_reduce():
    """Under the ZeRO layout the grad's DP all-reduce becomes a
    reduce-scatter (plus the param all-gather) — never both an AR and an
    RS for the same grad."""
    with un.guard():
        m = fluid.Program()
        with fluid.program_guard(m, fluid.Program()):
            x = fluid.layers.data("x", shape=[16], dtype="float32")
            y = fluid.layers.fc(x, 8, bias_attr=False, name="zf")
            loss = fluid.layers.mean(y)
            fluid.optimizer.Momentum(0.1, 0.9).minimize(loss)
    specs, feed_spec = extract_param_specs(m, {"dp": 8}, zero=True)
    assert any(v == ("dp",) for v in specs.values())
    an = run(m, {"dp": 8}, specs, fetches=[loss.name], batch=16)
    assert "PT740" in codes(an)
    grads_ar = {c.var for c in an.collectives if c.kind == "all_reduce"}
    grads_rs = {c.var for c in an.collectives if c.kind == "reduce_scatter"}
    assert not (grads_ar & grads_rs)
    assert any(v.endswith("@GRAD") for v in grads_rs)


# ---------------------------------------------------------------------------
# PT742/PT743/PT744
# ---------------------------------------------------------------------------

def _fc_loss_program():
    with un.guard():
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            y = fluid.layers.fc(x, 4, name="f")
            loss = fluid.layers.mean(y)
    return main, y.name, loss.name


def test_pt742_feed_not_dp_sharded():
    main, _, loss = _fc_loss_program()
    an = run(main, {"dp": 8}, feed_spec=(), fetches=[loss], batch=16)
    assert "PT742" in codes(an)
    an2 = run(main, {"dp": 8}, fetches=[loss], batch=16)  # default ('dp',)
    assert "PT742" not in codes(an2)


def test_pt743_sharded_fetch():
    main, y, loss = _fc_loss_program()
    an = run(main, {"dp": 8}, fetches=[y], batch=16)
    assert "PT743" in codes(an)
    assert any(c.kind == "all_gather" and c.var == y
               for c in an.collectives)
    # a replicated fetch (post-reduction loss) is fine
    an2 = run(main, {"dp": 8}, fetches=[loss], batch=16)
    assert "PT743" not in codes(an2)


def test_pt744_unknown_op_conservative():
    with un.guard():
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            gb = main.global_block
            gb.create_var(name="shp", shape=(2,), dtype="int64")
            gb.append_op("shape", {"Input": x.name}, {"Out": "shp"})
    an = run(main, {"dp": 8}, batch=16)
    assert "PT744" in codes(an)
    assert an.spec_of("shp") == (None,)
    # with the feed replicated nothing is being dropped -> silent
    an2 = run(main, {"dp": 8}, feed_spec=(), batch=16)
    assert "PT744" not in codes(an2)


def test_known_reductions_do_not_pt744():
    with un.guard():
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            loss = fluid.layers.mean(x)
    an = run(main, {"dp": 8}, fetches=[loss.name], batch=16)
    assert "PT744" not in codes(an)
    assert any(c.kind == "all_reduce" and c.var == loss.name
               for c in an.collectives)


# ---------------------------------------------------------------------------
# propagation over real programs
# ---------------------------------------------------------------------------

def test_dp_grad_all_reduce_derived_for_every_param():
    """Data parallelism's defining collective — one all-reduce (or ZeRO
    reduce-scatter) per param grad — falls out of spec propagation."""
    from paddle_tpu.models.mlp import build_mnist_mlp

    with un.guard():
        m = build_mnist_mlp()
    an = run(m["main"], {"dp": 8}, fetches=[m["loss"].name], batch=64)
    params = {p.name for p in m["main"].all_parameters()}
    reduced = {c.var[:-len("@GRAD")] for c in an.collectives
               if c.kind == "all_reduce" and c.var.endswith("@GRAD")}
    assert params == reduced
    # grad AR bytes equal the param bytes
    by_var = {c.var: c.bytes_full for c in an.collectives
              if c.kind == "all_reduce"}
    assert by_var["fc_0.w_0@GRAD"] == 784 * 200 * 4


def test_batch_spec_propagates_through_transformer():
    from paddle_tpu.models import BertConfig, build_bert_pretrain

    with un.guard():
        m = build_bert_pretrain(BertConfig.tiny(), seq_len=32)
    an = run(m["main"], {"dp": 8}, fetches=[m["loss"].name], batch=64)
    # no errors, and the batch axis survives the whole encoder stack —
    # embeddings, reshape/transpose head splits, fused attention, FFN:
    # the bulk of the activations stay dp-sharded
    assert not any(d.severity == "error" for d in an.diagnostics)
    params = {p.name for p in m["main"].all_parameters()}
    sharded_acts = [n for n, s in an.var_specs.items()
                    if s[:1] == ("dp",) and n not in params]
    assert len(sharded_acts) > 50, sharded_acts
    # attention outputs specifically (deepest layer)
    assert any(n.startswith("fused_multihead_attention_1")
               for n in sharded_acts)


def test_zoo_is_pt73x_clean_under_dp8_zero():
    """The lint-gate contract, as a test: training-zoo programs produce
    no gating PT73x findings under the dp=8 ZeRO assignment."""
    from paddle_tpu.models import build_deepfm

    with un.guard():
        m = build_deepfm()
    specs, _ = extract_param_specs(m["main"], {"dp": 8}, zero=True)
    an = run(m["main"], {"dp": 8}, specs,
             fetches=[m["loss"].name], batch=64)
    gating = {d.code for d in an.diagnostics
              if d.code in ("PT730", "PT731", "PT732", "PT733", "PT734",
                            "PT735", "PT736", "PT737", "PT738", "PT739",
                            "PT741", "PT742")}
    assert not gating, gating


def test_shared_subblock_collectives_counted_once():
    """seq2seq's recurrent bodies are each referenced by BOTH the forward
    recurrent op and recurrent_grad — propagation must walk a block once
    (the liveness _seen guard), never double-recording its collectives."""
    from paddle_tpu.models import build_seq2seq_train

    with un.guard():
        m = build_seq2seq_train(src_vocab=50, tgt_vocab=50)
    owners = {}
    for blk in m["main"].blocks:
        for op in blk.ops:
            sub = op.attrs.get("sub_block")
            if isinstance(sub, int):
                owners.setdefault(sub, []).append(op.type)
    assert any(len(v) > 1 for v in owners.values()), \
        "precondition: seq2seq shares sub-blocks between fwd and grad ops"
    an = run(m["main"], {"dp": 8}, fetches=[m["loss"].name], batch=64)
    seen = {}
    for c in an.collectives:
        key = (c.block_idx, c.op_idx, c.kind, c.var)
        assert key not in seen, f"collective recorded twice: {key}"
        seen[key] = c


def test_registered_pass_requires_liveness_and_noop_without_mesh():
    main, _, loss = _fc_loss_program()
    mgr = default_pass_manager()
    res = mgr.run_pipeline(main, ("sharding_check",), fetch_names=[loss],
                           verify="none")
    assert res.values["sharding_check"] is None
    assert not [d for d in res.diagnostics if d.code.startswith("PT73")]
    res2 = mgr.run_pipeline(main, ("sharding_check",), fetch_names=[loss],
                            batch_size=16,
                            options={"mesh": {"dp": 8}}, verify="none")
    an = res2.values["sharding_check"]
    assert an is not None and an.mesh == {"dp": 8}
    assert res2.context.has_analysis("liveness")  # the declared dependency


# ---------------------------------------------------------------------------
# per-chip memory plans
# ---------------------------------------------------------------------------

def test_single_device_plan_bit_identical():
    """The mesh=None path must be byte-identical to the pre-sharding
    planner: no spec keys in entries, no mesh keys in the dict."""
    from paddle_tpu.models.mlp import build_mnist_mlp

    with un.guard():
        m = build_mnist_mlp()
    fetches = [m["loss"].name, m["acc"].name]
    p1 = m["main"].memory_plan(fetch_names=fetches, batch_size=64)
    p2 = m["main"].memory_plan(fetch_names=fetches, batch_size=64)
    assert p1.to_dict() == p2.to_dict()
    assert p1.mesh is None and p1.staging_timeline is None
    assert all("spec" not in e.to_dict() for e in p1.entries)


def test_per_chip_plan_divides_sharded_state():
    from paddle_tpu.models.mlp import build_mnist_mlp

    with un.guard():
        m = build_mnist_mlp(optimizer="adam")
    fetches = [m["loss"].name, m["acc"].name]
    plain = m["main"].memory_plan(fetch_names=fetches, batch_size=64)
    specs, _ = extract_param_specs(m["main"], {"dp": 8}, zero=True)
    chip = m["main"].memory_plan(fetch_names=fetches, batch_size=64,
                                 mesh={"dp": 8}, specs=specs)
    assert chip.mesh == {"dp": 8}
    assert chip.peak_bytes < plain.peak_bytes
    ent = {e.name: e for e in chip.entries}
    mom = next(e for n, e in ent.items() if n.startswith("moment1_fc_0.w"))
    assert mom.spec[:1] == ("dp",)
    assert mom.global_bytes == mom.bytes * 8
    # replicated params count whole
    w = ent["fc_0.w_0"]
    assert w.bytes == w.global_bytes
    # dp-sharded feed divides by 8
    img = ent["img"]
    assert img.global_bytes == img.bytes * 8


def test_per_chip_plan_includes_collective_staging():
    from paddle_tpu.models.mlp import build_mnist_mlp

    with un.guard():
        m = build_mnist_mlp()
    fetches = [m["loss"].name]
    plan = m["main"].memory_plan(fetch_names=fetches, batch_size=64,
                                 mesh={"dp": 8})
    assert plan.staging_timeline is not None
    assert max(plan.staging_timeline) > 0
    st = staging_bytes_by_op(plan.sharding)
    (bidx, oi), nbytes = max(st.items(), key=lambda kv: kv[1])
    assert bidx == 0
    assert plan.staging_timeline[oi] >= nbytes


def test_per_chip_while_subblock_not_undercounted():
    """The conservative sub-block capture: sub-block-local vars carry no
    spec and count whole, and the sub-block peak still lands on the
    owning op — per-chip never under-counts the loop body."""
    from tests.test_while_grad import _build_while

    main, startup, loss = _build_while()
    plain = main.memory_plan(fetch_names=[loss.name], batch_size=16)
    chip = main.memory_plan(fetch_names=[loss.name], batch_size=16,
                            mesh={"dp": 4})
    assert plain.sub_plans and chip.sub_plans
    for oi, sub in chip.sub_plans.items():
        assert sub.mesh == {"dp": 4}
        # every sub-block entry either carries a propagated spec or is
        # counted at FULL size (never silently divided)
        for e in sub.entries:
            if not e.spec or all(a is None for a in e.spec):
                assert e.bytes == e.global_bytes
        # the owning op's timeline point carries the sub-block peak
        assert chip.timeline[oi] >= sub.peak_bytes
    # x is [T, B, D] with a STATIC leading dim — not batch sharded, so
    # the while program per-chip peak equals the single-device peak for
    # the sub-block portion (conservative, not divided)
    for oi in plain.sub_plans:
        assert chip.sub_plans[oi].peak_bytes == plain.sub_plans[oi].peak_bytes


# ---------------------------------------------------------------------------
# collective cost model + gauges
# ---------------------------------------------------------------------------

def test_wire_volume_formulas():
    from paddle_tpu.analysis.sharding_check import (CollectiveEvent,
                                                    ShardingAnalysis)

    an = ShardingAnalysis(
        mesh={"dp": 8}, batch_size=1, var_specs={}, param_specs={},
        feed_spec=(), diagnostics=[],
        collectives=[
            CollectiveEvent(0, 0, "all_reduce", "dp", "g", 800, ""),
            CollectiveEvent(0, 1, "all_gather", "dp", "p", 800, ""),
            CollectiveEvent(0, 2, "reduce_scatter", "dp", "h", 800, ""),
        ])
    comms = estimate_comms(an)
    # ring: AR = 2*(n-1)/n, AG/RS = (n-1)/n
    assert comms.wire_bytes_by_kind["all_reduce"] == int(800 * 2 * 7 / 8)
    assert comms.wire_bytes_by_kind["all_gather"] == int(800 * 7 / 8)
    assert comms.wire_bytes_by_kind["reduce_scatter"] == int(800 * 7 / 8)
    assert comms.total_wire_bytes == sum(comms.wire_bytes_by_kind.values())


def test_comms_compute_ratio_scales_with_bandwidth():
    from paddle_tpu.models.mlp import build_mnist_mlp

    with un.guard():
        m = build_mnist_mlp()
    an = run(m["main"], {"dp": 8}, fetches=[m["loss"].name], batch=64)
    comms = estimate_comms(an)
    cost = estimate_cost(m["main"], batch_size=64)
    r_slow = comms_compute_ratio(comms, cost, peak_tflops=100.0,
                                 ici_gbytes_per_s=10.0)
    r_fast = comms_compute_ratio(comms, cost, peak_tflops=100.0,
                                 ici_gbytes_per_s=100.0)
    assert r_slow == pytest.approx(10.0 * r_fast)
    assert r_fast > 0


def test_observe_comms_cost_gauges():
    from paddle_tpu.models.mlp import build_mnist_mlp

    with un.guard():
        m = build_mnist_mlp()
    monitor.reset()
    an = run(m["main"], {"dp": 8}, fetches=[m["loss"].name], batch=64)
    comms = estimate_comms(an)
    cost = estimate_cost(m["main"], batch_size=64)
    monitor.observe_comms_cost(m["main"], comms, cost)
    serial = str(m["main"]._serial)
    g = monitor.metric_value("executor_comms_gbytes_per_step",
                             program=serial, mesh="dp=8")
    assert g == pytest.approx(comms.gbytes_per_step)
    r = monitor.metric_value("executor_comms_compute_ratio",
                             program=serial, mesh="dp=8")
    assert r == pytest.approx(comms_compute_ratio(comms, cost))


def test_parallel_compile_emits_comms_gauges():
    """The CompiledProgram path records the predicted comms for the mesh
    it actually compiled (the monitor wiring, end to end)."""
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[8], dtype="float32")
            y = fluid.layers.fc(x, 4, name="cg")
            loss = fluid.layers.mean(y)
            fluid.optimizer.SGD(0.1).minimize(loss)
    monitor.reset()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    compiled = fluid.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name)
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(compiled,
                feed={"x": np.ones((16, 8), np.float32)},
                fetch_list=[loss.name])
    snap = monitor.get_registry().to_dict()
    fam = snap.get("executor_comms_gbytes_per_step")
    assert fam and fam["values"], "parallel compile did not record comms"


# ---------------------------------------------------------------------------
# spec extraction / runtime agreement
# ---------------------------------------------------------------------------

def test_zero1_spec_for_matches_build_rules():
    p = _param_program(("w", (8, 4)))
    v = p.global_block.var("w")
    assert zero1_spec_for(v, 1, True) == ()          # single device
    assert zero1_spec_for(v, 8, True) == ()          # not optimizer state
    v.is_optimizer_state = True
    assert zero1_spec_for(v, 8, True) == ("dp",)
    assert zero1_spec_for(v, 8, False) == ()         # AllReduce strategy
    assert zero1_spec_for(v, 16, True) == ()         # 8 % 16 indivisible
    v2 = p.global_block.create_var(name="emb", shape=(8, 4),
                                   dtype="float32", persistable=True)
    v2.is_distributed = True
    assert zero1_spec_for(v2, 8, False) == ("dp",)   # sharded table always


def test_extract_param_specs_zero_vs_allreduce():
    from paddle_tpu.models.mlp import build_mnist_mlp

    with un.guard():
        m = build_mnist_mlp(optimizer="adam")
    z, feed = extract_param_specs(m["main"], {"dp": 8}, zero=True)
    assert feed == ("dp",)
    assert any(n.startswith("moment") for n in z)
    assert all(s == ("dp",) for s in z.values())
    a, _ = extract_param_specs(m["main"], {"dp": 8}, zero=False)
    assert not any(n.startswith("moment") for n in a)


def test_spec_divisor_conservative_on_indivisible():
    assert spec_divisor(("dp",), {"dp": 8}, (16, 4)) == 8
    assert spec_divisor(("dp",), {"dp": 8}, (10, 4)) == 1   # kept whole
    assert spec_divisor(("dp", "tp"), {"dp": 2, "tp": 4}, (8, 8)) == 8
    assert spec_divisor((), {"dp": 8}, (16, 4)) == 1
    assert spec_divisor((None, "dp"), {"dp": 8}, (-1, 8), batch_size=4) == 8
    # one axis can split a value at most once — a malformed/composed spec
    # must never push the divisor past the mesh size (under-estimate)
    assert spec_divisor(("dp", "dp"), {"dp": 8}, (64, 64)) == 8


def test_composed_specs_never_reuse_an_axis():
    """A dp-sharded feed contracted against a param whose spec also uses
    dp must not compose to ('dp', 'dp') — the per-chip plan would divide
    by 64 on an 8-device mesh (the over-estimate invariant)."""
    with un.guard():
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            x = fluid.layers.data("x", shape=[64], dtype="float32")
            y = fluid.layers.fc(x, 64, bias_attr=False, name="l1")
    an = run(main, {"dp": 8}, {"l1.w_0": (None, "dp")}, batch=64)
    for name, sp in an.var_specs.items():
        axes = [a for a in sp if a is not None]
        assert len(axes) == len(set(axes)), (name, sp)
    n = an.n_devices
    for name, sp in an.var_specs.items():
        v = main.global_block.vars.get(name)
        if v is not None and v.shape is not None:
            assert spec_divisor(sp, an.mesh, v.shape, 64) <= n, (name, sp)


def test_per_chip_class_breakdown_reconciles_with_peak():
    """by_class_at(peak) — including the collective_staging bucket — must
    sum to the reported per-chip peak (minus sub-block charges, which the
    sub_block bucket carries)."""
    from paddle_tpu.models.mlp import build_mnist_mlp

    with un.guard():
        m = build_mnist_mlp()
    plan = m["main"].memory_plan(fetch_names=[m["loss"].name],
                                 batch_size=64, mesh={"dp": 8})
    peak = plan.peak_op_idx
    assert sum(plan.by_class_at(peak).values()) == plan.timeline[peak]
    assert max(plan.staging_timeline) > 0
    assert "collective_staging" in plan.class_timeline
    # single-device plans never grow the bucket
    plain = m["main"].memory_plan(fetch_names=[m["loss"].name],
                                  batch_size=64)
    assert "collective_staging" not in plain.class_timeline
