"""Prefix-reuse KV cache + chunked prefill + speculative decoding
(ISSUE 20).

Layers under test:
* kernels — q_len>1 chunk attention vs. the reference oracle (per-row
  causal masks), per-row-clamped chunk appends at the cache edge;
* ops — ``spec_accept``'s longest-agreeing-prefix rule;
* prefix cache — chain hashing, LRU bounds, and the copy-in/copy-out
  invariant (eviction can never corrupt a resident);
* serving — copy-on-write divergence at a mid-page boundary, chunked
  prefill interleaved with resident decode, greedy speculative
  bit-exactness, retired-slot clamp hygiene, and the negative controls
  (prefix cache off => zero hits; speculation off => no acceptance
  histogram).
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as fluid
import paddle_tpu.unique_name as un
from paddle_tpu import monitor, serving
from paddle_tpu.core.types import np_dtype
from paddle_tpu.kernels import (decode_attention_reference,
                                flash_attention_decode,
                                paged_kv_append_rows)
from paddle_tpu.models.gpt import GptConfig, build_gpt_generative
from paddle_tpu.serving.prefix_cache import PrefixCache

RNG = np.random.RandomState(20)


# ---------------------------------------------------------------------------
# kernel layer: chunk attention + per-row clamped appends
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("q_len", [2, 4, 8])
def test_chunk_kernel_matches_reference(q_len):
    """q_len>1 rides the same 8-row sublane tile with a per-row causal
    mask: query row i sees lengths + i keys."""
    B, H, S, D, P = 3, 2, 32, 64, 8
    BH = B * H
    q = jnp.asarray(RNG.randn(BH, q_len, D).astype(np.float32))
    k = jnp.asarray(RNG.randn(BH, S, D).astype(np.float32))
    v = jnp.asarray(RNG.randn(BH, S, D).astype(np.float32))
    lens = np.asarray([3, 9, 24 - q_len], np.int32)
    o = flash_attention_decode(q, k, v, lens, num_heads=H, page_size=P,
                               interpret=True)
    o_ref = decode_attention_reference(
        q, k, v, jnp.asarray(np.repeat(lens, H)), D ** -0.5)
    assert o.shape == (BH, q_len, D)
    np.testing.assert_allclose(np.asarray(o), np.asarray(o_ref),
                               atol=2e-5, rtol=1e-4)


def test_paged_kv_append_rows_clamps_per_row():
    """A chunk whose tail crosses the cache end collapses the overflow
    onto the LAST row (never shifts back over real rows the way a
    whole-block dynamic_update_slice start-clamp would)."""
    B, S, D, C = 2, 8, 4, 4
    cache = jnp.zeros((B, S, D), np.float32)
    new = jnp.asarray(
        np.arange(1, B * C * D + 1, dtype=np.float32).reshape(B, C, D))
    # row 0 starts in-range, rows 2..3 overflow for batch 1
    out = np.asarray(paged_kv_append_rows(cache, new, np.array([2, 6])))
    np.testing.assert_array_equal(out[0, 2:6], np.asarray(new)[0])
    # batch 1: rows 6, 7 get chunk rows 0, 1; overflow rows 2 and 3 both
    # clamp onto row 7 — LAST writer wins, earlier rows intact
    np.testing.assert_array_equal(out[1, 6], np.asarray(new)[1, 0])
    np.testing.assert_array_equal(out[1, 7], np.asarray(new)[1, 3])
    np.testing.assert_array_equal(out[1, :6], np.zeros((6, D)))


def test_spec_accept_longest_agreeing_prefix():
    from paddle_tpu import layers

    with un.guard():
        main, start = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, start):
            s = layers.data("s", shape=[3, 4], dtype="int64",
                            append_batch_size=False)
            d = layers.data("d", shape=[3, 3], dtype="int64",
                            append_batch_size=False)
            p = layers.data("p", shape=[3, 1], dtype="int64",
                            append_batch_size=False)
            acc, tok, pos = layers.spec_accept(s, d, p)
    exe = fluid.Executor(fluid.CPUPlace())
    sampled = np.array([[10, 11, 12, 13],     # full agreement
                        [20, 99, 22, 23],     # disagree at draft 0
                        [30, 31, 77, 33]],    # disagree at draft 1
                       np.int64)
    drafts = np.array([[10, 11, 12],
                       [21, 22, 23],
                       [30, 31, 32]], np.int64)
    start_pos = np.array([[5], [6], [7]], np.int64)
    a, t, npos = exe.run(main, feed={"s": sampled, "d": drafts,
                                     "p": start_pos},
                         fetch_list=[acc, tok, pos])
    np.testing.assert_array_equal(a.ravel(), [3, 0, 2])
    # NewTok is the bonus token Sampled[:, m]
    np.testing.assert_array_equal(t.ravel(), [13, 20, 77])
    np.testing.assert_array_equal(npos.ravel(), [5 + 4, 6 + 1, 7 + 3])


# ---------------------------------------------------------------------------
# prefix cache unit
# ---------------------------------------------------------------------------

def _fake_pages(i):
    """Deterministic per-page K/V payloads (1 layer)."""
    return ([np.full((2, 4, 3), float(i) + 0.5, np.float32)],
            [np.full((2, 4, 3), float(i) + 0.25, np.float32)])


def test_prefix_cache_match_insert_and_last_token_rule():
    pc = PrefixCache(page_size=4, capacity_pages=8)
    prompt = np.arange(100, 109, dtype=np.int64)     # 9 tokens -> 2 pages
    rows, entries = pc.match(prompt)
    assert rows == 0 and entries == [] and pc.misses == 1
    assert pc.insert(prompt, _fake_pages) == 2
    rows, entries = pc.match(prompt)
    assert rows == 8 and len(entries) == 2 and pc.hits == 1
    np.testing.assert_array_equal(entries[1]["k"][0], _fake_pages(1)[0][0])
    # exactly one page + the never-cached last token: 8 tokens -> 1 page
    rows, _ = pc.match(prompt[:8])
    assert rows == 4
    # a mid-page-divergent prompt shares page 0 only
    div = prompt.copy()
    div[6] = 777
    rows, entries = pc.match(div)
    assert rows == 4 and len(entries) == 1
    # a first-page mismatch shares nothing (chain hash, not per-page)
    div0 = prompt.copy()
    div0[0] = 777
    assert pc.match(div0)[0] == 0


def test_prefix_cache_lru_eviction_is_bounded():
    pc = PrefixCache(page_size=4, capacity_pages=3)
    prompts = [np.concatenate([[1000 + i], np.arange(8)]).astype(np.int64)
               for i in range(5)]   # distinct page-0 chains
    for p in prompts:
        pc.insert(p, _fake_pages)
    # 5 prompts x 2 pages inserted, capacity 3 -> 7 LRU evictions
    assert len(pc) == 3 and pc.evictions == 7
    # oldest entries evicted; the newest survive
    assert pc.match(prompts[0])[0] == 0
    assert pc.match(prompts[-1])[0] > 0
    st = pc.stats()
    assert st["pages"] == 3 and st["capacity_pages"] == 3
    assert pc.evict_all() == 3 and len(pc) == 0


# ---------------------------------------------------------------------------
# serving integration
# ---------------------------------------------------------------------------

def _build_net(**kw):
    with un.guard():
        return build_gpt_generative(GptConfig.tiny(), **kw)


@pytest.fixture(scope="module")
def net():
    """2 slots, 64-row KV in 8-row pages, one 16 bucket, chunk=8, k=4."""
    return _build_net(batch_slots=2, max_seq=64, page_size=8,
                      prompt_buckets=(16,), prefill_chunk=8, spec_k=4)


def _engine(net, **gen_kw):
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(net["startup"], scope=scope)
    eng = serving.GenerativeEngine(
        net, scope=scope, executor=exe,
        config=serving.ServingConfig(max_batch=2, queue_depth=64,
                                     deadline_s=0),
        gen_config=serving.GenerationConfig(decode_chunk=2, **gen_kw))
    return eng


def _run_one(eng, prompt, max_new=10):
    return list(eng.submit(prompt, max_new_tokens=max_new)
                .result(timeout=120)[0])


def test_prefix_hit_skips_prefill_and_is_bit_exact(net):
    """The tentpole contract: a repeated prefix provably skips bucket
    prefill (hit counters + chunk-suffix path) and the output stream is
    bit-identical to the cold run."""
    shared = RNG.randint(1, 128, 12).astype(np.int64)   # spans 1 page
    p1 = np.concatenate([shared, [5, 6]])
    p2 = np.concatenate([shared, [7, 8, 9]])
    base_eng = _engine(net, prefix_cache=False, chunked_prefill=False)
    base_eng.warm_up()
    with base_eng:
        cold1 = _run_one(base_eng, p1)
        cold2 = _run_one(base_eng, p2)
    eng = _engine(net, prefix_cache=True, chunked_prefill=True)
    eng.warm_up()
    with eng:
        assert _run_one(eng, p1) == cold1          # miss: publishes pages
        assert _run_one(eng, p2) == cold2          # hit: chunked suffix
        st = eng.generation_stats()
    pc = st["prefix_cache"]
    assert pc["hits"] == 1 and pc["misses"] == 1
    assert pc["pages_reused"] >= 1 and pc["pages"] >= 1
    assert st["prefill_chunks"] >= 1               # the suffix slices
    assert st["decode_recompiles"] == 0
    assert eng.accounting()["exact"]


def test_cow_divergence_at_mid_page_boundary(net):
    """Two prompts agreeing past a page boundary but diverging MID-page:
    the second request reuses only whole agreed pages and its divergent
    suffix never leaks into the first stream's pages (copy-in CoW)."""
    shared = RNG.randint(1, 128, 10).astype(np.int64)
    p1 = np.concatenate([shared, [11, 12, 13]])    # 13 tokens
    p2 = p1.copy()
    p2[9] = 99                                     # diverges inside page 1
    base_eng = _engine(net, prefix_cache=False, chunked_prefill=False)
    base_eng.warm_up()
    with base_eng:
        cold1 = _run_one(base_eng, p1)
        cold2 = _run_one(base_eng, p2)
    eng = _engine(net, prefix_cache=True, chunked_prefill=True)
    eng.warm_up()
    with eng:
        assert _run_one(eng, p1) == cold1
        # p2 shares page 0 (rows 0..7) but not page 1 (divergent row 9)
        assert _run_one(eng, p2) == cold2
        # p1 resubmitted AFTER p2's divergent run: its pages are intact
        assert _run_one(eng, p1) == cold1
        st = eng.generation_stats()
    assert st["prefix_cache"]["hits"] >= 2
    assert eng.accounting()["exact"]


def test_eviction_while_resident_decodes_never_corrupts(net):
    """Evict every prefix page while a stream that admitted THROUGH the
    cache is still decoding: the resident owns copies, so its tokens
    stay bit-exact (refuse-or-copy, never corrupt)."""
    shared = RNG.randint(1, 128, 12).astype(np.int64)
    p1 = np.concatenate([shared, [3, 4]])
    p2 = np.concatenate([shared, [5, 6, 7]])
    base_eng = _engine(net, prefix_cache=False, chunked_prefill=False)
    base_eng.warm_up()
    with base_eng:
        cold = _run_one(base_eng, p2, max_new=24)
    eng = _engine(net, prefix_cache=True, chunked_prefill=True)
    eng.warm_up()
    with eng:
        _run_one(eng, p1)                         # publish the pages
        f = eng.submit(p2, max_new_tokens=24)     # admits via prefix hit
        it = f.stream(timeout=120)
        first = next(it)     # first token proves the hit-admission ran
        # evict mid-stream, repeatedly, while the resident decodes
        for _ in range(20):
            eng._prefix_cache.evict_all()
        assert [first] + list(it) == cold
    assert eng.generation_stats()["prefix_cache"]["hits"] >= 1
    assert eng.accounting()["exact"]


def test_chunked_prefill_interleaves_with_resident_decode(net):
    """A prompt past the largest bucket (16) admits via chunk slices
    while a resident keeps decoding; both streams bit-match their
    solo cold runs."""
    p_short = RNG.randint(1, 128, 6).astype(np.int64)
    p_long = RNG.randint(1, 128, 30).astype(np.int64)   # > bucket 16
    base_eng = _engine(net, prefix_cache=False, chunked_prefill=True)
    base_eng.warm_up()
    with base_eng:
        cold_short = _run_one(base_eng, p_short, max_new=20)
        cold_long = _run_one(base_eng, p_long, max_new=8)
    eng = _engine(net, prefix_cache=False, chunked_prefill=True)
    eng.warm_up()
    with eng:
        f_short = eng.submit(p_short, max_new_tokens=20)
        f_long = eng.submit(p_long, max_new_tokens=8)
        assert list(f_short.result(timeout=120)[0]) == cold_short
        assert list(f_long.result(timeout=120)[0]) == cold_long
        st = eng.generation_stats()
    assert st["prefill_chunks"] >= 4    # ceil(30 / 8) slices
    assert st["decode_recompiles"] == 0
    assert eng.accounting()["exact"]


def test_over_bucket_prompt_refused_without_chunked_prefill(net):
    eng = _engine(net, prefix_cache=False, chunked_prefill=False)
    with pytest.raises(ValueError, match="chunked_prefill"):
        eng._build_gen_request(RNG.randint(1, 128, 20).astype(np.int64),
                               4, 0, None)


def test_speculative_greedy_is_bit_exact_and_accepts(net):
    """The tentpole bit-exactness contract: greedy speculative output ==
    greedy non-speculative output, with a non-trivial acceptance rate
    (the n-gram draft exploits the tiny model's repetitive stream)."""
    monitor.reset()
    prompts = [RNG.randint(1, 128, 5 + i).astype(np.int64)
               for i in range(4)]
    base_eng = _engine(net, prefix_cache=False, chunked_prefill=False,
                       speculative=False)
    base_eng.warm_up()
    with base_eng:
        cold = [_run_one(base_eng, p, max_new=16) for p in prompts]
    eng = _engine(net, prefix_cache=False, chunked_prefill=False,
                  speculative=True)
    # prefill:16 + decode + verify (no chunk program: both chunked
    # prefill and the prefix cache are off)
    assert eng.warm_up() == 3
    with eng:
        hot = [_run_one(eng, p, max_new=16) for p in prompts]
        st = eng.generation_stats()
    assert hot == cold
    assert st["speculative"]["enabled"] and st["speculative"]["chunks"] > 0
    assert st["speculative"]["accepted_tokens"] > 0
    assert st["decode_recompiles"] == 0
    h = monitor.metric_value("serving_spec_accepted_len", default=None)
    assert h and h["count"] == st["speculative"]["chunks"] \
        and h["max"] >= 1
    assert eng.accounting()["exact"]


def test_spec_capacity_guard_falls_back_to_plain_decode(net):
    """Near KV capacity the verify chunk would overflow the cache: the
    engine must fall back to plain decode chunks, still bit-exact."""
    L = 16
    p = RNG.randint(1, 128, L).astype(np.int64)
    max_new = 64 - L            # fills the cache to the brim
    base_eng = _engine(net, prefix_cache=False, chunked_prefill=False,
                       speculative=False)
    base_eng.warm_up()
    with base_eng:
        cold = _run_one(base_eng, p, max_new=max_new)
    eng = _engine(net, prefix_cache=False, chunked_prefill=False,
                  speculative=True)
    eng.warm_up()
    with eng:
        assert _run_one(eng, p, max_new=max_new) == cold
    assert eng.accounting()["exact"]


def test_retired_slot_stays_frozen_and_readmits(net):
    """OOB-clamp x retired slots: after a stream retires, later decode
    and verify dispatches leave its cache rows bit-untouched (the decode
    gate is cleared host-side), and the slot re-admits cleanly."""
    eng = _engine(net, prefix_cache=False, chunked_prefill=False,
                  speculative=True)
    eng.warm_up()
    p1 = RNG.randint(1, 128, 4).astype(np.int64)
    p2 = RNG.randint(1, 128, 7).astype(np.int64)
    with eng:
        _run_one(eng, p1, max_new=2)     # retires quickly
        # retire clears the decode gate from the dispatcher thread;
        # result() may resolve a beat earlier, so poll briefly
        import time as _time
        deadline = _time.monotonic() + 5.0
        while _time.monotonic() < deadline:
            active = np.array(eng._scope.find_var("gpt_gen_active"))
            if float(active.sum()) == 0.0:
                break
            _time.sleep(0.01)
        assert float(active.sum()) == 0.0, "retire must clear the gate"
        # snapshot the slot cache rows AFTER retire
        k0_name = "gpt_kv_k_0"
        snap = np.array(eng._scope.find_var(k0_name))
        _run_one(eng, p2, max_new=12)    # long stream, spec dispatches
        # p2 reuses a slot; the OTHER slot's rows are bit-identical
        after = np.array(eng._scope.find_var(k0_name))
        other = [s for s in range(2)
                 if not np.array_equal(snap[s], after[s])]
        assert len(other) <= 1, \
            "a retired slot's cache rows changed without an admission"
    assert eng.accounting()["exact"]


def test_negative_controls_prefix_off_spec_off(net):
    """prefix cache off => stats None and zero hit counters; speculation
    off => no acceptance histogram ever observed."""
    monitor.reset()
    eng = _engine(net, prefix_cache=False, chunked_prefill=False,
                  speculative=False)
    eng.warm_up()
    shared = RNG.randint(1, 128, 12).astype(np.int64)
    with eng:
        for tail in ([1, 2], [3, 4, 5]):
            _run_one(eng, np.concatenate([shared, tail]))
        st = eng.generation_stats()
    assert st["prefix_cache"] is None
    assert not st["speculative"]["enabled"]
    assert st["speculative"]["chunks"] == 0
    assert monitor.metric_value("serving_prefix_hits_total", 0.0) == 0.0
    assert monitor.metric_value("serving_spec_accepted_len",
                                default=None) is None
    assert eng.accounting()["exact"]
