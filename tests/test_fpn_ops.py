"""RPN/FPN proposal pipeline + lstmp OpTests (reference
detection/generate_proposals_op.cc, distribute_fpn_proposals_op.cc,
collect_fpn_proposals_op.cc, lstmp_op.h) against numpy oracles."""
import numpy as np

import paddle_tpu as fluid

RNG = np.random.RandomState(11)


def _run_op(op_type, inputs, outputs_spec, attrs):
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        blk = fluid.default_main_program().global_block
        in_map, feed = {}, {}
        for slot, v in inputs.items():
            arrs = v if isinstance(v, list) else [(slot.lower(), v)]
            vs = []
            for name, arr in arrs:
                dt = {"float32": "float32", "int64": "int64",
                      "int32": "int32"}[str(arr.dtype)]
                vs.append(blk.create_var(name=name, shape=arr.shape,
                                         dtype=dt, is_data=True))
                feed[name] = arr
            in_map[slot] = vs if isinstance(v, list) else vs[0]
        out_map, fetch = {}, []
        for slot, n_or_list in outputs_spec.items():
            if isinstance(n_or_list, int):
                vs = [blk.create_var(name=f"{slot}_{i}", shape=(1,),
                                     dtype="float32")
                      for i in range(n_or_list)]
                out_map[slot] = vs
                fetch += [v.name for v in vs]
            else:
                v = blk.create_var(name=slot.lower() + "_out", shape=(1,),
                                   dtype="float32")
                out_map[slot] = v
                fetch.append(v.name)
        blk.append_op(op_type, inputs=in_map, outputs=out_map, attrs=attrs)
        exe = fluid.Executor(fluid.CPUPlace())
        res = exe.run(fluid.default_main_program(), feed=feed,
                      fetch_list=fetch)
    return dict(zip(fetch, [np.asarray(r) for r in res]))


def test_distribute_fpn_proposals():
    # areas chosen to land on known levels: sqrt(area)/224 -> log2
    sizes = [32, 64, 112, 224, 448, 500]
    rois = np.array([[0, 0, s - 1, s - 1] for s in sizes], np.float32)
    res = _run_op(
        "distribute_fpn_proposals", {"FpnRois": rois},
        {"MultiFpnRois": 4, "MultiLevelRoIsNum": 4, "RestoreIndex": None},
        {"min_level": 2, "max_level": 5, "refer_level": 4,
         "refer_scale": 224})
    # level = clip(floor(log2(s/224)) + 4, 2, 5):
    # 32->2(floor(-2.8)=-3 clip), 64->2(floor(-1.8)=-2), 112->3, 224->4,
    # 448->5, 500->5
    counts = [int(res[f"MultiLevelRoIsNum_{i}"].reshape(-1)[0])
              for i in range(4)]
    assert counts == [2, 1, 1, 2], counts
    lvl2 = res["MultiFpnRois_0"]
    np.testing.assert_allclose(lvl2[:2], rois[:2])
    assert (lvl2[2:] == -1).all()
    # restore index inverts the level-sort
    restore = res["restoreindex_out"].reshape(-1)
    level_sorted = np.concatenate(
        [res[f"MultiFpnRois_{i}"][:counts[i]] for i in range(4)])
    np.testing.assert_allclose(level_sorted[restore], rois)


def test_distribute_fpn_proposals_ignores_padding():
    """r5 review finding: -1-padded rows (generate_proposals' padding) must
    reach NO level and get RestoreIndex = -1."""
    rois = np.array([[0, 0, 223, 223],
                     [-1, -1, -1, -1],
                     [-1, -1, -1, -1]], np.float32)
    res = _run_op(
        "distribute_fpn_proposals", {"FpnRois": rois},
        {"MultiFpnRois": 4, "MultiLevelRoIsNum": 4, "RestoreIndex": None},
        {"min_level": 2, "max_level": 5, "refer_level": 4,
         "refer_scale": 224})
    counts = [int(res[f"MultiLevelRoIsNum_{i}"].reshape(-1)[0])
              for i in range(4)]
    assert counts == [0, 0, 1, 0], counts
    restore = res["restoreindex_out"].reshape(-1)
    assert restore[0] == 0 and (restore[1:] == -1).all()


def test_collect_fpn_proposals():
    r1 = np.array([[0, 0, 10, 10], [1, 1, 5, 5], [-1, -1, -1, -1]],
                  np.float32)
    r2 = np.array([[2, 2, 8, 8], [-1, -1, -1, -1], [-1, -1, -1, -1]],
                  np.float32)
    s1 = np.array([0.9, 0.2, 0.0], np.float32)
    s2 = np.array([0.7, 0.0, 0.0], np.float32)
    res = _run_op(
        "collect_fpn_proposals",
        {"MultiLevelRois": [("mr0", r1), ("mr1", r2)],
         "MultiLevelScores": [("ms0", s1), ("ms1", s2)]},
        {"FpnRois": None, "RoisNum": None}, {"post_nms_topN": 2})
    got = res["fpnrois_out"]
    np.testing.assert_allclose(got[0], [0, 0, 10, 10])
    np.testing.assert_allclose(got[1], [2, 2, 8, 8])
    assert int(res["roisnum_out"].reshape(-1)[0]) == 2


def test_generate_proposals_shapes_and_ordering():
    n, a, h, w = 2, 3, 4, 4
    scores = RNG.rand(n, a, h, w).astype(np.float32)
    deltas = (0.1 * RNG.randn(n, 4 * a, h, w)).astype(np.float32)
    im_info = np.array([[64, 64, 1.0], [64, 64, 1.0]], np.float32)
    anchors = np.zeros((h, w, a, 4), np.float32)
    for yy in range(h):
        for xx in range(w):
            for ai in range(a):
                cx, cy = xx * 16 + 8, yy * 16 + 8
                sz = 8 * (ai + 1)
                anchors[yy, xx, ai] = [cx - sz, cy - sz, cx + sz, cy + sz]
    var = np.full((h, w, a, 4), 1.0, np.float32)
    res = _run_op(
        "generate_proposals",
        {"Scores": scores, "BboxDeltas": deltas, "ImInfo": im_info,
         "Anchors": anchors, "Variances": var},
        {"RpnRois": None, "RpnRoiProbs": None, "RpnRoisNum": None},
        {"pre_nms_topN": 24, "post_nms_topN": 8, "nms_thresh": 0.7,
         "min_size": 2.0, "eta": 1.0})
    rois = res["rpnrois_out"]
    probs = res["rpnroiprobs_out"]
    counts = res["rpnroisnum_out"].reshape(-1)
    assert rois.shape == (n, 8, 4) and probs.shape == (n, 8, 1)
    for i in range(n):
        c = int(counts[i])
        assert 1 <= c <= 8
        valid = rois[i, :c]
        # clipped to image, min-size respected, probs sorted descending
        assert (valid[:, 0] >= 0).all() and (valid[:, 2] <= 63).all()
        assert ((valid[:, 2] - valid[:, 0] + 1) >= 2).all()
        p = probs[i, :c, 0]
        assert (np.diff(p) <= 1e-6).all()
        assert (rois[i, c:] == -1).all()


def test_dynamic_lstmp_layer():
    """lstmp: projection output has proj_size channels, grads flow, and a
    tiny fit improves the loss."""
    b, t, d, hidden, proj = 4, 5, 6, 8, 3
    rng = np.random.RandomState(0)
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        xv = fluid.layers.data(name="x", shape=[d], dtype="float32",
                               lod_level=1)
        y = fluid.layers.data(name="y", shape=[proj], dtype="float32")
        gates = fluid.layers.fc(input=xv, size=4 * hidden,
                                num_flatten_dims=2)
        proj_out, cell = fluid.layers.dynamic_lstmp(
            input=gates, size=4 * hidden, proj_size=proj)
        last = fluid.layers.sequence_last_step(proj_out)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(last, y))
        fluid.optimizer.Adam(learning_rate=0.02).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        feed = {"x": rng.randn(b, t, d).astype(np.float32),
                "x@LOD": np.array([5, 3, 5, 2], np.int32),
                "y": rng.rand(b, proj).astype(np.float32)}
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            vals = []
            for _ in range(40):
                o = exe.run(fluid.default_main_program(), feed=feed,
                            fetch_list=[loss, proj_out])
                vals.append(float(np.asarray(o[0]).reshape(-1)[0]))
            p = np.asarray(o[1])
    assert p.shape == (b, t, proj)
    # padded steps zeroed
    assert (p[1, 3:] == 0).all() and (p[3, 2:] == 0).all()
    assert vals[-1] < 0.5 * vals[0], (vals[0], vals[-1])
