"""Sparse embedding gradients (SelectedRows role).

Reference: paddle/fluid/framework/selected_rows.h:32 (the {rows, value,
height} gradient type of is_sparse lookups), operators/optimizers/adam_op.h
SparseAdamFunctor (lazy/non-lazy), sgd_op.h + adagrad_op.h sparse branches.
Here the grad of an ``is_sparse`` lookup_table is a SelectedRows pytree with
rows sized by touched ids (batch x seq), NOT vocab — verified structurally
below — and every sparse-vs-dense pair must converge identically where the
semantics are dense-equivalent.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core.selected_rows import (SelectedRows, concat_merge,
                                           is_selected_rows, merge_rows)

VOCAB, DIM, BATCH, SEQ = 50, 8, 4, 6


def test_merge_rows_dedups_and_pads():
    import jax.numpy as jnp

    ids = jnp.array([3, 1, 3, 7, 1, 3], dtype=jnp.int32)
    vals = jnp.arange(6, dtype=jnp.float32)[:, None] * jnp.ones((6, 2))
    sr = merge_rows(ids, vals, height=10)
    assert sr.rows.shape == (6,)
    dense = np.asarray(sr.to_dense())
    expect = np.zeros((10, 2), np.float32)
    for i, r in enumerate([3, 1, 3, 7, 1, 3]):
        expect[r] += i
    np.testing.assert_allclose(dense, expect)
    # canonical: unique rows lead, sentinel (height) pads the tail
    rows = np.asarray(sr.rows)
    assert sorted(rows[:3].tolist()) == [1, 3, 7]
    assert (rows[3:] == 10).all()


def test_concat_merge_sums_shared_table_grads():
    import jax.numpy as jnp

    a = merge_rows(jnp.array([1, 2]), jnp.ones((2, 3)), 5)
    b = merge_rows(jnp.array([2, 4]), 2 * jnp.ones((2, 3)), 5)
    dense = np.asarray(concat_merge(a, b).to_dense())
    expect = np.zeros((5, 3), np.float32)
    expect[1] += 1
    expect[2] += 3
    expect[4] += 2
    np.testing.assert_allclose(dense, expect)


def _build_emb_net(is_sparse, optimizer, padding_idx=None):
    ids = fluid.layers.data(name="ids", shape=[SEQ], dtype="int64")
    emb = fluid.layers.embedding(
        input=ids, size=[VOCAB, DIM], is_sparse=is_sparse,
        padding_idx=padding_idx, param_attr=fluid.ParamAttr(name="emb_w"))
    # touch only some rows; a dense fc after keeps the grad path realistic
    pooled = fluid.layers.reduce_mean(emb, dim=1)
    pred = fluid.layers.fc(input=pooled, size=1,
                           param_attr=fluid.ParamAttr(name="head_w"))
    loss = fluid.layers.mean(pred * pred)
    optimizer().minimize(loss)
    return loss


def _train(is_sparse, optimizer, steps=3, padding_idx=None, fetch_grad=False):
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        loss = _build_emb_net(is_sparse, optimizer, padding_idx)
        main, startup = (fluid.default_main_program(),
                         fluid.default_startup_program())
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        rng = np.random.RandomState(0)
        feeds = [{"ids": rng.randint(0, VOCAB, (BATCH, SEQ)).astype(np.int64)}
                 for _ in range(steps)]
        grads = None
        with fluid.scope_guard(scope):
            exe.run(startup)
            losses = []
            for f in feeds:
                fetch = [loss] + (["emb_w@GRAD"] if fetch_grad else [])
                outs = exe.run(main, feed=f, fetch_list=fetch,
                               return_numpy=False)
                losses.append(float(np.asarray(outs[0]).reshape(-1)[0]))
                if fetch_grad:
                    grads = outs[1]
            w = scope.numpy("emb_w")
    return losses, w, grads


SGD = lambda: fluid.optimizer.SGD(learning_rate=0.1)
MOMENTUM = lambda: fluid.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
NESTEROV = lambda: fluid.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                            use_nesterov=True)
ADAM = lambda: fluid.optimizer.Adam(learning_rate=0.05)
ADAGRAD = lambda: fluid.optimizer.Adagrad(learning_rate=0.1)


@pytest.mark.parametrize("opt", [SGD, MOMENTUM, NESTEROV, ADAM, ADAGRAD],
                         ids=["sgd", "momentum", "nesterov", "adam",
                              "adagrad"])
def test_sparse_matches_dense_training(opt):
    """Sparse grads use dense-equivalent update semantics (non-lazy): the
    parameter trajectory must match the dense path bit-for-bit-ish."""
    dense_losses, dense_w, _ = _train(False, opt)
    sparse_losses, sparse_w, _ = _train(True, opt)
    np.testing.assert_allclose(sparse_losses, dense_losses, rtol=1e-5)
    np.testing.assert_allclose(sparse_w, dense_w, rtol=1e-5, atol=1e-7)


def test_grad_is_selected_rows_sized_by_touched_ids():
    """The structural claim: an is_sparse lookup's grad buffer is
    [batch*seq, dim] + an int32 row vector — not [vocab, dim]."""
    _, _, grad = _train(True, SGD, steps=1, fetch_grad=True)
    assert is_selected_rows(grad)
    assert grad.values.shape == (BATCH * SEQ, DIM)
    assert grad.rows.shape == (BATCH * SEQ,)
    assert grad.height == VOCAB
    _, _, dense_grad = _train(False, SGD, steps=1, fetch_grad=True)
    assert not is_selected_rows(dense_grad)
    assert np.asarray(dense_grad).shape == (VOCAB, DIM)


def test_sparse_padding_idx_rows_get_no_update():
    losses, w, grad = _train(True, SGD, steps=2, padding_idx=3,
                             fetch_grad=True)
    # padding row's grad is dropped entirely (forward zeroed its output)
    assert not np.asarray((grad.rows == 3).any())
    d_losses, d_w, _ = _train(False, SGD, steps=2, padding_idx=3)
    np.testing.assert_allclose(w, d_w, rtol=1e-5, atol=1e-7)


def test_lazy_adam_touches_only_grad_rows():
    """lazy_mode=True (reference adam_op.h lazy branch): untouched rows'
    moments must NOT decay and their params must NOT move."""
    lazy = lambda: fluid.optimizer.Adam(learning_rate=0.05, lazy_mode=True)
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        loss = _build_emb_net(True, lazy)
        main, startup = (fluid.default_main_program(),
                         fluid.default_startup_program())
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        ids = np.full((BATCH, SEQ), 5, np.int64)  # touch ONLY row 5
        with fluid.scope_guard(scope):
            exe.run(startup)
            w0 = scope.numpy("emb_w").copy()
            exe.run(main, feed={"ids": ids}, fetch_list=[loss])
            w1 = scope.numpy("emb_w")
        untouched = np.ones(VOCAB, bool)
        untouched[5] = False
        np.testing.assert_array_equal(w1[untouched], w0[untouched])
        assert np.abs(w1[5] - w0[5]).max() > 0


def test_sparse_with_global_norm_clip():
    """r5 review finding: clip/AMP ops must accept SelectedRows grads."""
    def opt():
        fluid.clip.set_gradient_clip(
            fluid.clip.GradientClipByGlobalNorm(clip_norm=0.01))
        return fluid.optimizer.SGD(learning_rate=0.1)

    try:
        losses, w, _ = _train(True, opt, steps=3)
        d_losses, d_w, _ = _train(False, opt, steps=3)
    finally:
        # set_gradient_clip is process-global: leaking clip_norm=0.01 made
        # later suites' training tests fail their loss-decrease assertions.
        # The conftest autouse fixture also resets it; this stays so the
        # test is leak-free when run outside the suite's conftest
        fluid.clip.set_gradient_clip(None)
    np.testing.assert_allclose(losses, d_losses, rtol=1e-5)
    np.testing.assert_allclose(w, d_w, rtol=1e-5, atol=1e-7)


def test_sparse_with_dynamic_loss_scaling():
    from paddle_tpu.contrib import mixed_precision as mp

    def opt():
        return mp.decorate(fluid.optimizer.SGD(learning_rate=0.1),
                           use_dynamic_loss_scaling=True,
                           init_loss_scaling=128.0)

    losses, w, _ = _train(True, opt, steps=3)
    d_losses, d_w, _ = _train(False, opt, steps=3)
    np.testing.assert_allclose(losses, d_losses, rtol=1e-5)
    np.testing.assert_allclose(w, d_w, rtol=1e-4, atol=1e-6)


def test_chained_run_with_sparse_grads():
    """SelectedRows must survive the run_chained scan path (it is a pytree)."""
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        loss = _build_emb_net(True, SGD)
        main, startup = (fluid.default_main_program(),
                         fluid.default_startup_program())
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        feed = {"ids": np.random.RandomState(1).randint(
            0, VOCAB, (BATCH, SEQ)).astype(np.int64)}
        with fluid.scope_guard(scope):
            exe.run(startup)
            out = exe.run_chained(main, feed=feed, fetch_list=[loss], steps=3)
        assert np.asarray(out[0]).shape == (3,)
