"""Runnable distributed-training script (reference test_dist_base.py model
scripts: dist_mnist.py subclassing TestDistRunnerBase:61). Trains a fixed MLP
regression on deterministic synthetic data; under the launcher each rank
feeds its slice of the SAME global batch, standalone feeds the full batch —
losses must match bit-for-bit up to float tolerance. Rank 0 prints the loss
series as one JSON line prefixed with LOSSES."""
import json
import os
import sys

import numpy as np

GLOBAL_BATCH = 8
STEPS = 10
DIM = 16


def main():
    nranks = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
    rank = int(os.getenv("PADDLE_TRAINER_ID", "0"))
    if nranks > 1:
        from paddle_tpu import distributed as dist

        dist.init_parallel_env()
    else:
        import jax

        from paddle_tpu.distributed import force_cpu_device_count

        jax.config.update("jax_platforms", "cpu")
        force_cpu_device_count(2)

    import paddle_tpu as fluid

    if os.getenv("DIST_MODEL") == "deepfm":
        from paddle_tpu.models.deepfm import build_deepfm

        m = build_deepfm(vocab=64, num_fields=4, emb_dim=4, lr=0.05,
                         sharded=True)
        m["main"].random_seed = 31
        main_p, startup, loss = m["main"], m["startup"], m["loss"]
        rng = np.random.RandomState(42)
        ids = rng.randint(0, 64, (GLOBAL_BATCH, 4)).astype(np.int64)
        feeds = {"feat_ids": ids,
                 "label": (ids.sum(1) % 2).astype(np.float32).reshape(-1, 1)}
    else:
        main_p, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_p, startup):
            x = fluid.layers.data("x", shape=[DIM], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="float32")
            h = fluid.layers.fc(x, 32, act="relu", name="d_fc1")
            pred = fluid.layers.fc(h, 1, name="d_fc2")
            loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        rng = np.random.RandomState(42)
        w_true = np.linspace(-1, 1, DIM).astype(np.float32).reshape(DIM, 1)
        xb = rng.rand(GLOBAL_BATCH, DIM).astype(np.float32)
        feeds = {"x": xb, "y": np.tanh(xb @ w_true).astype(np.float32)}
        with fluid.program_guard(main_p, startup):
            if os.getenv("DIST_OPT") == "adam":
                fluid.optimizer.Adam(learning_rate=0.02).minimize(loss)
            else:
                fluid.optimizer.SGD(learning_rate=0.02).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    local = GLOBAL_BATCH // nranks
    losses = []
    if os.getenv("DIST_LOCALSGD"):
        # LocalSGD: plain per-rank program, parameter averaging every k
        from paddle_tpu.incubate.fleet.collective import LocalSGDSync

        k = int(os.getenv("DIST_LOCALSGD"))
        sync = LocalSGDSync(main_p, k_steps=k)
        import paddle_tpu.executor as _ex

        scope = _ex.global_scope()
        for step in range(STEPS):
            sl = slice(rank * local, (rank + 1) * local) if nranks > 1 \
                else slice(None)
            lv = exe.run(main_p, feed={kk: v[sl] for kk, v in feeds.items()},
                         fetch_list=[loss])[0]
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
            sync.step(scope)
        w = np.asarray(scope.find_var("d_fc1.w_0")).ravel()[:6].tolist()
        print(f"PARAMS{rank} " + json.dumps(w), flush=True)
    else:
        bs = fluid.BuildStrategy()
        if os.getenv("DIST_REDUCE") == "1":
            bs.reduce_strategy = fluid.BuildStrategy.ReduceStrategy.Reduce
        compiled = fluid.CompiledProgram(main_p).with_data_parallel(
            loss_name=loss.name, build_strategy=bs)
        for step in range(STEPS):
            sl = slice(rank * local, (rank + 1) * local) if nranks > 1 \
                else slice(None)
            lv = exe.run(compiled, feed={k: v[sl] for k, v in feeds.items()},
                         fetch_list=[loss])[0]
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
    if rank == 0:
        print("LOSSES " + json.dumps(losses), flush=True)
    if nranks > 1:
        # hard-exit teardown: this jax build's gloo transport double-frees
        # nondeterministically when interpreter teardown (or even
        # jax.distributed.shutdown) runs its destructors against the XLA
        # CPU client. The ranks are already synchronized by the final
        # training collective; skip every destructor and leave the
        # coordination sockets to die with the process.
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(0)
    return 0


if __name__ == "__main__":
    sys.exit(main())
