"""FLAGS_epilogue_fusion — the GEMM-epilogue fusion pass
(analysis/epilogue_fusion.py + ops/fused_gemm.py + kernels/fused_gemm.py).

Covers the ISSUE-13 fusion-correctness checklist: pattern-match positive
and negative controls (fetched intermediate refuses, multi-consumer
refuses, backward-carrying program refuses), the fused-vs-unfused
numerical witness per epilogue kind, compile-cache separation (the fused
program gets its own ``_serial``), and kernel-vs-reference parity across
tile-boundary shapes (interpret mode — no hardware needed)."""
import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.unique_name as un
from paddle_tpu.analysis.epilogue_fusion import (FusionDecision,
                                                 fuse_epilogues)
from paddle_tpu.kernels.fused_gemm import (classify_gemm, fused_gemm,
                                           fused_gemm_reference)


@pytest.fixture(autouse=True)
def _flag_reset():
    prev = fluid.get_flags(["FLAGS_epilogue_fusion", "FLAGS_use_fused_gemm",
                            "FLAGS_fused_gemm_blocks"])
    yield
    fluid.set_flags(prev)


def _mlp(act="gelu", width=128, fetch_mid=False):
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[width], dtype="float32")
            h = fluid.layers.fc(x, width, act=act)
            pred = fluid.layers.fc(h, width)
    return main, startup, pred


def _run(main, startup, fetch, feed, fused):
    fluid.set_flags({"FLAGS_epilogue_fusion": fused})
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        (out,) = exe.run(main, feed=feed, fetch_list=[fetch])
    return np.asarray(out), exe


def _feed(width=128, batch=32, seed=0):
    return {"x": np.random.RandomState(seed).randn(
        batch, width).astype(np.float32)}


# ---------------------------------------------------------------------------
# pattern matching: positive and negative controls
# ---------------------------------------------------------------------------

def test_fuses_bias_activation_chain_and_matches_bitwise():
    main, startup, pred = _mlp("gelu")
    feed = _feed()
    base, _ = _run(main, startup, pred.name, feed, fused=False)
    fused, exe = _run(main, startup, pred.name, feed, fused=True)
    assert np.array_equal(base, fused)
    fp = next(p for p in exe._fusion_cache.values()
              if any(op.type == "fused_gemm_epilogue"
                     for op in p.global_block.ops))
    types = [op.type for op in fp.global_block.ops]
    assert types.count("fused_gemm_epilogue") == 2
    assert "mul" not in types and "elementwise_add" not in types


def test_applied_chains_report_pt750_and_unsupported_tiling_pt755():
    """PT750 per fused chain; PT755 when the chain's GEMM dims have no
    kernel tiling (n=100 is not lane-aligned — the dense replay runs)."""
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[128], dtype="float32")
            good = fluid.layers.fc(x, 128, act="relu")
            bad = fluid.layers.fc(good, 100)     # n=100: no kernel tiling
    diags = []
    dec = fuse_epilogues(main, fetch_names=[bad.name], diags=diags)
    assert dec.applied and dec.n_fused == 2
    codes = [d.code for d in diags]
    assert codes.count("PT750") == 2
    assert codes.count("PT755") == 1
    pt755 = next(d for d in diags if d.code == "PT755")
    assert "n=100" in pt755.message


def test_chain_kinds_matched():
    """Every epilogue kind the kernel supports pattern-matches and carries
    its parts label."""
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[128], dtype="float32")
            h = fluid.layers.fc(x, 128)                    # mul+bias
            r = fluid.layers.elementwise_add(h, x)         # +residual
            ln = fluid.layers.layer_norm(r, begin_norm_axis=1)
            out = fluid.layers.fc(ln, 128, act="relu")     # mul+bias+relu
    dec = fuse_epilogues(main, fetch_names=[out.name])
    assert dec.applied
    kinds = sorted(c["epilogue"] for c in dec.chains)
    assert kinds == ["bias+relu", "bias+residual+layer_norm"]


def test_fetched_intermediate_refuses():
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[128], dtype="float32")
            h = fluid.layers.fc(x, 128, act="gelu")
    # fetch the TRUE mid-chain intermediate (the mul output): the chain
    # must not extend past a fetched value, leaving nothing to fuse
    mul_out = next(op.output("Out")[0] for op in main.global_block.ops
                   if op.type == "mul")
    diags = []
    dec = fuse_epilogues(main, fetch_names=[h.name, mul_out], diags=diags)
    assert not dec.applied
    assert any(d.code == "PT751" for d in diags)
    # and the executor still runs the untransformed program correctly
    feed = _feed()
    out, exe = _run(main, startup, h.name, feed, fused=True)
    base, _ = _run(main, startup, h.name, feed, fused=False)
    assert np.array_equal(out, base)


def test_multi_consumer_intermediate_refuses():
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[128], dtype="float32")
            h = fluid.layers.fc(x, 128)          # mul + bias
            a = fluid.layers.gelu(h)
            b = fluid.layers.relu(h)             # second consumer of h
            out = fluid.layers.elementwise_add(a, b)
    diags = []
    dec = fuse_epilogues(main, fetch_names=[out.name], diags=diags)
    # the mul+bias prefix may fuse (the mul output feeds only the add),
    # but the bias output must NOT fold its activation in
    assert any(d.code == "PT752" for d in diags)
    if dec.applied:
        assert all("gelu" not in c["epilogue"] and "relu" not in
                   c["epilogue"] for c in dec.chains)


def test_backward_program_refuses():
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[64], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="float32")
            p = fluid.layers.fc(x, 1)
            loss = fluid.layers.mean(fluid.layers.square_error_cost(p, y))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    diags = []
    dec = fuse_epilogues(main, fetch_names=[loss.name], diags=diags)
    assert not dec.applied and "backward" in dec.reason
    assert any(d.code == "PT753" for d in diags)


def test_layer_norm_with_consumed_stats_refuses_ln_fold():
    """A layer_norm whose Mean output is fetched cannot fold away."""
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[128], dtype="float32")
            h = fluid.layers.fc(x, 128)
            ln = fluid.layers.layer_norm(h, begin_norm_axis=1)
    ln_op = next(op for op in main.global_block.ops
                 if op.type == "layer_norm")
    mean_name = ln_op.output("Mean")[0]
    dec = fuse_epilogues(main, fetch_names=[ln.name, mean_name])
    # the bias part may still fuse; layer_norm must survive unfused
    if dec.applied:
        assert all("layer_norm" not in c["epilogue"] for c in dec.chains)


# ---------------------------------------------------------------------------
# numerical witness per epilogue kind
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kind", ["bias", "bias+relu", "bias+gelu",
                                  "bias+residual",
                                  "bias+residual+layer_norm"])
def test_fused_matches_unfused_per_epilogue_kind(kind):
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[128], dtype="float32")
            act = ("relu" if "relu" in kind
                   else "gelu" if "gelu" in kind else None)
            h = fluid.layers.fc(x, 128, act=act)
            if "residual" in kind:
                h = fluid.layers.elementwise_add(h, x)
            if "layer_norm" in kind:
                h = fluid.layers.layer_norm(h, begin_norm_axis=1)
    dec = fuse_epilogues(main, fetch_names=[h.name])
    assert dec.applied and dec.n_fused == 1
    assert dec.chains[0]["epilogue"] == kind
    feed = _feed()
    base, _ = _run(main, startup, h.name, feed, fused=False)
    fused, _ = _run(main, startup, h.name, feed, fused=True)
    # dense route (CPU suite): the fused op replays the original rules —
    # exact bits, the fidelity contract the witness enforces
    assert np.array_equal(base, fused)


def test_witness_refuses_wrong_lowering(monkeypatch):
    """Break the fused op's lowering: the fidelity witness must catch it
    and the pass must refuse rather than emit a wrong program."""
    from paddle_tpu.core import registry

    opdef = registry.get_op_def("fused_gemm_epilogue")
    real = opdef.lower

    def wrong(ctx, ins, attrs):
        out = real(ctx, ins, attrs)
        out["Out"] = [v + 1.0 for v in out["Out"]]
        return out

    monkeypatch.setattr(opdef, "lower", wrong)
    main, startup, pred = _mlp("gelu")
    diags = []
    dec = fuse_epilogues(main, fetch_names=[pred.name], diags=diags)
    assert not dec.applied and "witness" in dec.reason
    assert any(d.code == "PT754" for d in diags)


def test_amp_program_fuses_and_matches():
    """Under the AMP policy the fused op must reproduce the unfused
    chain's per-op casts (mul white-listed, epilogue params untouched)."""
    from paddle_tpu.contrib import mixed_precision as mp

    main, startup, pred = _mlp("gelu")
    mp.decorate_program(main)
    feed = _feed()
    base, _ = _run(main, startup, pred.name, feed, fused=False)
    fused, _ = _run(main, startup, pred.name, feed, fused=True)
    assert np.array_equal(base, fused)


# ---------------------------------------------------------------------------
# cache separation + executor integration
# ---------------------------------------------------------------------------

def test_fused_program_gets_own_serial_and_cache_entries():
    main, startup, pred = _mlp("gelu")
    feed = _feed()
    fluid.set_flags({"FLAGS_epilogue_fusion": 1})
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        (a,) = exe.run(main, feed=feed, fetch_list=[pred.name])
        fluid.set_flags({"FLAGS_epilogue_fusion": 0})
        (b,) = exe.run(main, feed=feed, fetch_list=[pred.name])
    fp = next(iter(exe._fusion_cache.values()))
    assert fp._serial != main._serial
    serials = {k[0][0] for k in exe._cache}
    # both the fused clone and the plain program compiled their own steps
    assert fp._serial in serials and main._serial in serials
    assert np.array_equal(a, b)


def test_run_chained_fused_matches_plain():
    main, startup, pred = _mlp("relu")
    feed = _feed()

    def chained(fused):
        fluid.set_flags({"FLAGS_epilogue_fusion": fused})
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            outs = exe.run_chained(main, feed=feed,
                                   fetch_list=[pred.name], steps=3,
                                   scope=scope)
        return np.asarray(outs[0])

    assert np.array_equal(chained(False), chained(True))


# ---------------------------------------------------------------------------
# kernel-vs-reference parity across tile-boundary shapes (interpret mode)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("shape", [(128, 128, 128), (64, 128, 256),
                                   (256, 384, 128), (8, 128, 128)])
@pytest.mark.parametrize("kind", ["plain", "bias+gelu", "ln"])
def test_kernel_parity_tile_boundaries(shape, kind):
    import jax.numpy as jnp

    m, n, k = shape
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(m, k).astype(np.float32))
    y = jnp.asarray(rng.randn(k, n).astype(np.float32))
    kw = {}
    if kind != "plain":
        kw["bias"] = jnp.asarray(rng.randn(n).astype(np.float32))
    if kind == "bias+gelu":
        kw["activation"] = "gelu"
    if kind == "ln":
        kw["layer_norm"] = True
        kw["ln_scale"] = jnp.asarray(rng.randn(n).astype(np.float32))
        kw["ln_bias"] = jnp.asarray(rng.randn(n).astype(np.float32))
    got = np.asarray(fused_gemm(x, y, interpret=True, **kw))
    want = np.asarray(fused_gemm_reference(x, y, **kw))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-4)


def test_classify_refuses_bad_tilings_with_reason():
    kind, reason = classify_gemm(100, 128, 128)
    assert kind == "unsupported" and "block_m=100" in reason
    kind, reason = classify_gemm(128, 1000, 128)
    assert kind == "unsupported" and "n=1000" in reason
    kind, reason = classify_gemm(128, 128, 100)
    assert kind == "unsupported" and "k=100" in reason
    # layer_norm demands the whole row in one block
    kind, reason = classify_gemm(128, 4096 * 4, 128, layer_norm=True)
    assert kind == "unsupported" and "layer_norm" in reason
    assert classify_gemm(128, 256, 128)[0] == "supported"


def test_always_mode_raises_loudly_on_unsupported_tiling():
    from paddle_tpu.ops.fused_gemm import fused_gemm_route

    fluid.set_flags({"FLAGS_use_fused_gemm": "always"})
    with pytest.raises(ValueError, match="no kernel tiling"):
        fused_gemm_route(100, 128, 128, layer_norm=False,
                         blocks=(128, 128, 128))


def test_kernel_route_matches_dense_route():
    """FLAGS_use_fused_gemm=always runs the interpret-mode kernel off-TPU;
    results must sit within the declared witness tolerance of the dense
    replay (the same bound the fusion witness enforces)."""
    main, startup, pred = _mlp("gelu")
    feed = _feed()
    base, _ = _run(main, startup, pred.name, feed, fused=False)
    fluid.set_flags({"FLAGS_use_fused_gemm": "always"})
    fused, _ = _run(main, startup, pred.name, feed, fused=True)
    np.testing.assert_allclose(base, fused, rtol=2e-4, atol=1e-4)


def test_tuned_blocks_flag_changes_cache_key():
    """Flipping FLAGS_fused_gemm_blocks must recompile, never silently
    reuse the old executable (blocks are part of the compile-cache key)."""
    from paddle_tpu import monitor

    main, startup, pred = _mlp("relu")
    feed = _feed()
    fluid.set_flags({"FLAGS_epilogue_fusion": 1})
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[pred.name])
        n0 = len(exe._cache)
        fluid.set_flags({"FLAGS_fused_gemm_blocks": "64,128,128"})
        (out,) = exe.run(main, feed=feed, fetch_list=[pred.name])
        assert len(exe._cache) == n0 + 1
    assert np.isfinite(out).all()


def test_fully_fused_program_reports_no_phantom_refusals():
    """The probe past a chain's surviving output is not a refusal: a
    fully-fused MLP whose final output is fetched must report
    n_refused == 0 and no PT751/PT752 for the value the fused op itself
    writes."""
    main, startup, pred = _mlp()
    diags = []
    dec = fuse_epilogues(main, feed_names=["x"],
                         fetch_names=[pred.name], diags=diags)
    assert dec.applied and dec.n_fused == 2
    assert dec.n_refused == 0
    phantom = [d for d in diags if d.code in ("PT751", "PT752")]
    assert not phantom, phantom


# ---------------------------------------------------------------------------
# write hazards between chain ops (PT756) — never a wrong program
# ---------------------------------------------------------------------------

def _clobbered_input_program():
    """mul -> increment(x, in_place) -> elementwise_add -> relu: the
    increment rewrites the chain's X input between the mul (its original
    read) and the chain's last op (where the fused op would read it)."""
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[128], dtype="float32")
            h = fluid.layers.fc(x, 128, act="relu")
            fluid.layers.increment(x, in_place=True)
    gb = main.global_block
    gb.ops.insert(1, gb.ops.pop())      # [mul, increment, add, relu]
    main._bump_version()
    return main, startup, h


def test_inplace_rewrite_of_chain_input_refuses_pt756():
    main, startup, h = _clobbered_input_program()
    assert [op.type for op in main.global_block.ops] == [
        "mul", "increment", "elementwise_add", "relu"]
    diags = []
    dec = fuse_epilogues(main, fetch_names=[h.name], diags=diags)
    assert not dec.applied
    assert any(d.code == "PT756" for d in diags), diags


def test_inplace_rewrite_runs_untransformed_and_matches():
    """Executor path: with fusion ON the clobbered program must run
    bit-identically to fusion OFF — it refuses, never a wrong program
    (before the PT756 gate this fused and returned (x+1)@W values)."""
    main, startup, h = _clobbered_input_program()
    feed = _feed()
    base, _ = _run(main, startup, h.name, feed, fused=False)
    fused, exe = _run(main, startup, h.name, feed, fused=True)
    assert np.array_equal(base, fused)
    assert not any(op.type == "fused_gemm_epilogue"
                   for p in exe._fusion_cache.values()
                   for op in p.global_block.ops)


def test_clobbered_intermediate_refuses_pt756():
    """A non-chain op that WRITES (without reading) a chain intermediate
    between its def and its read: the original add consumes the clobbered
    value, the fused op would recompute from the mul — refuse."""
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[128], dtype="float32")
            h = fluid.layers.fc(x, 128)
    gb = main.global_block
    mul_idx, mul_out = next(
        (i, op.output("Out")[0]) for i, op in enumerate(gb.ops)
        if op.type == "mul")
    gb.append_op("fill_constant", outputs={"Out": [mul_out]},
                 attrs={"shape": [32, 128], "dtype": "float32",
                        "value": 0.0})
    gb.ops.insert(mul_idx + 1, gb.ops.pop())    # [mul, fill, add]
    main._bump_version()
    diags = []
    dec = fuse_epilogues(main, fetch_names=[h.name], diags=diags)
    assert not dec.applied
    assert any(d.code == "PT756" for d in diags), diags


def test_residual_produced_between_chain_ops_still_fuses():
    """The legitimate def-between-chain-ops case: a residual operand
    PRODUCED (first write) between the matmul and its add is not a
    hazard — the fused op sits at the chain's last position precisely so
    this read stays def-before-use."""
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[128], dtype="float32")
            h = fluid.layers.fc(x, 128)                # mul, add (bias)
            r = fluid.layers.relu(x)                   # residual producer
            o = fluid.layers.elementwise_add(h, r)     # + residual
    gb = main.global_block
    types = [op.type for op in gb.ops]
    assert types == ["mul", "elementwise_add", "relu", "elementwise_add"]
    diags = []
    dec = fuse_epilogues(main, fetch_names=[o.name], diags=diags)
    assert dec.applied, [str(d) for d in diags]
    assert not any(d.code == "PT756" for d in diags)


# ---------------------------------------------------------------------------
# alpha-scaled matmul: one route authority (op, witness, PT755 agree)
# ---------------------------------------------------------------------------

def _alpha_chain(alpha):
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            a = fluid.layers.data("a", shape=[128, 128], dtype="float32",
                                  append_batch_size=False)
            b = fluid.layers.data("b", shape=[128, 128], dtype="float32",
                                  append_batch_size=False)
            c = fluid.layers.data("c", shape=[128], dtype="float32",
                                  append_batch_size=False)
            mm = fluid.layers.matmul(a, b, alpha=alpha)
            o = fluid.layers.relu(fluid.layers.elementwise_add(mm, c))
    rng = np.random.RandomState(0)
    feed = {"a": rng.randn(128, 128).astype(np.float32),
            "b": rng.randn(128, 128).astype(np.float32),
            "c": rng.randn(128).astype(np.float32)}
    return main, startup, o, feed


def test_alpha_scaled_matmul_routes_dense_and_reports_pt755():
    """alpha != 1 has no kernel variant: the shared route authority
    (fused_gemm_route) sends the witness down the bit-exact dense path
    and PT755 records why — even though the 128^3 tiling itself is
    kernel-supported."""
    from paddle_tpu.ops.fused_gemm import fused_gemm_route

    main, startup, o, feed = _alpha_chain(2.0)
    diags = []
    dec = fuse_epilogues(main, fetch_names=[o.name], diags=diags)
    assert dec.applied and dec.n_fused == 1
    pt755 = [d for d in diags if d.code == "PT755"]
    assert len(pt755) == 1 and "alpha=2.0" in pt755[0].message, pt755
    # the op lowering and the witness agree: primitive, even under the
    # 'always' promise (there is no kernel variant to insist on)
    route, reason = fused_gemm_route(128, 128, 128, layer_norm=False,
                                     blocks=(128, 128, 128), alpha=2.0)
    assert route == "primitive" and "alpha" in reason
    fluid.set_flags({"FLAGS_use_fused_gemm": "always"})
    route, _ = fused_gemm_route(128, 128, 128, layer_norm=False,
                                blocks=(128, 128, 128), alpha=2.0)
    assert route == "primitive"


def test_alpha_scaled_matmul_fused_is_bit_exact():
    main, startup, o, feed = _alpha_chain(2.0)
    base, _ = _run(main, startup, o.name, feed, fused=False)
    fused, exe = _run(main, startup, o.name, feed, fused=True)
    assert np.array_equal(base, fused)
    assert any(op.type == "fused_gemm_epilogue"
               for p in exe._fusion_cache.values()
               for op in p.global_block.ops)


# ---------------------------------------------------------------------------
# the witness runs the configuration that actually runs
# ---------------------------------------------------------------------------

def test_amp_program_fuses_on_kernel_route():
    """Under AMP the kernel route must hand back the unfused chain's
    promoted dtype (bf16 GEMM output meeting f32 epilogue params -> f32):
    before the out_dtype fix the witness meta check refused every AMP
    program on exactly the kernel route, so fusion never applied in its
    showcase configuration."""
    from paddle_tpu.contrib import mixed_precision as mp

    main, startup, pred = _mlp("gelu")
    mp.decorate_program(main)
    fluid.set_flags({"FLAGS_use_fused_gemm": "always"})
    dec = fuse_epilogues(main, fetch_names=[pred.name])
    assert dec.applied and dec.n_fused == 2, dec.reason
    feed = _feed()
    base, _ = _run(main, startup, pred.name, feed, fused=False)
    fused, _ = _run(main, startup, pred.name, feed, fused=True)
    assert base.dtype == fused.dtype == np.float32
    tol = 2e-2      # WITNESS_TOLERANCES['bfloat16']: the compute dtype
    assert np.allclose(base, fused, rtol=tol, atol=tol)


def test_witness_batch_resolves_dynamic_dims():
    """The executor plumbs the real feed rows into the pass; the PT755
    tiling report must classify at that m, not the sentinel 8 — a
    batch-250 feed is not sublane-aligned even though the sentinel is."""
    main, startup, pred = _mlp("relu")
    diags = []
    dec = fuse_epilogues(main, fetch_names=[pred.name], diags=diags,
                         batch=250)
    assert dec.applied
    pt755 = [d for d in diags if d.code == "PT755"]
    assert pt755 and "m=250" in pt755[0].message, pt755
    # the sentinel default (8) IS aligned: no PT755
    diags8 = []
    dec8 = fuse_epilogues(main, fetch_names=[pred.name], diags=diags8)
    assert dec8.applied
    assert not [d for d in diags8 if d.code == "PT755"]


def test_witness_runs_the_tuned_gemm_blocks():
    """gemm_blocks (the autotuner config the executor threads into the
    real compile's LowerCtx) must reach the witness and the PT755
    classify: a block size that does not divide the problem flips the
    route to dense, and the report must say so."""
    main, startup, pred = _mlp("relu")
    diags = []
    dec = fuse_epilogues(main, fetch_names=[pred.name], diags=diags,
                         gemm_blocks=(128, 128, 100))
    assert dec.applied
    pt755 = [d for d in diags if d.code == "PT755"]
    assert pt755 and "block_k=100" in pt755[0].message, pt755


def test_executor_fusion_cache_keys_on_tuned_blocks():
    """A cost-DB update that changes the tuned gemm blocks must
    re-witness: the executor's fusion-decision cache key includes the
    blocks resolved for this compile."""
    main, startup, pred = _mlp("relu")
    feed = _feed()
    fluid.set_flags({"FLAGS_epilogue_fusion": 1})
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[pred.name])
        n0 = len(exe._fusion_cache)
        fluid.set_flags({"FLAGS_fused_gemm_blocks": "64,128,128"})
        exe.run(main, feed=feed, fetch_list=[pred.name])
    assert len(exe._fusion_cache) == n0 + 1
    assert any(k[2] == (64, 128, 128) for k in exe._fusion_cache)
