"""paddle_tpu.analysis.concurrency + monitor.lockwitness — the PT800
lock-order linter, its CI gate (tools/lint_concurrency.py), and the
FLAGS_lock_witness runtime witness (ISSUE 16 tentpole). Positive and
negative controls: the fixture suite under tests/fixtures/concurrency
must trip every code family, the real package must gate clean, and the
witness must observe the same lock-order edges the static graph
predicts."""
import os
import threading
import time

import pytest

import paddle_tpu as fluid
from paddle_tpu import monitor
from paddle_tpu.analysis.concurrency import (analyze_package, analyze_paths,
                                             static_edge_set)

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "concurrency")


def _fixture_report(name):
    return analyze_paths([os.path.join(FIXTURES, name)], root=FIXTURES)


def _codes(report):
    return {d.code for d in report.diagnostics}


# -- static analysis: positive controls ------------------------------------

def test_ab_ba_deadlock_fixture_trips_pt800():
    rep = _fixture_report("deadlock_ab.py")
    pt800 = [d for d in rep.diagnostics if d.code == "PT800"]
    assert pt800, "AB/BA lock order must be reported as a cycle"
    assert pt800[0].severity == "error"
    assert "Worker._a" in pt800[0].op_type
    assert "Worker._b" in pt800[0].op_type
    # both orientations of the cycle are in the static edge set
    edges = rep.edge_set()
    a = next(e for e in edges if e[0].endswith("Worker._a"))
    assert (a[1], a[0]) in edges


def test_sleep_under_lock_fixture_trips_pt801_direct_and_transitive():
    rep = _fixture_report("sleep_under_lock.py")
    keys = {d.op_type for d in rep.diagnostics if d.code == "PT801"}
    # direct: get() sleeps inside the with-block
    assert any(k.endswith("CompileCache.get+time.sleep") for k in keys)
    # transitive: warm() holds the lock and calls _backoff() which sleeps
    # — the case a lexical grep cannot see
    assert any(k.endswith("CompileCache.warm+time.sleep") for k in keys)


def test_unguarded_attr_fixture_trips_pt802():
    rep = _fixture_report("unguarded_attr.py")
    pt802 = [d for d in rep.diagnostics if d.code == "PT802"]
    assert [d.op_type for d in pt802] == ["Stats.count"]
    # __init__ writes must not count as the second context on their own:
    # the finding exists because _loop (thread) and snapshot (caller)
    # both touch the attribute outside the lock
    assert "_loop" in pt802[0].message


# -- static analysis: negative controls ------------------------------------

def test_clean_fixture_produces_no_findings():
    rep = _fixture_report("clean.py")
    assert rep.diagnostics == [], (
        "Condition.wait under its own lock, Event.wait(timeout) and "
        "*_locked helpers must not be flagged: "
        + "; ".join(f"{d.code} {d.op_type}" for d in rep.diagnostics))


def test_clean_fixture_still_sees_the_locks_and_edges():
    rep = _fixture_report("clean.py")
    kinds = {d.kind for d in rep.locks.values()}
    assert {"lock", "condition", "event"} <= kinds
    # the consistent a-before-b order is one edge, acyclically
    assert any(e[0].endswith("Pipeline._a") and e[1].endswith("Pipeline._b")
               for e in rep.edge_set())


# -- the package gate ------------------------------------------------------

@pytest.fixture(scope="module")
def package_report():
    return analyze_package()


def test_package_has_no_lock_order_cycles(package_report):
    assert not [d for d in package_report.diagnostics
                if d.code == "PT800"], "a PT800 in the package is a deadlock"


def test_package_findings_are_all_allowlisted(package_report):
    from tools.lint_concurrency import ALLOWLIST, GATING_CODES
    unlisted = [d for d in package_report.diagnostics
                if d.code in GATING_CODES
                and (d.code, d.op_type) not in ALLOWLIST]
    assert unlisted == [], (
        "fix it or allowlist it with a reason: "
        + "; ".join(f"{d.code} {d.op_type} at {d.site}" for d in unlisted))
    # and the allowlist carries no stale entries (a fixed finding must
    # drop off the list, not linger as documentation)
    live = {(d.code, d.op_type) for d in package_report.diagnostics}
    stale = [k for k in ALLOWLIST if k not in live]
    assert stale == [], f"stale allowlist entries: {stale}"
    assert all(reason.strip() for reason in ALLOWLIST.values())


def test_package_inventories_the_named_framework_locks(package_report):
    # the witness factories take the canonical name as a literal; the
    # static analyzer reads the same literal, so the serving-tier locks
    # appear under exactly the names the runtime witness will report
    for name in ("ServingEngine._lock", "FleetRouter._lock",
                 "ReplicaSupervisor._lock", "Executor._lock",
                 "CompiledProgram._cache_lock", "Scope._lock",
                 "_CompiledStep._aot_lock", "aot_cache._warned_lock"):
        assert name in package_report.locks, name


def test_lint_cli_gate_is_clean(tmp_path, capsys):
    from tools.lint_concurrency import main
    out = tmp_path / "report.json"
    assert main(["--json", str(out)]) == 0
    assert "[ok] paddle_tpu" in capsys.readouterr().out
    import json
    rep = json.loads(out.read_text())
    assert rep["status"] == "ok"
    assert rep["targets"][0]["gating"] == []
    assert all(e["reason"] for e in rep["allowlist"])


def test_lint_cli_negative_control_fails(capsys):
    from tools.lint_concurrency import main
    assert main(["--negative-control"]) == 1
    captured = capsys.readouterr().out
    assert "-> FAIL" in captured
    for code in ("PT800", "PT801", "PT802"):
        assert code in captured


# -- runtime witness -------------------------------------------------------

@pytest.fixture()
def witness_on():
    fluid.set_flags({"FLAGS_lock_witness": 1})
    monitor.reset_witness()
    yield
    monitor.reset_witness()
    fluid.set_flags({"FLAGS_lock_witness": 0})


def test_witness_disabled_returns_plain_primitives():
    fluid.set_flags({"FLAGS_lock_witness": 0})
    assert isinstance(monitor.make_lock("t.plain"), type(threading.Lock()))
    assert isinstance(monitor.make_rlock("t.plain_r"),
                      type(threading.RLock()))
    assert isinstance(monitor.make_condition("t.plain_c"),
                      threading.Condition)
    assert monitor.witness_report()["enabled"] is False


def test_witness_records_nested_acquisition_edge(witness_on):
    outer = monitor.make_lock("t.outer")
    inner = monitor.make_lock("t.inner")
    with outer:
        with inner:
            pass
    assert ("t.outer", "t.inner") in monitor.witness_edges()
    assert ("t.inner", "t.outer") not in monitor.witness_edges()
    rep = monitor.witness_report()
    assert rep["enabled"] is True
    assert rep["locks"]["t.outer"]["acquisitions"] == 1
    assert rep["locks"]["t.inner"]["hold"]["count"] == 1
    assert rep["cycles"] == []


def test_witness_observes_runtime_ab_ba_cycle(witness_on):
    a = monitor.make_lock("t.a")
    b = monitor.make_lock("t.b")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    cycles = monitor.witness_cycles()
    assert cycles, "AB then BA at runtime must surface as a cycle"
    assert set(cycles[0]) == {"t.a", "t.b"}


def test_witness_reentrant_rlock_adds_no_self_edge(witness_on):
    r = monitor.make_rlock("t.re")
    with r:
        with r:
            pass
    assert monitor.witness_edges() == set()
    assert monitor.witness_cycles() == []


def test_witness_condition_wait_releases_the_lock(witness_on):
    lock = monitor.make_lock("t.cond_lock")
    cond = monitor.make_condition("t.cond", lock)
    hits = []

    def waiter():
        with cond:
            while not hits:
                cond.wait(timeout=1.0)
            hits.append("woken")

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with cond:        # acquirable => wait() really released the lock
        hits.append("notified")
        cond.notify()
    t.join(timeout=2.0)
    assert not t.is_alive()
    assert hits == ["notified", "woken"]
    # two threads acquired; wait-side reacquire counts too, and the
    # wait must not have manufactured a lock-order edge
    assert monitor.witness_report()["locks"]["t.cond_lock"][
        "acquisitions"] >= 3
    assert monitor.witness_edges() == set()


def test_witness_wait_hold_histograms_accumulate(witness_on):
    lock = monitor.make_lock("t.held")
    with lock:
        time.sleep(0.02)
    stats = monitor.witness_report()["locks"]["t.held"]
    assert stats["hold"]["count"] == 1
    assert stats["hold"]["max"] >= 0.015
    assert stats["wait"]["count"] == 1


def test_runtime_edges_are_a_subset_of_the_static_graph(witness_on,
                                                        package_report):
    """The witness gate contract: drive a real executor path with the
    witness on; every runtime lock-order edge over framework-named locks
    must be predicted by the static graph."""
    import numpy as np

    static = static_edge_set(package_report)
    static_names = {n for e in static for n in e} | set(
        package_report.locks)
    x = fluid.layers.data("x", shape=[4], dtype="float32")
    pred = fluid.layers.fc(x, 1)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    exe.run(feed={"x": np.zeros((2, 4), np.float32)},
            fetch_list=[pred.name])
    runtime = {e for e in monitor.witness_edges()
               if e[0] in static_names and e[1] in static_names}
    extra = runtime - static
    assert extra == set(), (
        f"runtime lock-order edges the static graph did not predict: "
        f"{sorted(extra)}")
    assert monitor.witness_cycles() == []
