"""OpTest coverage: conv/pool/norm/dropout/losses/embedding/topk.
(reference analogues: test_conv2d_op.py, test_pool2d_op.py,
test_batch_norm_op.py, test_layer_norm_op.py, test_softmax_with_cross_entropy_op.py,
test_lookup_table_op.py, test_top_k_op.py)"""
import numpy as np
import pytest

from op_test import OpTest

RNG = np.random.RandomState(7)  # only for label/index generation


def _x(shape, lo=-1.0, hi=1.0, seed=7):
    rng = np.random.RandomState(seed + int(np.prod(shape)) % 1000)
    return rng.uniform(lo, hi, shape).astype(np.float32)


def _ref_conv2d(x, w, stride, pad):
    n, c, h, ww = x.shape
    oc, ic, kh, kw = w.shape
    xp = np.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    oh = (h + 2 * pad - kh) // stride + 1
    ow = (ww + 2 * pad - kw) // stride + 1
    out = np.zeros((n, oc, oh, ow), np.float64)
    for i in range(oh):
        for j in range(ow):
            patch = xp[:, :, i * stride:i * stride + kh,
                       j * stride:j * stride + kw]  # n,c,kh,kw
            out[:, :, i, j] = np.einsum("nchw,ochw->no", patch, w)
    return out.astype(np.float32)


def test_conv2d():
    class T(OpTest):
        op_type = "conv2d"

        def setup(self):
            x = _x((2, 3, 8, 8))
            w = _x((4, 3, 3, 3))
            self.inputs = {"Input": x, "Filter": w}
            self.attrs = {"strides": [2, 2], "paddings": [1, 1],
                          "dilations": [1, 1], "groups": 1}
            self.outputs = {"Output": _ref_conv2d(x, w, 2, 1)}

    T().check_output(atol=1e-4, rtol=1e-3)
    T().check_grad(["Input", "Filter"], "Output", max_relative_error=1e-2)


def test_pool2d_max():
    class T(OpTest):
        op_type = "pool2d"

        def setup(self):
            x = _x((2, 3, 6, 6))
            ref = x.reshape(2, 3, 3, 2, 3, 2).max(axis=(3, 5))
            self.inputs = {"X": x}
            self.attrs = {"pooling_type": "max", "ksize": [2, 2],
                          "strides": [2, 2], "paddings": [0, 0]}
            self.outputs = {"Out": ref}

    T().check_output()


def test_pool2d_avg_global():
    class T(OpTest):
        op_type = "pool2d"

        def setup(self):
            x = _x((2, 5, 7, 7))
            self.inputs = {"X": x}
            self.attrs = {"pooling_type": "avg", "ksize": [1, 1],
                          "strides": [1, 1], "paddings": [0, 0],
                          "global_pooling": True}
            self.outputs = {"Out": x.mean(axis=(2, 3), keepdims=True)}

    T().check_output(atol=1e-5, rtol=1e-4)
    T().check_grad(["X"], "Out")


def test_batch_norm_training():
    class T(OpTest):
        op_type = "batch_norm"

        def setup(self):
            x = _x((4, 3, 5, 5))
            scale, bias = _x((3,), 0.5, 1.5, seed=1), _x((3,), seed=2)
            mean, var = np.zeros(3, np.float32), np.ones(3, np.float32)
            mom, eps = 0.9, 1e-5
            mu = x.mean(axis=(0, 2, 3))
            v = x.var(axis=(0, 2, 3))
            y = ((x - mu.reshape(1, 3, 1, 1)) /
                 np.sqrt(v.reshape(1, 3, 1, 1) + eps)
                 ) * scale.reshape(1, 3, 1, 1) + bias.reshape(1, 3, 1, 1)
            self.inputs = {"X": x, "Scale": scale, "Bias": bias,
                           "Mean": mean, "Variance": var}
            self.attrs = {"momentum": mom, "epsilon": eps, "is_test": False}
            self.outputs = {
                "Y": y,
                "MeanOut": mean * mom + mu * (1 - mom),
                "VarianceOut": var * mom + v * (1 - mom),
                "SavedMean": mu,
                "SavedVariance": 1.0 / np.sqrt(v + eps),
            }

    T().check_output(atol=1e-4, rtol=1e-3)


def test_layer_norm():
    class T(OpTest):
        op_type = "layer_norm"

        def setup(self):
            x = _x((4, 10))
            scale, bias = _x((10,), 0.5, 1.5, seed=1), _x((10,), seed=2)
            eps = 1e-5
            mu = x.mean(-1, keepdims=True)
            v = x.var(-1, keepdims=True)
            y = (x - mu) / np.sqrt(v + eps) * scale + bias
            self.inputs = {"X": x, "Scale": scale, "Bias": bias}
            self.attrs = {"epsilon": eps, "begin_norm_axis": 1}
            self.outputs = {"Y": y, "Mean": mu.reshape(4),
                            "Variance": v.reshape(4)}

    T().check_output(atol=1e-4, rtol=1e-3)
    T().check_grad(["X", "Scale", "Bias"], "Y", max_relative_error=1e-2)


def test_softmax_with_cross_entropy():
    class T(OpTest):
        op_type = "softmax_with_cross_entropy"

        def setup(self):
            logits = _x((6, 10), -2, 2)
            label = RNG.randint(0, 10, (6, 1)).astype(np.int64)
            e = np.exp(logits - logits.max(-1, keepdims=True))
            sm = e / e.sum(-1, keepdims=True)
            loss = -np.log(np.take_along_axis(sm, label, axis=1) + 1e-20)
            self.inputs = {"Logits": logits, "Label": label}
            self.attrs = {"soft_label": False, "ignore_index": -100,
                          "axis": -1}
            self.outputs = {"Softmax": sm, "Loss": loss}

    T().check_output(atol=1e-5, rtol=1e-4)


def test_softmax_with_cross_entropy_soft_label():
    class T(OpTest):
        op_type = "softmax_with_cross_entropy"

        def setup(self):
            logits = _x((5, 7), -2, 2)
            lbl = RNG.uniform(0, 1, (5, 7)).astype(np.float32)
            lbl /= lbl.sum(-1, keepdims=True)
            e = np.exp(logits - logits.max(-1, keepdims=True))
            sm = e / e.sum(-1, keepdims=True)
            loss = -(lbl * np.log(sm)).sum(-1, keepdims=True)
            self.inputs = {"Logits": logits, "Label": lbl}
            self.attrs = {"soft_label": True, "axis": -1}
            self.outputs = {"Softmax": sm, "Loss": loss}

    T().check_output(atol=1e-5, rtol=1e-4)


def test_cross_entropy_grad():
    class T(OpTest):
        op_type = "cross_entropy"

        def setup(self):
            x = RNG.uniform(0.1, 1.0, (5, 4)).astype(np.float32)
            x /= x.sum(-1, keepdims=True)
            label = RNG.randint(0, 4, (5, 1)).astype(np.int64)
            self.inputs = {"X": x, "Label": label}
            self.outputs = {"Y": -np.log(
                np.take_along_axis(x, label, axis=1) + 1e-12)}

    T().check_output(atol=1e-5, rtol=1e-4)
    T().check_grad(["X"], "Y", max_relative_error=1e-2)


def test_lookup_table():
    class T(OpTest):
        op_type = "lookup_table"

        def setup(self):
            w = _x((10, 6))
            ids = RNG.randint(0, 10, (4, 1)).astype(np.int64)
            self.inputs = {"W": w, "Ids": ids}
            self.outputs = {"Out": w[ids.reshape(-1)]}

    T().check_output()
    T().check_grad(["W"], "Out")


def test_lookup_table_padding_idx():
    class T(OpTest):
        op_type = "lookup_table"

        def setup(self):
            w = _x((10, 6))
            ids = np.array([[1], [3], [3], [5]], np.int64)
            ref = w[ids.reshape(-1)].copy()
            ref[ids.reshape(-1) == 3] = 0.0
            self.inputs = {"W": w, "Ids": ids}
            self.attrs = {"padding_idx": 3}
            self.outputs = {"Out": ref}

    T().check_output()


def test_top_k():
    class T(OpTest):
        op_type = "top_k"

        def setup(self):
            x = _x((4, 9))
            k = 3
            idx = np.argsort(-x, axis=1)[:, :k]
            self.inputs = {"X": x}
            self.attrs = {"k": k}
            self.outputs = {"Out": np.take_along_axis(x, idx, axis=1),
                            "Indices": idx.astype(np.int64)}

    T().check_output()


def test_dropout_test_mode():
    class T(OpTest):
        op_type = "dropout"

        def setup(self):
            x = _x((4, 8))
            self.inputs = {"X": x}
            self.attrs = {"dropout_prob": 0.3, "is_test": True,
                          "dropout_implementation": "downgrade_in_infer"}
            self.outputs = {"Out": x * 0.7, "Mask": np.ones_like(x)}

    T().check_output()


def test_dropout_train_statistics():
    """Train mode is random: check mask statistics + scaling contract."""
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[1000], dtype="float32")
        out = fluid.layers.dropout(x, 0.4,
                                   dropout_implementation="upscale_in_train")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        xv = np.ones((8, 1000), np.float32)
        (o,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
    kept = (o != 0)
    assert abs(kept.mean() - 0.6) < 0.03
    np.testing.assert_allclose(o[kept], 1.0 / 0.6, rtol=1e-5)


def test_one_hot():
    class T(OpTest):
        op_type = "one_hot"

        def setup(self):
            ids = RNG.randint(0, 6, (5, 1)).astype(np.int64)
            ref = np.zeros((5, 6), np.float32)
            ref[np.arange(5), ids.reshape(-1)] = 1.0
            self.inputs = {"X": ids}
            self.attrs = {"depth": 6, "dtype": "float32"}
            self.outputs = {"Out": ref}

    T().check_output()


def test_concat_and_grad():
    class T(OpTest):
        op_type = "concat"

        def setup(self):
            a, b = _x((3, 4)), _x((3, 2))
            self.inputs = {"X": [("ca", a), ("cb", b)]}
            self.attrs = {"axis": 1}
            self.outputs = {"Out": np.concatenate([a, b], axis=1)}

    T().check_output()


def test_transpose():
    class T(OpTest):
        op_type = "transpose2"

        def setup(self):
            x = _x((2, 3, 4))
            self.inputs = {"X": x}
            self.attrs = {"axis": [0, 2, 1]}
            self.outputs = {"Out": x.transpose(0, 2, 1),
                            "XShape": np.zeros((0,), np.float32)}

    T().check_output(no_check=("XShape",))
    T().check_grad(["X"], "Out")


def test_reshape():
    class T(OpTest):
        op_type = "reshape2"

        def setup(self):
            x = _x((2, 3, 4))
            self.inputs = {"X": x}
            self.attrs = {"shape": [2, 12]}
            self.outputs = {"Out": x.reshape(2, 12),
                            "XShape": np.zeros((0,), np.float32)}

    T().check_output(no_check=("XShape",))


def test_slice():
    class T(OpTest):
        op_type = "slice"

        def setup(self):
            x = _x((4, 6, 5))
            self.inputs = {"Input": x}
            self.attrs = {"axes": [1, 2], "starts": [1, 0],
                          "ends": [4, 3], "decrease_axis": []}
            self.outputs = {"Out": x[:, 1:4, 0:3]}

    T().check_output()
    T().check_grad(["Input"], "Out")


def test_gather_grad():
    class T(OpTest):
        op_type = "gather"

        def setup(self):
            x = _x((8, 4))
            idx = np.array([1, 3, 3, 6], np.int64)
            self.inputs = {"X": x, "Index": idx}
            self.outputs = {"Out": x[idx]}

    T().check_output()
    T().check_grad(["X"], "Out")


def test_conv_pool_nhwc_lowering_matches_nchw():
    """FLAGS_conv_use_nhwc=always (the TPU lowering: NHWC inner layout,
    boundary transposes) must be numerically identical to the NCHW
    reference lowering — conv2d, depthwise, conv2d_transpose, pool2d."""
    import paddle_tpu as fluid
    from paddle_tpu import flags

    rng = np.random.RandomState(9)
    xb = rng.randn(2, 8, 16, 16).astype(np.float32)

    def build_and_run():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[8, 16, 16], dtype="float32")
            h = fluid.layers.conv2d(x, 16, 3, padding=1, act="relu")
            h = fluid.layers.pool2d(h, pool_size=2, pool_type="max",
                                    pool_stride=2)
            h = fluid.layers.conv2d(h, 16, 3, padding=1, groups=16)
            h = fluid.layers.conv2d_transpose(h, 8, filter_size=2, stride=2)
            h = fluid.layers.pool2d(h, pool_size=2, pool_type="avg",
                                    pool_stride=2)
            loss = fluid.layers.mean(h)
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            (out,) = exe.run(main, feed={"x": xb}, fetch_list=[h.name])
        return np.asarray(out)

    import paddle_tpu.unique_name as un

    try:
        flags.set_flags({"FLAGS_conv_use_nhwc": "never"})
        with un.guard():
            ref = build_and_run()
        flags.set_flags({"FLAGS_conv_use_nhwc": "always"})
        with un.guard():
            got = build_and_run()
    finally:
        flags.set_flags({"FLAGS_conv_use_nhwc": "auto"})
    np.testing.assert_allclose(got, ref, atol=1e-5, rtol=1e-5)


class TestConv2dTranspose(OpTest):
    """Scatter-add numpy oracle for the reference conv2d_transpose
    semantics (filter [in, out, kh, kw], out = (H-1)*s - 2p + k).
    Regression: the old lowering failed whenever in_ch != out_ch."""

    def setup(self):
        rng = np.random.RandomState(4)
        x = rng.randn(2, 6, 5, 5).astype(np.float32)
        w = rng.randn(6, 3, 3, 3).astype(np.float32)
        stride, pad = 2, 1
        B, I, H, W = x.shape
        _, O, KH, KW = w.shape
        full = np.zeros((B, O, (H-1)*stride+KH, (W-1)*stride+KW), np.float32)
        for b in range(B):
            for i in range(I):
                for h in range(H):
                    for wi in range(W):
                        full[b, :, h*stride:h*stride+KH,
                             wi*stride:wi*stride+KW] += x[b, i, h, wi] * w[i]
        out = full[:, :, pad:full.shape[2]-pad, pad:full.shape[3]-pad]
        self.op_type = "conv2d_transpose"
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = {"strides": [stride, stride], "paddings": [pad, pad],
                      "dilations": [1, 1]}
        self.outputs = {"Output": out}

    def test(self):
        self.check_output(rtol=1e-4, atol=1e-5)
        self.check_grad(["Input", "Filter"], "Output",
                        max_relative_error=2e-2)
