"""PipelineOptimizer (microbatched training; reference optimizer.py:2781 +
PipelineTrainer/SectionWorker): with a mean loss, M accumulated microbatch
gradients average to the full-batch gradient, so training must match the
plain path exactly."""
import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.unique_name as un


def _build(microbatches=None):
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[16], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="float32")
            h = fluid.layers.fc(x, 32, act="relu")
            pred = fluid.layers.fc(h, 1)
            loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
            opt = fluid.optimizer.SGD(learning_rate=0.05)
            if microbatches:
                opt = fluid.optimizer.PipelineOptimizer(
                    opt, num_microbatches=microbatches)
            opt.minimize(loss)
    return main, startup, loss


def _train(microbatches=None, steps=8, batch=32, compiled=False):
    main, startup, loss = _build(microbatches)
    main.random_seed = 23
    prog = main
    if compiled:
        prog = fluid.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(1)
    xb = rng.randn(batch, 16).astype(np.float32)
    yb = rng.randn(batch, 1).astype(np.float32)
    out = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(steps):
            (lv,) = exe.run(prog, feed={"x": xb, "y": yb},
                            fetch_list=[loss.name])
            out.append(float(np.asarray(lv).reshape(-1)[0]))
    return out


def test_pipeline_matches_plain_sgd():
    """Param updates must be identical (mean loss => averaged microbatch
    grads == full-batch grads); only the REPORTED loss differs (last
    microbatch vs full batch), so compare from step 1 via param effects."""
    base = _train(None)
    pipe = _train(4)
    # the training trajectory (loss after >=1 update) must track closely:
    # identical params => pipe's step-k loss over its last microbatch equals
    # base loss over that subset; check convergence + the end state via a
    # fresh full-batch eval below instead of comparing mid-run numbers
    assert pipe[-1] < pipe[0]
    assert base[-1] < base[0]


def test_pipeline_params_equal_plain():
    """After N steps the parameters are bit-comparable to the plain path."""
    def run(micro):
        main, startup, loss = _build(micro)
        main.random_seed = 23
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        rng = np.random.RandomState(1)
        xb = rng.randn(32, 16).astype(np.float32)
        yb = rng.randn(32, 1).astype(np.float32)
        with fluid.scope_guard(scope):
            exe.run(startup)
            for _ in range(6):
                exe.run(main, feed={"x": xb, "y": yb},
                        fetch_list=[loss.name])
            params = {n: np.asarray(v) for n, v in scope.vars.items()
                      if n.endswith(".w_0") or n.endswith(".b_0")}
        return params

    p_plain = run(None)
    p_pipe = run(4)
    assert p_plain.keys() == p_pipe.keys() and len(p_plain) >= 4
    for n in p_plain:
        np.testing.assert_allclose(p_pipe[n], p_plain[n], rtol=2e-5,
                                   atol=1e-6, err_msg=n)


def test_pipeline_under_data_parallel_mesh():
    losses = _train(2, compiled=True)
    assert losses[-1] < losses[0]


def test_pipeline_batch_divisibility_error():
    main, startup, loss = _build(3)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        exe.run(startup)
        with pytest.raises(Exception, match="divisible"):
            exe.run(main, feed={"x": rng.randn(32, 16).astype(np.float32),
                                "y": rng.randn(32, 1).astype(np.float32)},
                    fetch_list=[loss.name])


def test_gradient_merge_optimizer_alias():
    """GradientMergeOptimizer is the accumulation schedule under its own
    name (reference multi_batch_merge_pass); PipelineOptimizer subclasses
    it and records cut_list boundaries on the program."""
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[8], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="float32")
            h = fluid.layers.fc(x, 8, act="relu")
            pred = fluid.layers.fc(h, 1)
            loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
            opt = fluid.optimizer.PipelineOptimizer(
                fluid.optimizer.SGD(learning_rate=0.1),
                cut_list=[[h]], num_microbatches=2)
            opt.minimize(loss)
    assert main._pipeline_microbatches == 2
    assert main._pipeline_cut_names == [h.name]
    with pytest.raises(ValueError, match="unknown vars"):
        with un.guard():
            m2, s2 = fluid.Program(), fluid.Program()
            with fluid.program_guard(m2, s2):
                x = fluid.layers.data("x", shape=[8], dtype="float32")
                pred = fluid.layers.fc(x, 1)
                loss = fluid.layers.mean(pred)
                fluid.optimizer.PipelineOptimizer(
                    fluid.optimizer.SGD(learning_rate=0.1),
                    cut_list=["nonexistent_var"]).minimize(loss)


def _build_region_model(P=4, M=4, D=16):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[D], dtype="float32")
        y = fluid.layers.data("y", shape=[D], dtype="float32")
        pipe = fluid.layers.PipelineRegion(num_stages=P, num_microbatches=M)
        with pipe.stage(x) as s:
            w = s.param("w", [D, D])
            b = s.param("b", [D], is_bias=True)
            h = fluid.layers.tanh(fluid.layers.elementwise_add(
                fluid.layers.matmul(s.input, w), b))
            s.set_output(h)
        out = pipe.output
        loss = fluid.layers.mean(fluid.layers.square_error_cost(out, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss, out


def _run_region(main, startup, loss, wv, bv, xb, yb, steps=4, mesh=None):
    import jax

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for n in list(scope.vars):
            if n.endswith("w.pp_stacked"):
                scope.set_var(n, wv)
            if n.endswith("b.pp_stacked"):
                scope.set_var(n, bv)
        losses = []
        prog = main
        if mesh is not None:
            from paddle_tpu.parallel.compiled_program import CompiledProgram

            prog = fluid.CompiledProgram(main).with_data_parallel(
                loss_name=loss.name, places=mesh)
        for _ in range(steps):
            (lv,) = exe.run(prog, feed={"x": xb, "y": yb},
                            fetch_list=[loss.name])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
    return losses


def test_pipeline_region_matches_numpy_and_trains():
    """The pipeline op's scan path: forward equals the stage-by-stage
    numpy composition; SGD steps reduce the loss (grads flow through the
    stacked params)."""
    P, D = 4, 16
    rng = np.random.RandomState(3)
    wv = (rng.randn(P, D, D) / np.sqrt(D)).astype(np.float32)
    bv = (rng.randn(P, D) * 0.1).astype(np.float32)
    xb = rng.randn(8, D).astype(np.float32)
    yb = rng.randn(8, D).astype(np.float32)
    with un.guard():
        main, startup, loss, out = _build_region_model(P=P)
    losses = _run_region(main, startup, loss, wv, bv, xb, yb)
    h = xb
    for s in range(P):
        h = np.tanh(h @ wv[s] + bv[s])
    np.testing.assert_allclose(losses[0], ((h - yb) ** 2).mean(), rtol=1e-5)
    assert losses[-1] < losses[0]


@pytest.mark.known_flaky(
    reason="KNOWN_FAILURES.md 'Pre-existing flake': intermittently "
           "raises inside shard_map during the pipeline op's lowering in "
           "whole-SUITE runs only (jax-0.4.x shard_map shim class, "
           "surfaced order-dependently by cross-test jax global state, "
           "present since ISSUE 12); passes standalone. Expect ±1 on "
           "the tier-1 count")
def test_pipeline_region_gpipe_schedule_on_pp_mesh():
    """On a dp x pp mesh the op runs the REAL GPipe schedule (shard_map +
    ppermute between stages, stage params sharded over pp); losses must
    equal the scan path bit-for-bit-ish."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from paddle_tpu.parallel.sharding import make_mesh

    P, D = 4, 16
    rng = np.random.RandomState(3)
    wv = (rng.randn(P, D, D) / np.sqrt(D)).astype(np.float32)
    bv = (rng.randn(P, D) * 0.1).astype(np.float32)
    xb = rng.randn(8, D).astype(np.float32)
    yb = rng.randn(8, D).astype(np.float32)
    with un.guard():
        main, startup, loss, out = _build_region_model(P=P)
    plain = _run_region(main, startup, loss, wv, bv, xb, yb)
    with un.guard():
        main, startup, loss, out = _build_region_model(P=P)
    mesh = make_mesh({"dp": 2, "pp": 4})
    piped = _run_region(main, startup, loss, wv, bv, xb, yb, mesh=mesh)
    np.testing.assert_allclose(piped, plain, rtol=2e-5, atol=1e-6)


def test_pipeline_region_emits_collective_permute():
    """The pp-mesh path must be REAL pipelining: the compiled HLO contains
    collective-permute ops moving activations between stage ranks."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    import jax.numpy as jnp
    from paddle_tpu.parallel.sharding import compile_sharded_step, make_mesh

    D, P_ = 16, 4
    with un.guard():
        main, startup, loss, out = _build_region_model(P=P_, D=D)
    mesh = make_mesh({"dp": 2, "pp": 4})
    jitted, io = compile_sharded_step(main, mesh, ["x", "y"], [loss.name])
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    args = ([jnp.zeros((8, D), jnp.float32), jnp.zeros((8, D), jnp.float32)],
            [jnp.asarray(scope.find_var(n)) for n in io["donated"]],
            [jnp.asarray(scope.find_var(n)) for n in io["ro"]],
            jax.random.key(0))
    txt = jitted.lower(*args).compile().as_text()
    assert txt.count("collective-permute") > 0
