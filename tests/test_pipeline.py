"""PipelineOptimizer (microbatched training; reference optimizer.py:2781 +
PipelineTrainer/SectionWorker): with a mean loss, M accumulated microbatch
gradients average to the full-batch gradient, so training must match the
plain path exactly."""
import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.unique_name as un


def _build(microbatches=None):
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[16], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="float32")
            h = fluid.layers.fc(x, 32, act="relu")
            pred = fluid.layers.fc(h, 1)
            loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
            opt = fluid.optimizer.SGD(learning_rate=0.05)
            if microbatches:
                opt = fluid.optimizer.PipelineOptimizer(
                    opt, num_microbatches=microbatches)
            opt.minimize(loss)
    return main, startup, loss


def _train(microbatches=None, steps=8, batch=32, compiled=False):
    main, startup, loss = _build(microbatches)
    main.random_seed = 23
    prog = main
    if compiled:
        prog = fluid.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(1)
    xb = rng.randn(batch, 16).astype(np.float32)
    yb = rng.randn(batch, 1).astype(np.float32)
    out = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(steps):
            (lv,) = exe.run(prog, feed={"x": xb, "y": yb},
                            fetch_list=[loss.name])
            out.append(float(np.asarray(lv).reshape(-1)[0]))
    return out


def test_pipeline_matches_plain_sgd():
    """Param updates must be identical (mean loss => averaged microbatch
    grads == full-batch grads); only the REPORTED loss differs (last
    microbatch vs full batch), so compare from step 1 via param effects."""
    base = _train(None)
    pipe = _train(4)
    # the training trajectory (loss after >=1 update) must track closely:
    # identical params => pipe's step-k loss over its last microbatch equals
    # base loss over that subset; check convergence + the end state via a
    # fresh full-batch eval below instead of comparing mid-run numbers
    assert pipe[-1] < pipe[0]
    assert base[-1] < base[0]


def test_pipeline_params_equal_plain():
    """After N steps the parameters are bit-comparable to the plain path."""
    def run(micro):
        main, startup, loss = _build(micro)
        main.random_seed = 23
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        rng = np.random.RandomState(1)
        xb = rng.randn(32, 16).astype(np.float32)
        yb = rng.randn(32, 1).astype(np.float32)
        with fluid.scope_guard(scope):
            exe.run(startup)
            for _ in range(6):
                exe.run(main, feed={"x": xb, "y": yb},
                        fetch_list=[loss.name])
            params = {n: np.asarray(v) for n, v in scope.vars.items()
                      if n.endswith(".w_0") or n.endswith(".b_0")}
        return params

    p_plain = run(None)
    p_pipe = run(4)
    assert p_plain.keys() == p_pipe.keys() and len(p_plain) >= 4
    for n in p_plain:
        np.testing.assert_allclose(p_pipe[n], p_plain[n], rtol=2e-5,
                                   atol=1e-6, err_msg=n)


def test_pipeline_under_data_parallel_mesh():
    losses = _train(2, compiled=True)
    assert losses[-1] < losses[0]


def test_pipeline_batch_divisibility_error():
    main, startup, loss = _build(3)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        exe.run(startup)
        with pytest.raises(Exception, match="divisible"):
            exe.run(main, feed={"x": rng.randn(32, 16).astype(np.float32),
                                "y": rng.randn(32, 1).astype(np.float32)},
                    fetch_list=[loss.name])
