"""paddle_tpu.monitor — metrics registry, executor instrumentation,
recompilation diagnostics, event hooks, and the metrics_report CI gate
(ISSUE 3 tentpole; reference platform/profiler.h gave Fluid this kind of
visibility per op — here it is per executor hot path)."""
import json
import logging

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import monitor


@pytest.fixture(autouse=True)
def _fresh_monitor():
    monitor.reset()
    monitor.clear_hooks()
    yield
    monitor.reset()
    monitor.clear_hooks()


def _build_train():
    x = fluid.layers.data("x", shape=[4], dtype="float32")
    y = fluid.layers.data("y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(x, 1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(0.1).minimize(loss)
    return loss


def _feed(batch=8, dtype=np.float32):
    rng = np.random.RandomState(0)
    return {"x": rng.rand(batch, 4).astype(dtype),
            "y": rng.rand(batch, 1).astype(dtype)}


# -- registry --------------------------------------------------------------

def test_registry_counter_gauge_histogram():
    reg = monitor.MetricsRegistry()
    c = reg.counter("c_total", "a counter")
    c.inc()
    c.inc(2)
    assert c.value == 3
    c.labels(path="run").inc(5)
    assert c.labels(path="run").value == 5
    assert c.value == 3  # empty-label child is separate
    with pytest.raises(ValueError):
        c.inc(-1)

    g = reg.gauge("g")
    g.set(7)
    g.dec(2)
    assert g.value == 5

    h = reg.histogram("h_seconds", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    snap = h.labels().snapshot()
    assert snap["count"] == 3
    assert snap["min"] == 0.05 and snap["max"] == 5.0
    assert snap["buckets"]["0.1"] == 1       # cumulative: <=0.1
    assert snap["buckets"]["1.0"] == 2       # <=1.0
    assert snap["buckets"]["+Inf"] == 3

    with pytest.raises(TypeError):
        reg.gauge("c_total")  # kind conflict


def test_registry_exporters_json_and_prometheus():
    reg = monitor.MetricsRegistry()
    reg.counter("x_total", "help text").labels(kind="a").inc(2)
    reg.histogram("t_seconds", buckets=(1.0,)).observe(0.5)
    d = json.loads(reg.to_json())  # round-trips through JSON
    assert d["x_total"]["kind"] == "counter"
    assert d["x_total"]["values"][0] == {"labels": {"kind": "a"},
                                         "value": 2}
    text = reg.to_prometheus()
    assert '# TYPE x_total counter' in text
    assert 'x_total{kind="a"} 2' in text
    assert 't_seconds_bucket{le="1.0"} 1' in text
    assert 't_seconds_count 1' in text


# -- executor instrumentation ---------------------------------------------

def test_two_run_repeat_reports_one_compile_one_hit():
    """Acceptance bar: a two-exe.run repeat of the same program shows
    exactly 1 compile + 1 cache hit in the metrics JSON."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss = _build_train()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = _feed()
    with fluid.scope_guard(scope):
        exe.run(startup)
        monitor.reset()  # measurement window: just the two main runs
        exe.run(main, feed=feed, fetch_list=[loss])
        exe.run(main, feed=feed, fetch_list=[loss])
    snap = json.loads(json.dumps(monitor.snapshot(), default=str))
    lookups = {tuple(sorted(v["labels"].items())): v["value"]
               for v in snap["metrics"]
               ["executor_cache_lookups_total"]["values"]}
    assert lookups[(("path", "run"), ("result", "miss"))] == 1
    assert lookups[(("path", "run"), ("result", "hit"))] == 1
    compiles = snap["metrics"]["executor_compiles_total"]["values"]
    assert [v["value"] for v in compiles
            if v["labels"] == {"path": "run"}] == [1]
    assert snap["recompiles_total"] == 0
    # compile stage breakdown was measured (trace+lower / xla compile)
    stages = {tuple(v["labels"].items()): v["value"]
              for v in snap["metrics"]
              ["executor_compile_seconds"]["values"]}
    assert stages[(("stage", "trace_lower"),)]["count"] == 1
    assert stages[(("stage", "xla_compile"),)]["count"] == 1
    assert stages[(("stage", "xla_compile"),)]["sum"] > 0


def test_recompile_diagnostic_names_feed_signature_and_build_site():
    """Acceptance bar: changing the feed shape/dtype triggers a diagnostic
    naming the changed cache-key component and the program build site."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss = _build_train()   # build site recorded from THIS file
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=_feed(batch=8), fetch_list=[loss])
        assert monitor.recompile_count() == 0
        exe.run(main, feed=_feed(batch=16), fetch_list=[loss])   # shape
        exe.run(main, feed=_feed(batch=16, dtype=np.float64),
                fetch_list=[loss])                               # dtype
    evs = monitor.recompile_events()
    assert len(evs) == 2
    for ev in evs:
        assert ev.changed == ("feed_signature",)
        assert "test_monitor.py" in ev.build_site
    assert "(8, 4)" in evs[0].detail and "(16, 4)" in evs[0].detail
    assert "float64" in evs[1].detail
    assert monitor.recompile_count() == 2


def test_recompile_diagnostic_names_fetch_list_and_scope():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss = _build_train()
        pred = main.global_block.ops  # noqa: F841  (site anchor)
    exe = fluid.Executor(fluid.CPUPlace())
    feed = _feed()
    s1, s2 = fluid.Scope(), fluid.Scope()
    for s in (s1, s2):
        with fluid.scope_guard(s):
            exe.run(startup)
    with fluid.scope_guard(s1):
        exe.run(main, feed=feed, fetch_list=[loss])
        exe.run(main, feed=feed, fetch_list=[])       # fetch list changed
    ev = monitor.recompile_events()[-1]
    assert "fetch_list" in ev.changed
    with fluid.scope_guard(s2):
        exe.run(main, feed=feed, fetch_list=[])       # scope changed
    ev = monitor.recompile_events()[-1]
    assert "scope" in ev.changed


def test_recompile_warns_after_threshold(caplog):
    fluid.set_flags({"FLAGS_recompile_warn_threshold": 2})
    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            loss = _build_train()
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with caplog.at_level(logging.WARNING, logger="paddle_tpu.monitor"):
            with fluid.scope_guard(scope):
                exe.run(startup)
                for i in range(3):  # compile + 2 recompiles = threshold
                    exe.run(main, feed=_feed(batch=8 * (i + 1)),
                            fetch_list=[loss])
        warned = [r for r in caplog.records
                  if "recompiled 2 times" in r.message]
        assert len(warned) == 1
        assert "feed_signature" in warned[0].message
    finally:
        fluid.set_flags({"FLAGS_recompile_warn_threshold": 3})


def test_log_compiles_flag_logs_every_compile(caplog):
    fluid.set_flags({"FLAGS_log_compiles": 1})
    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            loss = _build_train()
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with caplog.at_level(logging.INFO, logger="paddle_tpu.monitor"):
            with fluid.scope_guard(scope):
                exe.run(startup)
                exe.run(main, feed=_feed(), fetch_list=[loss])
                exe.run(main, feed=_feed(batch=4), fetch_list=[loss])
        msgs = [r.message for r in caplog.records]
        assert any("compiling program" in m for m in msgs)
        assert any("cache-key changed in feed_signature" in m for m in msgs)
    finally:
        fluid.set_flags({"FLAGS_log_compiles": 0})


def test_monitor_flag_disables_collection():
    fluid.set_flags({"FLAGS_monitor": 0})
    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            loss = _build_train()
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            exe.run(main, feed=_feed(), fetch_list=[loss])
    finally:
        fluid.set_flags({"FLAGS_monitor": 1})
    assert monitor.metric_value("executor_steps_total", default=None,
                                path="run") is None
    assert monitor.recompile_events(recompiles_only=False) == []


# -- hooks -----------------------------------------------------------------

def test_hooks_observe_steps_and_compiles():
    begins, ends, compiles = [], [], []
    hook = monitor.add_hook(on_step_begin=begins.append,
                            on_step_end=ends.append,
                            on_compile=compiles.append)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss = _build_train()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = _feed()
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[loss])
        exe.run(main, feed=feed, fetch_list=[loss])
    assert len(begins) == 3 and len(ends) == 3  # startup + 2 main runs
    run_ends = [e for e in ends if e.program_serial == main._serial]
    assert [e.cache_hit for e in run_ends] == [False, True]
    assert all(e.duration_s > 0 for e in run_ends)
    assert run_ends[0].feed_bytes == 8 * 4 * 4 + 8 * 4  # x f32 + y f32
    assert run_ends[0].fetch_bytes == 4                 # scalar f32 loss
    assert run_ends[0].donated_buffers > 0
    comp = [c for c in compiles if c.program_serial == main._serial]
    assert len(comp) == 1
    assert comp[0].trace_lower_s > 0 and comp[0].compile_s > 0
    n_before = len(ends)
    monitor.remove_hook(hook)
    with fluid.scope_guard(scope):
        exe.run(main, feed=feed, fetch_list=[loss])
    assert len(ends) == n_before  # unsubscribed


def test_step_end_fires_even_when_the_step_raises():
    """Review finding: a step that raises (FLAGS_check_nan_inf) must still
    pair step_begin with step_end — hooks tracking in-flight steps would
    otherwise desync and failed dispatches would vanish from the metrics."""
    begins, ends = [], []
    monitor.add_hook(on_step_begin=begins.append, on_step_end=ends.append)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        out = fluid.layers.mean(fluid.layers.log(x))
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    fluid.set_flags({"FLAGS_check_nan_inf": 1})
    try:
        with fluid.scope_guard(scope):
            exe.run(startup)
            with pytest.raises(FloatingPointError):
                exe.run(main, feed={"x": -np.ones((2, 4), np.float32)},
                        fetch_list=[out.name])
    finally:
        fluid.set_flags({"FLAGS_check_nan_inf": 0})
    assert len(begins) == len(ends) == 2  # startup + the failing step
    assert monitor.metric_value("executor_steps_total", path="run") == 2


def test_raising_hook_does_not_break_execution():
    def bad_hook(rec):
        raise RuntimeError("observer crashed")

    monitor.add_hook(on_step_end=bad_hook)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss = _build_train()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        (v,) = exe.run(main, feed=_feed(), fetch_list=[loss])
    assert np.isfinite(np.asarray(v)).all()


# -- donation stats on the run_chained kept-state fixture (PR 2) -----------

def test_chained_donation_stats_kept_vs_donated():
    """The fetched-param fixture: liveness refuses donation for the param
    (kept, threads the carry) while the rest of the state donates — the
    monitor must report both sides, plus per-dispatch iteration counts."""
    ends = []
    monitor.add_hook(on_step_end=ends.append)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss = _build_train()
        param = next(v.name for v in main.global_block.vars.values()
                     if type(v).__name__ == "Parameter"
                     and v.name.endswith(".w_0"))
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run_chained(main, feed=_feed(), fetch_list=[loss, param],
                        steps=3)
    step = next(s for k, s in exe._cache.items() if k[0] == "chained")
    rec = next(e for e in ends if e.path == "chained")
    assert rec.iterations == 3
    assert rec.cache_hit is False
    assert rec.donated_buffers == len(step.donated_names) > 0
    assert rec.kept_buffers == len(step.kept_names) >= 1
    assert rec.donated_bytes > 0
    assert monitor.metric_value("executor_chained_iterations_total") == 3
    assert monitor.metric_value("executor_kept_buffers_total") >= 1


def test_aot_step_never_mutates_host_numpy_state():
    """The AOT fast path donates its state args; a host numpy param the
    user planted with scope.set_var must be copied, never zero-copy
    aliased — donating an aliased buffer would let XLA write the step
    output INTO the user's array (surfaced as an alignment-dependent
    test_pipeline failure). jit dispatch skips donation for numpy args;
    _own_donated restores that guarantee for the AOT executable."""
    import jax

    from paddle_tpu.executor import _own_donated

    w = np.ones((64, 64), np.float32)
    (owned,) = _own_donated([w])
    assert isinstance(owned, jax.Array)
    w[:] = 7  # mutating the host array must not reach the owned copy
    assert float(np.asarray(owned)[0, 0]) == 1.0

    # end-to-end: plant numpy params, train twice, host arrays stay intact
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss = _build_train()
    param = next(v.name for v in main.global_block.vars.values()
                 if type(v).__name__ == "Parameter")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = _feed()
    with fluid.scope_guard(scope):
        exe.run(startup)
        w0 = np.asarray(scope.find_var(param)).copy()
        planted = w0.copy()
        scope.set_var(param, planted)
        exe.run(main, feed=feed, fetch_list=[loss])
        exe.run(main, feed=feed, fetch_list=[loss])
        chained_planted = np.asarray(scope.find_var(param)).copy()
        scope.set_var(param, chained_planted)
        before = chained_planted.copy()
        exe.run_chained(main, feed=feed, fetch_list=[loss], steps=2)
    np.testing.assert_array_equal(planted, w0)
    np.testing.assert_array_equal(chained_planted, before)


# -- tools/metrics_report.py gate -----------------------------------------

def test_metrics_report_check_passes_and_writes_artifact(tmp_path):
    import tools.metrics_report as mr

    out = tmp_path / "metrics.json"
    assert mr.main(["--check", "--json", str(out)]) == 0
    data = json.loads(out.read_text())
    by_name = {s["name"]: s for s in data["scenarios"]}
    # acceptance: the repeat scenario shows exactly 1 compile + 1 hit
    assert by_name["run_repeat"]["metrics"]["run_compiles"] == 1
    assert by_name["run_repeat"]["metrics"]["run_hits"] == 1
    assert data["check"]["status"] == "ok"
    assert data["snapshot"]["recompiles_total"] == 0


def test_metrics_report_check_fails_on_forced_recompiles(tmp_path):
    import tools.metrics_report as mr

    out = tmp_path / "metrics_forced.json"
    rc = mr.main(["--check", "--force-recompile", "2", "--json", str(out)])
    assert rc != 0
    data = json.loads(out.read_text())
    forced = next(s for s in data["scenarios"] if s.get("forced"))
    assert forced["metrics"]["recompiles"] == 2
    assert "feed_signature" in str(forced["diagnostic"])
    assert data["check"]["status"] == "fail"


# ---------------------------------------------------------------------------
# histogram quantile estimation (serving SLOs: p50/p99)
# ---------------------------------------------------------------------------

def test_histogram_quantiles_interpolate_within_buckets():
    from paddle_tpu.monitor.registry import Histogram
    import threading

    h = Histogram(threading.RLock(), buckets=(1.0, 2.0, 4.0, 8.0))
    for v in (0.5, 1.5, 1.5, 3.0, 7.0):
        h.observe(v)
    p50 = h.quantile(0.5)
    assert 1.0 <= p50 <= 2.0, f"median of (0.5,1.5,1.5,3,7) ~ bucket (1,2], got {p50}"
    p99 = h.quantile(0.99)
    assert 4.0 <= p99 <= 7.0, "p99 lands in (4,8] but clamps to max=7"
    # clamping: a single observation pins every quantile to itself
    h1 = Histogram(threading.RLock(), buckets=(1.0, 2.0))
    h1.observe(1.7)
    assert h1.quantile(0.5) == h1.quantile(0.99) == 1.7


def test_histogram_quantiles_empty_and_overflow():
    from paddle_tpu.monitor.registry import Histogram
    import threading

    h = Histogram(threading.RLock(), buckets=(1.0,))
    assert h.quantile(0.5) is None
    with pytest.raises(ValueError):
        h.observe(1.0) or h.quantile(0.0)
    # +Inf bucket ranks report the observed max, not an invented bound
    h.observe(100.0)
    assert h.quantile(0.99) == 100.0


def test_histogram_snapshot_carries_p50_p99():
    monitor.reset()
    fam = monitor.histogram("unit_latency_seconds", "t")
    for v in (0.01, 0.02, 0.03, 0.04):
        fam.observe(v)
    snap = monitor.metric_value("unit_latency_seconds")
    assert snap["count"] == 4 and snap["p50"] is not None
    assert 0.01 <= snap["p50"] <= 0.03
    assert snap["p50"] <= snap["p99"] <= 0.04


def test_histogram_prometheus_exposition_conventions():
    """_bucket/_sum/_count lines, cumulative le counts ending at +Inf —
    what a Prometheus scraper of the serving sidecar expects."""
    monitor.reset()
    fam = monitor.histogram("unit_hist_seconds", "t")
    fam.labels(path="run").observe(0.002)
    fam.labels(path="run").observe(0.2)
    text = monitor.get_registry().to_prometheus()
    lines = [ln for ln in text.splitlines() if ln.startswith("unit_hist")]
    buckets = [ln for ln in lines if "_bucket" in ln]
    assert buckets and 'le="+Inf"' in buckets[-1]
    assert buckets[-1].endswith(" 2"), "+Inf bucket holds the total count"
    # cumulative: counts never decrease across the ordered buckets
    counts = [int(float(ln.rsplit(" ", 1)[1])) for ln in buckets]
    assert counts == sorted(counts)
    assert any(ln.startswith("unit_hist_seconds_sum") for ln in lines)
    assert any(ln.startswith("unit_hist_seconds_count") for ln in lines)
    assert "# TYPE unit_hist_seconds histogram" in text
