"""Unique name generator (reference: python/paddle/fluid/unique_name.py)."""
from __future__ import annotations

import contextlib
from collections import defaultdict


class UniqueNameGenerator:
    def __init__(self, prefix: str = ""):
        self.prefix = prefix
        self.ids = defaultdict(int)

    def __call__(self, key: str) -> str:
        tmp = self.ids[key]
        self.ids[key] += 1
        return self.prefix + "_".join([key, str(tmp)])


generator = UniqueNameGenerator()


def generate(key: str) -> str:
    return generator(key)


@contextlib.contextmanager
def guard(new_prefix: str = ""):
    global generator
    old = generator
    generator = UniqueNameGenerator(new_prefix)
    try:
        yield
    finally:
        generator = old


def switch(new_generator=None):
    global generator
    old = generator
    generator = new_generator or UniqueNameGenerator()
    return old
