"""High-level Trainer / Inferencer (reference
python/paddle/contrib/trainer.py:169 Trainer with epoch/step events,
:100 CheckpointConfig, :663 incremental save_checkpoint;
python/paddle/contrib/inferencer.py:31 Inferencer).

The event loop, checkpointing cadence and callbacks mirror the reference;
execution rides the TPU executor (and CompiledProgram when num_devices>1).

On top of the reference shape, the Trainer is the wiring point for the
resilience stack (docs/RESILIENCE.md):

* **recovery walk** (PR 4): ``_load_latest`` resumes from the newest
  checkpoint that verifies, skipping torn serials;
* **divergence restore** (PR 6): ``FLAGS_replica_divergence_policy=
  restore`` rolls back through the same walk mid-run;
* **elastic preemption tolerance** (``resilience.elastic``,
  ``FLAGS_elastic``): a typed ``DeviceLostError`` from the parallel step
  — or a watchdog-diagnosed hang there, the same dead chip seen earlier
  — tears down the failed ``CompiledProgram``, re-forms the mesh on the
  surviving devices, restores from the last VERIFIED serial and
  fast-forwards the data cursor, so training continues at reduced width
  with the SAME global batch (the per-replica slice widens by the
  gradient-accumulation factor). ``BeginEpochEvent`` re-fires for the
  epoch a recovery re-enters — handlers must tolerate replays of
  batches that were never committed;
* **graceful shutdown** (``resilience.graceful``): ``train()`` installs
  SIGTERM handlers for its duration; on a preemption notice the
  in-flight step finishes, a final verified checkpoint (data cursor
  included) is written, and ``train()`` returns with ``.interrupted``
  set so the process can exit 0.

Checkpoints carry a ``data_cursor`` (epoch, batch offset, reader state)
in their meta, and ``train()`` fast-forwards the reader past committed
batches on resume — a resumed run consumes exactly the not-yet-committed
batch sequence, no re-trained and no skipped data (deterministic readers
assumed; seed shuffles via ``reader.shuffle(..., seed=N)``).
"""
from __future__ import annotations

import logging
import os
import time
from typing import Callable, Optional

import numpy as np

from .. import io as io_mod
from .. import monitor as _monitor
from .. import resilience as _resilience
from .. import trace as _trace
from ..executor import CPUPlace, Executor, Scope, scope_guard
from ..framework import Program, program_guard
from ..parallel.compiled_program import CompiledProgram
from ..resilience import elastic as _elastic
from ..resilience import graceful as _graceful

__all__ = ["Trainer", "Inferencer", "CheckpointConfig",
           "BeginEpochEvent", "EndEpochEvent", "BeginStepEvent",
           "EndStepEvent"]

logger = logging.getLogger("paddle_tpu.resilience")


class _EpochRewind(Exception):
    """Internal control flow: a mid-step restore (divergence policy)
    rolled the state lineage back to a checkpoint that carries a data
    cursor — unwind to the epoch loop and re-enter from that cursor so
    the data stream rewinds WITH the state (each batch affects the
    committed lineage exactly once, same contract as the elastic path)."""


class BeginEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent:
    def __init__(self, epoch_id, step_id):
        self.epoch, self.step = epoch_id, step_id
        self.fetch_metrics = True


class EndStepEvent:
    def __init__(self, epoch_id, step_id, metrics):
        self.epoch, self.step, self.metrics = epoch_id, step_id, metrics


class CheckpointConfig:
    """reference contrib/trainer.py:100; ``sharded=True`` selects the
    format_version-2 sharded checkpoint (resilience.distributed): one
    fsynced blob per mesh shard, elastic restore across device counts —
    the format ZeRO-sharded optimizer state needs so a checkpoint never
    forces a full gather."""

    def __init__(self, checkpoint_dir: str, max_num_checkpoints: int = 3,
                 epoch_interval: int = 1, step_interval: int = 10,
                 sharded: bool = False):
        self.checkpoint_dir = checkpoint_dir
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = max(1, epoch_interval)
        self.step_interval = max(1, step_interval)
        self.sharded = bool(sharded)


class Trainer:
    """reference contrib/trainer.py:169: train_func returns the loss var
    (after building the whole model under this trainer's programs).

    ``build_strategy`` (parallel runs) reaches
    ``CompiledProgram.with_data_parallel`` — e.g.
    ``ReduceStrategy.Reduce`` for ZeRO-sharded optimizer state.
    ``elastic_devices_fn`` (optional zero-arg callable) overrides how the
    elastic recovery path enumerates healthy devices — the production
    default is ``jax.devices()`` (a lost chip disappears from the
    enumeration after the runtime restarts); tests and single-host
    simulations inject survivor sets through it."""

    def __init__(self, train_func: Callable, optimizer_func: Callable,
                 place=None, checkpoint_config: Optional[CheckpointConfig]
                 = None, parallel: bool = False, build_strategy=None):
        self.main_program = Program()
        self.startup_program = Program()
        self._ckpt = checkpoint_config
        with program_guard(self.main_program, self.startup_program):
            loss = train_func()
            if isinstance(loss, (list, tuple)):
                loss = loss[0]
            self.loss = loss
            optimizer_func().minimize(loss)
        self.place = place or CPUPlace()
        self.exe = Executor(self.place)
        self.scope = Scope()
        self._parallel = parallel
        self._build_strategy = build_strategy
        self._step = 0
        self._train_mesh = None   # set by train() on the parallel path
        # set by a mid-step divergence restore: the step that just ran was
        # rolled back, so the loop must adopt the checkpoint's counter
        # instead of incrementing past state that no longer exists
        self._restored_step = None
        # elastic recovery state (resilience.elastic, FLAGS_elastic)
        self.elastic_devices_fn: Optional[Callable] = None
        self.elastic_events: list = []   # one dict per rescale, in order
        self.interrupted = False         # graceful shutdown unwound train()
        self._elastic_rescales = 0
        self._healthy_steps = 0
        self._full_dp = None             # dp width train() started with
        self._full_ndev = None
        self._last_global_batch = None   # rows of the most recent batch
        # data cursor: where the NEXT batch comes from (epoch, batch,
        # reader state); checkpointed in meta so resume fast-forwards
        self._cursor = _elastic.DataCursor()
        self._resume_cursor: Optional[_elastic.DataCursor] = None
        with scope_guard(self.scope):
            self.exe.run(self.startup_program)
        if self._ckpt:
            self._load_latest()

    # -- checkpoints -----------------------------------------------------
    def _ckpt_path(self, serial: int) -> str:
        return os.path.join(self._ckpt.checkpoint_dir, f"checkpoint_{serial}")

    def _serials(self):
        """Serials of ``checkpoint_<int>`` DIRECTORIES only, ascending.
        Stray files, torn temp dirs and non-numeric entries in the
        checkpoint dir are ignored (resilience.iter_serials)."""
        return [s for s, _ in
                _resilience.iter_serials(self._ckpt.checkpoint_dir)]

    def _ckpt_mesh(self):
        """Mesh handed to sharded saves: the training mesh when parallel,
        else every local device as a dp axis (a 1-device host writes a
        valid single-shard v2 checkpoint)."""
        if not (self._ckpt and self._ckpt.sharded):
            return None
        if self._train_mesh is not None:
            return self._train_mesh
        import jax

        return {"dp": max(1, jax.device_count())}

    def _save_checkpoint(self):
        serials = self._serials()
        serial = (serials[-1] + 1) if serials else 0
        with scope_guard(self.scope), \
                _trace.span("trainer.checkpoint", serial=serial,
                            step=self._step):
            io_mod.save_checkpoint(self.exe, self._ckpt_path(serial),
                                   self.main_program,
                                   meta={"step": self._step,
                                         "data_cursor":
                                             self._cursor.to_dict()},
                                   mesh=self._ckpt_mesh())
        if _monitor.enabled():
            _monitor.counter("trainer_checkpoints_total",
                            "checkpoints written by contrib.Trainer").inc()
        # rotate (reference keeps max_num_checkpoints); never the serial
        # just written, even with max_num_checkpoints=1 or a racing writer
        # that renumbered the listing under us. <=0 keeps full history
        # (the pre-resilience [:-0] behavior, kept on purpose)
        keep = int(self._ckpt.max_num_checkpoints)
        if keep <= 0:
            return
        for old in self._serials()[:-keep]:
            if old == serial:
                continue
            import shutil

            shutil.rmtree(self._ckpt_path(old), ignore_errors=True)

    def _load_latest(self):
        """Resume from the newest checkpoint that passes verification,
        walking serials newest -> oldest past torn/corrupt ones (each skip
        counts on ``trainer_ckpt_fallback_total`` and logs its PT6xx
        diagnostic). An empty or garbage-only checkpoint dir starts fresh
        at step 0 instead of crashing."""
        with scope_guard(self.scope):
            meta, serial, skipped = _resilience.load_latest_checkpoint(
                self.exe, self._ckpt.checkpoint_dir,
                main_program=self.main_program, scope=self.scope)
        if meta is None:
            self._step = 0
            return None
        self._step = int(meta.get("step", 0))
        self._resume_cursor = _elastic.DataCursor.from_dict(
            meta.get("data_cursor"))
        return serial

    def _recover_from_checkpoint(self) -> bool:
        """Divergence-restore hook (FLAGS_replica_divergence_policy=
        restore): reload the newest VERIFIED checkpoint through the PR 4
        recovery walk WITHOUT zeroing the step counter on failure —
        a divergence with nothing restorable must escalate, not silently
        restart training at step 0."""
        with scope_guard(self.scope):
            # allow_legacy=False: rolling diverged replicas back onto an
            # UNVERIFIED pre-manifest checkpoint would trade one kind of
            # corrupt state for another — escalate to raise instead
            meta, serial, _skipped = _resilience.load_latest_checkpoint(
                self.exe, self._ckpt.checkpoint_dir,
                main_program=self.main_program, scope=self.scope,
                allow_legacy=False)
        if meta is None:
            return False
        self._step = int(meta.get("step", 0))
        self._restored_step = self._step
        # checkpoints with a data cursor rewind the DATA with the state
        # (the step loop unwinds via _EpochRewind); legacy checkpoints
        # without one keep the old continue-forward semantics
        self._resume_cursor = _elastic.DataCursor.from_dict(
            meta.get("data_cursor"))
        return True

    # -- elastic recovery (resilience.elastic) ---------------------------
    def _probe_devices(self, err=None) -> list:
        """The healthy device set: the error's own attribution when the
        runtime provided one, else ``elastic_devices_fn`` (tests /
        simulations), else ``jax.devices()``."""
        if err is not None and getattr(err, "survivors", None):
            return list(err.survivors)
        if self.elastic_devices_fn is not None:
            return list(self.elastic_devices_fn())
        import jax

        return list(jax.devices())

    def _elastic_enabled(self) -> bool:
        from ..flags import flag

        return bool(flag("elastic")) and self._parallel \
            and self._ckpt is not None

    def _unshard_stale_state(self, mesh) -> None:
        """Pull scope values still committed to a mesh OTHER than
        ``mesh`` back to host: jit refuses to reshard a committed array
        whose mesh differs from its declared in_sharding, so after a
        rescale everything the restore did not rewrite must become an
        uncommitted host array the next dispatch places itself. A value
        that cannot be read (its device really died) is left for the
        checkpoint restore / next-dispatch diagnostics."""
        import jax

        for name in list(self.scope.vars):
            v = self.scope.find_var(name)
            if not isinstance(v, jax.Array):
                continue
            vmesh = getattr(getattr(v, "sharding", None), "mesh", None)
            if vmesh is None or vmesh == mesh:
                continue
            try:
                self.scope.set_var(name, np.array(v))
            except Exception:
                logger.warning(
                    "elastic: could not host-copy '%s' off the old mesh "
                    "(device really gone?) — the checkpoint restore "
                    "must cover it", name)

    def _record_rescale(self, old_axes, new_axes, direction, serial,
                        cause, duration_s) -> dict:
        """One audit event + the monitor emission every rescale makes
        (recovery is never silent): ``elastic_rescales_total`` with the
        old/new topology and the grad-accum gauge preserving the global
        batch."""
        new_dp = int(new_axes.get("dp", 1))
        accum = _elastic.grad_accum_steps(
            self._full_dp or int(old_axes.get("dp", 1)), new_dp)
        event = {"old": _elastic.format_axes(old_axes),
                 "new": _elastic.format_axes(new_axes),
                 "direction": direction, "serial": serial,
                 "step": self._step, "cause": cause,
                 "grad_accum_steps": accum, "duration_s": duration_s}
        self.elastic_events.append(event)
        if _monitor.enabled():
            _monitor.counter(
                "elastic_rescales_total",
                "elastic mesh rescales by old/new topology").labels(
                old=event["old"], new=event["new"],
                direction=direction).inc()
            _monitor.gauge(
                "elastic_grad_accum_steps",
                "per-replica gradient-accumulation factor preserving "
                "the global batch at reduced width").set(accum)
        return event

    def _elastic_recover(self, err, prog) -> CompiledProgram:
        """Device-loss recovery: tear down the failed CompiledProgram,
        re-form the mesh on the surviving devices, restore from the last
        VERIFIED serial and queue the data-cursor fast-forward. Raises
        (typed) when elastic is off, the topology cannot be satisfied
        (PT610/PT611), the rescale budget is spent (PT612) or nothing
        restorable exists (PT614) — recovery is never silent either way.
        The whole episode is one trace (``trainer.elastic_recover``) so
        the flight recorder shows rescale + restore as spans, not logs."""
        recover_span = _trace.root_span(
            "trainer.elastic_recover", cause=type(err).__name__,
            step=self._step)
        recover_span.__enter__()
        try:
            out = self._elastic_recover_body(err, prog)
        except BaseException as e:
            recover_span.set_attribute("outcome", "failed")
            recover_span.__exit__(type(e), e, None)
            raise
        recover_span.set_attribute("outcome", "recovered")
        recover_span.__exit__(None, None, None)
        return out

    def _elastic_recover_body(self, err, prog) -> CompiledProgram:
        from ..flags import flag
        from ..parallel.sharding import make_mesh
        from ..resilience.distributed import WatchdogTimeout, mesh_axes

        if isinstance(err, WatchdogTimeout):
            # only a parallel-step hang escalates here: on a dead device
            # the wedged collective is usually diagnosed by the watchdog
            # before the runtime reports the loss. Other sections
            # (compile, single-device step) keep their typed failure.
            if not (self._elastic_enabled()
                    and err.section == "parallel_step"):
                raise err
            _elastic.record_device_lost("watchdog")
        elif not self._elastic_enabled():
            raise err
        if not isinstance(prog, CompiledProgram) or prog._mesh is None:
            raise err
        t0 = time.perf_counter()
        self._elastic_rescales += 1
        budget = int(flag("elastic_max_rescales"))
        if budget and self._elastic_rescales > budget:
            raise _elastic.ElasticRescaleError(
                "PT612", f"{self._elastic_rescales - 1} rescale(s) "
                         f"already performed this train() call "
                         f"(FLAGS_elastic_max_rescales={budget})") from err
        old_axes = mesh_axes(prog._mesh)
        old_dp = int(old_axes.get("dp", 1))
        devices = self._probe_devices(err)
        # the non-dp axes are load-bearing and the global batch must
        # divide the surviving dp width; PT610/PT611/PT613 refuse loudly
        # when the survivors cannot satisfy them
        new_axes = _elastic.plan_rescale(
            old_axes, len(devices), global_batch=self._last_global_batch)
        survivors = _elastic.survivor_devices(devices, new_axes)
        prog.rescale(make_mesh(new_axes, survivors))
        self._train_mesh = prog._mesh
        # restore from the last VERIFIED serial (never legacy: rescaling
        # onto unverified bytes would launder corruption into the new
        # topology), then fast-forward the data cursor on re-entry
        with scope_guard(self.scope):
            meta, serial, _skipped = _resilience.load_latest_checkpoint(
                self.exe, self._ckpt.checkpoint_dir,
                main_program=self.main_program, scope=self.scope,
                allow_legacy=False)
        if meta is None:
            raise _elastic.ElasticRescaleError(
                "PT614", f"device loss at '{getattr(err, 'site', '?')}' "
                         f"but no serial in "
                         f"'{self._ckpt.checkpoint_dir}' verifies") \
                from err
        # whatever the restore did not rewrite must leave the old mesh
        self._unshard_stale_state(prog._mesh)
        self._step = int(meta.get("step", 0))
        cur = _elastic.DataCursor.from_dict(meta.get("data_cursor"))
        if cur is None:
            # legacy checkpoint without a cursor (pre-elastic writer):
            # keep the historic continue-forward data semantics — the
            # same contract as the divergence path — instead of
            # silently re-consuming every committed batch from zero
            logger.warning(
                "elastic: restored checkpoint_%s carries no data_cursor "
                "(pre-elastic writer) — the data stream continues "
                "forward from the pre-loss position; save once to "
                "upgrade the checkpoint format", serial)
            cur = _elastic.DataCursor(epoch=self._cursor.epoch,
                                      batch=self._cursor.batch)
        self._resume_cursor = cur
        self._healthy_steps = 0
        new_dp = int(new_axes.get("dp", 1))
        # 'same' = restart in place: the survivor probe reported no
        # shrink (a reset chip recovered, or — the production default
        # jax.devices() — the runtime cannot re-enumerate in-process).
        # Legitimate once for a recovered reset; a dead chip loops here
        # and the PT612 budget is the bound that turns it into a typed
        # outage instead of an infinite teardown/restore cycle.
        direction = ("down" if new_dp < old_dp
                     else "up" if new_dp > old_dp else "same")
        event = self._record_rescale(
            old_axes, new_axes, direction, serial, type(err).__name__,
            time.perf_counter() - t0)
        if direction == "same":
            logger.warning(
                "elastic: survivor probe reported no capacity change "
                "(%s) — restarting in place; repeated losses on this "
                "topology exhaust FLAGS_elastic_max_rescales (PT612). "
                "Provide elastic_devices_fn (or error survivors) for a "
                "real downscale.", event["old"])
        if _monitor.enabled():
            _monitor.counter(
                "elastic_restores_total",
                "elastic recoveries that restored a verified "
                "checkpoint").inc()
        logger.warning(
            "elastic: %s -> rescaled %s -> %s (%d surviving device(s)), "
            "restored from checkpoint_%s at step %d, global batch "
            "preserved via grad-accum x%d (%.2fs)",
            type(err).__name__, event["old"], event["new"], len(devices),
            serial, self._step, event["grad_accum_steps"],
            event["duration_s"])
        return prog

    def _maybe_upscale(self, prog) -> None:
        """Capacity-return probe (FLAGS_elastic_upscale_after_steps):
        after N consecutive healthy steps at reduced width, re-enumerate
        devices and rescale BACK UP — no state restore, the live state
        re-shards onto the bigger mesh at the next dispatch. Capped at
        the width train() started with (the global batch is known to
        divide it)."""
        from ..flags import flag
        from ..parallel.sharding import make_mesh
        from ..resilience.distributed import mesh_axes

        n = int(flag("elastic_upscale_after_steps"))
        if not n or not self._elastic_enabled() \
                or not isinstance(prog, CompiledProgram) \
                or prog._mesh is None or self._full_ndev is None:
            return
        if self.elastic_devices_fn is None:
            # the default jax.devices() enumeration cannot reflect a
            # lost chip in-process, so an upscale decided from it could
            # re-adopt the dead device and oscillate the PT612 budget
            # away — capacity-return probing needs an authoritative
            # prober (elastic_devices_fn)
            if not getattr(self, "_warned_upscale_probe", False):
                self._warned_upscale_probe = True
                logger.warning(
                    "elastic: FLAGS_elastic_upscale_after_steps is set "
                    "but no elastic_devices_fn is installed — skipping "
                    "capacity-return probes (the default device "
                    "enumeration cannot be trusted after a loss)")
            return
        current = int(prog._mesh.devices.size)
        if current >= self._full_ndev:
            return
        self._healthy_steps += 1
        if self._healthy_steps < n:
            return
        self._healthy_steps = 0
        devices = self._probe_devices()
        if len(devices) <= current:
            return
        old_axes = mesh_axes(prog._mesh)
        t0 = time.perf_counter()
        try:
            new_axes = _elastic.plan_rescale(
                old_axes, min(len(devices), self._full_ndev),
                global_batch=self._last_global_batch)
        except _elastic.ElasticRescaleError:
            return   # probe only; an unsatisfiable upscale is not fatal
        if new_axes == old_axes:
            return
        survivors = _elastic.survivor_devices(devices, new_axes)
        prog.rescale(make_mesh(new_axes, survivors))
        self._train_mesh = prog._mesh
        # no restore on the way up — but the live state is committed to
        # the smaller mesh and must re-shard at the next dispatch
        self._unshard_stale_state(prog._mesh)
        event = self._record_rescale(old_axes, new_axes, "up", None,
                                     "capacity_returned",
                                     time.perf_counter() - t0)
        logger.warning(
            "elastic: capacity returned — rescaled %s -> %s without "
            "restore (live state re-shards at the next dispatch)",
            event["old"], event["new"])

    # -- the loop --------------------------------------------------------
    def train(self, num_epochs: int, event_handler: Callable,
              reader: Callable, feed_order):
        from ..data_feeder import DataFeeder

        feeder = DataFeeder(feed_list=list(feed_order),
                            program=self.main_program)
        prog = self.main_program
        if self._parallel:
            prog = CompiledProgram(self.main_program).with_data_parallel(
                loss_name=self.loss.name,
                build_strategy=self._build_strategy)
            self._train_mesh = prog._mesh
            self._full_dp = int(prog._mesh.shape.get("dp", 1))
            self._full_ndev = int(prog._mesh.devices.size)
        from ..resilience import distributed as _dist

        # the rescale budget and upscale streak are per train() call
        # (FLAGS_elastic_max_rescales documents it that way); the
        # elastic_events audit list stays cumulative across calls
        self._elastic_rescales = 0
        self._healthy_steps = 0
        prev_recovery = _dist._recovery
        if self._ckpt:
            _dist.set_divergence_recovery(self._recover_from_checkpoint)
        # SIGTERM/preemption notice -> finish the step, checkpoint, exit 0
        # (resilience.graceful). Scoped to this call: handlers restore on
        # exit; non-main-thread callers fall back to event polling only.
        installed = _graceful.install_signal_handlers()
        self.interrupted = False
        try:
            self._train_loop(num_epochs, event_handler, feeder, reader,
                             prog)
        finally:
            # scoped to this loop: a stale trainer's recovery walk must
            # never swallow a later, unrelated run's divergence
            _dist.set_divergence_recovery(prev_recovery)
            if installed:
                _graceful.uninstall_signal_handlers()

    def _consume_resume_cursor(self, reader):
        """(epoch, skip) for re-entering the loop at the pending resume
        cursor — shared by initial resume, elastic recovery and the
        divergence rewind so all three paths keep identical semantics."""
        cur = self._resume_cursor or _elastic.DataCursor()
        self._resume_cursor = None
        cur.apply_to_reader(reader)
        return cur.epoch, cur.batch

    def _train_loop(self, num_epochs, event_handler, feeder, reader, prog):
        from ..resilience.distributed import WatchdogTimeout

        epoch, skip = 0, 0
        if self._resume_cursor is not None:
            epoch, skip = self._consume_resume_cursor(reader)
        with scope_guard(self.scope):
            while epoch < num_epochs:
                try:
                    stopped = self._run_epoch(epoch, event_handler,
                                              feeder, reader, prog, skip)
                except (_elastic.DeviceLostError, WatchdogTimeout) as e:
                    # detection already dumped the flight recorder (the
                    # device-loss classifier / the watchdog expiry); the
                    # recovery episode itself is traced below
                    prog = self._elastic_recover(e, prog)
                    epoch, skip = self._consume_resume_cursor(reader)
                    continue   # re-enter from the restored cursor
                except _EpochRewind:
                    # a mid-step divergence restore rolled the lineage
                    # back: rewind the data stream with it
                    epoch, skip = self._consume_resume_cursor(reader)
                    continue
                if stopped:
                    return     # graceful shutdown: checkpointed, exit 0
                skip = 0
                epoch += 1

    def _run_epoch(self, epoch, event_handler, feeder, reader, prog,
                   skip) -> bool:
        """One epoch; ``skip`` batches are fast-forwarded (deterministic
        resume: those batches are already committed in the restored
        state). Returns True when a graceful shutdown unwound the loop."""
        event_handler(BeginEpochEvent(epoch))
        for step, batch in enumerate(reader()):
            if step < skip:
                # resume fast-forward: the restored state already
                # contains these batches' effect — consume-and-drop so
                # the NEXT batch is exactly the first uncommitted one
                if _monitor.enabled():
                    _monitor.counter(
                        "elastic_data_fastforward_batches_total",
                        "batches skipped by the data-cursor "
                        "fast-forward on resume").inc()
                continue
            begin = BeginStepEvent(epoch, step)
            event_handler(begin)
            fetches = [self.loss.name] if begin.fetch_metrics else []
            # one trace per training step (root span; data fetch,
            # executor dispatch, divergence checks and checkpoint writes
            # land as children). The trace covers everything from feed
            # build through the post-step checkpoint decision, so a
            # device loss or watchdog hang leaves a complete error-status
            # step trace in the flight recorder.
            step_span = _trace.root_span("trainer.step", epoch=epoch,
                                         step=step,
                                         global_step=self._step)
            step_span.__enter__()
            step_err: Optional[BaseException] = None
            t0 = time.perf_counter()
            try:
                # the batch the elastic planner must keep divisible
                # across a surviving dp width (PT613 refusal)
                try:
                    self._last_global_batch = len(batch)
                except TypeError:
                    pass
                with _trace.span("trainer.data"):
                    fd = feeder.feed(batch)
                # belt and braces for fully-async dispatch: a real device
                # loss can surface only HERE, at the metric materialization
                # — classify it typed so the elastic recovery still fires
                with _elastic.device_loss_classification("parallel_step"):
                    vals = self.exe.run(prog, feed=fd, fetch_list=fetches)
                    metrics = [float(np.asarray(v).reshape(-1)[0])
                               for v in vals]
            except BaseException as e:
                step_err = e
                raise
            finally:
                if step_err is not None:
                    step_span.set_attribute("outcome",
                                            type(step_err).__name__)
                    step_span.__exit__(type(step_err), step_err, None)
            post_err = None
            try:
                if self._restored_step is not None:
                    # a divergence restore rolled this step back mid-
                    # run: the scope holds the checkpoint's state, so
                    # the counter adopts the checkpoint's step instead
                    # of advancing past state that no longer exists
                    self._step = self._restored_step
                    self._restored_step = None
                    if self._resume_cursor is not None:
                        # the checkpoint carries a data cursor: rewind the
                        # data stream with the state (no EndStepEvent — the
                        # step that just ran was rolled back)
                        step_span.set_attribute("outcome",
                                                "divergence_rewind")
                        raise _EpochRewind()
                    # legacy checkpoint without a cursor: keep the historic
                    # continue-forward semantics
                else:
                    self._step += 1
                # the committed data position: the NEXT batch is step+1 of
                # this epoch (checkpointed with the state as data_cursor)
                self._cursor = _elastic.DataCursor.capture(epoch, step + 1,
                                                           reader)
                if _monitor.enabled():
                    _monitor.counter(
                        "trainer_steps_total",
                        "steps run by contrib.Trainer.train").inc()
                    _monitor.histogram(
                        "trainer_step_seconds",
                        "Trainer step wall time (feed build + executor "
                        "dispatch + metric fetch)").observe(
                        time.perf_counter() - t0)
                    if metrics:
                        _monitor.gauge(
                            "trainer_last_loss",
                            "most recent fetched loss").set(metrics[0])
                event_handler(EndStepEvent(epoch, step, metrics))
                self._maybe_upscale(prog)
                saved_this_step = False
                if self._ckpt and self._step % \
                        self._ckpt.step_interval == 0:
                    self._save_checkpoint()
                    saved_this_step = True
                if _graceful.shutdown_requested():
                    # preemption notice: the in-flight step completed
                    # above; write the final verified checkpoint (data
                    # cursor included) and unwind so the process can exit
                    # 0 — but never a byte-identical duplicate of the
                    # interval save that just ran (the grace window is
                    # for exiting)
                    if self._ckpt and not saved_this_step:
                        self._save_checkpoint()
                    self.interrupted = True
                    if _monitor.enabled():
                        _monitor.counter(
                            "trainer_graceful_exits_total",
                            "train() calls unwound by a graceful-shutdown "
                            "request after a final checkpoint").inc()
                    logger.warning(
                        "graceful shutdown: step %d checkpointed, train() "
                        "returning cleanly", self._step)
                    step_span.set_attribute("outcome", "graceful_exit")
                    return True
            except BaseException as e:
                post_err = e
                raise
            finally:
                # close the step trace on every unwind; the dispatch-
                # failure path closed it in the except block above. A
                # post-dispatch failure (event handler, checkpoint write,
                # upscale) must NOT be mislabeled 'ok' — the flight
                # recorder consulted for that incident would lie.
                # _EpochRewind is control flow, not an error: its span
                # closes clean with the 'divergence_rewind' outcome.
                if post_err is not None \
                        and not isinstance(post_err, _EpochRewind):
                    if step_span.attrs.get("outcome") is None:
                        step_span.set_attribute("outcome",
                                                type(post_err).__name__)
                    step_span.__exit__(type(post_err), post_err, None)
                else:
                    if step_span.attrs.get("outcome") is None \
                            and not step_span.error:
                        step_span.set_attribute("outcome", "ok")
                    step_span.__exit__(None, None, None)
        event_handler(EndEpochEvent(epoch))
        # next batch after a completed epoch is the next epoch's first
        self._cursor = _elastic.DataCursor.capture(epoch + 1, 0, reader)
        if self._ckpt and (epoch + 1) % \
                self._ckpt.epoch_interval == 0:
            self._save_checkpoint()
        return False

    def save_params(self, dirname: str):
        with scope_guard(self.scope):
            io_mod.save_params(self.exe, dirname, self.main_program)

    def save_inference_model(self, dirname, feeded_var_names, target_vars):
        with scope_guard(self.scope):
            io_mod.save_inference_model(dirname, feeded_var_names,
                                        target_vars, self.exe,
                                        main_program=self.main_program)

    def stop(self):
        self.exe.close()


class Inferencer:
    """reference contrib/inferencer.py:31: infer_func rebuilds the forward
    under fresh programs; params load from ``param_path``."""

    def __init__(self, infer_func: Callable, param_path: str, place=None,
                 parallel: bool = False):
        self.main_program = Program()
        self.startup_program = Program()
        with program_guard(self.main_program, self.startup_program):
            self.predict_var = infer_func()
        self.exe = Executor(place or CPUPlace())
        self.scope = Scope()
        with scope_guard(self.scope):
            self.exe.run(self.startup_program)
            io_mod.load_params(self.exe, param_path, self.main_program)

    def infer(self, inputs: dict):
        with scope_guard(self.scope):
            (out,) = self.exe.run(self.main_program, feed=inputs,
                                  fetch_list=[self.predict_var.name])
        return out
