"""High-level Trainer / Inferencer (reference
python/paddle/contrib/trainer.py:169 Trainer with epoch/step events,
:100 CheckpointConfig, :663 incremental save_checkpoint;
python/paddle/contrib/inferencer.py:31 Inferencer).

The event loop, checkpointing cadence and callbacks mirror the reference;
execution rides the TPU executor (and CompiledProgram when num_devices>1).
"""
from __future__ import annotations

import os
import time
from typing import Callable, Optional

import numpy as np

from .. import io as io_mod
from .. import monitor as _monitor
from .. import resilience as _resilience
from ..executor import CPUPlace, Executor, Scope, scope_guard
from ..framework import Program, program_guard
from ..parallel.compiled_program import CompiledProgram

__all__ = ["Trainer", "Inferencer", "CheckpointConfig",
           "BeginEpochEvent", "EndEpochEvent", "BeginStepEvent",
           "EndStepEvent"]


class BeginEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent:
    def __init__(self, epoch_id, step_id):
        self.epoch, self.step = epoch_id, step_id
        self.fetch_metrics = True


class EndStepEvent:
    def __init__(self, epoch_id, step_id, metrics):
        self.epoch, self.step, self.metrics = epoch_id, step_id, metrics


class CheckpointConfig:
    """reference contrib/trainer.py:100; ``sharded=True`` selects the
    format_version-2 sharded checkpoint (resilience.distributed): one
    fsynced blob per mesh shard, elastic restore across device counts —
    the format ZeRO-sharded optimizer state needs so a checkpoint never
    forces a full gather."""

    def __init__(self, checkpoint_dir: str, max_num_checkpoints: int = 3,
                 epoch_interval: int = 1, step_interval: int = 10,
                 sharded: bool = False):
        self.checkpoint_dir = checkpoint_dir
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = max(1, epoch_interval)
        self.step_interval = max(1, step_interval)
        self.sharded = bool(sharded)


class Trainer:
    """reference contrib/trainer.py:169: train_func returns the loss var
    (after building the whole model under this trainer's programs)."""

    def __init__(self, train_func: Callable, optimizer_func: Callable,
                 place=None, checkpoint_config: Optional[CheckpointConfig]
                 = None, parallel: bool = False):
        self.main_program = Program()
        self.startup_program = Program()
        self._ckpt = checkpoint_config
        with program_guard(self.main_program, self.startup_program):
            loss = train_func()
            if isinstance(loss, (list, tuple)):
                loss = loss[0]
            self.loss = loss
            optimizer_func().minimize(loss)
        self.place = place or CPUPlace()
        self.exe = Executor(self.place)
        self.scope = Scope()
        self._parallel = parallel
        self._step = 0
        self._train_mesh = None   # set by train() on the parallel path
        # set by a mid-step divergence restore: the step that just ran was
        # rolled back, so the loop must adopt the checkpoint's counter
        # instead of incrementing past state that no longer exists
        self._restored_step = None
        with scope_guard(self.scope):
            self.exe.run(self.startup_program)
        if self._ckpt:
            self._load_latest()

    # -- checkpoints -----------------------------------------------------
    def _ckpt_path(self, serial: int) -> str:
        return os.path.join(self._ckpt.checkpoint_dir, f"checkpoint_{serial}")

    def _serials(self):
        """Serials of ``checkpoint_<int>`` DIRECTORIES only, ascending.
        Stray files, torn temp dirs and non-numeric entries in the
        checkpoint dir are ignored (resilience.iter_serials)."""
        return [s for s, _ in
                _resilience.iter_serials(self._ckpt.checkpoint_dir)]

    def _ckpt_mesh(self):
        """Mesh handed to sharded saves: the training mesh when parallel,
        else every local device as a dp axis (a 1-device host writes a
        valid single-shard v2 checkpoint)."""
        if not (self._ckpt and self._ckpt.sharded):
            return None
        if self._train_mesh is not None:
            return self._train_mesh
        import jax

        return {"dp": max(1, jax.device_count())}

    def _save_checkpoint(self):
        serials = self._serials()
        serial = (serials[-1] + 1) if serials else 0
        with scope_guard(self.scope):
            io_mod.save_checkpoint(self.exe, self._ckpt_path(serial),
                                   self.main_program,
                                   meta={"step": self._step},
                                   mesh=self._ckpt_mesh())
        if _monitor.enabled():
            _monitor.counter("trainer_checkpoints_total",
                            "checkpoints written by contrib.Trainer").inc()
        # rotate (reference keeps max_num_checkpoints); never the serial
        # just written, even with max_num_checkpoints=1 or a racing writer
        # that renumbered the listing under us. <=0 keeps full history
        # (the pre-resilience [:-0] behavior, kept on purpose)
        keep = int(self._ckpt.max_num_checkpoints)
        if keep <= 0:
            return
        for old in self._serials()[:-keep]:
            if old == serial:
                continue
            import shutil

            shutil.rmtree(self._ckpt_path(old), ignore_errors=True)

    def _load_latest(self):
        """Resume from the newest checkpoint that passes verification,
        walking serials newest -> oldest past torn/corrupt ones (each skip
        counts on ``trainer_ckpt_fallback_total`` and logs its PT6xx
        diagnostic). An empty or garbage-only checkpoint dir starts fresh
        at step 0 instead of crashing."""
        with scope_guard(self.scope):
            meta, serial, skipped = _resilience.load_latest_checkpoint(
                self.exe, self._ckpt.checkpoint_dir,
                main_program=self.main_program, scope=self.scope)
        if meta is None:
            self._step = 0
            return None
        self._step = int(meta.get("step", 0))
        return serial

    def _recover_from_checkpoint(self) -> bool:
        """Divergence-restore hook (FLAGS_replica_divergence_policy=
        restore): reload the newest VERIFIED checkpoint through the PR 4
        recovery walk WITHOUT zeroing the step counter on failure —
        a divergence with nothing restorable must escalate, not silently
        restart training at step 0."""
        with scope_guard(self.scope):
            # allow_legacy=False: rolling diverged replicas back onto an
            # UNVERIFIED pre-manifest checkpoint would trade one kind of
            # corrupt state for another — escalate to raise instead
            meta, serial, _skipped = _resilience.load_latest_checkpoint(
                self.exe, self._ckpt.checkpoint_dir,
                main_program=self.main_program, scope=self.scope,
                allow_legacy=False)
        if meta is None:
            return False
        self._step = int(meta.get("step", 0))
        self._restored_step = self._step
        return True

    # -- the loop --------------------------------------------------------
    def train(self, num_epochs: int, event_handler: Callable,
              reader: Callable, feed_order):
        from ..data_feeder import DataFeeder

        feeder = DataFeeder(feed_list=list(feed_order),
                            program=self.main_program)
        prog = self.main_program
        if self._parallel:
            prog = CompiledProgram(self.main_program).with_data_parallel(
                loss_name=self.loss.name)
            self._train_mesh = prog._mesh
        from ..resilience import distributed as _dist

        prev_recovery = _dist._recovery
        if self._ckpt:
            _dist.set_divergence_recovery(self._recover_from_checkpoint)
        try:
            self._train_loop(num_epochs, event_handler, feeder, reader,
                             prog)
        finally:
            # scoped to this loop: a stale trainer's recovery walk must
            # never swallow a later, unrelated run's divergence
            _dist.set_divergence_recovery(prev_recovery)

    def _train_loop(self, num_epochs, event_handler, feeder, reader, prog):
        with scope_guard(self.scope):
            for epoch in range(num_epochs):
                event_handler(BeginEpochEvent(epoch))
                for step, batch in enumerate(reader()):
                    begin = BeginStepEvent(epoch, step)
                    event_handler(begin)
                    fetches = [self.loss.name] if begin.fetch_metrics else []
                    t0 = time.perf_counter()
                    vals = self.exe.run(prog, feed=feeder.feed(batch),
                                        fetch_list=fetches)
                    metrics = [float(np.asarray(v).reshape(-1)[0])
                               for v in vals]
                    if self._restored_step is not None:
                        # a divergence restore rolled this step back mid-
                        # run: the scope holds the checkpoint's state, so
                        # the counter adopts the checkpoint's step instead
                        # of advancing past state that no longer exists
                        self._step = self._restored_step
                        self._restored_step = None
                    else:
                        self._step += 1
                    if _monitor.enabled():
                        _monitor.counter(
                            "trainer_steps_total",
                            "steps run by contrib.Trainer.train").inc()
                        _monitor.histogram(
                            "trainer_step_seconds",
                            "Trainer step wall time (feed build + executor "
                            "dispatch + metric fetch)").observe(
                            time.perf_counter() - t0)
                        if metrics:
                            _monitor.gauge(
                                "trainer_last_loss",
                                "most recent fetched loss").set(metrics[0])
                    event_handler(EndStepEvent(epoch, step, metrics))
                    if self._ckpt and self._step % \
                            self._ckpt.step_interval == 0:
                        self._save_checkpoint()
                event_handler(EndEpochEvent(epoch))
                if self._ckpt and (epoch + 1) % \
                        self._ckpt.epoch_interval == 0:
                    self._save_checkpoint()

    def save_params(self, dirname: str):
        with scope_guard(self.scope):
            io_mod.save_params(self.exe, dirname, self.main_program)

    def save_inference_model(self, dirname, feeded_var_names, target_vars):
        with scope_guard(self.scope):
            io_mod.save_inference_model(dirname, feeded_var_names,
                                        target_vars, self.exe,
                                        main_program=self.main_program)

    def stop(self):
        self.exe.close()


class Inferencer:
    """reference contrib/inferencer.py:31: infer_func rebuilds the forward
    under fresh programs; params load from ``param_path``."""

    def __init__(self, infer_func: Callable, param_path: str, place=None,
                 parallel: bool = False):
        self.main_program = Program()
        self.startup_program = Program()
        with program_guard(self.main_program, self.startup_program):
            self.predict_var = infer_func()
        self.exe = Executor(place or CPUPlace())
        self.scope = Scope()
        with scope_guard(self.scope):
            self.exe.run(self.startup_program)
            io_mod.load_params(self.exe, param_path, self.main_program)

    def infer(self, inputs: dict):
        with scope_guard(self.scope):
            (out,) = self.exe.run(self.main_program, feed=inputs,
                                  fetch_list=[self.predict_var.name])
        return out
