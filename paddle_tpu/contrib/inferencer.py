"""reference python/paddle/contrib/inferencer.py — re-export; the class
lives beside Trainer in trainer.py."""
from .trainer import Inferencer  # noqa: F401
