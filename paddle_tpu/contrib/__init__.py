"""fluid.contrib namespace (reference: python/paddle/fluid/contrib/)."""
from . import mixed_precision  # noqa: F401
from . import slim  # noqa: F401
from .trainer import (CheckpointConfig, Trainer,  # noqa: F401
                      BeginEpochEvent, BeginStepEvent, EndEpochEvent,
                      EndStepEvent)
from .inferencer import Inferencer  # noqa: F401
