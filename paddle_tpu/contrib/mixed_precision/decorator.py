"""AMP decorator: wrap an optimizer so training runs in bf16 with fp32
master weights.

Reference: python/paddle/fluid/contrib/mixed_precision/decorator.py:27
(OptimizerWithMixedPrecision: rewrite program to fp16 via cast insertion,
scale loss, check/unscale grads, keep fp32 master weights). TPU-native
differences:

* No program rewrite — ``Program._amp_policy`` makes the LOWERING cast
  white-list op inputs to bf16 (see lowering.AmpPolicy). Parameters and
  optimizer state never leave fp32, so "master weights" need no twin vars.
* Loss scaling defaults OFF: bf16 has fp32's exponent range, so underflow
  scaling is unnecessary. The static/dynamic loss-scaling machinery is kept
  for fp16-compat API parity (check_finite_and_unscale /
  update_loss_scaling ops) and can be enabled with the reference arguments.
"""
from __future__ import annotations

from typing import Optional

from ... import unique_name
from ...framework import (Variable, default_main_program,
                          default_startup_program, program_guard)
from ...lowering import AmpPolicy
from .fp16_lists import AutoMixedPrecisionLists

__all__ = ["decorate", "decorate_program", "OptimizerWithMixedPrecision"]


def decorate_program(program, amp_lists=None, compute_dtype="bfloat16"):
    """Install the bf16 compute policy on a program directly — the
    inference-side entry (reference float16_transpiler.py rewrote inference
    programs to fp16; here it is one attribute). Returns the program."""
    lists = amp_lists or AutoMixedPrecisionLists()
    program._amp_policy = AmpPolicy(lists.white_list, lists.black_list,
                                    compute_dtype)
    program._bump_version()
    return program


def _create_persistable_scalar(name_hint, dtype, init_value):
    name = unique_name.generate(name_hint)
    main_block = default_main_program().global_block
    var = main_block.create_var(name=name, shape=(1,), dtype=dtype,
                                persistable=True, stop_gradient=True)
    startup = default_startup_program().global_block
    startup.create_var(name=name, shape=(1,), dtype=dtype, persistable=True)
    startup.append_op("fill_constant", outputs={"Out": name},
                      attrs={"shape": [1], "dtype": dtype,
                             "value": float(init_value)})
    return var


class OptimizerWithMixedPrecision:
    """reference decorator.py:27. Drop-in optimizer wrapper."""

    def __init__(self, optimizer, amp_lists: AutoMixedPrecisionLists,
                 init_loss_scaling: float, use_dynamic_loss_scaling: bool,
                 incr_every_n_steps: int, decr_every_n_nan_or_inf: int,
                 incr_ratio: float, decr_ratio: float,
                 compute_dtype: str = "bfloat16"):
        self._optimizer = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._init_loss_scaling = float(init_loss_scaling)
        self._use_dynamic_loss_scaling = use_dynamic_loss_scaling
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._compute_dtype = compute_dtype
        self._loss_scaling: Optional[Variable] = None
        self._found_inf: Optional[Variable] = None

    def get_loss_scaling(self) -> Optional[Variable]:
        return self._loss_scaling

    @property
    def _needs_scaling(self) -> bool:
        return self._use_dynamic_loss_scaling or self._init_loss_scaling != 1.0

    def _install_policy(self, program):
        decorate_program(program, self._amp_lists, self._compute_dtype)

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        """Scale the loss, run the inner optimizer's backward, then
        unscale/check the gradients. Returns (params_grads, scaled_loss)."""
        from ... import layers

        program = loss.block.program
        self._install_policy(program)
        with program_guard(program, startup_program), \
                program._op_role_guard("backward"):
            if self._needs_scaling:
                self._loss_scaling = _create_persistable_scalar(
                    "loss_scaling", "float32", self._init_loss_scaling)
                scaled_loss = layers.elementwise_mul(loss, self._loss_scaling)
            else:
                scaled_loss = loss
            params_grads = self._optimizer.backward(
                scaled_loss, startup_program, parameter_list, no_grad_set,
                callbacks)
            if self._needs_scaling:
                self._append_unscale_ops(program, params_grads)
        return params_grads, scaled_loss

    def _append_unscale_ops(self, program, params_grads):
        block = program.global_block
        grad_names = [g.name for _, g in params_grads]
        self._found_inf = block.create_var(
            name=unique_name.generate("find_infinite_scale"),
            shape=(1,), dtype="bool", stop_gradient=True)
        block.append_op("check_finite_and_unscale",
                        inputs={"X": grad_names,
                                "Scale": self._loss_scaling.name},
                        outputs={"Out": grad_names,
                                 "FoundInfinite": self._found_inf.name})
        if self._use_dynamic_loss_scaling:
            good = _create_persistable_scalar("good_steps", "int32", 0)
            bad = _create_persistable_scalar("bad_steps", "int32", 0)
            block.append_op(
                "update_loss_scaling",
                inputs={"FoundInfinite": self._found_inf.name,
                        "PrevLossScaling": self._loss_scaling.name,
                        "InGoodSteps": good.name, "InBadSteps": bad.name},
                outputs={"LossScaling": self._loss_scaling.name,
                         "OutGoodSteps": good.name,
                         "OutBadSteps": bad.name},
                attrs={"incr_every_n_steps": self._incr_every_n_steps,
                       "decr_every_n_nan_or_inf": self._decr_every_n_nan_or_inf,
                       "incr_ratio": self._incr_ratio,
                       "decr_ratio": self._decr_ratio})

    def apply_gradients(self, params_grads):
        if self._found_inf is None:
            return self._optimizer.apply_gradients(params_grads)
        # Skip-update semantics (reference behaviour on FoundInfinite): the
        # ENTIRE update — clip, regularizer, accumulators (momentum/beta-pow)
        # and param writes — runs inside a conditional_block gated on the
        # grads being finite, so an overflow step leaves params AND optimizer
        # state untouched (zeroed grads alone would still advance momentum).
        from ...layers.control_flow import _block_io

        program = params_grads[0][0].block.program
        with program._op_role_guard("optimize"):
            parent = program.current_block()
            notinf = parent.create_var(
                name=unique_name.generate("amp_grads_finite"), shape=(1,),
                dtype="bool", stop_gradient=True)
            parent.append_op("logical_not", inputs={"X": self._found_inf.name},
                             outputs={"Out": notinf.name})
            sub = program._create_block()
            try:
                optimize_ops = self._optimizer.apply_gradients(params_grads)
            finally:
                program._rollback()
            reads, writes = _block_io(sub, parent)
            parent.append_op("conditional_block",
                             inputs={"Cond": [notinf.name], "Input": reads},
                             outputs={"Out": writes},
                             attrs={"sub_block": sub.idx})
        return optimize_ops

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads, scaled_loss = self.backward(
            loss, startup_program, parameter_list, no_grad_set)
        program = loss.block.program
        with program_guard(program, startup_program):
            optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads


def decorate(optimizer, amp_lists=None, init_loss_scaling=1.0,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8,
             use_dynamic_loss_scaling=False, compute_dtype="bfloat16"):
    """reference decorator.py:27 ``decorate``. TPU defaults: bf16 compute,
    loss scaling off (enable with use_dynamic_loss_scaling for fp16-style
    behaviour)."""
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists or AutoMixedPrecisionLists(),
        init_loss_scaling, use_dynamic_loss_scaling,
        incr_every_n_steps, decr_every_n_nan_or_inf, incr_ratio, decr_ratio,
        compute_dtype)
