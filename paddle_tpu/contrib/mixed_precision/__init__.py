"""Mixed precision (AMP) — reference: fluid/contrib/mixed_precision/."""
from .decorator import OptimizerWithMixedPrecision, decorate  # noqa: F401
from .fp16_lists import AutoMixedPrecisionLists  # noqa: F401
