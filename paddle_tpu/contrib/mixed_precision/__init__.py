"""Mixed precision (AMP) — reference: fluid/contrib/mixed_precision/."""
from .decorator import (OptimizerWithMixedPrecision, decorate,  # noqa: F401
                        decorate_program)
from .fp16_lists import AutoMixedPrecisionLists  # noqa: F401
