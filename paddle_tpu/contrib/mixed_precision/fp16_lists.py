"""Op classification for mixed precision.

Reference: python/paddle/fluid/contrib/mixed_precision/fp16_lists.py:24
(AutoMixedPrecisionLists with white/black/gray sets). The sets here name this
framework's registered op types; the roles are the same — white ops run in
the low-precision compute dtype (MXU-bound matmuls/convs), black ops are
numerically fragile and pinned to fp32, everything else (gray) runs in
whatever dtype its inputs arrive.
"""
from __future__ import annotations

__all__ = ["AutoMixedPrecisionLists", "WHITE_LIST", "BLACK_LIST"]

# MXU-bound: the whole point of bf16
WHITE_LIST = {
    "mul", "matmul", "conv2d", "conv2d_transpose", "depthwise_conv2d",
}

# numerically fragile: exp/log/large reductions and normalisation statistics
BLACK_LIST = {
    "softmax", "softmax_with_cross_entropy", "cross_entropy", "log_softmax",
    "sigmoid_cross_entropy_with_logits", "mean", "layer_norm", "batch_norm",
    "group_norm", "instance_norm", "l2_normalize", "squared_l2_norm",
    "reduce_mean", "reduce_sum", "exp", "log", "pow", "softplus",
}


class AutoMixedPrecisionLists:
    """reference fp16_lists.py:24 — user-extendable white/black sets."""

    def __init__(self, custom_white_list=None, custom_black_list=None):
        self.white_list = set(WHITE_LIST)
        self.black_list = set(BLACK_LIST)
        if custom_white_list and custom_black_list:
            both = set(custom_white_list) & set(custom_black_list)
            if both:
                raise ValueError(f"ops in both custom lists: {sorted(both)}")
        for op in custom_white_list or ():
            self.black_list.discard(op)
            self.white_list.add(op)
        for op in custom_black_list or ():
            self.white_list.discard(op)
            self.black_list.add(op)
