from .quantization_pass import QuantizationTransformPass, quant_aware  # noqa: F401
