"""Quantization-aware training as a Program transform.

Reference: python/paddle/fluid/contrib/slim/quantization/
quantization_pass.py QuantizationTransformPass — walks the IrGraph and
inserts fake_quantize(+dequantize) ops on the inputs of quantizable ops
(conv2d, mul/matmul, depthwise_conv2d), abs_max for weights and
moving-average abs_max for activations.

Here the same rewrite happens on the Program: for every quantizable op, a
fake-quant op is spliced before each float input — weights (persistable
params) get in-graph abs_max, activations get a moving-average scale held
in a new persistable state var. Must run BEFORE minimize() so the
backward differentiates through the straight-through estimators.

Pass-order contract with GEMM-epilogue fusion (docs/ANALYSIS.md
"Quantization and epilogue fusion"): QAT must ALSO run before
``analysis.epilogue_fusion`` — fusion consumes the fake-quant outputs as
GEMM inputs and the PT900 pairing check stays satisfiable; run the other
way round, the GEMM this pass wants to annotate has been swallowed into a
``fused_gemm_epilogue`` op it does not know how to split, and the quant
scaffolding would silently attach to nothing. ``apply`` refuses a
pre-fused program loudly instead of mis-pairing.
"""
from __future__ import annotations

from typing import Optional, Sequence

from ....framework import Operator, Program, default_startup_program
from .... import unique_name

_DEFAULT_QUANTIZABLE = ("conv2d", "depthwise_conv2d", "mul", "matmul")


class QuantizationTransformPass:
    def __init__(self, weight_bits: int = 8, activation_bits: int = 8,
                 moving_rate: float = 0.9,
                 quantizable_op_type: Sequence[str] = _DEFAULT_QUANTIZABLE,
                 skip_pattern: str = "skip_quant"):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.moving_rate = moving_rate
        self.quantizable = tuple(quantizable_op_type)
        self.skip_pattern = skip_pattern

    def apply(self, program: Program,
              startup_program: Optional[Program] = None) -> int:
        """Insert fake-quant ops; returns how many inputs were quantized."""
        startup = startup_program or default_startup_program()
        block = program.global_block
        fused = [i for i, op in enumerate(block.ops)
                 if op.type == "fused_gemm_epilogue"]
        if fused:
            raise ValueError(
                f"QuantizationTransformPass: program already contains "
                f"{len(fused)} fused_gemm_epilogue op(s) (first at global "
                f"block index {fused[0]}) — quantization must run BEFORE "
                f"epilogue fusion, not after: the GEMMs this pass would "
                f"annotate are gone and the fake-quant/GEMM pairing the "
                f"PT900 check enforces could not be established. Apply "
                f"quant_aware() to the unfused program, then fuse "
                f"(docs/ANALYSIS.md, 'Quantization and epilogue fusion').")
        quantized_of = {}  # source var -> fake-quant output name
        n = 0
        new_ops = []
        for op in block.ops:
            if op.type in self.quantizable and \
                    self.skip_pattern not in str(
                        op.attrs.get("op_namescope", "")):
                for slot, names in op.inputs.items():
                    new_names = []
                    for name in names:
                        v = block.vars.get(name)
                        if v is None or not _is_float(v.dtype):
                            new_names.append(name)
                            continue
                        if name not in quantized_of:
                            qname, qops = self._make_quant(
                                block, startup, name,
                                is_weight=getattr(v, "persistable", False))
                            new_ops.extend(qops)
                            quantized_of[name] = qname
                            n += 1
                        new_names.append(quantized_of[name])
                    op.inputs[slot] = new_names
            new_ops.append(op)
            # A name this op (re)defines invalidates any cached fake-quant
            # of it: a later consumer must quantize the NEW value, not the
            # stale one computed from the earlier definition.
            for names in op.outputs.values():
                for name in names:
                    quantized_of.pop(name, None)
        block.ops = new_ops
        program._bump_version()
        return n

    def _make_quant(self, block, startup, name, is_weight):
        v = block.vars[name]
        qname = unique_name.generate(name + ".quantized")
        block.create_var(name=qname, shape=v.shape, dtype=v.dtype,
                         stop_gradient=False)
        scale_name = unique_name.generate(name + ".quant_scale")
        block.create_var(name=scale_name, shape=(1,), dtype="float32",
                         stop_gradient=True, persistable=not is_weight)
        ops = []
        if is_weight:
            op = Operator(block, "fake_quantize_dequantize_abs_max",
                          inputs={"X": [name]},
                          outputs={"Out": [qname],
                                   "OutScale": [scale_name]},
                          attrs={"bit_length": self.weight_bits})
        else:
            # moving-average scale: persistable state initialised to 1
            startup_blk = startup.global_block
            if not startup_blk.has_var(scale_name):
                startup_blk.create_var(name=scale_name, shape=(1,),
                                       dtype="float32", persistable=True)
                startup_blk.append_op(
                    "fill_constant", outputs={"Out": scale_name},
                    attrs={"shape": [1], "dtype": "float32", "value": 1.0})
            op = Operator(
                block, "fake_quantize_dequantize_moving_average_abs_max",
                inputs={"X": [name], "InScale": [scale_name]},
                outputs={"Out": [qname], "OutScale": [scale_name]},
                attrs={"bit_length": self.activation_bits,
                       "moving_rate": self.moving_rate})
        block._stamp(op)
        ops.append(op)
        return qname, ops


def quant_aware(program: Program, startup_program: Optional[Program] = None,
                weight_bits: int = 8, activation_bits: int = 8,
                quantizable_op_type: Sequence[str] = _DEFAULT_QUANTIZABLE):
    """The PaddleSlim-style one-call entry: rewrite ``program`` for QAT.
    Call BEFORE minimize()."""
    p = QuantizationTransformPass(weight_bits, activation_bits,
                                  quantizable_op_type=quantizable_op_type)
    p.apply(program, startup_program)
    return program


def _is_float(dtype) -> bool:
    return str(dtype).startswith("float") or str(dtype) == "bfloat16"
