"""Architecture search controllers (reference contrib/slim/searcher/
controller.py:28 EvolutionaryController, :59 SAController).

The reference's LightNAS wrapped these behind a socket-based
ControllerServer (nas/controller_server.py) so distributed trainers could
share one controller; on TPU the search loop is a host-side driver around
compiled evaluations, so the controllers are plain objects — start them in
the launcher process and broadcast tokens with the collectives if needed.
"""
from __future__ import annotations

import math

import numpy as np

__all__ = ["EvolutionaryController", "SAController"]


class EvolutionaryController:
    """Token-space search base (reference controller.py:28)."""

    def update(self, tokens, reward):
        raise NotImplementedError

    def reset(self, range_table, constrain_func=None):
        raise NotImplementedError

    def next_tokens(self):
        raise NotImplementedError


class SAController(EvolutionaryController):
    """Simulated annealing over integer token vectors (reference
    controller.py:59). Accept a worse reward with probability
    exp((reward - current) / T), T decaying by ``reduce_rate`` per step."""

    def __init__(self, range_table=None, reduce_rate=0.85,
                 init_temperature=1024, max_iter_number=300, seed=None):
        self._range_table = range_table
        self._reduce_rate = reduce_rate
        self._init_temperature = init_temperature
        self._max_iter_number = max_iter_number
        self._reward = -1
        self._tokens = None
        self._max_reward = -1
        self._best_tokens = None
        self._iter = 0
        self._constrain_func = None
        self._rng = np.random.RandomState(seed)

    def __getstate__(self):
        return {k: v for k, v in self.__dict__.items()
                if k != "_constrain_func"}

    @property
    def best_tokens(self):
        return self._best_tokens

    @property
    def max_reward(self):
        return self._max_reward

    def reset(self, range_table, init_tokens, constrain_func=None):
        self._range_table = list(range_table)
        self._constrain_func = constrain_func
        self._tokens = list(init_tokens)
        self._iter = 0

    def update(self, tokens, reward):
        self._iter += 1
        temperature = self._init_temperature * \
            self._reduce_rate ** self._iter
        if reward > self._reward or self._rng.random_sample() <= math.exp(
                min((reward - self._reward) / max(temperature, 1e-9), 0.0)):
            self._reward = reward
            self._tokens = list(tokens)
        if reward > self._max_reward:
            self._max_reward = reward
            self._best_tokens = list(tokens)

    def next_tokens(self, control_token=None):
        tokens = list(control_token) if control_token else list(self._tokens)
        new_tokens = list(tokens)
        index = int(len(self._range_table) * self._rng.random_sample())
        new_tokens[index] = (
            new_tokens[index]
            + self._rng.randint(max(self._range_table[index] - 1, 1)) + 1
        ) % self._range_table[index]
        if self._constrain_func is None:
            return new_tokens
        for _ in range(self._max_iter_number):
            if not self._constrain_func(new_tokens):
                index = int(len(self._range_table)
                            * self._rng.random_sample())
                new_tokens = list(tokens)
                new_tokens[index] = self._rng.randint(
                    self._range_table[index])
            else:
                break
        return new_tokens
