"""Model pruning (reference contrib/slim/prune/pruner.py).

TPU-first position, stated once: XLA compiles static shapes, and the MXU
gains nothing from zeroed lanes — so pruning here has two distinct modes
with different artifacts:

- **mask pruning** (`prune_parameters`): zero the selected channel groups
  in the scope, shapes unchanged. This is what the reference's iterative
  sensitive-pruning loop actually needs during training (prune -> finetune
  -> re-prune), and the only mode that composes with a compiled program
  mid-training.
- **shape shrinking** (`shrink_model`): numpy surgery on the scope + var
  metadata that REMOVES the pruned channels of matched conv/fc chains for
  deployment — the reference's final export semantics, where the FLOP
  savings become real.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

__all__ = ["Pruner", "StructurePruner", "prune_parameters", "apply_masks",
           "shrink_model"]


class Pruner:
    """Base class (reference pruner.py:22)."""

    def prune(self, param):
        pass


class StructurePruner(Pruner):
    """Group (channel) pruning by l1/l2 norm (reference pruner.py:34)."""

    def __init__(self, pruning_axis: Dict[str, int],
                 criterions: Dict[str, str]):
        self.pruning_axis = pruning_axis
        self.criterions = criterions

    def cal_pruned_idx(self, name: str, param: np.ndarray, ratio: float,
                       axis: Optional[int] = None) -> List[int]:
        criterion = self.criterions.get(name, self.criterions.get("*"))
        if axis is None:
            axis = self.pruning_axis.get(name, self.pruning_axis.get("*"))
        prune_num = int(round(param.shape[axis] * ratio))
        reduce_dims = tuple(i for i in range(param.ndim) if i != axis)
        if criterion == "l1_norm":
            scores = np.sum(np.abs(param), axis=reduce_dims)
        elif criterion == "l2_norm":
            scores = np.sqrt(np.sum(np.square(param), axis=reduce_dims))
        else:
            raise ValueError(f"unknown criterion {criterion!r}")
        return list(scores.argsort()[:prune_num])

    def prune_tensor(self, tensor: np.ndarray, pruned_idx, pruned_axis: int,
                     lazy: bool = False) -> np.ndarray:
        """lazy=True zeroes the groups (mask mode); lazy=False removes them
        (shrink mode) — reference pruner.py prune_tensor contract."""
        if lazy:
            out = np.array(tensor)
            idx = [slice(None)] * tensor.ndim
            idx[pruned_axis] = list(pruned_idx)
            out[tuple(idx)] = 0
            return out
        return np.delete(tensor, list(pruned_idx), axis=pruned_axis)


def prune_parameters(scope, ratios: Dict[str, float], criterion="l1_norm",
                     axis=0, tied: Optional[Dict[str, List[str]]] = None):
    """Mask-prune named parameters in ``scope`` by channel-group norm:
    zero the lowest-norm ``ratio`` of groups along ``axis``. ``tied`` maps
    a pruned param to vars sharing its channel axis (its bias, BN stats):
    a masked channel must read as FULLY dead — weight AND bias — or the
    downstream layers finetune against a constant the final shrink then
    removes. Returns {param: pruned channel indices}; re-apply with
    ``apply_masks`` after each finetune step to keep the zeros pinned."""
    pruner = StructurePruner({"*": axis}, {"*": criterion})
    pruned = {}
    for name, ratio in ratios.items():
        val = np.asarray(scope.find_var(name))
        idx = pruner.cal_pruned_idx(name, val, ratio)
        scope.set_var(name, pruner.prune_tensor(val, idx, axis, lazy=True))
        pruned[name] = idx
        for tied_name in (tied or {}).get(name, []):
            tv = np.asarray(scope.find_var(tied_name)).copy()
            tv[idx] = 0
            scope.set_var(tied_name, tv)
    return pruned


def apply_masks(scope, pruned: Dict[str, List[int]], axis=0,
                tied: Optional[Dict[str, List[str]]] = None):
    """Re-pin the pruned groups to zero (call after each finetune step —
    the optimizer update revives them otherwise)."""
    for name, idx in pruned.items():
        w = np.asarray(scope.find_var(name)).copy()
        sl = [slice(None)] * w.ndim
        sl[axis] = list(idx)
        w[tuple(sl)] = 0
        scope.set_var(name, w)
        for tied_name in (tied or {}).get(name, []):
            tv = np.asarray(scope.find_var(tied_name)).copy()
            tv[list(idx)] = 0
            scope.set_var(tied_name, tv)


def shrink_model(program, startup_program, scope,
                 ratios: Dict[str, float], criterion="l1_norm",
                 pruned_idx: Optional[Dict[str, List[int]]] = None):
    """Deployment-time channel removal for fc/conv chains: shrink param
    OUT-channels (axis 0 for conv [O,I,kh,kw], axis 1 for fc [in, out]) and
    the DOWNSTREAM consumer's IN-channels to match. Only straight-line
    producer->consumer chains are rewritten; anything else raises rather
    than silently corrupting shapes. Returns the pruned index map.

    After a mask-prune + finetune cycle, pass ``pruned_idx`` (the map
    ``prune_parameters`` returned): finetuning changes channel norms, so
    recomputing indices here would remove channels the finetune made
    important while keeping the zeroed ones."""
    block = program.global_block
    pruner = StructurePruner({}, {"*": criterion})

    # ops through which the channel dim flows unchanged: the walk continues
    # past these until it hits the next parametered op; anything else stops
    # the walk loudly rather than silently corrupting shapes
    _CHANNEL_PRESERVING = {
        "elementwise_add", "elementwise_sub", "elementwise_mul", "relu",
        "relu6", "leaky_relu", "sigmoid", "tanh", "batch_norm", "dropout",
        "pool2d", "scale", "prelu", "swish", "hard_swish",
    }

    def consumers_of(var_name):
        return [op for op in block.ops if var_name in op.input_arg_names]

    def shrink_param(var_name, idx, axis):
        w = np.asarray(scope.find_var(var_name))
        scope.set_var(var_name, pruner.prune_tensor(w, idx, axis))
        block.var(var_name).shape = tuple(
            np.asarray(scope.find_var(var_name)).shape)

    pruned = {}
    for name, ratio in ratios.items():
        val = np.asarray(scope.find_var(name))
        # conv weights are [O, I, kh, kw]; fc weights [in, out]
        out_axis = 0 if val.ndim == 4 else 1
        n_out = val.shape[out_axis]
        idx = (list(pruned_idx[name]) if pruned_idx and name in pruned_idx
               else pruner.cal_pruned_idx(name, val, ratio, axis=out_axis))
        if not idx:
            continue
        shrink_param(name, idx, out_axis)
        pruned[name] = idx

        # BFS from the producer's output through channel-preserving ops;
        # shrink side-input params (biases, bn stats) along their channel
        # axis and downstream weights along their IN-channel axis
        producer = next(op for op in block.ops
                        if name in op.input_arg_names)
        frontier = list(producer.output_arg_names)
        seen_vars = set(frontier)
        while frontier:
            var_name = frontier.pop()
            for op in consumers_of(var_name):
                # deployment transform: backward/optimizer ops re-derive
                # from the (shrunk) forward — never walk into them. NOTE:
                # after shrinking a TRAINING program, optimizer
                # accumulators keep their old shapes; rebuild the
                # optimizer (re-run minimize + startup) before continuing
                # to train, exactly as the reference slim rebuilds its
                # graph between prune rounds.
                if op.type.endswith("_grad") or \
                        op.attrs.get("__op_role__") in ("backward",
                                                        "optimize",
                                                        "lr_sched"):
                    continue
                param_ins = [n for n in op.input_arg_names
                             if n != name and block.has_var(n)
                             and type(block.var(n)).__name__ == "Parameter"]
                hit_weight = False
                for in_name in param_ins:
                    w = np.asarray(scope.find_var(in_name))
                    if w.ndim >= 2:
                        in_axis = 1 if w.ndim == 4 else 0
                        if w.shape[in_axis] == n_out:
                            shrink_param(in_name, idx, in_axis)
                            hit_weight = True
                    elif w.ndim == 1 and w.shape[0] == n_out:
                        shrink_param(in_name, idx, 0)  # bias / bn stats
                if hit_weight:
                    continue  # channel identity ends here
                if op.type in _CHANNEL_PRESERVING:
                    for out_name in op.output_arg_names:
                        if out_name not in seen_vars:
                            seen_vars.add(out_name)
                            frontier.append(out_name)
                elif not param_ins:
                    raise ValueError(
                        f"shrink_model: op '{op.type}' consumes pruned "
                        f"channels of '{name}' but is not channel-"
                        f"preserving; prune a layer with a straight "
                        f"conv/fc chain or use mask pruning")
    program._bump_version()
    return pruned
