"""Model compression (reference python/paddle/fluid/contrib/slim/)."""
from . import quantization  # noqa: F401
