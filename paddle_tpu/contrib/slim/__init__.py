"""Model compression (reference python/paddle/fluid/contrib/slim/):
quantization (QAT), pruning (mask + shape-shrink), distillation
(L2/FSP/soft-label over merged programs), search (SA controller)."""
from . import distillation  # noqa: F401
from . import prune  # noqa: F401
from . import quantization  # noqa: F401
from . import searcher  # noqa: F401
