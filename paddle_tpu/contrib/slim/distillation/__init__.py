"""Knowledge distillation (reference contrib/slim/distillation/distiller.py:
L2Distiller :25, FSPDistiller :103, SoftLabelDistiller :195).

The reference's GraphWrapper machinery merged teacher and student programs
into one IR graph and spliced loss ops in C++-adjacent passes. Here the
same result is two plain program transforms:

- ``merge_teacher_program``: append the teacher's ops/params into the
  student's program under a name prefix (teacher params load under their
  prefixed names and are frozen via stop_gradient) — one compiled XLA
  program runs both networks, letting the compiler share layout work.
- distillers: functions appending the distillation loss ops to the merged
  program and returning the loss Variable, mirroring the reference's
  distiller_loss contract.
"""
from __future__ import annotations

from ....framework import default_main_program, program_guard

__all__ = ["merge_teacher_program", "L2Distiller", "FSPDistiller",
           "SoftLabelDistiller", "fsp_matrix"]


def merge_teacher_program(student_program, teacher_program,
                          prefix="teacher_", feed_map=None,
                          teacher_startup=None, student_startup=None):
    """Append the teacher's global-block ops and vars into the student
    program, renaming every teacher var ``prefix + name``. ``feed_map``
    maps teacher feed names -> student var names so both nets read the
    same input batch. Teacher vars are created stop_gradient=True (frozen
    teacher — reference distillation_strategy.py on_compression_begin).
    When startup programs are given, the teacher's initializer ops merge
    into the student's startup under the same renames, so one
    ``exe.run(startup)`` initializes both nets (load real teacher weights
    over them afterwards with io.load_params).
    Returns {original teacher var name -> merged name}."""
    feed_map = feed_map or {}
    renames = {}

    def merge_block(src_block, dst_block):
        for name, v in src_block.vars.items():
            if name in feed_map:
                renames[name] = feed_map[name]
                continue
            new_name = prefix + name
            renames.setdefault(name, new_name)
            if dst_block.has_var(new_name):
                continue
            if type(v).__name__ == "Parameter":
                # must stay a Parameter: io.save/load_params filters on the
                # class, so plain vars would be silently skipped when
                # loading real teacher weights — but frozen (the teacher
                # never trains here)
                nv = dst_block.create_parameter(
                    new_name, v.shape, v.dtype, trainable=False)
                nv.persistable = True
                nv.stop_gradient = True
            else:
                nv = dst_block.create_var(
                    name=new_name, shape=v.shape, dtype=v.dtype,
                    persistable=v.persistable, stop_gradient=True,
                    is_data=getattr(v, "is_data", False))
            nv.lod_level = getattr(v, "lod_level", 0)
        for op in src_block.ops:
            if op.type in ("feed", "fetch"):
                continue
            inputs = {slot: [renames.get(n, n) for n in names]
                      for slot, names in op.inputs.items()}
            outputs = {slot: [renames.get(n, n) for n in names]
                       for slot, names in op.outputs.items()}
            dst_block.append_op(op.type, inputs=inputs, outputs=outputs,
                                attrs=dict(op.attrs))

    merge_block(teacher_program.global_block, student_program.global_block)
    if teacher_startup is not None and student_startup is not None:
        merge_block(teacher_startup.global_block,
                    student_startup.global_block)
    return renames


class L2Distiller:
    """||student_fmap - teacher_fmap||^2 (reference distiller.py:25)."""

    def __init__(self, student_feature_map, teacher_feature_map,
                 distillation_loss_weight=1.0):
        self.student_feature_map = student_feature_map
        self.teacher_feature_map = teacher_feature_map
        self.weight = distillation_loss_weight

    def distiller_loss(self, program=None):
        from ....layers import nn as L

        program = program or default_main_program()
        blk = program.global_block
        with program_guard(program):  # loss ops must land in THIS program
            s = blk.var(self.student_feature_map)
            t = blk.var(self.teacher_feature_map)
            diff = L.elementwise_sub(s, t)
            loss = L.reduce_mean(L.square(diff))
            return L.scale(loss, scale=float(self.weight))


def fsp_matrix(a, b):
    """Flow-of-solution-procedure matrix (reference distiller.py:191):
    for feature maps [N, C1, H, W] and [N, C2, H, W],
    fsp = a_flat @ b_flat^T / (H*W) -> [N, C1, C2]."""
    from ....layers import nn as L

    n, c1 = a.shape[0], a.shape[1]
    c2 = b.shape[1]
    hw = int(a.shape[2]) * int(a.shape[3])
    a2 = L.reshape(a, [-1, c1, hw])
    b2 = L.reshape(b, [-1, c2, hw])
    prod = L.matmul(a2, L.transpose(b2, [0, 2, 1]))
    return L.scale(prod, scale=1.0 / hw)


class FSPDistiller:
    """FSP-matrix distillation (reference distiller.py:103): match the
    student's and teacher's layer-pair flow matrices by l2."""

    def __init__(self, student_pairs, teacher_pairs,
                 distillation_loss_weight=1.0):
        self.student_pairs = student_pairs
        self.teacher_pairs = teacher_pairs
        self.weight = distillation_loss_weight

    def distiller_loss(self, program=None):
        from ....layers import nn as L

        program = program or default_main_program()
        blk = program.global_block
        with program_guard(program):
            losses = []
            for (s0, s1), (t0, t1) in zip(self.student_pairs,
                                          self.teacher_pairs):
                sf = fsp_matrix(blk.var(s0), blk.var(s1))
                tf = fsp_matrix(blk.var(t0), blk.var(t1))
                losses.append(L.reduce_mean(L.square(
                    L.elementwise_sub(sf, tf))))
            total = losses[0]
            for extra in losses[1:]:
                total = L.elementwise_add(total, extra)
            return L.scale(total, scale=float(self.weight))


class SoftLabelDistiller:
    """Soft-target cross entropy with temperatures (reference
    distiller.py:195): CE(softmax(t/T_t), softmax(s/T_s))."""

    def __init__(self, student_feature_map, teacher_feature_map,
                 student_temperature=1.0, teacher_temperature=1.0,
                 distillation_loss_weight=1.0):
        self.student_feature_map = student_feature_map
        self.teacher_feature_map = teacher_feature_map
        self.student_temperature = student_temperature
        self.teacher_temperature = teacher_temperature
        self.weight = distillation_loss_weight

    def distiller_loss(self, program=None):
        from ....layers import nn as L

        program = program or default_main_program()
        blk = program.global_block
        with program_guard(program):
            s = L.scale(blk.var(self.student_feature_map),
                        scale=1.0 / self.student_temperature)
            t = L.scale(blk.var(self.teacher_feature_map),
                        scale=1.0 / self.teacher_temperature)
            s_log_prob = L.log_softmax(s)
            t_prob = L.softmax(t)
            ce = L.reduce_mean(
                L.reduce_sum(L.elementwise_mul(
                    L.scale(t_prob, scale=-1.0), s_log_prob), dim=-1))
            return L.scale(ce, scale=float(self.weight))
