"""Type system for the TPU-native framework.

Plays the role of the reference's ``framework.proto`` VarType/DataType enums
(reference: paddle/fluid/framework/framework.proto:105-160) but maps directly
onto numpy/jax dtypes instead of a protobuf enum.
"""
from __future__ import annotations

import enum

import numpy as np


class VarType(enum.Enum):
    """Variable kinds (reference framework.proto:105 ``VarType.Type``)."""

    LOD_TENSOR = "lod_tensor"
    SELECTED_ROWS = "selected_rows"
    LOD_TENSOR_ARRAY = "lod_tensor_array"
    STEP_SCOPES = "step_scopes"
    READER = "reader"
    RAW = "raw"


# Canonical dtype strings. We use numpy-style names everywhere; bf16 is
# first-class because it is the native TPU matmul type.
_CANONICAL = {
    "float32": "float32",
    "float64": "float64",
    "float16": "float16",
    "bfloat16": "bfloat16",
    "int8": "int8",
    "uint8": "uint8",
    "int16": "int16",
    "uint16": "uint16",
    "int32": "int32",
    "uint32": "uint32",
    "int64": "int64",
    "uint64": "uint64",
    "bool": "bool",
    # aliases
    "fp32": "float32",
    "fp64": "float64",
    "fp16": "float16",
    "bf16": "bfloat16",
    "float": "float32",
    "double": "float64",
    "int": "int32",
    "long": "int64",
}


def canonical_dtype(dtype) -> str:
    """Normalise a user-provided dtype (str / np.dtype / jnp dtype) to a
    canonical string name."""
    if dtype is None:
        return "float32"
    if isinstance(dtype, str):
        name = dtype.lower()
        if name in _CANONICAL:
            return _CANONICAL[name]
        raise ValueError(f"unknown dtype string: {dtype!r}")
    # handle jax / numpy dtype-like objects (incl. ml_dtypes.bfloat16)
    name = np.dtype(dtype).name if not hasattr(dtype, "name") else dtype.name
    if name in _CANONICAL:
        return _CANONICAL[name]
    name = str(dtype)
    if name in _CANONICAL:
        return _CANONICAL[name]
    raise ValueError(f"unknown dtype: {dtype!r}")


def np_dtype(dtype) -> np.dtype:
    name = canonical_dtype(dtype)
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


# what a 64-bit dtype request degrades to when jax runs with x64 disabled
_X64_FALLBACK = {"int64": "int32", "uint64": "uint32", "float64": "float32"}

# memoized behavioural probe result: does THIS jax runtime actually deliver
# 64-bit dtypes? None = not probed yet. (Runtime enable_x64 toggling after
# the first probe is not observed — the same documented contract as the
# flags module's env-var reads.)
_X64_ACTIVE = None


def _x64_active() -> bool:
    """Whether jax delivers 64-bit dtypes, decided by BEHAVIOUR, not
    introspection: convert an int64 numpy array (an implicit conversion
    never warns) and look at what comes back. Two generations of
    introspection broke here — ``jax.config.jax_enable_x64`` became an
    always-truthy holder object, and ``jax.dtypes.canonicalize_dtype``
    raised on some backend builds while every jnp constructor still
    truncated-and-warned (the int64 spam in every BENCH tail at
    ops/tensor.py:30). The empty-array conversion is what the runtime
    actually does, so it cannot drift from the warning behaviour."""
    global _X64_ACTIVE
    if _X64_ACTIVE is None:
        try:
            import jax.numpy as jnp

            _X64_ACTIVE = bool(
                np.dtype(jnp.asarray(np.zeros(0, np.int64)).dtype).itemsize
                == 8)
        except Exception:
            # probe impossible (backend init failure mid-teardown): fall
            # back to canonicalize_dtype, else assume the common x64-off
            # default — requesting the narrow type in an x64-on runtime
            # merely loses width; requesting the wide one in an x64-off
            # runtime is the warn-per-traced-op spam this exists to kill
            try:
                import jax

                _X64_ACTIVE = bool(np.dtype(jax.dtypes.canonicalize_dtype(
                    np.dtype("int64"))).itemsize == 8)
            except Exception:
                _X64_ACTIVE = False
    return _X64_ACTIVE


def jnp_dtype(dtype) -> np.dtype:
    """``np_dtype`` for dtypes handed to jax constructors (jnp.full,
    jax.random.*, jnp.arange, ``Array.astype``...): with ``jax_enable_x64``
    off, explicitly requesting int64/float64 makes every call site emit a
    truncation warning before silently downcasting — spamming bench output
    once per traced op. Canonicalize here instead: request exactly the type
    jax will deliver anyway, decided by the behavioural probe
    ``_x64_active`` (introspection-based probes failed open twice — see its
    docstring). Host-side numpy arrays (feeds, serialized attrs) keep full
    width via ``np_dtype``."""
    dt = np_dtype(dtype)
    if dt.name in _X64_FALLBACK and not _x64_active():
        return np.dtype(_X64_FALLBACK[dt.name])
    return dt


def is_floating(dtype) -> bool:
    return canonical_dtype(dtype) in ("float16", "float32", "float64", "bfloat16")


def is_integer(dtype) -> bool:
    return canonical_dtype(dtype) in ("int8", "uint8", "int16", "int32", "int64")
