"""SelectedRows — sparse row-slice gradients, the TPU way.

Reference role: paddle/fluid/framework/selected_rows.h:32 (a {rows, value,
height} triple used as the gradient type of ``is_sparse`` embedding lookups)
plus the sparse branches of the optimizer kernels
(operators/optimizers/adam_op.h SparseAdamFunctor, sgd_op.h, momentum).

TPU-first design: XLA needs static shapes, so the rows vector is fixed at
``N = number of lookups this step`` (batch x seq), NOT the dynamic number of
unique ids. ``merge_rows`` canonicalizes at creation time — sort + segment
sum — so every downstream consumer sees duplicate-free rows, with unused
trailing slots holding the out-of-bounds sentinel ``height`` that XLA
scatter's mode="drop" discards. Memory/compute per step is O(N x dim), not
O(vocab x dim): exactly the property the reference's SelectedRows bought on
parameter servers, delivered here via gather/scatter + segment ops that XLA
lowers to efficient TPU sort/scan kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
class SelectedRows:
    """rows: int32[N] (duplicate-free, sentinel-padded with ``height``),
    values: float[N, ...tail], height: static int (table row count)."""

    def __init__(self, rows, values, height: int):
        self.rows = rows
        self.values = values
        self.height = int(height)

    def tree_flatten(self):
        return (self.rows, self.values), self.height

    @classmethod
    def tree_unflatten(cls, height, children):
        return cls(children[0], children[1], height)

    # NOTE: deliberately no ``.dtype``/``.shape`` attributes — the executor's
    # nan-check walk and feed signature logic treat anything with those as a
    # dense array.

    def astype(self, dtype) -> "SelectedRows":
        return SelectedRows(self.rows, self.values.astype(dtype), self.height)

    def scale(self, s) -> "SelectedRows":
        return SelectedRows(self.rows, self.values * s, self.height)

    def to_dense(self):
        """Materialize the dense [height, ...] gradient (fallback for
        consumers without a sparse path). Sentinel rows are dropped."""
        z = jnp.zeros((self.height,) + tuple(self.values.shape[1:]),
                      self.values.dtype)
        return z.at[self.rows].add(self.values, mode="drop")

    def __repr__(self):
        return (f"SelectedRows(n={self.rows.shape[0]}, "
                f"height={self.height}, tail={self.values.shape[1:]})")


def is_selected_rows(v) -> bool:
    return isinstance(v, SelectedRows)


def merge_rows(ids, values, height: int) -> SelectedRows:
    """Canonical SelectedRows from raw (possibly duplicated) lookup ids and
    per-lookup gradient rows: sort ids, segment-sum duplicate rows, pad the
    tail with the ``height`` sentinel. The reference does this merge in
    operators/math/selected_rows_functor.cc MergeAdd; here it is three XLA
    ops (sort, scan for segment ids, two segment reductions)."""
    n = ids.shape[0]
    ids = ids.astype(jnp.int32)
    order = jnp.argsort(ids)
    sids = ids[order]
    svals = values[order]
    first = jnp.concatenate(
        [jnp.ones((1,), bool), sids[1:] != sids[:-1]])
    seg = jnp.cumsum(first) - 1                       # [n] segment index
    summed = jax.ops.segment_sum(svals, seg, num_segments=n)
    rows = jax.ops.segment_min(sids, seg, num_segments=n)
    n_unique = seg[-1] + 1
    valid = jnp.arange(n) < n_unique
    rows = jnp.where(valid, rows, height)             # sentinel -> dropped
    # zero sentinel slots' values too: ids pre-routed to ``height`` (e.g.
    # padding_idx) summed real cotangents there, and norm/clip consumers
    # reduce over values — a dropped-at-scatter row must also read as zero
    live = (rows < height).reshape((n,) + (1,) * (values.ndim - 1))
    summed = jnp.where(live, summed, 0)
    return SelectedRows(rows.astype(jnp.int32), summed, height)


def concat_merge(a: SelectedRows, b: SelectedRows) -> SelectedRows:
    """Sum of two SelectedRows (shared-table multi-consumer grads): concat
    then re-merge. Sentinel rows sort to the end and stay sentinels."""
    assert a.height == b.height, "summing grads of different tables"
    return merge_rows(jnp.concatenate([a.rows, b.rows]),
                      jnp.concatenate([a.values, b.values]), a.height)
