"""Operator registry: schemas, shape inference, lowering rules, grad makers.

This is the TPU-native replacement for the reference's static-init op registry
(reference: paddle/fluid/framework/op_registry.h:68-243 REGISTER_OPERATOR /
REGISTER_OP_*_KERNEL macros and op_proto_maker.h attribute schemas). Instead of
per-device kernel maps, each op registers ONE ``lower`` rule that emits jax ops
while a whole program block is traced to a single XLA executable — the ngraph
subgraph-bridge strategy (reference: paddle/fluid/operators/ngraph/) applied to
the entire block.

Gradients: the reference attaches a C++ GradOpDescMaker per op
(grad_op_desc_maker.h:36). Here the default grad maker is *generic*: it emits a
``<type>_grad`` op whose lowering recomputes the forward rule under ``jax.vjp``.
XLA CSEs the duplicated forward subexpression, so there is no runtime cost, and
we get 500-op autodiff coverage without 500 hand-written grad kernels. Ops with
special semantics can register a custom grad maker or custom grad lowering.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence

_OP_REGISTRY: Dict[str, "OpDef"] = {}


@dataclasses.dataclass
class IOSpec:
    """One input/output slot of an op (reference OpProto::Var)."""

    name: str
    duplicable: bool = False  # slot may hold a list of vars (e.g. sum's X)
    optional: bool = False    # slot may be absent
    no_grad: bool = False     # never produce/needs no gradient for this slot


@dataclasses.dataclass
class AttrSpec:
    name: str
    default: Any = None
    required: bool = False


@dataclasses.dataclass
class OpDef:
    """Schema + behaviour of one operator type."""

    type: str
    inputs: List[IOSpec] = dataclasses.field(default_factory=list)
    outputs: List[IOSpec] = dataclasses.field(default_factory=list)
    attrs: Dict[str, AttrSpec] = dataclasses.field(default_factory=dict)
    # infer_shape(op, block): set shapes/dtypes on output vars at build time.
    infer_shape: Optional[Callable] = None
    # lower(ctx, ins, attrs) -> {out_slot: [jax_array, ...]}
    lower: Optional[Callable] = None
    # 'auto' -> generic vjp grad; None -> non-differentiable; callable -> custom
    # maker(op, block, no_grad_set) -> list of op-dicts for the backward block.
    grad: Any = "auto"
    # If set, custom lowering for the auto '<type>_grad' op.
    grad_lower: Optional[Callable] = None
    # stateful ops (random) receive a PRNG key in ctx
    needs_rng: bool = False
    # slots of the *forward* op that the auto-grad lowering does not need
    # (lets the executor drop dead buffers, cf. NoNeedBufferVarsInference)
    no_need_buffer: Sequence[str] = ()
    # raw ops get lower(ctx, op, env) instead of lower(ctx, ins, attrs):
    # control-flow ops need the op's var names and sub-block access
    # (reference: while_op.cc runs a sub-block with its own Executor)
    raw: bool = False

    def input_spec(self, slot: str) -> Optional[IOSpec]:
        for s in self.inputs:
            if s.name == slot:
                return s
        return None

    def output_spec(self, slot: str) -> Optional[IOSpec]:
        for s in self.outputs:
            if s.name == slot:
                return s
        return None


def register_op(
    type: str,
    inputs: Sequence = (),
    outputs: Sequence = (),
    attrs: Optional[Dict[str, Any]] = None,
    infer_shape: Optional[Callable] = None,
    grad: Any = "auto",
    grad_lower: Optional[Callable] = None,
    needs_rng: bool = False,
    no_need_buffer: Sequence[str] = (),
    raw: bool = False,
):
    """Decorator registering ``fn`` as the lowering rule for op ``type``.

    ``inputs``/``outputs`` entries are either slot-name strings or IOSpec.
    ``attrs`` maps attr name -> default value (or AttrSpec).
    """

    def norm_io(items) -> List[IOSpec]:
        out = []
        for it in items:
            if isinstance(it, IOSpec):
                out.append(it)
            else:
                out.append(IOSpec(name=it))
        return out

    def norm_attrs(a) -> Dict[str, AttrSpec]:
        result = {}
        for k, v in (a or {}).items():
            result[k] = v if isinstance(v, AttrSpec) else AttrSpec(name=k, default=v)
        return result

    def deco(fn: Callable) -> Callable:
        if type in _OP_REGISTRY:
            raise ValueError(f"op '{type}' registered twice")
        _OP_REGISTRY[type] = OpDef(
            type=type,
            inputs=norm_io(inputs),
            outputs=norm_io(outputs),
            attrs=norm_attrs(attrs),
            infer_shape=infer_shape,
            lower=fn,
            grad=grad,
            grad_lower=grad_lower,
            needs_rng=needs_rng,
            no_need_buffer=tuple(no_need_buffer),
            raw=raw,
        )
        return fn

    return deco


def get_op_def(type: str) -> OpDef:
    if type not in _OP_REGISTRY:
        raise KeyError(
            f"operator '{type}' is not registered; known ops: "
            f"{sorted(_OP_REGISTRY)[:20]}... ({len(_OP_REGISTRY)} total)"
        )
    return _OP_REGISTRY[type]


def has_op(type: str) -> bool:
    return type in _OP_REGISTRY


def all_ops() -> List[str]:
    return sorted(_OP_REGISTRY)
