"""Weight-decay regularizers (reference: python/paddle/fluid/regularizer.py:
L1DecayRegularizer :184, L2DecayRegularizer :112 — appended to grads before
the optimizer op)."""
from __future__ import annotations

from .layer_helper import LayerHelper

__all__ = ["L1Decay", "L2Decay", "L1DecayRegularizer", "L2DecayRegularizer",
           "append_regularization_ops"]


class WeightDecayRegularizer:
    def _append_regularization_op(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def _append_regularization_op(self, param, grad, block):
        helper = LayerHelper("l2_decay")
        decay = helper.create_variable_for_type_inference(param.dtype, True)
        block.append_op("scale", inputs={"X": param}, outputs={"Out": decay},
                        attrs={"scale": self._coeff})
        out = helper.create_variable_for_type_inference(grad.dtype, True)
        block.append_op("sum", inputs={"X": [grad, decay]},
                        outputs={"Out": out})
        return out


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def _append_regularization_op(self, param, grad, block):
        helper = LayerHelper("l1_decay")
        sign = helper.create_variable_for_type_inference(param.dtype, True)
        # sign(x) = x / (|x| + eps): avoid a dedicated op
        absx = helper.create_variable_for_type_inference(param.dtype, True)
        block.append_op("abs", inputs={"X": param}, outputs={"Out": absx})
        shifted = helper.create_variable_for_type_inference(param.dtype, True)
        block.append_op("scale", inputs={"X": absx}, outputs={"Out": shifted},
                        attrs={"scale": 1.0, "bias": 1e-12})
        block.append_op("elementwise_div", inputs={"X": param, "Y": shifted},
                        outputs={"Out": sign}, attrs={"axis": -1})
        decay = helper.create_variable_for_type_inference(param.dtype, True)
        block.append_op("scale", inputs={"X": sign}, outputs={"Out": decay},
                        attrs={"scale": self._coeff})
        out = helper.create_variable_for_type_inference(grad.dtype, True)
        block.append_op("sum", inputs={"X": [grad, decay]},
                        outputs={"Out": out})
        return out


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer


def append_regularization_ops(params_grads, regularization=None):
    out = []
    for p, g in params_grads:
        reg = getattr(p, "regularizer", None) or regularization
        if reg is None:
            out.append((p, g))
        else:
            out.append((p, reg._append_regularization_op(p, g, p.block)))
    return out
