"""Gradient clipping (reference: python/paddle/fluid/clip.py:137-233)."""
from __future__ import annotations

from typing import List, Optional, Tuple

from .layer_helper import LayerHelper

__all__ = ["GradientClipByValue", "GradientClipByNorm",
           "GradientClipByGlobalNorm", "set_gradient_clip",
           "append_gradient_clip_ops", "error_clip_callback", "ErrorClipByValue"]

_clip_attr = {"__global__": None}


class BaseGradientClipAttr:
    def _append_clip_op(self, block, grad):
        raise NotImplementedError


class ErrorClipByValue:
    def __init__(self, max, min=None):
        self.max = max
        self.min = min if min is not None else -max


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _append_clip_op(self, block, grad):
        helper = LayerHelper("clip_grad")
        out = helper.create_variable_for_type_inference(grad.dtype, True)
        block.append_op("clip", inputs={"X": grad}, outputs={"Out": out},
                        attrs={"min": self.min, "max": self.max})
        return out


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _append_clip_op(self, block, grad):
        helper = LayerHelper("clip_grad_norm")
        out = helper.create_variable_for_type_inference(grad.dtype, True)
        block.append_op("clip_by_norm", inputs={"X": grad},
                        outputs={"Out": out},
                        attrs={"max_norm": self.clip_norm})
        return out


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    """g_i <- g_i * clip_norm / max(global_norm, clip_norm)."""

    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _append_global_ops(self, block, params_grads):
        helper = LayerHelper("global_norm_clip")
        sq_norms = []
        for _, g in params_grads:
            sq = helper.create_variable_for_type_inference(g.dtype, True)
            block.append_op("squared_l2_norm", inputs={"X": g},
                            outputs={"Out": sq})
            sq_norms.append(sq)
        total = helper.create_variable_for_type_inference("float32", True)
        block.append_op("sum", inputs={"X": sq_norms}, outputs={"Out": total})
        gnorm = helper.create_variable_for_type_inference("float32", True)
        block.append_op("sqrt", inputs={"X": total}, outputs={"Out": gnorm})
        clipv = helper.create_variable_for_type_inference("float32", True)
        block.append_op("fill_constant", outputs={"Out": clipv},
                        attrs={"shape": [1], "dtype": "float32",
                               "value": self.clip_norm})
        denom = helper.create_variable_for_type_inference("float32", True)
        block.append_op("elementwise_max", inputs={"X": gnorm, "Y": clipv},
                        outputs={"Out": denom}, attrs={"axis": -1})
        scale = helper.create_variable_for_type_inference("float32", True)
        block.append_op("elementwise_div", inputs={"X": clipv, "Y": denom},
                        outputs={"Out": scale}, attrs={"axis": -1})
        outs = []
        for p, g in params_grads:
            out = helper.create_variable_for_type_inference(g.dtype, True)
            block.append_op("elementwise_mul", inputs={"X": g, "Y": scale},
                            outputs={"Out": out}, attrs={"axis": 0})
            outs.append((p, out))
        return outs


def set_gradient_clip(clip, param_list=None, program=None):
    _clip_attr["__global__"] = clip


def append_gradient_clip_ops(params_grads) -> List[Tuple]:
    clip = _clip_attr.get("__global__")
    if clip is None:
        return params_grads
    block = params_grads[0][0].block
    if isinstance(clip, GradientClipByGlobalNorm):
        return clip._append_global_ops(block, params_grads)
    return [(p, clip._append_clip_op(block, g)) for p, g in params_grads]


def error_clip_callback(block, context):
    pass
