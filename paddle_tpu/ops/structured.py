"""Structured prediction + candidate sampling ops.

Reference kernels, all CPU-loop based, re-derived as vectorized XLA programs:
* linear_chain_crf / crf_decoding — operators/linear_chain_crf_op.h:172
  (ForwardOneSequence: L1-normalized alpha recursion) and
  operators/crf_decoding_op.h (Viterbi). Here the forward runs in log space
  under ``lax.scan`` (the L1 trick exists to stop fp underflow in prob
  space; logsumexp is the numerically-stable equivalent that also
  differentiates cleanly, so the backward is the generic vjp instead of the
  reference's hand-written forward-backward marginals).
* nce — operators/nce_op.h (sampled logistic loss).
* hierarchical_sigmoid — operators/hierarchical_sigmoid_op.h +
  math/matrix_bit_code.h:105 SimpleCode (c = label + C; index(bit) =
  (c >> (bit+1)) - 1; bit(bit) = c & (1 << bit)).
* edit_distance — operators/edit_distance_op.h (Levenshtein DP); the
  anti-diagonal inner dependency becomes a cummin prefix trick so each DP
  row is one vectorized step.
* ctc_align — operators/ctc_align_op.h (merge repeats, drop blanks).
* chunk_eval — operators/chunk_eval_op.h (IOB/IOE/IOBES/plain chunk F1).

Sequence inputs follow the repo's padded + ``@LOD`` lengths encoding
(layers/sequence.py): ops take explicit length tensors.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.scipy.special import logsumexp

from ..core.types import jnp_dtype
from .common import IOSpec, out, register_op, x

# ---------------------------------------------------------------------------
# linear-chain CRF
# ---------------------------------------------------------------------------


def _crf_parts(transition):
    # reference layout (linear_chain_crf_op.h:187-189): row 0 start weights,
    # row 1 end weights, rows 2.. the [D, D] transition matrix
    return transition[0], transition[1], transition[2:]


def _canon_label(label):
    if label.ndim >= 3 and label.shape[-1] == 1:
        label = jnp.squeeze(label, -1)
    return label.astype(jnp.int32)


@register_op("linear_chain_crf",
             inputs=[IOSpec("Emission"), IOSpec("Transition"),
                     IOSpec("Label", no_grad=True),
                     IOSpec("Length", optional=True, no_grad=True)],
             outputs=[IOSpec("Alpha", optional=True),
                      IOSpec("EmissionExps", optional=True),
                      IOSpec("TransitionExps", optional=True),
                      "LogLikelihood"])
def _linear_chain_crf(ctx, ins, attrs):
    """Per-sequence negative log-likelihood (a cost, like the reference:
    ForwardOneSequence returns ``-ll``). Alpha is emitted in LOG space —
    documented deviation from the reference's L1-normalized prob-space
    alpha, which exists only as scratch for its hand-written backward."""
    em, w = x(ins, "Emission"), x(ins, "Transition")
    label = _canon_label(x(ins, "Label"))
    b, t, d = em.shape
    length = x(ins, "Length")
    length = (jnp.full((b,), t, jnp.int32) if length is None
              else length.reshape(-1).astype(jnp.int32))
    start, end, trans = _crf_parts(w)
    mask = jnp.arange(t)[None, :] < length[:, None]            # [B,T]

    # numerator: score of the gold path
    em_gold = jnp.take_along_axis(em, label[..., None], axis=2)[..., 0]
    gold = jnp.sum(em_gold * mask, 1) + start[label[:, 0]]
    if t > 1:
        tr_gold = trans[label[:, :-1], label[:, 1:]]
        gold = gold + jnp.sum(tr_gold * mask[:, 1:], 1)
    last = jnp.clip(length - 1, 0, t - 1)
    last_lbl = jnp.take_along_axis(label, last[:, None], 1)[:, 0]
    gold = gold + end[last_lbl]

    # denominator: log-partition via the alpha recursion
    alpha0 = start[None, :] + em[:, 0]                          # [B,D]

    def step(alpha, xs):
        x_t, m_t = xs
        nxt = logsumexp(alpha[:, :, None] + trans[None], axis=1) + x_t
        nxt = jnp.where(m_t[:, None], nxt, alpha)
        return nxt, nxt

    if t > 1:
        alpha_t, alphas = jax.lax.scan(
            step, alpha0,
            (em[:, 1:].transpose(1, 0, 2), mask[:, 1:].T))
        alpha_full = jnp.concatenate(
            [alpha0[:, None], alphas.transpose(1, 0, 2)], axis=1)
    else:
        alpha_t, alpha_full = alpha0, alpha0[:, None]
    log_z = logsumexp(alpha_t + end[None, :], axis=1)

    nll = (log_z - gold).reshape(b, 1)
    row_max = jnp.max(em, axis=2, keepdims=True)
    return {"Alpha": [alpha_full],
            "EmissionExps": [jnp.exp(em - row_max)],
            "TransitionExps": [jnp.exp(w)],
            "LogLikelihood": [nll]}


@register_op("crf_decoding",
             inputs=[IOSpec("Emission", no_grad=True),
                     IOSpec("Transition", no_grad=True),
                     IOSpec("Label", optional=True, no_grad=True),
                     IOSpec("Length", optional=True, no_grad=True)],
             outputs=["ViterbiPath"], grad=None)
def _crf_decoding(ctx, ins, attrs):
    """Viterbi decode (reference crf_decoding_op.h). With Label given the
    output is the 0/1 per-position correctness mask the reference emits
    (consumed by chunk_eval-style evaluators)."""
    em, w = x(ins, "Emission"), x(ins, "Transition")
    b, t, d = em.shape
    length = x(ins, "Length")
    length = (jnp.full((b,), t, jnp.int32) if length is None
              else length.reshape(-1).astype(jnp.int32))
    start, end, trans = _crf_parts(w)
    mask = jnp.arange(t)[None, :] < length[:, None]

    delta0 = start[None, :] + em[:, 0]
    ident = jnp.broadcast_to(jnp.arange(d)[None, :], (b, d))

    def step(delta, xs):
        x_t, m_t = xs
        scores = delta[:, :, None] + trans[None]                # [B,from,to]
        best = jnp.max(scores, axis=1) + x_t
        bp = jnp.argmax(scores, axis=1).astype(jnp.int32)
        # padded steps: identity backpointers, frozen delta
        bp = jnp.where(m_t[:, None], bp, ident)
        nxt = jnp.where(m_t[:, None], best, delta)
        return nxt, bp

    if t > 1:
        delta_t, bps = jax.lax.scan(
            step, delta0, (em[:, 1:].transpose(1, 0, 2), mask[:, 1:].T))
    else:
        delta_t, bps = delta0, jnp.zeros((0, b, d), jnp.int32)
    last_tag = jnp.argmax(delta_t + end[None, :], axis=1).astype(jnp.int32)

    def back(tag, bp):
        prev = jnp.take_along_axis(bp, tag[:, None], 1)[:, 0]
        return prev, tag

    first_tag, tags = jax.lax.scan(back, last_tag, bps, reverse=True)
    if t > 1:
        path = jnp.concatenate(
            [first_tag[:, None], tags.transpose(1, 0)], axis=1)
    else:
        path = last_tag[:, None]
    path = jnp.where(mask, path, 0).astype(jnp_dtype("int64"))

    label = x(ins, "Label")
    if label is not None:
        lbl = _canon_label(label)
        return out(jnp.where(mask, (path == lbl).astype(jnp_dtype("int64")), 0),
                   "ViterbiPath")
    return out(path, "ViterbiPath")


# ---------------------------------------------------------------------------
# NCE
# ---------------------------------------------------------------------------


def _log_uniform_probs(ids, vocab):
    ids = ids.astype(jnp.float32)
    return jnp.log((ids + 2.0) / (ids + 1.0)) / math.log(vocab + 1.0)


def _nce_sample(key, sampler, shape, vocab):
    if sampler == 1:  # log_uniform (Zipf), reference sampler.h LogUniform
        u = jax.random.uniform(key, shape)
        ids = jnp.exp(u * math.log(vocab + 1.0)) - 1.0
        return jnp.clip(ids.astype(jnp.int32), 0, vocab - 1)
    return jax.random.randint(key, shape, 0, vocab)


@register_op("nce",
             inputs=[IOSpec("Input"), IOSpec("Label", no_grad=True),
                     IOSpec("Weight"), IOSpec("Bias", optional=True),
                     IOSpec("SampleWeight", optional=True, no_grad=True)],
             outputs=["Cost", "SampleLogits", "SampleLabels"],
             attrs={"num_total_classes": 0, "num_neg_samples": 10,
                    "sampler": 0, "seed": 0, "is_sparse": False,
                    "remote_prefetch": False, "custom_neg_classes": []})
def _nce(ctx, ins, attrs):
    """NCE loss, exact reference math (nce_op.h:237-245): o = sigmoid(s),
    b = k * q(class); cost_true = -log(o / (o + b)), cost_neg =
    -log(b / (o + b)) — computed in stable softplus form:
    cost_true = softplus(log(b) + softplus(-s)),
    cost_neg  = softplus(-softplus(-s) - log(b)).
    SampleLogits carries sigmoid(s) like the reference. Sampling uses the
    op's folded PRNG key, so the grad replay (generic vjp re-trace with the
    same uid) draws the SAME negatives — the property the reference gets by
    seeding per-op."""
    inp = x(ins, "Input")
    label = x(ins, "Label").astype(jnp.int32)
    if label.ndim == 1:
        label = label[:, None]
    weight, bias = x(ins, "Weight"), x(ins, "Bias")
    b = inp.shape[0]
    vocab = int(attrs["num_total_classes"])
    k = int(attrs["num_neg_samples"])
    sampler = int(attrs["sampler"])
    if sampler == 2:
        raise NotImplementedError(
            "nce custom_dist sampling: pass sampler=0 (uniform) or 1 "
            "(log_uniform); custom distributions need host-side alias "
            "tables the XLA program cannot consume")
    # explicit seed -> reproducible negatives across runs/programs (the
    # contract sibling RNG ops honor); else the op's folded per-step key
    key = (jax.random.key(int(attrs["seed"])) if attrs.get("seed")
           else ctx.rng())
    neg = _nce_sample(key, sampler, (b, k), vocab)
    num_true = label.shape[1]
    all_ids = jnp.concatenate([label, neg], axis=1)             # [B, nt+k]
    w_rows = weight[all_ids]                                    # [B, nt+k, d]
    logits = jnp.einsum("bd,bsd->bs", inp, w_rows)
    if bias is not None:
        logits = logits + bias[all_ids]
    if sampler == 1:
        q = _log_uniform_probs(all_ids, vocab)
    else:
        q = jnp.full(all_ids.shape, 1.0 / vocab)
    log_b = jnp.log(k * q)
    sp_neg_s = jax.nn.softplus(-logits)             # -log(sigmoid(s))
    cost_true = jax.nn.softplus(log_b + sp_neg_s)   # -log(o / (o + b))
    cost_neg = jax.nn.softplus(-sp_neg_s - log_b)   # -log(b / (o + b))
    cost = (jnp.sum(cost_true[:, :num_true], 1)
            + jnp.sum(cost_neg[:, num_true:], 1))
    sw = x(ins, "SampleWeight")
    if sw is not None:
        cost = cost * sw.reshape(-1)
    return {"Cost": [cost.reshape(b, 1)],
            "SampleLogits": [jax.nn.sigmoid(logits)],
            "SampleLabels": [all_ids.astype(jnp_dtype("int64"))]}


# ---------------------------------------------------------------------------
# hierarchical sigmoid
# ---------------------------------------------------------------------------


@register_op("hierarchical_sigmoid",
             inputs=[IOSpec("X"), IOSpec("W"), IOSpec("Label", no_grad=True),
                     IOSpec("PathTable", optional=True, no_grad=True),
                     IOSpec("PathCode", optional=True, no_grad=True),
                     IOSpec("Bias", optional=True)],
             outputs=["Out", IOSpec("PreOut", optional=True)],
             attrs={"num_classes": 2, "is_sparse": False,
                    "remote_prefetch": False})
def _hierarchical_sigmoid(ctx, ins, attrs):
    """Complete-binary-tree hsigmoid (reference hierarchical_sigmoid_op.h +
    matrix_bit_code.h SimpleCode): heap code c = label + C, path node
    index(j) = (c >> (j+1)) - 1, target bit(j) = (c >> j) & 1, walked for
    floor(log2(c)) levels. Custom trees come in via PathTable/PathCode
    ([B, L] node ids / bits, -1 padded). Loss is the summed sigmoid
    cross-entropy along the path."""
    inp, w = x(ins, "X"), x(ins, "W")
    label = x(ins, "Label").reshape(-1).astype(jnp.int32)
    bias = x(ins, "Bias")
    path_table, path_code = x(ins, "PathTable"), x(ins, "PathCode")
    b = inp.shape[0]
    if path_table is not None:
        idx = path_table.astype(jnp.int32)                      # [B, L]
        bits = path_code.astype(jnp.float32)
        valid = idx >= 0
        idx = jnp.maximum(idx, 0)
    else:
        c = label + int(attrs["num_classes"])                   # heap code
        max_len = max(int(math.ceil(math.log2(int(attrs["num_classes"])))), 1)
        j = jnp.arange(max_len)[None, :]
        # code length = floor(log2(c)); bits walked right-to-left
        length = jnp.floor(jnp.log2(c.astype(jnp.float32))).astype(jnp.int32)
        valid = j < length[:, None]
        idx = jnp.where(valid, (c[:, None] >> (j + 1)) - 1, 0)
        bits = ((c[:, None] >> j) & 1).astype(jnp.float32)
    pre = jnp.einsum("bd,bld->bl", inp, w[idx])                 # [B, L]
    if bias is not None:
        pre = pre + bias.reshape(-1)[idx]
    # sigmoid CE with logits z vs target t: softplus(z) - z*t
    ce = jax.nn.softplus(pre) - pre * bits
    cost = jnp.sum(jnp.where(valid, ce, 0.0), axis=1)
    return {"Out": [cost.reshape(b, 1)],
            "PreOut": [jnp.where(valid, pre, 0.0)]}


# ---------------------------------------------------------------------------
# edit distance
# ---------------------------------------------------------------------------


@register_op("edit_distance",
             inputs=[IOSpec("Hyps", no_grad=True),
                     IOSpec("Refs", no_grad=True),
                     IOSpec("HypsLength", optional=True, no_grad=True),
                     IOSpec("RefsLength", optional=True, no_grad=True)],
             outputs=["Out", "SequenceNum"],
             attrs={"normalized": False}, grad=None)
def _edit_distance(ctx, ins, attrs):
    """Levenshtein distance per sequence pair (reference
    edit_distance_op.h). The classic DP row update has a serial dependency
    through new_row[j-1]; it decomposes as a cummin over (candidate[j] - j)
    so every row is one vectorized step under lax.scan."""
    hyp = x(ins, "Hyps")
    ref = x(ins, "Refs")
    if hyp.ndim == 3 and hyp.shape[-1] == 1:
        hyp = jnp.squeeze(hyp, -1)
    if ref.ndim == 3 and ref.shape[-1] == 1:
        ref = jnp.squeeze(ref, -1)
    b, th = hyp.shape
    tr = ref.shape[1]
    hlen = x(ins, "HypsLength")
    rlen = x(ins, "RefsLength")
    hlen = (jnp.full((b,), th, jnp.int32) if hlen is None
            else hlen.reshape(-1).astype(jnp.int32))
    rlen = (jnp.full((b,), tr, jnp.int32) if rlen is None
            else rlen.reshape(-1).astype(jnp.int32))
    ref_mask = jnp.arange(tr)[None, :] < rlen[:, None]
    row0 = jnp.concatenate(
        [jnp.zeros((b, 1)), jnp.where(ref_mask, 1.0, 0.0).cumsum(1)], axis=1)

    def step(row, xs):
        h_t, active = xs                                        # [B], [B]
        sub = (h_t[:, None] != ref).astype(jnp.float32)
        cand = jnp.minimum(row[:, 1:] + 1.0, row[:, :-1] + sub)  # [B, tr]
        first = row[:, :1] + 1.0                                # j = 0
        m = jnp.concatenate([first, cand], axis=1) - jnp.arange(tr + 1)[None]
        new_row = jax.lax.associative_scan(jnp.minimum, m, axis=1) \
            + jnp.arange(tr + 1)[None]
        new_row = jnp.where(active[:, None], new_row, row)
        return new_row, None

    active = jnp.arange(th)[None, :] < hlen[:, None]
    final_row, _ = jax.lax.scan(step, row0, (hyp.T, active.T))
    dist = jnp.take_along_axis(final_row, rlen[:, None], axis=1)[:, 0]
    # reference: empty ref -> distance = hyp length
    dist = jnp.where(rlen == 0, hlen.astype(dist.dtype), dist)
    if attrs.get("normalized"):
        dist = dist / jnp.maximum(rlen.astype(dist.dtype), 1.0)
    return {"Out": [dist.reshape(b, 1).astype(jnp.float32)],
            "SequenceNum": [jnp.array([b], jnp_dtype("int64"))]}


# ---------------------------------------------------------------------------
# ctc_align
# ---------------------------------------------------------------------------


@register_op("ctc_align",
             inputs=[IOSpec("Input", no_grad=True),
                     IOSpec("InputLength", optional=True, no_grad=True)],
             outputs=["Output", "OutputLength"],
             attrs={"blank": 0, "merge_repeated": True, "padding_value": 0},
             grad=None)
def _ctc_align(ctx, ins, attrs):
    """CTC alignment (reference ctc_align_op.h): merge repeats, drop
    blanks. Output is padded + per-sequence lengths (the repo's LoD
    encoding of the reference's variable-length LoDTensor output)."""
    inp = x(ins, "Input")
    if inp.ndim == 3 and inp.shape[-1] == 1:
        inp = jnp.squeeze(inp, -1)
    b, t = inp.shape
    ilen = x(ins, "InputLength")
    ilen = (jnp.full((b,), t, jnp.int32) if ilen is None
            else ilen.reshape(-1).astype(jnp.int32))
    blank = int(attrs["blank"])
    pad_val = int(attrs.get("padding_value", 0))
    in_range = jnp.arange(t)[None, :] < ilen[:, None]
    keep = (inp != blank) & in_range
    if attrs.get("merge_repeated", True):
        prev = jnp.concatenate(
            [jnp.full((b, 1), -1, inp.dtype), inp[:, :-1]], axis=1)
        keep = keep & ((inp != prev) | ~jnp.concatenate(
            [jnp.zeros((b, 1), bool), in_range[:, :-1]], axis=1))
    pos = jnp.cumsum(keep, axis=1) - 1                          # target slot
    pos = jnp.where(keep, pos, t)                               # drop -> OOB
    outp = jnp.full((b, t), pad_val, inp.dtype)
    bidx = jnp.broadcast_to(jnp.arange(b)[:, None], (b, t))
    outp = outp.at[bidx, pos].set(inp, mode="drop")
    out_len = jnp.sum(keep, axis=1).astype(jnp.int32)
    return {"Output": [outp.astype(jnp_dtype("int64"))], "OutputLength": [out_len]}


# ---------------------------------------------------------------------------
# chunk_eval
# ---------------------------------------------------------------------------

_CHUNK_SCHEMES = {"plain": 0, "IOB": 2, "IOE": 2, "IOBES": 4}


def _chunk_marks(tags, scheme, num_types, seq_mask):
    """(is_begin, is_end, type) per position for a tag sequence under the
    given scheme — vectorized restatement of reference chunk_eval_op.h
    Segment extraction (GetSegments)."""
    n_tag = _CHUNK_SCHEMES[scheme]
    if scheme == "plain":
        ctype = tags
        inside = (tags >= 0) & (tags < num_types) & seq_mask
        tag_kind = None
    else:
        ctype = tags // n_tag
        tag_kind = tags % n_tag
        inside = (ctype < num_types) & (tags >= 0) & seq_mask
    prev_inside = jnp.pad(inside[:, :-1], ((0, 0), (1, 0)))
    prev_type = jnp.pad(ctype[:, :-1], ((0, 0), (1, 0)),
                        constant_values=-1)
    next_inside = jnp.pad(inside[:, 1:], ((0, 0), (0, 1)))
    next_type = jnp.pad(ctype[:, 1:], ((0, 0), (0, 1)),
                        constant_values=-1)
    same_prev = prev_inside & (prev_type == ctype)
    same_next = next_inside & (next_type == ctype)
    if scheme == "plain":
        begin = inside & ~same_prev
        end = inside & ~same_next
    elif scheme == "IOB":                     # B=0, I=1
        is_b = tag_kind == 0
        begin = inside & (is_b | ~same_prev)
        nxt_kind = jnp.pad(tag_kind[:, 1:], ((0, 0), (0, 1)),
                           constant_values=0)
        end = inside & (~same_next | (nxt_kind == 0))
    elif scheme == "IOE":                     # I=0, E=1
        is_e = tag_kind == 1
        prev_kind = jnp.pad(tag_kind[:, :-1], ((0, 0), (1, 0)),
                            constant_values=1)
        begin = inside & (~same_prev | (prev_kind == 1))
        end = inside & (is_e | ~same_next)
    else:                                     # IOBES: B=0,I=1,E=2,S=3
        kind = tag_kind
        begin = inside & ((kind == 0) | (kind == 3))
        end = inside & ((kind == 2) | (kind == 3))
    return begin, end, ctype


def _next_end_pos(end, t):
    """pos[i] = index of the first end >= i (t when none)."""
    idx = jnp.where(end, jnp.arange(t)[None, :], t)
    return jax.lax.associative_scan(jnp.minimum, idx, reverse=True, axis=1)


@register_op("chunk_eval",
             inputs=[IOSpec("Inference", no_grad=True),
                     IOSpec("Label", no_grad=True),
                     IOSpec("SeqLength", optional=True, no_grad=True)],
             outputs=["Precision", "Recall", "F1-Score", "NumInferChunks",
                      "NumLabelChunks", "NumCorrectChunks"],
             attrs={"num_chunk_types": 1, "chunk_scheme": "IOB",
                    "excluded_chunk_types": []}, grad=None)
def _chunk_eval(ctx, ins, attrs):
    inf = x(ins, "Inference")
    lab = x(ins, "Label")
    if inf.ndim == 3 and inf.shape[-1] == 1:
        inf = jnp.squeeze(inf, -1)
    if lab.ndim == 3 and lab.shape[-1] == 1:
        lab = jnp.squeeze(lab, -1)
    inf = inf.astype(jnp.int32)
    lab = lab.astype(jnp.int32)
    b, t = inf.shape
    slen = x(ins, "SeqLength")
    slen = (jnp.full((b,), t, jnp.int32) if slen is None
            else slen.reshape(-1).astype(jnp.int32))
    seq_mask = jnp.arange(t)[None, :] < slen[:, None]
    scheme = attrs.get("chunk_scheme", "IOB")
    num_types = int(attrs["num_chunk_types"])
    excluded = list(attrs.get("excluded_chunk_types") or [])

    ib, ie, it = _chunk_marks(inf, scheme, num_types, seq_mask)
    lb, le, lt = _chunk_marks(lab, scheme, num_types, seq_mask)

    def _not_excluded(ctype):
        ok = jnp.ones(ctype.shape, bool)
        for e in excluded:
            ok = ok & (ctype != e)
        return ok

    n_inf = jnp.sum(ib & _not_excluded(it))
    n_lab = jnp.sum(lb & _not_excluded(lt))
    # a chunk is correct iff both sequences start a chunk at i with the
    # same type and both chunks end at the same position
    correct = (ib & lb & (it == lt) & _not_excluded(it)
               & (_next_end_pos(ie, t) == _next_end_pos(le, t)))
    n_correct = jnp.sum(correct)

    prec = jnp.where(n_inf > 0, n_correct / n_inf, 0.0).astype(jnp.float32)
    rec = jnp.where(n_lab > 0, n_correct / n_lab, 0.0).astype(jnp.float32)
    f1 = jnp.where(prec + rec > 0, 2 * prec * rec / (prec + rec),
                   0.0).astype(jnp.float32)
    as1 = lambda v, dt: jnp.asarray(v, dt).reshape((1,))
    return {"Precision": [as1(prec, jnp.float32)],
            "Recall": [as1(rec, jnp.float32)],
            "F1-Score": [as1(f1, jnp.float32)],
            "NumInferChunks": [as1(n_inf, jnp_dtype("int64"))],
            "NumLabelChunks": [as1(n_lab, jnp_dtype("int64"))],
            "NumCorrectChunks": [as1(n_correct, jnp_dtype("int64"))]}
