"""fused_multihead_attention: the `operators/fused/` role on TPU.

The reference ships hand-fused kernels where op-by-op execution leaves
performance on the table (reference: paddle/fluid/operators/fused/
fused_embedding_fc_lstm_op.cc, fusion_lstm_op.cc; the xbyak JIT framework
operators/jit/kernel_base.h). On TPU the one attention-shaped fusion XLA
cannot do itself — never materialising the [S, S] score matrix — is the
Pallas flash-attention kernel (kernels/flash_attention.py). This op routes:

- TPU backend + supported shapes -> compiled Pallas kernel (in-kernel
  PRNG dropout, online softmax, two-kernel flash backward);
- anything else -> an equivalent primitive composition that XLA fuses as
  well as it can (and which serves as the numerics oracle in tests).

`FLAGS_use_flash_attention` = auto|always|never picks the path explicitly;
`always` off-TPU runs the kernel in interpret mode (slow — test use only).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .common import IOSpec, register_op, x
from .. import flags


def _route(sq: int, sk: int, dropout: float) -> str:
    """'pallas' | 'pallas-interpret' | 'primitive'."""
    from ..kernels import classify_shapes

    mode = flags.flag("use_flash_attention")
    if mode == "never":
        return "primitive"
    kind, reason = classify_shapes(sq, sk)
    if kind == "unsupported":
        if mode == "always":
            raise ValueError(
                f"FLAGS_use_flash_attention=always but seq lengths "
                f"({sq}, {sk}) have no kernel tiling: {reason}")
        return "primitive"
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        return "pallas"
    if mode == "always":
        if dropout > 0.0:
            # loud, not a silent primitive fallback: 'always' is a promise
            # that the kernel runs, and the TPU PRNG the in-kernel dropout
            # needs has no interpret-mode lowering
            raise NotImplementedError(
                "FLAGS_use_flash_attention=always with attn_dropout>0 "
                "requires a TPU backend (in-kernel PRNG dropout)")
        return "pallas-interpret"
    return "primitive"


def _primitive_attention(ctx, q, k, v, bias, causal, scale, dropout,
                         is_test):
    """[BH, S, D] oracle path; matches the kernel semantics exactly."""
    prec = ("highest" if q.dtype == jnp.float32 else "default")
    s = jnp.einsum("bqd,bkd->bqk", q, k, precision=prec) * scale
    if bias is not None:
        H = q.shape[0] // bias.shape[0]
        s = s + jnp.repeat(bias.astype(s.dtype), H, axis=0)[:, None, :]
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        m = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(m[None], s, jnp.asarray(-1e30, s.dtype))
    p = jax.nn.softmax(s, axis=-1)
    if dropout > 0.0 and not is_test:
        keep = jax.random.bernoulli(ctx.rng(), 1.0 - dropout, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout), 0.0)
    return jnp.einsum("bqk,bkd->bqd", p.astype(v.dtype), v, precision=prec)


@register_op("fused_multihead_attention",
             inputs=[IOSpec("Q"), IOSpec("K"), IOSpec("V"),
                     IOSpec("BiasQK", optional=True, no_grad=True)],
             outputs=["Out"],
             attrs={"causal": False, "scale": 0.0, "attn_dropout": 0.0,
                    "is_test": False, "sequence_parallel": False},
             needs_rng=True)
def _fused_mha(ctx, ins, attrs):
    """Q/K/V: [B, num_heads, S, head_dim]. BiasQK: additive key bias,
    [B, S] or [B, 1, 1, S] (the models/bert.py padding-mask encoding).
    scale 0.0 means 1/sqrt(head_dim).

    ``sequence_parallel=True`` lowers onto ring attention over the mesh's
    'sp' axis (parallel/ring_attention.py — K/V blocks rotate via
    lax.ppermute, the online-softmax state combines across ring steps):
    the context-parallel long-sequence path, reachable from the fluid API
    instead of only from the parallel package (VERDICT r4 item 8)."""
    q, k, v = x(ins, "Q"), x(ins, "K"), x(ins, "V")
    bias = x(ins, "BiasQK")
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    scale = attrs["scale"] or float(D) ** -0.5
    dropout = 0.0 if attrs.get("is_test") else float(attrs["attn_dropout"])
    causal = bool(attrs["causal"])

    if attrs.get("sequence_parallel"):
        mesh = ctx.mesh
        if mesh is not None and "sp" in mesh.axis_names \
                and mesh.shape["sp"] > 1:
            if bias is not None:
                raise NotImplementedError(
                    "sequence_parallel attention with BiasQK: fold padding "
                    "into the sequence instead — the ring path has no "
                    "global [B, S] bias plumbing yet")
            if dropout > 0.0:
                raise NotImplementedError(
                    "sequence_parallel attention with attn_dropout>0: the "
                    "ring path's per-block kernels do not coordinate a "
                    "global dropout mask")
            from ..parallel.ring_attention import ring_attention

            o = ring_attention(q.transpose(0, 2, 1, 3),
                               k.transpose(0, 2, 1, 3),
                               v.transpose(0, 2, 1, 3),
                               mesh, seq_axis="sp", causal=causal,
                               scale=scale)
            return {"Out": [o.transpose(0, 2, 1, 3)]}
        # no mesh / degenerate sp axis: a 1-shard ring IS plain attention

    if bias is not None:
        if bias.ndim == 4:          # [B, 1, 1, S]
            bias = bias.reshape(bias.shape[0], bias.shape[-1])
        elif bias.ndim != 2:
            raise ValueError(
                f"BiasQK must be [B, S] or [B, 1, 1, S], got {bias.shape}")

    q3 = q.reshape(B * H, Sq, D)
    k3 = k.reshape(B * H, Sk, D)
    v3 = v.reshape(B * H, Sk, D)
    route = _route(Sq, Sk, dropout)
    if route == "primitive":
        o = _primitive_attention(ctx, q3, k3, v3, bias, causal, scale,
                                 dropout, attrs.get("is_test", False))
    else:
        from ..kernels import flash_attention

        # deterministic seed tied to this op instance: the grad op folds in
        # the forward uid, so backward regenerates identical dropout masks
        seed = jax.lax.convert_element_type(
            jax.random.bits(ctx.rng(), (), jnp.uint32) >> 1, jnp.int32)
        o = flash_attention(q3, k3, v3, bias=bias, causal=causal,
                            scale=scale, dropout_rate=dropout, seed=seed,
                            num_heads=H,
                            interpret=(route == "pallas-interpret"))
    return {"Out": [o.reshape(B, H, Sq, D)]}
