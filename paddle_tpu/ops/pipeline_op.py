"""`pipeline` op: Program-level pipeline parallelism over a 'pp' mesh axis.

The reference cuts the program into device-placed sections streaming scopes
through queues (reference: optimizer.py:2781 PipelineOptimizer,
framework/trainer.h:110 PipelineTrainer, device_worker.h:267 SectionWorker).
Here the repeated stage is a sub-block (authored once via
layers.PipelineRegion); its parameters are [P, ...]-stacked persistable
vars sharded over the mesh's 'pp' axis, so each rank STORES only its
stage's slice — real placement, not annotation theater. Lowering:

- mesh has a 'pp' axis of size num_stages -> parallel/pipeline.py's GPipe
  schedule (shard_map + lax.ppermute of activations between ranks);
- otherwise -> a lax.scan over the stacked leaves (identical math, no
  collectives) — the single-chip / test-mesh path.

The backward closes over the same function with jax.vjp (the while-op
pattern), so reversed ppermutes pipeline the backward automatically.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .common import IOSpec, register_op

EMPTY = "@EMPTY@"


def _stage_closure(ctx, op, env):
    """(x, stacked_tuple) -> y pure function from the sub-block."""
    from ..lowering import lower_block

    sub = ctx.program.blocks[op.attrs["sub_block"]]
    in_name = op.attrs["in_name"]
    out_name = op.attrs["out_name"]
    slice_names = list(op.attrs["param_slices"])

    def stage_fn(leaves, h):
        benv = dict(env)
        benv[in_name] = h
        benv.update(zip(slice_names, leaves))
        lower_block(sub, benv, ctx)
        return benv[out_name]

    return stage_fn


def _pipeline_apply(ctx, op, env, x, stacked):
    from ..parallel.pipeline import pipeline

    P_ = int(op.attrs["num_stages"])
    M = int(op.attrs["num_microbatches"])
    stage_fn = _stage_closure(ctx, op, env)
    mesh = ctx.mesh
    if mesh is not None and "pp" in mesh.axis_names:
        if mesh.shape["pp"] != P_:
            raise ValueError(
                f"pipeline op has num_stages={P_} but the mesh 'pp' axis "
                f"has {mesh.shape['pp']} ranks — they must match (one "
                f"stage per rank)")
        return pipeline(lambda pl, h: stage_fn(pl, h), tuple(stacked), x,
                        mesh, M, place_params=False)

    def body(h, leaves):
        return stage_fn(leaves, h), None

    y, _ = jax.lax.scan(body, x, tuple(stacked))
    return y


def _pipeline_lower(ctx, op, env):
    x = env[op.inputs["X"][0]]
    stacked = [env[n] for n in op.inputs["StackedParams"]]
    env[op.outputs["Out"][0]] = _pipeline_apply(ctx, op, env, x, stacked)


def _pipeline_grad_lower(ctx, op, env):
    """vjp through the whole schedule (the while-grad pattern); grads flow
    to X and every stacked param."""
    x = env[op.inputs["X"][0]]
    stacked = [env[n] for n in op.inputs["StackedParams"]]

    def fn(x_, stacked_):
        return _pipeline_apply(ctx, op, env, x_, list(stacked_))

    y, vjp_fn = jax.vjp(fn, x, tuple(stacked))
    gy_name = op.inputs["Out@GRAD"][0]
    gy = jnp.asarray(env[gy_name]).astype(y.dtype).reshape(y.shape)
    gx, gstacked = vjp_fn(gy)
    for slot, grads in (("X@GRAD", [gx]),
                        ("StackedParams@GRAD", list(gstacked))):
        names = op.outputs.get(slot, [])
        for n, g in zip(names, grads):
            if n != EMPTY:
                env[n] = g


def _pipeline_infer_shape(op, block):
    xv = block._var_recursive(op.inputs["X"][0])
    out = block._var_recursive(op.outputs["Out"][0])
    out.shape = xv.shape
    out.dtype = xv.dtype


register_op("pipeline",
            inputs=[IOSpec("X"), IOSpec("StackedParams", duplicable=True)],
            outputs=["Out"],
            attrs={"sub_block": None, "num_stages": 0,
                   "num_microbatches": 1, "in_name": "", "out_name": "",
                   "param_slices": []},
            grad="auto", grad_lower=_pipeline_grad_lower, raw=True,
            infer_shape=_pipeline_infer_shape)(_pipeline_lower)
