"""Generative-inference ops: fused decode attention over a paged KV cache,
bulk KV writes, last-position gathers and in-program token sampling.

These are the decode-step building blocks of ``models/gpt.py`` and the
serving layer's prefill/decode split (``serving.generate``). Two design
rules shape them:

* **The KV append is fused into the decode attention op** (CODA, PAPERS.md
  arXiv 2605.19269: fold decode-step epilogue work into the fused kernels):
  ``fused_decode_attention`` reads AND writes the cache vars at one op
  index, so ``analysis.liveness.safe_donation_set`` proves the cache
  buffers donatable — the executor updates the multi-megabyte cache in
  place instead of copying it every token, including through
  ``run_chained``'s scan carry. A separate append-then-attend op pair
  would read the cache after its write and the liveness proof would
  (correctly) refuse the donation.
* **Sampling runs in-program** (``sample_token``): the sampled token is a
  program state write, so a whole decode chunk runs as ONE ``run_chained``
  dispatch with no host round-trip per token; seeded through the op-uid
  PRNG discipline, CI runs are deterministic.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .common import IOSpec, register_op, x
from .. import flags
from ..core.types import jnp_dtype


def _route_decode(s_max: int, page_size: int, q_len: int = 1) -> str:
    """'pallas' | 'pallas-interpret' | 'primitive' for a decode/chunk
    shape. ``q_len`` > 1 is the chunked-prefill / speculative-verify
    chunk; the kernel rides one 8-row sublane tile, so chunks past 8
    rows fall back to the primitive path (never an error — the chunk
    size is a scheduling knob, not a hardware contract)."""
    from ..kernels import classify_shapes

    mode = flags.flag("use_flash_attention")
    if mode == "never":
        return "primitive"
    kind, reason = classify_shapes(1, s_max, block_k=page_size)
    if kind != "decode":
        if mode == "always":
            raise ValueError(
                f"FLAGS_use_flash_attention=always but the decode shape "
                f"has no kernel tiling: {reason}")
        return "primitive"
    if q_len > 8:
        return "primitive"
    if jax.default_backend() == "tpu":
        return "pallas"
    return "pallas-interpret" if mode == "always" else "primitive"


@register_op(
    "fused_decode_attention",
    inputs=[IOSpec("Q"), IOSpec("KNew"), IOSpec("VNew"),
            IOSpec("CacheK"), IOSpec("CacheV"),
            IOSpec("Positions", no_grad=True),
            IOSpec("SlotMask", optional=True, no_grad=True)],
    outputs=["Out", "CacheKOut", "CacheVOut"],
    attrs={"scale": 0.0, "page_size": 128},
    grad=None)
def _fused_decode_attention(ctx, ins, attrs):
    """One autoregressive decode/verify chunk, epilogue fused:

    1. append this chunk's K/V rows (``KNew``/``VNew`` [B, H, C, D],
       C = q_len; C == 1 is the classic decode step) into the paged
       caches ([B, H, S_max, D]) at per-sequence ``Positions`` ([B, 1]
       int — the sequence length BEFORE this chunk), one row at a time
       with per-row clamping onto the last cache row;
    2. attend the C query rows against the updated cache with a
       per-sequence, per-row causal length mask (query row i sees keys
       at positions < pos + i + 1 — its own K row and everything before,
       never a later chunk row).

    ``SlotMask`` [B, 1] (optional) keeps un-masked sequences' caches
    bit-untouched — the chunked-prefill and speculative-verify dispatches
    run a subset of slots while their neighbours keep decoding.
    ``CacheKOut``/``CacheVOut`` are the updated caches — program builders
    point them back at the cache vars, making this the one op that reads
    and writes them (the donation-proof shape, see module docstring).
    Retired sequences whose position saturates past S_max - 1 clamp onto
    the last row and their output is garbage by design — the serving
    layer discards it (the last row is never inside a live length mask).
    """
    from ..kernels import (decode_attention_reference, flash_attention_decode,
                           paged_kv_append_rows)

    q, kn, vn = x(ins, "Q"), x(ins, "KNew"), x(ins, "VNew")
    ck, cv = x(ins, "CacheK"), x(ins, "CacheV")
    pos = x(ins, "Positions")
    smask = x(ins, "SlotMask")
    B, H, q_len, D = q.shape
    if q_len < 1:
        raise ValueError(
            f"fused_decode_attention: q_len must be >= 1, got {q_len}")
    S = ck.shape[2]
    page = int(attrs.get("page_size") or 128)
    scale = attrs["scale"] or float(D) ** -0.5
    pos_b = pos.reshape(B).astype(jnp.int32)
    ck2 = paged_kv_append_rows(ck, kn, pos_b)
    cv2 = paged_kv_append_rows(cv, vn, pos_b)
    if smask is not None:
        m = (smask.reshape(B) > 0).reshape((B, 1, 1, 1))
        ck2 = jnp.where(m, ck2, ck)
        cv2 = jnp.where(m, cv2, cv)
    lengths = jnp.minimum(pos_b + 1, S)

    q3 = q.reshape(B * H, q_len, D)
    k3 = ck2.reshape(B * H, S, D)
    v3 = cv2.reshape(B * H, S, D)
    route = _route_decode(S, page, q_len=q_len)
    if route == "primitive":
        o = decode_attention_reference(q3, k3, v3,
                                       jnp.repeat(lengths, H, axis=0), scale)
    else:
        o = flash_attention_decode(
            q3, k3, v3, lengths, scale=scale, num_heads=H,
            page_size=page, interpret=(route == "pallas-interpret"))
    return {"Out": [o.reshape(B, H, q_len, D)],
            "CacheKOut": [ck2], "CacheVOut": [cv2]}


@register_op(
    "kv_cache_append",
    inputs=[IOSpec("Cache"), IOSpec("New"),
            IOSpec("Positions", no_grad=True),
            IOSpec("SlotMask", optional=True, no_grad=True)],
    outputs=["Out"],
    attrs={},
    grad=None)
def _kv_cache_append(ctx, ins, attrs):
    """Bulk KV write: place ``New`` [B, H, L, D] rows into ``Cache``
    [B, H, S_max, D] starting at per-sequence ``Positions`` [B, 1] (the
    prefill path writes a whole prompt, L = prompt bucket, at position 0).
    ``SlotMask`` [B, 1] (optional) keeps un-masked sequences' cache rows
    untouched — the continuous-batching refill writes only the slots being
    prefilled while their neighbours keep decoding. Builders point ``Out``
    back at the cache var: the op reads and writes it at one index, so the
    buffer donates (liveness-proven in-place update)."""
    from ..kernels import paged_kv_append

    cache, new, pos = x(ins, "Cache"), x(ins, "New"), x(ins, "Positions")
    mask = x(ins, "SlotMask")
    B = cache.shape[0]
    upd = paged_kv_append(cache, new, pos.reshape(B))
    if mask is not None:
        m = (mask.reshape(B) > 0).reshape((B,) + (1,) * (cache.ndim - 1))
        upd = jnp.where(m, upd, cache)
    return {"Out": [upd]}


@register_op(
    "spec_accept",
    inputs=[IOSpec("Sampled", no_grad=True),
            IOSpec("Drafts", no_grad=True),
            IOSpec("Start", no_grad=True)],
    outputs=["AcceptLen", "NewTok", "NewPos"],
    attrs={},
    grad=None)
def _spec_accept(ctx, ins, attrs):
    """Speculative-decoding accept rule, in-program (no host round-trip
    between verify and state commit). ``Sampled`` [B, k] int64 holds the
    target model's token at every chunk position: ``Sampled[:, i]`` is
    the token the target emits AFTER seeing the chunk's first ``i + 1``
    tokens. ``Drafts`` [B, k-1] int64 are the draft's proposals (the
    chunk tokens 1..k-1). ``Start`` [B, 1] int is the sequence length
    before the chunk.

    The longest agreeing prefix ``m = |{j : Drafts[:, :j] ==
    Sampled[:, :j]}|`` accepts ``m`` draft tokens plus the target's own
    bonus token ``Sampled[:, m]`` (the in-program fallback: at m == 0
    the dispatch still emits one token, exactly the non-speculative
    step). Outputs: ``AcceptLen`` [B, 1] = m, ``NewTok`` [B, 1] =
    ``Sampled[:, m]``, ``NewPos`` [B, 1] = ``Start + m + 1`` (the new
    sequence length: the chunk's first token plus m accepted drafts are
    now committed cache rows; rejected rows sit past the length mask and
    are overwritten by the next dispatch)."""
    s, d = x(ins, "Sampled"), x(ins, "Drafts")
    start = x(ins, "Start")
    B, k = s.shape
    if d.shape != (B, k - 1):
        raise ValueError(
            f"spec_accept: Drafts must be [B, k-1] = [{B}, {k - 1}] for "
            f"Sampled [B, k] = {tuple(s.shape)}, got {tuple(d.shape)}")
    i64 = jnp_dtype("int64")
    if k == 1:
        m = jnp.zeros((B,), jnp.int32)
    else:
        agree = (s[:, :k - 1] == d).astype(jnp.int32)
        m = jnp.sum(jnp.cumprod(agree, axis=1), axis=1)
    new_tok = jnp.take_along_axis(s, m[:, None].astype(jnp.int32), axis=1)
    new_pos = start.reshape(B, 1).astype(i64) + m[:, None] + 1
    return {"AcceptLen": [m[:, None].astype(i64)],
            "NewTok": [new_tok.astype(i64)],
            "NewPos": [new_pos.astype(i64)]}


@register_op(
    "sequence_gather",
    inputs=[IOSpec("X"), IOSpec("Index", no_grad=True)],
    outputs=["Out"])
def _sequence_gather(ctx, ins, attrs):
    """Per-sequence gather along axis 1: X [B, S, ...], Index [B, 1] ->
    Out [B, ...] = X[b, Index[b]]. The prefill path uses it to pull the
    last real prompt position's hidden state out of a padded batch
    (indices clamp into [0, S-1])."""
    xv, idx = x(ins, "X"), x(ins, "Index")
    B = xv.shape[0]
    i = jnp.clip(idx.reshape(B).astype(jnp.int32), 0, xv.shape[1] - 1)
    i = i.reshape((B, 1) + (1,) * (xv.ndim - 2))
    taken = jnp.take_along_axis(xv, jnp.broadcast_to(
        i, (B, 1) + xv.shape[2:]), axis=1)
    return {"Out": [taken[:, 0]]}


@register_op(
    "sample_token",
    inputs=[IOSpec("Logits", no_grad=True)],
    outputs=["Out"],
    attrs={"strategy": "greedy", "temperature": 1.0, "top_k": 0},
    needs_rng=True,
    grad=None)
def _sample_token(ctx, ins, attrs):
    """Next-token selection from ``Logits`` [B, V] -> ``Out`` [B, 1] int64.

    ``strategy='greedy'`` is pure argmax (deterministic, the CI default);
    ``'sample'`` draws from softmax(logits / temperature), optionally
    truncated to the ``top_k`` highest-probability tokens. The PRNG key is
    the executor's op-uid-folded key, so a fixed ``program.random_seed``
    reproduces the same token sequence run over run."""
    logits = x(ins, "Logits").astype(jnp.float32)
    strategy = str(attrs.get("strategy", "greedy"))
    if strategy == "greedy":
        tok = jnp.argmax(logits, axis=-1)
    elif strategy == "sample":
        temp = max(float(attrs.get("temperature", 1.0)), 1e-6)
        scaled = logits / temp
        k = int(attrs.get("top_k", 0))
        if k > 0:
            k = min(k, scaled.shape[-1])
            thresh = jax.lax.top_k(scaled, k)[0][:, -1:]
            scaled = jnp.where(scaled >= thresh, scaled, -1e30)
        tok = jax.random.categorical(ctx.rng(), scaled, axis=-1)
    else:
        raise ValueError(
            f"sample_token: unknown strategy '{strategy}' "
            f"(expected 'greedy' or 'sample')")
    return {"Out": [tok.astype(jnp_dtype("int64"))[:, None]]}
