"""Control-flow ops: while, conditional_block, recurrent (StaticRNN), tensor
arrays, beam search.

Reference: paddle/fluid/operators/controlflow/while_op.cc (sub-block run in a
loop over StepScopes), conditional_block_op.cc, recurrent_op.cc (static RNN
over time steps with memory vars), tensor_array_read_write.cc,
math/beam_search.cc.

TPU-native redesign — the reference interprets sub-blocks with a nested
Executor and dynamic StepScopes; XLA needs structured control flow:

* ``while``       -> ``lax.while_loop``. Loop state = the op's Out vars (all
                     parent-block vars the body writes). Tensor arrays in the
                     carry become fixed-capacity buffers (see TensorArrayVal).
                     DIFFERENTIABLE when constructed with max_len: the grad
                     op replays the loop as a masked lax.scan under jax.vjp
                     (reference WhileGradOp over saved StepScopes).
* ``conditional_block`` -> ``lax.cond`` with a zero/passthrough else-branch.
* ``recurrent``   -> ``lax.scan`` over the time axis: memories are the carry,
                     step inputs the xs, step outputs the stacked ys. Fully
                     differentiable via a custom vjp grad lowering, so
                     StaticRNN trains (reference recurrent_grad op).
* beam_search     -> dense batched [batch*beam] top-k (the reference's
                     LoD-based variable beams trade away; fixed beam width is
                     the XLA-idiomatic encoding).
"""
from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import IOSpec, register_op
from ..lowering import lower_block
from ..core.types import jnp_dtype
from .common import out, x

EMPTY = "@EMPTY@"


# ---------------------------------------------------------------------------
# Tensor arrays (reference LoDTensorArray + tensor_array_read_write.cc)
# ---------------------------------------------------------------------------

class TensorArrayVal:
    """Value of a LOD_TENSOR_ARRAY var inside the lowering env.

    Two modes:
    * list mode (outside loops): ``entries`` is a Python list, indices are
      trace-time constants — append/overwrite freely.
    * buffer mode (loop carry): fixed ``capacity`` stacked buffer + traced
      ``size``; writes become dynamic_update_slice. XLA requires static
      shapes inside while bodies, so capacity is fixed when the array enters
      a loop (While(max_len=...) or the default capacity).
    """

    def __init__(self, entries=None, buffer=None, size=None):
        self.entries: List[Any] = entries if entries is not None else []
        self.buffer = buffer
        self.size = size

    @property
    def buffered(self) -> bool:
        return self.buffer is not None

    def to_buffer(self, capacity: int) -> "TensorArrayVal":
        if self.buffered:
            return self
        if not self.entries:
            raise ValueError(
                "tensor array entering a While loop has no entries yet — "
                "write the initial element (e.g. array_write at step 0) "
                "before the loop so its element shape is known")
        elem = jnp.asarray(self.entries[0])
        buf = jnp.zeros((capacity,) + elem.shape, elem.dtype)
        for i, e in enumerate(self.entries):
            buf = buf.at[i].set(e)
        return TensorArrayVal(buffer=buf,
                              size=jnp.asarray(len(self.entries), jnp.int32))

    def write(self, i, value) -> "TensorArrayVal":
        if not self.buffered:
            # list mode: under jit even constant indices are tracers, so
            # writes APPEND (overwriting a concrete in-range index when one
            # is available) — the reference LoDTensorArray's append-if-past-
            # end behaviour, with sequential writes assumed otherwise
            entries = list(self.entries)
            if _is_concrete_index(i) and int(np.asarray(i)) < len(entries):
                entries[int(np.asarray(i))] = value
            else:
                entries.append(value)
            return TensorArrayVal(entries=entries)
        i = jnp.asarray(i).reshape(()).astype(jnp.int32)
        buf = jax.lax.dynamic_update_index_in_dim(self.buffer, value, i, 0)
        return TensorArrayVal(buffer=buf, size=jnp.maximum(self.size, i + 1))

    def read(self, i):
        if not self.buffered:
            if _is_concrete_index(i):
                return self.entries[int(np.asarray(i))]
            return jax.lax.dynamic_index_in_dim(
                self.stack(), jnp.asarray(i).reshape(()).astype(jnp.int32),
                0, keepdims=False)
        i = jnp.asarray(i).reshape(()).astype(jnp.int32)
        return jax.lax.dynamic_index_in_dim(self.buffer, i, 0, keepdims=False)

    def length(self):
        if self.buffered:
            return self.size.reshape((1,)).astype(jnp_dtype("int64"))
        return jnp.asarray([len(self.entries)], jnp_dtype("int64"))

    def stack(self):
        """Dense [T, ...] view (T = capacity in buffer mode, padded)."""
        if self.buffered:
            return self.buffer
        return jnp.stack([jnp.asarray(e) for e in self.entries])


_DEFAULT_CAPACITY = 128


def _is_concrete_index(i) -> bool:
    try:
        int(np.asarray(i))
        return True
    except Exception:
        return False


def _ta_flatten(ta):
    if ta.buffered:
        return (ta.buffer, ta.size), ("buffered",)
    return tuple(ta.entries), ("list",)


def _ta_unflatten(aux, children):
    if aux[0] == "buffered":
        return TensorArrayVal(buffer=children[0], size=children[1])
    return TensorArrayVal(entries=list(children))


jax.tree_util.register_pytree_node(TensorArrayVal, _ta_flatten, _ta_unflatten)


@register_op("create_array", outputs=["Out"], attrs={"dtype": "float32"},
             grad=None, infer_shape=lambda op, block: None)
def _create_array(ctx, ins, attrs):
    return out(TensorArrayVal())


@register_op("write_to_array", inputs=["X", IOSpec("I", no_grad=True),
                                       IOSpec("Array", optional=True)],
             outputs=["Out"], grad=None,
             infer_shape=lambda op, block: None)
def _write_to_array(ctx, ins, attrs):
    arr = x(ins, "Array") or TensorArrayVal()
    return out(arr.write(x(ins, "I"), x(ins, "X")))


@register_op("read_from_array", inputs=["X", IOSpec("I", no_grad=True)],
             outputs=["Out"], grad=None, infer_shape=lambda op, block: None)
def _read_from_array(ctx, ins, attrs):
    return out(x(ins, "X").read(x(ins, "I")))


@register_op("lod_array_length", inputs=["X"], outputs=["Out"], grad=None,
             infer_shape=lambda op, block: None)
def _lod_array_length(ctx, ins, attrs):
    return out(x(ins, "X").length())


@register_op("tensor_array_to_tensor", inputs=["X"], outputs=["Out"],
             attrs={"axis": 0}, grad=None,
             infer_shape=lambda op, block: None)
def _tensor_array_to_tensor(ctx, ins, attrs):
    stacked = x(ins, "X").stack()
    ax = attrs.get("axis", 0)
    if ax == 0:
        return out(stacked)
    return out(jnp.moveaxis(stacked, 0, ax))


# ---------------------------------------------------------------------------
# while
# ---------------------------------------------------------------------------

def _as_pred(v):
    return jnp.asarray(v).reshape(()).astype(bool)


def _while_carry(op, env, capacity):
    cond_name = op.inputs["Condition"][0]
    out_names = list(dict.fromkeys(op.outputs.get("Out", [])))
    carry_names = [cond_name] + [n for n in out_names if n != cond_name]
    init = []
    for n in carry_names:
        v = env[n]
        if isinstance(v, TensorArrayVal):
            v = v.to_buffer(capacity)
        init.append(v)
    return carry_names, init


def _while_body(sub, carry_names, env, ctx, capacity):
    def body_fn(carry):
        benv = dict(env)  # outer reads close over (loop-invariant)
        benv.update(zip(carry_names, carry))
        lower_block(sub, benv, ctx)
        new = []
        for n in carry_names:
            v = benv[n]
            if isinstance(v, TensorArrayVal) and not v.buffered:
                v = v.to_buffer(capacity)
            new.append(v)
        return tuple(new)

    return body_fn


def _while_init_key(uid):
    return f"__while_init_{uid}__"


def _while_lower(ctx, op, env):
    program = ctx.program
    sub = program.blocks[op.attrs["sub_block"]]
    max_len = int(op.attrs.get("max_len") or 0)
    capacity = max_len or _DEFAULT_CAPACITY
    carry_names, init = _while_carry(op, env, capacity)
    body_fn = _while_body(sub, carry_names, env, ctx, capacity)

    if max_len > 0:
        # max_len BOUNDS the loop (a counter rides the carry), so the
        # forward while_loop and the grad op's max_len-step masked scan
        # see identical trip counts — otherwise a condition that outlives
        # max_len would make the backward silently differentiate a shorter
        # loop than the forward ran
        def cond_fn(c):
            return _as_pred(c[1][0]) & (c[0] < max_len)

        def body(c):
            return c[0] + 1, body_fn(c[1])

        _, final = jax.lax.while_loop(cond_fn, body,
                                      (jnp.asarray(0, jnp.int32),
                                       tuple(init)))
    else:
        final = jax.lax.while_loop(lambda c: _as_pred(c[0]), body_fn,
                                   tuple(init))
    for n, v in zip(carry_names, final):
        env[n] = v
    # stash the pre-loop carry for the grad op (same trace): the while
    # writes its outputs in place, so the inits are gone from env after this
    env[_while_init_key(op.attrs.get("__uid__", 0))] = (carry_names, init)


def _zero_ct(v):
    """Cotangent of zeros matching a carry leaf (float0 for integer/bool
    leaves, per jax.vjp's convention)."""
    def leaf(a):
        a = jnp.asarray(a)
        if jnp.issubdtype(a.dtype, jnp.inexact):
            return jnp.zeros_like(a)
        return jnp.zeros(a.shape, jax.dtypes.float0)

    return jax.tree.map(leaf, v)


def _while_grad_lower(ctx, op, env):
    """Differentiable bounded while (VERDICT r2 item 8; reference
    while_op.cc WhileGradOp runs the body backward over saved StepScopes).

    The loop is replayed as a lax.scan over max_len iterations with an
    active-mask select — identical values to the forward while_loop, since
    once the condition goes false the carry is frozen — and jax.vjp through
    the scan yields grads for the carried inits and the loop-invariant
    external reads."""
    attrs = op.attrs
    max_len = int(attrs.get("max_len") or 0)
    if max_len <= 0:
        raise ValueError(
            "differentiating a While requires a static iteration bound: "
            "construct it as layers.While(cond, max_len=N) (XLA reverse-mode"
            " needs a fixed trip count to unroll the backward scan over)")
    sub = ctx.program.blocks[attrs["sub_block"]]
    fwd_uid = attrs.get("__fwd_uid__", 0)
    stash = env.get(_while_init_key(fwd_uid))
    if stash is None:
        raise RuntimeError("while_grad lowered without its forward op in "
                           "the same trace")
    carry_names, init = stash
    fwd_ctx = ctx.with_uid(fwd_uid)

    # loop-invariant differentiable external reads (body closure)
    body_reads = [n for n in op.inputs.get("X", [])
                  if n not in carry_names and n in env
                  and not isinstance(env[n], TensorArrayVal)
                  and jnp.issubdtype(jnp.result_type(env[n]), jnp.inexact)]
    # differentiable carry positions (plain float arrays)
    diff_pos = [i for i, v in enumerate(init)
                if not isinstance(v, TensorArrayVal)
                and jnp.issubdtype(jnp.result_type(v), jnp.inexact)]

    def fn(diff_init, read_vals):
        base_env = dict(env)
        base_env.update(zip(body_reads, read_vals))
        cur = list(init)
        for i, v in zip(diff_pos, diff_init):
            cur[i] = v
        body_fn = _while_body(sub, carry_names, base_env, fwd_ctx,
                              max_len)

        def step(carry, _):
            cond = _as_pred(carry[0])
            new = body_fn(carry)
            sel = tuple(
                jax.tree.map(lambda a, b: jnp.where(cond, a, b), n_, o_)
                for n_, o_ in zip(new, carry))
            return sel, None

        final, _ = jax.lax.scan(step, tuple(cur), None, length=max_len)
        return tuple(final[i] for i in diff_pos)

    primal_init = [init[i] for i in diff_pos]
    read_vals = [env[n] for n in body_reads]
    outs, vjp_fn = jax.vjp(fn, primal_init, read_vals)

    # cotangents: Out@GRAD entries aligned with the forward Out list
    grad_of = {}
    for n, g in zip(op.inputs.get("__out__Out", []),
                    op.inputs.get("Out@GRAD", [])):
        if g != EMPTY and n not in grad_of:
            grad_of[n] = g
    cts = []
    for k, i in enumerate(diff_pos):
        n = carry_names[i]
        g = env.get(grad_of.get(n, ""), None)
        if g is None:
            cts.append(_zero_ct(outs[k]))
        else:
            cts.append(jnp.asarray(g).astype(outs[k].dtype)
                       .reshape(outs[k].shape))
    g_init, g_reads = vjp_fn(tuple(cts))

    x_names = op.inputs.get("X", [])
    g_names = op.outputs.get("X@GRAD", [])
    carry_grad = {carry_names[i]: g for i, g in zip(diff_pos, g_init)}
    read_grad = dict(zip(body_reads, g_reads))
    for n, gname in zip(x_names, g_names):
        if gname == EMPTY:
            continue
        g = carry_grad.get(n)
        if g is None:
            g = read_grad.get(n)
        if g is not None:
            env[gname] = g


register_op("while",
            inputs=[IOSpec("X", duplicable=True), IOSpec("Condition")],
            outputs=[IOSpec("Out", duplicable=True),
                     IOSpec("StepScopes", optional=True)],
            attrs={"sub_block": None, "max_len": 0, "is_test": False},
            grad="auto", grad_lower=_while_grad_lower, raw=True,
            infer_shape=lambda op, block: None)(_while_lower)


# ---------------------------------------------------------------------------
# conditional_block
# ---------------------------------------------------------------------------

def _conditional_block_lower(ctx, op, env):
    program = ctx.program
    sub = program.blocks[op.attrs["sub_block"]]
    pred = _as_pred(env[op.inputs["Cond"][0]])
    out_names = list(dict.fromkeys(op.outputs.get("Out", [])))

    def true_fn():
        benv = dict(env)
        lower_block(sub, benv, ctx)
        return tuple(benv[n] for n in out_names)

    shapes = jax.eval_shape(true_fn)

    def false_fn():
        # vars already defined keep their value; fresh outputs are zeros
        # (reference conditional_block leaves them uninitialized; zeros is
        # the defined TPU behaviour)
        vals = []
        for n, s in zip(out_names, shapes):
            v = env.get(n)
            if v is not None and not isinstance(v, TensorArrayVal):
                va = jnp.asarray(v)
                if (tuple(va.shape), va.dtype) != (tuple(s.shape), s.dtype):
                    raise ValueError(
                        f"conditional_block output '{n}': the sub-block "
                        f"produces shape {tuple(s.shape)} dtype {s.dtype} but "
                        f"the pre-existing value (kept when the condition is "
                        f"false) has shape {tuple(va.shape)} dtype {va.dtype}"
                        f" — both branches of a conditional must agree; avoid"
                        f" reshaping/recasting an outer var inside the block")
            vals.append(v if v is not None else jnp.zeros(s.shape, s.dtype))
        return tuple(vals)

    res = jax.lax.cond(pred, true_fn, false_fn)
    for n, v in zip(out_names, res):
        env[n] = v


register_op("conditional_block",
            inputs=[IOSpec("Cond"), IOSpec("Input", duplicable=True,
                                           optional=True)],
            outputs=[IOSpec("Out", duplicable=True),
                     IOSpec("Scope", optional=True)],
            attrs={"sub_block": None, "is_scalar_condition": True},
            grad=None, raw=True,
            infer_shape=lambda op, block: None)(_conditional_block_lower)


# ---------------------------------------------------------------------------
# recurrent (StaticRNN) — lax.scan, differentiable
# ---------------------------------------------------------------------------

def _recurrent_fn(ctx, op):
    """Build fn(xs, init_states, params) -> (stacked_outputs, final_states)
    from the op's sub-block; shared by forward and grad lowerings."""
    sub = ctx.program.blocks[op.attrs["sub_block"]]
    step_in_names = op.attrs["step_input_names"]     # sub-block var names
    pre_names = op.attrs["pre_memory_names"]
    new_names = op.attrs["new_memory_names"]
    step_out_names = op.attrs["step_output_names"]
    param_names = op.inputs.get("Params", [])

    def fn(xs, init_states, params, outer_env):
        def body(carry, xt):
            benv = dict(outer_env)
            benv.update(zip(param_names, params))
            benv.update(zip(pre_names, carry))
            benv.update(zip(step_in_names, xt))
            lower_block(sub, benv, ctx)
            new_carry = tuple(benv[n] for n in new_names)
            ys = tuple(benv[n] for n in step_out_names)
            return new_carry, ys

        final, stacked = jax.lax.scan(body, tuple(init_states), tuple(xs))
        return stacked, final

    return fn


def _recurrent_lower(ctx, op, env):
    fn = _recurrent_fn(ctx, op)
    xs = [env[n] for n in op.inputs.get("Inputs", [])]
    init = [env[n] for n in op.inputs.get("InitStates", [])]
    params = [env[n] for n in op.inputs.get("Params", [])]
    stacked, final = fn(xs, init, params, env)
    for n, v in zip(op.outputs.get("Outputs", []), stacked):
        env[n] = v
    for n, v in zip(op.outputs.get("FinalStates", []), final):
        env[n] = v


def _recurrent_grad_lower(ctx, op, env):
    """Grad of recurrent: vjp through the scan (reference recurrent_grad —
    backward-in-time loop with memory grads — is exactly scan's vjp)."""
    fwd_ctx = ctx.with_uid(op.attrs.get("__fwd_uid__", 0))
    # reconstruct a meta-op view with the forward's slots
    fn = _recurrent_fn(fwd_ctx, _FwdView(op))
    xs = [env[n] for n in op.inputs.get("Inputs", [])]
    init = [env[n] for n in op.inputs.get("InitStates", [])]
    params = [env[n] for n in op.inputs.get("Params", [])]

    def wrapped(xs_, init_, params_):
        stacked, final = fn(xs_, init_, params_, env)
        return tuple(stacked) + tuple(final)

    n_out = len(op.attrs["step_output_names"])
    primal_out, vjp_fn = jax.vjp(wrapped, xs, init, params)
    cts = []
    grad_names = op.inputs.get("Outputs@GRAD", [])
    final_grad_names = op.inputs.get("FinalStates@GRAD", [])
    for i, val in enumerate(primal_out):
        names = grad_names if i < n_out else final_grad_names
        j = i if i < n_out else i - n_out
        g = env.get(names[j]) if j < len(names) and names[j] != EMPTY else None
        if g is None:
            g = jnp.zeros_like(val)
        cts.append(g.astype(val.dtype).reshape(val.shape))
    gx, ginit, gparams = vjp_fn(tuple(cts))
    for slot, grads in (("Inputs", gx), ("InitStates", ginit),
                        ("Params", gparams)):
        names = op.outputs.get(slot + "@GRAD", [])
        for n, g in zip(names, grads):
            if n != EMPTY and g is not None:
                env[n] = g


class _FwdView:
    """Present a recurrent_grad op as its forward op (same attrs carry the
    sub-block + name maps; inputs hold the forward slots untouched)."""

    def __init__(self, grad_op):
        self.attrs = grad_op.attrs
        self.inputs = grad_op.inputs
        self.outputs = {}
        self.block = grad_op.block


register_op("recurrent",
            inputs=[IOSpec("Inputs", duplicable=True, optional=True),
                    IOSpec("InitStates", duplicable=True, optional=True),
                    IOSpec("Params", duplicable=True, optional=True)],
            outputs=[IOSpec("Outputs", duplicable=True),
                     IOSpec("FinalStates", duplicable=True, optional=True)],
            attrs={"sub_block": None, "step_input_names": [],
                   "pre_memory_names": [], "new_memory_names": [],
                   "step_output_names": [], "is_test": False},
            grad="auto", grad_lower=_recurrent_grad_lower, raw=True,
            infer_shape=lambda op, block: None)(_recurrent_lower)


# ---------------------------------------------------------------------------
# beam search (dense batched; reference math/beam_search.cc is LoD-based)
# ---------------------------------------------------------------------------

@register_op("beam_search",
             inputs=[IOSpec("pre_ids"), IOSpec("pre_scores"),
                     IOSpec("ids", optional=True), IOSpec("scores")],
             outputs=["selected_ids", "selected_scores", "parent_idx"],
             attrs={"beam_size": 4, "end_id": 0, "level": 0,
                    "is_accumulated": True}, grad=None)
def _beam_search(ctx, ins, attrs):
    """One beam step. scores: [batch*beam, K] candidate log-probs (already
    accumulated if is_accumulated); pre_ids/pre_scores: [batch*beam, 1].
    Finished beams (pre_id == end_id) propagate with unchanged score.
    Outputs [batch*beam, 1] ids/scores and [batch*beam] parent indices."""
    beam = int(attrs["beam_size"])
    end_id = int(attrs["end_id"])
    pre_ids = x(ins, "pre_ids").reshape(-1)          # [B*beam]
    pre_scores = x(ins, "pre_scores").reshape(-1)
    scores = x(ins, "scores")                         # [B*beam, K]
    ids = x(ins, "ids")
    nbk, k = scores.shape
    batch = nbk // beam
    if ids is None:
        ids = jnp.broadcast_to(jnp.arange(k, dtype=jnp_dtype("int64")), (nbk, k))
    if not attrs.get("is_accumulated", True):
        scores = pre_scores[:, None] + jnp.log(
            jnp.clip(scores, 1e-20, None))
    finished = pre_ids == end_id
    # finished beams contribute exactly one candidate: themselves
    neg_inf = jnp.asarray(-1e9, scores.dtype)
    cand_scores = jnp.where(finished[:, None], neg_inf, scores)
    cand_scores = cand_scores.at[:, 0].set(
        jnp.where(finished, pre_scores, cand_scores[:, 0]))
    cand_ids = jnp.where(finished[:, None], end_id, ids)
    # per source sequence: pick top beam over beam*K candidates
    flat_scores = cand_scores.reshape(batch, beam * k)
    top_scores, top_pos = jax.lax.top_k(flat_scores, beam)   # [B, beam]
    src_beam = top_pos // k                                  # local parent
    within = top_pos % k
    parent = (jnp.arange(batch, dtype=jnp_dtype("int64"))[:, None] * beam
              + src_beam.astype(jnp_dtype("int64")))      # global row
    sel_ids = jnp.take_along_axis(
        cand_ids.reshape(batch, beam * k), top_pos, axis=1)
    return {"selected_ids": [sel_ids.reshape(-1, 1).astype(jnp_dtype("int64"))],
            "selected_scores": [top_scores.reshape(-1, 1)],
            "parent_idx": [parent.reshape(-1)]}


@register_op("beam_search_decode",
             inputs=[IOSpec("Ids"), IOSpec("Scores"),
                     IOSpec("ParentIdx", optional=True)],
             outputs=["SentenceIds", "SentenceScores"],
             attrs={"beam_size": 4, "end_id": 0}, grad=None,
             infer_shape=lambda op, block: None)
def _beam_search_decode(ctx, ins, attrs):
    """Backtrack beam pointers. Ids/Scores/ParentIdx are tensor arrays
    written once per decode step: ids [B*beam,1], parents [B*beam].
    Returns [T, B*beam] id/score matrices read through the parent chain
    (rows beyond a sequence's end hold end_id)."""
    ids_ta, sc_ta, par_ta = x(ins, "Ids"), x(ins, "Scores"), x(ins, "ParentIdx")
    end_id = int(attrs.get("end_id", 0))
    ids = ids_ta.stack()          # [T, B*beam, 1] (T = capacity if buffered)
    scores = sc_ta.stack()
    parents = par_ta.stack()      # [T, B*beam]
    T = ids.shape[0]
    nbk = ids.shape[1]
    ids2 = ids.reshape(T, nbk)
    scores2 = scores.reshape(T, nbk)
    # buffered arrays may have unwritten tail rows (capacity > steps taken):
    # mask them to identity-parent + end_id so backtracking passes through
    if ids_ta.buffered:
        valid = (jnp.arange(T) < ids_ta.size)[:, None]      # [T, 1]
        ident = jnp.broadcast_to(
            jnp.arange(nbk, dtype=parents.dtype), (T, nbk))
        parents = jnp.where(valid, parents, ident)
        ids2 = jnp.where(valid, ids2, end_id)
        scores2 = jnp.where(valid, scores2, 0.0)

    def back(carry, t):
        ptr = carry                       # [B*beam] row to follow at step t
        idt = ids2[t][ptr]
        sct = scores2[t][ptr]
        ptr = parents[t][ptr]
        return ptr, (idt, sct)

    init = jnp.arange(nbk, dtype=jnp_dtype("int64"))
    _, (out_ids, out_scores) = jax.lax.scan(
        back, init, jnp.arange(T - 1, -1, -1))
    # scan walked backwards: reverse to chronological order
    return {"SentenceIds": [out_ids[::-1]],
            "SentenceScores": [out_scores[::-1]]}
