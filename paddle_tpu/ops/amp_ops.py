"""Mixed-precision support ops: gradient finiteness check + loss scaling.

Reference: the AMP decorator's scale/unscale logic
(python/paddle/fluid/contrib/mixed_precision/decorator.py:120-208) which the
reference builds out of isfinite/scale/cast ops; here the two composite steps
are single ops so the whole check lowers to a handful of fused XLA reductions.
bf16 training on TPU does not need loss scaling at all (same exponent range as
fp32) — the machinery exists for fp16-compat API parity and is exercised by
tests with fp16-style dynamic scaling settings.
"""
from __future__ import annotations

import jax.numpy as jnp

from .common import IOSpec, out, register_op, x


@register_op("check_finite_and_unscale",
             inputs=[IOSpec("X", duplicable=True), IOSpec("Scale")],
             outputs=[IOSpec("Out", duplicable=True),
                      IOSpec("FoundInfinite")],
             grad=None, infer_shape=lambda op, block: None)
def _check_finite_and_unscale(ctx, ins, attrs):
    """Out_i = X_i / Scale, zeroed when ANY X_i has a non-finite element;
    FoundInfinite is the bool flag. Zeroing (instead of the reference's
    skip-update) keeps the step a single static XLA program: an optimizer
    step over zero grads leaves params unchanged."""
    from ..core.selected_rows import SelectedRows, is_selected_rows

    xs = ins.get("X", [])
    scale = x(ins, "Scale").reshape(()).astype(jnp.float32)
    found = jnp.zeros((), bool)
    for v in xs:
        vals = v.values if is_selected_rows(v) else v
        found = found | ~jnp.all(jnp.isfinite(vals.astype(jnp.float32)))
    outs = []
    for v in xs:
        if is_selected_rows(v):
            u = (v.values.astype(jnp.float32) / scale).astype(v.values.dtype)
            outs.append(SelectedRows(
                v.rows, jnp.where(found, jnp.zeros_like(u), u), v.height))
            continue
        unscaled = (v.astype(jnp.float32) / scale).astype(v.dtype)
        outs.append(jnp.where(found, jnp.zeros_like(unscaled), unscaled))
    return {"Out": outs, "FoundInfinite": [found.reshape((1,))]}


@register_op("update_loss_scaling",
             inputs=[IOSpec("FoundInfinite"), IOSpec("PrevLossScaling"),
                     IOSpec("InGoodSteps"), IOSpec("InBadSteps")],
             outputs=["LossScaling", "OutGoodSteps", "OutBadSteps"],
             attrs={"incr_every_n_steps": 1000,
                    "decr_every_n_nan_or_inf": 2,
                    "incr_ratio": 2.0, "decr_ratio": 0.5},
             grad=None, infer_shape=lambda op, block: None)
def _update_loss_scaling(ctx, ins, attrs):
    """Dynamic loss-scale state machine (reference decorator.py:167
    update_loss_scaling): grow scale after N consecutive finite steps,
    shrink after M nan/inf steps."""
    found = x(ins, "FoundInfinite").reshape(()).astype(bool)
    scale = x(ins, "PrevLossScaling").reshape(()).astype(jnp.float32)
    good = x(ins, "InGoodSteps").reshape(()).astype(jnp.int32)
    bad = x(ins, "InBadSteps").reshape(()).astype(jnp.int32)
    incr_n = int(attrs["incr_every_n_steps"])
    decr_n = int(attrs["decr_every_n_nan_or_inf"])
    incr, decr = float(attrs["incr_ratio"]), float(attrs["decr_ratio"])

    new_good = jnp.where(found, 0, good + 1)
    new_bad = jnp.where(found, bad + 1, 0)
    do_incr = new_good >= incr_n
    do_decr = new_bad >= decr_n
    new_scale = jnp.where(do_incr, scale * incr,
                          jnp.where(do_decr, jnp.maximum(scale * decr, 1.0),
                                    scale))
    new_good = jnp.where(do_incr | do_decr, 0, new_good)
    new_bad = jnp.where(do_incr | do_decr, 0, new_bad)
    return {"LossScaling": [new_scale.reshape((1,))],
            "OutGoodSteps": [new_good.reshape((1,))],
            "OutBadSteps": [new_bad.reshape((1,))]}
