"""Misc dense ops: tensor utilities, norms, specialty losses, CTR helpers.

Reference kernels cited per op (paddle/fluid/operators/<name>_op.{h,cc}).
All vectorised jnp — no scalar loops — so XLA fuses them.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.types import jnp_dtype
from .common import IOSpec, out, register_op, x


# ---------------------------------------------------------------------------
# tensor utilities
# ---------------------------------------------------------------------------

@register_op("linspace", inputs=[IOSpec("Start", no_grad=True),
                                 IOSpec("Stop", no_grad=True),
                                 IOSpec("Num", no_grad=True)],
             outputs=["Out"], attrs={"dtype": "float32"}, grad=None)
def _linspace(ctx, ins, attrs):
    start = float(np.asarray(x(ins, "Start")).reshape(-1)[0])
    stop = float(np.asarray(x(ins, "Stop")).reshape(-1)[0])
    num = int(np.asarray(x(ins, "Num")).reshape(-1)[0])
    return out(jnp.linspace(start, stop, num,
                            dtype=jnp_dtype(attrs["dtype"])))


@register_op("fill", outputs=["Out"],
             attrs={"value": [], "shape": [], "dtype": "float32",
                    "force_cpu": False}, grad=None)
def _fill(ctx, ins, attrs):
    """reference fill_op.cc: fill Out with an explicit value list."""
    vals = jnp.asarray(attrs["value"], jnp_dtype(attrs["dtype"]))
    return out(vals.reshape([int(s) for s in attrs["shape"]]))


@register_op("fill_any_like", inputs=[IOSpec("X", no_grad=True)],
             outputs=["Out"], attrs={"value": 0.0, "dtype": -1}, grad=None)
def _fill_any_like(ctx, ins, attrs):
    xv = x(ins)
    dt = xv.dtype if attrs.get("dtype", -1) in (-1, None) \
        else jnp_dtype(attrs["dtype"])
    return out(jnp.full(xv.shape, attrs["value"], dt))


@register_op("fill_zeros_like2", inputs=[IOSpec("X", no_grad=True)],
             outputs=["Out"], attrs={"dtype": -1}, grad=None)
def _fill_zeros_like2(ctx, ins, attrs):
    return out(jnp.zeros_like(x(ins)))


@register_op("multiplex", inputs=[IOSpec("Ids", no_grad=True),
                                  IOSpec("X", duplicable=True)],
             outputs=["Out"])
def _multiplex(ctx, ins, attrs):
    """reference multiplex_op.h: row r of Out = row r of X[Ids[r]]."""
    ids = jnp.asarray(x(ins, "Ids")).reshape(-1).astype(jnp.int32)
    stack = jnp.stack(ins["X"])                    # [K, N, ...]
    rows = jnp.arange(stack.shape[1])
    return out(stack[ids, rows])


@register_op("strided_slice",
             inputs=[IOSpec("Input"),
                     IOSpec("StartsTensor", optional=True, no_grad=True),
                     IOSpec("EndsTensor", optional=True, no_grad=True),
                     IOSpec("StridesTensor", optional=True, no_grad=True)],
             outputs=["Out"],
             attrs={"axes": [], "starts": [], "ends": [], "strides": [],
                    "infer_flags": [], "decrease_axis": []})
def _strided_slice(ctx, ins, attrs):
    xv = x(ins, "Input")

    def grab(name, key):
        t = x(ins, name)
        return ([int(v) for v in np.asarray(t).reshape(-1)]
                if t is not None else [int(v) for v in attrs[key]])

    axes = [int(a) for a in attrs["axes"]]
    starts = grab("StartsTensor", "starts")
    ends = grab("EndsTensor", "ends")
    strides = grab("StridesTensor", "strides") or [1] * len(axes)
    idx = [slice(None)] * xv.ndim
    for a, s, e, st in zip(axes, starts, ends, strides):
        idx[a] = slice(s, e, st)
    res = xv[tuple(idx)]
    for a in sorted([int(d) for d in attrs.get("decrease_axis", [])],
                    reverse=True):
        res = jnp.squeeze(res, a)
    return out(res)


@register_op("unique", inputs=[IOSpec("X", no_grad=True)],
             outputs=["Out", "Index"], attrs={"dtype": "int32"}, grad=None)
def _unique(ctx, ins, attrs):
    """reference unique_op.h: first-occurrence order; Index maps each X
    element to its position in Out. Static-shape encoding: Out is padded
    to len(X) with the first unique value, plus '@COUNT' companioning is
    unnecessary since Index fully determines usage."""
    xv = jnp.asarray(x(ins)).reshape(-1)
    n = xv.shape[0]
    # first-occurrence rank: idx of first equal element
    eq = xv[None, :] == xv[:, None]
    first = jnp.argmax(eq, axis=1)                  # first index with same val
    is_first = first == jnp.arange(n)
    # order of appearance among firsts
    rank = jnp.cumsum(is_first) - 1
    # map each element to rank of its first occurrence
    index = rank[first]
    order = jnp.where(is_first, jnp.arange(n), n)
    perm = jnp.argsort(order)
    uniq = xv[perm]                                 # firsts first, pad tail
    return {"Out": [uniq], "Index": [index.astype(jnp_dtype(
        attrs.get("dtype", "int32")))]}


@register_op("unique_with_counts", inputs=[IOSpec("X", no_grad=True)],
             outputs=["Out", "Index", "Count"], attrs={"dtype": "int32"},
             grad=None)
def _unique_with_counts(ctx, ins, attrs):
    res = _unique(ctx, ins, attrs)
    index = res["Index"][0]
    n = index.shape[0]
    count = jnp.zeros((n,), index.dtype).at[index].add(1)
    res["Count"] = [count]
    return res


@register_op("size", inputs=[IOSpec("Input", no_grad=True)],
             outputs=["Out"], grad=None)
def _size(ctx, ins, attrs):
    return out(jnp.asarray(int(np.prod(x(ins, "Input").shape)), jnp_dtype("int64")))


@register_op("is_empty", inputs=[IOSpec("X", no_grad=True)],
             outputs=["Out"], grad=None)
def _is_empty(ctx, ins, attrs):
    return out(jnp.asarray(int(np.prod(x(ins).shape)) == 0))


@register_op("minus", inputs=["X", "Y"], outputs=["Out"])
def _minus(ctx, ins, attrs):
    return out(x(ins, "X") - x(ins, "Y"))


@register_op("random_crop", inputs=[IOSpec("X", no_grad=True),
                                    IOSpec("Seed", optional=True,
                                           no_grad=True)],
             outputs=["Out", "SeedOut"], attrs={"shape": [], "startup_seed": 0},
             grad=None, needs_rng=True)
def _random_crop(ctx, ins, attrs):
    """reference random_crop_op.h: crop the trailing dims to `shape` at a
    random offset."""
    xv = x(ins, "X")
    shape = [int(s) for s in attrs["shape"]]
    k = len(shape)
    key = (jax.random.key(int(attrs["startup_seed"]))
           if attrs.get("startup_seed") else ctx.rng())
    starts = []
    for i, s in enumerate(shape):
        limit = xv.shape[xv.ndim - k + i] - s
        key, sub = jax.random.split(key)
        starts.append(jax.random.randint(sub, (), 0, max(limit, 0) + 1))
    begin = [0] * (xv.ndim - k) + starts
    sizes = list(xv.shape[:xv.ndim - k]) + shape
    res = jax.lax.dynamic_slice(xv, begin, sizes)
    return {"Out": [res], "SeedOut": [jnp.zeros((1,), jnp_dtype("int64"))]}


# ---------------------------------------------------------------------------
# norms & products
# ---------------------------------------------------------------------------

@register_op("l1_norm", inputs=["X"], outputs=["Out"])
def _l1_norm(ctx, ins, attrs):
    return out(jnp.sum(jnp.abs(x(ins))))


@register_op("norm", inputs=["X"], outputs=["Out", "Norm"],
             attrs={"axis": 1, "epsilon": 1e-10})
def _norm(ctx, ins, attrs):
    """reference norm_op.h: l2-normalize along axis; Norm holds the
    denominators."""
    xv = x(ins)
    nrm = jnp.sqrt(jnp.sum(xv * xv, axis=attrs["axis"], keepdims=True)
                   + attrs["epsilon"])
    return {"Out": [xv / nrm], "Norm": [nrm]}


@register_op("squared_l2_distance", inputs=["X", "Y"],
             outputs=["sub_result", "Out"])
def _squared_l2_distance(ctx, ins, attrs):
    xv, yv = x(ins, "X"), x(ins, "Y")
    sub = xv - yv
    return {"sub_result": [sub],
            "Out": [jnp.sum(sub * sub, axis=tuple(range(1, sub.ndim)),
                            keepdims=sub.ndim > 1).reshape(xv.shape[0], 1)]}


@register_op("bilinear_tensor_product",
             inputs=[IOSpec("X"), IOSpec("Y"), IOSpec("Weight"),
                     IOSpec("Bias", optional=True)],
             outputs=["Out"])
def _bilinear_tensor_product(ctx, ins, attrs):
    """reference bilinear_tensor_product_op.h: out_k = x W_k y^T + b."""
    xv, yv, w = x(ins, "X"), x(ins, "Y"), x(ins, "Weight")
    res = jnp.einsum("bi,kij,bj->bk", xv, w, yv)
    b = x(ins, "Bias")
    if b is not None:
        res = res + b.reshape(1, -1)
    return out(res)


@register_op("fsp", inputs=["X", "Y"], outputs=["Out"])
def _fsp(ctx, ins, attrs):
    """reference fsp_op.h (distillation flow matrix):
    Out[b] = X[b] (CxHW) @ Y[b]^T (HWxC') / (H*W)."""
    xv, yv = x(ins, "X"), x(ins, "Y")
    B, Cx, H, W = xv.shape
    Cy = yv.shape[1]
    xm = xv.reshape(B, Cx, H * W)
    ym = yv.reshape(B, Cy, H * W)
    return out(jnp.einsum("bch,bdh->bcd", xm, ym) / (H * W))


@register_op("add_position_encoding", inputs=["X"], outputs=["Out"],
             attrs={"alpha": 1.0, "beta": 1.0})
def _add_position_encoding(ctx, ins, attrs):
    """reference add_position_encoding_op.h: sinusoid PE added to [B,S,D]."""
    xv = x(ins)
    B, S, D = xv.shape
    half = D // 2
    pos = jnp.arange(S, dtype=xv.dtype)[:, None]
    div = jnp.power(10000.0, jnp.arange(half, dtype=xv.dtype) / half)
    pe = jnp.concatenate([jnp.sin(pos / div), jnp.cos(pos / div)], axis=1)
    return out(attrs["alpha"] * xv + attrs["beta"] * pe[None])


# ---------------------------------------------------------------------------
# specialty losses
# ---------------------------------------------------------------------------

@register_op("modified_huber_loss", inputs=[IOSpec("X"),
                                            IOSpec("Y", no_grad=True)],
             outputs=["IntermediateVal", "Out"])
def _modified_huber_loss(ctx, ins, attrs):
    """reference modified_huber_loss_op.h:40-49."""
    xv, yv = x(ins, "X"), x(ins, "Y")
    inter = xv * (2.0 * yv - 1.0)
    loss = jnp.where(inter < -1.0, -4.0 * inter,
                     jnp.where(inter < 1.0, (1.0 - inter) ** 2, 0.0))
    return {"IntermediateVal": [inter], "Out": [loss]}


@register_op("teacher_student_sigmoid_loss",
             inputs=[IOSpec("X"), IOSpec("Label", no_grad=True)],
             outputs=["Y"],
             attrs={"soft_max_up_bound": 15.0, "soft_max_lower_bound": -15.0})
def _teacher_student_sigmoid_loss(ctx, ins, attrs):
    """reference teacher_student_sigmoid_loss_op.h: label encodes
    click (z) and teacher score (z'): -2 -> no-z' noclick, -1 -> no-z'
    click, [0,1) -> z'+0 noclick, [1,2] -> z'+1 click."""
    xv = x(ins, "X").reshape(-1)
    lbl = x(ins, "Label").reshape(-1).astype(xv.dtype)
    base = jnp.maximum(xv, 0.0) + jnp.log1p(jnp.exp(-jnp.abs(xv)))
    ce0 = base                      # z = 0 term
    ce1 = base - xv                 # z = 1 term
    t0 = base - xv * lbl            # teacher term, noclick
    t1 = base - xv * (lbl - 1.0)    # teacher term, click
    y = jnp.where(lbl < -1.0, ce0,
                  jnp.where(lbl < 0.0, ce1,
                            jnp.where(lbl < 1.0, ce0 + t0, ce1 + t1)))
    return {"Y": [y.reshape(-1, 1)]}


@register_op("center_loss",
             inputs=[IOSpec("X"), IOSpec("Label", no_grad=True),
                     IOSpec("Centers", no_grad=True),
                     IOSpec("CenterUpdateRate", no_grad=True)],
             outputs=["CentersOut", "SampleCenterDiff", "Loss"],
             attrs={"cluster_num": 0, "need_update": True})
def _center_loss(ctx, ins, attrs):
    """reference center_loss_op.h: loss = 0.5*|x - c_y|^2; centers move by
    alpha * mean diff per class."""
    xv = x(ins, "X")
    lbl = jnp.asarray(x(ins, "Label")).reshape(-1).astype(jnp.int32)
    centers = x(ins, "Centers")
    alpha = jnp.asarray(x(ins, "CenterUpdateRate")).reshape(-1)[0]
    diff = xv - centers[lbl]
    loss = 0.5 * jnp.sum(diff * diff, axis=1, keepdims=True)
    if attrs.get("need_update", True):
        acc = jnp.zeros_like(centers).at[lbl].add(diff)
        cnt = jnp.ones((centers.shape[0],), xv.dtype).at[lbl].add(1.0)
        new_centers = centers + alpha * acc / cnt[:, None]
    else:
        new_centers = centers
    return {"CentersOut": [new_centers], "SampleCenterDiff": [diff],
            "Loss": [loss]}


@register_op("cvm", inputs=[IOSpec("X"), IOSpec("CVM", no_grad=True)],
             outputs=["Y"], attrs={"use_cvm": True})
def _cvm(ctx, ins, attrs):
    """reference cvm_op.h:26-39: CTR show/click head columns — either
    log-transform them (use_cvm) or strip them."""
    xv = x(ins, "X")
    if attrs.get("use_cvm", True):
        c0 = jnp.log(xv[:, 0:1] + 1.0)
        c1 = jnp.log(xv[:, 1:2] + 1.0) - c0
        return {"Y": [jnp.concatenate([c0, c1, xv[:, 2:]], axis=1)]}
    return {"Y": [xv[:, 2:]]}


@register_op("data_norm", inputs=[IOSpec("X"),
                                  IOSpec("BatchSize", no_grad=True),
                                  IOSpec("BatchSum", no_grad=True),
                                  IOSpec("BatchSquareSum", no_grad=True)],
             outputs=["Y", "Means", "Scales"],
             attrs={"epsilon": 1e-4})
def _data_norm(ctx, ins, attrs):
    """reference data_norm_op.cc: normalize by accumulated batch stats
    (the CTR streaming-normalisation op)."""
    xv = x(ins, "X")
    n = x(ins, "BatchSize")
    s = x(ins, "BatchSum")
    sq = x(ins, "BatchSquareSum")
    means = s / n
    scales = jnp.sqrt(n / sq)
    return {"Y": [(xv - means) * scales], "Means": [means],
            "Scales": [scales]}


@register_op("sampling_id", inputs=[IOSpec("X", no_grad=True)],
             outputs=["Out"], attrs={"min": 0.0, "max": 1.0, "seed": 0},
             grad=None, needs_rng=True)
def _sampling_id(ctx, ins, attrs):
    """reference sampling_id_op.h: sample column index per row of a prob
    matrix."""
    xv = x(ins)
    key = (jax.random.key(attrs["seed"]) if attrs.get("seed")
           else ctx.rng())
    return out(jax.random.categorical(
        key, jnp.log(jnp.maximum(xv, 1e-20)), axis=1).astype(jnp_dtype("int64")))


@register_op("similarity_focus", inputs=[IOSpec("X", no_grad=True)],
             outputs=["Out"], attrs={"axis": 1, "indexes": []}, grad=None)
def _similarity_focus(ctx, ins, attrs):
    """reference similarity_focus_op.h: for each selected channel, mark the
    (h, w) argmax rows/cols across the other spatial dims with 1."""
    xv = x(ins)
    N, C, H, W = xv.shape
    res = jnp.zeros_like(xv)
    for idx in attrs["indexes"]:
        ch = xv[:, int(idx)]                       # [N, H, W]
        hmax = jnp.argmax(jnp.max(ch, axis=2), axis=1)   # [N]
        wmax = jnp.argmax(jnp.max(ch, axis=1), axis=1)   # [N]
        rows = (jnp.arange(H)[None, :] == hmax[:, None])
        cols = (jnp.arange(W)[None, :] == wmax[:, None])
        mark = (rows[:, :, None] | cols[:, None, :]).astype(xv.dtype)
        res = jnp.maximum(res, mark[:, None, :, :])
    return out(res)


@register_op("hash", inputs=[IOSpec("X", no_grad=True)], outputs=["Out"],
             attrs={"num_hash": 1, "mod_by": 100000000}, grad=None)
def _hash(ctx, ins, attrs):
    """reference hash_op.h (xxhash of int rows): TPU-native stand-in uses a
    multiplicative integer mix per hash seed — same contract (deterministic
    int ids -> [num_hash] buckets), different hash family."""
    xv = jnp.asarray(x(ins)).astype(jnp.uint32)
    flat = xv.reshape(xv.shape[0], -1)
    outs = []
    for i in range(int(attrs["num_hash"])):
        seed = jnp.uint32(0x9E3779B9 + i * 0x85EBCA6B)
        h = jnp.full((flat.shape[0],), seed, jnp.uint32)
        for j in range(flat.shape[1]):
            h = (h ^ flat[:, j]) * jnp.uint32(16777619)
        outs.append(h % jnp.uint32(attrs["mod_by"]))
    res = jnp.stack(outs, axis=1).astype(jnp_dtype("int64"))
    return out(res.reshape(xv.shape[0], int(attrs["num_hash"]), 1))


# ---------------------------------------------------------------------------
# conv-ish specials
# ---------------------------------------------------------------------------

@register_op("row_conv", inputs=[IOSpec("X"), IOSpec("Filter")],
             outputs=["Out"])
def _row_conv(ctx, ins, attrs):
    """reference row_conv_op.cc (lookahead conv for DeepSpeech):
    out[t] = sum_{j<k} x[t+j] * w[j], batch-major [B, T, D]."""
    xv, w = x(ins, "X"), x(ins, "Filter")
    k = w.shape[0]
    B, T, D = xv.shape
    pad = jnp.pad(xv, ((0, 0), (0, k - 1), (0, 0)))
    res = sum(pad[:, j:j + T] * w[j][None, None, :] for j in range(k))
    return out(res)


@register_op("conv_shift", inputs=["X", "Y"], outputs=["Out"])
def _conv_shift(ctx, ins, attrs):
    """reference conv_shift_op.cc: circular correlation of each row of X
    [B, M] with kernel row Y [B, N]."""
    xv, yv = x(ins, "X"), x(ins, "Y")
    B, M = xv.shape
    N = yv.shape[1]
    half = (N - 1) // 2
    idx = (jnp.arange(M)[:, None] + jnp.arange(N)[None, :] - half) % M
    return out(jnp.einsum("bmn,bn->bm", xv[:, idx], yv))


@register_op("label_smooth", inputs=[IOSpec("X"),
                                     IOSpec("PriorDist", optional=True,
                                            no_grad=True)],
             outputs=["Out"], attrs={"epsilon": 0.0})
def _label_smooth(ctx, ins, attrs):
    xv = x(ins, "X")
    eps = attrs["epsilon"]
    prior = x(ins, "PriorDist")
    if prior is None:
        return out((1 - eps) * xv + eps / xv.shape[-1])
    return out((1 - eps) * xv + eps * prior.reshape(1, -1))


@register_op("one_hot_v2", inputs=[IOSpec("X", no_grad=True)],
             outputs=["Out"], attrs={"depth": 1, "dtype": "float32"},
             grad=None)
def _one_hot_v2(ctx, ins, attrs):
    """one_hot minus the trailing-1 requirement (2.x surface)."""
    ids = jnp.asarray(x(ins)).astype(jnp.int32)
    depth = int(attrs["depth"])
    return out(jax.nn.one_hot(ids, depth,
                              dtype=jnp_dtype(attrs["dtype"])))


@register_op("cross_entropy2", inputs=[IOSpec("X"),
                                       IOSpec("Label", no_grad=True)],
             outputs=["Y", "XShape", "MatchX"], attrs={"ignore_index": -100})
def _cross_entropy2(ctx, ins, attrs):
    """reference cross_entropy2_op: hard-label CE that also returns the
    matched probabilities (MatchX) for the grad."""
    xv = x(ins, "X")
    lbl = jnp.asarray(x(ins, "Label")).astype(jnp.int32)
    ignore = attrs.get("ignore_index", -100)
    li = lbl.reshape(lbl.shape[:-1] + (1,)) if lbl.shape[-1:] != (1,) else lbl
    safe = jnp.where(li == ignore, 0, li)
    match = jnp.take_along_axis(xv, safe, axis=-1)
    y = jnp.where(li == ignore, 0.0, -jnp.log(jnp.maximum(match, 1e-20)))
    return {"Y": [y], "XShape": [jnp.asarray(xv.shape, jnp_dtype("int64"))],
            "MatchX": [match]}


@register_op("spectral_norm",
             inputs=[IOSpec("Weight"), IOSpec("U", no_grad=True),
                     IOSpec("V", no_grad=True)],
             outputs=["Out"],
             attrs={"dim": 0, "power_iters": 1, "eps": 1e-12})
def _spectral_norm(ctx, ins, attrs):
    """Weight / sigma_max (reference spectral_norm_op.h): sigma estimated by
    power iteration from the U/V buffers. Deviation from the reference: the
    reference mutates its U/V inputs in place so iterations accumulate
    across steps; here the op is pure — U/V are a warm start and
    ``power_iters`` iterations run per call (raise power_iters for the same
    effect). Iterations run under stop_gradient like the reference."""
    w = x(ins, "Weight")
    u = x(ins, "U").reshape(-1)
    v = x(ins, "V").reshape(-1)
    dim, iters, eps = (int(attrs.get("dim", 0)),
                       int(attrs.get("power_iters", 1)),
                       float(attrs.get("eps", 1e-12)))
    perm = [dim] + [i for i in range(w.ndim) if i != dim]
    mat = jnp.transpose(w, perm).reshape(w.shape[dim], -1)   # [h, rest]

    def norm(a):
        return a / (jnp.linalg.norm(a) + eps)

    u, v = jax.lax.stop_gradient(u), jax.lax.stop_gradient(v)
    for _ in range(max(iters, 0)):
        v = norm(jax.lax.stop_gradient(mat).T @ u)
        u = norm(jax.lax.stop_gradient(mat) @ v)
    sigma = u @ (mat @ v)
    return out(w / sigma)


@register_op("tree_conv",
             inputs=[IOSpec("NodesVector"), IOSpec("EdgeSet", no_grad=True),
                     IOSpec("Filter")],
             outputs=["Out"], attrs={"max_depth": 2})
def _tree_conv(ctx, ins, attrs):
    """Tree-based convolution (reference tree_conv_op.cc, Mou et al.
    TBCNN). The reference builds per-root DFS patches on CPU
    (math/tree2col.cc construct_patch); here the patch sum is re-derived as
    ``max_depth`` powers of the child-adjacency matrix, so the whole op is
    three matmul chains per depth — MXU-friendly and O(d * N^2 * F).

    NodesVector [B, N, F] (node id v -> row v-1), EdgeSet [B, E, 2]
    (parent, child) node-id pairs, 0 = padding, edge order defines sibling
    order. Filter [F, 3, out, k] with the reference's (l, r, t) slot
    layout. Out [B, N, out, k]; roots whose patch is empty produce zeros
    (the reference drops them from its packed output; fixed shapes keep
    them as zero rows)."""
    feats = x(ins, "NodesVector")
    edges = x(ins, "EdgeSet").astype(jnp.int32)
    filt = x(ins, "Filter")
    b, n, f = feats.shape
    e = edges.shape[1]
    m = int(attrs.get("max_depth", 2))
    f_l, f_r, f_t = filt[:, 0], filt[:, 1], filt[:, 2]     # [F, out, k]
    out_sz, k = filt.shape[2], filt.shape[3]

    def one(feat, edge):
        uu, vv = edge[:, 0], edge[:, 1]                    # node ids, 1-based
        live = (uu > 0) & (vv > 0)
        # child adjacency over 0-based rows; dead edges -> dropped
        a = jnp.zeros((n, n), feat.dtype).at[
            jnp.where(live, uu - 1, n),
            jnp.where(live, vv - 1, n)].set(1.0, mode="drop")
        # sibling index (1-based, edge order) and sibling count per child
        same_parent = (uu[None, :] == uu[:, None]) & live[None, :] & \
            live[:, None]
        earlier = jnp.tril(jnp.ones((e, e), bool), k=-1)  # [i,j]=1 iff j<i
        idx_edge = jnp.sum(same_parent & earlier, axis=1) + 1     # [E]
        pclen_edge = jnp.sum(same_parent, axis=1)
        sib_idx = jnp.ones((n,), feat.dtype).at[
            jnp.where(live, vv - 1, n)].set(
            idx_edge.astype(feat.dtype), mode="drop")
        pclen = jnp.ones((n,), feat.dtype).at[
            jnp.where(live, vv - 1, n)].set(
            pclen_edge.astype(feat.dtype), mode="drop")
        tmp = jnp.where(pclen == 1, 0.5, (sib_idx - 1)
                        / jnp.maximum(pclen - 1, 1))
        acc = jnp.zeros((n, out_sz * k), feat.dtype)
        w_l = f_l.reshape(f, -1)
        w_r = f_r.reshape(f, -1)
        w_t = f_t.reshape(f, -1)
        reach = jnp.eye(n, dtype=feat.dtype)               # A^0
        for d in range(m):
            et = (m - d) / m
            xt = reach @ feat                              # [N, F]
            xl = reach @ (tmp[:, None] * feat)
            # at d=0 (the root) eta_l/eta_r carry a (1-eta_t)=0 factor,
            # so the per-node tmp value never contributes there
            el_x = (1 - et) * xl
            er_x = (1 - et) * xt - (1 - et) ** 2 * xl
            acc = acc + et * (xt @ w_t) + el_x @ w_l + er_x @ w_r
            reach = reach @ a
        return acc.reshape(n, out_sz, k)

    return out(jax.vmap(one)(feats, edges))
