"""Neural-net ops: matmul/mul, conv, pooling, normalisation, dropout.

References: paddle/fluid/operators/{mul,matmul,conv,pool,batch_norm,
layer_norm,group_norm,dropout}_op.* — rebuilt on lax conv/dot primitives so
XLA tiles them onto the MXU. Convs run in NCHW logical layout (the reference's
layout) but lax is free to relayout internally for TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .common import IOSpec, out, register_op, x


@register_op("mul", inputs=["X", "Y"], outputs=["Out"],
             attrs={"x_num_col_dims": 1, "y_num_col_dims": 1})
def _mul(ctx, ins, attrs):
    """fc's matmul: X flattened to 2D at x_num_col_dims (reference mul_op.cc)."""
    xv, yv = x(ins, "X"), x(ins, "Y")
    xnc, ync = attrs["x_num_col_dims"], attrs["y_num_col_dims"]
    xs, ys = xv.shape, yv.shape
    x2 = xv.reshape((int(np.prod(xs[:xnc])), int(np.prod(xs[xnc:]))))
    y2 = yv.reshape((int(np.prod(ys[:ync])), int(np.prod(ys[ync:]))))
    res = x2 @ y2
    return out(res.reshape(xs[:xnc] + ys[ync:]))


@register_op("matmul", inputs=["X", "Y"], outputs=["Out"],
             attrs={"transpose_X": False, "transpose_Y": False, "alpha": 1.0})
def _matmul(ctx, ins, attrs):
    xv, yv = x(ins, "X"), x(ins, "Y")
    if attrs["transpose_X"]:
        if xv.ndim == 1:
            pass
        else:
            xv = jnp.swapaxes(xv, -1, -2)
    if attrs["transpose_Y"]:
        if yv.ndim == 1:
            pass
        else:
            yv = jnp.swapaxes(yv, -1, -2)
    res = jnp.matmul(xv, yv)
    if attrs.get("alpha", 1.0) != 1.0:
        res = res * attrs["alpha"]
    return out(res)


def _conv_padding(padding, ksize, dilations):
    return [(p, p) for p in padding]


def _use_nhwc() -> bool:
    """TPU convs want channels on the 128-lane minor dim (NHWC). The API
    stays NCHW (the reference layout); the lowering transposes at the op
    boundary — consecutive conv/pool layers' transposes cancel in XLA, so
    steady-state compute runs NHWC end to end. docs/PERF_NOTES.md has the
    measured effect."""
    from .. import flags

    mode = flags.flag("conv_use_nhwc")
    if mode == "always":
        return True
    if mode == "never":
        return False
    return jax.default_backend() == "tpu"


@register_op("conv2d", inputs=[IOSpec("Input"), IOSpec("Filter"),
                               IOSpec("Bias", optional=True)],
             outputs=["Output"],
             attrs={"strides": [1, 1], "paddings": [0, 0], "dilations": [1, 1],
                    "groups": 1, "use_cudnn": True, "data_format": "NCHW"})
def _conv2d(ctx, ins, attrs):
    inp, flt = x(ins, "Input"), x(ins, "Filter")
    pad = _conv_padding(attrs["paddings"], flt.shape[2:], attrs["dilations"])
    if _use_nhwc():
        res = jax.lax.conv_general_dilated(
            inp.transpose(0, 2, 3, 1), flt.transpose(2, 3, 1, 0),
            window_strides=attrs["strides"], padding=pad,
            rhs_dilation=attrs["dilations"],
            feature_group_count=attrs.get("groups", 1),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ).transpose(0, 3, 1, 2)
    else:
        res = jax.lax.conv_general_dilated(
            inp, flt,
            window_strides=attrs["strides"], padding=pad,
            rhs_dilation=attrs["dilations"],
            feature_group_count=attrs.get("groups", 1),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
    b = x(ins, "Bias")
    if b is not None:
        res = res + b.reshape((1, -1, 1, 1))
    return {"Output": [res]}


@register_op("depthwise_conv2d", inputs=[IOSpec("Input"), IOSpec("Filter"),
                                         IOSpec("Bias", optional=True)],
             outputs=["Output"],
             attrs={"strides": [1, 1], "paddings": [0, 0], "dilations": [1, 1],
                    "groups": 1, "use_cudnn": False, "data_format": "NCHW"})
def _depthwise_conv2d(ctx, ins, attrs):
    return _conv2d(ctx, ins, attrs)


@register_op("conv2d_transpose", inputs=[IOSpec("Input"), IOSpec("Filter"),
                                         IOSpec("Bias", optional=True)],
             outputs=["Output"],
             attrs={"strides": [1, 1], "paddings": [0, 0], "dilations": [1, 1],
                    "groups": 1, "output_size": [], "data_format": "NCHW"})
def _conv2d_transpose(ctx, ins, attrs):
    """Transposed conv as an lhs-dilated forward conv with the spatially
    flipped kernel (reference conv_transpose_op.h col2im semantics):
    out = conv(x dilated by stride, flip(W), padding (k-1)*d - p).
    Verified against a scatter-add oracle (tests/test_ops_nn.py).
    Filter layout is the reference's (in, out/groups, kh, kw)."""
    inp, flt = x(ins, "Input"), x(ins, "Filter")
    strides = attrs["strides"]
    dil = attrs["dilations"]
    pads = attrs["paddings"]
    k = flt.shape[2:]
    pad = [((k[i] - 1) * dil[i] - pads[i],) * 2 for i in range(2)]
    groups = attrs.get("groups", 1)
    if groups != 1:
        raise NotImplementedError("conv2d_transpose groups>1 not supported")
    wf = jnp.flip(flt, (2, 3))
    if _use_nhwc():
        res = jax.lax.conv_general_dilated(
            inp.transpose(0, 2, 3, 1), wf.transpose(2, 3, 0, 1),
            window_strides=(1, 1), padding=pad,
            lhs_dilation=strides, rhs_dilation=dil,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ).transpose(0, 3, 1, 2)
    else:
        res = jax.lax.conv_general_dilated(
            inp, wf, window_strides=(1, 1), padding=pad,
            lhs_dilation=strides, rhs_dilation=dil,
            dimension_numbers=("NCHW", "IOHW", "NCHW"),
        )
    b = x(ins, "Bias")
    if b is not None:
        res = res + b.reshape((1, -1, 1, 1))
    return {"Output": [res]}


@register_op("pool2d", inputs=["X"], outputs=["Out"],
             attrs={"pooling_type": "max", "ksize": [2, 2], "strides": [2, 2],
                    "paddings": [0, 0], "global_pooling": False,
                    "exclusive": True, "adaptive": False, "ceil_mode": False,
                    "use_cudnn": True, "data_format": "NCHW"})
def _pool2d(ctx, ins, attrs):
    xv = x(ins)
    ksize = list(attrs["ksize"])
    strides = list(attrs["strides"])
    pads = list(attrs["paddings"])
    in_hw = list(xv.shape[2:])
    if attrs.get("global_pooling") or (attrs.get("adaptive")
                                       and ksize == [1, 1]):
        ksize = in_hw
        strides = list(ksize)
        pads = [0, 0]
    elif attrs.get("adaptive"):
        if all(d % o == 0 for d, o in zip(in_hw, ksize)):
            # uniform regions: adaptive == fixed-window pool (window = D/o)
            strides = [d // o for d, o in zip(in_hw, ksize)]
            ksize, pads = list(strides), [0, 0]
        else:
            return out(_adaptive_pool2d(xv, ksize, attrs["pooling_type"]))
    # ceil_mode adds right/bottom padding so the last partial window counts
    # (reference pooling.cc output size ceil((in - k + 2p)/s) + 1)
    extra = [0, 0]
    if attrs.get("ceil_mode") and not attrs.get("global_pooling"):
        for i in range(2):
            out_ceil = -(-(in_hw[i] - ksize[i] + 2 * pads[i]) // strides[i]) + 1
            extra[i] = max(
                0, (out_ceil - 1) * strides[i] + ksize[i]
                - (in_hw[i] + 2 * pads[i]))
    nhwc = _use_nhwc()
    if nhwc:
        xv = xv.transpose(0, 2, 3, 1)   # keep the conv chain in NHWC
        window = (1,) + tuple(ksize) + (1,)
        strd = (1,) + tuple(strides) + (1,)
        padding = ((0, 0),) + tuple(
            (p, p + e) for p, e in zip(pads, extra)) + ((0, 0),)
    else:
        window = (1, 1) + tuple(ksize)
        strd = (1, 1) + tuple(strides)
        padding = ((0, 0), (0, 0)) + tuple(
            (p, p + e) for p, e in zip(pads, extra))
    if attrs["pooling_type"] == "max":
        init = -jnp.inf
        res = jax.lax.reduce_window(xv, init, jax.lax.max, window, strd, padding)
    else:
        summed = jax.lax.reduce_window(xv, 0.0, jax.lax.add, window, strd, padding)
        if attrs.get("exclusive", True) and (any(p > 0 for p in pads)
                                             or any(e > 0 for e in extra)):
            ones = jnp.ones_like(xv)
            count = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strd, padding)
            res = summed / count
        else:
            res = summed / float(np.prod(ksize))
    if nhwc:
        res = res.transpose(0, 3, 1, 2)
    return out(res)


def _adaptive_pool2d(xv, out_hw, pooling_type):
    """General adaptive pooling: region i spans [floor(i*D/o), ceil((i+1)*D/o)).
    Regions are non-uniform, so reduce_window cannot express it; out_hw is a
    static attr, so a Python loop over output cells traces to a fixed graph."""
    in_h, in_w = xv.shape[2:]
    oh, ow = out_hw
    reduce_fn = jnp.max if pooling_type == "max" else jnp.mean
    rows = []
    for i in range(oh):
        h0, h1 = (i * in_h) // oh, -((-(i + 1) * in_h) // oh)
        cols = []
        for j in range(ow):
            w0, w1 = (j * in_w) // ow, -((-(j + 1) * in_w) // ow)
            cols.append(reduce_fn(xv[:, :, h0:h1, w0:w1], axis=(2, 3)))
        rows.append(jnp.stack(cols, axis=-1))
    return jnp.stack(rows, axis=-2)


@register_op("batch_norm",
             inputs=[IOSpec("X"), IOSpec("Scale"), IOSpec("Bias"),
                     IOSpec("Mean"), IOSpec("Variance")],
             outputs=["Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"],
             attrs={"momentum": 0.9, "epsilon": 1e-5, "is_test": False,
                    "use_global_stats": False, "data_layout": "NCHW"})
def _batch_norm(ctx, ins, attrs):
    """Reference batch_norm_op.cc. Running stats update happens by writing the
    MeanOut/VarianceOut outputs, which alias the Mean/Variance persistable
    vars in the program — the env-threading in lowering.py makes that an
    in-place-style update without mutation."""
    xv = x(ins, "X")
    scale, bias = x(ins, "Scale"), x(ins, "Bias")
    mean, var = x(ins, "Mean"), x(ins, "Variance")
    eps, mom = attrs["epsilon"], attrs["momentum"]
    layout = attrs.get("data_layout", "NCHW")
    axes = (0, 2, 3) if (xv.ndim == 4 and layout == "NCHW") else tuple(
        i for i in range(xv.ndim) if i != xv.ndim - 1
    ) if layout == "NHWC" else (0,)
    use_global = attrs.get("is_test") or attrs.get("use_global_stats")
    if use_global:
        use_mean, use_var = mean, var
        mean_out, var_out = mean, var
        saved_mean = jnp.zeros_like(mean)
        saved_var = jnp.zeros_like(var)
    else:
        use_mean = jnp.mean(xv, axis=axes)
        use_var = jnp.var(xv, axis=axes)
        mean_out = mean * mom + use_mean * (1 - mom)
        var_out = var * mom + use_var * (1 - mom)
        saved_mean = use_mean
        saved_var = 1.0 / jnp.sqrt(use_var + eps)
    bshape = [1] * xv.ndim
    c_axis = 1 if layout == "NCHW" else xv.ndim - 1
    bshape[c_axis] = xv.shape[c_axis]
    rs = lambda t: t.reshape(bshape)
    y = (xv - rs(use_mean)) * rs(1.0 / jnp.sqrt(use_var + eps)) * rs(scale) + rs(bias)
    return {"Y": [y], "MeanOut": [mean_out], "VarianceOut": [var_out],
            "SavedMean": [saved_mean], "SavedVariance": [saved_var]}


@register_op("layer_norm",
             inputs=[IOSpec("X"), IOSpec("Scale", optional=True),
                     IOSpec("Bias", optional=True)],
             outputs=["Y", "Mean", "Variance"],
             attrs={"epsilon": 1e-5, "begin_norm_axis": 1})
def _layer_norm(ctx, ins, attrs):
    xv = x(ins, "X")
    scale, bias = x(ins, "Scale"), x(ins, "Bias")
    bna = attrs["begin_norm_axis"]
    axes = tuple(range(bna, xv.ndim))
    mean = jnp.mean(xv, axis=axes, keepdims=True)
    var = jnp.var(xv, axis=axes, keepdims=True)
    y = (xv - mean) / jnp.sqrt(var + attrs["epsilon"])
    if scale is not None:
        y = y * scale.reshape((1,) * bna + xv.shape[bna:])
    if bias is not None:
        y = y + bias.reshape((1,) * bna + xv.shape[bna:])
    lead = int(np.prod(xv.shape[:bna]))
    return {"Y": [y], "Mean": [mean.reshape((lead,))],
            "Variance": [var.reshape((lead,))]}


@register_op("group_norm",
             inputs=[IOSpec("X"), IOSpec("Scale", optional=True),
                     IOSpec("Bias", optional=True)],
             outputs=["Y", "Mean", "Variance"],
             attrs={"epsilon": 1e-5, "groups": 1})
def _group_norm(ctx, ins, attrs):
    xv = x(ins, "X")
    n, c = xv.shape[0], xv.shape[1]
    g = attrs["groups"]
    xg = xv.reshape((n, g, c // g) + xv.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    y = ((xg - mean) / jnp.sqrt(var + attrs["epsilon"])).reshape(xv.shape)
    scale, bias = x(ins, "Scale"), x(ins, "Bias")
    bshape = (1, c) + (1,) * (xv.ndim - 2)
    if scale is not None:
        y = y * scale.reshape(bshape)
    if bias is not None:
        y = y + bias.reshape(bshape)
    return {"Y": [y], "Mean": [mean.reshape((n, g))],
            "Variance": [var.reshape((n, g))]}


@register_op("instance_norm",
             inputs=[IOSpec("X"), IOSpec("Scale", optional=True),
                     IOSpec("Bias", optional=True)],
             outputs=["Y", "SavedMean", "SavedVariance"],
             attrs={"epsilon": 1e-5})
def _instance_norm(ctx, ins, attrs):
    xv = x(ins, "X")
    axes = tuple(range(2, xv.ndim))
    mean = jnp.mean(xv, axis=axes, keepdims=True)
    var = jnp.var(xv, axis=axes, keepdims=True)
    y = (xv - mean) / jnp.sqrt(var + attrs["epsilon"])
    scale, bias = x(ins, "Scale"), x(ins, "Bias")
    bshape = (1, xv.shape[1]) + (1,) * (xv.ndim - 2)
    if scale is not None:
        y = y * scale.reshape(bshape)
    if bias is not None:
        y = y + bias.reshape(bshape)
    n, c = xv.shape[0], xv.shape[1]
    return {"Y": [y], "SavedMean": [mean.reshape((n * c,))],
            "SavedVariance": [(1.0 / jnp.sqrt(var + attrs["epsilon"])).reshape((n * c,))]}


@register_op("dropout", inputs=["X"], outputs=["Out", "Mask"],
             attrs={"dropout_prob": 0.5, "is_test": False, "seed": 0,
                    "fix_seed": False,
                    "dropout_implementation": "downgrade_in_infer"},
             needs_rng=True)
def _dropout(ctx, ins, attrs):
    """The grad op recomputes this under vjp with the SAME ctx key (fwd uid is
    folded in), so the mask is bit-identical between forward and backward."""
    xv = x(ins)
    p = attrs["dropout_prob"]
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    if attrs.get("is_test"):
        y = xv * (1.0 - p) if impl == "downgrade_in_infer" else xv
        return {"Out": [y], "Mask": [jnp.ones_like(xv)]}
    key = jax.random.key(attrs["seed"]) if attrs.get("fix_seed") else ctx.rng()
    keep = jax.random.bernoulli(key, 1.0 - p, xv.shape)
    mask = keep.astype(xv.dtype)
    y = xv * mask
    if impl == "upscale_in_train" and p < 1.0:
        y = y / (1.0 - p)
    return {"Out": [y], "Mask": [mask]}


@register_op("l2_normalize", inputs=["X"], outputs=["Out", "Norm"],
             attrs={"axis": -1, "epsilon": 1e-12})
def _l2_normalize(ctx, ins, attrs):
    xv = x(ins)
    norm = jnp.sqrt(jnp.sum(jnp.square(xv), axis=attrs["axis"], keepdims=True)
                    + attrs["epsilon"])
    return {"Out": [xv / norm], "Norm": [norm]}


@register_op("prelu", inputs=["X", "Alpha"], outputs=["Out"],
             attrs={"mode": "all"})
def _prelu(ctx, ins, attrs):
    xv, alpha = x(ins, "X"), x(ins, "Alpha")
    mode = attrs.get("mode", "all")
    if mode == "channel":
        alpha = alpha.reshape((1, -1) + (1,) * (xv.ndim - 2))
    elif mode == "element":
        alpha = alpha.reshape((1,) + xv.shape[1:])
    elif mode == "all":
        alpha = alpha.reshape(())
    return out(jnp.where(xv > 0, xv, alpha * xv))


@register_op("interpolate_nearest", inputs=["X"], outputs=["Out"],
             attrs={"out_h": 0, "out_w": 0, "align_corners": False})
def _interp_nearest(ctx, ins, attrs):
    xv = x(ins)
    n, c = xv.shape[:2]
    return out(jax.image.resize(
        xv, (n, c, attrs["out_h"], attrs["out_w"]), method="nearest"))


@register_op("bilinear_interp", inputs=["X"], outputs=["Out"],
             attrs={"out_h": 0, "out_w": 0, "align_corners": True})
def _bilinear_interp(ctx, ins, attrs):
    xv = x(ins)
    n, c = xv.shape[:2]
    return out(jax.image.resize(
        xv, (n, c, attrs["out_h"], attrs["out_w"]), method="bilinear"))
