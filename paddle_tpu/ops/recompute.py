"""Gradient checkpointing (recompute) as a program transform + one raw op.

Reference: python/paddle/fluid/optimizer.py:3074 RecomputeOptimizer and
backward.py:555 _append_backward_ops_with_checkpoints_ — the reference
re-emits each forward segment's ops inside the backward pass so activations
between user checkpoints are freed and rebuilt.

TPU-native design: the segment becomes ONE ``recompute_segment`` op holding
the original forward ops in a sub-block. Its lowering runs the sub-block
under ``jax.vjp(jax.checkpoint(seg_fn), ...)``:

* residuals saved across the fwd→bwd gap are exactly the segment INPUTS
  (checkpoint tensors + params) — jax.checkpoint marks every internal value
  as recompute-on-backward, and emits the recompute behind an optimization
  barrier so XLA CSE cannot merge it back with the forward pass (the failure
  mode of naive op-duplication remat);
* the vjp closure is handed to the matching ``recompute_segment_grad`` op
  through the trace environment — both ops lower inside the same jit trace,
  so the linearization is shared and the forward is never computed twice at
  trace level.

RNG-consuming ops (dropout) replay bit-identically: jax.checkpoint re-traces
the same function, and every op's PRNG key is derived from its stable
``__uid__`` (lowering.LowerCtx.rng).
"""
from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from ..core.registry import IOSpec, register_op
from ..lowering import EMPTY_VAR_NAME, lower_block

__all__ = ["insert_recompute_segments"]


def _is_inexact(v) -> bool:
    return v is not None and jnp.issubdtype(jnp.result_type(v), jnp.inexact)


def _vjp_key(uid: int) -> str:
    return f"__recompute_vjp_{uid}__"


def _recompute_segment_lower(ctx, op, env):
    sub = ctx.program.blocks[op.attrs["sub_block"]]
    in_names = op.inputs.get("Input", [])
    out_names = op.outputs.get("Out", [])
    diff = [n for n in in_names if _is_inexact(env.get(n))]

    def seg_fn(diff_vals):
        benv = dict(env)
        benv.update(zip(diff, diff_vals))
        lower_block(sub, benv, ctx)
        return tuple(benv[n] for n in out_names)

    primals = tuple(env[n] for n in diff)
    outs, vjp_fn = jax.vjp(jax.checkpoint(seg_fn), primals)
    for n, v in zip(out_names, outs):
        env[n] = v
    # hand the shared linearization to the grad op (same trace); keyed by the
    # forward op's uid, which the grad op carries as __fwd_uid__
    env[_vjp_key(op.attrs.get("__uid__", 0))] = (vjp_fn, diff, outs)


def _recompute_segment_grad_lower(ctx, op, env):
    entry = env.get(_vjp_key(op.attrs.get("__fwd_uid__", 0)))
    if entry is None:
        raise RuntimeError(
            "recompute_segment_grad lowered without its forward op in the "
            "same trace — the program was cut between forward and backward")
    vjp_fn, diff, fwd_outs = entry
    grad_in = op.inputs.get("Out@GRAD", [])
    cts = []
    for i, val in enumerate(fwd_outs):
        if not _is_inexact(val):
            # integer/bool segment outputs take float0 cotangents per vjp
            cts.append(jnp.zeros(jnp.shape(val), jax.dtypes.float0))
            continue
        g = env.get(grad_in[i]) if (i < len(grad_in)
                                    and grad_in[i] != EMPTY_VAR_NAME) else None
        if g is None:
            g = jnp.zeros_like(val)
        else:
            g = g.astype(val.dtype).reshape(val.shape)
        cts.append(g)
    (grads,) = vjp_fn(tuple(cts))
    grad_map = dict(zip(diff, grads))
    in_names = op.inputs.get("Input", [])
    for n, gname in zip(in_names, op.outputs.get("Input@GRAD", [])):
        if gname == EMPTY_VAR_NAME:
            continue
        g = grad_map.get(n)
        if g is not None:
            env[gname] = g


register_op("recompute_segment",
            inputs=[IOSpec("Input", duplicable=True, optional=True)],
            outputs=[IOSpec("Out", duplicable=True)],
            attrs={"sub_block": None},
            grad="auto", grad_lower=_recompute_segment_grad_lower, raw=True,
            infer_shape=lambda op, block: None)(_recompute_segment_lower)


def insert_recompute_segments(loss, checkpoints, extra_live=()) -> int:
    """Rewrite ``loss``'s block: forward ops up to each checkpoint collapse
    into ``recompute_segment`` ops. Returns the number of segments created.

    Must run BEFORE append_backward (RecomputeOptimizer.backward does). Vars
    internal to a segment are demoted to sub-block locals — they no longer
    exist between forward and backward, which is the entire point; fetching
    them from user code stops working (same trade the reference makes).

    ``extra_live`` names are treated as observed-after-every-cut (kept as
    segment outputs, never demoted): the auto-remat pass
    (analysis/remat.py) passes fetch names and optimizer-tail reads here so
    a TRANSPARENT transform never breaks a fetch the manual API is allowed
    to break.
    """
    block = loss.block
    program = block.program
    ckpt_names = {c.name if hasattr(c, "name") else c for c in checkpoints}

    ops = list(block.ops)
    producer = {}
    for i, o in enumerate(ops):
        for n in o.output_arg_names:
            if n in ckpt_names:
                producer[n] = i
    cuts = sorted({producer[n] for n in ckpt_names if n in producer})
    if not cuts:
        return 0

    # names read after each cut index, plus names that must survive:
    # checkpoints themselves, persistables, the loss. One reverse sweep,
    # snapshotting the running read-set only at the cut positions.
    keep_always = set(ckpt_names) | {loss.name} | {
        n for n in extra_live if n != EMPTY_VAR_NAME}
    reads_after_cut = {}
    running: set = set()
    cut_set = set(cuts)
    for i in range(len(ops) - 1, -1, -1):
        if i in cut_set:
            reads_after_cut[i] = set(running)
        running.update(n for n in ops[i].input_arg_names
                       if n != EMPTY_VAR_NAME)

    new_ops: List = []
    start = 0
    n_segments = 0
    for cut in cuts:
        seg = ops[start:cut + 1]
        rest_reads = reads_after_cut[cut]
        if len(seg) <= 1:
            # a 1-op segment saves nothing; leave it inline
            new_ops.extend(seg)
            start = cut + 1
            continue
        produced: List[str] = []
        for o in seg:
            for n in o.output_arg_names:
                if n != EMPTY_VAR_NAME and n not in produced:
                    produced.append(n)
        reads: List[str] = []
        produced_set = set(produced)
        for o in seg:
            for n in o.input_arg_names:
                if (n != EMPTY_VAR_NAME and n not in produced_set
                        and n not in reads):
                    reads.append(n)
        outs = [n for n in produced
                if n in rest_reads or n in keep_always
                or (block.has_var(n) and block.var(n).persistable)]

        sub = program._create_block(parent_idx=block.idx)
        program._rollback()
        for o in seg:
            o.block = sub
        sub.ops = seg
        # demote internals to sub-block locals so _block_io-style analyses
        # and the executor's liveness never see them at the parent level
        for n in produced:
            if n not in outs and block.has_var(n):
                sub.vars[n] = block.vars.pop(n)

        from ..framework import Operator

        seg_op = Operator(block, "recompute_segment",
                          inputs={"Input": reads}, outputs={"Out": outs},
                          attrs={"sub_block": sub.idx})
        block._stamp(seg_op)  # stable __uid__ + op-role
        new_ops.append(seg_op)
        n_segments += 1
        start = cut + 1
    new_ops.extend(ops[start:])
    block.ops = new_ops
    program._bump_version()
    return n_segments
