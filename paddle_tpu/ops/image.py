"""Image/vision ops: sampling grids, shuffles, interpolation, 3-D conv/pool.

Reference kernels: paddle/fluid/operators/{grid_sampler,pixel_shuffle,
affine_grid,affine_channel,shuffle_channel,space_to_depth,temporal_shift,
unfold,lrn,crop,pad_constant_like,spp,conv3d,pool3d}_op.* — rebuilt on jnp
gather/reshape/conv primitives (vectorised, no scalar loops) so XLA tiles
them for the TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .common import IOSpec, out, register_op, x


# ---------------------------------------------------------------------------
# sampling
# ---------------------------------------------------------------------------

@register_op("grid_sampler", inputs=["X", "Grid"], outputs=["Output"],
             attrs={"padding_mode": "zeros", "mode": "bilinear",
                    "align_corners": True})
def _grid_sampler(ctx, ins, attrs):
    """reference grid_sampler_op.h: sample X [N,C,H,W] at normalized
    [-1,1] grid coords [N,Hg,Wg,2]. mode: bilinear|nearest; padding_mode:
    zeros|border|reflection."""
    xv, grid = x(ins, "X"), x(ins, "Grid")
    N, C, H, W = xv.shape
    mode = attrs.get("mode", "bilinear")
    pad = attrs.get("padding_mode", "zeros")
    if mode not in ("bilinear", "nearest") or pad not in (
            "zeros", "border", "reflection"):
        raise NotImplementedError(
            f"grid_sampler mode={mode} padding_mode={pad}")
    gx, gy = grid[..., 0], grid[..., 1]
    if attrs.get("align_corners", True):
        fx = (gx + 1.0) * (W - 1) / 2.0
        fy = (gy + 1.0) * (H - 1) / 2.0
    else:
        fx = ((gx + 1.0) * W - 1.0) / 2.0
        fy = ((gy + 1.0) * H - 1.0) / 2.0

    def reflect(f, n):
        # reflect about [0, n-1] with period 2(n-1) (align_corners reflect)
        if n == 1:
            return jnp.zeros_like(f)
        period = 2.0 * (n - 1)
        f = jnp.abs(jnp.mod(f, period))
        return jnp.where(f > n - 1, period - f, f)

    if pad == "reflection":
        fx, fy = reflect(fx, W), reflect(fy, H)

    def gather(yy, xx):
        okx = (xx >= 0) & (xx <= W - 1)
        oky = (yy >= 0) & (yy <= H - 1)
        xi = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
        yi = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
        # [N,Hg,Wg] indices into [N,C,H,W] -> [N,C,Hg,Wg]
        v = jax.vmap(lambda img, yb, xb: img[:, yb, xb])(xv, yi, xi)
        if pad == "zeros":
            v = jnp.where((okx & oky)[:, None, :, :], v, 0.0)
        # border/reflection: the clip above IS the padding rule
        return v

    if mode == "nearest":
        return {"Output": [gather(jnp.round(fy), jnp.round(fx))]}

    x0 = jnp.floor(fx)
    y0 = jnp.floor(fy)
    wx = fx - x0
    wy = fy - y0
    v00 = gather(y0, x0)
    v01 = gather(y0, x0 + 1)
    v10 = gather(y0 + 1, x0)
    v11 = gather(y0 + 1, x0 + 1)
    wxb = wx[:, None]
    wyb = wy[:, None]
    res = (v00 * (1 - wxb) * (1 - wyb) + v01 * wxb * (1 - wyb)
           + v10 * (1 - wxb) * wyb + v11 * wxb * wyb)
    return {"Output": [res]}


@register_op("affine_grid", inputs=[IOSpec("Theta"),
                                    IOSpec("OutputShape", optional=True,
                                           no_grad=True)],
             outputs=["Output"],
             attrs={"use_cudnn": True, "align_corners": True,
                    "output_shape": []})
def _affine_grid(ctx, ins, attrs):
    """reference affine_grid_op.h: theta [N,2,3] -> sampling grid
    [N,H,W,2] of normalized coords."""
    theta = x(ins, "Theta")
    shape = x(ins, "OutputShape")
    if shape is not None:
        hw = [int(v) for v in np.asarray(shape).reshape(-1)]
    else:
        hw = [int(v) for v in attrs["output_shape"]]
    H, W = hw[-2], hw[-1]
    if attrs.get("align_corners", True):
        xs = jnp.linspace(-1.0, 1.0, W)
        ys = jnp.linspace(-1.0, 1.0, H)
    else:
        xs = (jnp.arange(W) * 2 + 1) / W - 1.0
        ys = (jnp.arange(H) * 2 + 1) / H - 1.0
    gx, gy = jnp.meshgrid(xs, ys)
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1)            # [H,W,3]
    res = jnp.einsum("hwk,nck->nhwc", base, theta)       # [N,H,W,2]
    return {"Output": [res]}


@register_op("pixel_shuffle", inputs=["X"], outputs=["Out"],
             attrs={"upscale_factor": 1})
def _pixel_shuffle(ctx, ins, attrs):
    """reference pixel_shuffle_op.h: [N, C*r^2, H, W] -> [N, C, H*r, W*r]."""
    xv = x(ins)
    r = int(attrs["upscale_factor"])
    N, C, H, W = xv.shape
    c = C // (r * r)
    v = xv.reshape(N, c, r, r, H, W)
    v = v.transpose(0, 1, 4, 2, 5, 3)
    return out(v.reshape(N, c, H * r, W * r))


@register_op("affine_channel",
             inputs=[IOSpec("X"), IOSpec("Scale"), IOSpec("Bias")],
             outputs=["Out"], attrs={"data_layout": "NCHW"})
def _affine_channel(ctx, ins, attrs):
    xv, s, b = x(ins, "X"), x(ins, "Scale"), x(ins, "Bias")
    if attrs.get("data_layout", "NCHW") == "NCHW":
        shape = (1, -1) + (1,) * (xv.ndim - 2)
    else:
        shape = (1,) * (xv.ndim - 1) + (-1,)
    return out(xv * s.reshape(shape) + b.reshape(shape))


@register_op("shuffle_channel", inputs=["X"], outputs=["Out"],
             attrs={"group": 1})
def _shuffle_channel(ctx, ins, attrs):
    xv = x(ins)
    g = int(attrs["group"])
    N, C, H, W = xv.shape
    v = xv.reshape(N, g, C // g, H, W).swapaxes(1, 2)
    return out(v.reshape(N, C, H, W))


@register_op("space_to_depth", inputs=["X"], outputs=["Out"],
             attrs={"blocksize": 1})
def _space_to_depth(ctx, ins, attrs):
    xv = x(ins)
    b = int(attrs["blocksize"])
    N, C, H, W = xv.shape
    v = xv.reshape(N, C, H // b, b, W // b, b)
    v = v.transpose(0, 3, 5, 1, 2, 4)
    return out(v.reshape(N, C * b * b, H // b, W // b))


@register_op("temporal_shift", inputs=["X"], outputs=["Out"],
             attrs={"seg_num": 1, "shift_ratio": 0.25})
def _temporal_shift(ctx, ins, attrs):
    """reference temporal_shift_op.h: shift 1/4 channels fwd/back in time."""
    xv = x(ins)
    T = int(attrs["seg_num"])
    ratio = float(attrs["shift_ratio"])
    NT, C, H, W = xv.shape
    N = NT // T
    v = xv.reshape(N, T, C, H, W)
    c1 = int(C * ratio)
    c2 = int(C * 2 * ratio)
    pad = jnp.zeros_like(v[:, :1])
    back = jnp.concatenate([v[:, 1:, :c1], pad[:, :, :c1]], axis=1)
    fwd = jnp.concatenate([pad[:, :, c1:c2], v[:, :-1, c1:c2]], axis=1)
    keep = v[:, :, c2:]
    res = jnp.concatenate([back, fwd, keep], axis=2)
    return out(res.reshape(NT, C, H, W))


@register_op("unfold", inputs=["X"], outputs=["Y"],
             attrs={"kernel_sizes": [3, 3], "strides": [1, 1],
                    "paddings": [0, 0, 0, 0], "dilations": [1, 1]})
def _unfold(ctx, ins, attrs):
    """reference unfold_op.h (im2col): [N,C,H,W] -> [N, C*kh*kw, L]."""
    xv = x(ins)
    kh, kw = attrs["kernel_sizes"]
    sh, sw = attrs["strides"]
    pads = attrs["paddings"]
    dh, dw = attrs["dilations"]
    N, C, H, W = xv.shape
    ph0, pw0, ph1, pw1 = (pads + pads)[:4] if len(pads) == 2 else pads
    xp = jnp.pad(xv, ((0, 0), (0, 0), (ph0, ph1), (pw0, pw1)))
    oh = (H + ph0 + ph1 - (dh * (kh - 1) + 1)) // sh + 1
    ow = (W + pw0 + pw1 - (dw * (kw - 1) + 1)) // sw + 1
    cols = []
    for i in range(kh):
        for j in range(kw):
            patch = jax.lax.dynamic_slice_in_dim(
                jax.lax.dynamic_slice_in_dim(xp, i * dh, (oh - 1) * sh + 1, 2),
                j * dw, (ow - 1) * sw + 1, 3)
            cols.append(patch[:, :, ::sh, ::sw])
    res = jnp.stack(cols, axis=2)                  # [N,C,kh*kw,oh,ow]
    return {"Y": [res.reshape(N, C * kh * kw, oh * ow)]}


@register_op("im2sequence", inputs=[IOSpec("X"),
                                    IOSpec("Y", optional=True, no_grad=True)],
             outputs=["Out"],
             attrs={"kernels": [1, 1], "strides": [1, 1],
                    "paddings": [0, 0, 0, 0], "out_stride": [1, 1]})
def _im2sequence(ctx, ins, attrs):
    """reference im2sequence_op.h: sliding windows as a sequence
    [N*oh*ow, C*kh*kw] (batch-major flattened; LoD handled by the padded
    encoding upstream)."""
    xv = x(ins, "X")
    kh, kw = attrs["kernels"]
    cols = _unfold(ctx, {"X": [xv]},
                   {"kernel_sizes": attrs["kernels"],
                    "strides": attrs["strides"],
                    "paddings": attrs["paddings"],
                    "dilations": [1, 1]})["Y"][0]
    N, CKK, L = cols.shape
    res = cols.transpose(0, 2, 1).reshape(N * L, CKK)
    return out(res)


@register_op("lrn", inputs=["X"], outputs=["Out", "MidOut"],
             attrs={"n": 5, "k": 2.0, "alpha": 1e-4, "beta": 0.75})
def _lrn(ctx, ins, attrs):
    """reference lrn_op.h: local response norm across channels."""
    xv = x(ins)
    n, k = int(attrs["n"]), attrs["k"]
    alpha, beta = attrs["alpha"], attrs["beta"]
    sq = xv * xv
    half = n // 2
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    acc = sum(pad[:, i:i + xv.shape[1]] for i in range(n))
    mid = k + alpha * acc
    return {"Out": [xv / jnp.power(mid, beta)], "MidOut": [mid]}


@register_op("crop", inputs=[IOSpec("X"), IOSpec("Y", optional=True,
                                                 no_grad=True),
                             IOSpec("Offsets", optional=True, no_grad=True)],
             outputs=["Out"], attrs={"offsets": [], "shape": []})
def _crop(ctx, ins, attrs):
    xv = x(ins, "X")
    yv = x(ins, "Y")
    shape = (list(yv.shape) if yv is not None
             else [int(s) for s in attrs["shape"]])
    offs_in = x(ins, "Offsets")
    offs = ([int(v) for v in np.asarray(offs_in).reshape(-1)]
            if offs_in is not None else
            ([int(v) for v in attrs["offsets"]] or [0] * xv.ndim))
    idx = tuple(slice(o, o + s) for o, s in zip(offs, shape))
    return out(xv[idx])


@register_op("crop_tensor",
             inputs=[IOSpec("X"),
                     IOSpec("Shape", optional=True, no_grad=True),
                     IOSpec("Offsets", optional=True, no_grad=True)],
             outputs=["Out"], attrs={"offsets": [], "shape": []})
def _crop_tensor(ctx, ins, attrs):
    shape_in = x(ins, "Shape")
    attrs = dict(attrs)
    if shape_in is not None:
        attrs["shape"] = [int(v) for v in np.asarray(shape_in).reshape(-1)]
    return _crop(ctx, {"X": ins["X"], "Offsets": ins.get("Offsets")}, attrs)


@register_op("pad_constant_like", inputs=[IOSpec("X", no_grad=True),
                                          IOSpec("Y")],
             outputs=["Out"], attrs={"pad_value": 0.0})
def _pad_constant_like(ctx, ins, attrs):
    xv, yv = x(ins, "X"), x(ins, "Y")
    pads = [(0, xd - yd) for xd, yd in zip(xv.shape, yv.shape)]
    return out(jnp.pad(yv, pads, constant_values=attrs["pad_value"]))


@register_op("spp", inputs=["X"], outputs=["Out"],
             attrs={"pyramid_height": 2, "pooling_type": "max"})
def _spp(ctx, ins, attrs):
    """reference spp_op.h: spatial pyramid pooling -> [N, C*sum(4^l)]."""
    xv = x(ins)
    N, C = xv.shape[:2]
    outs = []
    for level in range(int(attrs["pyramid_height"])):
        bins = 2 ** level
        H, W = xv.shape[2:]
        # adaptive bins: region [floor(i*H/b), ceil((i+1)*H/b))
        rows = []
        for i in range(bins):
            h0, h1 = (i * H) // bins, -((-(i + 1) * H) // bins)
            for j in range(bins):
                w0, w1 = (j * W) // bins, -((-(j + 1) * W) // bins)
                reg = xv[:, :, h0:h1, w0:w1]
                rows.append(reg.max(axis=(2, 3))
                            if attrs["pooling_type"] == "max"
                            else reg.mean(axis=(2, 3)))
        outs.append(jnp.stack(rows, axis=-1).reshape(N, -1))
    return out(jnp.concatenate(outs, axis=1))


@register_op("unpool", inputs=[IOSpec("X"), IOSpec("Indices", no_grad=True)],
             outputs=["Out"],
             attrs={"unpooling_type": "max", "ksize": [2, 2],
                    "strides": [2, 2], "paddings": [0, 0]})
def _unpool(ctx, ins, attrs):
    """reference unpool_op.h: scatter pooled values back by saved indices."""
    xv, idx = x(ins, "X"), x(ins, "Indices")
    N, C, H, W = xv.shape
    oh = (H - 1) * attrs["strides"][0] - 2 * attrs["paddings"][0] + \
        attrs["ksize"][0]
    ow = (W - 1) * attrs["strides"][1] - 2 * attrs["paddings"][1] + \
        attrs["ksize"][1]
    flat = jnp.zeros((N, C, oh * ow), xv.dtype)
    res = jax.vmap(jax.vmap(
        lambda f, v, i: f.at[i.reshape(-1)].set(v.reshape(-1))))(
            flat, xv, idx.astype(jnp.int32))
    return out(res.reshape(N, C, oh, ow))


@register_op("max_pool2d_with_index", inputs=["X"], outputs=["Out", "Mask"],
             attrs={"ksize": [2, 2], "strides": [2, 2], "paddings": [0, 0],
                    "global_pooling": False, "adaptive": False})
def _max_pool2d_with_index(ctx, ins, attrs):
    """reference pool_with_index_op: max pool + argmax indices (flattened
    per-channel H*W offsets, the unpool contract)."""
    xv = x(ins)
    N, C, H, W = xv.shape
    kh, kw = attrs["ksize"]
    sh, sw = attrs["strides"]
    ph, pw = attrs["paddings"]
    if attrs.get("global_pooling"):
        kh, kw, sh, sw, ph, pw = H, W, H, W, 0, 0
    oh = (H + 2 * ph - kh) // sh + 1
    ow = (W + 2 * pw - kw) // sw + 1
    neg = jnp.asarray(-jnp.inf, xv.dtype)
    xp = jnp.pad(xv, ((0, 0), (0, 0), (ph, ph), (pw, pw)),
                 constant_values=neg)
    pos = jnp.arange(H * W).reshape(H, W)
    pos = jnp.pad(pos, ((ph, ph), (pw, pw)), constant_values=-1)
    patches = []
    ppos = []
    for i in range(kh):
        for j in range(kw):
            patches.append(xp[:, :, i:i + oh * sh:sh, j:j + ow * sw:sw])
            ppos.append(pos[i:i + oh * sh:sh, j:j + ow * sw:sw])
    stack = jnp.stack(patches, axis=-1)            # [N,C,oh,ow,k]
    posst = jnp.stack(ppos, axis=-1)               # [oh,ow,k]
    amax = jnp.argmax(stack, axis=-1)
    res = jnp.max(stack, axis=-1)
    idx = posst[jnp.arange(oh)[:, None], jnp.arange(ow)[None, :]][
        None, None].repeat(N, 0).repeat(C, 1)
    mask = jnp.take_along_axis(idx, amax[..., None], axis=-1)[..., 0]
    return {"Out": [res], "Mask": [mask.astype(jnp.int32)]}


# ---------------------------------------------------------------------------
# 3-D conv / pool
# ---------------------------------------------------------------------------

@register_op("conv3d", inputs=[IOSpec("Input"), IOSpec("Filter"),
                               IOSpec("Bias", optional=True)],
             outputs=["Output"],
             attrs={"strides": [1, 1, 1], "paddings": [0, 0, 0],
                    "dilations": [1, 1, 1], "groups": 1,
                    "data_format": "NCDHW"})
def _conv3d(ctx, ins, attrs):
    inp, flt = x(ins, "Input"), x(ins, "Filter")
    res = jax.lax.conv_general_dilated(
        inp, flt, window_strides=attrs["strides"],
        padding=[(p, p) for p in attrs["paddings"]],
        rhs_dilation=attrs["dilations"],
        feature_group_count=attrs.get("groups", 1),
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
    b = x(ins, "Bias")
    if b is not None:
        res = res + b.reshape((1, -1, 1, 1, 1))
    return {"Output": [res]}


@register_op("conv3d_transpose", inputs=[IOSpec("Input"), IOSpec("Filter"),
                                         IOSpec("Bias", optional=True)],
             outputs=["Output"],
             attrs={"strides": [1, 1, 1], "paddings": [0, 0, 0],
                    "dilations": [1, 1, 1], "groups": 1})
def _conv3d_transpose(ctx, ins, attrs):
    """Same lhs-dilated formulation as conv2d_transpose (ops/nn.py)."""
    inp, flt = x(ins, "Input"), x(ins, "Filter")
    k = flt.shape[2:]
    dil = attrs["dilations"]
    pads = attrs["paddings"]
    pad = [((k[i] - 1) * dil[i] - pads[i],) * 2 for i in range(3)]
    wf = jnp.flip(flt, (2, 3, 4))
    res = jax.lax.conv_general_dilated(
        inp, wf, window_strides=(1, 1, 1), padding=pad,
        lhs_dilation=attrs["strides"], rhs_dilation=dil,
        dimension_numbers=("NCDHW", "IODHW", "NCDHW"))
    b = x(ins, "Bias")
    if b is not None:
        res = res + b.reshape((1, -1, 1, 1, 1))
    return {"Output": [res]}


@register_op("pool3d", inputs=["X"], outputs=["Out"],
             attrs={"pooling_type": "max", "ksize": [2, 2, 2],
                    "strides": [2, 2, 2], "paddings": [0, 0, 0],
                    "global_pooling": False, "exclusive": True,
                    "adaptive": False, "ceil_mode": False})
def _pool3d(ctx, ins, attrs):
    xv = x(ins)
    ksize = list(attrs["ksize"])
    strides = list(attrs["strides"])
    pads = list(attrs["paddings"])
    if attrs.get("global_pooling"):
        ksize = list(xv.shape[2:])
        strides = list(ksize)
        pads = [0, 0, 0]
    window = (1, 1) + tuple(ksize)
    strd = (1, 1) + tuple(strides)
    padding = ((0, 0), (0, 0)) + tuple((p, p) for p in pads)
    if attrs["pooling_type"] == "max":
        res = jax.lax.reduce_window(xv, -jnp.inf, jax.lax.max, window, strd,
                                    padding)
    else:
        s = jax.lax.reduce_window(xv, 0.0, jax.lax.add, window, strd, padding)
        if attrs.get("exclusive", True) and any(p > 0 for p in pads):
            ones = jnp.ones_like(xv)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strd,
                                        padding)
            res = s / cnt
        else:
            res = s / float(np.prod(ksize))
    return out(res)


@register_op("trilinear_interp",
             inputs=[IOSpec("X"), IOSpec("OutSize", optional=True,
                                         no_grad=True)],
             outputs=["Out"],
             attrs={"out_d": -1, "out_h": -1, "out_w": -1,
                    "align_corners": True, "align_mode": 1,
                    "interp_method": "trilinear"})
def _trilinear_interp(ctx, ins, attrs):
    xv = x(ins, "X")
    os = x(ins, "OutSize")
    if os is not None:
        od, oh, ow = [int(v) for v in np.asarray(os).reshape(-1)]
    else:
        od, oh, ow = attrs["out_d"], attrs["out_h"], attrs["out_w"]
    N, C = xv.shape[:2]
    if attrs.get("align_corners", True):
        # jax.image.resize uses half-pixel centers; emulate align_corners
        # with explicit linspace gather instead
        def axis_idx(n_in, n_out):
            if n_out == 1:
                return jnp.zeros((1,))
            return jnp.linspace(0.0, n_in - 1, n_out)
        d = axis_idx(xv.shape[2], od)
        h = axis_idx(xv.shape[3], oh)
        w = axis_idx(xv.shape[4], ow)

        def lin(v, idx, axis):
            lo = jnp.floor(idx).astype(jnp.int32)
            hi = jnp.minimum(lo + 1, v.shape[axis] - 1)
            wgt = (idx - lo).reshape([-1 if i == axis else 1
                                      for i in range(v.ndim)])
            return (jnp.take(v, lo, axis) * (1 - wgt)
                    + jnp.take(v, hi, axis) * wgt)

        res = lin(lin(lin(xv, d, 2), h, 3), w, 4)
    else:
        res = jax.image.resize(xv, (N, C, od, oh, ow), method="trilinear")
    return out(res)
