"""Sequence (LoD) ops on the padded + lengths encoding.

Reference: paddle/fluid/operators/sequence_ops/ (~15 LoD-aware ops over
packed LoDTensors, lod_tensor.h:104) — the reference stores variable-length
batches packed with offset tables and every kernel walks the offsets.

TPU-native encoding (SURVEY §5): XLA wants static shapes, so a lod_level-1
tensor is a padded ``[batch, max_len, ...]`` array plus an int32 ``[batch]``
lengths array living in a companion variable ``<name>@LOD`` (see
layers/sequence.py and DataFeeder varlen handling; max_len is bucketed by
the feeder so the compile cache stays bounded). Every op here takes the
lengths through a ``SeqLen`` input slot, masks with
``iota < len`` instead of walking offsets, and writes zeros at invalid
positions so downstream ops see deterministic padding. Grads come from the
generic jax.vjp path — masking makes padded positions' gradients zero
automatically.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .common import IOSpec, out, register_op, x

__all__ = []


def _mask(lengths, max_len):
    """[batch, max_len] bool validity mask from [batch] lengths."""
    return jnp.arange(max_len)[None, :] < lengths[:, None]


def _expand_mask(m, ndim):
    """Broadcast a [batch, time] mask over trailing feature dims."""
    return m.reshape(m.shape + (1,) * (ndim - 2))


def _dtype_min(dt):
    return jnp.finfo(dt).min if jnp.issubdtype(dt, jnp.inexact) \
        else jnp.iinfo(dt).min


def _dtype_max(dt):
    return jnp.finfo(dt).max if jnp.issubdtype(dt, jnp.inexact) \
        else jnp.iinfo(dt).max


@register_op("sequence_pool",
             inputs=[IOSpec("X"), IOSpec("SeqLen", no_grad=True)],
             outputs=["Out", IOSpec("MaxIndex", optional=True)],
             attrs={"pooltype": "AVERAGE", "pad_value": 0.0})
def _sequence_pool(ctx, ins, attrs):
    """reference sequence_pool_op.h: one pooled row per sequence."""
    xv, ln = x(ins, "X"), x(ins, "SeqLen")
    t = attrs["pooltype"].upper()
    m = _expand_mask(_mask(ln, xv.shape[1]), xv.ndim)
    lnf = jnp.maximum(ln, 1).astype(xv.dtype).reshape(
        (-1,) + (1,) * (xv.ndim - 2))
    if t == "SUM":
        res = jnp.where(m, xv, 0).sum(axis=1)
    elif t == "AVERAGE":
        res = jnp.where(m, xv, 0).sum(axis=1) / lnf
    elif t == "SQRT":
        res = jnp.where(m, xv, 0).sum(axis=1) / jnp.sqrt(lnf)
    elif t == "MAX":
        res = jnp.where(m, xv, _dtype_min(xv.dtype)).max(axis=1)
        res = jnp.where(ln.reshape(lnf.shape) > 0, res, attrs["pad_value"])
    elif t == "MIN":
        res = jnp.where(m, xv, _dtype_max(xv.dtype)).min(axis=1)
        res = jnp.where(ln.reshape(lnf.shape) > 0, res, attrs["pad_value"])
    elif t == "LAST":
        idx = jnp.maximum(ln - 1, 0)
        res = jnp.take_along_axis(
            xv, idx.reshape((-1, 1) + (1,) * (xv.ndim - 2)), axis=1
        ).squeeze(1)
    elif t == "FIRST":
        res = xv[:, 0]
    else:
        raise ValueError(f"sequence_pool: unknown pooltype {t}")
    return out(res)


@register_op("sequence_softmax",
             inputs=[IOSpec("X"), IOSpec("SeqLen", no_grad=True)],
             outputs=["Out"], attrs={})
def _sequence_softmax(ctx, ins, attrs):
    """reference sequence_softmax_op.h: softmax within each sequence;
    padded positions get probability 0."""
    xv, ln = x(ins, "X"), x(ins, "SeqLen")
    m = _expand_mask(_mask(ln, xv.shape[1]), xv.ndim)
    neg = jnp.finfo(xv.dtype).min
    e = jax.nn.softmax(jnp.where(m, xv, neg), axis=1)
    return out(jnp.where(m, e, 0))


@register_op("sequence_reverse",
             inputs=[IOSpec("X"), IOSpec("SeqLen", no_grad=True)],
             outputs=["Y"], attrs={})
def _sequence_reverse(ctx, ins, attrs):
    """reference sequence_reverse_op.h: reverse each sequence's valid
    prefix; padding stays in place."""
    xv, ln = x(ins, "X"), x(ins, "SeqLen")
    t = jnp.arange(xv.shape[1])[None, :]
    src = jnp.where(t < ln[:, None], ln[:, None] - 1 - t, t)
    return {"Y": [jnp.take_along_axis(
        xv, src.reshape(src.shape + (1,) * (xv.ndim - 2)), axis=1)]}


@register_op("sequence_expand",
             inputs=[IOSpec("X"), IOSpec("Y", no_grad=True),
                     IOSpec("SeqLen", no_grad=True)],
             outputs=["Out"], attrs={"ref_level": -1})
def _sequence_expand(ctx, ins, attrs):
    """reference sequence_expand_op.h, padded analogue of the common case:
    X has one row per sequence; each row is broadcast over Y's time steps
    (masked by Y's lengths). The reference's general per-level expansion of
    an X that itself has a time axis has no padded encoding here — rejected
    loudly rather than producing a wrong-rank tensor."""
    xv, yv, ln = x(ins, "X"), x(ins, "Y"), x(ins, "SeqLen")
    if xv.ndim >= yv.ndim:
        raise ValueError(
            f"sequence_expand: X (shape {xv.shape}) must be one row per "
            f"sequence (rank < Y's rank {yv.shape}); expanding an X with "
            f"its own time axis is not supported in the padded encoding")
    max_len = yv.shape[1]
    rep = jnp.broadcast_to(xv[:, None], (xv.shape[0], max_len) + xv.shape[1:])
    m = _expand_mask(_mask(ln, max_len), rep.ndim)
    return out(jnp.where(m, rep, 0))


@register_op("sequence_concat",
             inputs=[IOSpec("X", duplicable=True),
                     IOSpec("SeqLen", duplicable=True, no_grad=True)],
             outputs=["Out", IOSpec("OutLen", no_grad=True)],
             attrs={})
def _sequence_concat(ctx, ins, attrs):
    """reference sequence_concat_op.h: concatenate along time per sequence
    (out length = sum of lengths), not along the padded axis."""
    xs, lns = ins["X"], ins["SeqLen"]
    total = sum(v.shape[1] for v in xs)
    batch = xs[0].shape[0]
    t = jnp.arange(total)[None, :]  # [1, total]
    res = jnp.zeros((batch, total) + xs[0].shape[2:], xs[0].dtype)
    offset = jnp.zeros((batch, 1), lns[0].dtype)
    for v, ln in zip(xs, lns):
        # positions offset <= t < offset+len come from v[t - offset]
        local = t - offset
        sel = (local >= 0) & (local < ln[:, None])
        idx = jnp.clip(local, 0, v.shape[1] - 1)
        gathered = jnp.take_along_axis(
            v, idx.reshape(idx.shape + (1,) * (v.ndim - 2)), axis=1)
        res = jnp.where(_expand_mask(sel, res.ndim), gathered, res)
        offset = offset + ln[:, None]
    return {"Out": [res], "OutLen": [sum(ln for ln in lns)]}


@register_op("sequence_pad",
             inputs=[IOSpec("X"), IOSpec("SeqLen", no_grad=True),
                     IOSpec("PadValue", no_grad=True)],
             outputs=["Out", IOSpec("Length", no_grad=True)],
             attrs={"padded_length": -1})
def _sequence_pad(ctx, ins, attrs):
    """reference sequence_pad_op.h: emit the padded tensor with the pad
    value written at invalid positions, plus the Length tensor."""
    xv, ln, pv = x(ins, "X"), x(ins, "SeqLen"), x(ins, "PadValue")
    plen = attrs.get("padded_length", -1)
    if plen and plen > 0:
        cur = xv.shape[1]
        if plen < cur:
            xv = xv[:, :plen]
        elif plen > cur:
            pad = [(0, 0), (0, plen - cur)] + [(0, 0)] * (xv.ndim - 2)
            xv = jnp.pad(xv, pad)
    m = _expand_mask(_mask(ln, xv.shape[1]), xv.ndim)
    fill = pv.reshape((1,) * xv.ndim) if pv is not None else 0.0
    return {"Out": [jnp.where(m, xv, fill)], "Length": [ln]}


@register_op("sequence_unpad",
             inputs=[IOSpec("X"), IOSpec("Length", no_grad=True)],
             outputs=["Out", IOSpec("OutLen", no_grad=True)], attrs={})
def _sequence_unpad(ctx, ins, attrs):
    """reference sequence_unpad_op.h: padded + Length -> LoD tensor. In the
    padded encoding this re-associates lengths and zeroes the padding."""
    xv, ln = x(ins, "X"), x(ins, "Length")
    ln = ln.reshape(-1).astype(jnp.int32)
    m = _expand_mask(_mask(ln, xv.shape[1]), xv.ndim)
    return {"Out": [jnp.where(m, xv, 0)], "OutLen": [ln]}


@register_op("sequence_slice",
             inputs=[IOSpec("X"), IOSpec("SeqLen", no_grad=True),
                     IOSpec("Offset", no_grad=True),
                     IOSpec("Length", no_grad=True)],
             outputs=["Out", IOSpec("OutLen", no_grad=True)], attrs={})
def _sequence_slice(ctx, ins, attrs):
    """reference sequence_slice_op.h: per-sequence [offset, offset+length)
    window."""
    xv = x(ins, "X")
    off = x(ins, "Offset").reshape(-1)
    length = x(ins, "Length").reshape(-1)
    t = jnp.arange(xv.shape[1])[None, :]
    src = jnp.clip(off[:, None] + t, 0, xv.shape[1] - 1)
    g = jnp.take_along_axis(
        xv, src.reshape(src.shape + (1,) * (xv.ndim - 2)), axis=1)
    m = _expand_mask(t < length[:, None], xv.ndim)
    return {"Out": [jnp.where(m, g, 0)], "OutLen": [length.astype(jnp.int32)]}


@register_op("sequence_erase",
             inputs=[IOSpec("X", no_grad=True), IOSpec("SeqLen", no_grad=True)],
             outputs=["Out", IOSpec("OutLen", no_grad=True)],
             attrs={"tokens": []})
def _sequence_erase(ctx, ins, attrs):
    """reference sequence_erase_op.h: drop the listed token ids and compact
    each sequence to the front (int ids; not differentiable)."""
    xv, ln = x(ins, "X"), x(ins, "SeqLen")
    tokens = jnp.asarray(list(attrs["tokens"]) or [-1 << 30], xv.dtype)
    valid = _mask(ln, xv.shape[1])
    keep = valid & ~jnp.isin(xv, tokens)
    # stable compaction: kept positions sort before dropped ones
    order = jnp.argsort(~keep, axis=1, stable=True)
    compacted = jnp.take_along_axis(xv, order, axis=1)
    new_len = keep.sum(axis=1).astype(jnp.int32)
    m = _mask(new_len, xv.shape[1])
    return {"Out": [jnp.where(m, compacted, 0)], "OutLen": [new_len]}


@register_op("sequence_enumerate",
             inputs=[IOSpec("X", no_grad=True), IOSpec("SeqLen", no_grad=True)],
             outputs=["Out"], attrs={"win_size": 2, "pad_value": 0})
def _sequence_enumerate(ctx, ins, attrs):
    """reference sequence_enumerate_op.h: sliding windows of ids,
    pad_value past each sequence's end."""
    xv, ln = x(ins, "X"), x(ins, "SeqLen")
    win, pad = attrs["win_size"], attrs["pad_value"]
    cols = []
    t = jnp.arange(xv.shape[1])[None, :]
    for k in range(win):
        idx = jnp.clip(t + k, 0, xv.shape[1] - 1)
        v = jnp.take_along_axis(xv, idx, axis=1)
        cols.append(jnp.where(t + k < ln[:, None], v, pad))
    return out(jnp.stack(cols, axis=-1))


@register_op("sequence_conv",
             inputs=[IOSpec("X"), IOSpec("Filter"),
                     IOSpec("SeqLen", no_grad=True)],
             outputs=["Out"],
             attrs={"contextLength": 3, "contextStart": -1,
                    "contextStride": 1})
def _sequence_conv(ctx, ins, attrs):
    """reference sequence_conv_op.h: im2col over the time axis within each
    sequence then GEMM — out[t] = concat(x[t+start .. t+start+L-1]) @ W,
    with out-of-sequence context rows zero."""
    xv, w, ln = x(ins, "X"), x(ins, "Filter"), x(ins, "SeqLen")
    L, start = attrs["contextLength"], attrs["contextStart"]
    t = jnp.arange(xv.shape[1])[None, :]
    valid = t < ln[:, None]
    frames = []
    for k in range(L):
        idx = t + start + k
        ok = (idx >= 0) & (idx < ln[:, None])
        src = jnp.clip(idx, 0, xv.shape[1] - 1)
        v = jnp.take_along_axis(
            xv, src.reshape(src.shape + (1,) * (xv.ndim - 2)), axis=1)
        frames.append(jnp.where(ok[..., None], v, 0))
    col = jnp.concatenate(frames, axis=-1)  # [b, T, L*d]
    res = jnp.einsum("btc,co->bto", col, w)
    return out(jnp.where(valid[..., None], res, 0))


@register_op("sequence_mask", inputs=[IOSpec("X", no_grad=True)],
             outputs=["Y"], attrs={"maxlen": -1, "out_dtype": "float32"})
def _sequence_mask(ctx, ins, attrs):
    """reference sequence_mask_op.h: lengths -> [.., maxlen] 0/1 mask."""
    from ..core.types import jnp_dtype

    ln = x(ins, "X")
    maxlen = attrs["maxlen"]
    if maxlen is None or maxlen <= 0:
        raise ValueError("sequence_mask on TPU needs a static maxlen attr")
    m = jnp.arange(maxlen)[None, :] < ln.reshape(-1, 1)
    m = m.reshape(tuple(ln.shape) + (maxlen,))
    # jnp_dtype: int64 out_dtype must canonicalize before the astype or
    # every trace warns about the x64 truncation
    return {"Y": [m.astype(jnp_dtype(attrs["out_dtype"]))]}
