"""Tensor creation / manipulation ops.

References: paddle/fluid/operators/{fill_constant,uniform_random,
gaussian_random,assign,reshape,transpose,concat,split,slice,squeeze,
unsqueeze,stack,expand,gather,scatter,one_hot,lookup_table,top_k,argsort,
cumsum,shape}_op.* — rebuilt as jnp/lax expressions; random ops draw from the
ctx PRNG key (threaded per-op via fold_in, replacing cuRAND + global seeds).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.types import jnp_dtype, np_dtype
from .common import IOSpec, out, register_op, x


def _shape_from_attr(ins, attrs):
    """Resolve output shape: ShapeTensor input > shape attr."""
    shape = list(attrs.get("shape") or [])
    return tuple(int(s) for s in shape)


@register_op("fill_constant", outputs=["Out"],
             attrs={"shape": [], "value": 0.0, "dtype": "float32", "force_cpu": False})
def _fill_constant(ctx, ins, attrs):
    shape = _shape_from_attr(ins, attrs)
    dt = jnp_dtype(attrs["dtype"])
    return out(jnp.full(shape, attrs["value"], dtype=dt))


def _infer_like_batch(op, block):
    # Out has X's shape with input dim 0 replaced; -1 aware
    xv = block._var_recursive(op.input("Input")[0])
    shape = list(op.attrs["shape"])
    idx_in = op.attrs.get("input_dim_idx", 0)
    idx_out = op.attrs.get("output_dim_idx", 0)
    shape[idx_out] = xv.shape[idx_in] if xv.shape else -1
    if block.has_var(op.output("Out")[0]):
        v = block.var(op.output("Out")[0])
        v.shape = tuple(shape)
        v.dtype = op.attrs.get("dtype", "float32")


@register_op("fill_constant_batch_size_like", inputs=["Input"], outputs=["Out"],
             attrs={"shape": [], "value": 0.0, "dtype": "float32",
                    "input_dim_idx": 0, "output_dim_idx": 0},
             infer_shape=_infer_like_batch, grad=None)
def _fill_constant_bsl(ctx, ins, attrs):
    inp = x(ins, "Input")
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = inp.shape[attrs.get("input_dim_idx", 0)]
    return out(jnp.full(tuple(shape), attrs["value"], dtype=jnp_dtype(attrs["dtype"])))


@register_op("fill_zeros_like", inputs=["X"], outputs=["Out"], grad=None)
def _fill_zeros_like(ctx, ins, attrs):
    return out(jnp.zeros_like(x(ins)))


@register_op("uniform_random", outputs=["Out"],
             attrs={"shape": [], "min": -1.0, "max": 1.0, "seed": 0,
                    "dtype": "float32"},
             needs_rng=True, grad=None)
def _uniform_random(ctx, ins, attrs):
    shape = _shape_from_attr(ins, attrs)
    key = jax.random.key(attrs["seed"]) if attrs.get("seed") else ctx.rng()
    return out(jax.random.uniform(key, shape, dtype=jnp_dtype(attrs["dtype"]),
                                  minval=attrs["min"], maxval=attrs["max"]))


@register_op("gaussian_random", outputs=["Out"],
             attrs={"shape": [], "mean": 0.0, "std": 1.0, "seed": 0,
                    "dtype": "float32"},
             needs_rng=True, grad=None)
def _gaussian_random(ctx, ins, attrs):
    shape = _shape_from_attr(ins, attrs)
    key = jax.random.key(attrs["seed"]) if attrs.get("seed") else ctx.rng()
    sample = jax.random.normal(key, shape, dtype=jnp_dtype(attrs["dtype"]))
    return out(sample * attrs["std"] + attrs["mean"])


@register_op("truncated_gaussian_random", outputs=["Out"],
             attrs={"shape": [], "mean": 0.0, "std": 1.0, "seed": 0,
                    "dtype": "float32"},
             needs_rng=True, grad=None)
def _truncated_gaussian_random(ctx, ins, attrs):
    shape = _shape_from_attr(ins, attrs)
    key = jax.random.key(attrs["seed"]) if attrs.get("seed") else ctx.rng()
    sample = jax.random.truncated_normal(key, -2.0, 2.0, shape,
                                         dtype=jnp_dtype(attrs["dtype"]))
    return out(sample * attrs["std"] + attrs["mean"])


@register_op("assign", inputs=["X"], outputs=["Out"])
def _assign(ctx, ins, attrs):
    return out(x(ins))


@register_op("assign_value", outputs=["Out"],
             attrs={"shape": [], "dtype": "float32", "values": []}, grad=None)
def _assign_value(ctx, ins, attrs):
    # host-side numpy keeps full width (a >2**31 int64 constant would
    # OverflowError under numpy 2); narrowing happens at the jnp boundary
    vals = np.asarray(attrs["values"], dtype=np_dtype(attrs["dtype"]))
    return out(jnp.asarray(vals.reshape(attrs["shape"]),
                           dtype=jnp_dtype(attrs["dtype"])))


@register_op("shape", inputs=["Input"], outputs=["Out"], grad=None)
def _shape(ctx, ins, attrs):
    return out(jnp.asarray(x(ins, "Input").shape, dtype=jnp.int32))


def _infer_reshape(op, block):
    xv = block._var_recursive(op.input("X")[0])
    shape = list(op.attrs["shape"])
    if xv.shape is not None:
        has_neg = -1 in shape
        for i, s in enumerate(shape):
            if s == 0:
                shape[i] = xv.shape[i]
        concrete = [s for s in shape if s != -1]
        if has_neg and all(d != -1 for d in xv.shape):
            total = int(np.prod(xv.shape))
            rest = int(np.prod(concrete)) if concrete else 1
            shape[shape.index(-1)] = total // rest
    ov = block.var(op.output("Out")[0])
    ov.shape = tuple(shape)
    ov.dtype = xv.dtype
    if op.output("XShape"):
        xs = block.var(op.output("XShape")[0])
        xs.shape = (0,) + tuple(xv.shape or ())
        xs.dtype = xv.dtype


@register_op("reshape2", inputs=[IOSpec("X"), IOSpec("Shape", optional=True, no_grad=True)],
             outputs=["Out", "XShape"], attrs={"shape": []},
             infer_shape=_infer_reshape)
def _reshape2(ctx, ins, attrs):
    xv = x(ins)
    shape = [xv.shape[i] if s == 0 else s for i, s in enumerate(attrs["shape"])] \
        if any(s == 0 for s in attrs["shape"]) else list(attrs["shape"])
    return {"Out": [jnp.reshape(xv, shape)], "XShape": [jnp.zeros((0,), xv.dtype)]}


@register_op("transpose2", inputs=["X"], outputs=["Out", "XShape"],
             attrs={"axis": []})
def _transpose2(ctx, ins, attrs):
    xv = x(ins)
    return {"Out": [jnp.transpose(xv, attrs["axis"])],
            "XShape": [jnp.zeros((0,), xv.dtype)]}


@register_op("concat", inputs=[IOSpec("X", duplicable=True)], outputs=["Out"],
             attrs={"axis": 0})
def _concat(ctx, ins, attrs):
    return out(jnp.concatenate([v for v in ins["X"] if v is not None],
                               axis=attrs["axis"]))


@register_op("split", inputs=["X"], outputs=[IOSpec("Out", duplicable=True)],
             attrs={"num": 0, "sections": [], "axis": 0})
def _split(ctx, ins, attrs):
    xv = x(ins)
    axis = attrs["axis"]
    if attrs.get("sections"):
        idx = np.cumsum(attrs["sections"][:-1]).tolist()
        parts = jnp.split(xv, idx, axis=axis)
    else:
        parts = jnp.split(xv, attrs["num"], axis=axis)
    return {"Out": list(parts)}


@register_op("slice", inputs=["Input"], outputs=["Out"],
             attrs={"axes": [], "starts": [], "ends": [],
                    "decrease_axis": []})
def _slice(ctx, ins, attrs):
    xv = x(ins, "Input")
    idx = [slice(None)] * xv.ndim
    for ax, st, en in zip(attrs["axes"], attrs["starts"], attrs["ends"]):
        idx[ax] = slice(st, en)
    res = xv[tuple(idx)]
    if attrs.get("decrease_axis"):
        res = jnp.squeeze(res, axis=tuple(attrs["decrease_axis"]))
    return out(res)


@register_op("squeeze2", inputs=["X"], outputs=["Out", "XShape"],
             attrs={"axes": []})
def _squeeze2(ctx, ins, attrs):
    xv = x(ins)
    if attrs["axes"]:
        # reference squeeze_op: listed axes are squeezed only if size-1;
        # non-1 listed axes are ignored (never fall back to squeezing all)
        axes = tuple(a for a in attrs["axes"] if xv.shape[a] == 1)
    else:
        axes = tuple(i for i, d in enumerate(xv.shape) if d == 1)
    return {"Out": [jnp.squeeze(xv, axis=axes) if axes else xv],
            "XShape": [jnp.zeros((0,), xv.dtype)]}


@register_op("unsqueeze2", inputs=["X"], outputs=["Out", "XShape"],
             attrs={"axes": []})
def _unsqueeze2(ctx, ins, attrs):
    xv = x(ins)
    res = xv
    for a in sorted(attrs["axes"]):
        res = jnp.expand_dims(res, a)
    return {"Out": [res], "XShape": [jnp.zeros((0,), xv.dtype)]}


@register_op("stack", inputs=[IOSpec("X", duplicable=True)], outputs=["Y"],
             attrs={"axis": 0})
def _stack(ctx, ins, attrs):
    return {"Y": [jnp.stack(ins["X"], axis=attrs["axis"])]}


@register_op("unstack", inputs=["X"], outputs=[IOSpec("Y", duplicable=True)],
             attrs={"axis": 0, "num": 0})
def _unstack(ctx, ins, attrs):
    xv = x(ins)
    parts = [jnp.squeeze(p, attrs["axis"])
             for p in jnp.split(xv, xv.shape[attrs["axis"]], attrs["axis"])]
    return {"Y": parts}


@register_op("expand", inputs=["X"], outputs=["Out"], attrs={"expand_times": []})
def _expand(ctx, ins, attrs):
    return out(jnp.tile(x(ins), attrs["expand_times"]))


@register_op("gather", inputs=[IOSpec("X"), IOSpec("Index", no_grad=True)],
             outputs=["Out"])
def _gather(ctx, ins, attrs):
    return out(jnp.take(x(ins, "X"), x(ins, "Index").astype(jnp.int32), axis=0))


@register_op("scatter", inputs=[IOSpec("X"), IOSpec("Ids", no_grad=True), IOSpec("Updates")],
             outputs=["Out"], attrs={"overwrite": True})
def _scatter(ctx, ins, attrs):
    xv, ids, upd = x(ins, "X"), x(ins, "Ids"), x(ins, "Updates")
    ids = ids.astype(jnp.int32)
    if attrs.get("overwrite", True):
        return out(xv.at[ids].set(upd))
    return out(xv.at[ids].add(upd))


@register_op("one_hot", inputs=[IOSpec("X", no_grad=True)], outputs=["Out"],
             attrs={"depth": 1, "dtype": "float32"}, grad=None)
def _one_hot(ctx, ins, attrs):
    ids = x(ins)
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = jnp.squeeze(ids, -1)
    return out(jax.nn.one_hot(ids.astype(jnp.int32), attrs["depth"],
                              dtype=jnp_dtype(attrs["dtype"])))


def _lookup_table_grad(ctx, ins, attrs):
    """Custom grad: SelectedRows for ``is_sparse`` tables (reference
    selected_rows.h:32 — grads sized by touched rows, not vocab), dense
    scatter-add otherwise. Both share id canonicalization: squeeze the
    trailing 1, clip OOB ids to match the forward gather's mode="clip",
    route padding_idx rows to the drop sentinel (their forward output was
    zeroed, so their gradient is zero by construction)."""
    from ..core.selected_rows import SelectedRows, merge_rows

    w, ids, g = x(ins, "W"), x(ins, "Ids"), x(ins, "Out@GRAD")
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = jnp.squeeze(ids, -1)
    height, dim = w.shape[0], w.shape[-1]
    ids_flat = jnp.clip(ids.reshape(-1).astype(jnp.int32), 0, height - 1)
    pad = attrs.get("padding_idx", -1)
    if pad is not None and pad != -1:
        ids_flat = jnp.where(ids_flat == pad, height, ids_flat)
    g_flat = g.reshape(-1, dim).astype(w.dtype)
    if attrs.get("is_sparse") or attrs.get("is_distributed"):
        return {"W@GRAD": [merge_rows(ids_flat, g_flat, height)]}
    dense = jnp.zeros_like(w).at[ids_flat].add(g_flat, mode="drop")
    return {"W@GRAD": [dense]}


@register_op("lookup_table", inputs=[IOSpec("W"), IOSpec("Ids", no_grad=True)],
             outputs=["Out"],
             attrs={"is_sparse": False, "is_distributed": False,
                    "padding_idx": -1, "remote_prefetch": False},
             grad_lower=_lookup_table_grad)
def _lookup_table(ctx, ins, attrs):
    w, ids = x(ins, "W"), x(ins, "Ids")
    squeeze_last = ids.ndim >= 2 and ids.shape[-1] == 1
    if squeeze_last:
        ids = jnp.squeeze(ids, -1)
    # mode="clip" = XLA/TPU gather OOB semantics; jnp's default "fill" turns
    # an oversized id into silent NaNs (the reference bounds-checks on CPU)
    emb = jnp.take(w, ids.astype(jnp.int32), axis=0, mode="clip")
    pad = attrs.get("padding_idx", -1)
    if pad is not None and pad != -1:
        mask = (ids != pad)[..., None]
        emb = jnp.where(mask, emb, 0.0)
    return out(emb)


@register_op("lookup_table_v2", inputs=[IOSpec("W"), IOSpec("Ids", no_grad=True)],
             outputs=["Out"], attrs={"is_sparse": False, "padding_idx": -1},
             grad_lower=_lookup_table_grad)
def _lookup_table_v2(ctx, ins, attrs):
    return _lookup_table(ctx, ins, attrs)


@register_op("top_k", inputs=["X"], outputs=["Out", "Indices"], attrs={"k": 1},
             grad=None)
def _top_k(ctx, ins, attrs):
    vals, idx = jax.lax.top_k(x(ins), attrs["k"])
    return {"Out": [vals], "Indices": [idx.astype(jnp_dtype("int64"))]}


@register_op("arg_max", inputs=["X"], outputs=["Out"], attrs={"axis": -1},
             grad=None)
def _arg_max(ctx, ins, attrs):
    return out(jnp.argmax(x(ins), axis=attrs["axis"]).astype(jnp_dtype("int64")))


@register_op("arg_min", inputs=["X"], outputs=["Out"], attrs={"axis": -1},
             grad=None)
def _arg_min(ctx, ins, attrs):
    return out(jnp.argmin(x(ins), axis=attrs["axis"]).astype(jnp_dtype("int64")))


@register_op("argsort", inputs=["X"], outputs=["Out", "Indices"],
             attrs={"axis": -1, "descending": False}, grad=None)
def _argsort(ctx, ins, attrs):
    xv = x(ins)
    axis = attrs["axis"]
    idx = jnp.argsort(xv, axis=axis, descending=attrs.get("descending", False))
    return {"Out": [jnp.take_along_axis(xv, idx, axis=axis)],
            "Indices": [idx.astype(jnp_dtype("int64"))]}


@register_op("cumsum", inputs=["X"], outputs=["Out"],
             attrs={"axis": -1, "exclusive": False, "reverse": False})
def _cumsum(ctx, ins, attrs):
    xv = x(ins)
    axis = attrs["axis"]
    if attrs.get("reverse"):
        xv = jnp.flip(xv, axis)
    res = jnp.cumsum(xv, axis=axis)
    if attrs.get("exclusive"):
        res = res - xv
    if attrs.get("reverse"):
        res = jnp.flip(res, axis)
    return out(res)


@register_op("where", inputs=[IOSpec("Condition", no_grad=True), IOSpec("X"), IOSpec("Y")],
             outputs=["Out"])
def _where(ctx, ins, attrs):
    return out(jnp.where(x(ins, "Condition"), x(ins, "X"), x(ins, "Y")))


@register_op("range",
             inputs=[IOSpec("Start", optional=True, no_grad=True),
                     IOSpec("End", optional=True, no_grad=True),
                     IOSpec("Step", optional=True, no_grad=True)],
             outputs=["Out"],
             attrs={"start": 0.0, "end": 0.0, "step": 1.0, "dtype": "float32",
                    "use_attrs": True},
             grad=None)
def _range(ctx, ins, attrs):
    """XLA needs a static length; the layer passes numeric bounds as attrs.
    Tensor inputs are only accepted if they are compile-time constants."""
    if attrs.get("use_attrs", True):
        st, en, sp = attrs["start"], attrs["end"], attrs["step"]
    else:
        try:
            st = float(x(ins, "Start"))
            en = float(x(ins, "End"))
            sp = float(x(ins, "Step"))
        except (TypeError, jax.errors.ConcretizationTypeError) as e:
            raise ValueError(
                "range op: Start/End/Step must be compile-time constants "
                "under XLA (static shapes); pass numbers, not computed "
                "tensors") from e
    return out(jnp.arange(st, en, sp, dtype=jnp_dtype(attrs.get("dtype", "float32"))))


@register_op("increment", inputs=["X"], outputs=["Out"], attrs={"step": 1.0},
             grad=None)
def _increment(ctx, ins, attrs):
    xv = x(ins)
    # keep X's dtype: int counters must stay int (loop carries require it)
    return out(xv + jnp.asarray(attrs["step"], xv.dtype))


@register_op("flatten2", inputs=["X"], outputs=["Out", "XShape"], attrs={"axis": 1})
def _flatten2(ctx, ins, attrs):
    xv = x(ins)
    ax = attrs["axis"]
    lead = int(np.prod(xv.shape[:ax])) if ax > 0 else 1
    return {"Out": [jnp.reshape(xv, (lead, -1))],
            "XShape": [jnp.zeros((0,), xv.dtype)]}


@register_op("pad", inputs=["X"], outputs=["Out"],
             attrs={"paddings": [], "pad_value": 0.0})
def _pad(ctx, ins, attrs):
    xv = x(ins)
    p = attrs["paddings"]
    cfg = [(p[2 * i], p[2 * i + 1]) for i in range(xv.ndim)]
    return out(jnp.pad(xv, cfg, constant_values=attrs["pad_value"]))


@register_op("gather_nd", inputs=[IOSpec("X"), IOSpec("Index", no_grad=True)],
             outputs=["Out"])
def _gather_nd(ctx, ins, attrs):
    """reference gather_nd_op.h: index's last dim addresses leading dims of
    X; output = X[idx[..., 0], idx[..., 1], ...]."""
    xv, idx = x(ins, "X"), x(ins, "Index")
    flat_idx = tuple(jnp.moveaxis(idx, -1, 0).astype(jnp.int32))
    return out(xv[flat_idx])


@register_op("scatter_nd_add",
             inputs=[IOSpec("X"), IOSpec("Index", no_grad=True),
                     IOSpec("Updates")],
             outputs=["Out"])
def _scatter_nd_add(ctx, ins, attrs):
    xv, idx, upd = x(ins, "X"), x(ins, "Index"), x(ins, "Updates")
    flat_idx = tuple(jnp.moveaxis(idx, -1, 0).astype(jnp.int32))
    return out(xv.at[flat_idx].add(upd))


@register_op("reverse", inputs=[IOSpec("X")], outputs=["Out"],
             attrs={"axis": [0]})
def _reverse(ctx, ins, attrs):
    ax = attrs["axis"]
    ax = [ax] if isinstance(ax, int) else list(ax)
    return out(jnp.flip(x(ins), axis=tuple(ax)))


@register_op("expand_as", inputs=[IOSpec("X"),
                                  IOSpec("target_tensor", no_grad=True)],
             outputs=["Out"])
def _expand_as(ctx, ins, attrs):
    xv, ref = x(ins, "X"), x(ins, "target_tensor")
    reps = tuple(int(t // s) for s, t in zip(xv.shape, ref.shape))
    return out(jnp.tile(xv, reps))


@register_op("diag", inputs=[IOSpec("Diagonal", no_grad=True)],
             outputs=["Out"], grad=None)
def _diag(ctx, ins, attrs):
    return out(jnp.diag(x(ins, "Diagonal")))


@register_op("eye", outputs=["Out"],
             attrs={"num_rows": 0, "num_columns": -1, "dtype": "float32"},
             grad=None)
def _eye(ctx, ins, attrs):
    n = attrs["num_rows"]
    m = attrs["num_columns"]
    m = n if m is None or m < 0 else m
    return out(jnp.eye(n, m, dtype=jnp_dtype(attrs["dtype"])))


@register_op("pad2d", inputs=[IOSpec("X")], outputs=["Out"],
             attrs={"paddings": [0, 0, 0, 0], "mode": "constant",
                    "pad_value": 0.0, "data_format": "NCHW"})
def _pad2d(ctx, ins, attrs):
    """reference pad2d_op.cc: NCHW spatial padding [top,bottom,left,right],
    constant/reflect/edge modes."""
    xv = x(ins)
    t, b, l, r = attrs["paddings"]
    if attrs.get("data_format", "NCHW") == "NHWC":
        width = [(0, 0), (t, b), (l, r), (0, 0)]
    else:
        width = [(0, 0), (0, 0), (t, b), (l, r)]
    mode = attrs["mode"]
    if mode == "constant":
        return out(jnp.pad(xv, width, constant_values=attrs["pad_value"]))
    jmode = {"reflect": "reflect", "edge": "edge"}[mode]
    return out(jnp.pad(xv, width, mode=jmode))


@register_op("roll", inputs=[IOSpec("X")], outputs=["Out"],
             attrs={"shifts": [0], "axis": []})
def _roll(ctx, ins, attrs):
    ax = attrs.get("axis") or None
    return out(jnp.roll(x(ins), tuple(attrs["shifts"]),
                        axis=tuple(ax) if ax else None))


@register_op("shard_index", inputs=[IOSpec("X", no_grad=True)],
             outputs=["Out"],
             attrs={"index_num": 0, "nshards": 1, "shard_id": 0,
                    "ignore_value": -1}, grad=None)
def _shard_index(ctx, ins, attrs):
    """reference shard_index_op.h: map global ids to shard-local ids."""
    v = x(ins)
    per = (attrs["index_num"] + attrs["nshards"] - 1) // attrs["nshards"]
    sid = attrs["shard_id"]
    local = v - sid * per
    ok = (v // per) == sid
    return out(jnp.where(ok, local, attrs["ignore_value"]))


@register_op("uniform_random_batch_size_like", inputs=[IOSpec("Input", no_grad=True)],
             outputs=["Out"],
             attrs={"shape": [], "min": -1.0, "max": 1.0, "seed": 0,
                    "dtype": "float32", "input_dim_idx": 0,
                    "output_dim_idx": 0},
             needs_rng=True, grad=None)
def _uniform_random_bsl(ctx, ins, attrs):
    """reference uniform_random_batch_size_like_op.cc: shape attr with one
    dim replaced by Input's dim at input_dim_idx (static under XLA)."""
    inp = x(ins, "Input")
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = \
        inp.shape[attrs.get("input_dim_idx", 0)]
    key = jax.random.key(attrs["seed"]) if attrs.get("seed") else ctx.rng()
    return out(jax.random.uniform(key, tuple(shape),
                                  dtype=jnp_dtype(attrs["dtype"]),
                                  minval=attrs["min"], maxval=attrs["max"]))


@register_op("gaussian_random_batch_size_like",
             inputs=[IOSpec("Input", no_grad=True)], outputs=["Out"],
             attrs={"shape": [], "mean": 0.0, "std": 1.0, "seed": 0,
                    "dtype": "float32", "input_dim_idx": 0,
                    "output_dim_idx": 0},
             needs_rng=True, grad=None)
def _gaussian_random_bsl(ctx, ins, attrs):
    inp = x(ins, "Input")
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = \
        inp.shape[attrs.get("input_dim_idx", 0)]
    key = jax.random.key(attrs["seed"]) if attrs.get("seed") else ctx.rng()
    return out(attrs["mean"] + attrs["std"]
               * jax.random.normal(key, tuple(shape),
                                   dtype=jnp_dtype(attrs["dtype"])))
