"""Detection ops (reference paddle/fluid/operators/detection/, 60 files).

Implemented TPU-first: everything is fixed-shape and vectorised — the
reference's LoD-shaped outputs (variable detections per image) become
fixed-size outputs padded with -1 rows, the XLA-idiomatic encoding (same
trade as the dense beam search). Math verified against the reference
kernels cited per op.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .common import IOSpec, out, register_op, x


def _expand_aspect_ratios(ars, flip):
    """reference prior_box ExpandAspectRatios: [1] + ars (+ 1/ar if flip)."""
    res = [1.0]
    for ar in ars:
        if any(abs(ar - r) < 1e-6 for r in res):
            continue
        res.append(float(ar))
        if flip:
            res.append(1.0 / float(ar))
    return res


@register_op("prior_box",
             inputs=[IOSpec("Input", no_grad=True),
                     IOSpec("Image", no_grad=True)],
             outputs=["Boxes", "Variances"],
             attrs={"min_sizes": [], "max_sizes": [], "aspect_ratios": [1.0],
                    "variances": [0.1, 0.1, 0.2, 0.2], "flip": False,
                    "clip": False, "step_w": 0.0, "step_h": 0.0,
                    "offset": 0.5, "min_max_aspect_ratios_order": False},
             grad=None)
def _prior_box(ctx, ins, attrs):
    """reference prior_box_op.h:96-160 (default prior order: expanded
    aspect ratios then the sqrt(min*max) square)."""
    feat, img = x(ins, "Input"), x(ins, "Image")
    H, W = feat.shape[2], feat.shape[3]
    IH, IW = img.shape[2], img.shape[3]
    step_w = attrs["step_w"] or IW / W
    step_h = attrs["step_h"] or IH / H
    offset = attrs["offset"]
    ars = _expand_aspect_ratios(attrs["aspect_ratios"], attrs["flip"])
    mins, maxs = attrs["min_sizes"], attrs["max_sizes"]

    cx = (jnp.arange(W) + offset) * step_w       # [W]
    cy = (jnp.arange(H) + offset) * step_h       # [H]
    cxg, cyg = jnp.meshgrid(cx, cy)              # [H, W]
    whs = []
    for si, mn in enumerate(mins):
        if attrs.get("min_max_aspect_ratios_order"):
            # reference alt order (prior_box_op.h:107-140): min square,
            # max square, then the non-1 aspect ratios
            whs.append((mn / 2.0, mn / 2.0))
            if maxs:
                s = np.sqrt(mn * maxs[si]) / 2.0
                whs.append((s, s))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((mn * np.sqrt(ar) / 2.0, mn / np.sqrt(ar) / 2.0))
        else:
            for ar in ars:
                whs.append((mn * np.sqrt(ar) / 2.0, mn / np.sqrt(ar) / 2.0))
            if maxs:
                s = np.sqrt(mn * maxs[si]) / 2.0
                whs.append((s, s))
    bw = jnp.asarray([w for w, _ in whs], feat.dtype)  # [P]
    bh = jnp.asarray([h for _, h in whs], feat.dtype)
    x0 = (cxg[..., None] - bw) / IW
    y0 = (cyg[..., None] - bh) / IH
    x1 = (cxg[..., None] + bw) / IW
    y1 = (cyg[..., None] + bh) / IH
    boxes = jnp.stack([x0, y0, x1, y1], axis=-1)  # [H, W, P, 4]
    if attrs["clip"]:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(attrs["variances"], feat.dtype),
                           boxes.shape)
    return {"Boxes": [boxes], "Variances": [var]}


@register_op("anchor_generator",
             inputs=[IOSpec("Input", no_grad=True)],
             outputs=["Anchors", "Variances"],
             attrs={"anchor_sizes": [64.0, 128.0, 256.0, 512.0],
                    "aspect_ratios": [0.5, 1.0, 2.0],
                    "variances": [0.1, 0.1, 0.2, 0.2],
                    "stride": [16.0, 16.0], "offset": 0.5},
             grad=None)
def _anchor_generator(ctx, ins, attrs):
    """reference anchor_generator_op.h:55-84: RPN anchors in pixel coords.

    Legacy pixel conventions matter for parity with reference-trained RPN
    heads: centers at idx*stride + offset*(stride-1), base_w/base_h
    quantized through round(sqrt(stride_area/ar)), corners at
    ctr +/- 0.5*(wh-1)."""
    feat = x(ins, "Input")
    H, W = feat.shape[2], feat.shape[3]
    sw, sh = attrs["stride"]
    offset = attrs["offset"]
    cx = jnp.arange(W) * sw + offset * (sw - 1)
    cy = jnp.arange(H) * sh + offset * (sh - 1)
    cxg, cyg = jnp.meshgrid(cx, cy)
    whs = []
    for ar in attrs["aspect_ratios"]:
        for size in attrs["anchor_sizes"]:
            base_w = np.round(np.sqrt(sw * sh / ar))
            base_h = np.round(base_w * ar)
            anchor_w = (size / sw) * base_w
            anchor_h = (size / sh) * base_h
            whs.append((0.5 * (anchor_w - 1), 0.5 * (anchor_h - 1)))
    bw = jnp.asarray([w for w, _ in whs], feat.dtype)
    bh = jnp.asarray([h for _, h in whs], feat.dtype)
    anchors = jnp.stack([cxg[..., None] - bw, cyg[..., None] - bh,
                         cxg[..., None] + bw, cyg[..., None] + bh], -1)
    var = jnp.broadcast_to(jnp.asarray(attrs["variances"], feat.dtype),
                           anchors.shape)
    return {"Anchors": [anchors], "Variances": [var]}


def _iou_matrix(a, b, normalized=True):
    """[N,4] x [M,4] -> [N,M] (reference iou_similarity_op.h)."""
    off = 0.0 if normalized else 1.0
    area = lambda bx: jnp.maximum(bx[..., 2] - bx[..., 0] + off, 0) * \
        jnp.maximum(bx[..., 3] - bx[..., 1] + off, 0)
    ix0 = jnp.maximum(a[:, None, 0], b[None, :, 0])
    iy0 = jnp.maximum(a[:, None, 1], b[None, :, 1])
    ix1 = jnp.minimum(a[:, None, 2], b[None, :, 2])
    iy1 = jnp.minimum(a[:, None, 3], b[None, :, 3])
    inter = jnp.maximum(ix1 - ix0 + off, 0) * jnp.maximum(iy1 - iy0 + off, 0)
    union = area(a)[:, None] + area(b)[None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


@register_op("iou_similarity",
             inputs=[IOSpec("X", no_grad=True), IOSpec("Y", no_grad=True)],
             outputs=["Out"], attrs={"box_normalized": True}, grad=None)
def _iou_similarity(ctx, ins, attrs):
    return out(_iou_matrix(x(ins, "X"), x(ins, "Y"),
                           attrs.get("box_normalized", True)))


@register_op("box_coder",
             inputs=[IOSpec("PriorBox", no_grad=True),
                     IOSpec("PriorBoxVar", optional=True, no_grad=True),
                     IOSpec("TargetBox")],
             outputs=["OutputBox"],
             attrs={"code_type": "encode_center_size",
                    "box_normalized": True, "axis": 0})
def _box_coder(ctx, ins, attrs):
    """reference box_coder_op.h: center-size encode/decode."""
    prior = x(ins, "PriorBox")
    pvar = x(ins, "PriorBoxVar")
    tb = x(ins, "TargetBox")
    norm = attrs.get("box_normalized", True)
    off = 0.0 if norm else 1.0
    pw = prior[:, 2] - prior[:, 0] + off
    ph = prior[:, 3] - prior[:, 1] + off
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    if attrs["code_type"].lower().startswith("encode"):
        tw = tb[:, None, 2] - tb[:, None, 0] + off
        th = tb[:, None, 3] - tb[:, None, 1] + off
        tcx = tb[:, None, 0] + tw * 0.5
        tcy = tb[:, None, 1] + th * 0.5
        ox = (tcx - pcx[None, :]) / pw[None, :]
        oy = (tcy - pcy[None, :]) / ph[None, :]
        ow = jnp.log(jnp.abs(tw) / pw[None, :])
        oh = jnp.log(jnp.abs(th) / ph[None, :])
        res = jnp.stack([ox, oy, ow, oh], -1)  # [N, M, 4]
        if pvar is not None:
            res = res / pvar[None, :, :]
        return {"OutputBox": [res]}
    # decode: target [N, M, 4] deltas over priors
    axis = attrs.get("axis", 0)
    pw_, ph_, pcx_, pcy_ = (v[None, :] if axis == 0 else v[:, None]
                            for v in (pw, ph, pcx, pcy))
    d = tb if pvar is None else tb * (pvar[None, :, :] if axis == 0
                                      else pvar[:, None, :])
    dcx = d[..., 0] * pw_ + pcx_
    dcy = d[..., 1] * ph_ + pcy_
    dw = jnp.exp(d[..., 2]) * pw_
    dh = jnp.exp(d[..., 3]) * ph_
    res = jnp.stack([dcx - dw * 0.5, dcy - dh * 0.5,
                     dcx + dw * 0.5 - off, dcy + dh * 0.5 - off], -1)
    return {"OutputBox": [res]}


@register_op("box_clip", inputs=[IOSpec("Input"),
                                 IOSpec("ImInfo", no_grad=True)],
             outputs=["Output"])
def _box_clip(ctx, ins, attrs):
    """reference box_clip_op.h: clip to [0, im-1] per image; ImInfo [N,3]
    (h, w, scale)."""
    boxes, im = x(ins, "Input"), x(ins, "ImInfo")
    h = (im[:, 0] / im[:, 2] - 1).reshape(-1, 1)
    w = (im[:, 1] / im[:, 2] - 1).reshape(-1, 1)
    x0 = jnp.clip(boxes[..., 0], 0, w)
    y0 = jnp.clip(boxes[..., 1], 0, h)
    x1 = jnp.clip(boxes[..., 2], 0, w)
    y1 = jnp.clip(boxes[..., 3], 0, h)
    return {"Output": [jnp.stack([x0, y0, x1, y1], -1)]}


@register_op("yolo_box",
             inputs=[IOSpec("X", no_grad=True),
                     IOSpec("ImgSize", no_grad=True)],
             outputs=["Boxes", "Scores"],
             attrs={"anchors": [], "class_num": 1, "conf_thresh": 0.01,
                    "downsample_ratio": 32}, grad=None)
def _yolo_box(ctx, ins, attrs):
    """reference yolo_box_op.h: decode YOLOv3 head to corner boxes in
    image pixels + per-class scores; low-conf boxes zeroed."""
    xv, imgsize = x(ins, "X"), x(ins, "ImgSize")
    anchors = attrs["anchors"]
    an = len(anchors) // 2
    cls = attrs["class_num"]
    N, C, H, W = xv.shape
    v = xv.reshape(N, an, 5 + cls, H, W)
    grid_x = jnp.arange(W).reshape(1, 1, 1, W)
    grid_y = jnp.arange(H).reshape(1, 1, H, 1)
    img_h = imgsize[:, 0].reshape(N, 1, 1, 1).astype(xv.dtype)
    img_w = imgsize[:, 1].reshape(N, 1, 1, 1).astype(xv.dtype)
    input_size = attrs["downsample_ratio"] * H
    aw = jnp.asarray(anchors[0::2], xv.dtype).reshape(1, an, 1, 1)
    ah = jnp.asarray(anchors[1::2], xv.dtype).reshape(1, an, 1, 1)
    bx = (grid_x + jax.nn.sigmoid(v[:, :, 0])) * img_w / W
    by = (grid_y + jax.nn.sigmoid(v[:, :, 1])) * img_h / H
    bw = jnp.exp(v[:, :, 2]) * aw * img_w / input_size
    bh = jnp.exp(v[:, :, 3]) * ah * img_h / input_size
    conf = jax.nn.sigmoid(v[:, :, 4])
    keep = conf >= attrs["conf_thresh"]
    x0 = jnp.maximum(bx - bw / 2, 0)
    y0 = jnp.maximum(by - bh / 2, 0)
    x1 = jnp.minimum(bx + bw / 2, img_w - 1)
    y1 = jnp.minimum(by + bh / 2, img_h - 1)
    boxes = jnp.stack([x0, y0, x1, y1], -1) * keep[..., None]
    scores = jax.nn.sigmoid(v[:, :, 5:]) * conf[:, :, None] * \
        keep[:, :, None]
    boxes = boxes.reshape(N, an * H * W, 4)  # already [N,an,H,W,4]
    scores = scores.transpose(0, 1, 3, 4, 2).reshape(N, an * H * W, cls)
    return {"Boxes": [boxes], "Scores": [scores]}


def _nms_class(boxes, scores, iou_thresh, score_thresh, top_k, eta=1.0,
               normalized=True):
    """Greedy NMS. ``eta`` < 1 shrinks the IoU threshold after each kept
    box (reference NMSFast adaptive_threshold: thresh *= eta while
    thresh > 0.5). Returns (keep_mask, order, sorted boxes/scores)."""
    order = jnp.argsort(-scores)
    sboxes = boxes[order]
    sscores = scores[order]
    iou = _iou_matrix(sboxes, sboxes, normalized)
    n = boxes.shape[0]
    k = min(top_k, n) if top_k and top_k > 0 else n

    def body(i, state):
        keep, thresh = state
        ok = (sscores[i] > score_thresh) & ~jnp.any(
            jnp.where(jnp.arange(n) < i, (iou[i] > thresh) & keep, False))
        keep = keep.at[i].set(ok)
        thresh = jnp.where(ok & (eta < 1.0) & (thresh > 0.5),
                           thresh * eta, thresh)
        return keep, thresh

    keep, _ = jax.lax.fori_loop(
        0, n, body, (jnp.zeros((n,), bool),
                     jnp.asarray(iou_thresh, sboxes.dtype)))
    # only the top_k kept survive
    rank = jnp.cumsum(keep) - 1
    keep = keep & (rank < k)
    return keep, order, sboxes, sscores


@register_op("multiclass_nms",
             inputs=[IOSpec("BBoxes", no_grad=True),
                     IOSpec("Scores", no_grad=True)],
             outputs=["Out"],
             attrs={"background_label": 0, "score_threshold": 0.0,
                    "nms_top_k": 400, "nms_threshold": 0.3, "nms_eta": 1.0,
                    "keep_top_k": 100, "normalized": True}, grad=None)
def _multiclass_nms(ctx, ins, attrs):
    """reference multiclass_nms_op.cc. LoD output becomes fixed shape
    [N, keep_top_k, 6] = (label, score, x0, y0, x1, y1), -1-padded."""
    bboxes, scores = x(ins, "BBoxes"), x(ins, "Scores")
    N, C, M = scores.shape
    keep_k = attrs["keep_top_k"]
    n_fg = C - (1 if 0 <= attrs["background_label"] < C else 0)
    if keep_k is None or keep_k < 0:
        keep_k = n_fg * M  # reference keep_top_k=-1: keep everything
    bg = attrs["background_label"]
    eta = attrs.get("nms_eta", 1.0)

    def per_image(bx, sc):
        rows = []
        for c in range(C):
            if c == bg:
                continue
            keep, order, sb, ss = _nms_class(
                bx, sc[c], attrs["nms_threshold"],
                attrs["score_threshold"], attrs["nms_top_k"], eta,
                attrs.get("normalized", True))
            lbl = jnp.full((M,), float(c), bx.dtype)
            row = jnp.concatenate([lbl[:, None], ss[:, None], sb], axis=1)
            rows.append(jnp.where(keep[:, None], row, -1.0))
        allr = jnp.concatenate(rows, 0)  # [(C-1)*M, 6]
        # take the keep_k highest-scored surviving rows
        score_col = jnp.where(allr[:, 0] >= 0, allr[:, 1], -jnp.inf)
        top = jnp.argsort(-score_col)[:keep_k]
        res = allr[top]
        return jnp.where(jnp.isfinite(score_col[top])[:, None], res, -1.0)

    return out(jax.vmap(per_image)(bboxes, scores))


def _roi_align_one(feat, roi, spatial_scale, ph, pw, sampling_ratio):
    """Bilinear ROI align for one roi on one image's features [C,H,W]
    (reference roi_align_op.h)."""
    C, H, W = feat.shape
    x0, y0, x1, y1 = roi[0] * spatial_scale, roi[1] * spatial_scale, \
        roi[2] * spatial_scale, roi[3] * spatial_scale
    rw = jnp.maximum(x1 - x0, 1.0)
    rh = jnp.maximum(y1 - y0, 1.0)
    bin_w = rw / pw
    bin_h = rh / ph
    # reference sampling_ratio<=0 adapts the grid to ceil(roi/pooled) PER
    # ROI — a data-dependent shape XLA cannot express; the static fallback
    # is a 4x4 grid (pass an explicit sampling_ratio for exact reference
    # parity)
    s = sampling_ratio if sampling_ratio > 0 else 4
    # sample points per bin: s x s grid
    iy = jnp.arange(ph).reshape(ph, 1, 1, 1)
    ix = jnp.arange(pw).reshape(1, pw, 1, 1)
    sy = jnp.arange(s).reshape(1, 1, s, 1)
    sx = jnp.arange(s).reshape(1, 1, 1, s)
    yy = y0 + iy * bin_h + (sy + 0.5) * bin_h / s
    xx = x0 + ix * bin_w + (sx + 0.5) * bin_w / s

    yy = jnp.clip(yy, 0.0, H - 1)
    xx = jnp.clip(xx, 0.0, W - 1)
    y_lo = jnp.floor(yy).astype(jnp.int32)
    x_lo = jnp.floor(xx).astype(jnp.int32)
    y_hi = jnp.minimum(y_lo + 1, H - 1)
    x_hi = jnp.minimum(x_lo + 1, W - 1)
    ly, lx = yy - y_lo, xx - x_lo

    def gather(yi, xi):
        return feat[:, yi, xi]  # [C, ph, pw, s, s]

    v = gather(y_lo, x_lo) * ((1 - ly) * (1 - lx)) + \
        gather(y_lo, x_hi) * ((1 - ly) * lx) + \
        gather(y_hi, x_lo) * (ly * (1 - lx)) + \
        gather(y_hi, x_hi) * (ly * lx)
    return v.mean(axis=(-2, -1))  # [C, ph, pw]


@register_op("roi_align",
             inputs=[IOSpec("X"), IOSpec("ROIs", no_grad=True),
                     IOSpec("RoisBatchIdx", optional=True, no_grad=True)],
             outputs=["Out"],
             attrs={"spatial_scale": 1.0, "pooled_height": 1,
                    "pooled_width": 1, "sampling_ratio": -1})
def _roi_align(ctx, ins, attrs):
    """ROIs [R, 4] (x0,y0,x1,y1 in image coords); RoisBatchIdx [R] int32
    maps each roi to its image (the reference uses the ROIs LoD)."""
    feat, rois = x(ins, "X"), x(ins, "ROIs")
    bidx = x(ins, "RoisBatchIdx")
    if bidx is None:
        bidx = jnp.zeros((rois.shape[0],), jnp.int32)

    def one(roi, bi):
        return _roi_align_one(feat[bi], roi, attrs["spatial_scale"],
                              attrs["pooled_height"],
                              attrs["pooled_width"],
                              attrs["sampling_ratio"])

    return out(jax.vmap(one)(rois, bidx.astype(jnp.int32)))


@register_op("roi_pool",
             inputs=[IOSpec("X"), IOSpec("ROIs", no_grad=True),
                     IOSpec("RoisBatchIdx", optional=True, no_grad=True)],
             outputs=["Out", IOSpec("Argmax", optional=True, no_grad=True)],
             attrs={"spatial_scale": 1.0, "pooled_height": 1,
                    "pooled_width": 1})
def _roi_pool(ctx, ins, attrs):
    """reference roi_pool_op.h: max pool over quantized bins."""
    feat, rois = x(ins, "X"), x(ins, "ROIs")
    bidx = x(ins, "RoisBatchIdx")
    if bidx is None:
        bidx = jnp.zeros((rois.shape[0],), jnp.int32)
    ph, pw = attrs["pooled_height"], attrs["pooled_width"]
    scale = attrs["spatial_scale"]
    H, W = feat.shape[2], feat.shape[3]
    neg = jnp.finfo(feat.dtype).min

    def one(roi, bi):
        f = feat[bi]  # [C,H,W]
        x0 = jnp.round(roi[0] * scale).astype(jnp.int32)
        y0 = jnp.round(roi[1] * scale).astype(jnp.int32)
        x1 = jnp.round(roi[2] * scale).astype(jnp.int32)
        y1 = jnp.round(roi[3] * scale).astype(jnp.int32)
        rh = jnp.maximum(y1 - y0 + 1, 1)
        rw = jnp.maximum(x1 - x0 + 1, 1)
        ys = jnp.arange(H).reshape(1, H, 1)
        xs = jnp.arange(W).reshape(1, 1, W)
        py = jnp.arange(ph).reshape(ph, 1, 1)
        px = jnp.arange(pw).reshape(pw, 1, 1)
        y_lo = y0 + jnp.floor(py * rh / ph).astype(jnp.int32)
        y_hi = y0 + jnp.ceil((py + 1) * rh / ph).astype(jnp.int32)
        x_lo = x0 + jnp.floor(px * rw / pw).astype(jnp.int32)
        x_hi = x0 + jnp.ceil((px + 1) * rw / pw).astype(jnp.int32)
        ymask = (ys >= y_lo) & (ys < y_hi)          # [ph, H, 1]
        xmask = (xs >= x_lo) & (xs < x_hi)          # [pw, 1, W]
        m = ymask[:, None, :, :] & xmask[None, :, :, :]  # [ph,pw,H,W]
        vals = jnp.where(m[None], f[:, None, None], neg)
        res = vals.max(axis=(-2, -1))               # [C, ph, pw]
        return jnp.where(res == neg, 0.0, res)

    return {"Out": [jax.vmap(one)(rois, bidx.astype(jnp.int32))]}


# ---------------------------------------------------------------------------
# RPN / FPN proposal pipeline
# ---------------------------------------------------------------------------


def _decode_proposals(anchors, deltas, variances):
    """bbox_deltas -> boxes around anchors (reference
    detection/generate_proposals_op.cc BoxCoder path, variance-scaled)."""
    wa = anchors[:, 2] - anchors[:, 0] + 1.0
    ha = anchors[:, 3] - anchors[:, 1] + 1.0
    cxa = anchors[:, 0] + 0.5 * wa
    cya = anchors[:, 1] + 0.5 * ha
    dx, dy, dw, dh = deltas[:, 0], deltas[:, 1], deltas[:, 2], deltas[:, 3]
    if variances is not None:
        dx = dx * variances[:, 0]
        dy = dy * variances[:, 1]
        dw = dw * variances[:, 2]
        dh = dh * variances[:, 3]
    # reference kBBoxClipDefault = log(1000/16): stop exp overflow
    clip = jnp.log(1000.0 / 16.0)
    cx = dx * wa + cxa
    cy = dy * ha + cya
    w = jnp.exp(jnp.minimum(dw, clip)) * wa
    h = jnp.exp(jnp.minimum(dh, clip)) * ha
    return jnp.stack([cx - 0.5 * w, cy - 0.5 * h,
                      cx + 0.5 * w - 1.0, cy + 0.5 * h - 1.0], axis=1)


@register_op("generate_proposals",
             inputs=[IOSpec("Scores", no_grad=True),
                     IOSpec("BboxDeltas", no_grad=True),
                     IOSpec("ImInfo", no_grad=True),
                     IOSpec("Anchors", no_grad=True),
                     IOSpec("Variances", optional=True, no_grad=True)],
             outputs=["RpnRois", "RpnRoiProbs", "RpnRoisNum"],
             attrs={"pre_nms_topN": 6000, "post_nms_topN": 1000,
                    "nms_thresh": 0.5, "min_size": 0.1, "eta": 1.0},
             grad=None)
def _generate_proposals(ctx, ins, attrs):
    """RPN proposal generation (reference
    detection/generate_proposals_op.cc): decode deltas around anchors, clip
    to image, drop boxes smaller than min_size (image-scale adjusted),
    keep pre_nms_topN by score, NMS, keep post_nms_topN. The reference's
    variable-length LoD output becomes fixed [N, post, 4] padded with -1 +
    a RpnRoisNum lengths vector (the repo's LoD encoding). NMS cost is the
    O(K^2) IoU matrix over K = min(pre_nms_topN, A*H*W) — keep pre_nms_topN
    moderate on TPU."""
    scores = x(ins, "Scores")            # [N, A, H, W]
    deltas = x(ins, "BboxDeltas")        # [N, 4A, H, W]
    im_info = x(ins, "ImInfo")           # [N, 3] (h, w, scale)
    anchors = x(ins, "Anchors").reshape(-1, 4)
    variances = x(ins, "Variances")
    if variances is not None:
        variances = variances.reshape(-1, 4)
    n, a, h, w = scores.shape
    k_all = a * h * w
    pre_k = min(int(attrs["pre_nms_topN"]), k_all)
    post_k = min(int(attrs["post_nms_topN"]), pre_k)
    nms_thresh = float(attrs["nms_thresh"])
    eta = float(attrs.get("eta", 1.0))
    min_size = max(float(attrs["min_size"]), 1.0)

    def per_image(sc, dl, info):
        s_flat = sc.transpose(1, 2, 0).reshape(-1)           # H,W,A order
        d_flat = dl.reshape(a, 4, h, w).transpose(2, 3, 0, 1).reshape(-1, 4)
        boxes = _decode_proposals(anchors, d_flat, variances)
        img_h, img_w, scale = info[0], info[1], info[2]
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0.0, img_w - 1.0),
            jnp.clip(boxes[:, 1], 0.0, img_h - 1.0),
            jnp.clip(boxes[:, 2], 0.0, img_w - 1.0),
            jnp.clip(boxes[:, 3], 0.0, img_h - 1.0)], axis=1)
        ws = boxes[:, 2] - boxes[:, 0] + 1.0
        hs = boxes[:, 3] - boxes[:, 1] + 1.0
        ms = min_size * scale
        valid = (ws >= ms) & (hs >= ms)
        s_masked = jnp.where(valid, s_flat, -jnp.inf)
        top = jnp.argsort(-s_masked)[:pre_k]
        tb, ts = boxes[top], s_masked[top]
        keep, order, sb, ss = _nms_class(
            tb, ts, nms_thresh, -jnp.inf, post_k, eta, normalized=False)
        rank = jnp.where(keep, jnp.cumsum(keep) - 1, post_k)
        rois = jnp.full((post_k, 4), -1.0, boxes.dtype)
        probs = jnp.zeros((post_k,), boxes.dtype)
        rois = rois.at[rank].set(sb, mode="drop")
        probs = probs.at[rank].set(ss, mode="drop")
        count = jnp.minimum(jnp.sum(keep), post_k).astype(jnp.int32)
        return rois, probs[:, None], count

    rois, probs, counts = jax.vmap(per_image)(scores, deltas, im_info)
    return {"RpnRois": [rois], "RpnRoiProbs": [probs],
            "RpnRoisNum": [counts]}


@register_op("distribute_fpn_proposals",
             inputs=[IOSpec("FpnRois", no_grad=True),
                     IOSpec("RoisNum", optional=True, no_grad=True)],
             outputs=[IOSpec("MultiFpnRois", duplicable=True),
                      IOSpec("MultiLevelRoIsNum", duplicable=True),
                      IOSpec("RestoreIndex")],
             attrs={"min_level": 2, "max_level": 5, "refer_level": 4,
                    "refer_scale": 224, "pixel_offset": True}, grad=None)
def _distribute_fpn_proposals(ctx, ins, attrs):
    """Route each RoI to its FPN level (reference
    detection/distribute_fpn_proposals_op.cc): level = floor(log2(
    sqrt(area) / refer_scale)) + refer_level, clipped to [min, max].
    Per-level outputs are [R, 4] front-compacted and -1 padded with a
    lengths vector each; RestoreIndex is the permutation that rebuilds the
    original order from the level-sorted concatenation."""
    rois = x(ins, "FpnRois")             # [R, 4], -1-padded rows possible
    r = rois.shape[0]
    rois_num = x(ins, "RoisNum")
    # padding rows (generate_proposals pads with -1 and reports RpnRoisNum)
    # must reach NO level: they'd otherwise compute w=h=1 and flood min_level
    valid = rois[:, 2] >= 0
    if rois_num is not None:
        # per-image layout: counts[i] valid rows lead each equal-size block
        # (exactly what a flattened [N, post, 4] from generate_proposals
        # is); a single count degenerates to the whole-tensor prefix
        counts = rois_num.reshape(-1)
        n_img = counts.shape[0]
        if r % n_img == 0:
            blk = r // n_img
            pos = jnp.arange(r)
            valid = valid & ((pos % blk) < counts[pos // blk])
        else:
            valid = valid & (jnp.arange(r) < counts.sum())
    off = 1.0 if attrs.get("pixel_offset", True) else 0.0
    ws = rois[:, 2] - rois[:, 0] + off
    hs = rois[:, 3] - rois[:, 1] + off
    lo, hi = int(attrs["min_level"]), int(attrs["max_level"])
    scale = jnp.sqrt(jnp.maximum(ws * hs, 1e-6))
    lvl = jnp.floor(jnp.log2(scale / float(attrs["refer_scale"]) + 1e-12)) \
        + int(attrs["refer_level"])
    lvl = jnp.clip(lvl, lo, hi).astype(jnp.int32)
    lvl = jnp.where(valid, lvl, hi + 1)         # overflow level: collected
    #                                             by nothing, sorts last

    order = jnp.argsort(lvl, stable=True)       # original idx, level-sorted
    restore = jnp.argsort(order, stable=True).astype(jnp.int32)
    restore = jnp.where(valid, restore, -1)

    multi_rois, multi_num = [], []
    for level in range(lo, hi + 1):
        is_l = lvl == level
        cnt = jnp.sum(is_l).astype(jnp.int32)
        rank = jnp.where(is_l, jnp.cumsum(is_l) - 1, r)
        out_l = jnp.full((r, 4), -1.0, rois.dtype).at[rank].set(
            rois, mode="drop")
        multi_rois.append(out_l)
        multi_num.append(cnt.reshape((1,)))
    return {"MultiFpnRois": multi_rois, "MultiLevelRoIsNum": multi_num,
            "RestoreIndex": [restore[:, None]]}


@register_op("collect_fpn_proposals",
             inputs=[IOSpec("MultiLevelRois", duplicable=True, no_grad=True),
                     IOSpec("MultiLevelScores", duplicable=True,
                             no_grad=True),
                     IOSpec("MultiLevelRoIsNum", duplicable=True,
                             optional=True, no_grad=True)],
             outputs=["FpnRois", "RoisNum"],
             attrs={"post_nms_topN": 100}, grad=None)
def _collect_fpn_proposals(ctx, ins, attrs):
    """Merge per-level proposals and keep the post_nms_topN best by score
    (reference detection/collect_fpn_proposals_op.cc). Padded rows
    (negative coords) are treated as absent."""
    rois_list = [v for v in ins.get("MultiLevelRois", []) if v is not None]
    score_list = [v for v in ins.get("MultiLevelScores", []) if v is not None]
    all_rois = jnp.concatenate(rois_list, axis=0)
    all_scores = jnp.concatenate(
        [s.reshape(-1) for s in score_list], axis=0)
    valid = all_rois[:, 2] >= 0
    masked = jnp.where(valid, all_scores, -jnp.inf)
    k = min(int(attrs["post_nms_topN"]), all_rois.shape[0])
    top = jnp.argsort(-masked)[:k]
    sel = all_rois[top]
    sel_valid = jnp.isfinite(masked[top])
    sel = jnp.where(sel_valid[:, None], sel, -1.0)
    count = jnp.sum(sel_valid).astype(jnp.int32)
    return {"FpnRois": [sel], "RoisNum": [count.reshape((1,))]}
