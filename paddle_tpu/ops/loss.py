"""Loss + metric + reduce ops.

References: paddle/fluid/operators/{softmax_with_cross_entropy,cross_entropy,
mean,reduce_ops/*,metrics/*,smooth_l1_loss,huber_loss,sigmoid_cross_entropy...}
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.types import jnp_dtype
from .common import IOSpec, out, register_op, x


@register_op("mean", inputs=["X"], outputs=["Out"])
def _mean(ctx, ins, attrs):
    return out(jnp.mean(x(ins)))


def _reduce(fn):
    def lower(ctx, ins, attrs):
        xv = x(ins)
        if attrs.get("reduce_all"):
            axes = None
        else:
            axes = tuple(a if a >= 0 else a + xv.ndim for a in attrs.get("dim", [0]))
        return out(fn(xv, axis=axes, keepdims=attrs.get("keep_dim", False)))

    return lower


for _name, _fn in [
    ("reduce_sum", jnp.sum),
    ("reduce_mean", jnp.mean),
    ("reduce_max", jnp.max),
    ("reduce_min", jnp.min),
    ("reduce_prod", jnp.prod),
]:
    register_op(_name, inputs=["X"], outputs=["Out"],
                attrs={"dim": [0], "keep_dim": False, "reduce_all": False})(_reduce(_fn))

register_op("reduce_all", inputs=["X"], outputs=["Out"],
            attrs={"dim": [0], "keep_dim": False, "reduce_all": False},
            grad=None)(_reduce(jnp.all))
register_op("reduce_any", inputs=["X"], outputs=["Out"],
            attrs={"dim": [0], "keep_dim": False, "reduce_all": False},
            grad=None)(_reduce(jnp.any))


@register_op("softmax_with_cross_entropy",
             inputs=[IOSpec("Logits"), IOSpec("Label", no_grad=True)],
             outputs=["Softmax", "Loss"],
             attrs={"soft_label": False, "ignore_index": -100, "axis": -1,
                    "numeric_stable_mode": True})
def _softmax_with_cross_entropy(ctx, ins, attrs):
    logits, label = x(ins, "Logits"), x(ins, "Label")
    axis = attrs.get("axis", -1)
    logp = jax.nn.log_softmax(logits, axis=axis)
    softmax = jnp.exp(logp)
    if attrs.get("soft_label"):
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
    else:
        lbl = label
        if lbl.ndim == logits.ndim and lbl.shape[axis] == 1:
            pass
        else:
            lbl = jnp.expand_dims(lbl, axis)
        lbl = lbl.astype(jnp.int32)
        ig = attrs.get("ignore_index", -100)
        ignored = lbl == ig
        safe_lbl = jnp.where(ignored, 0, lbl)  # avoid OOB wrap on gather
        picked = jnp.take_along_axis(logp, safe_lbl, axis=axis)
        loss = jnp.where(ignored, 0.0, -picked)
    return {"Softmax": [softmax], "Loss": [loss]}


@register_op("cross_entropy",
             inputs=[IOSpec("X"), IOSpec("Label", no_grad=True)],
             outputs=["Y"],
             attrs={"soft_label": False, "ignore_index": -100})
def _cross_entropy(ctx, ins, attrs):
    xv, label = x(ins, "X"), x(ins, "Label")
    eps = 1e-12
    if attrs.get("soft_label"):
        y = -jnp.sum(label * jnp.log(xv + eps), axis=-1, keepdims=True)
    else:
        lbl = label if label.ndim == xv.ndim else jnp.expand_dims(label, -1)
        lbl = lbl.astype(jnp.int32)
        ig = attrs.get("ignore_index", -100)
        ignored = lbl == ig
        picked = jnp.take_along_axis(xv, jnp.where(ignored, 0, lbl), axis=-1)
        y = jnp.where(ignored, 0.0, -jnp.log(picked + eps))
    return {"Y": [y]}


@register_op("sigmoid_cross_entropy_with_logits",
             inputs=[IOSpec("X"), IOSpec("Label", no_grad=True)],
             outputs=["Out"], attrs={"ignore_index": -100, "normalize": False})
def _sigmoid_ce(ctx, ins, attrs):
    xv, lbl = x(ins, "X"), x(ins, "Label")
    loss = jnp.maximum(xv, 0) - xv * lbl + jnp.log1p(jnp.exp(-jnp.abs(xv)))
    ignored = lbl == attrs.get("ignore_index", -100)
    loss = jnp.where(ignored, 0.0, loss)
    if attrs.get("normalize"):
        valid = jnp.maximum(jnp.sum((~ignored).astype(loss.dtype)), 1.0)
        loss = loss / valid
    return out(loss)


@register_op("square_error_cost", inputs=["X", "Y"], outputs=["Out"])
def _square_error_cost(ctx, ins, attrs):
    return out(jnp.square(x(ins, "X") - x(ins, "Y")))


@register_op("huber_loss", inputs=[IOSpec("X"), IOSpec("Y", no_grad=True)],
             outputs=["Out", "Residual"], attrs={"delta": 1.0})
def _huber_loss(ctx, ins, attrs):
    xv, yv = x(ins, "X"), x(ins, "Y")
    d = attrs["delta"]
    r = yv - xv
    ar = jnp.abs(r)
    loss = jnp.where(ar <= d, 0.5 * r * r, d * (ar - 0.5 * d))
    return {"Out": [loss], "Residual": [r]}


@register_op("smooth_l1_loss",
             inputs=[IOSpec("X"), IOSpec("Y", no_grad=True),
                     IOSpec("InsideWeight", optional=True, no_grad=True),
                     IOSpec("OutsideWeight", optional=True, no_grad=True)],
             outputs=["Out", "Diff"], attrs={"sigma": 1.0})
def _smooth_l1(ctx, ins, attrs):
    xv, yv = x(ins, "X"), x(ins, "Y")
    iw, ow = x(ins, "InsideWeight"), x(ins, "OutsideWeight")
    sigma2 = attrs["sigma"] ** 2
    diff = xv - yv
    if iw is not None:
        diff = diff * iw
    ad = jnp.abs(diff)
    loss = jnp.where(ad < 1.0 / sigma2, 0.5 * sigma2 * diff * diff,
                     ad - 0.5 / sigma2)
    if ow is not None:
        loss = loss * ow
    loss = jnp.sum(loss.reshape(xv.shape[0], -1), axis=1, keepdims=True)
    return {"Out": [loss], "Diff": [diff]}


@register_op("log_loss", inputs=[IOSpec("Predicted"), IOSpec("Labels", no_grad=True)],
             outputs=["Loss"], attrs={"epsilon": 1e-4})
def _log_loss(ctx, ins, attrs):
    p, l = x(ins, "Predicted"), x(ins, "Labels")
    eps = attrs["epsilon"]
    return {"Loss": [-l * jnp.log(p + eps) - (1 - l) * jnp.log(1 - p + eps)]}


@register_op("accuracy",
             inputs=[IOSpec("Out", no_grad=True), IOSpec("Indices", no_grad=True),
                     IOSpec("Label", no_grad=True)],
             outputs=["Accuracy", "Correct", "Total"], grad=None)
def _accuracy(ctx, ins, attrs):
    """Reference metrics/accuracy_op: Indices is the top-k index matrix."""
    idx, label = x(ins, "Indices"), x(ins, "Label")
    lbl = label.reshape((-1, 1)).astype(idx.dtype)
    correct_k = jnp.any(idx == lbl, axis=1)
    num_correct = jnp.sum(correct_k.astype(jnp.float32))
    total = jnp.asarray(idx.shape[0], jnp.int32)
    return {"Accuracy": [(num_correct / idx.shape[0]).reshape((1,))],
            "Correct": [num_correct.astype(jnp.int32).reshape((1,))],
            "Total": [total.reshape((1,))]}


@register_op("auc",
             inputs=[IOSpec("Predict", no_grad=True), IOSpec("Label", no_grad=True),
                     IOSpec("StatPos", no_grad=True), IOSpec("StatNeg", no_grad=True)],
             outputs=["AUC", "StatPosOut", "StatNegOut"],
             attrs={"curve": "ROC", "num_thresholds": 4095}, grad=None)
def _auc(ctx, ins, attrs):
    pred, label = x(ins, "Predict"), x(ins, "Label")
    pos_stat, neg_stat = x(ins, "StatPos"), x(ins, "StatNeg")
    nt = attrs["num_thresholds"]
    p1 = pred[:, 1] if pred.ndim == 2 and pred.shape[1] == 2 else pred.reshape(-1)
    lbl = label.reshape(-1).astype(jnp.float32)
    bins = jnp.clip((p1 * nt).astype(jnp.int32), 0, nt)
    pos_add = jnp.zeros((nt + 1,), jnp_dtype("int64")).at[bins].add(lbl.astype(jnp_dtype("int64")))
    neg_add = jnp.zeros((nt + 1,), jnp_dtype("int64")).at[bins].add((1 - lbl).astype(jnp_dtype("int64")))
    pos = pos_stat.reshape(-1) + pos_add
    neg = neg_stat.reshape(-1) + neg_add
    # trapezoid over thresholds descending
    tp = jnp.cumsum(pos[::-1])
    fp = jnp.cumsum(neg[::-1])
    tot_pos, tot_neg = tp[-1], fp[-1]
    tpr = tp / jnp.maximum(tot_pos, 1)
    fpr = fp / jnp.maximum(tot_neg, 1)
    auc = jnp.trapezoid(tpr, fpr)
    return {"AUC": [auc.reshape((1,)).astype(jnp.float64)
                    if auc.dtype == jnp.float64 else auc.reshape((1,))],
            "StatPosOut": [pos.reshape(pos_stat.shape)],
            "StatNegOut": [neg.reshape(neg_stat.shape)]}


@register_op("kldiv_loss", inputs=[IOSpec("X"), IOSpec("Target", no_grad=True)],
             outputs=["Loss"], attrs={"reduction": "mean"})
def _kldiv_loss(ctx, ins, attrs):
    """reference kldiv_loss_op.h: x is log-prob, target is prob."""
    xv, t = x(ins, "X"), x(ins, "Target")
    loss = t * (jnp.where(t > 0, jnp.log(jnp.where(t > 0, t, 1.0)), 0.0) - xv)
    red = attrs.get("reduction", "mean")
    if red == "mean":
        return {"Loss": [jnp.mean(loss)]}
    if red == "sum":
        return {"Loss": [jnp.sum(loss)]}
    if red == "batchmean":
        return {"Loss": [jnp.sum(loss) / xv.shape[0]]}
    return {"Loss": [loss]}


@register_op("hinge_loss", inputs=[IOSpec("Logits"),
                                   IOSpec("Labels", no_grad=True)],
             outputs=["Loss"])
def _hinge_loss(ctx, ins, attrs):
    """reference hinge_loss_op.h: labels in {0,1}."""
    logits, labels = x(ins, "Logits"), x(ins, "Labels")
    signs = 2.0 * labels.astype(logits.dtype) - 1.0
    return {"Loss": [jnp.maximum(0.0, 1.0 - signs * logits)]}


@register_op("margin_rank_loss",
             inputs=[IOSpec("Label", no_grad=True), IOSpec("X1"),
                     IOSpec("X2")],
             outputs=["Out", IOSpec("Activated", no_grad=True)],
             attrs={"margin": 0.0})
def _margin_rank_loss(ctx, ins, attrs):
    lbl, x1, x2 = x(ins, "Label"), x(ins, "X1"), x(ins, "X2")
    raw = -lbl * (x1 - x2) + attrs["margin"]
    return {"Out": [jnp.maximum(0.0, raw)],
            "Activated": [(raw > 0).astype(x1.dtype)]}


@register_op("rank_loss", inputs=[IOSpec("Label", no_grad=True),
                                  IOSpec("Left"), IOSpec("Right")],
             outputs=["Out"])
def _rank_loss(ctx, ins, attrs):
    """reference rank_loss_op.h: RankNet pairwise loss."""
    lbl, l, r = x(ins, "Label"), x(ins, "Left"), x(ins, "Right")
    d = l - r
    return out(jnp.logaddexp(0.0, d) - lbl * d)


@register_op("bpr_loss", inputs=[IOSpec("X"), IOSpec("Label", no_grad=True)],
             outputs=["Y"])
def _bpr_loss(ctx, ins, attrs):
    """reference bpr_loss_op.h: Bayesian Personalized Ranking over logits
    [N, C] with positive-item label [N, 1]."""
    xv, lbl = x(ins, "X"), x(ins, "Label")
    pos = jnp.take_along_axis(xv, lbl.reshape(-1, 1).astype(jnp.int32), 1)
    diff = pos - xv  # [N, C]
    n, c = xv.shape
    loss = -jnp.log(jax.nn.sigmoid(diff) + 1e-8)
    mask = jnp.ones((n, c), xv.dtype).at[
        jnp.arange(n), lbl.reshape(-1).astype(jnp.int32)].set(0.0)
    return {"Y": [(loss * mask).sum(1, keepdims=True) / (c - 1)]}


@register_op("cos_sim", inputs=[IOSpec("X"), IOSpec("Y")],
             outputs=["Out", IOSpec("XNorm", optional=True, no_grad=True),
                      IOSpec("YNorm", optional=True, no_grad=True)])
def _cos_sim(ctx, ins, attrs):
    xv, yv = x(ins, "X"), x(ins, "Y")
    xn = jnp.sqrt((xv * xv).sum(-1, keepdims=True))
    yn = jnp.sqrt((yv * yv).sum(-1, keepdims=True))
    return {"Out": [(xv * yv).sum(-1, keepdims=True) / (xn * yn + 1e-12)],
            "XNorm": [xn], "YNorm": [yn]}


@register_op("mean_iou", inputs=[IOSpec("Predictions", no_grad=True),
                                 IOSpec("Labels", no_grad=True)],
             outputs=["OutMeanIou", "OutWrong", "OutCorrect"],
             attrs={"num_classes": 2}, grad=None)
def _mean_iou(ctx, ins, attrs):
    """reference mean_iou_op.h: mean IoU over classes present."""
    pred = x(ins, "Predictions").reshape(-1).astype(jnp.int32)
    lbl = x(ins, "Labels").reshape(-1).astype(jnp.int32)
    nc = attrs["num_classes"]
    inter = jnp.zeros((nc,), jnp.float32).at[
        jnp.where(pred == lbl, pred, nc - 1)].add(
        (pred == lbl).astype(jnp.float32))
    area_p = jnp.zeros((nc,), jnp.float32).at[pred].add(1.0)
    area_l = jnp.zeros((nc,), jnp.float32).at[lbl].add(1.0)
    union = area_p + area_l - inter
    present = union > 0
    iou = jnp.where(present, inter / jnp.where(present, union, 1.0), 0.0)
    miou = iou.sum() / jnp.maximum(present.sum(), 1)
    # reference increments wrong for BOTH the predicted and true class on a
    # mismatch, so accumulated correct+wrong reconstructs the union
    miss = (pred != lbl).astype(jnp.float32)
    wrong = (jnp.zeros((nc,), jnp.float32).at[pred].add(miss)
             .at[lbl].add(miss)).astype(jnp.int32)
    correct = inter.astype(jnp.int32)
    return {"OutMeanIou": [miou], "OutWrong": [wrong],
            "OutCorrect": [correct]}


@register_op("precision_recall",
             inputs=[IOSpec("MaxProbs", no_grad=True),
                     IOSpec("Indices", no_grad=True),
                     IOSpec("Labels", no_grad=True),
                     IOSpec("Weights", optional=True, no_grad=True),
                     IOSpec("StatesInfo", optional=True, no_grad=True)],
             outputs=["BatchMetrics", "AccumMetrics", "AccumStatesInfo"],
             attrs={"class_number": 2}, grad=None)
def _precision_recall(ctx, ins, attrs):
    """reference precision_recall_op.h: per-class TP/FP/FN stats ->
    (macro/micro precision, recall, F1) for the batch and accumulated."""
    idx = x(ins, "Indices").reshape(-1).astype(jnp.int32)
    lbl = x(ins, "Labels").reshape(-1).astype(jnp.int32)
    wts = x(ins, "Weights")
    w = jnp.ones(idx.shape, jnp.float32) if wts is None \
        else wts.reshape(-1).astype(jnp.float32)
    nc = attrs["class_number"]
    hit = (idx == lbl).astype(jnp.float32) * w
    miss = (idx != lbl).astype(jnp.float32) * w
    tp = jnp.zeros((nc,), jnp.float32).at[
        jnp.where(idx == lbl, idx, 0)].add(hit)
    fp = jnp.zeros((nc,), jnp.float32).at[idx].add(miss)
    fn = jnp.zeros((nc,), jnp.float32).at[lbl].add(miss)
    states = jnp.stack([tp, fp, jnp.zeros_like(tp), fn], axis=1)  # [C,4]
    prev = x(ins, "StatesInfo")
    acc_states = states if prev is None else states + prev

    def metrics(s):
        tp_, fp_, _, fn_ = s[:, 0], s[:, 1], s[:, 2], s[:, 3]
        prec = jnp.where(tp_ + fp_ > 0, tp_ / (tp_ + fp_ + 1e-12), 0.0)
        rec = jnp.where(tp_ + fn_ > 0, tp_ / (tp_ + fn_ + 1e-12), 0.0)
        f1 = jnp.where(prec + rec > 0, 2 * prec * rec / (prec + rec + 1e-12),
                       0.0)
        macro = jnp.stack([prec.mean(), rec.mean(), f1.mean()])
        tps, fps, fns = tp_.sum(), fp_.sum(), fn_.sum()
        mp = jnp.where(tps + fps > 0, tps / (tps + fps + 1e-12), 0.0)
        mr = jnp.where(tps + fns > 0, tps / (tps + fns + 1e-12), 0.0)
        mf = jnp.where(mp + mr > 0, 2 * mp * mr / (mp + mr + 1e-12), 0.0)
        return jnp.concatenate([macro, jnp.stack([mp, mr, mf])])

    return {"BatchMetrics": [metrics(states)],
            "AccumMetrics": [metrics(acc_states)],
            "AccumStatesInfo": [acc_states]}
