"""Fake-quantization ops for quantization-aware training.

Reference: paddle/fluid/operators/fake_quantize_op.{cc,h} —
FakeQuantizeAbsMax / FakeQuantizeDequantizeMovingAverageAbsMax, inserted by
the slim QuantizationTransformPass. Forward simulates int8 rounding;
backward is the straight-through estimator (grad passes unchanged), which
here falls out of writing the output as x + stop_gradient(quant(x) - x) —
no custom grad registration needed under jax.vjp.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .common import IOSpec, out, register_op, x


def _fake_quant(v, scale, bits):
    qmax = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(scale, 1e-8)
    q = jnp.round(jnp.clip(v / s, -1.0, 1.0) * qmax) / qmax * s
    # straight-through estimator: identity gradient, quantized value
    return v + jax.lax.stop_gradient(q - v)


@register_op("fake_quantize_dequantize_abs_max",
             inputs=[IOSpec("X")],
             outputs=["Out", IOSpec("OutScale", no_grad=True)],
             attrs={"bit_length": 8})
def _fake_quant_abs_max(ctx, ins, attrs):
    """Per-tensor abs-max scale computed in-graph (reference
    fake_quantize_op.h FindAbsMaxFunctor + ClipAndFakeQuantFunctor)."""
    v = x(ins)
    scale = jnp.max(jnp.abs(v))
    return {"Out": [_fake_quant(v, scale, attrs["bit_length"])],
            "OutScale": [scale.reshape((1,))]}


@register_op("fake_quantize_dequantize_moving_average_abs_max",
             inputs=[IOSpec("X"), IOSpec("InScale", no_grad=True)],
             outputs=["Out", IOSpec("OutScale", no_grad=True)],
             attrs={"bit_length": 8, "moving_rate": 0.9, "is_test": False})
def _fake_quant_moving_avg(ctx, ins, attrs):
    """Activation quantization: the scale is an exponential moving average
    of batch abs-maxes held in a persistable var (reference
    FakeQuantizeDequantizeMovingAverageAbsMaxOp state)."""
    v, in_scale = x(ins, "X"), x(ins, "InScale")
    rate = attrs["moving_rate"]
    cur = jnp.max(jnp.abs(v))
    if attrs.get("is_test"):
        scale = in_scale.reshape(())
    else:
        scale = rate * in_scale.reshape(()) + (1 - rate) * cur
    return {"Out": [_fake_quant(v, scale, attrs["bit_length"])],
            "OutScale": [scale.reshape((1,))]}
