"""Operator library: importing this package registers all lowering rules."""
from . import math  # noqa: F401
from . import tensor  # noqa: F401
from . import nn  # noqa: F401
from . import loss  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import control_flow  # noqa: F401
from . import amp_ops  # noqa: F401
from . import recompute  # noqa: F401
from . import sequence_ops  # noqa: F401
from . import rnn_ops  # noqa: F401
from . import generation  # noqa: F401
from . import detection  # noqa: F401
from . import quant_ops  # noqa: F401
from . import fused_attention  # noqa: F401
from . import fused_gemm  # noqa: F401
from . import pipeline_op  # noqa: F401
from . import image  # noqa: F401
from . import misc  # noqa: F401
from . import misc2  # noqa: F401
from . import structured  # noqa: F401

from ..core.registry import all_ops, get_op_def, has_op, register_op  # noqa: F401
