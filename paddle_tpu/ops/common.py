"""Shared helpers for op lowering rules."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.registry import IOSpec, register_op  # re-export for op modules

__all__ = ["register_op", "IOSpec", "x", "out", "broadcast_to_x", "unary"]


def x(ins, slot="X", i=0):
    """Fetch the i-th value of a slot (None if absent)."""
    vals = ins.get(slot)
    if not vals:
        return None
    return vals[i] if i < len(vals) else None


def out(val, slot="Out"):
    return {slot: [val]}


def broadcast_to_x(xv, yv, axis: int):
    """Reference elementwise broadcast rule (elementwise_op_function.h):
    Y's shape must match a contiguous span of X's dims starting at ``axis``
    (axis==-1 means align trailing dims, i.e. numpy broadcasting)."""
    if xv.shape == yv.shape:
        return yv
    if axis == -1 or axis is None:
        return yv  # numpy trailing-dim broadcasting handles it
    pad_left = axis
    pad_right = xv.ndim - axis - yv.ndim
    if pad_right < 0:
        raise ValueError(
            f"elementwise axis={axis} incompatible: x{xv.shape} y{yv.shape}"
        )
    return yv.reshape((1,) * pad_left + yv.shape + (1,) * pad_right)


def unary(op_type, fn, **kwargs):
    """Register a single-input single-output elementwise op."""

    @register_op(op_type, inputs=["X"], outputs=["Out"], **kwargs)
    def _lower(ctx, ins, attrs, _fn=fn):
        return out(_fn(x(ins)))

    return _lower
