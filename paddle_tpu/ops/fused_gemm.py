"""``fused_gemm_epilogue``: the op the epilogue-fusion pass rewrites
mul/matmul → elementwise_add → activation → residual → layer_norm chains
into (analysis/epilogue_fusion.py; CODA, PAPERS.md).

Routing mirrors fused_attention.py:

- TPU backend + supported tiling -> the Pallas fused-GEMM kernel
  (kernels/fused_gemm.py): the whole epilogue runs on the in-VMEM f32
  accumulator tile;
- anything else -> a dense replay of the ORIGINAL unfused op rules, in the
  original order, with the program's AMP policy applied per sub-op exactly
  as ``lowering._lower_op_inner`` would — bit-exact against the unfused
  program by construction (this is what makes the fusion pass's fidelity
  witness an equality check off-TPU).

``FLAGS_use_fused_gemm`` = auto|always|never picks the path; ``always``
off-TPU runs the kernel in interpret mode (slow — tests only) and raises
loudly on unsupported tilings instead of silently falling back.

Kernel block sizes resolve, in order: ``FLAGS_fused_gemm_blocks``
("m,n,k") > the autotuner's best-known config threaded into this
compile's ``LowerCtx.gemm_blocks`` (paddle_tpu.tuning, via the
executor's ``_tuned_compile_config``) > (128, 128, 128).
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import flags
from ..core import registry
from .common import IOSpec, register_op, x

__all__ = ["fused_gemm_route", "resolve_gemm_blocks"]


def resolve_gemm_blocks(ctx=None) -> Tuple[int, int, int]:
    """(block_m, block_n, block_k) for the kernel path: explicit flag wins,
    then the autotuner blocks the executor bound into this compile's
    ``LowerCtx`` (per-compile, never a shared Program attribute — the
    values traced are the values in the compile-cache key even under
    concurrent compiles), then the defaults."""
    from ..kernels.fused_gemm import DEFAULT_BLOCKS

    raw = str(flags.flag("fused_gemm_blocks")).strip()
    if raw:
        parts = [p for p in raw.replace("x", ",").split(",") if p.strip()]
        if len(parts) != 3:
            raise ValueError(
                f"FLAGS_fused_gemm_blocks must be 'm,n,k', got {raw!r}")
        return tuple(int(p) for p in parts)
    tuned = getattr(ctx, "gemm_blocks", None)
    if tuned:
        return tuple(int(b) for b in tuned)
    return DEFAULT_BLOCKS


def fused_gemm_route(m: int, n: int, k: int, *, layer_norm: bool,
                     blocks: Tuple[int, int, int],
                     alpha: float = 1.0) -> Tuple[str, str]:
    """('pallas' | 'pallas-interpret' | 'primitive', reason). The single
    route authority: the op lowering, the fusion pass's fidelity witness
    and its PT755 reporting must all agree on which path runs."""
    from ..kernels.fused_gemm import classify_gemm

    mode = flags.flag("use_fused_gemm")
    if mode == "never":
        return "primitive", "FLAGS_use_fused_gemm=never"
    if alpha != 1.0:
        # the kernel computes X@Y + epilogue; an alpha-scaled matmul
        # always replays the dense rules (not an 'always'-mode error —
        # there is no kernel variant to insist on)
        return "primitive", f"alpha={alpha} != 1 runs the dense replay"
    kind, reason = classify_gemm(m, n, k, layer_norm=layer_norm,
                                 block_m=blocks[0], block_n=blocks[1],
                                 block_k=blocks[2])
    if kind != "supported":
        if mode == "always":
            # loud, not a silent dense fallback: 'always' is a promise
            raise ValueError(
                f"FLAGS_use_fused_gemm=always but (m={m}, n={n}, k={k}) "
                f"has no kernel tiling: {reason}")
        return "primitive", reason
    if jax.default_backend() == "tpu":
        return "pallas", reason
    if mode == "always":
        return "pallas-interpret", reason
    return "primitive", f"non-TPU backend ({reason})"


def _amp_cast(ctx, op_type: str, ins: dict) -> dict:
    """Apply the program's AMP policy to one replayed sub-op, exactly as
    ``lowering._lower_op_inner`` does for the unfused chain."""
    policy = getattr(ctx.program, "_amp_policy", None) if ctx.program \
        else None
    if policy is None:
        return ins
    return policy.cast_ins(op_type, ins)


def _replay(ctx, op_type: str, ins: dict, attrs: dict):
    """Run one original op rule over concrete/traced values (the dense
    fallback path and the witness both go through here)."""
    opdef = registry.get_op_def(op_type)
    full = dict(opdef.attrs and {k: v.default for k, v in
                                 opdef.attrs.items()} or {})
    full.update(attrs)
    return opdef.lower(ctx, _amp_cast(ctx, op_type, ins), full)


def _base_attrs(attrs: dict) -> dict:
    if attrs["base_type"] == "mul":
        return {"x_num_col_dims": attrs["x_num_col_dims"],
                "y_num_col_dims": attrs["y_num_col_dims"]}
    return {"transpose_X": attrs["transpose_X"],
            "transpose_Y": attrs["transpose_Y"],
            "alpha": attrs["alpha"]}


def _primitive_chain(ctx, xv, yv, bias, residual, ln_scale, ln_bias, attrs):
    """The unfused chain, op rule by op rule, in the matched order —
    bit-exact against the original program (same rules, same AMP casts,
    same dtype promotions)."""
    cur = _replay(ctx, attrs["base_type"], {"X": [xv], "Y": [yv]},
                  _base_attrs(attrs))["Out"][0]
    if bias is not None:
        cur = _replay(ctx, "elementwise_add", {"X": [cur], "Y": [bias]},
                      {"axis": attrs["bias_axis"]})["Out"][0]
    act = attrs["activation"]
    if act == "relu":
        cur = _replay(ctx, "relu", {"X": [cur]}, {})["Out"][0]
    elif act == "gelu":
        cur = _replay(ctx, "gelu", {"X": [cur]},
                      {"approximate": attrs["gelu_approximate"]})["Out"][0]
    if residual is not None:
        cur = _replay(ctx, "elementwise_add", {"X": [cur], "Y": [residual]},
                      {"axis": attrs["residual_axis"]})["Out"][0]
    if attrs["layer_norm"]:
        ins = {"X": [cur], "Scale": [ln_scale], "Bias": [ln_bias]}
        cur = _replay(ctx, "layer_norm", ins,
                      {"epsilon": attrs["epsilon"],
                       "begin_norm_axis": attrs["begin_norm_axis"]})["Y"][0]
    return cur


def _gemm_2d_view(xv, yv, attrs):
    """(x2 [M,K], y2 [K,N], out_shape) — the strictly-2-D view the kernel
    computes in; mirrors the mul/matmul rules' own reshapes."""
    if attrs["base_type"] == "mul":
        xnc, ync = attrs["x_num_col_dims"], attrs["y_num_col_dims"]
        xs, ys = xv.shape, yv.shape
        x2 = xv.reshape((int(np.prod(xs[:xnc])), int(np.prod(xs[xnc:]))))
        y2 = yv.reshape((int(np.prod(ys[:ync])), int(np.prod(ys[ync:]))))
        return x2, y2, xs[:xnc] + ys[ync:]
    x2 = jnp.swapaxes(xv, -1, -2) if attrs["transpose_X"] else xv
    y2 = jnp.swapaxes(yv, -1, -2) if attrs["transpose_Y"] else yv
    return x2, y2, (x2.shape[0], y2.shape[1])


@register_op("fused_gemm_epilogue",
             inputs=[IOSpec("X"), IOSpec("Y"),
                     IOSpec("Bias", optional=True, no_grad=True),
                     IOSpec("Residual", optional=True),
                     IOSpec("LnScale", optional=True, no_grad=True),
                     IOSpec("LnBias", optional=True, no_grad=True)],
             outputs=["Out"],
             attrs={"base_type": "mul",
                    "x_num_col_dims": 1, "y_num_col_dims": 1,
                    "transpose_X": False, "transpose_Y": False, "alpha": 1.0,
                    "activation": "none", "gelu_approximate": False,
                    "bias_axis": -1, "residual_axis": -1,
                    "layer_norm": False, "epsilon": 1e-5,
                    "begin_norm_axis": -1},
             grad=None)
def _fused_gemm_epilogue(ctx, ins, attrs):
    """Out = epilogue(X [mul|matmul] Y): bias-add, relu/gelu, residual-add,
    layer_norm — folded into the GEMM on the kernel route, replayed rule by
    rule on the dense route. Only the epilogue-fusion pass emits this op
    (its matcher guarantees the attr/shape invariants); it never carries a
    backward (the pass refuses training programs), so ``grad=None``."""
    xv, yv = x(ins, "X"), x(ins, "Y")
    bias = x(ins, "Bias")
    residual = x(ins, "Residual")
    ln_scale, ln_bias = x(ins, "LnScale"), x(ins, "LnBias")

    blocks = resolve_gemm_blocks(ctx)
    x2, y2, out_shape = _gemm_2d_view(xv, yv, attrs)
    m, k = int(x2.shape[0]), int(x2.shape[1])
    n = int(y2.shape[1])
    route, _reason = fused_gemm_route(
        m, n, k, layer_norm=bool(attrs["layer_norm"]), blocks=blocks,
        alpha=float(attrs.get("alpha", 1.0)))
    if route == "primitive":
        return {"Out": [_primitive_chain(ctx, xv, yv, bias, residual,
                                         ln_scale, ln_bias, attrs)]}

    from ..kernels.fused_gemm import fused_gemm

    policy = getattr(ctx.program, "_amp_policy", None) if ctx.program \
        else None
    if policy is not None and attrs["base_type"] in policy.white:
        cast = policy.compute_dtype
        if x2.dtype == jnp.float32:
            x2 = x2.astype(cast)
        if y2.dtype == jnp.float32:
            y2 = y2.astype(cast)
    res2 = residual.reshape((m, n)) if residual is not None else None
    # the unfused chain's output dtype: the epilogue ops are AMP-neutral,
    # so a compute-dtype GEMM output meeting f32 epilogue params promotes
    # op by op exactly as jnp's binary promotion — the kernel must hand
    # back the same dtype or the fusion pass's witness meta check
    # (rightly) refuses every AMP program on this route
    out_dt = x2.dtype
    for extra in (bias, res2, ln_scale, ln_bias):
        if extra is not None:
            out_dt = jnp.result_type(out_dt, extra.dtype)
    o = fused_gemm(
        x2, y2, bias=bias, residual=res2, ln_scale=ln_scale,
        ln_bias=ln_bias, activation=attrs["activation"],
        gelu_approximate=bool(attrs["gelu_approximate"]),
        layer_norm=bool(attrs["layer_norm"]),
        ln_eps=float(attrs["epsilon"]),
        block_m=blocks[0], block_n=blocks[1], block_k=blocks[2],
        out_dtype=out_dt, interpret=(route == "pallas-interpret"))
    return {"Out": [o.reshape(out_shape)]}
