"""Recurrent ops: lstm (dynamic_lstm), gru (dynamic_gru), gru_unit,
cudnn_lstm, warpctc — on the padded+lengths encoding.

Reference kernels (gate math verified against the C++ sources):
* lstm_op.h + math/detail/lstm_kernel.h — gate layout 4H = [c~, i, f, o]
  (value_in at 0, input gate at H, forget at 2H, output at 3H); peephole
  terms i += prev_c*checkI, f += prev_c*checkF, o += c*checkO; cell
  c = c~*i + prev_c*f; h = o * act(c).
* gru_op.h + math/detail/gru_kernel.h — gate layout 3H = [u, r, c~];
  weight [H, 3H] splits into W_ur [H, 2H] and W_c [H, H]; candidate gate
  += (r*prev) @ W_c; default (origin_mode=False) h = (1-u)*prev + u*c~.
* cudnn_lstm_op.cu.cc — a whole multi-layer LSTM in one op; here the flat
  weight packs per layer [W_ih (4H,in), W_hh (4H,H), b_ih (4H), b_hh (4H)]
  and the loop is a stack of scans (the cuDNN black box becomes XLA-fused
  scans).
* warpctc_op.h — CTC loss; the external warp-ctc library becomes a
  log-semiring forward DP under lax.scan, differentiable by jax.vjp (no
  hand-written backward needed).

All run batch-major padded [B, T, ...] with an int32 lengths array; steps
past a sequence's length leave the carry unchanged (masked select), so
final states equal the reference's LoD-packed results.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .common import IOSpec, out, register_op, x

_ACT = {"sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh, "relu": jax.nn.relu,
        "identity": lambda v: v}


def _scan_outputs(step, carry, xs_tm, lengths):
    """scan with per-step freeze once t >= len (padded steps are no-ops);
    returns (final_carry, stacked_per_step_carries)."""
    T = xs_tm.shape[0]

    def body(c, inp):
        t, xt = inp
        new = step(c, xt)
        keep = (t < lengths)[:, None]
        sel = tuple(jnp.where(keep, n, o) for n, o in zip(new, c))
        return sel, sel

    final, stacked = jax.lax.scan(body, carry, (jnp.arange(T), xs_tm))
    return final, stacked


@register_op("lstm",
             inputs=[IOSpec("Input"), IOSpec("Weight"),
                     IOSpec("Bias", optional=True),
                     IOSpec("H0", optional=True), IOSpec("C0", optional=True),
                     IOSpec("SeqLen", no_grad=True)],
             outputs=["Hidden", IOSpec("Cell", optional=True)],
             attrs={"use_peepholes": True, "is_reverse": False,
                    "gate_activation": "sigmoid",
                    "cell_activation": "tanh",
                    "candidate_activation": "tanh", "cell_clip": 0.0})
def _lstm(ctx, ins, attrs):
    """dynamic_lstm: Input [B,T,4H] is the pre-projected x@W_x (the
    reference also takes projected input), Weight [H,4H] recurrent."""
    xg = x(ins, "Input")
    w = x(ins, "Weight")
    bias = x(ins, "Bias")
    ln = x(ins, "SeqLen")
    B, T, H4 = xg.shape
    H = H4 // 4
    act_g = _ACT[attrs["gate_activation"]]
    act_c = _ACT[attrs["cell_activation"]]
    act_cand = _ACT[attrs["candidate_activation"]]
    peep = attrs.get("use_peepholes", False) and bias is not None \
        and bias.reshape(-1).shape[0] >= 7 * H
    b = None if bias is None else bias.reshape(-1)
    gate_b = None if b is None else b[:4 * H]
    ckI = b[4 * H:5 * H] if peep else 0.0
    ckF = b[5 * H:6 * H] if peep else 0.0
    ckO = b[6 * H:7 * H] if peep else 0.0

    h0 = x(ins, "H0")
    c0 = x(ins, "C0")
    h0 = jnp.zeros((B, H), xg.dtype) if h0 is None else h0
    c0 = jnp.zeros((B, H), xg.dtype) if c0 is None else c0

    xs = jnp.moveaxis(xg, 1, 0)  # [T,B,4H]
    if attrs.get("is_reverse"):
        # reverse each VALID prefix (padding stays at the tail)
        t_idx = jnp.arange(T)[:, None]
        src = jnp.where(t_idx < ln[None, :], ln[None, :] - 1 - t_idx, t_idx)
        xs = jnp.take_along_axis(xs, src[:, :, None], axis=0)

    def step(carry, xt):
        h, c = carry
        g = xt + h @ w
        if gate_b is not None:
            g = g + gate_b
        cand = act_cand(g[:, :H])
        i = act_g(g[:, H:2 * H] + c * ckI)
        f = act_g(g[:, 2 * H:3 * H] + c * ckF)
        new_c = cand * i + c * f
        clip = attrs.get("cell_clip", 0.0)
        if clip and clip > 0:
            new_c = jnp.clip(new_c, -clip, clip)
        o = act_g(g[:, 3 * H:] + new_c * ckO)
        new_h = o * act_c(new_c)
        return new_h, new_c

    (hT, cT), (hs, _) = _scan_outputs(step, (h0, c0), xs, ln)
    hidden = jnp.moveaxis(hs, 0, 1)  # [B,T,H]
    if attrs.get("is_reverse"):
        t_idx = jnp.arange(T)[None, :]
        src = jnp.where(t_idx < ln[:, None], ln[:, None] - 1 - t_idx, t_idx)
        hidden = jnp.take_along_axis(hidden, src[:, :, None], axis=1)
    mask = (jnp.arange(T)[None, :] < ln[:, None])[..., None]
    return {"Hidden": [jnp.where(mask, hidden, 0)], "Cell": [cT]}


@register_op("lstmp",
             inputs=[IOSpec("Input"), IOSpec("Weight"),
                     IOSpec("ProjWeight"), IOSpec("Bias", optional=True),
                     IOSpec("H0", optional=True), IOSpec("C0", optional=True),
                     IOSpec("SeqLen", no_grad=True)],
             outputs=["Projection", "Cell"],
             attrs={"use_peepholes": True, "is_reverse": False,
                    "gate_activation": "sigmoid", "cell_activation": "tanh",
                    "candidate_activation": "tanh",
                    "proj_activation": "tanh", "cell_clip": 0.0,
                    "proj_clip": 0.0})
def _lstmp(ctx, ins, attrs):
    """LSTM with recurrent projection (reference lstmp_op.h): the [B,P]
    projection r = proj_act(h @ ProjWeight) is what recurs through
    Weight [P,4H], shrinking the recurrent matmul from HxH to PxH —
    the LSTMP of Sak et al. that the reference ships for speech."""
    xg = x(ins, "Input")
    w = x(ins, "Weight")                 # [P, 4H]
    w_proj = x(ins, "ProjWeight")        # [H, P]
    bias = x(ins, "Bias")
    ln = x(ins, "SeqLen")
    B, T, H4 = xg.shape
    H = H4 // 4
    P = w_proj.shape[1]
    act_g = _ACT[attrs["gate_activation"]]
    act_c = _ACT[attrs["cell_activation"]]
    act_cand = _ACT[attrs["candidate_activation"]]
    act_p = _ACT[attrs["proj_activation"]]
    peep = attrs.get("use_peepholes", False) and bias is not None \
        and bias.reshape(-1).shape[0] >= 7 * H
    b = None if bias is None else bias.reshape(-1)
    gate_b = None if b is None else b[:4 * H]
    ckI = b[4 * H:5 * H] if peep else 0.0
    ckF = b[5 * H:6 * H] if peep else 0.0
    ckO = b[6 * H:7 * H] if peep else 0.0

    h0 = x(ins, "H0")                    # [B, P] initial projection
    c0 = x(ins, "C0")
    r0 = jnp.zeros((B, P), xg.dtype) if h0 is None else h0
    c0 = jnp.zeros((B, H), xg.dtype) if c0 is None else c0

    xs = jnp.moveaxis(xg, 1, 0)
    if attrs.get("is_reverse"):
        t_idx = jnp.arange(T)[:, None]
        src = jnp.where(t_idx < ln[None, :], ln[None, :] - 1 - t_idx, t_idx)
        xs = jnp.take_along_axis(xs, src[:, :, None], axis=0)

    cell_clip = attrs.get("cell_clip", 0.0)
    proj_clip = attrs.get("proj_clip", 0.0)

    def step(carry, xt):
        r, c = carry
        g = xt + r @ w
        if gate_b is not None:
            g = g + gate_b
        cand = act_cand(g[:, :H])
        i = act_g(g[:, H:2 * H] + c * ckI)
        f = act_g(g[:, 2 * H:3 * H] + c * ckF)
        new_c = cand * i + c * f
        if cell_clip and cell_clip > 0:
            new_c = jnp.clip(new_c, -cell_clip, cell_clip)
        o = act_g(g[:, 3 * H:] + new_c * ckO)
        new_h = o * act_c(new_c)
        new_r = act_p(new_h @ w_proj)
        if proj_clip and proj_clip > 0:
            new_r = jnp.clip(new_r, -proj_clip, proj_clip)
        return new_r, new_c

    (rT, cT), (rs, _) = _scan_outputs(step, (r0, c0), xs, ln)
    proj = jnp.moveaxis(rs, 0, 1)        # [B,T,P]
    if attrs.get("is_reverse"):
        t_idx = jnp.arange(T)[None, :]
        src = jnp.where(t_idx < ln[:, None], ln[:, None] - 1 - t_idx, t_idx)
        proj = jnp.take_along_axis(proj, src[:, :, None], axis=1)
    mask = (jnp.arange(T)[None, :] < ln[:, None])[..., None]
    return {"Projection": [jnp.where(mask, proj, 0)], "Cell": [cT]}


@register_op("gru",
             inputs=[IOSpec("Input"), IOSpec("Weight"),
                     IOSpec("Bias", optional=True),
                     IOSpec("H0", optional=True),
                     IOSpec("SeqLen", no_grad=True)],
             outputs=["Hidden"],
             attrs={"is_reverse": False, "origin_mode": False,
                    "gate_activation": "sigmoid", "activation": "tanh"})
def _gru(ctx, ins, attrs):
    """dynamic_gru: Input [B,T,3H] pre-projected, Weight [H,3H]."""
    xg, w, ln = x(ins, "Input"), x(ins, "Weight"), x(ins, "SeqLen")
    bias = x(ins, "Bias")
    B, T, H3 = xg.shape
    H = H3 // 3
    act_g = _ACT[attrs["gate_activation"]]
    act_c = _ACT[attrs["activation"]]
    w_ur, w_c = w[:, :2 * H], w[:, 2 * H:]
    h0 = x(ins, "H0")
    h = jnp.zeros((B, H), xg.dtype) if h0 is None else h0
    xs = jnp.moveaxis(xg, 1, 0)
    if bias is not None:
        xs = xs + bias.reshape(-1)[None, None, :]
    if attrs.get("is_reverse"):
        t_idx = jnp.arange(T)[:, None]
        src = jnp.where(t_idx < ln[None, :], ln[None, :] - 1 - t_idx, t_idx)
        xs = jnp.take_along_axis(xs, src[:, :, None], axis=0)

    def step(carry, xt):
        (h_prev,) = carry
        ur = xt[:, :2 * H] + h_prev @ w_ur
        u = act_g(ur[:, :H])
        r = act_g(ur[:, H:])
        cand = act_c(xt[:, 2 * H:] + (r * h_prev) @ w_c)
        if attrs.get("origin_mode"):
            h_new = u * h_prev + cand - u * cand
        else:
            h_new = h_prev - u * h_prev + u * cand
        return (h_new,)

    (hT,), (hs,) = _scan_outputs(step, (h,), xs, ln)
    hidden = jnp.moveaxis(hs, 0, 1)
    if attrs.get("is_reverse"):
        t_idx = jnp.arange(T)[None, :]
        src = jnp.where(t_idx < ln[:, None], ln[:, None] - 1 - t_idx, t_idx)
        hidden = jnp.take_along_axis(hidden, src[:, :, None], axis=1)
    mask = (jnp.arange(T)[None, :] < ln[:, None])[..., None]
    return {"Hidden": [jnp.where(mask, hidden, 0)]}


@register_op("gru_unit",
             inputs=[IOSpec("Input"), IOSpec("HiddenPrev"), IOSpec("Weight"),
                     IOSpec("Bias", optional=True)],
             outputs=["Gate", "ResetHiddenPrev", "Hidden"],
             attrs={"activation": "tanh", "gate_activation": "sigmoid",
                    "origin_mode": False})
def _gru_unit(ctx, ins, attrs):
    """One GRU step (reference gru_unit_op.h), same math as gru above."""
    xt, h_prev, w = x(ins, "Input"), x(ins, "HiddenPrev"), x(ins, "Weight")
    bias = x(ins, "Bias")
    H = h_prev.shape[-1]
    act_g = _ACT[attrs["gate_activation"]]
    act_c = _ACT[attrs["activation"]]
    if bias is not None:
        xt = xt + bias.reshape(-1)[None, :]
    ur = xt[:, :2 * H] + h_prev @ w[:, :2 * H]
    u, r = act_g(ur[:, :H]), act_g(ur[:, H:])
    reset_h = r * h_prev
    cand = act_c(xt[:, 2 * H:] + reset_h @ w[:, 2 * H:])
    if attrs.get("origin_mode"):
        h_new = u * h_prev + cand - u * cand
    else:
        h_new = h_prev - u * h_prev + u * cand
    gate = jnp.concatenate([u, r, cand], axis=1)
    return {"Gate": [gate], "ResetHiddenPrev": [reset_h], "Hidden": [h_new]}


@register_op("cudnn_lstm",
             inputs=[IOSpec("Input"), IOSpec("W"),
                     IOSpec("InitH", optional=True),
                     IOSpec("InitC", optional=True),
                     IOSpec("SeqLen", no_grad=True)],
             outputs=["Out", "LastH", "LastC"],
             attrs={"hidden_size": 0, "num_layers": 1,
                    "dropout_prob": 0.0, "is_test": False},
             needs_rng=True)
def _cudnn_lstm(ctx, ins, attrs):
    """Multi-layer LSTM in one op (reference cudnn_lstm_op.cu.cc). Input
    [B,T,D]; flat W packs per layer [W_ih(4H,in), W_hh(4H,H), b_ih, b_hh],
    gate order [c~, i, f, o] for consistency with the lstm op."""
    xv, wflat, ln = x(ins, "Input"), x(ins, "W"), x(ins, "SeqLen")
    B, T, D = xv.shape
    H = attrs["hidden_size"]
    L = attrs["num_layers"]
    init_h, init_c = x(ins, "InitH"), x(ins, "InitC")
    init_h = jnp.zeros((L, B, H), xv.dtype) if init_h is None else init_h
    init_c = jnp.zeros((L, B, H), xv.dtype) if init_c is None else init_c

    wflat = wflat.reshape(-1)
    offset = 0
    seq = xv
    last_h, last_c = [], []
    for layer in range(L):
        in_dim = D if layer == 0 else H
        n_wih = 4 * H * in_dim
        n_whh = 4 * H * H
        w_ih = wflat[offset:offset + n_wih].reshape(4 * H, in_dim)
        offset += n_wih
        w_hh = wflat[offset:offset + n_whh].reshape(4 * H, H)
        offset += n_whh
        b = wflat[offset:offset + 4 * H] + wflat[offset + 4 * H:
                                                 offset + 8 * H]
        offset += 8 * H

        gates = jnp.einsum("btd,gd->btg", seq, w_ih) + b[None, None, :]
        xs = jnp.moveaxis(gates, 1, 0)

        def step(carry, xt, w_hh=w_hh, H=H):
            h, c = carry
            g = xt + h @ w_hh.T
            cand = jnp.tanh(g[:, :H])
            i = jax.nn.sigmoid(g[:, H:2 * H])
            f = jax.nn.sigmoid(g[:, 2 * H:3 * H])
            o = jax.nn.sigmoid(g[:, 3 * H:])
            nc = cand * i + c * f
            return o * jnp.tanh(nc), nc

        (hT, cT), (hs, _) = _scan_outputs(step, (init_h[layer],
                                                 init_c[layer]), xs, ln)
        seq = jnp.moveaxis(hs, 0, 1)
        if layer < L - 1 and attrs.get("dropout_prob", 0.0) > 0 \
                and not attrs.get("is_test"):
            keep = 1.0 - attrs["dropout_prob"]
            mask = jax.random.bernoulli(ctx.rng(), keep, seq.shape)
            seq = jnp.where(mask, seq / keep, 0)
        last_h.append(hT)
        last_c.append(cT)
    mask = (jnp.arange(T)[None, :] < ln[:, None])[..., None]
    return {"Out": [jnp.where(mask, seq, 0)],
            "LastH": [jnp.stack(last_h)], "LastC": [jnp.stack(last_c)]}


@register_op("warpctc",
             inputs=[IOSpec("Logits"), IOSpec("Label", no_grad=True),
                     IOSpec("LogitsLength", no_grad=True),
                     IOSpec("LabelLength", no_grad=True)],
             outputs=["Loss"],
             attrs={"blank": 0, "norm_by_times": False})
def _warpctc(ctx, ins, attrs):
    """CTC loss (reference warpctc_op.h binding the warp-ctc library).

    Log-semiring forward DP over the blank-extended label sequence under
    lax.scan — differentiable through jax.vjp, so no custom backward.
    Logits [B, T, C] unnormalised; Label [B, L] padded; per-sample lengths.
    """
    logits = x(ins, "Logits")
    labels = x(ins, "Label").astype(jnp.int32)
    tlen = x(ins, "LogitsLength").reshape(-1).astype(jnp.int32)
    llen = x(ins, "LabelLength").reshape(-1).astype(jnp.int32)
    blank = attrs.get("blank", 0)
    B, T, C = logits.shape
    L = labels.shape[1]
    S = 2 * L + 1
    logp = jax.nn.log_softmax(logits, axis=-1)

    # extended sequence: blank, l1, blank, l2, ..., blank
    ext = jnp.full((B, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(labels)
    ext_valid = jnp.arange(S)[None, :] < (2 * llen + 1)[:, None]
    # can-skip: ext[s] != blank and ext[s] != ext[s-2]
    skip_ok = jnp.zeros((B, S), bool)
    skip_ok = skip_ok.at[:, 2:].set(
        (ext[:, 2:] != blank) & (ext[:, 2:] != ext[:, :-2]))

    NEG = jnp.asarray(-1e30, logp.dtype)
    alpha0 = jnp.full((B, S), NEG)
    alpha0 = alpha0.at[:, 0].set(logp[:, 0, blank])
    first_lbl = jnp.take_along_axis(logp[:, 0, :], ext[:, 1:2], axis=1)[:, 0]
    alpha0 = alpha0.at[:, 1].set(jnp.where(llen > 0, first_lbl, NEG))

    def step(alpha, t):
        stay = alpha
        prev1 = jnp.concatenate([jnp.full((B, 1), NEG), alpha[:, :-1]], 1)
        prev2 = jnp.concatenate([jnp.full((B, 2), NEG), alpha[:, :-2]], 1)
        prev2 = jnp.where(skip_ok, prev2, NEG)
        merged = jnp.logaddexp(jnp.logaddexp(stay, prev1), prev2)
        emit = jnp.take_along_axis(logp[:, t, :], ext, axis=1)
        new = merged + emit
        new = jnp.where(ext_valid, new, NEG)
        # frames past a sample's length leave alpha unchanged
        return jnp.where((t < tlen)[:, None], new, alpha), None

    alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
    # loss = -log(alpha[2*llen] + alpha[2*llen - 1])
    idx_last = (2 * llen)[:, None]
    idx_prev = jnp.maximum(2 * llen - 1, 0)[:, None]
    a_last = jnp.take_along_axis(alpha, idx_last, axis=1)[:, 0]
    a_prev = jnp.take_along_axis(alpha, idx_prev, axis=1)[:, 0]
    a_prev = jnp.where(llen > 0, a_prev, NEG)
    ll = jnp.logaddexp(a_last, a_prev)
    loss = -ll
    if attrs.get("norm_by_times"):
        loss = loss / jnp.maximum(tlen, 1).astype(loss.dtype)
    return {"Loss": [loss.reshape(B, 1)]}
