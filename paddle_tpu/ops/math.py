"""Elementwise, activation, scale/sum/cast ops.

TPU-native equivalents of reference op families:
* activations — paddle/fluid/operators/activation_op.{cc,cu}
* elementwise — paddle/fluid/operators/elementwise/ (broadcast rule from
  elementwise_op_function.h: Y spans X's dims starting at attr ``axis``)
* scale/sum/cast/clip — paddle/fluid/operators/{scale,sum,cast,clip}_op.*

Each is a pure jnp expression; XLA fuses chains of these into surrounding
matmuls, which is why there is no hand-written "fused_elemwise_activation"
here (reference operators/fused/) — the compiler does it.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.types import jnp_dtype
from .common import IOSpec, broadcast_to_x, out, register_op, unary, x

# -- activations ------------------------------------------------------------
unary("relu", jax.nn.relu)
unary("sigmoid", jax.nn.sigmoid)
unary("tanh", jnp.tanh)
unary("exp", jnp.exp)
unary("log", jnp.log)
unary("square", jnp.square)
unary("sqrt", jnp.sqrt)
unary("rsqrt", jax.lax.rsqrt)
unary("abs", jnp.abs)
unary("ceil", jnp.ceil, grad=None)
unary("floor", jnp.floor, grad=None)
unary("round", jnp.round, grad=None)
unary("reciprocal", lambda v: 1.0 / v)
unary("softplus", jax.nn.softplus)
unary("softsign", jax.nn.soft_sign)
unary("sin", jnp.sin)
unary("cos", jnp.cos)
unary("logsigmoid", jax.nn.log_sigmoid)
unary("erf", jax.scipy.special.erf)
unary("tan", jnp.tan)
unary("asin", jnp.arcsin)
unary("acos", jnp.arccos)
unary("atan", jnp.arctan)
unary("sinh", jnp.sinh)
unary("cosh", jnp.cosh)
unary("log1p", jnp.log1p)
unary("expm1", jnp.expm1)
unary("log2", jnp.log2)
unary("log10", jnp.log10)
unary("sign", jnp.sign, grad=None)
unary("silu", jax.nn.silu)
unary("mish", lambda v: v * jnp.tanh(jax.nn.softplus(v)))


@register_op("selu", inputs=["X"], outputs=["Out"],
             attrs={"scale": 1.0507009873554805, "alpha": 1.6732632423543772})
def _selu(ctx, ins, attrs):
    v = x(ins)
    return out(attrs["scale"] * jnp.where(
        v > 0, v, attrs["alpha"] * (jnp.exp(v) - 1.0)))



@register_op("stanh", inputs=["X"], outputs=["Out"],
             attrs={"scale_a": 0.67, "scale_b": 1.7159})
def _stanh(ctx, ins, attrs):
    return out(attrs["scale_b"] * jnp.tanh(attrs["scale_a"] * x(ins)))


@register_op("brelu", inputs=["X"], outputs=["Out"],
             attrs={"t_min": 0.0, "t_max": 24.0})
def _brelu(ctx, ins, attrs):
    return out(jnp.clip(x(ins), attrs["t_min"], attrs["t_max"]))


@register_op("hard_shrink", inputs=["X"], outputs=["Out"],
             attrs={"threshold": 0.5})
def _hard_shrink(ctx, ins, attrs):
    v, t = x(ins), attrs["threshold"]
    return out(jnp.where(jnp.abs(v) > t, v, 0.0))


@register_op("softshrink", inputs=["X"], outputs=["Out"],
             attrs={"lambda": 0.5})
def _softshrink(ctx, ins, attrs):
    v, lam = x(ins), attrs["lambda"]
    return out(jnp.where(v > lam, v - lam, jnp.where(v < -lam, v + lam, 0.0)))


@register_op("thresholded_relu", inputs=["X"], outputs=["Out"],
             attrs={"threshold": 1.0})
def _thresholded_relu(ctx, ins, attrs):
    v = x(ins)
    return out(jnp.where(v > attrs["threshold"], v, 0.0))


@register_op("maxout", inputs=["X"], outputs=["Out"],
             attrs={"groups": 1, "axis": 1})
def _maxout(ctx, ins, attrs):
    """reference maxout_op.h: channels fold into groups, max within each."""
    v, g = x(ins), attrs["groups"]
    ax = attrs.get("axis", 1)
    ax = ax if ax >= 0 else ax + v.ndim
    c = v.shape[ax]
    shp = v.shape[:ax] + (c // g, g) + v.shape[ax + 1:]
    return out(v.reshape(shp).max(axis=ax + 1))


@register_op("gelu", inputs=["X"], outputs=["Out"], attrs={"approximate": False})
def _gelu(ctx, ins, attrs):
    return out(jax.nn.gelu(x(ins), approximate=bool(attrs.get("approximate", False))))


@register_op("leaky_relu", inputs=["X"], outputs=["Out"], attrs={"alpha": 0.02})
def _leaky_relu(ctx, ins, attrs):
    return out(jax.nn.leaky_relu(x(ins), negative_slope=attrs["alpha"]))


@register_op("relu6", inputs=["X"], outputs=["Out"], attrs={"threshold": 6.0})
def _relu6(ctx, ins, attrs):
    return out(jnp.clip(x(ins), 0.0, attrs["threshold"]))


@register_op("elu", inputs=["X"], outputs=["Out"], attrs={"alpha": 1.0})
def _elu(ctx, ins, attrs):
    return out(jax.nn.elu(x(ins), alpha=attrs["alpha"]))


@register_op("hard_sigmoid", inputs=["X"], outputs=["Out"], attrs={"slope": 0.2, "offset": 0.5})
def _hard_sigmoid(ctx, ins, attrs):
    return out(jnp.clip(attrs["slope"] * x(ins) + attrs["offset"], 0.0, 1.0))


@register_op("swish", inputs=["X"], outputs=["Out"], attrs={"beta": 1.0})
def _swish(ctx, ins, attrs):
    v = x(ins)
    return out(v * jax.nn.sigmoid(attrs["beta"] * v))


@register_op("hard_swish", inputs=["X"], outputs=["Out"],
             attrs={"threshold": 6.0, "scale": 6.0, "offset": 3.0})
def _hard_swish(ctx, ins, attrs):
    v = x(ins)
    return out(v * jnp.clip(v + attrs["offset"], 0, attrs["threshold"]) / attrs["scale"])


@register_op("pow", inputs=["X"], outputs=["Out"], attrs={"factor": 1.0})
def _pow(ctx, ins, attrs):
    return out(jnp.power(x(ins), attrs["factor"]))


@register_op("softmax", inputs=["X"], outputs=["Out"], attrs={"axis": -1})
def _softmax(ctx, ins, attrs):
    return out(jax.nn.softmax(x(ins), axis=attrs.get("axis", -1)))


@register_op("log_softmax", inputs=["X"], outputs=["Out"], attrs={"axis": -1})
def _log_softmax(ctx, ins, attrs):
    return out(jax.nn.log_softmax(x(ins), axis=attrs.get("axis", -1)))


# -- elementwise binary -----------------------------------------------------

def _ew(fn):
    # SelectedRows x scalar is value-wise ONLY for multiplicative ops (the
    # implicit-zero untouched rows stay zero under *, /); add/max/etc. would
    # need every vocab row touched — those densify loudly via the generic
    # error instead of silently corrupting grads
    sparse_ok = fn in (jnp.multiply, jnp.divide, jnp.true_divide)

    def lower(ctx, ins, attrs):
        from ..core.selected_rows import is_selected_rows

        xv, yv = x(ins, "X"), x(ins, "Y")
        if sparse_ok and is_selected_rows(xv) and not is_selected_rows(yv) \
                and getattr(yv, "size", 0) == 1:
            # sparse grad x scalar (global-norm clip's g * scale, loss-scale
            # unscale): apply to values, keep the SelectedRows structure
            from ..core.selected_rows import SelectedRows

            return out(SelectedRows(xv.rows, fn(xv.values, yv.reshape(())),
                                    xv.height))
        yv = broadcast_to_x(xv, yv, attrs.get("axis", -1))
        return out(fn(xv, yv))

    return lower


for _name, _fn in [
    ("elementwise_add", jnp.add),
    ("elementwise_sub", jnp.subtract),
    ("elementwise_mul", jnp.multiply),
    ("elementwise_div", jnp.divide),
    ("elementwise_min", jnp.minimum),
    ("elementwise_max", jnp.maximum),
    ("elementwise_pow", jnp.power),
    ("elementwise_mod", jnp.mod),
    ("elementwise_floordiv", jnp.floor_divide),
]:
    register_op(_name, inputs=["X", "Y"], outputs=["Out"], attrs={"axis": -1})(_ew(_fn))


# -- scale / sum / cast / clip ---------------------------------------------

@register_op("scale", inputs=["X"], outputs=["Out"],
             attrs={"scale": 1.0, "bias": 0.0, "bias_after_scale": True})
def _scale(ctx, ins, attrs):
    from ..core.selected_rows import is_selected_rows

    v = x(ins)
    if is_selected_rows(v):
        # grad scaling (1/N, loss scale): bias on a sparse grad is malformed
        assert attrs.get("bias", 0.0) == 0.0, "scale(SelectedRows) with bias"
        return out(v.scale(attrs["scale"]))
    if attrs.get("bias_after_scale", True):
        return out(v * attrs["scale"] + attrs["bias"])
    return out((v + attrs["bias"]) * attrs["scale"])


@register_op("sum", inputs=[IOSpec("X", duplicable=True)], outputs=["Out"])
def _sum(ctx, ins, attrs):
    from ..core.selected_rows import concat_merge, is_selected_rows

    vals = [v for v in ins.get("X", []) if v is not None]
    sparse = [v for v in vals if is_selected_rows(v)]
    if sparse:
        # multi-consumer grads of a shared is_sparse table (backward.py's
        # sum-dedup): concat + re-merge stays O(touched rows). Mixed
        # dense+sparse densifies (reference selected_rows_functor.cc Add).
        acc = sparse[0]
        for v in sparse[1:]:
            acc = concat_merge(acc, v)
        dense = [v for v in vals if not is_selected_rows(v)]
        if not dense:
            return out(acc)
        d = dense[0]
        for v in dense[1:]:
            d = d + v
        return out(d + acc.to_dense())
    acc = vals[0]
    for v in vals[1:]:
        acc = acc + v
    return out(acc)


@register_op("cast", inputs=["X"], outputs=["Out"],
             attrs={"in_dtype": None, "out_dtype": "float32"})
def _cast(ctx, ins, attrs):
    # jnp_dtype, not np_dtype: an int64 cast under disabled x64 would emit
    # a truncation UserWarning per traced op before downcasting anyway
    return out(x(ins).astype(jnp_dtype(attrs["out_dtype"])))


@register_op("clip", inputs=["X"], outputs=["Out"], attrs={"min": -1.0, "max": 1.0})
def _clip(ctx, ins, attrs):
    from ..core.selected_rows import SelectedRows, is_selected_rows

    v = x(ins)
    if is_selected_rows(v):
        return out(SelectedRows(
            v.rows, jnp.clip(v.values, attrs["min"], attrs["max"]),
            v.height))
    return out(jnp.clip(v, attrs["min"], attrs["max"]))


@register_op("clip_by_norm", inputs=["X"], outputs=["Out"], attrs={"max_norm": 1.0})
def _clip_by_norm(ctx, ins, attrs):
    from ..core.selected_rows import SelectedRows, is_selected_rows

    v = x(ins)
    if is_selected_rows(v):
        # rows are duplicate-free (merged at creation), so the values norm
        # IS the grad norm — reference clip_by_norm_op.h SelectedRows path
        norm = jnp.sqrt(jnp.sum(jnp.square(v.values)))
        s = jnp.minimum(attrs["max_norm"] / jnp.maximum(norm, 1e-12), 1.0)
        return out(SelectedRows(v.rows, v.values * s, v.height))
    norm = jnp.sqrt(jnp.sum(jnp.square(v)))
    scale = jnp.minimum(attrs["max_norm"] / jnp.maximum(norm, 1e-12), 1.0)
    return out(v * scale)


@register_op("squared_l2_norm", inputs=["X"], outputs=["Out"])
def _squared_l2_norm(ctx, ins, attrs):
    from ..core.selected_rows import is_selected_rows

    v = x(ins)
    if is_selected_rows(v):
        return out(jnp.sum(jnp.square(v.values)).reshape((1,)))
    return out(jnp.sum(jnp.square(v)).reshape((1,)))


# -- comparison / logical (non-differentiable) ------------------------------

for _name, _fn in [
    ("equal", jnp.equal),
    ("not_equal", jnp.not_equal),
    ("less_than", jnp.less),
    ("less_equal", jnp.less_equal),
    ("greater_than", jnp.greater),
    ("greater_equal", jnp.greater_equal),
]:
    def _cmp_lower(ctx, ins, attrs, _fn=_fn):
        return out(_fn(x(ins, "X"), x(ins, "Y")))

    register_op(_name, inputs=["X", "Y"], outputs=["Out"], grad=None)(_cmp_lower)


for _name, _fn in [
    ("logical_and", jnp.logical_and),
    ("logical_or", jnp.logical_or),
    ("logical_xor", jnp.logical_xor),
]:
    def _logical_lower(ctx, ins, attrs, _fn=_fn):
        return out(_fn(x(ins, "X"), x(ins, "Y")))

    register_op(_name, inputs=["X", "Y"], outputs=["Out"], grad=None)(_logical_lower)


@register_op("logical_not", inputs=["X"], outputs=["Out"], grad=None)
def _logical_not(ctx, ins, attrs):
    return out(jnp.logical_not(x(ins)))


@register_op("isfinite", inputs=["X"], outputs=["Out"], grad=None)
def _isfinite(ctx, ins, attrs):
    return out(jnp.all(jnp.isfinite(x(ins))).reshape((1,)))
