"""Round-5 op tail: deformable convolution family, position-sensitive ROI
pooling, SelectedRows utilities, host-callback py_func, sampled softmax,
trilinear resize, and padded-encoding sequence reshape/expand_as.

References: paddle/fluid/operators/deformable_conv_op.cu (v2, modulated),
deformable_psroi_pooling_op.cu, psroi_pool_op.h, prroi_pool_op.h,
math/sampled_id... (sampled_softmax_with_cross_entropy_op.cc), cvm_op.h,
py_func_op.cc, merge_selected_rows_op.cc,
get_tensor_from_selected_rows_op.cc, sequence_reshape_op.h,
sequence_expand_as_op.h, interpolate_op trilinear path.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.types import jnp_dtype
from .common import IOSpec, out, register_op, x


def _bilinear_sample(img, yy, xx):
    """img [C, H, W], yy/xx arbitrary same-shaped float coords; zero outside
    (the deformable-conv convention, deformable_conv_op.cu DmcnIm2colBilinear
    with boundary zeroing)."""
    c, h, w = img.shape
    y0 = jnp.floor(yy)
    x0 = jnp.floor(xx)
    y1, x1 = y0 + 1, x0 + 1
    wy1 = yy - y0
    wx1 = xx - x0
    wy0, wx0 = 1.0 - wy1, 1.0 - wx1

    def tap(yi, xi, wt):
        inside = (yi >= 0) & (yi <= h - 1) & (xi >= 0) & (xi <= w - 1)
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        v = img[:, yc, xc]                       # [C, ...coords]
        return v * (wt * inside)[None]

    return (tap(y0, x0, wy0 * wx0) + tap(y0, x1, wy0 * wx1)
            + tap(y1, x0, wy1 * wx0) + tap(y1, x1, wy1 * wx1))


@register_op("deformable_conv",
             inputs=[IOSpec("Input"), IOSpec("Offset"),
                     IOSpec("Mask", optional=True), IOSpec("Filter")],
             outputs=["Output"],
             attrs={"strides": [1, 1], "paddings": [0, 0],
                    "dilations": [1, 1], "groups": 1,
                    "deformable_groups": 1, "im2col_step": 64})
def _deformable_conv(ctx, ins, attrs):
    """Deformable conv v2 (modulated when Mask given; v1 otherwise) —
    reference deformable_conv_op.cu. Each kernel tap samples the input at
    its regular grid position plus a learned offset via bilinear
    interpolation; the im2col_step attr is a CUDA blocking knob with no XLA
    analogue (accepted, ignored)."""
    inp = x(ins, "Input")            # [B, C, H, W]
    offset = x(ins, "Offset")        # [B, 2*dg*kh*kw, Ho, Wo]
    mask = x(ins, "Mask")            # [B, dg*kh*kw, Ho, Wo] or None
    filt = x(ins, "Filter")          # [O, C/g, kh, kw]
    b, c, h, w = inp.shape
    o, cg, kh, kw = filt.shape
    g = int(attrs.get("groups", 1))
    dg = int(attrs.get("deformable_groups", 1))
    sh, sw = attrs["strides"]
    ph, pw = attrs["paddings"]
    dh, dw = attrs["dilations"]
    ho, wo = offset.shape[2], offset.shape[3]
    off = offset.reshape(b, dg, kh * kw, 2, ho, wo)
    msk = (None if mask is None
           else mask.reshape(b, dg, kh * kw, ho, wo))

    oy = jnp.arange(ho) * sh - ph
    ox = jnp.arange(wo) * sw - pw
    base_y = oy[:, None]                          # [Ho, 1]
    base_x = ox[None, :]                          # [1, Wo]
    cpg = c // dg                                  # channels per dg group

    def per_image(img, off_b, msk_b):
        taps = []
        for t in range(kh * kw):
            i, j = t // kw, t % kw
            groups_out = []
            for d in range(dg):
                yy = base_y + i * dh + off_b[d, t, 0]   # [Ho, Wo]
                xx = base_x + j * dw + off_b[d, t, 1]
                v = _bilinear_sample(img[d * cpg:(d + 1) * cpg], yy, xx)
                if msk_b is not None:
                    v = v * msk_b[d, t][None]
                groups_out.append(v)
            taps.append(jnp.concatenate(groups_out, axis=0))  # [C, Ho, Wo]
        return jnp.stack(taps)                    # [kh*kw, C, Ho, Wo]

    if msk is not None:
        samp = jax.vmap(per_image)(inp, off, msk)
    else:
        samp = jax.vmap(lambda img, off_b: per_image(img, off_b, None))(
            inp, off)
    # grouped contraction: out[b,o,:,:] = sum_{c in group(o), t} w * samp
    filt_t = filt.reshape(g, o // g, cg, kh * kw)
    samp_g = samp.reshape(b, kh * kw, g, cg, ho, wo)
    res = jnp.einsum("btgchw,goct->bgohw", samp_g, filt_t)
    return {"Output": [res.reshape(b, o, ho, wo)]}


@register_op("psroi_pool",
             inputs=[IOSpec("X"), IOSpec("ROIs", no_grad=True),
                     IOSpec("RoisBatchIdx", optional=True, no_grad=True)],
             outputs=["Out"],
             attrs={"output_channels": 1, "spatial_scale": 1.0,
                    "pooled_height": 1, "pooled_width": 1}, grad=None)
def _psroi_pool(ctx, ins, attrs):
    """Position-sensitive ROI average pooling (reference psroi_pool_op.h):
    output channel o's bin (i, j) averages input channel
    o*ph*pw + i*pw + j over that bin's region."""
    inp = x(ins, "X")                # [B, oc*ph*pw, H, W]
    rois = x(ins, "ROIs")            # [R, 4]
    bidx = _roi_batch_indices("psroi_pool", inp, rois,
                              x(ins, "RoisBatchIdx"), None)
    oc = int(attrs["output_channels"])
    ph, pw = int(attrs["pooled_height"]), int(attrs["pooled_width"])
    scale = float(attrs["spatial_scale"])
    _, _, hh, ww = inp.shape

    def one(roi, bi):
        img = inp[bi]                               # [C, H, W]
        x0 = jnp.round(roi[0] * scale)
        y0 = jnp.round(roi[1] * scale)
        x1 = jnp.round(roi[2] * scale) + 1.0
        y1 = jnp.round(roi[3] * scale) + 1.0
        rh = jnp.maximum(y1 - y0, 0.1) / ph
        rw = jnp.maximum(x1 - x0, 0.1) / pw
        outv = []
        ys = jnp.arange(hh)
        xs = jnp.arange(ww)
        for i in range(ph):
            for j in range(pw):
                hs = jnp.floor(y0 + i * rh)
                he = jnp.ceil(y0 + (i + 1) * rh)
                ws_ = jnp.floor(x0 + j * rw)
                we = jnp.ceil(x0 + (j + 1) * rw)
                m = ((ys[:, None] >= hs) & (ys[:, None] < he)
                     & (xs[None, :] >= ws_) & (xs[None, :] < we))
                chans = jnp.arange(oc) * ph * pw + i * pw + j
                region = img[chans]                 # [oc, H, W]
                s = jnp.sum(region * m[None], axis=(1, 2))
                cnt = jnp.maximum(jnp.sum(m), 1)
                outv.append(s / cnt)
        return jnp.stack(outv, 1).reshape(oc, ph, pw)

    return out(jax.vmap(one)(rois, bidx))


def _roi_batch_indices(op_type, inp, rois, bidx, nums, layer=None):
    """Resolve each ROI's image index from RoisBatchIdx [R] or BatchRoINums
    [B] (counts per image). With neither and batch > 1, refuse: pooling
    every ROI from image 0 computes silently wrong results."""
    r = rois.shape[0]
    if bidx is not None:
        return bidx.reshape(-1).astype(jnp.int32)
    if nums is not None:
        # counts are runtime data; total_repeat_length keeps the shape
        # static. Callers must ensure sum(nums) == R — a mismatch pads or
        # truncates the tail, which cannot be detected inside the trace
        return jnp.repeat(jnp.arange(inp.shape[0], dtype=jnp.int32),
                          nums.reshape(-1).astype(jnp.int32),
                          total_repeat_length=r)
    if inp.shape[0] > 1:
        raise NotImplementedError(
            f"{op_type}: X has batch size {inp.shape[0]} but neither "
            f"RoisBatchIdx nor BatchRoINums was given — every ROI would "
            f"pool from image 0; pass rois_batch_idx through the layer "
            f"wrapper (fluid.layers.{layer or op_type})")
    return jnp.zeros((r,), jnp.int32)


@register_op("prroi_pool",
             inputs=[IOSpec("X"), IOSpec("ROIs", no_grad=True),
                     IOSpec("BatchRoINums", optional=True, no_grad=True),
                     IOSpec("RoisBatchIdx", optional=True, no_grad=True)],
             outputs=["Out"],
             attrs={"spatial_scale": 1.0, "pooled_height": 1,
                    "pooled_width": 1, "sample_num": 4})
def _prroi_pool(ctx, ins, attrs):
    """Precise ROI pooling (reference prroi_pool_op.h). Deviation: the
    reference integrates bilinear interpolation in closed form; here each
    bin averages a dense sample_num x sample_num bilinear grid — converges
    to the same value and keeps the op a fixed-shape gather program."""
    inp = x(ins, "X")
    rois = x(ins, "ROIs")
    bidx = _roi_batch_indices("prroi_pool", inp, rois,
                              x(ins, "RoisBatchIdx"),
                              x(ins, "BatchRoINums"))
    ph, pw = int(attrs["pooled_height"]), int(attrs["pooled_width"])
    scale = float(attrs["spatial_scale"])
    s = max(int(attrs.get("sample_num", 4)), 1)

    def one(roi, bi):
        img = inp[bi]
        x0, y0 = roi[0] * scale, roi[1] * scale
        x1, y1 = roi[2] * scale, roi[3] * scale
        bw = jnp.maximum(x1 - x0, 1e-4) / pw
        bh = jnp.maximum(y1 - y0, 1e-4) / ph
        iy = jnp.arange(ph).reshape(ph, 1, 1, 1)
        ix = jnp.arange(pw).reshape(1, pw, 1, 1)
        sy = (jnp.arange(s).reshape(1, 1, s, 1) + 0.5) / s
        sx = (jnp.arange(s).reshape(1, 1, 1, s) + 0.5) / s
        yy = y0 + (iy + sy) * bh
        xx = x0 + (ix + sx) * bw
        v = _bilinear_sample(img, yy, xx)          # [C, ph, pw, s, s]
        return v.mean(axis=(-2, -1))

    return out(jax.vmap(one)(rois, bidx))


@register_op("deformable_psroi_pooling",
             inputs=[IOSpec("Input"), IOSpec("ROIs", no_grad=True),
                     IOSpec("Trans"),
                     IOSpec("RoisBatchIdx", optional=True, no_grad=True)],
             outputs=["Output", "TopCount"],
             attrs={"no_trans": False, "spatial_scale": 1.0,
                    "output_dim": 1, "group_size": [1, 1],
                    "pooled_height": 1, "pooled_width": 1,
                    "part_size": [1, 1], "sample_per_part": 4,
                    "trans_std": 0.1})
def _deformable_psroi_pooling(ctx, ins, attrs):
    """Deformable PS-ROI pooling (reference
    deformable_psroi_pooling_op.cu): each bin's sample grid is shifted by
    the learned normalized Trans offsets before position-sensitive
    averaging."""
    inp = x(ins, "Input")            # [B, od*gh*gw, H, W]
    rois = x(ins, "ROIs")            # [R, 4]
    trans = x(ins, "Trans")          # [R, 2, part_h, part_w]
    bidx = _roi_batch_indices("deformable_psroi_pooling", inp, rois,
                              x(ins, "RoisBatchIdx"), None,
                              layer="deformable_roi_pooling")
    od = int(attrs["output_dim"])
    gh, gw = [int(v) for v in attrs["group_size"]]
    ph, pw = int(attrs["pooled_height"]), int(attrs["pooled_width"])
    part_h, part_w = [int(v) for v in attrs["part_size"]]
    spp = int(attrs["sample_per_part"])
    scale = float(attrs["spatial_scale"])
    t_std = float(attrs["trans_std"])
    no_trans = bool(attrs.get("no_trans", False))

    def one(roi, tr, bi):
        img = inp[bi]
        x0 = roi[0] * scale - 0.5
        y0 = roi[1] * scale - 0.5
        x1 = (roi[2] + 1.0) * scale - 0.5
        y1 = (roi[3] + 1.0) * scale - 0.5
        rw = jnp.maximum(x1 - x0, 0.1)
        rh = jnp.maximum(y1 - y0, 0.1)
        bw, bh = rw / pw, rh / ph
        outv = jnp.zeros((od, ph, pw), inp.dtype)
        cnt = jnp.zeros((ph, pw), inp.dtype)
        sub_h = bh / spp
        sub_w = bw / spp
        for i in range(ph):
            for j in range(pw):
                pi = min(int(i * part_h / ph), part_h - 1)
                pj = min(int(j * part_w / pw), part_w - 1)
                if no_trans:
                    dx = dy = 0.0
                else:
                    dx = tr[0, pi, pj] * t_std * rw
                    dy = tr[1, pi, pj] * t_std * rh
                sy = (y0 + i * bh + dy
                      + (jnp.arange(spp)[:, None] + 0.5) * sub_h)
                sx = (x0 + j * bw + dx
                      + (jnp.arange(spp)[None, :] + 0.5) * sub_w)
                yy = jnp.broadcast_to(sy, (spp, spp))
                xx = jnp.broadcast_to(sx, (spp, spp))
                gi = min(int(i * gh / ph), gh - 1)
                gj = min(int(j * gw / pw), gw - 1)
                chans = jnp.arange(od) * gh * gw + gi * gw + gj
                v = _bilinear_sample(img[chans], yy, xx)   # [od, spp, spp]
                outv = outv.at[:, i, j].set(v.mean(axis=(-2, -1)))
                cnt = cnt.at[i, j].set(float(spp * spp))
        return outv, cnt

    res, cnts = jax.vmap(one)(rois, trans, bidx)
    return {"Output": [res], "TopCount": [cnts]}


# -- SelectedRows utilities -------------------------------------------------


@register_op("merge_selected_rows", inputs=["X"], outputs=["Out"],
             grad=None)
def _merge_selected_rows(ctx, ins, attrs):
    """reference merge_selected_rows_op.cc: sum duplicate rows. Our
    SelectedRows are canonical (merged at creation), so this re-merges
    only when handed raw rows; dense input passes through."""
    from ..core.selected_rows import is_selected_rows, merge_rows

    v = x(ins)
    if is_selected_rows(v):
        return out(merge_rows(v.rows, v.values, v.height))
    return out(v)


@register_op("get_tensor_from_selected_rows", inputs=["X"],
             outputs=["Out"], grad=None)
def _get_tensor_from_selected_rows(ctx, ins, attrs):
    """reference get_tensor_from_selected_rows_op.cc: densify."""
    from ..core.selected_rows import is_selected_rows

    v = x(ins)
    return out(v.to_dense() if is_selected_rows(v) else v)


# -- CTR / sampling ---------------------------------------------------------


@register_op("sampled_softmax_with_cross_entropy",
             inputs=[IOSpec("Logits"), IOSpec("Label", no_grad=True)],
             outputs=["Samples", "Probabilities", "Loss"],
             attrs={"num_samples": 5, "seed": 0, "use_customized_samples":
                    False, "remove_accidental_hits": True},
             needs_rng=True)
def _sampled_softmax_ce(ctx, ins, attrs):
    """reference sampled_softmax_with_cross_entropy_op.cc: softmax CE over
    the true class + num_samples log-uniform negatives, logits adjusted by
    -log(expected count). Accidental hits (a sampled negative equal to the
    true label) are masked out when remove_accidental_hits."""
    logits = x(ins, "Logits")        # [B, C]
    label = x(ins, "Label").reshape(-1).astype(jnp.int32)
    b, c = logits.shape
    ns = int(attrs["num_samples"])
    key = (jax.random.key(int(attrs["seed"])) if attrs.get("seed")
           else ctx.rng())
    u = jax.random.uniform(key, (b, ns))
    neg = jnp.clip((jnp.exp(u * math.log(c + 1.0)) - 1.0).astype(jnp.int32),
                   0, c - 1)
    samples = jnp.concatenate([label[:, None], neg], axis=1)  # [B, 1+ns]
    q = jnp.log((samples + 2.0) / (samples + 1.0)) / math.log(c + 1.0)
    picked = jnp.take_along_axis(logits, samples, axis=1) - jnp.log(q)
    if attrs.get("remove_accidental_hits", True):
        hit = (samples[:, 1:] == label[:, None])
        picked = picked.at[:, 1:].add(jnp.where(hit, -1e20, 0.0))
    lse = jax.nn.logsumexp(picked, axis=1, keepdims=True)
    prob = jnp.exp(picked - lse)
    loss = (lse[:, 0] - picked[:, 0]).reshape(b, 1)
    return {"Samples": [samples.astype(jnp_dtype("int64"))],
            "Probabilities": [prob], "Loss": [loss]}


# -- host callback ----------------------------------------------------------

_PY_FUNCS = []


def register_py_func(fn) -> int:
    _PY_FUNCS.append(fn)
    return len(_PY_FUNCS) - 1


@register_op("py_func", inputs=[IOSpec("X", duplicable=True)],
             outputs=[IOSpec("Out", duplicable=True)],
             attrs={"func_id": 0, "out_shapes": [], "out_dtypes": []},
             grad=None)
def _py_func(ctx, ins, attrs):
    """reference py_func_op.cc (host python callback inside the graph) —
    on TPU this is jax.pure_callback: the compiled program stalls on the
    host roundtrip, so this is a debugging/IO escape hatch, not a compute
    path. backward_func is unsupported (the callback is opaque to vjp)."""
    from ..core.types import np_dtype

    fn = _PY_FUNCS[int(attrs["func_id"])]
    shapes = attrs["out_shapes"]
    dtypes = attrs["out_dtypes"]
    result_shape = [jax.ShapeDtypeStruct(tuple(s), np_dtype(d))
                    for s, d in zip(shapes, dtypes)]

    def host_fn(*arrays):
        res = fn(*arrays)
        if not isinstance(res, (list, tuple)):
            res = (res,)
        return tuple(np.asarray(r) for r in res)

    vals = [v for v in ins.get("X", []) if v is not None]
    res = jax.pure_callback(host_fn, result_shape, *vals)
    return {"Out": list(res)}


# -- resize / sequence tail -------------------------------------------------


@register_op("sequence_reshape",
             inputs=[IOSpec("X"), IOSpec("SeqLen", no_grad=True)],
             outputs=["Out", "OutLen"], attrs={"new_dim": 1})
def _sequence_reshape(ctx, ins, attrs):
    """reference sequence_reshape_op.h on the padded encoding: [B, T, D]
    -> [B, T*D/new_dim, new_dim]; lengths scale by D/new_dim."""
    xv = x(ins, "X")
    ln = x(ins, "SeqLen").reshape(-1).astype(jnp.int32)
    b, t, d = xv.shape
    nd = int(attrs["new_dim"])
    if (t * d) % nd:
        raise ValueError(f"sequence_reshape: T*D={t*d} not divisible by "
                         f"new_dim={nd}")
    new_len = (ln * d) // nd
    return {"Out": [xv.reshape(b, (t * d) // nd, nd)],
            "OutLen": [new_len]}


@register_op("sequence_expand_as",
             inputs=[IOSpec("X"), IOSpec("Y", no_grad=True),
                     IOSpec("SeqLen", no_grad=True)],
             outputs=["Out"], attrs={})
def _sequence_expand_as(ctx, ins, attrs):
    """reference sequence_expand_as_op.h: row i of X repeats to fill
    sequence i of Y. Padded encoding: X [B, K] broadcasts over Y's time
    axis, zeroed past each length."""
    xv = x(ins, "X")
    yv = x(ins, "Y")
    ln = x(ins, "SeqLen").reshape(-1).astype(jnp.int32)
    t = yv.shape[1]
    if xv.ndim == 1:
        xv = xv[:, None]
    expanded = jnp.broadcast_to(xv[:, None, :],
                                (xv.shape[0], t, xv.shape[-1]))
    mask = (jnp.arange(t)[None, :] < ln[:, None])[..., None]
    return out(jnp.where(mask, expanded, 0))


@register_op("sequence_scatter",
             inputs=[IOSpec("X"), IOSpec("Ids", no_grad=True),
                     IOSpec("Updates"), IOSpec("SeqLen", no_grad=True)],
             outputs=["Out"], attrs={})
def _sequence_scatter(ctx, ins, attrs):
    """reference sequence_scatter_op.h on the padded encoding: for each
    batch row b, Out[b, Ids[b, t]] += Updates[b, t] for t < len(b)."""
    xv = x(ins, "X")                  # [B, D]
    ids = x(ins, "Ids")
    upd = x(ins, "Updates")
    ln = x(ins, "SeqLen").reshape(-1).astype(jnp.int32)
    if ids.ndim == 3 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    if upd.ndim == 3 and upd.shape[-1] == 1:
        upd = upd[..., 0]
    b, t = ids.shape
    d = xv.shape[1]
    valid = jnp.arange(t)[None, :] < ln[:, None]
    tgt = jnp.where(valid, jnp.clip(ids.astype(jnp.int32), 0, d - 1), d)
    bidx = jnp.broadcast_to(jnp.arange(b)[:, None], (b, t))
    return out(xv.at[bidx, tgt].add(
        jnp.where(valid, upd, 0).astype(xv.dtype), mode="drop"))
