"""Optimizer update ops.

Reference: paddle/fluid/operators/optimizers/ (13 update rules, each a CUDA
kernel). Here each is a pure jnp update; the whole train step (forward +
backward + all updates) compiles into ONE XLA executable, so the per-param
"fused optimizer" passes of the reference (ir/fuse_optimizer_ops_pass/) are
unnecessary — XLA fuses across params in the same program.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.selected_rows import is_selected_rows
from .common import IOSpec, register_op, x


@register_op("sgd", inputs=["Param", "Grad", "LearningRate"],
             outputs=["ParamOut"], grad=None)
def _sgd(ctx, ins, attrs):
    p, g, lr = x(ins, "Param"), x(ins, "Grad"), x(ins, "LearningRate")
    lr = lr.reshape(()).astype(p.dtype)
    if is_selected_rows(g):
        # reference sgd_op.h sparse branch: update only touched rows;
        # sentinel-padded rows fall off via scatter mode="drop"
        return {"ParamOut": [p.at[g.rows].add(
            -lr * g.values.astype(p.dtype), mode="drop")]}
    return {"ParamOut": [p - lr * g.astype(p.dtype)]}


@register_op("momentum", inputs=["Param", "Grad", "Velocity", "LearningRate"],
             outputs=["ParamOut", "VelocityOut"],
             attrs={"mu": 0.9, "use_nesterov": False,
                    "regularization_method": "", "regularization_coeff": 0.0},
             grad=None)
def _momentum(ctx, ins, attrs):
    p = x(ins, "Param")
    g = x(ins, "Grad")
    v, lr = x(ins, "Velocity"), x(ins, "LearningRate").reshape(())
    mu = attrs["mu"]
    if is_selected_rows(g):
        # dense-semantics momentum with a sparse grad (missing rows carry
        # g=0 but their velocity still decays — reference momentum_op.h
        # DenseMomentumFunctor over a SelectedRows grad): the grad never
        # materializes dense, only elementwise O(vocab) state math remains
        gv = g.values.astype(p.dtype)
        if attrs.get("regularization_method") == "l2_decay":
            v_out = (mu * v + attrs["regularization_coeff"] * p).at[
                g.rows].add(gv, mode="drop")
            if attrs.get("use_nesterov"):
                p_out = (p - lr * (attrs["regularization_coeff"] * p
                                   + mu * v_out)).at[g.rows].add(
                    -lr * gv, mode="drop")
            else:
                p_out = p - lr * v_out
            return {"ParamOut": [p_out], "VelocityOut": [v_out]}
        v_out = (mu * v).at[g.rows].add(gv, mode="drop")
        if attrs.get("use_nesterov"):
            p_out = (p - lr * mu * v_out).at[g.rows].add(-lr * gv,
                                                         mode="drop")
        else:
            p_out = p - lr * v_out
        return {"ParamOut": [p_out], "VelocityOut": [v_out]}
    g = g.astype(p.dtype)
    if attrs.get("regularization_method") == "l2_decay":
        g = g + attrs["regularization_coeff"] * p
    v_out = mu * v + g
    if attrs.get("use_nesterov"):
        p_out = p - (g + mu * v_out) * lr
    else:
        p_out = p - lr * v_out
    return {"ParamOut": [p_out], "VelocityOut": [v_out]}


@register_op("lars_momentum", inputs=["Param", "Grad", "Velocity", "LearningRate"],
             outputs=["ParamOut", "VelocityOut"],
             attrs={"mu": 0.9, "lars_coeff": 0.001, "lars_weight_decay": 0.0005,
                    "epsilon": 0.0},
             grad=None)
def _lars_momentum(ctx, ins, attrs):
    p, g = x(ins, "Param"), x(ins, "Grad")
    v, lr = x(ins, "Velocity"), x(ins, "LearningRate").reshape(())
    mu, lars, wd = attrs["mu"], attrs["lars_coeff"], attrs["lars_weight_decay"]
    pn = jnp.sqrt(jnp.sum(jnp.square(p)))
    gn = jnp.sqrt(jnp.sum(jnp.square(g)))
    local_lr = jnp.where(
        (pn > 0) & (gn > 0),
        lr * lars * pn / (gn + wd * pn + attrs.get("epsilon", 0.0)),
        lr,
    )
    v_out = mu * v + local_lr * (g + wd * p)
    return {"ParamOut": [p - v_out], "VelocityOut": [v_out]}


@register_op("adam",
             inputs=["Param", "Grad", "LearningRate", "Moment1", "Moment2",
                     "Beta1Pow", "Beta2Pow"],
             outputs=["ParamOut", "Moment1Out", "Moment2Out",
                      "Beta1PowOut", "Beta2PowOut"],
             attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8,
                    "lazy_mode": False},
             grad=None)
def _adam(ctx, ins, attrs):
    p = x(ins, "Param")
    g = x(ins, "Grad")
    lr = x(ins, "LearningRate").reshape(())
    m1, m2 = x(ins, "Moment1"), x(ins, "Moment2")
    b1p, b2p = x(ins, "Beta1Pow").reshape(()), x(ins, "Beta2Pow").reshape(())
    b1, b2, eps = attrs["beta1"], attrs["beta2"], attrs["epsilon"]
    # bias correction uses the CURRENT pow accumulators (initialised to beta
    # at step 1), matching reference adam_op.h; pows advance afterwards
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    pows = {"Beta1PowOut": [(b1p * b1).reshape((1,))],
            "Beta2PowOut": [(b2p * b2).reshape((1,))]}
    if is_selected_rows(g):
        gv = g.values.astype(p.dtype)
        rows = g.rows
        if attrs.get("lazy_mode"):
            # reference adam_op.h SparseAdamFunctor lazy_mode: moments and
            # param touched ONLY at grad rows — O(touched x dim) update
            m1_r = b1 * m1[rows] + (1 - b1) * gv
            m2_r = b2 * m2[rows] + (1 - b2) * gv * gv
            upd = -lr_t * m1_r / (jnp.sqrt(m2_r) + eps)
            return {"ParamOut": [p.at[rows].add(upd, mode="drop")],
                    "Moment1Out": [m1.at[rows].set(m1_r, mode="drop")],
                    "Moment2Out": [m2.at[rows].set(m2_r, mode="drop")],
                    **pows}
        # non-lazy dense semantics (missing rows see g=0: moments decay,
        # params still move on the decayed moment) without materializing a
        # dense grad — scatter the (1-beta) terms into the decayed moments
        m1_out = (b1 * m1).at[rows].add((1 - b1) * gv, mode="drop")
        m2_out = (b2 * m2).at[rows].add((1 - b2) * gv * gv, mode="drop")
        p_out = p - lr_t * m1_out / (jnp.sqrt(m2_out) + eps)
        return {"ParamOut": [p_out], "Moment1Out": [m1_out],
                "Moment2Out": [m2_out], **pows}
    g = g.astype(p.dtype)
    m1_out = b1 * m1 + (1 - b1) * g
    m2_out = b2 * m2 + (1 - b2) * g * g
    p_out = p - lr_t * m1_out / (jnp.sqrt(m2_out) + eps)
    return {"ParamOut": [p_out], "Moment1Out": [m1_out], "Moment2Out": [m2_out],
            **pows}


@register_op("adamw",
             inputs=["Param", "Grad", "LearningRate", "Moment1", "Moment2",
                     "Beta1Pow", "Beta2Pow"],
             outputs=["ParamOut", "Moment1Out", "Moment2Out",
                      "Beta1PowOut", "Beta2PowOut"],
             attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8,
                    "weight_decay": 0.01},
             grad=None)
def _adamw(ctx, ins, attrs):
    p = x(ins, "Param")
    lr = x(ins, "LearningRate").reshape(())
    res = _adam(ctx, ins, attrs)
    res["ParamOut"] = [res["ParamOut"][0] - lr * attrs["weight_decay"] * p]
    return res


@register_op("adagrad", inputs=["Param", "Grad", "Moment", "LearningRate"],
             outputs=["ParamOut", "MomentOut"], attrs={"epsilon": 1e-6},
             grad=None)
def _adagrad(ctx, ins, attrs):
    p, g = x(ins, "Param"), x(ins, "Grad")
    m, lr = x(ins, "Moment"), x(ins, "LearningRate").reshape(())
    if is_selected_rows(g):
        # adagrad with g=0 is the identity, so the touched-rows update IS
        # dense semantics (reference adagrad_op.h sparse branch)
        gv = g.values.astype(p.dtype)
        m_r = m[g.rows] + gv * gv
        upd = -lr * gv / (jnp.sqrt(m_r) + attrs["epsilon"])
        return {"ParamOut": [p.at[g.rows].add(upd, mode="drop")],
                "MomentOut": [m.at[g.rows].set(m_r, mode="drop")]}
    m_out = m + g * g
    p_out = p - lr * g / (jnp.sqrt(m_out) + attrs["epsilon"])
    return {"ParamOut": [p_out], "MomentOut": [m_out]}


@register_op("decayed_adagrad", inputs=["Param", "Grad", "Moment",
                                        "LearningRate"],
             outputs=["ParamOut", "MomentOut"],
             attrs={"decay": 0.95, "epsilon": 1e-6}, grad=None)
def _decayed_adagrad(ctx, ins, attrs):
    """reference optimizers/decayed_adagrad_op.h: decayed average of grad^2,
    unlike adagrad's monotone accumulation."""
    p, g = x(ins, "Param"), x(ins, "Grad")
    m, lr = x(ins, "Moment"), x(ins, "LearningRate").reshape(())
    decay = attrs["decay"]
    m_out = decay * m + (1 - decay) * g * g
    p_out = p - lr * g / (jnp.sqrt(m_out) + attrs["epsilon"])
    return {"ParamOut": [p_out], "MomentOut": [m_out]}


@register_op("average_accumulates",
             inputs=["Param", "InSum1", "InSum2", "InSum3",
                     "InNumAccumulates", "InOldNumAccumulates",
                     "InNumUpdates"],
             outputs=["OutSum1", "OutSum2", "OutSum3", "OutNumAccumulates",
                      "OutOldNumAccumulates", "OutNumUpdates"],
             attrs={"average_window": 0.15, "min_average_window": 10000,
                    "max_average_window": 10000}, grad=None)
def _average_accumulates(ctx, ins, attrs):
    """reference operators/average_accumulates_op.h — the state machine behind
    ModelAverage: sum_1 accumulates params each step; sum_2 archives sum_1
    every kMaxNumAccumulates steps (float-precision guard); when the window is
    full, everything rolls into sum_3 and counting restarts. Branches become
    jnp.where so the whole rule stays jittable."""
    kMaxNumAccumulates = 16384
    p = x(ins, "Param")
    s1, s2, s3 = x(ins, "InSum1"), x(ins, "InSum2"), x(ins, "InSum3")
    num_acc = x(ins, "InNumAccumulates").reshape(())
    old_num = x(ins, "InOldNumAccumulates").reshape(())
    num_upd = x(ins, "InNumUpdates").reshape(())

    num_upd = num_upd + 1
    num_acc = num_acc + 1
    s1 = s1 + p

    # archive sum_1 into sum_2 periodically to bound fp error
    archive = (num_upd % kMaxNumAccumulates) == 0
    s2 = jnp.where(archive, s2 + s1, s2)
    s1 = jnp.where(archive, jnp.zeros_like(s1), s1)

    # window full -> roll into sum_3, restart counting
    window = jnp.minimum(
        jnp.asarray(attrs["max_average_window"], num_acc.dtype),
        (num_upd.astype(jnp.float32)
         * attrs["average_window"]).astype(num_acc.dtype))
    full = (num_acc >= attrs["min_average_window"]) & (num_acc >= window)
    s3 = jnp.where(full, s1 + s2, s3)
    s1 = jnp.where(full, jnp.zeros_like(s1), s1)
    s2 = jnp.where(full, jnp.zeros_like(s2), s2)
    old_num = jnp.where(full, num_acc, old_num)
    num_acc = jnp.where(full, jnp.zeros_like(num_acc), num_acc)

    return {"OutSum1": [s1], "OutSum2": [s2], "OutSum3": [s3],
            "OutNumAccumulates": [num_acc.reshape((1,))],
            "OutOldNumAccumulates": [old_num.reshape((1,))],
            "OutNumUpdates": [num_upd.reshape((1,))]}


@register_op("adadelta", inputs=["Param", "Grad", "AvgSquaredGrad",
                                 "AvgSquaredUpdate"],
             outputs=["ParamOut", "AvgSquaredGradOut", "AvgSquaredUpdateOut"],
             attrs={"rho": 0.95, "epsilon": 1e-6}, grad=None)
def _adadelta(ctx, ins, attrs):
    p, g = x(ins, "Param"), x(ins, "Grad")
    asg, asu = x(ins, "AvgSquaredGrad"), x(ins, "AvgSquaredUpdate")
    rho, eps = attrs["rho"], attrs["epsilon"]
    asg_out = rho * asg + (1 - rho) * g * g
    update = -jnp.sqrt((asu + eps) / (asg_out + eps)) * g
    asu_out = rho * asu + (1 - rho) * update * update
    return {"ParamOut": [p + update], "AvgSquaredGradOut": [asg_out],
            "AvgSquaredUpdateOut": [asu_out]}


@register_op("adamax", inputs=["Param", "Grad", "LearningRate", "Moment",
                               "InfNorm", "Beta1Pow"],
             outputs=["ParamOut", "MomentOut", "InfNormOut"],
             attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8}, grad=None)
def _adamax(ctx, ins, attrs):
    p, g = x(ins, "Param"), x(ins, "Grad")
    lr = x(ins, "LearningRate").reshape(())
    m, inf = x(ins, "Moment"), x(ins, "InfNorm")
    b1p = x(ins, "Beta1Pow").reshape(())
    b1, b2, eps = attrs["beta1"], attrs["beta2"], attrs["epsilon"]
    m_out = b1 * m + (1 - b1) * g
    inf_out = jnp.maximum(b2 * inf, jnp.abs(g) + eps)
    lr_t = lr / (1 - b1p)
    return {"ParamOut": [p - lr_t * m_out / inf_out], "MomentOut": [m_out],
            "InfNormOut": [inf_out]}


@register_op("rmsprop", inputs=["Param", "Grad", "MeanSquare", "MeanGrad",
                                "Moment", "LearningRate"],
             outputs=["ParamOut", "MomentOut", "MeanSquareOut", "MeanGradOut"],
             attrs={"decay": 0.9, "momentum": 0.0, "epsilon": 1e-10,
                    "centered": False},
             grad=None)
def _rmsprop(ctx, ins, attrs):
    p, g = x(ins, "Param"), x(ins, "Grad")
    ms, mg = x(ins, "MeanSquare"), x(ins, "MeanGrad")
    mom, lr = x(ins, "Moment"), x(ins, "LearningRate").reshape(())
    rho, mu, eps = attrs["decay"], attrs["momentum"], attrs["epsilon"]
    ms_out = rho * ms + (1 - rho) * g * g
    if attrs.get("centered"):
        mg_out = rho * mg + (1 - rho) * g
        denom = ms_out - mg_out * mg_out + eps
    else:
        mg_out = mg
        denom = ms_out + eps
    mom_out = mu * mom + lr * g / jnp.sqrt(denom)
    return {"ParamOut": [p - mom_out], "MomentOut": [mom_out],
            "MeanSquareOut": [ms_out], "MeanGradOut": [mg_out]}


@register_op("ftrl", inputs=["Param", "SquaredAccumulator", "LinearAccumulator",
                             "Grad", "LearningRate"],
             outputs=["ParamOut", "SquaredAccumOut", "LinearAccumOut"],
             attrs={"l1": 0.0, "l2": 0.0, "lr_power": -0.5}, grad=None)
def _ftrl(ctx, ins, attrs):
    p, g = x(ins, "Param"), x(ins, "Grad")
    sq, lin = x(ins, "SquaredAccumulator"), x(ins, "LinearAccumulator")
    lr = x(ins, "LearningRate").reshape(())
    l1, l2, lrp = attrs["l1"], attrs["l2"], attrs["lr_power"]
    new_sq = sq + g * g
    sigma = (jnp.power(new_sq, -lrp) - jnp.power(sq, -lrp)) / lr
    lin_out = lin + g - sigma * p
    quad = jnp.power(new_sq, -lrp) / lr + 2 * l2
    pre = jnp.clip(lin_out, -l1, l1) - lin_out
    p_out = pre / quad
    return {"ParamOut": [p_out], "SquaredAccumOut": [new_sq],
            "LinearAccumOut": [lin_out]}


@register_op("lamb",
             inputs=["Param", "Grad", "LearningRate", "Moment1", "Moment2",
                     "Beta1Pow", "Beta2Pow"],
             outputs=["ParamOut", "Moment1Out", "Moment2Out",
                      "Beta1PowOut", "Beta2PowOut"],
             attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-6,
                    "weight_decay": 0.01},
             grad=None)
def _lamb(ctx, ins, attrs):
    p = x(ins, "Param")
    g = x(ins, "Grad").astype(p.dtype)
    lr = x(ins, "LearningRate").reshape(())
    m1, m2 = x(ins, "Moment1"), x(ins, "Moment2")
    b1p, b2p = x(ins, "Beta1Pow").reshape(()), x(ins, "Beta2Pow").reshape(())
    b1, b2, eps, wd = (attrs["beta1"], attrs["beta2"], attrs["epsilon"],
                       attrs["weight_decay"])
    m1_out = b1 * m1 + (1 - b1) * g
    m2_out = b2 * m2 + (1 - b2) * g * g
    m1_hat = m1_out / (1 - b1p)
    m2_hat = m2_out / (1 - b2p)
    r = m1_hat / (jnp.sqrt(m2_hat) + eps) + wd * p
    w_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
    ratio = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
    return {"ParamOut": [p - lr * ratio * r], "Moment1Out": [m1_out],
            "Moment2Out": [m2_out],
            "Beta1PowOut": [(b1p * b1).reshape((1,))],
            "Beta2PowOut": [(b2p * b2).reshape((1,))]}
