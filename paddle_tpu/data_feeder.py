"""DataFeeder: convert python/numpy minibatches into executor feeds.

Reference: python/paddle/fluid/data_feeder.py (DataFeeder.feed converts a
list of sample tuples into per-variable LoDTensors on the target place).
Here the target representation is a dict name -> numpy batch; device
placement happens in the executor (or ahead of time in the DataLoader).
"""
from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from .core.types import np_dtype
from .framework import Variable

__all__ = ["DataFeeder", "coerce_feed_array"]


def coerce_feed_array(var: Variable, arr: np.ndarray) -> np.ndarray:
    """Coerce one batched array to a feed variable's declared dtype/rank:
    same-kind dtype cast, and label scalars fed as [N, 1] (the reference
    DataFeeder's LoDTensor convention). Shared by DataFeeder and the
    DataLoader staging path."""
    want = np_dtype(var.dtype)
    if arr.dtype != want and arr.dtype.kind == np.dtype(want).kind:
        arr = arr.astype(want)
    if var.shape is not None and arr.ndim == len(var.shape) - 1:
        arr = arr.reshape(arr.shape + (1,))
    return arr


def bucket_length(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= n; doubles past the largest configured bucket.
    Bounds the number of distinct padded shapes — and therefore XLA
    recompiles — the varlen path can produce (SURVEY §5 bucketed compile
    cache; the reference needs no buckets because LoD shapes are dynamic)."""
    for b in buckets:
        if n <= b:
            return b
    b = buckets[-1] if buckets else 1
    while b < n:
        b *= 2
    return b


DEFAULT_SEQ_BUCKETS = (8, 16, 32, 64, 128, 256, 512, 1024)


class DataFeeder:
    def __init__(self, feed_list: Sequence, place=None, program=None,
                 seq_buckets: Sequence[int] = DEFAULT_SEQ_BUCKETS):
        self.feed_names: List[str] = []
        self.feed_vars: List[Variable] = []
        for v in feed_list:
            if isinstance(v, str):
                if program is None:
                    raise ValueError("string feed names need a program")
                v = program.global_block.var(v)
            self.feed_vars.append(v)
            self.feed_names.append(v.name)
        self.place = place
        self.seq_buckets = tuple(seq_buckets)

    def feed(self, iterable) -> Dict[str, np.ndarray]:
        """iterable: list of sample tuples, one tuple per example, fields
        aligned with feed_list. Returns {name: batched ndarray} with dtypes
        coerced to each variable's declared dtype. For lod_level>=1 vars the
        samples are variable-length sequences: they are padded to a bucketed
        max_len and a '<name>@LOD' int32 lengths entry is added (the padded
        + lengths encoding consumed by the sequence ops)."""
        samples = list(iterable)
        if not samples:
            raise ValueError("empty minibatch")
        cols = list(zip(*[s if isinstance(s, (list, tuple)) else (s,)
                          for s in samples]))
        if len(cols) != len(self.feed_names):
            raise ValueError(
                f"sample has {len(cols)} fields, feed_list expects "
                f"{len(self.feed_names)} ({self.feed_names})")
        out = {}
        for var, col in zip(self.feed_vars, cols):
            if var.lod_level >= 1:
                arr, lengths = self._pad_varlen(var, col)
                out[var.name] = arr
                out[var.name + "@LOD"] = lengths
            else:
                arr = np.stack([np.asarray(v, dtype=np_dtype(var.dtype))
                                for v in col])
                out[var.name] = coerce_feed_array(var, arr)
        return out

    def _pad_varlen(self, var: Variable, col):
        dt = np_dtype(var.dtype)
        seqs = [np.asarray(v, dtype=dt) for v in col]
        lengths = np.array([s.shape[0] for s in seqs], dtype=np.int32)
        max_len = bucket_length(int(lengths.max()), self.seq_buckets)
        feat = seqs[0].shape[1:]
        arr = np.zeros((len(seqs), max_len) + feat, dtype=dt)
        for i, s in enumerate(seqs):
            arr[i, :s.shape[0]] = s
        if var.shape is not None and arr.ndim == len(var.shape) - 1:
            # token scalars fed as [.., 1] (reference LoDTensor convention)
            arr = arr.reshape(arr.shape + (1,))
        return arr, lengths
