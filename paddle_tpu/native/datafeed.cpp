// Native data-feed engine: multi-threaded MultiSlot text parsing into a
// bounded blocking queue, drained in fixed-size batches.
//
// Reference: paddle/fluid/framework/data_feed.{h,cc} — MultiSlotDataFeed
// (:532) parses the MultiSlot text protocol ("<num> <v...>" per slot per
// line) on worker threads; LoDTensorBlockingQueue
// (operators/reader/lod_tensor_blocking_queue.h) hands batches to the
// trainer. This is the TPU-native equivalent of that C++ ingest path: the
// GIL-free parse + queue live here, Python only moves ready numpy batches
// to the device (where jax.device_put overlaps the transfer).
//
// C ABI (ctypes-friendly, no pybind11 in this environment):
//   df_create(spec)      spec = "name:f|i:len,..." fixed-length slots
//   df_set_capacity(h, cap)
//   df_add_file(h, path)
//   df_start(h, nthreads)
//   df_next(h, batch, float** fbufs, long long** ibufs) -> rows filled
//   df_parse_errors(h)   (call after df_stop_join for a final count)
//   df_stop_join(h)      stop + join producers, handle stays valid
//   df_destroy(h)
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <fstream>
#include <mutex>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

namespace {

struct SlotSpec {
  std::string name;
  bool is_float;
  int len;  // values per instance (fixed-length slots)
};

struct Instance {
  std::vector<float> fvals;     // concatenated float slots
  std::vector<int64_t> ivals;   // concatenated int slots
};

struct Feed {
  std::vector<SlotSpec> slots;
  std::vector<std::string> files;
  size_t capacity = 1024;

  std::mutex mu;
  std::condition_variable cv_push, cv_pop;
  std::deque<Instance> queue;
  std::vector<std::thread> workers;
  std::atomic<int> live_workers{0};
  std::atomic<size_t> parse_errors{0};
  std::atomic<bool> stop{false};
  bool started = false;

  int flen = 0, ilen = 0;  // per-instance totals

  ~Feed() {
    // wake producers parked on a full queue so join() can't deadlock when
    // the consumer abandons iteration early
    stop = true;
    {
      std::lock_guard<std::mutex> lk(mu);
      cv_push.notify_all();
      cv_pop.notify_all();
    }
    join();
  }

  void join() {
    for (auto& t : workers)
      if (t.joinable()) t.join();
    workers.clear();
  }

  bool parse_line(const std::string& line, Instance* out) {
    const char* p = line.c_str();
    char* end = nullptr;
    out->fvals.reserve(flen);
    out->ivals.reserve(ilen);
    for (const auto& s : slots) {
      long n = strtol(p, &end, 10);
      if (end == p || n != s.len) return false;  // strict fixed-length
      p = end;
      for (long k = 0; k < n; ++k) {
        if (s.is_float) {
          float v = strtof(p, &end);
          if (end == p) return false;
          out->fvals.push_back(v);
        } else {
          long long v = strtoll(p, &end, 10);
          if (end == p) return false;
          out->ivals.push_back((int64_t)v);
        }
        p = end;
      }
    }
    return true;
  }

  void worker(size_t start_idx, size_t stride) {
    for (size_t fi = start_idx; fi < files.size() && !stop; fi += stride) {
      std::ifstream in(files[fi]);
      std::string line;
      while (!stop && std::getline(in, line)) {
        // blank/whitespace-only lines are skipped, not errors (matches the
        // Python fallback's `if not toks: continue`)
        if (line.find_first_not_of(" \t\r") == std::string::npos) continue;
        Instance inst;
        if (!parse_line(line, &inst)) {
          parse_errors++;
          continue;
        }
        std::unique_lock<std::mutex> lk(mu);
        cv_push.wait(lk, [&] { return queue.size() < capacity || stop; });
        if (stop) break;
        queue.push_back(std::move(inst));
        cv_pop.notify_one();
      }
    }
    if (--live_workers == 0) {
      std::lock_guard<std::mutex> lk(mu);
      cv_pop.notify_all();
    }
  }

  void start(int nthreads) {
    flen = ilen = 0;
    for (const auto& s : slots) (s.is_float ? flen : ilen) += s.len;
    live_workers = nthreads;
    started = true;
    for (int i = 0; i < nthreads; ++i)
      workers.emplace_back([this, i, nthreads] { worker(i, nthreads); });
  }

  // Fill row-major [batch, len] buffers; returns rows actually written
  // (may be < batch at end of data; 0 = exhausted).
  int next(int batch, float** fbufs, int64_t** ibufs) {
    int rows = 0;
    while (rows < batch) {
      Instance inst;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_pop.wait(lk, [&] {
          return !queue.empty() || live_workers.load() == 0 || stop;
        });
        if (stop) break;
        if (queue.empty()) break;  // drained and no producers left
        inst = std::move(queue.front());
        queue.pop_front();
        cv_push.notify_one();
      }
      size_t fo = 0, io = 0, fslot = 0, islot = 0;
      for (const auto& s : slots) {
        if (s.is_float) {
          std::memcpy(fbufs[fslot] + (size_t)rows * s.len,
                      inst.fvals.data() + fo, s.len * sizeof(float));
          fo += s.len;
          fslot++;
        } else {
          std::memcpy(ibufs[islot] + (size_t)rows * s.len,
                      inst.ivals.data() + io, s.len * sizeof(int64_t));
          io += s.len;
          islot++;
        }
      }
      rows++;
    }
    return rows;
  }
};

}  // namespace

extern "C" {

void* df_create(const char* spec) {
  auto* f = new Feed();
  std::stringstream ss(spec);
  std::string tok;
  while (std::getline(ss, tok, ',')) {
    size_t a = tok.find(':'), b = tok.rfind(':');
    if (a == std::string::npos || b == a) {
      delete f;
      return nullptr;
    }
    SlotSpec s;
    s.name = tok.substr(0, a);
    s.is_float = tok.substr(a + 1, b - a - 1) == "f";
    s.len = atoi(tok.c_str() + b + 1);
    if (s.len <= 0) {
      delete f;
      return nullptr;
    }
    f->slots.push_back(s);
  }
  return f->slots.empty() ? (delete f, nullptr) : f;
}

void df_set_capacity(void* h, int cap) {
  static_cast<Feed*>(h)->capacity = cap > 0 ? cap : 1024;
}

void df_add_file(void* h, const char* path) {
  static_cast<Feed*>(h)->files.emplace_back(path);
}

int df_start(void* h, int nthreads) {
  auto* f = static_cast<Feed*>(h);
  if (f->started || nthreads <= 0) return -1;
  f->start(nthreads);
  return 0;
}

int df_next(void* h, int batch, float** fbufs, int64_t** ibufs) {
  return static_cast<Feed*>(h)->next(batch, fbufs, ibufs);
}

long long df_parse_errors(void* h) {
  return (long long)static_cast<Feed*>(h)->parse_errors.load();
}

// Stop producers and join them WITHOUT freeing the handle, so counters can
// be read race-free before df_destroy.
void df_stop_join(void* h) {
  auto* f = static_cast<Feed*>(h);
  f->stop = true;
  {
    std::lock_guard<std::mutex> lk(f->mu);
    f->cv_push.notify_all();
    f->cv_pop.notify_all();
  }
  f->join();
}

void df_destroy(void* h) { delete static_cast<Feed*>(h); }

}  // extern "C"
