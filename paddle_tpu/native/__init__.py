"""Native (C++) runtime components, built on demand with the system g++.

Reference: the framework's native ingest path —
paddle/fluid/framework/data_feed.cc MultiSlotDataFeed +
operators/reader/lod_tensor_blocking_queue.h — is C++ so parsing never
holds the GIL. Same here: datafeed.cpp compiles once into a cached shared
object; if no compiler is available the callers fall back to the Python
readers (degraded but functional).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_lib = None
_build_error: Optional[str] = None


def _build_dir() -> str:
    d = os.environ.get("PADDLE_TPU_NATIVE_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "paddle_tpu", "native")
    os.makedirs(d, exist_ok=True)
    return d


def load_datafeed() -> Optional[ctypes.CDLL]:
    """Compile-and-load (cached by source hash). None if no toolchain."""
    global _lib, _build_error
    if _lib is not None:
        return _lib
    if _build_error is not None:
        return None
    src = os.path.join(_HERE, "datafeed.cpp")
    with open(src, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:16]
    so = os.path.join(_build_dir(), f"datafeed_{tag}.so")
    if not os.path.exists(so):
        tmp = so + f".tmp{os.getpid()}"
        cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
               src, "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
            os.replace(tmp, so)
        except (subprocess.CalledProcessError, FileNotFoundError) as e:
            _build_error = getattr(e, "stderr", str(e)) or str(e)
            return None
    lib = ctypes.CDLL(so)
    lib.df_create.restype = ctypes.c_void_p
    lib.df_create.argtypes = [ctypes.c_char_p]
    lib.df_set_capacity.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.df_add_file.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.df_start.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.df_start.restype = ctypes.c_int
    lib.df_next.argtypes = [ctypes.c_void_p, ctypes.c_int,
                            ctypes.POINTER(ctypes.c_void_p),
                            ctypes.POINTER(ctypes.c_void_p)]
    lib.df_next.restype = ctypes.c_int
    lib.df_parse_errors.argtypes = [ctypes.c_void_p]
    lib.df_parse_errors.restype = ctypes.c_longlong
    lib.df_stop_join.argtypes = [ctypes.c_void_p]
    lib.df_destroy.argtypes = [ctypes.c_void_p]
    _lib = lib
    return _lib


def build_error() -> Optional[str]:
    return _build_error
