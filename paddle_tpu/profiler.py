"""Profiler: fluid.profiler API over jax.profiler.

Reference: python/paddle/fluid/profiler.py (:225 profiler context manager,
:127 start_profiler, :168 stop_profiler) and the C++ RecordEvent/CUPTI
tracer (platform/profiler.h, device_tracer.h). On TPU the equivalent
substrate is the XLA/XPlane trace: jax.profiler.trace writes a TensorBoard-
loadable (and Perfetto-convertible) dump — the tools/timeline.py role.
Op-level host annotations use jax.profiler.TraceAnnotation, the RecordEvent
analogue.
"""
from __future__ import annotations

import contextlib
import time
from collections import defaultdict

import jax

__all__ = ["profiler", "start_profiler", "stop_profiler", "reset_profiler",
           "RecordEvent", "cuda_profiler", "npu_profiler"]

_trace_dir = None
_host_events = defaultdict(lambda: [0, 0.0])  # name -> [count, total_s]
_host_spans = []  # (name, t0_s, t1_s, small_tid) while profiling
_tid_map = {}     # thread ident -> stable small timeline row id
import threading as _threading  # noqa: E402

_tid_lock = _threading.Lock()


def start_profiler(state="All", tracer_option=None, profile_path="/tmp/profile"):
    global _trace_dir
    _trace_dir = profile_path
    jax.profiler.start_trace(profile_path)


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    global _trace_dir
    jax.profiler.stop_trace()
    _print_host_report(sorted_key)
    # span dump consumed by tools/timeline.py (the reference writes
    # profiler.proto consumed by its timeline.py; here it is JSON)
    if _trace_dir:
        import json
        import os

        with open(os.path.join(_trace_dir, "host_events.json"), "w") as f:
            json.dump([{"name": n, "t0": a, "t1": b, "tid": t}
                       for n, a, b, t in _host_spans], f)
    _trace_dir = None
    _host_spans.clear()


def reset_profiler():
    _host_events.clear()
    _host_spans.clear()


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile",
             tracer_option=None):
    start_profiler(state, tracer_option, profile_path)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


class RecordEvent:
    """Host-side RAII marker (reference platform/profiler.h:81); shows up in
    the XPlane trace as a TraceAnnotation and in the host-side table."""

    def __init__(self, name: str):
        self.name = name
        self._ann = jax.profiler.TraceAnnotation(name)

    def __enter__(self):
        self._t0 = time.perf_counter()
        self._ann.__enter__()
        return self

    def __exit__(self, *exc):
        self._ann.__exit__(*exc)
        t1 = time.perf_counter()
        rec = _host_events[self.name]
        rec[0] += 1
        rec[1] += t1 - self._t0
        if _trace_dir is not None:
            import threading

            ident = threading.get_ident()
            with _tid_lock:
                tid = _tid_map.setdefault(ident, len(_tid_map))
            _host_spans.append((self.name, self._t0, t1, tid))
        return False


def _print_host_report(sorted_key=None):
    if not _host_events:
        return
    rows = [(name, cnt, tot, tot / cnt)
            for name, (cnt, tot) in _host_events.items()]
    if sorted_key in ("total", None):
        rows.sort(key=lambda r: -r[2])
    elif sorted_key == "calls":
        rows.sort(key=lambda r: -r[1])
    elif sorted_key == "ave":
        rows.sort(key=lambda r: -r[3])
    print(f"{'Event':<40}{'Calls':>8}{'Total(s)':>12}{'Avg(s)':>12}")
    for name, cnt, tot, avg in rows:
        print(f"{name:<40}{cnt:>8}{tot:>12.6f}{avg:>12.6f}")


@contextlib.contextmanager
def cuda_profiler(*a, **k):
    """Compat no-op (reference profiler.py:39): TPU has no nvprof."""
    yield


npu_profiler = cuda_profiler
