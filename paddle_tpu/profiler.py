"""Profiler: fluid.profiler API over jax.profiler.

Reference: python/paddle/fluid/profiler.py (:225 profiler context manager,
:127 start_profiler, :168 stop_profiler) and the C++ RecordEvent/CUPTI
tracer (platform/profiler.h, device_tracer.h). On TPU the equivalent
substrate is the XLA/XPlane trace: jax.profiler.trace writes a TensorBoard-
loadable (and Perfetto-convertible) dump — the tools/timeline.py role.
Op-level host annotations use jax.profiler.TraceAnnotation, the RecordEvent
analogue; ``paddle_tpu.monitor`` feeds its executor spans (compile stages,
step dispatch) through RecordEvent too, so they land in the same timeline.

Thread-safety: all host-side state (event aggregates, span list, tid map)
is guarded by one module lock — RecordEvent is used from DataLoader worker
threads while ``stop_profiler`` snapshots and clears from the main thread.

``stop_profiler`` returns the host report as a structure (and logs it via
``logging``) so test suites and servers can consume it; the printed table
remains for CLI compatibility with the reference.
"""
from __future__ import annotations

import contextlib
import logging
import threading
import time
from collections import defaultdict

from .monitor.lockwitness import make_lock
from typing import Optional

import jax

__all__ = ["profiler", "start_profiler", "stop_profiler", "reset_profiler",
           "RecordEvent", "cuda_profiler", "npu_profiler"]

log = logging.getLogger("paddle_tpu.profiler")

# one lock for every piece of host-side profiling state: RecordEvent
# exits on worker threads race stop_profiler's snapshot-and-clear
_lock = make_lock("profiler._lock")
_trace_dir: Optional[str] = None
_host_events = defaultdict(lambda: [0, 0.0])  # name -> [count, total_s]
# (name, t0_s, t1_s, small_tid, epoch0_s) while profiling: t0/t1 are
# perf_counter (durations), epoch0 is time.time() at __enter__ — the
# shared wall-clock anchor that lets tools/timeline.py merge these host
# events with paddle_tpu.trace spans on one Chrome timeline
_host_spans = []
_tid_map = {}     # thread ident -> stable small timeline row id


def start_profiler(state="All", tracer_option=None, profile_path="/tmp/profile"):
    global _trace_dir
    with _lock:
        _trace_dir = profile_path
    jax.profiler.start_trace(profile_path)


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    """Stop tracing; aggregate and emit the host-side event report.

    Returns ``{"events": [{"name", "calls", "total_s", "avg_s"}, ...],
    "sorted_by": key, "spans_path": path-or-None}`` — the structure a test
    suite or server asserts on. The same table is logged at INFO on the
    ``paddle_tpu.profiler`` logger and printed (reference CLI behaviour).
    """
    global _trace_dir
    jax.profiler.stop_trace()
    with _lock:
        trace_dir, _trace_dir = _trace_dir, None
        spans = list(_host_spans)
        _host_spans.clear()
        events = {name: (cnt, tot)
                  for name, (cnt, tot) in _host_events.items()}
    report = _host_report(events, sorted_key)
    table = _format_host_report(report)
    if table:
        log.info("host event report (sorted by %s):\n%s",
                 report["sorted_by"], table)
        print(table)
    # span dump consumed by tools/timeline.py (the reference writes
    # profiler.proto consumed by its timeline.py; here it is JSON)
    if trace_dir:
        import json
        import os

        path = os.path.join(trace_dir, "host_events.json")
        with open(path, "w") as f:
            json.dump([{"name": n, "t0": a, "t1": b, "tid": t,
                        "epoch": e}
                       for n, a, b, t, e in spans], f)
        report["spans_path"] = path
    return report


def reset_profiler():
    with _lock:
        _host_events.clear()
        _host_spans.clear()


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile",
             tracer_option=None):
    start_profiler(state, tracer_option, profile_path)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


class RecordEvent:
    """Host-side RAII marker (reference platform/profiler.h:81); shows up in
    the XPlane trace as a TraceAnnotation and in the host-side table."""

    def __init__(self, name: str):
        self.name = name
        self._ann = jax.profiler.TraceAnnotation(name)

    def __enter__(self):
        self._t0 = time.perf_counter()
        # wall-clock anchor at open: perf_counter deltas alone cannot be
        # merged with trace spans or other processes' dumps
        self._epoch0 = time.time()
        self._ann.__enter__()
        return self

    def __exit__(self, *exc):
        self._ann.__exit__(*exc)
        t1 = time.perf_counter()
        ident = threading.get_ident()
        with _lock:
            rec = _host_events[self.name]
            rec[0] += 1
            rec[1] += t1 - self._t0
            if _trace_dir is not None:
                tid = _tid_map.setdefault(ident, len(_tid_map))
                _host_spans.append((self.name, self._t0, t1, tid,
                                    self._epoch0))
        return False


def _host_report(events, sorted_key=None) -> dict:
    rows = [{"name": name, "calls": cnt, "total_s": tot,
             "avg_s": tot / cnt}
            for name, (cnt, tot) in events.items()]
    sorted_by = sorted_key or "total"
    if sorted_by == "total":
        rows.sort(key=lambda r: -r["total_s"])
    elif sorted_by == "calls":
        rows.sort(key=lambda r: -r["calls"])
    elif sorted_by == "ave":
        rows.sort(key=lambda r: -r["avg_s"])
    return {"events": rows, "sorted_by": sorted_by, "spans_path": None}


def _format_host_report(report: dict) -> str:
    if not report["events"]:
        return ""
    lines = [f"{'Event':<40}{'Calls':>8}{'Total(s)':>12}{'Avg(s)':>12}"]
    for r in report["events"]:
        lines.append(f"{r['name']:<40}{r['calls']:>8}"
                     f"{r['total_s']:>12.6f}{r['avg_s']:>12.6f}")
    return "\n".join(lines)


@contextlib.contextmanager
def cuda_profiler(*a, **k):
    """Compat no-op (reference profiler.py:39): TPU has no nvprof."""
    yield


npu_profiler = cuda_profiler
