"""DistributeTranspiler — the 2019 parameter-server front door, on TPU.

Reference: python/paddle/fluid/transpiler/distribute_transpiler.py:230
(DistributeTranspiler), :494 (transpile), :130 (DistributeTranspilerConfig).

The TPU-native decision, stated once: **there are no parameter servers.**
Parameters live on the chips, sharded by GSPMD over the device mesh, and
gradient exchange is an XLA all-reduce over ICI — the job the reference
splits between trainers, pservers, and gRPC is one compiled program here.
This shim keeps a 2019 PS script runnable without rewriting it:

- **sync pserver mode** maps onto the collective path. The "trainer"
  program is the original program (run it through ``CompiledProgram``'s
  data-parallel path, or plain ``Executor`` single-chip — the same thing
  the reference's trainer did, minus send/recv). The "pserver" program is
  an empty no-op program: a process whose role is PSERVER starts, runs it,
  and exits immediately — the chips already hold the parameters.
- **async / half-async / DC-ASGD / GEO modes raise** with a migration
  message. Their consistency semantics (stale updates tolerated for
  throughput) bought back network latency that ICI does not have; there is
  no TPU analogue, and silently running them synchronously would change
  convergence behavior the user tuned for. This raise IS the documented
  decision surface (VERDICT r3 item 4).
- **nccl2 / collective modes** record endpoints and return the program
  unchanged: bootstrap moved to ``distributed.init_parallel_env`` (the
  gen_nccl_id replacement, reference gen_nccl_id_op.cc:162).
"""
from __future__ import annotations

from typing import Optional

from ..framework import (Program, default_main_program,
                         default_startup_program)
from .ps_dispatcher import HashName, PSDispatcher, RoundRobin

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig",
           "HashName", "RoundRobin", "PSDispatcher"]

_ASYNC_MIGRATION_MSG = (
    "async parameter-server training has no TPU analogue: its relaxed "
    "consistency (communicator.h:273 AsyncCommunicator merging stale "
    "grads) traded convergence for network latency that ICI does not "
    "have. Use sync_mode=True (lowered onto XLA collectives), or "
    "fleet.DistributedStrategy(use_local_sgd=True) for reduced "
    "communication frequency with defined semantics."
)

_GEO_MIGRATION_MSG = (
    "GEO-SGD (communicator.h:320 GeoSgdCommunicator, param deltas every "
    "k steps) is intentionally unsupported on TPU. LocalSGD has the same "
    "communication profile with defined convergence: "
    "fleet.DistributedStrategy(use_local_sgd=True)."
)


class DistributeTranspilerConfig:
    """Reference distribute_transpiler.py:130. Knobs that still steer the
    TPU lowering are honored; the rest are accepted for parity (they
    configured gRPC block-slicing that XLA's GSPMD partitioner now owns)."""

    slice_var_up = True
    split_method = None
    min_block_size = 8192
    enable_dc_asgd = False
    mode = "pserver"            # pserver | nccl2 | collective
    print_log = False
    wait_port = True
    _runtime_split_send_recv = False
    _sync_mode = True
    geo_sgd_mode = False
    geo_sgd_need_push_nums = 100
    nccl_comm_num = 1
    use_hierarchical_allreduce = False
    hierarchical_allreduce_inter_nranks = 0
    collective_mode = None      # grad_allreduce | local_sgd

    def __init__(self):
        pass

    @property
    def runtime_split_send_recv(self):
        return self._runtime_split_send_recv

    @runtime_split_send_recv.setter
    def runtime_split_send_recv(self, value):
        if value is None:
            raise ValueError("runtime_split_send_recv can't be None")
        if value and self._sync_mode:
            raise ValueError("set config.sync_mode=False before enabling "
                             "runtime_split_send_recv")
        self._runtime_split_send_recv = value

    @property
    def sync_mode(self):
        return self._sync_mode

    @sync_mode.setter
    def sync_mode(self, value):
        if value is None:
            raise ValueError("sync_mode can't be None")
        if value and self._runtime_split_send_recv:
            raise ValueError("set runtime_split_send_recv=False before "
                             "enabling sync_mode")
        self._sync_mode = value


class DistributeTranspiler:
    """Reference distribute_transpiler.py:230. See module docstring for the
    TPU mapping of each mode."""

    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        self.config = config or DistributeTranspilerConfig()
        if self.config.split_method is None:
            self.config.split_method = RoundRobin
        assert self.config.min_block_size >= 8192
        assert self.config.split_method.__bases__[0] == PSDispatcher
        self._transpiled = False

    def transpile(self, trainer_id, program=None,
                  pservers="127.0.0.1:6174", trainers=1, sync_mode=True,
                  startup_program=None,
                  current_endpoint="127.0.0.1:6174"):
        """Reference :494. Records the cluster layout; the program itself is
        NOT rewritten (no send/recv splicing — collectives are inserted by
        GSPMD at compile time, multi_devices_graph_pass.cc:454's job)."""
        self.origin_program = program or default_main_program()
        self.startup_program = startup_program or default_startup_program()
        self.trainer_id = trainer_id
        self.sync_mode = sync_mode

        if self.config.mode in ("nccl2", "collective"):
            # reference nccl2 path ignores sync_mode (distribute_transpiler
            # .py:560 returns before the pserver machinery); collective mode
            # (reference _transpile_collective) likewise only records the
            # cluster layout — bootstrap is distributed.init_parallel_env
            if not isinstance(trainers, str):
                raise ValueError(f"{self.config.mode} mode takes trainers as "
                                 "a comma-separated endpoint string")
            self.trainer_endpoints = trainers.split(",")
            self.trainer_num = len(self.trainer_endpoints)
            self.current_endpoint = current_endpoint
            self.origin_program._trainers_endpoints = self.trainer_endpoints
            self._transpiled = True
            return

        if self.config.geo_sgd_mode:
            raise NotImplementedError(_GEO_MIGRATION_MSG)
        if not sync_mode or not self.config.sync_mode:
            raise NotImplementedError(_ASYNC_MIGRATION_MSG)
        if self.config.enable_dc_asgd:
            raise NotImplementedError(_ASYNC_MIGRATION_MSG)

        self.trainer_num = int(trainers)
        self.pserver_endpoints = [ep.strip() for ep in pservers.split(",")]
        self.current_endpoint = current_endpoint
        # logical shard layout: which pserver each parameter WOULD have
        # lived on (kept so checkpoint tooling can answer layout questions;
        # nothing at runtime consumes it — GSPMD owns real placement)
        dispatcher = self.config.split_method(self.pserver_endpoints)
        params = [v for v in self.origin_program.global_block.vars.values()
                  if getattr(v, "trainable", False)
                  or type(v).__name__ == "Parameter"]
        self.param_grad_ep_mapping = {ep: {"params": [], "grads": []}
                                      for ep in self.pserver_endpoints}
        for p, ep in zip(params, dispatcher.dispatch(params)):
            self.param_grad_ep_mapping[ep]["params"].append(p)
        self._transpiled = True

    def _require_transpiled(self):
        if not self._transpiled:
            raise RuntimeError("call transpile() first")

    def get_trainer_program(self, wait_port=True):
        """Reference :832. The trainer program is the ORIGINAL program:
        gradient exchange is compiled in by GSPMD when the program runs
        under CompiledProgram/fleet, not spliced in as send/recv ops."""
        self._require_transpiled()
        return self.origin_program

    def get_pserver_program(self, endpoint):
        """Reference :966. A no-op program: on TPU the parameters already
        live device-sharded, so a pserver-role process has nothing to
        serve. Running it returns immediately, letting unmodified 2019
        launch scripts (which spawn pserver processes) complete cleanly."""
        self._require_transpiled()
        if endpoint not in self.pserver_endpoints:
            raise ValueError(f"endpoint {endpoint!r} not in pserver list "
                             f"{self.pserver_endpoints}")
        prog = Program()
        prog._is_pserver_noop = True
        prog._pserver_endpoint = endpoint
        return prog

    def get_pserver_programs(self, endpoint):
        """Reference :1223 — (main, startup) pair for a pserver."""
        return (self.get_pserver_program(endpoint),
                self.get_startup_program(endpoint))

    def get_startup_program(self, endpoint=None, pserver_program=None,
                            startup_program=None):
        """Reference :1252. Pserver startup is empty for the same reason
        its main program is."""
        self._require_transpiled()
        prog = Program()
        prog._is_pserver_noop = True
        return prog
