"""Parameter-server shard dispatchers (API parity).

Reference: python/paddle/fluid/transpiler/ps_dispatcher.py:18 (PSDispatcher),
:46 (HashName), :65 (RoundRobin). On TPU there are no parameter servers —
parameters live mesh-sharded on the chips — but the dispatch policy objects
remain part of ``DistributeTranspilerConfig.split_method``'s public surface,
and the shim uses them to report which *logical* shard each variable would
have landed on (useful for checkpoint-layout compatibility tooling).
"""
from __future__ import annotations

__all__ = ["PSDispatcher", "HashName", "RoundRobin"]


class PSDispatcher:
    """Base class: dispatch a list of variables onto endpoints."""

    def __init__(self, pserver_endpoints):
        self._eps = list(pserver_endpoints)
        self._step = 0

    @property
    def eps(self):
        return self._eps

    def reset(self):
        self._step = 0

    def dispatch(self, varlist):
        raise NotImplementedError("use HashName or RoundRobin")


class HashName(PSDispatcher):
    """Hash each var name onto an endpoint (reference ps_dispatcher.py:46)."""

    def __init__(self, pserver_endpoints):
        super().__init__(pserver_endpoints)

    def _hash_block(self, block_str, total):
        # stable across processes (builtin hash() is salted per-interpreter,
        # which would scatter the same var to different servers per rank).
        # This intentionally DIVERGES from the reference's builtin hash():
        # the var->endpoint layout here answers "which shard would this
        # param have lived on" for checkpoint tooling within THIS framework
        # only — nothing consumes reference-layout parity, and the
        # reference's own layout was never stable across interpreters.
        import zlib

        return zlib.crc32(block_str.encode()) % total

    def dispatch(self, varlist):
        eplist = []
        for var in varlist:
            name = var if isinstance(var, str) else var.name
            server_id = self._hash_block(name, len(self._eps))
            eplist.append(self._eps[server_id])
        return eplist


class RoundRobin(PSDispatcher):
    """Distribute vars round-robin (reference ps_dispatcher.py:65)."""

    def __init__(self, pserver_endpoints):
        super().__init__(pserver_endpoints)

    def dispatch(self, varlist):
        eplist = []
        for _ in varlist:
            eplist.append(self._eps[self._step])
            self._step = (self._step + 1) % len(self._eps)
        return eplist
