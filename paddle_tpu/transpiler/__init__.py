"""Transpiler package — 2019 distributed front-door compatibility.

Reference: python/paddle/fluid/transpiler/. The PS/async machinery is
re-decided for TPU (see distribute_transpiler module docstring); the
memory transpilers are documented no-ops (XLA owns buffers).
"""
from .distribute_transpiler import (DistributeTranspiler,
                                    DistributeTranspilerConfig)
from .geo_sgd_transpiler import GeoSgdTranspiler
from .memory_optimization_transpiler import memory_optimize, release_memory
from .ps_dispatcher import HashName, PSDispatcher, RoundRobin

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig",
           "GeoSgdTranspiler", "memory_optimize", "release_memory",
           "HashName", "PSDispatcher", "RoundRobin"]
