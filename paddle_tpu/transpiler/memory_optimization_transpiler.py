"""Legacy memory-optimize transpiler (reference
transpiler/memory_optimization_transpiler.py:18 memory_optimize, :42
release_memory).

On TPU these are no-ops by design, not omission: buffer liveness, reuse,
and in-place rewriting are owned by XLA buffer assignment (the reference's
own 1.6 release already deprecated this pass in favor of compile-time
analysis). The functions stay importable so 2019 scripts run unchanged;
they validate arguments and return the program untouched.
"""
from __future__ import annotations

import warnings

__all__ = ["memory_optimize", "release_memory"]


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=True):
    if level not in (0, 1):
        raise ValueError("level must be 0 or 1")
    warnings.warn(
        "memory_optimize is a no-op on TPU: XLA buffer assignment performs "
        "liveness-based reuse and in-placing at compile time "
        "(reference deprecated this pass for the same reason).",
        stacklevel=2)
    return input_program


def release_memory(input_program, skip_opt_set=None):
    warnings.warn(
        "release_memory is a no-op on TPU: intermediate buffers are freed "
        "by XLA's buffer assignment, not graph rewriting.", stacklevel=2)
    return input_program
