"""GEO-SGD transpiler compat surface (reference
transpiler/geo_sgd_transpiler.py, communicator.h:320 GeoSgdCommunicator).

GEO-SGD shipped parameter *deltas* every k steps between trainers and
pservers with no global barrier — an asynchronous consistency model built
for slow networks. ICI makes the premise obsolete and the semantics
unreproducible (there is no pserver to absorb the races), so this class
raises at construction with the supported migration: LocalSGD, which has
the same k-step communication cadence with well-defined averaging.
"""
from __future__ import annotations

__all__ = ["GeoSgdTranspiler"]


class GeoSgdTranspiler:
    def __init__(self, config=None):
        raise NotImplementedError(
            "GEO-SGD is intentionally unsupported on TPU (async pserver "
            "consistency has no ICI analogue). Migrate to LocalSGD: "
            "fleet.DistributedStrategy(use_local_sgd=True) gives the same "
            "k-step communication cadence with defined averaging "
            "semantics.")
