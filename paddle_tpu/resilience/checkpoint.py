"""Crash-safe checkpoint integrity: manifest, verification, atomic publish.

The failure model (docs/RESILIENCE.md): a process can die at ANY byte of a
checkpoint write (preemption, OOM-kill, power). The old
``save_checkpoint`` wrote ``ckpt.npz``/``meta.json`` straight into the live
directory, so a kill mid-write left a *torn* checkpoint that
``Trainer._load_latest`` happily loaded as garbage. The fix has two halves:

* **atomic publish** (``io.save_checkpoint``): write into a temp dir
  sibling, fsync every file and the directory, then ``rename`` into place —
  the live path either holds the complete old checkpoint or the complete
  new one, never a mixture.
* **verification** (this module): the final ``manifest.json`` carries a
  per-file sha256 + byte count, the param inventory, and the framework
  version. ``verify_checkpoint`` replays the hashes before a single byte is
  loaded; failures raise :class:`CheckpointCorruptError` with a stable
  PT6xx code (the checkpoint-integrity band of the PT* diagnostic space,
  docs/ANALYSIS.md) naming exactly what failed.

``load_latest_checkpoint`` is the recovery walk shared by
``contrib.Trainer._load_latest`` and ``tools/chaos_check.py``: serials are
tried newest -> oldest, torn/corrupt ones are skipped (counted on
``trainer_ckpt_fallback_total``), and training resumes from the newest
checkpoint that *proves* intact.
"""
from __future__ import annotations

import glob
import hashlib
import json
import logging
import os
import re
import shutil
from typing import Dict, List, Optional, Tuple

__all__ = ["CheckpointCorruptError", "CKPT_CODES", "FORMAT_VERSION",
           "MANIFEST_NAME", "finalize_manifest", "verify_checkpoint",
           "verify_sharding_section", "atomic_replace_dir", "fsync_dir",
           "iter_serials", "load_latest_checkpoint"]

logger = logging.getLogger("paddle_tpu.resilience")

# max SUPPORTED format. 2 = v1 + a "sharding" section (resilience.
# distributed): per-mesh-shard blob files, the mesh shape, and a per-param
# sharding spec. Plain (non-sharded) checkpoints are still STAMPED 1 —
# their layout is byte-identical to v1, so a framework rollback keeps
# restoring them instead of refusing with PT604.
FORMAT_VERSION = 2
MANIFEST_NAME = "manifest.json"

# PT6xx: checkpoint-integrity diagnostics (sibling band of the verifier's
# PT1xx-PT5xx in analysis/diagnostics.py; stable codes, documented in
# docs/RESILIENCE.md)
CKPT_CODES = {
    "PT600": "checkpoint manifest missing (torn write or pre-manifest dir)",
    "PT601": "checkpoint manifest unreadable or not a verification manifest",
    "PT602": "file listed in the manifest is missing from the checkpoint",
    "PT603": "file content does not match its manifest sha256/size "
             "(torn write or tampering)",
    "PT604": "checkpoint format version newer than this framework supports",
    # PT605-PT609: sharded (format_version 2) checkpoints
    "PT605": "shard-count mismatch: the manifest's num_shards, shard file "
             "list and per-param specs disagree",
    "PT606": "per-param sharding spec does not match the declared var "
             "(bad axis, non-divisible parts, or missing piece)",
    "PT607": "torn shard write: a shard file the manifest declares is "
             "absent or was never integrity-hashed (a distributed writer "
             "died mid-checkpoint)",
    "PT608": "shard reassembly mismatch: concatenated pieces do not "
             "produce the declared var shape/dtype",
    "PT609": "sharding section malformed (missing/ill-typed fields)",
}


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed integrity verification. Carries the PT6xx
    ``code``, the checkpoint ``dirname`` and a ``detail`` naming the exact
    file/field that failed."""

    def __init__(self, code: str, dirname: str, detail: str):
        self.code = code
        self.dirname = dirname
        self.detail = detail
        super().__init__(
            f"[{code}] checkpoint '{dirname}': {detail} — {CKPT_CODES[code]}")


def _sha256(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(chunk), b""):
            h.update(block)
    return h.hexdigest()


def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir(path: str) -> None:
    """Durably record directory entries (the rename itself needs this)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass   # some filesystems refuse dir fsync; rename is still atomic
    finally:
        os.close(fd)


def _rel_files(dirname: str) -> List[str]:
    out = []
    for root, _dirs, files in os.walk(dirname):
        for f in files:
            out.append(os.path.relpath(os.path.join(root, f), dirname))
    return sorted(out)


def finalize_manifest(dirname: str, params: Optional[Dict[str, dict]] = None,
                      extra: Optional[dict] = None) -> dict:
    """Upgrade the var-inventory ``manifest.json`` that ``_save_var_list``
    wrote into the integrity manifest: per-file sha256 + bytes over every
    OTHER file in the dir (the manifest cannot hash itself), param
    inventory, framework + format versions. Everything is fsynced; the
    caller then atomically publishes the directory."""
    manifest_path = os.path.join(dirname, MANIFEST_NAME)
    manifest: dict = {}
    if os.path.exists(manifest_path):
        with open(manifest_path) as f:
            manifest = json.load(f)
    files = {}
    for rel in _rel_files(dirname):
        if rel == MANIFEST_NAME:
            continue
        full = os.path.join(dirname, rel)
        _fsync_file(full)
        files[rel] = {"sha256": _sha256(full),
                      "bytes": os.path.getsize(full)}
    from .. import __version__

    manifest.update({
        # plain checkpoints stay format 1 (byte-identical layout to what
        # older builds wrote AND verify), so a framework rollback can
        # still restore them; only the sharding section requires 2
        "format_version": 2 if manifest.get("sharding") else 1,
        "framework_version": __version__,
        "files": files,
    })
    if params is not None:
        manifest["vars"] = params
    if extra:
        manifest.update(extra)
    with open(manifest_path, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    fsync_dir(dirname)
    return manifest


def verify_checkpoint(dirname: str) -> dict:
    """Replay the manifest before loading anything. Returns the manifest on
    success; raises :class:`CheckpointCorruptError` (PT600-PT604) naming
    the first failure otherwise."""
    manifest_path = os.path.join(dirname, MANIFEST_NAME)
    if not os.path.isdir(dirname) or not os.path.exists(manifest_path):
        raise CheckpointCorruptError("PT600", dirname,
                                     f"no {MANIFEST_NAME} present")
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
        if not isinstance(manifest, dict):
            raise ValueError("manifest is not an object")
    except (ValueError, OSError) as e:
        raise CheckpointCorruptError("PT601", dirname,
                                     f"cannot parse {MANIFEST_NAME}: {e}")
    files = manifest.get("files")
    if not isinstance(files, dict):
        raise CheckpointCorruptError(
            "PT601", dirname,
            f"{MANIFEST_NAME} has no 'files' integrity section (written by "
            f"a pre-resilience save_checkpoint?)")
    version = manifest.get("format_version", 0)
    if int(version) > FORMAT_VERSION:
        raise CheckpointCorruptError(
            "PT604", dirname,
            f"format_version {version} > supported {FORMAT_VERSION}")
    if manifest.get("sharding") is not None:
        # sharded structural checks first: a torn shard gets its specific
        # PT607 diagnosis rather than the generic missing-file PT602
        verify_sharding_section(dirname, manifest)
    for rel, want in sorted(files.items()):
        full = os.path.join(dirname, rel)
        if not os.path.exists(full):
            raise CheckpointCorruptError("PT602", dirname,
                                         f"'{rel}' listed but missing")
        size = os.path.getsize(full)
        if "bytes" in want and size != int(want["bytes"]):
            raise CheckpointCorruptError(
                "PT603", dirname,
                f"'{rel}' is {size} bytes, manifest says {want['bytes']}")
        if _sha256(full) != want.get("sha256"):
            raise CheckpointCorruptError(
                "PT603", dirname, f"'{rel}' sha256 mismatch")
    return manifest


def verify_sharding_section(dirname: str, manifest: dict) -> dict:
    """Structural checks for a format_version-2 sharded checkpoint, run
    BEFORE any blob is read: the sharding section is well-formed (PT609),
    its counts agree (PT605), and every declared shard file both exists on
    disk and is covered by the integrity section (PT607 — the torn
    distributed write: one writer died after the manifest named its shard
    but before the shard file landed or was hashed; a raw KeyError deep in
    the loader is exactly what the recovery walk must never see).
    Content-level checks (PT606/PT608) happen at load, where the pieces
    are actually read."""
    sh = manifest.get("sharding")
    if not isinstance(sh, dict):
        raise CheckpointCorruptError(
            "PT609", dirname, "'sharding' section is not an object")
    shard_files = sh.get("shard_files")
    specs = sh.get("specs")
    n = sh.get("num_shards")
    if not isinstance(shard_files, list) or not isinstance(specs, dict) \
            or not isinstance(n, int) or not isinstance(sh.get("mesh"),
                                                        dict):
        raise CheckpointCorruptError(
            "PT609", dirname,
            "sharding section lacks num_shards/mesh/shard_files/specs")
    if len(shard_files) != n:
        raise CheckpointCorruptError(
            "PT605", dirname,
            f"num_shards={n} but {len(shard_files)} shard files declared")
    for name, spec in sorted(specs.items()):
        if not isinstance(spec, dict) or "dim" not in spec \
                or "parts" not in spec:
            raise CheckpointCorruptError(
                "PT609", dirname, f"spec for '{name}' lacks dim/parts")
        if int(spec["parts"]) != n:
            raise CheckpointCorruptError(
                "PT605", dirname,
                f"'{name}' declares parts={spec['parts']} but the "
                f"checkpoint holds {n} shards")
    files = manifest.get("files") or {}
    for rel in shard_files:
        if not os.path.exists(os.path.join(dirname, str(rel))):
            raise CheckpointCorruptError(
                "PT607", dirname, f"shard file '{rel}' declared but absent")
        if rel not in files:
            raise CheckpointCorruptError(
                "PT607", dirname,
                f"shard file '{rel}' present but never integrity-hashed "
                f"(its writer died before finalize)")
    return sh


def atomic_replace_dir(tmp: str, dst: str) -> None:
    """Publish ``tmp`` at ``dst``. The fresh-path case (``dst`` absent or
    an empty placeholder — every Trainer serial, since serials are never
    re-used) is a single atomic rename. Overwriting a NON-empty ``dst``
    (direct re-save to one path) needs two renames because POSIX has no
    portable atomic directory swap: old -> ``<dst>.replaced.<pid>``, tmp
    -> ``dst``. A SIGKILL exactly between them leaves the old checkpoint
    at the ``.replaced`` name (recovery does not scan it — prefer
    serial-per-save layouts when overwrite-crash matters); an exception
    restores it. Stale ``.replaced`` litter from such kills is cleaned up
    on the next publish."""
    parent = os.path.dirname(os.path.abspath(dst)) or "."
    for stale in glob.glob(f"{dst}.replaced.*"):
        shutil.rmtree(stale, ignore_errors=True)
    if os.path.isdir(dst) and os.listdir(dst):
        aside = f"{dst}.replaced.{os.getpid()}"
        os.rename(dst, aside)
        try:
            os.rename(tmp, dst)
        except BaseException:
            os.rename(aside, dst)   # put the old checkpoint back
            raise
        fsync_dir(parent)
        shutil.rmtree(aside, ignore_errors=True)
    else:
        if os.path.isdir(dst):
            os.rmdir(dst)   # empty placeholder (e.g. pytest tmp_path)
        os.rename(tmp, dst)
        fsync_dir(parent)


_SERIAL_RE = re.compile(r"^checkpoint_(\d+)$")


def iter_serials(checkpoint_dir: str) -> List[Tuple[int, str]]:
    """(serial, path) for every ``checkpoint_<int>`` DIRECTORY, ascending.
    Files, temp dirs (``.checkpoint_*.tmp.*``) and non-numeric entries are
    ignored — a garbage-filled checkpoint dir must never crash recovery."""
    if not os.path.isdir(checkpoint_dir):
        return []
    out = []
    for name in os.listdir(checkpoint_dir):
        m = _SERIAL_RE.match(name)
        path = os.path.join(checkpoint_dir, name)
        if m and os.path.isdir(path):
            out.append((int(m.group(1)), path))
    return sorted(out)


def load_latest_checkpoint(executor, checkpoint_dir: str, main_program=None,
                           scope=None, allow_legacy: bool = True):
    """Walk serials newest -> oldest, skipping any checkpoint that fails
    verification or loading. Returns ``(meta, serial, skipped)`` where
    ``skipped`` is a list of ``{serial, path, code, error}`` dicts for the
    checkpoints passed over; ``(None, None, skipped)`` when nothing loads.
    Each skip increments ``trainer_ckpt_fallback_total``.

    When NO serial verifies and ``allow_legacy`` is set, a second pass
    retries (newest -> oldest) the serials whose only defect was a missing
    integrity manifest (PT600/PT601 — what a pre-resilience writer
    produced for every checkpoint) with ``verify=False``: resuming from an
    unverified-but-loadable legacy checkpoint beats silently restarting at
    step 0 and letting rotation delete it. Genuinely torn blobs still fail
    to load (npz CRC) and are skipped. Verified checkpoints ALWAYS win,
    even over a newer legacy-shaped one — that newer one is
    indistinguishable from a torn write."""
    from .. import io as io_mod
    from .. import monitor as _monitor

    def _skip(serial, path, code, err, why):
        skipped.append({"serial": serial, "path": path,
                        "code": str(code), "error": str(err)})
        if _monitor.enabled():
            _monitor.counter(
                "trainer_ckpt_fallback_total",
                "checkpoints skipped during recovery (torn/corrupt/"
                "unloadable)").labels(code=str(code)).inc()
        logger.warning(
            "resilience: checkpoint_%d %s (%s), falling back: %s",
            serial, why, code, err)

    skipped: List[dict] = []
    serials = iter_serials(checkpoint_dir)
    for serial, path in reversed(serials):
        try:
            meta = io_mod.load_checkpoint(executor, path,
                                          main_program=main_program,
                                          scope=scope)
        except Exception as e:
            _skip(serial, path, getattr(e, "code", type(e).__name__), e,
                  "failed verification/load")
            continue
        return meta, serial, skipped
    if allow_legacy:
        legacy = {s["serial"] for s in skipped
                  if s["code"] in ("PT600", "PT601")}
        for serial, path in reversed(serials):
            if serial not in legacy:
                continue
            try:
                meta = io_mod.load_checkpoint(executor, path,
                                              main_program=main_program,
                                              scope=scope, verify=False)
            except Exception as e:
                _skip(serial, path, "legacy_load_failed", e,
                      "has no integrity manifest and did not load")
                continue
            logger.warning(
                "resilience: no serial in '%s' passed verification; "
                "resumed from UNVERIFIED legacy checkpoint_%d (written by "
                "a pre-resilience build?). Save once to upgrade it to the "
                "manifest format.", checkpoint_dir, serial)
            return meta, serial, skipped
    return None, None, skipped
