"""Distributed resilience: sharded elastic checkpoints, cross-replica
divergence detection, and the step watchdog.

PR 4 made restart-after-failure a first-class path for the single-host
executor; this module extends it into the parallel layer (ROADMAP item 3 —
ZeRO-sharded optimizer state per arXiv 2004.13336 — is only safe once a
dp-sharded Adam moment can be checkpointed WITHOUT a full gather and a
host crash cannot lose the run). Three pillars:

* **Sharded elastic checkpoints** (``io.save_checkpoint(..., mesh=...)``,
  manifest ``format_version`` 2): every mesh shard lands as its own
  fsynced blob under the serial, the manifest records per-shard sha256 +
  the mesh shape + a per-param sharding spec, and publish stays the PR 4
  atomic temp-dir + rename. Restore reassembles the full value
  (= the full-gather path, bit for bit), so a run saved on dp=8 resumes
  on dp=4 or on one host — the next dispatch re-shards onto whatever mesh
  exists. PT605–PT609 diagnose shard-count/spec mismatches and torn shard
  writes (``resilience.checkpoint.CKPT_CODES``).
* **Cross-replica divergence detection** (``FLAGS_replica_check_interval``):
  every N-th data-parallel step each device reduces its LOCAL copy of the
  replicated params/optimizer state to a pair of uint32 checksums inside a
  jitted ``shard_map`` — no host gather of tensors, only ``2*V`` words —
  and replicas that must hold identical bytes are compared host-side.
  Disagreement raises :class:`ReplicaDivergenceError` naming the first
  diverged param, or (``FLAGS_replica_divergence_policy=restore``) rolls
  back to the last verified checkpoint via the PR 4 recovery walk.
* **Step watchdog** (``FLAGS_step_timeout_s``): a daemon thread armed
  around compile/step/collective sections. On expiry it dumps every
  thread's stack, the active program serial and the last recompile
  diagnosis, then interrupts the hung section so it raises
  :class:`WatchdogTimeout` instead of hanging CI forever; a section still
  stuck one extra timeout later (native-code hang) hard-exits 124 with
  the diagnosis already on stderr (``FLAGS_watchdog_hard_exit``).

Deterministic testing: ``faults.py`` grew the ``shard_write`` site (before
each per-shard blob) and the ``hang`` site/action (an interruptible stall
inside the armed dispatch sections). End-to-end proof:
``tools/chaos_check.py --multichip``.
"""
from __future__ import annotations

import contextlib
import dataclasses
import itertools
import json
import logging
import os
import sys
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..monitor.lockwitness import make_lock
from .faults import fault_point

__all__ = ["ReplicaDivergenceError", "WatchdogTimeout", "watchdog_section",
           "replica_divergence_check", "handle_divergence",
           "set_divergence_recovery", "save_sharded_vars",
           "load_sharded_vars", "shard_axis_of", "mesh_axes"]

logger = logging.getLogger("paddle_tpu.resilience")

COMMON_FILE = "common.npz"


# ---------------------------------------------------------------------------
# pillar 1: sharded elastic checkpoints (manifest format_version 2)
# ---------------------------------------------------------------------------

def mesh_axes(mesh) -> Dict[str, int]:
    """Normalise a mesh argument (jax Mesh | {'dp': 8} | 8) to axis sizes."""
    if mesh is None:
        return {}
    if isinstance(mesh, int):
        return {"dp": int(mesh)}
    if isinstance(mesh, dict):
        return {str(k): int(v) for k, v in mesh.items()}
    shape = getattr(mesh, "shape", None)
    if shape is not None:
        return {str(k): int(v) for k, v in dict(shape).items()}
    raise TypeError(f"save_checkpoint: cannot read a mesh shape from "
                    f"{mesh!r} (want a jax Mesh, a dict of axis sizes, or "
                    f"an int shard count)")


def shard_axis_of(value, axis: str) -> Optional[int]:
    """The array dim ``value`` is sharded on over mesh axis ``axis``
    (from its live NamedSharding), or None when replicated/off-mesh."""
    sharding = getattr(value, "sharding", None)
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return None
    for dim, entry in enumerate(spec):
        if entry is None:
            continue
        names = entry if isinstance(entry, (tuple, list)) else (entry,)
        if axis in names:
            return dim
    return None


def _shard_file_name(k: int, n: int) -> str:
    return f"shard_{k:05d}-of-{n:05d}.npz"


def save_sharded_vars(dirname: str, vars_: Sequence, scope, mesh) -> dict:
    """Write ``vars_`` (program Variables with scope values) as a sharded
    checkpoint into ``dirname`` (the temp dir of ``io.save_checkpoint``'s
    atomic publish). Vars whose live jax sharding splits a dim over the
    mesh's dp axis are written as one slice per shard file
    (``shard_write`` fault site fires before each); everything replicated
    goes to ``common.npz``. Returns the manifest skeleton
    (vars inventory + the ``sharding`` section) it wrote — fsync and the
    per-file sha256 happen in ``resilience.checkpoint.finalize_manifest``.
    """
    from .. import monitor as _monitor

    axes = mesh_axes(mesh)
    axis = "dp" if "dp" in axes else (next(iter(axes)) if axes else "dp")
    n = max(1, int(axes.get(axis, 1)))
    inventory: Dict[str, dict] = {}
    specs: Dict[str, dict] = {}
    common: Dict[str, np.ndarray] = {}
    shards: List[Dict[str, Any]] = [dict() for _ in range(n)]
    key_owner: Dict[str, str] = {}
    for v in vars_:
        val = scope.find_var(v.name)
        if val is None:
            raise RuntimeError(
                f"save: variable '{v.name}' has no value in scope")
        key = v.name.replace("/", "__")
        if key_owner.setdefault(key, v.name) != v.name:
            # the '/'->'__' mangling is not injective; refusing loudly
            # beats one var's bytes silently overwriting another's
            raise RuntimeError(
                f"save: var names '{key_owner[key]}' and '{v.name}' both "
                f"serialize to blob key '{key}' — rename one")
        dim = shard_axis_of(val, axis)
        shape = tuple(getattr(val, "shape", np.shape(val)))
        inventory[v.name] = {"shape": list(shape),
                             "dtype": str(getattr(val, "dtype",
                                                  np.asarray(val).dtype))}
        if n > 1 and dim is not None and dim < len(shape) \
                and shape[dim] % n != 0:
            # uneven live sharding cannot round-trip through equal-split
            # shard files; the replicated fallback below re-gathers the
            # whole value — loud, because that is the memory blow-up the
            # sharded format exists to avoid
            logger.warning(
                "sharded checkpoint: '%s' is sharded on dim %d but "
                "%d %% %d != 0 — falling back to a full-gather "
                "replicated write for this var", v.name, dim,
                shape[dim], n)
        if n > 1 and dim is not None and dim < len(shape) \
                and shape[dim] % n == 0:
            specs[v.name] = {"dim": int(dim), "parts": n}
            # slice-wise, never a full host gather: each piece is pulled
            # on its own so the host never holds more than one slice of a
            # dp-sharded value (the whole point of the sharded format)
            sz = shape[dim] // n
            for k in range(n):
                idx = (slice(None),) * dim + (slice(k * sz, (k + 1) * sz),)
                shards[k][key] = (val, idx)
        else:
            common[key] = np.asarray(val)
    with open(os.path.join(dirname, COMMON_FILE), "wb") as f:
        np.savez(f, **common)
    shard_files = [_shard_file_name(k, n) for k in range(n)]
    for k, fname in enumerate(shard_files):
        # one host of a distributed writer dying here is the failure the
        # format must survive: the manifest/publish never happens, the
        # serial stays unpublished, recovery falls back (chaos multichip)
        fault_point("shard_write")
        pieces = {key: np.asarray(val[idx])
                  for key, (val, idx) in shards[k].items()}
        with open(os.path.join(dirname, fname), "wb") as f:
            np.savez(f, **pieces)
    if _monitor.enabled():
        _monitor.counter(
            "resilience_shards_written_total",
            "per-shard blob files written by sharded checkpoints").inc(n)
    manifest = {"vars": inventory, "filename": None,
                "sharding": {"mesh": axes, "axis": axis, "num_shards": n,
                             "common_file": COMMON_FILE,
                             "shard_files": shard_files, "specs": specs}}
    with open(os.path.join(dirname, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    return manifest


def _load_npz(path: str) -> Dict[str, np.ndarray]:
    with np.load(path, allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


def load_sharded_vars(dirname: str, manifest: dict, vars_: Sequence,
                      scope) -> None:
    """Reassemble a format_version-2 sharded checkpoint into ``scope``.

    This IS the full-gather restore: every sharded var's pieces are
    concatenated back to the full value, so restoring on fewer devices (or
    one host) is bit-identical to a gather-then-save checkpoint — the next
    dispatch re-shards onto whatever mesh the resumed run has (elastic
    dp=8 -> dp=4 -> 1). Two-phase like ``io._load_var_list``: everything
    is read and validated before the first ``set_var`` so a failed load
    never half-mutates the scope. Content mismatches raise
    ``CheckpointCorruptError`` PT606/PT608."""
    import jax.numpy as jnp

    from .checkpoint import (CheckpointCorruptError,
                             verify_sharding_section)
    from .. import monitor as _monitor

    # structural checks again here: the verify=False path (and any direct
    # caller) must still get PT605/PT607/PT609 instead of a raw KeyError
    sh = verify_sharding_section(dirname, manifest)
    n = int(sh["num_shards"])
    specs = sh["specs"]
    inventory = manifest.get("vars") or {}
    common = _load_npz(os.path.join(dirname, sh.get("common_file",
                                                    COMMON_FILE)))
    shard_blobs = [_load_npz(os.path.join(dirname, f))
                   for f in sh["shard_files"]]
    staged: List[Tuple[str, np.ndarray]] = []
    for v in vars_:
        key = v.name.replace("/", "__")
        spec = specs.get(v.name)
        want = inventory.get(v.name)
        if spec is None:
            if key not in common:
                raise RuntimeError(
                    f"load: '{v.name}' missing from sharded checkpoint "
                    f"'{dirname}'")
            arr = common[key]
        else:
            dim = int(spec["dim"])
            if want is not None and dim >= len(want.get("shape", ())):
                raise CheckpointCorruptError(
                    "PT606", dirname,
                    f"'{v.name}' spec shards dim {dim} but the var is "
                    f"{len(want['shape'])}-d")
            pieces = []
            for k, blob in enumerate(shard_blobs):
                if key not in blob:
                    raise CheckpointCorruptError(
                        "PT606", dirname,
                        f"piece of '{v.name}' missing from shard {k}/{n}")
                pieces.append(blob[key])
            try:
                arr = np.concatenate(pieces, axis=dim)
            except Exception as e:
                raise CheckpointCorruptError(
                    "PT608", dirname,
                    f"'{v.name}' pieces do not concatenate on dim {dim}: "
                    f"{e}")
        if want is not None and list(arr.shape) != list(want["shape"]):
            raise CheckpointCorruptError(
                "PT608", dirname,
                f"'{v.name}' reassembled to {list(arr.shape)}, manifest "
                f"says {want['shape']}")
        if v.shape is not None and tuple(arr.shape) != tuple(v.shape) \
                and -1 not in (v.shape or ()):
            raise RuntimeError(
                f"load: shape mismatch for '{v.name}': checkpoint "
                f"{arr.shape} vs program {v.shape}")
        staged.append((v.name, arr))
    for name, arr in staged:
        scope.set_var(name, jnp.asarray(arr))
    if _monitor.enabled():
        _monitor.counter(
            "resilience_sharded_restores_total",
            "sharded (format_version 2) checkpoints reassembled into a "
            "scope").inc()


# ---------------------------------------------------------------------------
# pillar 2: cross-replica divergence detection
# ---------------------------------------------------------------------------

class ReplicaDivergenceError(RuntimeError):
    """Replicated state disagrees across data-parallel replicas. Carries
    ``param`` (the first diverged name) and ``diverged`` (all of them).
    Never retried (``transient = False``): diverged replicas are a
    determinism bug or corrupted memory, not infrastructure noise."""

    transient = False

    def __init__(self, diverged: Sequence[str], axis: str = "dp"):
        self.diverged = list(diverged)
        self.param = self.diverged[0] if self.diverged else "<unknown>"
        super().__init__(
            f"replica divergence across the '{axis}' axis: param "
            f"'{self.param}' holds different bytes on different replicas "
            f"({len(self.diverged)} diverged var(s): "
            f"{', '.join(self.diverged[:5])}"
            f"{', …' if len(self.diverged) > 5 else ''}). Replicated "
            f"state must be bit-identical; this is nondeterminism or "
            f"memory corruption, not noise — restore from the last "
            f"verified checkpoint (FLAGS_replica_divergence_policy="
            f"restore) or debug the step.")


def _bits_u32(x):
    """LOSSLESS uint32 view of an array's bit patterns, branched by item
    width so no dtype can alias two different bit patterns to one
    checksum word (wraparound arithmetic downstream is fine: the checksum
    only needs replica-equality)."""
    import jax.numpy as jnp
    import numpy as _np
    from jax import lax

    dt = _np.dtype(x.dtype)
    if dt.itemsize == 8:      # float64/int64/uint64 under jax_enable_x64
        w = lax.bitcast_convert_type(x, jnp.uint64).ravel()
        return jnp.concatenate([(w >> 32).astype(jnp.uint32),
                                (w & jnp.uint64(0xFFFFFFFF)).astype(
                                    jnp.uint32)])
    if dt.itemsize == 4:
        u = lax.bitcast_convert_type(x, jnp.uint32) if dt.kind == "f" \
            else x.astype(jnp.uint32)      # int32<->uint32 is bijective
    elif dt.itemsize == 2:    # float16/bfloat16/int16/uint16
        u = (lax.bitcast_convert_type(x, jnp.uint16)
             if dt.kind == "f" or dt.name == "bfloat16"
             else x).astype(jnp.uint32)
    else:                     # int8/uint8/bool — one word per element
        u = x.astype(jnp.uint32)
    return u.ravel()


_checker_cache: Dict[tuple, Any] = {}


def _pspec_of(v):
    from jax.sharding import PartitionSpec as P

    spec = getattr(getattr(v, "sharding", None), "spec", None)
    return spec if spec is not None else P()


def _get_shard_map():
    try:
        from jax import shard_map
    except ImportError:     # older jax
        from jax.experimental.shard_map import shard_map
    return shard_map


def replica_divergence_check(mesh, values: Dict[str, Any],
                             axis: Optional[str] = None) -> List[str]:
    """Names in ``values`` whose device copies disagree where the sharding
    says they must agree.

    Each device reduces its LOCAL block to two uint32 checksums (bit-
    pattern sum + position-weighted sum) inside one jitted ``shard_map``
    over the whole mesh — the only host transfer is ``2`` words per var
    per device. Host-side, two devices are required to match iff they
    share coordinates on every axis the var is actually sharded over —
    for state replicated over ``dp`` (params, and Adam moments outside
    ZeRO) that compares physical replica bytes across the dp axis.
    ``axis`` restricts the sweep to ONE replication axis (vars sharded
    over it are skipped); the default ``None`` compares across every
    axis a value is replicated over, which is strictly stronger."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    if not values:
        return []
    items = sorted(values.items())
    names = [n for n, _ in items]
    vals = [v for _, v in items]
    metas = tuple((tuple(v.shape), str(v.dtype), _pspec_of(v))
                  for v in vals)
    axis_names = tuple(mesh.axis_names)
    key = (mesh, metas, axis)
    fn = _checker_cache.get(key)
    if fn is None:
        n_axes = len(axis_names)
        in_specs = tuple(m[2] for m in metas)

        def local(*xs):
            sums = []
            for x in xs:
                u = _bits_u32(x)
                if u.size:
                    s1 = jnp.sum(u, dtype=jnp.uint32)
                    w = (jnp.arange(u.size, dtype=jnp.uint32) << 1) \
                        | jnp.uint32(1)
                    s2 = jnp.sum(u * w, dtype=jnp.uint32)
                else:
                    s1 = s2 = jnp.uint32(0)
                sums.append(jnp.stack([s1, s2]))
            out = jnp.stack(sums)                      # [V, 2] per device
            return out.reshape((1,) * n_axes + out.shape)

        fn = jax.jit(_get_shard_map()(
            local, mesh=mesh, in_specs=in_specs,
            out_specs=P(*axis_names, None, None)))
        # bounded: evict oldest so dead meshes / compiled checkers from
        # long sessions (notebooks, test suites) cannot accumulate forever
        while len(_checker_cache) >= 8:
            _checker_cache.pop(next(iter(_checker_cache)))
        _checker_cache[key] = fn
    sums = np.asarray(fn(*vals))     # [*mesh_shape, V, 2] — tiny
    mesh_shape = sums.shape[:len(axis_names)]
    diverged = []
    for i, (name, meta) in enumerate(zip(names, metas)):
        spec = meta[2]
        sharded_axes = set()
        for entry in spec:
            if entry is None:
                continue
            for a in (entry if isinstance(entry, (tuple, list))
                      else (entry,)):
                if a:
                    sharded_axes.add(a)
        # collapse the axes this var is SHARDED over (each coordinate is a
        # different block — nothing to compare); whatever axes remain are
        # replication axes, along which every checksum must be identical
        # group-defining dims: sharded axes always (each coordinate is a
        # different block), plus — when the sweep is restricted to one
        # axis — every OTHER axis, so only ``axis`` replicas compare
        keep = {d for d, a in enumerate(axis_names)
                if a in sharded_axes or (axis is not None and a != axis)}
        per_var = sums[..., i, :]
        # iterate shard groups explicitly (mesh ranks are few): one group
        # per coordinate along the sharded axes, slicing all replica axes
        ranges = [range(mesh_shape[d]) if d in keep else (slice(None),)
                  for d in range(len(axis_names))]
        ok = True
        for coords in itertools.product(*ranges):
            flat = per_var[tuple(coords)].reshape(-1, 2)
            if flat.shape[0] > 1 and not (flat == flat[0]).all():
                ok = False
                break
        if not ok:
            diverged.append(name)
    return diverged


# restore policy wiring: contrib.Trainer registers its recovery walk here
# (the PR 4 newest->oldest verified-checkpoint reload); anything returning
# truthy means "state restored, keep training"
_recovery: Optional[Callable[[], Any]] = None


def set_divergence_recovery(fn: Optional[Callable[[], Any]]) -> None:
    global _recovery
    _recovery = fn


def block_until_ready_concrete(tree) -> None:
    """``jax.block_until_ready`` that no-ops for traced values (a jit
    caller's tracers) but lets REAL async runtime failures propagate —
    a bare except here would detach a failed dispatch from its call
    site. Used by the eager collective wrappers (parallel.pipeline /
    parallel.ring_attention) while watchdog-armed."""
    import jax

    try:
        from jax.core import Tracer
    except Exception:       # jax moved it; fall back to no filtering
        Tracer = ()
    leaves = jax.tree_util.tree_leaves(tree)
    if any(isinstance(leaf, Tracer) for leaf in leaves):
        return
    jax.block_until_ready(tree)


def handle_divergence(diverged: Sequence[str], path: str = "parallel",
                      axis: str = "dp") -> None:
    """Apply ``FLAGS_replica_divergence_policy`` to a non-empty diverged
    set: ``raise`` trips :class:`ReplicaDivergenceError`; ``restore``
    rolls the scope back to the last verified checkpoint through the
    registered recovery walk and keeps training (escalating to raise when
    nothing restorable exists)."""
    from .. import monitor as _monitor
    from ..flags import flag

    if _monitor.enabled():
        _monitor.counter(
            "resilience_divergence_detected_total",
            "cross-replica divergence detections").labels(path=path).inc()
    from .. import trace as _trace

    _trace.record_incident(
        "replica_divergence",
        detail=f"path {path}, axis {axis}: "
               f"{', '.join(list(diverged)[:5])}")
    policy = str(flag("replica_divergence_policy")).strip().lower()
    if policy not in ("raise", "restore"):
        raise ValueError(
            f"FLAGS_replica_divergence_policy={policy!r} — expected "
            f"raise or restore")
    err = ReplicaDivergenceError(diverged, axis=axis)
    if policy == "restore" and _recovery is not None:
        restored = False
        try:
            restored = bool(_recovery())
        except Exception:
            logger.exception("divergence recovery walk itself failed")
        if restored:
            if _monitor.enabled():
                _monitor.counter(
                    "resilience_divergence_restores_total",
                    "divergences resolved by rolling back to the last "
                    "verified checkpoint").inc()
            logger.warning(
                "replica divergence on '%s' (+%d more): restored the last "
                "verified checkpoint, training continues "
                "(FLAGS_replica_divergence_policy=restore)", err.param,
                max(0, len(err.diverged) - 1))
            return
        logger.error("replica divergence: restore policy had nothing to "
                     "restore — escalating to raise")
    raise err


# ---------------------------------------------------------------------------
# pillar 3: step watchdog
# ---------------------------------------------------------------------------

class _WatchdogInterrupt(BaseException):
    """Async exception the watchdog raises INSIDE a hung non-main thread
    (``PyThreadState_SetAsyncExc``) — the cross-thread analogue of the
    ``interrupt_main``/KeyboardInterrupt path the main thread gets. A
    ``BaseException`` so broad ``except Exception`` handlers inside the
    hung section cannot swallow it; ``watchdog_section`` converts it to
    :class:`WatchdogTimeout` before callers see it. Serving's dispatch
    thread is the reason this exists: a slow-batch hang there must die
    diagnosed and typed, not ride straight to the hard-exit escalation."""


def _interrupt_thread(thread_id: int) -> bool:
    """Raise :class:`_WatchdogInterrupt` asynchronously in ``thread_id``.
    Delivery happens at the thread's next bytecode boundary — enough for
    Python-level stalls (the ``hang`` fault action sleeps in 20 ms slices);
    a hang inside native code stays for the hard-exit escalation."""
    import ctypes

    res = ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(thread_id), ctypes.py_object(_WatchdogInterrupt))
    if res > 1:
        # "affected more than one thread" — undo per CPython docs
        ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(thread_id), None)
        return False
    return res == 1


class WatchdogTimeout(RuntimeError):
    """An armed compile/step/collective section exceeded
    ``FLAGS_step_timeout_s``. The full diagnosis (all thread stacks, the
    active program serial, the last recompile diagnosis) was already
    dumped to the resilience logger and stderr when the deadline fired.
    ``transient = False``: a hang is never retried."""

    transient = False

    def __init__(self, section: str, seconds: float, detail: str = ""):
        self.section = section
        self.seconds = seconds
        self.detail = detail
        super().__init__(
            f"watchdog: section '{section}' exceeded "
            f"FLAGS_step_timeout_s={seconds:g}s"
            f"{' (' + detail + ')' if detail else ''} — thread stacks and "
            f"the last recompile diagnosis were dumped at expiry")


@dataclasses.dataclass
class _Section:
    token: int
    section: str
    detail: str
    timeout: float
    deadline: float
    thread_id: int
    expired: bool = False
    hard_deadline: Optional[float] = None


_wd_lock = make_lock("resilience.distributed._wd_lock")
_wd_armed: Dict[int, _Section] = {}
_wd_tokens = itertools.count(1)
_wd_thread: Optional[threading.Thread] = None


def _dump_section(s: _Section) -> str:
    lines = [
        f"watchdog: section '{s.section}' exceeded {s.timeout:g}s "
        f"({s.detail or 'no detail'})",
    ]
    try:
        from .. import monitor as _monitor

        evs = _monitor.get_tracker().events(recompiles_only=False)
        if evs:
            e = evs[-1]
            lines.append(
                f"  last compile: path={e.path} program_serial="
                f"{e.program_serial} recompile={e.recompile} "
                f"changed={list(e.changed)} at {e.build_site}")
        else:
            lines.append("  last compile: <none recorded>")
    except Exception:
        lines.append("  last compile: <monitor unavailable>")
    frames = sys._current_frames()
    by_id = {t.ident: t for t in threading.enumerate()}
    for tid, frame in frames.items():
        t = by_id.get(tid)
        name = t.name if t else "?"
        mark = " [hung section]" if tid == s.thread_id else ""
        lines.append(f"-- thread '{name}' ({tid}){mark} --")
        lines.append("".join(traceback.format_stack(frame)).rstrip())
    # flight recorder: the hang's diagnosis ships with the last N trace
    # spans (the hung request/step's chain among them) — incidents() /
    # the ci_trace_report artifact carry the structured form
    try:
        from .. import trace as _trace

        incident = _trace.record_incident(
            "watchdog_timeout",
            detail=f"section '{s.section}' ({s.detail or 'no detail'}) "
                   f"exceeded {s.timeout:g}s")
        if incident["recent_spans"]:
            lines.append(f"-- flight recorder: last "
                         f"{len(incident['recent_spans'])} span(s) --")
            for d in incident["recent_spans"][-12:]:
                lines.append(
                    f"  {d['name']} trace={d['trace_id']} "
                    f"status={d['status']} "
                    f"dur={d['duration_s'] if d['duration_s'] is not None else '?'} "
                    f"attrs={d['attrs']}")
        elif not incident["flight_recorder_enabled"]:
            lines.append("-- flight recorder: disabled (FLAGS_trace / "
                         "FLAGS_flight_recorder_size) — no span context --")
    except Exception:
        logger.exception("flight-recorder dump failed (diagnosis "
                         "continues without span context)")
    text = "\n".join(lines)
    logger.error("%s", text)
    print(text, file=sys.stderr, flush=True)
    return text


def _wd_loop() -> None:
    import _thread

    while True:
        now = time.monotonic()
        with _wd_lock:
            sections = list(_wd_armed.values())
        for s in sections:
            if not s.expired and now >= s.deadline:
                s.expired = True
                s.hard_deadline = now + max(s.timeout, 1.0)
                try:
                    _dump_section(s)
                except Exception:   # the dump must never kill the dog
                    logger.exception("watchdog diagnosis dump failed")
                try:
                    from .. import monitor as _monitor

                    _monitor.record_watchdog_timeout(s.section)
                except Exception:
                    pass
                with _wd_lock:
                    still = s.token in _wd_armed
                if still:
                    if s.thread_id == threading.main_thread().ident:
                        _thread.interrupt_main()
                    else:
                        # non-main thread (e.g. the serving dispatcher):
                        # deliver the typed interrupt directly into it
                        _interrupt_thread(s.thread_id)
            elif s.expired and s.hard_deadline is not None \
                    and now >= s.hard_deadline:
                with _wd_lock:
                    still = s.token in _wd_armed
                if not still:
                    continue   # disarmed between snapshot and deadline
                from ..flags import flag

                if flag("watchdog_hard_exit"):
                    print(f"watchdog: section '{s.section}' still hung "
                          f"{max(s.timeout, 1.0):g}s after the diagnosis "
                          f"dump (uninterruptible native code?) — "
                          f"os._exit(124)", file=sys.stderr, flush=True)
                    os._exit(124)
                s.hard_deadline = None   # dump once, then leave it be
        time.sleep(0.05 if sections else 0.2)


def _ensure_wd_thread() -> None:
    global _wd_thread
    if _wd_thread is None or not _wd_thread.is_alive():
        _wd_thread = threading.Thread(target=_wd_loop,
                                      name="paddle_tpu-watchdog",
                                      daemon=True)
        _wd_thread.start()


@contextlib.contextmanager
def watchdog_section(section: str, detail: str = "", timeout=None,
                     program=None):
    """Arm the watchdog around a compile/step/collective region.

    ``timeout`` defaults to ``FLAGS_step_timeout_s``; 0/None disarms (the
    default — the context manager is then a no-op). When the deadline
    fires the watchdog dumps the diagnosis and interrupts the hung
    thread — ``interrupt_main`` for the main thread, an async
    :class:`_WatchdogInterrupt` (``PyThreadState_SetAsyncExc``) for any
    other thread, e.g. the serving dispatcher. Either pending interrupt
    is converted to :class:`WatchdogTimeout` here, so callers see one
    typed, documented failure instead of a hang; a section stuck in
    uninterruptible native code still escalates to the hard exit."""
    if timeout is None:
        from ..flags import flag

        timeout = float(flag("step_timeout_s"))
    if not timeout or timeout <= 0:
        yield None
        return
    if program is not None and not detail:
        detail = f"program serial {getattr(program, '_serial', '?')}"
    s = _Section(token=next(_wd_tokens), section=section, detail=detail,
                 timeout=float(timeout),
                 deadline=time.monotonic() + float(timeout),
                 thread_id=threading.get_ident())
    from .. import monitor as _monitor

    if _monitor.enabled():
        _monitor.counter(
            "watchdog_sections_armed_total",
            "watchdog-armed executor sections").labels(
            section=section).inc()
    with _wd_lock:
        _wd_armed[s.token] = s
    _ensure_wd_thread()
    converted = False
    try:
        yield s
    except KeyboardInterrupt:
        if s.expired:
            converted = True
            raise WatchdogTimeout(section, s.timeout, s.detail) from None
        raise
    except _WatchdogInterrupt:
        # the cross-thread delivery path (non-main sections): always ours
        # — nothing else raises this type
        converted = True
        raise WatchdogTimeout(section, s.timeout, s.detail) from None
    finally:
        with _wd_lock:
            _wd_armed.pop(s.token, None)
        if s.expired and not converted:
            # the section finished in the race window between expiry and
            # interrupt delivery: absorb the in-flight interrupt here (it
            # was aimed at this section) instead of letting it detonate in
            # whatever innocent code runs next. The watchdog polls every
            # 0.05s, so a few short sleeps cover the window.
            try:
                for _ in range(4):
                    time.sleep(0.02)
            except (KeyboardInterrupt, _WatchdogInterrupt):
                logger.warning(
                    "watchdog: absorbed a late interrupt for section "
                    "'%s' that completed at its deadline", section)
