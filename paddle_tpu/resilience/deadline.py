"""One deadline implementation for every wall-clock budget in the stack.

Before this module there were two half-deadlines: the retry loop's
per-site ``timeout`` arithmetic (``resilience.retry``) and the ad-hoc
"how long has this request waited" checks a serving layer would grow.
Both are the same object — a monotonic start time plus a budget — so both
now consume :class:`Deadline`:

* ``call_with_retry`` builds one per failing site (the budget is measured
  from the first failure, preserving the zero-cost happy path) and asks
  ``deadline.expired`` before each retry.
* ``serving.ServingEngine`` attaches one to every admitted request; the
  dispatcher sweeps ``expired`` queues entries and ``check()`` raises the
  typed terminal outcome.

:class:`DeadlineExceeded` subclasses ``TimeoutError`` (callers that catch
the stdlib type keep working) but pins ``transient = False`` so
``retry.is_transient`` never retries an expired budget — retrying a
deadline only makes it later.
"""
from __future__ import annotations

import time
from typing import Optional

__all__ = ["Deadline", "DeadlineExceeded"]


class DeadlineExceeded(TimeoutError):
    """A wall-clock budget ran out. ``transient = False``: the retry
    classifier must never absorb an expired deadline (a TimeoutError is
    otherwise retryable). ``trace_id`` is stamped by the serving engine
    when the expired operation belongs to a traced request."""

    transient = False
    trace_id = ""

    def __init__(self, what: str, budget_s: float, elapsed_s: float):
        self.what = what
        self.budget_s = float(budget_s)
        self.elapsed_s = float(elapsed_s)
        super().__init__(
            f"deadline exceeded: {what or 'operation'} ran "
            f"{elapsed_s:.3f}s against a {budget_s:g}s budget")


class Deadline:
    """A monotonic wall-clock budget.

    ``Deadline(0.5, what="request 17")`` starts the clock at construction;
    ``None``/``0``/negative budgets mean *unbounded* (every query says
    there is time left — callers need no special case). Usable three ways:

    * polling: ``if dl.expired: shed(...)`` / ``dl.remaining()``
    * asserting: ``dl.check()`` raises :class:`DeadlineExceeded`
    * bracketing: ``with Deadline(2.0, what="compile"): ...`` re-checks on
      clean exit, so a body that silently overran raises instead of
      pretending it met its budget (an in-flight exception wins — the
      deadline never masks the real failure).
    """

    __slots__ = ("budget_s", "what", "_t0")

    def __init__(self, budget_s: Optional[float], what: str = ""):
        b = float(budget_s) if budget_s else 0.0
        self.budget_s = b if b > 0 else None   # None = unbounded
        self.what = what
        self._t0 = time.monotonic()

    def elapsed(self) -> float:
        return time.monotonic() - self._t0

    def remaining(self) -> Optional[float]:
        """Seconds left (may be negative once expired); None = unbounded."""
        if self.budget_s is None:
            return None
        return self.budget_s - self.elapsed()

    @property
    def expired(self) -> bool:
        r = self.remaining()
        return r is not None and r <= 0

    def check(self, what: Optional[str] = None) -> None:
        if self.expired:
            raise DeadlineExceeded(what or self.what, self.budget_s,
                                   self.elapsed())

    def __enter__(self) -> "Deadline":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is None:
            self.check()
        return False

    def __repr__(self) -> str:
        if self.budget_s is None:
            return f"Deadline(unbounded, what={self.what!r})"
        return (f"Deadline({self.budget_s:g}s, remaining="
                f"{self.remaining():.3f}s, what={self.what!r})")
