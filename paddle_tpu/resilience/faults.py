"""Deterministic fault injection (``FLAGS_fault_plan``).

Production TPU training assumes preemption and transient infrastructure
failure are routine (PAPERS.md, cross-replica sharding paper: restart is a
first-class event, not an accident). The rest of ``paddle_tpu.resilience``
— retry/backoff, crash-safe checkpoints, torn-checkpoint fallback — is only
*testable* if failures can be produced on demand, deterministically, at the
exact boundaries where real ones occur. This module is that switchboard.

Injection sites (probed via :func:`fault_point`):

=============  ==============================================================
site           probed where
=============  ==============================================================
``compile``    executor AOT build (``Executor._ensure_executable``) and the
               data-parallel compile (``CompiledProgram._get_compiled``)
``device_put`` feed host->device transfer (``Executor._to_device_array``,
               CompiledProgram feed packing)
``step``       immediately before a compiled step executes (run /
               run_chained / CompiledProgram)
``device_lost`` inside the parallel-step dispatch (CompiledProgram), where
               a real preempted/reset chip surfaces — the injected error
               is classified into a typed ``DeviceLostError``
               (``resilience.elastic``) exactly like the real zoo, so the
               elastic rescale path is testable deterministically
``ckpt_write`` inside ``io.save_checkpoint`` after the blobs are written but
               BEFORE the manifest/rename — a ``kill`` here leaves a torn
               temp dir, never a torn live checkpoint
``shard_write`` before EACH per-shard file of a sharded (format_version 2)
               checkpoint write (``resilience.distributed``) — a ``kill``
               on shard k models one host of a distributed writer dying
               mid-checkpoint
``hang``       inside the watchdog-armed dispatch section of every executor
               path (run / run_chained / CompiledProgram) — pair with the
               ``hang`` action to stall a step the watchdog must break
``enqueue``    ``serving.ServingEngine.submit`` before admission control —
               an injected fault here is a typed submission failure the
               caller sees (never a silent drop)
``batch_dispatch`` in the serving dispatch thread immediately before a
               batch executes — an injected fault fails that batch's
               requests with typed errors and feeds the circuit breaker
``overload``   inside serving admission control — a fired rule forces the
               request to be rejected ``Overloaded`` exactly as if the
               queue were full (synthetic pressure for the load gate)
``wire_connect``  fleet router, before a dispatch connection is opened
               (``fleet.router._connect_and_post``) — the request provably
               has NOT been sent yet, so a fired fault here exercises the
               unadmitted-retry path
``wire_response`` around one HTTP response body: fleet front-end before it
               writes (``_respond_best_effort``) AND fleet router before it
               reads (``_post_once``) — a fault here models the wire dying
               or lying AFTER the request may have been admitted
``wire_stream``   around one streaming ND-JSON chunk: front-end ``_chunk``
               and router ``_stream_tokens`` — mid-generation wire chaos
=============  ==============================================================

The three ``wire_*`` sites are probed via :func:`fault_action` (not
:func:`fault_point`) and accept three extra **data-plane actions** the
call site performs itself: ``drop`` (sever the connection), ``stall``
(sleep ``FLAGS_fault_stall_s`` — an interruptible trickle that models a
stalling-but-listening peer) and ``corrupt`` (mangle the payload bytes).
Exception actions still work at wire sites; the data-plane actions are
refused at non-wire sites at parse time.

Plan grammar (``FLAGS_fault_plan``, comma-separated rules)::

    site:N:action     fire on the first N hits of the site
    site:@K:action    fire exactly on the K-th hit (1-based)
    site:pX:action    fire with probability X per hit (seeded by
                      FLAGS_fault_seed — the same plan replays identically)

Actions: an exception class name (``RuntimeError``, ``OSError``,
``TimeoutError``, ``ConnectionError`` — raised as an *injected* subclass so
handlers can tell injected faults from real ones), ``kill`` —
``os._exit(137)``, a mid-write SIGKILL stand-in that skips every ``finally``
block exactly like the real signal — or ``hang``: an interruptible stall
(a loop of short sleeps, so the step watchdog's ``interrupt_main`` can
break it; a real collective hang blocks in native code and is covered by
the watchdog's hard-exit escalation instead).

Example: ``FLAGS_fault_plan="compile:2:RuntimeError,ckpt_write:1:kill"``
makes the first two compile attempts fail transiently (retry/backoff must
absorb them) and kills the process during the first checkpoint write
(crash-safe rename must leave the previous checkpoint intact).
"""
from __future__ import annotations

import dataclasses
import logging
import os
import random
import re
from typing import Dict, List, Optional

from ..monitor.lockwitness import make_lock

__all__ = ["FaultPlan", "InjectedFault", "fault_point", "fault_action",
           "stall", "install_plan", "clear_plan", "fault_plan_guard",
           "active_plan", "SITES", "WIRE_SITES", "DATA_ACTIONS"]

logger = logging.getLogger("paddle_tpu.resilience")

SITES = ("compile", "device_put", "step", "ckpt_write", "shard_write",
         "hang", "enqueue", "batch_dispatch", "overload", "device_lost",
         "wire_connect", "wire_response", "wire_stream")

# sites whose faults are performed by the CALL SITE (fault_action): the
# fleet wire layer can drop/stall/corrupt, which a raised exception
# cannot express
WIRE_SITES = frozenset({"wire_connect", "wire_response", "wire_stream"})
DATA_ACTIONS = ("drop", "stall", "corrupt")

# injected exceptions carry this mixin so retry/give-up handlers can tell a
# scripted fault from a real infrastructure error (real errors keep their
# pre-resilience behavior; injected ones must propagate for the chaos gate)
class InjectedFault(Exception):
    pass


_BASES = {"RuntimeError": RuntimeError, "OSError": OSError,
          "IOError": OSError, "TimeoutError": TimeoutError,
          "ConnectionError": ConnectionError}
_INJECTED_CLS: Dict[str, type] = {}


def _injected_class(name: str) -> type:
    if name not in _INJECTED_CLS:
        base = _BASES[name]
        _INJECTED_CLS[name] = type(f"Injected{base.__name__}",
                                   (base, InjectedFault), {})
    return _INJECTED_CLS[name]


_RULE_RE = re.compile(r"^(?P<site>[a-z_]+):(?P<when>@?\d+|p(?:0?\.\d+|1(?:\.0+)?))"
                      r":(?P<action>[A-Za-z_]+)$")


@dataclasses.dataclass
class _Rule:
    site: str
    action: str          # "kill" or an exception class name
    count: Optional[int] = None   # fire on the first N hits
    at: Optional[int] = None      # fire exactly on hit #K
    prob: Optional[float] = None  # fire with probability p per hit

    def fires(self, hit: int, rng: random.Random) -> bool:
        if self.at is not None:
            return hit == self.at
        if self.count is not None:
            return hit <= self.count
        return rng.random() < (self.prob or 0.0)


class FaultPlan:
    """A parsed, seeded fault schedule. Hit counters are per-plan (and the
    plan is per-process), so the same spec replays the same faults. Hit
    accounting is lock-guarded: serving probes ``enqueue`` from concurrent
    submitter threads, and a torn counter would make an ``@K`` rule fire
    twice or never."""

    def __init__(self, spec: str = "", seed: int = 0):
        import threading

        self.spec = spec or ""
        self.seed = int(seed)
        self.rules: Dict[str, List[_Rule]] = {}
        self.hits: Dict[str, int] = {}
        self.fired: List[tuple] = []   # (site, hit, action) audit trail
        self._rng = random.Random(self.seed)
        self._lock = make_lock("FaultPlan._lock")
        for part in filter(None, (p.strip() for p in self.spec.split(","))):
            m = _RULE_RE.match(part)
            if not m:
                raise ValueError(
                    f"FLAGS_fault_plan: cannot parse rule '{part}' — expected"
                    f" site:N:action, site:@K:action or site:pX:action")
            site, when, action = m.group("site", "when", "action")
            if site not in SITES:
                raise ValueError(f"FLAGS_fault_plan: unknown site '{site}' "
                                 f"(known: {', '.join(SITES)})")
            if action not in ("kill", "hang") \
                    and action not in DATA_ACTIONS \
                    and action not in _BASES:
                raise ValueError(
                    f"FLAGS_fault_plan: unknown action '{action}' (known: "
                    f"kill, hang, {', '.join(DATA_ACTIONS)}, "
                    f"{', '.join(sorted(_BASES))})")
            if action in DATA_ACTIONS and site not in WIRE_SITES:
                raise ValueError(
                    f"FLAGS_fault_plan: action '{action}' is a data-plane "
                    f"wire action — only the wire sites "
                    f"({', '.join(sorted(WIRE_SITES))}) can perform it")
            rule = _Rule(site=site, action=action)
            if when.startswith("@"):
                rule.at = int(when[1:])
            elif when.startswith("p"):
                rule.prob = float(when[1:])
            else:
                rule.count = int(when)
            self.rules.setdefault(site, []).append(rule)

    @property
    def active(self) -> bool:
        return bool(self.rules)

    def _fire(self, site: str):
        """Record one pass through ``site`` under the plan lock; returns
        ``(rule, hit_number)`` when a rule fired, else ``None``. The
        ``fired`` audit trail records every fired rule (including the
        data-plane wire actions the call site performs itself)."""
        rules = self.rules.get(site)
        if not rules:
            return None
        with self._lock:
            self.hits[site] = k = self.hits.get(site, 0) + 1
            fired_rule = next(
                (r for r in rules if r.fires(k, self._rng)), None)
            if fired_rule is not None:
                self.fired.append((site, k, fired_rule.action))
        return None if fired_rule is None else (fired_rule, k)

    def hit(self, site: str) -> None:
        """Probe ``site``; perform the scheduled action if a rule fires
        (raise an injected exception or kill the process). Counting and
        rule evaluation run under the plan lock; the action itself runs
        outside it (a ``hang`` must never stall other threads' probes)."""
        fired = self._fire(site)
        if fired is None:
            return
        rule, k = fired
        if rule.action in DATA_ACTIONS:
            # a data-plane action reaching a raise-style probe would be a
            # plan/call-site mismatch (parse validation pins these to the
            # wire sites, which probe via action()); log, never crash
            logger.warning("fault_plan: data action '%s' fired at "
                           "fault_point site '%s' — ignored (probe via "
                           "fault_action)", rule.action, site)
            return
        self._perform(rule, site, k)

    def action(self, site: str) -> Optional[str]:
        """Probe ``site`` for the wire call sites: a fired data-plane
        action (``drop``/``stall``/``corrupt``) is RETURNED for the call
        site to perform; exception/kill/hang actions are performed here
        exactly like :meth:`hit`. ``None`` = nothing fired."""
        fired = self._fire(site)
        if fired is None:
            return None
        rule, k = fired
        if rule.action in DATA_ACTIONS:
            from .. import monitor as _monitor

            if _monitor.enabled():
                _monitor.counter(
                    "resilience_faults_injected_total",
                    "faults fired by the FLAGS_fault_plan schedule").labels(
                    site=site, action=rule.action).inc()
            logger.warning("fault_plan: wire action '%s' at site '%s' "
                           "(hit #%d)", rule.action, site, k)
            return rule.action
        self._perform(rule, site, k)
        return None

    def _perform(self, rule: _Rule, site: str, k: int) -> None:
        from .. import monitor as _monitor

        if _monitor.enabled():
            _monitor.counter(
                "resilience_faults_injected_total",
                "faults fired by the FLAGS_fault_plan schedule").labels(
                site=site, action=rule.action).inc()
        if rule.action == "kill":
            logger.warning("fault_plan: KILL at site '%s' (hit #%d)",
                           site, k)
            os._exit(137)
        if rule.action == "hang":
            import time

            logger.warning("fault_plan: HANG at site '%s' (hit #%d) — "
                           "stalling until interrupted", site, k)
            # short sleeps so a pending interrupt (the watchdog's
            # interrupt_main, or its cross-thread async raise) is
            # delivered between iterations; a single long sleep would
            # ride out the interrupt flag in C
            while True:
                time.sleep(0.02)
        logger.warning("fault_plan: injecting %s at site '%s' (hit #%d)",
                       rule.action, site, k)
        raise _injected_class(rule.action)(
            f"[resilience] injected {rule.action} at site '{site}' "
            f"(hit #{k} of plan '{self.spec}')")


# -- active-plan resolution -------------------------------------------------
# explicit install_plan wins; otherwise FLAGS_fault_plan/FLAGS_fault_seed is
# parsed lazily and cached on the (spec, seed) pair.

_installed: Optional[FaultPlan] = None
_flag_cache: Optional[tuple] = None   # (spec, seed, plan)


def install_plan(plan: Optional[FaultPlan]) -> None:
    global _installed
    _installed = plan


def clear_plan() -> None:
    install_plan(None)


def active_plan() -> Optional[FaultPlan]:
    if _installed is not None:
        return _installed if _installed.active else None
    from ..flags import flag

    spec = flag("fault_plan")
    if not spec:
        return None
    seed = int(flag("fault_seed"))
    global _flag_cache
    if _flag_cache is None or _flag_cache[:2] != (spec, seed):
        _flag_cache = (spec, seed, FaultPlan(spec, seed))
    return _flag_cache[2]


def fault_point(site: str) -> None:
    """The injection probe. No active plan -> a dict lookup and return."""
    plan = active_plan()
    if plan is not None:
        plan.hit(site)


def fault_action(site: str) -> Optional[str]:
    """The wire-site probe: returns a fired data-plane action
    (``drop``/``stall``/``corrupt``) for the call site to perform, raises
    injected exceptions exactly like :func:`fault_point`, or returns
    ``None`` when nothing fired."""
    plan = active_plan()
    if plan is None:
        return None
    return plan.action(site)


def stall(seconds: Optional[float] = None) -> None:
    """The ``stall`` wire action: sleep ``FLAGS_fault_stall_s`` (or an
    explicit ``seconds``) in short slices, so signal delivery and
    interpreter shutdown stay responsive while a stalling peer is being
    modeled."""
    import time

    if seconds is None:
        from ..flags import flag

        seconds = float(flag("fault_stall_s"))
    deadline = time.monotonic() + max(0.0, seconds)
    while True:
        left = deadline - time.monotonic()
        if left <= 0:
            return
        time.sleep(min(0.05, left))


class fault_plan_guard:
    """``with fault_plan_guard("compile:2:RuntimeError"):`` — install a plan
    for a test body, restoring the previous plan (and flag cache) on exit."""

    def __init__(self, spec_or_plan, seed: int = 0):
        self._plan = (spec_or_plan if isinstance(spec_or_plan, FaultPlan)
                      else FaultPlan(spec_or_plan, seed))

    def __enter__(self) -> FaultPlan:
        self._prev = _installed
        install_plan(self._plan)
        return self._plan

    def __exit__(self, *exc):
        install_plan(self._prev)
        return False
