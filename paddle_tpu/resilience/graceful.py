"""Graceful preemption shutdown: SIGTERM -> finish the step -> checkpoint
-> exit 0.

TPU fleets announce most evictions (maintenance drains, spot preemption
notices) as a SIGTERM seconds-to-minutes before the SIGKILL. The naive
handler dies mid-step and leans on the crash-safe checkpoint machinery;
this module turns the notice into a CLEAN exit instead: one process-wide
shutdown :class:`threading.Event` that signal handlers set, consumers
poll, and sleepers wake on.

Consumers:

* ``contrib.Trainer.train`` installs the handlers for its duration
  (restoring the previous ones on exit): after the in-flight step
  completes it writes a final verified checkpoint — data cursor included
  — and returns, so the process exits 0 and the NEXT incarnation resumes
  exactly where the notice landed.
* ``serving.ServingEngine.install_preemption_handler()`` registers a
  drain-stop: on the signal the engine stops admitting, finishes every
  queued request (each still reaches exactly one terminal outcome) and
  ``ready()`` flips false so the load balancer routes away.
* ``resilience.retry`` backoff sleeps wait on this event (plus a
  per-thread stop event) instead of ``time.sleep`` — a shutdown or an
  engine ``stop()`` is never blocked behind a multi-second backoff.

The handler itself only sets the event and spawns a daemon thread for
the registered callbacks — nothing checkpoint-sized runs in signal
context.
"""
from __future__ import annotations

import logging
import signal
import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..monitor.lockwitness import make_lock

__all__ = ["shutdown_event", "shutdown_requested", "request_shutdown",
           "on_shutdown", "install_signal_handlers",
           "uninstall_signal_handlers", "reset_shutdown_state"]

logger = logging.getLogger("paddle_tpu.resilience")

_lock = make_lock("resilience.graceful._lock")
_event = threading.Event()
_reason: Optional[str] = None
_callbacks: List[Callable[[], None]] = []
# signum -> (previous handler, refcount). Refcounted because several
# scoped owners share one process-wide handler (a Trainer.train() call
# AND a ServingEngine's preemption registration): the previous handler
# is restored only when the LAST owner uninstalls — a trainer exiting
# must not tear down the engine's preemption route.
_installed: Dict[int, list] = {}


def shutdown_event() -> threading.Event:
    """The process-wide shutdown event (wait on it to sleep
    interruptibly; see ``resilience.retry``)."""
    return _event


def shutdown_requested() -> bool:
    return _event.is_set()


def shutdown_reason() -> Optional[str]:
    return _reason


_finished = False


def request_shutdown(reason: str = "request") -> None:
    """Flip the shutdown event (idempotent) and run the registered
    callbacks in a daemon thread. SIGNAL-SAFE: handlers run on the main
    thread between bytecodes, possibly while that very thread holds
    ``_lock`` (or the logging lock) — so this function takes NO lock and
    does NO logging itself; everything blocking is deferred to the
    spawned thread, with a lock-guarded once-flag absorbing the
    double-spawn race."""
    global _reason
    if _event.is_set():
        return
    _reason = reason
    _event.set()
    threading.Thread(target=_finish_shutdown, args=(reason,),
                     name="paddle_tpu-graceful-shutdown",
                     daemon=True).start()


def _finish_shutdown(reason: str) -> None:
    global _finished
    with _lock:
        if _finished:
            return
        _finished = True
        callbacks = list(_callbacks)
    logger.warning("graceful shutdown requested (%s): finishing in-flight "
                   "work, then checkpoint/drain and exit", reason)
    try:
        from .. import monitor as _monitor

        if _monitor.enabled():
            _monitor.counter(
                "graceful_shutdown_requests_total",
                "graceful shutdowns initiated (signal or explicit)"
            ).labels(reason=reason).inc()
    except Exception:
        pass
    _run_callbacks(callbacks)


def _run_callbacks(callbacks) -> None:
    for cb in callbacks:
        try:
            cb()
        except Exception:
            logger.exception("graceful shutdown callback %r failed", cb)


def on_shutdown(callback: Callable[[], None]) -> Callable[[], None]:
    """Register ``callback`` to run (in a daemon thread) when shutdown is
    requested; returns an unregister function. If shutdown was ALREADY
    requested the callback is dispatched immediately — still on a daemon
    thread, so a late-starting engine drains without blocking the
    registering caller."""
    with _lock:
        already = _event.is_set()
        if not already:
            _callbacks.append(callback)

    def unregister() -> None:
        with _lock:
            try:
                _callbacks.remove(callback)
            except ValueError:
                pass

    if already:
        threading.Thread(target=_run_callbacks, args=([callback],),
                         name="paddle_tpu-graceful-shutdown",
                         daemon=True).start()
    return unregister


def install_signal_handlers(
        signals: Tuple[int, ...] = (signal.SIGTERM,)) -> bool:
    """Route ``signals`` into :func:`request_shutdown`. Idempotent; only
    the main thread may install (CPython restriction) — other threads
    get ``False`` and the caller falls back to polling the event.
    Previously-installed handlers are remembered for
    :func:`uninstall_signal_handlers`."""
    if threading.current_thread() is not threading.main_thread():
        return False

    def _handler(signum, frame):
        request_shutdown(f"signal_{signum}")

    installed = False
    for signum in signals:
        with _lock:
            entry = _installed.get(signum)
            if entry is not None:
                entry[1] += 1
                installed = True
                continue
        try:
            prev = signal.signal(signum, _handler)
        except (ValueError, OSError):   # non-main thread race / bad signum
            continue
        with _lock:
            _installed[signum] = [prev, 1]
        installed = True
    return installed


def uninstall_signal_handlers(
        signals: Tuple[int, ...] = (signal.SIGTERM,)) -> None:
    """Release one owner's hold on the handlers (scoped use:
    ``Trainer.train`` installs for its duration only). The previous
    handler is restored only when no other owner — e.g. a ServingEngine
    preemption registration — still holds one."""
    restore = []
    with _lock:
        for signum in signals:
            entry = _installed.get(signum)
            if entry is None:
                continue
            entry[1] -= 1
            if entry[1] <= 0:
                restore.append((signum, entry[0]))
                del _installed[signum]
    for signum, prev in restore:
        try:
            signal.signal(signum, prev)
        except (ValueError, TypeError, OSError):
            pass


def reset_shutdown_state() -> None:
    """Test hook: clear the event, reason and callback list (handlers
    stay as they are — tests that installed them restore explicitly)."""
    global _reason, _finished
    with _lock:
        _event.clear()
        _reason = None
        _finished = False
        _callbacks.clear()
