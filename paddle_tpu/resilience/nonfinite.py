"""Graceful non-finite step degradation (``FLAGS_nan_inf_policy``).

``FLAGS_check_nan_inf`` compiles per-op finite checks into every step
(executor.make_step_fn); before this module its only possible outcome was a
``FloatingPointError`` into the training loop — one bad batch (a single inf
logit at scale is routine) killed a run that a human would have shrugged
through. The policy ladder:

* ``raise`` (default): restore the scope bit-exactly to its pre-step
  values, then raise with op provenance — catching the error leaves a
  usable session (the sanitizer never poisons parameters with the nan
  step's updates). On a path that cannot image pre-step buffers
  (multi-process global arrays) the step's outputs are written back
  instead (the inputs were donated; without the write-back the scope
  would reference deleted buffers).
* ``skip``: DROP the step — the scope is rolled back bit-exactly to its
  pre-step values and training continues. Because the executor donates
  parameter buffers (the liveness-proven in-place update from PR 2), the
  old buffers would normally be consumed by XLA; under this policy the
  executor donates fresh *copies* and keeps the originals as the rollback
  image, so "pre-step values" means the exact same bits, not a re-read.
  ``FLAGS_nan_inf_max_consecutive_skips`` consecutive trips escalate to
  ``raise`` — persistent non-finiteness is a bug, not noise.
* ``zero_grad``: same bit-exact rollback (for a stateless optimizer this
  IS the zero-gradient update: params unchanged), but it never escalates —
  the keep-training-through-noise mode. True masked-gradient semantics
  would require re-running the fused step with zeroed grads; the
  approximation is documented in docs/RESILIENCE.md.

Each dropped step increments ``steps_skipped_nonfinite_total{path,policy}``.
The consecutive-skip counter lives on the Executor (``_nonfinite_consec``)
so independent executors escalate independently.
"""
from __future__ import annotations

import logging

__all__ = ["policy", "rollback_active", "record_skip", "record_clean",
           "witness_attribution", "POLICIES"]

logger = logging.getLogger("paddle_tpu.resilience")

POLICIES = ("raise", "skip", "zero_grad")


def witness_attribution() -> str:
    """First-offending-var attribution from the numerics witness, as a
    message suffix. The executor records the step's witness stats BEFORE
    the nan-check protocol runs (executor.strip_witness_stats), so when a
    skip or escalation fires here the witness already knows WHICH var went
    non-finite first in program order — finer-grained than the nan-check
    label when several ops tripped in one step. Empty string when the
    witness is off or the last step was clean."""
    from ..monitor import numwitness

    offender = numwitness.first_offender()
    if offender is None:
        return ""
    return (f" [numerics witness: first non-finite var this step was "
            f"'{offender}']")


def policy() -> str:
    from ..flags import flag

    p = str(flag("nan_inf_policy")).strip().lower()
    if p not in POLICIES:
        raise ValueError(
            f"FLAGS_nan_inf_policy={p!r} — expected one of {POLICIES}")
    return p


def rollback_active() -> bool:
    """True when the executor must preserve pre-step donated buffers:
    whenever the sanitizer is on. ``skip``/``zero_grad`` need the image to
    drop the step; ``raise`` needs it so the raise restores pre-step state
    instead of leaving nan-poisoned parameters in the scope."""
    from ..flags import flag

    if not flag("check_nan_inf"):
        return False
    policy()  # validate eagerly: a typo'd policy fails the step, not the trip
    return True


def record_skip(path: str, label: str, exe=None) -> None:
    """Account one dropped step AFTER the scope has been rolled back.
    Raises ``FloatingPointError`` when ``skip`` escalation trips — the
    scope is already restored, so even the escalation leaves a usable
    session."""
    from .. import monitor as _monitor
    from ..flags import flag

    from .. import trace as _trace

    pol = policy()
    attribution = witness_attribution()
    _trace.record_incident(
        "nonfinite_step",
        detail=f"path '{path}': non-finite value in {label} "
               f"(policy {pol}){attribution}")
    if _monitor.enabled():
        _monitor.counter(
            "steps_skipped_nonfinite_total",
            "steps dropped (state rolled back) by FLAGS_nan_inf_policy").\
            labels(path=path, policy=pol).inc()
    if pol == "skip" and exe is not None:
        exe._nonfinite_consec = getattr(exe, "_nonfinite_consec", 0) + 1
        limit = int(flag("nan_inf_max_consecutive_skips"))
        if limit and exe._nonfinite_consec >= limit:
            raise FloatingPointError(
                f"FLAGS_nan_inf_policy=skip escalated to raise: "
                f"{exe._nonfinite_consec} consecutive non-finite steps "
                f"(limit {limit}; last: non-finite value in "
                f"{label}).{attribution} Persistent non-finiteness is a "
                f"model/data bug, not transient noise — state was rolled "
                f"back to pre-step values.")
    logger.warning(
        "nan_inf_policy=%s: dropping step on path '%s' (non-finite value "
        "in %s)%s; state rolled back to pre-step values", pol, path, label,
        attribution)


def record_clean(exe) -> None:
    """A finite step resets the consecutive-skip escalation counter."""
    if exe is not None:
        exe._nonfinite_consec = 0
