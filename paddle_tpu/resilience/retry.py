"""Retry with exponential backoff + jitter for transient executor sites.

A transient device or compile error (preempted chip, flaky host transfer,
RPC hiccup) used to abort the whole run; the reference stack's answer was
"restart the trainer and reload". Here the two sites where transience is
real — compile and device transfer — are wrapped in a bounded, seeded,
metric-emitting retry loop. Non-transient errors (shape/dtype mistakes,
``FloatingPointError`` from the nan sanitizer, PT* verifier findings) are
*never* retried: retrying a deterministic bug just triples its latency.

Classification is by exception type: ``RuntimeError`` / ``OSError`` /
``TimeoutError`` / ``ConnectionError`` are transient, everything else
(``TypeError``, ``ValueError`` — including ``ProgramVerificationError`` —
``FloatingPointError``, ...) is permanent and re-raised immediately.

Metrics (docs/OBSERVABILITY.md): ``resilience_retries_total{site}`` on each
retried attempt, ``resilience_giveups_total{site}`` when the budget is
exhausted (the caller then sees :class:`RetryExhaustedError` chained onto
the final cause).
"""
from __future__ import annotations

import dataclasses
import logging
import random
import threading
import time
from typing import Callable, Optional

from .deadline import Deadline

__all__ = ["RetryPolicy", "RetryExhaustedError", "call_with_retry",
           "retrying", "is_transient", "policy_for",
           "set_thread_stop_event"]

logger = logging.getLogger("paddle_tpu.resilience")

# order matters: a FloatingPointError is not an OSError etc., but keep the
# permanent list explicit so subclass surprises (ProgramVerificationError is
# a ValueError) stay non-retryable by construction
_TRANSIENT = (RuntimeError, OSError, TimeoutError, ConnectionError)
_PERMANENT = (TypeError, ValueError, KeyError, IndexError, AttributeError,
              NotImplementedError, FloatingPointError, MemoryError,
              RecursionError, AssertionError)


def is_transient(exc: BaseException) -> bool:
    # classes can opt out of retry explicitly (WatchdogTimeout,
    # ReplicaDivergenceError, DeviceLostError: RuntimeErrors by type,
    # but retrying a hang, a determinism bug or a DEAD CHIP only delays
    # the diagnosis/rescale)
    if getattr(exc, "transient", None) is False:
        return False
    if not isinstance(exc, _TRANSIENT) or isinstance(exc, _PERMANENT):
        return False
    # a transient-typed wrapper chained onto a permanent cause is a
    # deterministic bug in disguise (e.g. lowering's _OpLoweringError, a
    # RuntimeError raised `from` the op's AttributeError/TypeError):
    # retrying it just triples the latency of the real diagnostic
    cause = exc.__cause__
    if cause is not None and not is_transient(cause):
        return False
    return True


@dataclasses.dataclass
class RetryPolicy:
    """max_attempts counts the first try: 3 means 1 try + 2 retries.
    ``timeout`` is the per-site wall-clock budget across all attempts; once
    it is spent the next failure gives up even with attempts remaining."""

    max_attempts: int = 3
    base_delay: float = 0.05
    max_delay: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.25        # delay *= 1 + jitter * U[0,1)
    timeout: Optional[float] = 30.0

    def delay(self, attempt: int, rng: random.Random) -> float:
        d = min(self.max_delay,
                self.base_delay * self.multiplier ** (attempt - 1))
        return d * (1.0 + self.jitter * rng.random())


# backoff sleeps are INTERRUPTIBLE: they wake when the process-wide
# graceful-shutdown event (resilience.graceful) or a stop event the
# calling thread registered (the serving dispatch thread registers its
# engine's) fires — a shutdown or engine.stop() must never sit behind a
# multi-second backoff in progress.
_local = threading.local()


def set_thread_stop_event(event: Optional[threading.Event]) -> None:
    """Bind ``event`` to the CALLING thread: any backoff sleep this
    thread enters wakes (and aborts the retry, typed) when it fires.
    Pass ``None`` to unbind."""
    _local.stop_event = event


def _wait_backoff(delay: float) -> Optional[str]:
    """Sleep ``delay`` seconds; returns the interruption reason
    (``"shutdown"``/``"stop"``) when a stop event fired early, else
    ``None`` after the full sleep."""
    from .graceful import shutdown_event

    events = [("shutdown", shutdown_event())]
    thread_ev = getattr(_local, "stop_event", None)
    if thread_ev is not None:
        events.append(("stop", thread_ev))
    deadline = time.monotonic() + delay
    while True:
        for name, ev in events:
            if ev.is_set():
                return name
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return None
        # with one event a plain wait() suffices; with two, short slices
        # keep both responsive (50 ms is noise against backoff scales)
        events[0][1].wait(remaining if len(events) == 1
                          else min(remaining, 0.05))


class RetryExhaustedError(RuntimeError):
    """Raised after the retry budget for a site is spent; ``last_error`` is
    the final underlying failure (also chained as ``__cause__``)."""

    def __init__(self, site: str, attempts: int, last_error: BaseException):
        self.site = site
        self.attempts = attempts
        self.last_error = last_error
        super().__init__(
            f"resilience: site '{site}' still failing after {attempts} "
            f"attempt(s); giving up. Last error: "
            f"{type(last_error).__name__}: {last_error}")


def policy_for(site: str) -> RetryPolicy:
    """The FLAGS-configured policy (same knobs for every site; pass an
    explicit :class:`RetryPolicy` to ``call_with_retry`` to specialize)."""
    from ..flags import flag

    return RetryPolicy(max_attempts=max(1, int(flag("retry_max_attempts"))),
                       base_delay=float(flag("retry_base_delay")),
                       max_delay=float(flag("retry_max_delay")),
                       timeout=float(flag("retry_timeout")) or None)


def call_with_retry(site: str, fn: Callable, *args,
                    policy: Optional[RetryPolicy] = None, **kwargs):
    """Run ``fn`` under the site's retry policy. Transient failures are
    retried with exponential backoff + seeded jitter; permanent ones are
    re-raised untouched on the first occurrence. The happy path costs one
    ``try`` — policy/flag resolution is deferred to the first failure, so
    wrapping a hot site (per-feed device_put) is free; the ``timeout``
    budget is therefore measured from the first failure, not the call."""
    from .. import monitor as _monitor

    from .. import trace as _trace

    pol = policy
    rng = deadline = None
    attempt = 0
    traced = _trace.enabled()
    while True:
        attempt += 1
        try:
            if traced:
                # one span per attempt: a request trace shows each retry
                # as its own interval with the attempt number and outcome
                with _trace.span("retry." + site, site=site,
                                 attempt=attempt):
                    return fn(*args, **kwargs)
            return fn(*args, **kwargs)
        except Exception as e:
            if not is_transient(e):
                raise
            if pol is None:
                pol = policy_for(site)
            if rng is None:
                import zlib

                from ..flags import flag

                # crc32, not hash(): str hashes are salted per process, and
                # the documented contract is that the same plan+seed
                # replays identically across runs
                rng = random.Random((int(flag("fault_seed")) << 16)
                                    ^ zlib.crc32(site.encode()))
                # the per-site budget is one Deadline (shared with the
                # serving request deadlines — resilience.deadline), started
                # at the first failure so the happy path stays free
                deadline = Deadline(pol.timeout, what=f"retry site '{site}'")
            out_of_time = deadline.expired
            if attempt >= pol.max_attempts or out_of_time:
                if _monitor.enabled():
                    _monitor.counter(
                        "resilience_giveups_total",
                        "transient-site retry budgets exhausted").labels(
                        site=site).inc()
                logger.error(
                    "resilience: site '%s' gave up after %d attempt(s)%s: %s",
                    site, attempt,
                    " (timeout)" if out_of_time else "", e)
                raise RetryExhaustedError(site, attempt, e) from e
            if _monitor.enabled():
                _monitor.counter(
                    "resilience_retries_total",
                    "transient-site failures absorbed by retry").labels(
                    site=site).inc()
            d = pol.delay(attempt, rng)
            logger.warning(
                "resilience: transient %s at site '%s' (attempt %d/%d), "
                "retrying in %.3fs: %s", type(e).__name__, site, attempt,
                pol.max_attempts, d, e)
            if d > 0:
                interrupted = _wait_backoff(d)
                if interrupted is not None:
                    # a graceful shutdown / engine stop fired mid-backoff:
                    # abort the retry loop typed instead of finishing the
                    # sleep — the caller's teardown is waiting on us.
                    # Counted apart from giveups: 'budget exhausted' and
                    # 'teardown requested' must stay distinguishable
                    if _monitor.enabled():
                        _monitor.counter(
                            "resilience_retry_aborts_total",
                            "retry loops aborted mid-backoff by a "
                            "shutdown/stop event (not a budget "
                            "exhaustion)").labels(
                            site=site, reason=interrupted).inc()
                    logger.warning(
                        "resilience: backoff at site '%s' interrupted by "
                        "%s after attempt %d — aborting retries", site,
                        interrupted, attempt)
                    raise RetryExhaustedError(site, attempt, e) from e


def retrying(site: str, policy: Optional[RetryPolicy] = None):
    """Decorator form: ``@retrying("device_put")`` wraps a callable in
    :func:`call_with_retry` for that site."""
    def deco(fn: Callable):
        import functools

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return call_with_retry(site, fn, *args, policy=policy, **kwargs)
        return wrapped
    return deco
