"""paddle_tpu.resilience — fault tolerance as a first-class subsystem.

The ROADMAP's north star is production-scale training where preemption,
transient infrastructure failure and the occasional non-finite batch are
ROUTINE, not fatal (PAPERS.md: data-parallel TPU training at scale only
works because restart-after-failure is assumed). Four pieces, wired through
io / executor / contrib.Trainer / monitor:

* :mod:`~paddle_tpu.resilience.checkpoint` — crash-safe checkpoints:
  ``io.save_checkpoint`` writes into a temp dir, emits a ``manifest.json``
  with per-file sha256 + param inventory + framework version, fsyncs, then
  atomically renames; ``io.load_checkpoint`` verifies before loading;
  ``load_latest_checkpoint`` (used by ``Trainer._load_latest``) walks
  serials newest->oldest skipping torn/corrupt checkpoints with PT6xx
  diagnostics instead of crashing or silently loading garbage.
* :mod:`~paddle_tpu.resilience.faults` — deterministic, seeded fault
  injection (``FLAGS_fault_plan="compile:2:RuntimeError,ckpt_write:1:kill"``)
  at the compile / device_put / step / ckpt_write sites. The only way the
  rest of this subsystem is testable; ``tools/chaos_check.py`` is the CI
  gate built on it.
* :mod:`~paddle_tpu.resilience.retry` — exponential backoff + seeded
  jitter for the transient sites (compile, device transfer), with
  ``resilience_retries_total`` / ``resilience_giveups_total`` metrics and a
  per-site wall-clock budget. Shape/dtype/verifier errors never retry.
* :mod:`~paddle_tpu.resilience.nonfinite` — ``FLAGS_nan_inf_policy =
  raise|skip|zero_grad``: under ``skip`` a tripped step is dropped with the
  scope rolled back bit-exactly (donation-aware: the executor donates
  copies and keeps the originals), N consecutive skips escalate to raise.
* :mod:`~paddle_tpu.resilience.distributed` — the parallel layer: sharded
  elastic checkpoints (manifest format_version 2, PT605–PT609), cross-
  replica divergence detection (``FLAGS_replica_check_interval`` /
  ``FLAGS_replica_divergence_policy``), and the step watchdog
  (``FLAGS_step_timeout_s``) that turns hangs into diagnosed failures.
  CI proof: ``tools/chaos_check.py --multichip``.
* :mod:`~paddle_tpu.resilience.elastic` — preemption-tolerant training:
  the jax/XLA error zoo at the parallel-step/collective sites is
  classified into a typed ``DeviceLostError`` (never retried), the mesh
  re-forms on the surviving devices (PT610–PT614 refusal diagnostics
  when the topology cannot satisfy the checkpoint's non-dp axes), state
  restores from the last verified sharded serial, and the data cursor
  (``meta.json: data_cursor``) fast-forwards the reader so a rescaled
  resume consumes exactly the remaining batch sequence.
  ``contrib.Trainer`` wires the loop under ``FLAGS_elastic``; CI proof:
  ``tools/chaos_check.py --elastic``.
* :mod:`~paddle_tpu.resilience.graceful` — SIGTERM/preemption-notice
  shutdown: one process-wide event that handlers set, the Trainer and
  ``serving.ServingEngine`` consume (finish the step / drain the queue,
  write a final verified checkpoint, exit 0), and retry backoff sleeps
  wake on.

Failure model, flag reference and checkpoint format: docs/RESILIENCE.md.
"""
from __future__ import annotations

from .checkpoint import (CKPT_CODES, FORMAT_VERSION, CheckpointCorruptError,
                         atomic_replace_dir, finalize_manifest, iter_serials,
                         load_latest_checkpoint, verify_checkpoint,
                         verify_sharding_section)
from .deadline import Deadline, DeadlineExceeded
from .distributed import (ReplicaDivergenceError, WatchdogTimeout,
                          handle_divergence, replica_divergence_check,
                          set_divergence_recovery, watchdog_section)
from .elastic import (ELASTIC_CODES, DataCursor, DeviceLostError,
                      ElasticRescaleError, classify_device_error,
                      grad_accum_steps, plan_rescale, survivor_devices)
from .faults import (SITES, FaultPlan, InjectedFault, active_plan,
                     clear_plan, fault_plan_guard, fault_point, install_plan)
from .graceful import (install_signal_handlers, on_shutdown,
                       request_shutdown, shutdown_event,
                       shutdown_requested, uninstall_signal_handlers)
from .nonfinite import POLICIES
from .retry import (RetryExhaustedError, RetryPolicy, call_with_retry,
                    is_transient, policy_for, retrying,
                    set_thread_stop_event)

__all__ = [
    # checkpoint integrity
    "CheckpointCorruptError", "CKPT_CODES", "FORMAT_VERSION",
    "verify_checkpoint", "verify_sharding_section", "finalize_manifest",
    "atomic_replace_dir", "iter_serials", "load_latest_checkpoint",
    # distributed resilience (sharded ckpts, divergence, watchdog)
    "ReplicaDivergenceError", "WatchdogTimeout", "watchdog_section",
    "replica_divergence_check", "handle_divergence",
    "set_divergence_recovery",
    # fault injection
    "FaultPlan", "InjectedFault", "fault_point", "fault_plan_guard",
    "install_plan", "clear_plan", "active_plan", "SITES",
    # retry + deadlines (one implementation for retry budgets AND serving
    # request deadlines)
    "RetryPolicy", "RetryExhaustedError", "retrying", "call_with_retry",
    "is_transient", "policy_for", "Deadline", "DeadlineExceeded",
    "set_thread_stop_event",
    # elastic preemption tolerance (device loss -> mesh rescale -> resume)
    "DeviceLostError", "ElasticRescaleError", "ELASTIC_CODES",
    "classify_device_error", "plan_rescale", "grad_accum_steps",
    "survivor_devices", "DataCursor",
    # graceful (SIGTERM/preemption-notice) shutdown
    "shutdown_event", "shutdown_requested", "request_shutdown",
    "on_shutdown", "install_signal_handlers",
    "uninstall_signal_handlers",
    # non-finite degradation
    "POLICIES",
]
