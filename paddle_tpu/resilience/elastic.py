"""Elastic preemption-tolerant training: device-loss detection + mesh
rescale planning + deterministic data resume.

Production TPU fleets lose chips: preemptions, host evictions and device
resets are ROUTINE (PAPERS.md, arXiv 2004.13336 — once optimizer state is
dp-sharded, surviving a device loss REQUIRES a re-shard path). PR 6
shipped the hard half — sharded elastic checkpoints whose restore on a
different dp width is proven byte-equal — but nothing detected a lost
device or drove the resume: a preempted run died with an untyped jax
error and a human restarted it. This module is the missing control loop,
in four pieces (docs/RESILIENCE.md, "Elastic training"):

1. **Device-loss detection** — :func:`classify_device_error` maps the
   jax/XLA error zoo at the parallel-step and collective sites onto a
   typed :class:`DeviceLostError` (``transient = False``: retry must
   NEVER absorb a dead chip — backing off against a missing device only
   delays the rescale). The ``device_lost`` fault site
   (``resilience.faults``) injects one deterministically.
2. **Mesh rescale** — :func:`plan_rescale` re-forms the axis layout on
   the surviving device set: non-dp axes (pp/sp) are load-bearing and
   kept intact, the dp axis absorbs the loss (dp=8 -> 4, and back up
   when capacity returns). A surviving topology that cannot satisfy the
   checkpoint's non-dp axes refuses with a PT61x
   :class:`ElasticRescaleError` instead of wedging.
3. **Global-batch preservation** — :func:`grad_accum_steps`: after a
   rescale the driver keeps feeding the SAME global batch, so each
   surviving replica's slice grows by ``old_dp / new_dp``. Because the
   loss is a mean over the global batch, widening the per-replica slice
   inside one fused step is arithmetically identical to running
   ``old_dp/new_dp`` gradient-accumulation micro-steps and applying the
   optimizer once — the loss trajectory is comparable (on-device:
   bit-comparable) across topologies and the PR 6 divergence checker
   stays meaningful.
4. **Deterministic data resume** — :class:`DataCursor`: the data-
   pipeline position (epoch, batch offset, reader/shuffle state) is
   checkpointed in the manifest (``meta.json: data_cursor``) and the
   reader is fast-forwarded on restore, so a rescaled resume consumes
   exactly the not-yet-committed batch sequence — no re-trained and no
   skipped data.

``contrib.Trainer`` wires the loop (``FLAGS_elastic``, default on for
parallel runs with a checkpoint config): a :class:`DeviceLostError` — or
a watchdog-diagnosed hang on the parallel step, which on a dead device
is the same event seen later — tears down the failed ``CompiledProgram``,
re-forms the mesh on the survivors, restores from the last VERIFIED
sharded serial via the PR 6 elastic-restore path, fast-forwards the data
cursor, and keeps training. Every rescale increments
``elastic_rescales_total{old,new,direction}`` and logs the serial it
restored from — recovery is never silent. End-to-end proof:
``tools/chaos_check.py --elastic``.
"""
from __future__ import annotations

import contextlib
import logging
import re
from typing import Dict, Optional, Sequence

__all__ = ["DeviceLostError", "ElasticRescaleError", "ELASTIC_CODES",
           "classify_device_error", "device_loss_classification",
           "record_device_lost", "plan_rescale", "grad_accum_steps",
           "format_axes", "DataCursor", "survivor_devices"]

logger = logging.getLogger("paddle_tpu.resilience")

# PT61x: elastic-rescale diagnostics (sibling band of the checkpoint
# integrity PT60x codes in resilience/checkpoint.py; docs/RESILIENCE.md)
ELASTIC_CODES = {
    "PT610": "surviving devices cannot satisfy the mesh's non-dp axes "
             "(pp/sp need more devices than survive; dp is the only "
             "elastic axis)",
    "PT611": "surviving data-parallel width would fall below the "
             "configured minimum",
    "PT612": "elastic rescale budget exhausted (FLAGS_elastic_max_"
             "rescales) — repeated device loss is an outage, not churn",
    "PT613": "global batch is not divisible by any feasible surviving "
             "dp width — batch preservation is impossible on this "
             "topology",
    "PT614": "no verified checkpoint to restore after a device loss — "
             "elastic recovery has nothing to resume from",
}


class DeviceLostError(RuntimeError):
    """A device (or its host) is gone: preemption, reset, eviction.
    ``transient = False`` — :func:`resilience.retry.is_transient` must
    never classify a dead chip as infrastructure noise; backoff against
    a missing device only delays the mesh rescale. Carries the ``site``
    that observed the loss and (when the runtime could attribute it)
    the surviving device list."""

    transient = False

    def __init__(self, detail: str, site: str = "parallel_step",
                 survivors=None):
        self.site = site
        self.detail = detail
        self.survivors = survivors
        super().__init__(
            f"[elastic] device lost at site '{site}': {detail} — a dead "
            f"chip is never retried. With FLAGS_elastic=1 a parallel "
            f"contrib.Trainer run rescales the mesh onto the survivors "
            f"and resumes from the last verified checkpoint "
            f"(docs/RESILIENCE.md).")


class ElasticRescaleError(RuntimeError):
    """The elastic path cannot recover — carries a stable PT61x ``code``
    (see :data:`ELASTIC_CODES`) naming exactly why. ``transient =
    False``: an unsatisfiable topology does not get better by retrying."""

    transient = False

    def __init__(self, code: str, detail: str):
        self.code = code
        self.detail = detail
        super().__init__(f"[{code}] elastic rescale refused: {detail} — "
                         f"{ELASTIC_CODES[code]}")


# -- 1. device-loss detection ----------------------------------------------

# the jax/XLA error zoo that means "a device/host is gone", curated from
# PJRT/TPU-runtime failure strings. Matched case-insensitively against the
# whole exception chain; deliberately specific — a generic RuntimeError
# must stay transient-retryable, misclassifying a compile hiccup as a
# dead chip would trigger a pointless rescale.
_DEVICE_LOSS_PATTERNS = tuple(re.compile(p, re.IGNORECASE) for p in (
    r"site 'device_lost'",                    # the injected fault marker
    r"device\s+(?:\S+\s+)?(?:is\s+)?(?:lost|halted|rebooted|reset)",
    r"(?:tpu|device|chip|core)\s+.*\b(?:unhealthy|unavailable|"
    r"disappeared|removed)",
    r"\bpreempt(?:ed|ion)\b",
    r"slice\s+health|ici\s+.*\b(?:down|failure|timed?\s*out)",
    r"failed\s+to\s+(?:connect\s+to|enumerate)\s+.*(?:device|worker|host)",
    r"(?:socket\s+closed|connection\s+reset\s+by\s+peer)"
    r".*(?:worker|host|coordinator)",
    r"host\s+.*\b(?:evicted|terminated|unreachable)",
    r"\bNCCL\b.*\b(?:unhandled|failure|error)",
))


def record_device_lost(site: str) -> None:
    """One definition of the ``elastic_device_lost_total`` counter for
    every detection site (classifier, watchdog escalation) — two literal
    copies would drift apart and split the series. Also the one choke
    point where the flight recorder dumps: a device loss ships with the
    last N trace spans (the dying step/request chain among them)."""
    from .. import monitor as _monitor
    from .. import trace as _trace

    if _monitor.enabled():
        _monitor.counter(
            "elastic_device_lost_total",
            "device losses detected (classified from the jax/XLA error "
            "zoo, injected, or escalated from a watchdog-diagnosed "
            "parallel-step hang)").labels(site=site).inc()
    _trace.record_incident("device_lost", detail=f"site {site}")


def _chain(exc: BaseException):
    seen = set()
    while exc is not None and id(exc) not in seen:
        seen.add(id(exc))
        yield exc
        exc = exc.__cause__ or exc.__context__


def classify_device_error(exc: BaseException,
                          site: str = "parallel_step"
                          ) -> Optional[DeviceLostError]:
    """Map an exception raised at a parallel-step/collective site onto a
    typed :class:`DeviceLostError`, or ``None`` when it is NOT a device
    loss (shape bugs, transient compile errors, nan trips keep their
    existing recovery paths). Walks the ``__cause__``/``__context__``
    chain so a wrapped XLA runtime error is still recognized; an
    exception that is already a :class:`DeviceLostError` passes through
    unchanged."""
    for e in _chain(exc):
        if isinstance(e, DeviceLostError):
            return e
    for e in _chain(exc):
        # the type gate applies PER CHAIN ELEMENT, like the text match:
        # an Exception-typed wrapper around an XlaRuntimeError must
        # still classify, while a ValueError/TypeError anywhere stays a
        # program bug whatever its message says
        if not isinstance(e, (RuntimeError, OSError, ConnectionError)):
            continue
        text = f"{type(e).__name__}: {e}"
        if any(p.search(text) for p in _DEVICE_LOSS_PATTERNS):
            record_device_lost(site)
            # survivor attribution comes from the element that MATCHED
            # (the runtime's own error) — a wrapper rarely carries it
            return DeviceLostError(
                f"{type(exc).__name__}: {exc}", site=site,
                survivors=(getattr(e, "survivors", None)
                           or getattr(exc, "survivors", None)))
    return None


@contextlib.contextmanager
def device_loss_classification(site: str):
    """Shared dispatch-site wrapper: run the body and re-raise anything
    that classifies as a device loss as the typed
    :class:`DeviceLostError` (chained), leaving every other exception on
    its existing path. One implementation for the parallel-step and
    collective sites, the way ``watchdog_section`` is shared."""
    try:
        yield
    except Exception as e:
        lost = classify_device_error(e, site=site)
        if lost is not None and lost is not e:
            raise lost from e
        raise


# -- 2. mesh rescale planning ----------------------------------------------

def format_axes(axes: Dict[str, int]) -> str:
    """``{'dp': 8, 'pp': 2}`` -> ``"dp=8,pp=2"`` (metric-label form)."""
    return ",".join(f"{k}={v}" for k, v in axes.items()) or "dp=1"


def plan_rescale(old_axes: Dict[str, int], n_devices: int, *,
                 dp_axis: str = "dp", min_dp: int = 1,
                 global_batch: Optional[int] = None) -> Dict[str, int]:
    """Axis sizes for the survivor mesh: every non-dp axis (pp stages, sp
    ring) keeps its size — those axes carry state layout the checkpoint
    depends on — and the dp axis absorbs the loss (or the recovery, when
    ``n_devices`` grew back). Refuses with a typed PT61x
    :class:`ElasticRescaleError` when the surviving topology cannot
    satisfy the non-dp axes (PT610), the dp width would fall below
    ``min_dp`` (PT611), or no feasible dp width divides ``global_batch``
    (PT613 — batch preservation impossible)."""
    old_axes = {str(k): int(v) for k, v in old_axes.items()} or \
        {dp_axis: 1}
    if dp_axis not in old_axes:
        old_axes = {dp_axis: 1, **old_axes}
    non_dp = 1
    for k, v in old_axes.items():
        if k != dp_axis:
            non_dp *= max(1, v)
    if n_devices < non_dp:
        raise ElasticRescaleError(
            "PT610",
            f"mesh axes {format_axes(old_axes)} need {non_dp} device(s) "
            f"for the non-{dp_axis} axes alone, but only {n_devices} "
            f"survive")
    dp = n_devices // non_dp
    if dp < max(1, min_dp):
        raise ElasticRescaleError(
            "PT611",
            f"{n_devices} surviving device(s) over non-{dp_axis} axes "
            f"of {non_dp} leave {dp_axis}={dp} < min {min_dp}")
    if global_batch is not None:
        capacity_dp = dp
        while dp > max(1, min_dp) and int(global_batch) % dp:
            dp -= 1
        if int(global_batch) % dp:
            raise ElasticRescaleError(
                "PT613",
                f"global batch {global_batch} is not divisible by any "
                f"feasible {dp_axis} width <= {n_devices // non_dp} "
                f"(min {min_dp})")
        if dp < capacity_dp:
            # at min_dp=1 a divisor always exists, so the refusal above
            # is only reachable under an explicit floor — but giving up
            # width to divisibility must never be silent: the surplus
            # devices idle until the batch (or min_dp) changes
            logger.warning(
                "elastic: global batch %s is not divisible by %s=%d — "
                "rescaling to %s=%d and leaving %d device(s) idle; set "
                "min_dp (PT613 refusal) or pick a divisible global "
                "batch to reclaim them", global_batch, dp_axis,
                capacity_dp, dp_axis, dp, (capacity_dp - dp) * non_dp)
    new_axes = dict(old_axes)
    new_axes[dp_axis] = dp
    return new_axes


# -- 3. global-batch preservation ------------------------------------------

def grad_accum_steps(old_dp: int, new_dp: int) -> int:
    """Per-replica gradient-accumulation factor that keeps the effective
    global batch after a rescale: each surviving replica processes
    ``ceil(old_dp / new_dp)`` times its previous share inside the fused
    step. Gradients of a mean loss are linear in the batch, so widening
    the per-replica slice is exactly accumulating that many micro-grads
    before one optimizer application."""
    old_dp, new_dp = max(1, int(old_dp)), max(1, int(new_dp))
    return max(1, -(-old_dp // new_dp))


# -- 4. deterministic data resume ------------------------------------------

class DataCursor:
    """The data-pipeline position checkpointed with the model state
    (``meta.json: data_cursor``): epoch index, batches already COMMITTED
    this epoch (consumed by a step whose effect the checkpoint contains),
    and the reader's own resume state (e.g. a seeded shuffle's
    ``state_dict`` — ``reader.shuffle(..., seed=N)``). On restore the
    trainer fast-forwards the reader past ``batch`` batches of epoch
    ``epoch``, so the resumed run sees exactly the not-yet-committed
    batch sequence: batches consumed after the checkpoint but before the
    crash were rolled back with the state and are re-consumed — each
    batch affects the committed lineage exactly once."""

    def __init__(self, epoch: int = 0, batch: int = 0,
                 reader_state: Optional[dict] = None):
        self.epoch = int(epoch)
        self.batch = int(batch)
        self.reader_state = dict(reader_state) if reader_state else None

    def to_dict(self) -> dict:
        d = {"epoch": self.epoch, "batch": self.batch}
        if self.reader_state is not None:
            d["reader_state"] = self.reader_state
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> Optional["DataCursor"]:
        if not isinstance(d, dict):
            return None
        return cls(epoch=d.get("epoch", 0), batch=d.get("batch", 0),
                   reader_state=d.get("reader_state"))

    def apply_to_reader(self, reader) -> None:
        """Hand the reader its persisted resume state (a no-op for plain
        generator functions — their determinism is positional and the
        trainer's batch skip covers it). A persisted ``epoch`` field is
        realigned to THIS cursor's epoch: the state was captured after
        the reader advanced past the epoch being re-entered, and the
        next ``reader()`` call must replay exactly that epoch's order."""
        if self.reader_state is not None \
                and hasattr(reader, "set_state_dict"):
            state = dict(self.reader_state)
            if "epoch" in state:
                state["epoch"] = self.epoch
            reader.set_state_dict(state)

    @staticmethod
    def capture(epoch: int, batch: int, reader=None) -> "DataCursor":
        state = None
        if reader is not None and hasattr(reader, "state_dict"):
            try:
                state = dict(reader.state_dict())
            except Exception:
                logger.exception(
                    "elastic: reader.state_dict() failed; the cursor "
                    "falls back to positional epoch/batch resume")
        return DataCursor(epoch=epoch, batch=batch, reader_state=state)

    def __repr__(self):
        return (f"DataCursor(epoch={self.epoch}, batch={self.batch}"
                f"{', reader_state=…' if self.reader_state else ''})")


def survivor_devices(devices: Sequence, axes: Dict[str, int]):
    """The device prefix a rescaled mesh uses: ``prod(axes)`` devices in
    enumeration order (stable across the runs of one incarnation — the
    PR 6 restore re-shards state onto whatever mesh exists, so the
    choice only has to be deterministic, not minimal-movement)."""
    n = 1
    for v in axes.values():
        n *= max(1, int(v))
    devices = list(devices)
    if len(devices) < n:
        raise ElasticRescaleError(
            "PT610", f"need {n} device(s) for {format_axes(axes)}, have "
                     f"{len(devices)}")
    return devices[:n]
