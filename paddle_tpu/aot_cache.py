"""Warm-start AOT executable cache: compiled-executable export/import.

The fleet serving tier (docs/SERVING.md "Fleet tier") starts replicas by
the dozen, and every cold replica used to pay the full compile storm —
one XLA build per (program, shape bucket) before it could flip
``ready()`` true. The executor already builds real AOT executables
(``_ensure_executable``); this module persists them: after a successful
``lowered.compile()`` the executable is serialized to disk
(``jax.experimental.serialize_executable``), and the next process that
needs the same executable loads it instead of compiling — warm-up time
drops from seconds-per-bucket to milliseconds (measured cold-vs-warm in
``ci_fleet_report.json``).

Keying. The in-memory step-cache keys lean on per-process serials
(``program._serial``, ``scope._serial``) — useless across restarts. The
disk key reuses the autotuner's durable identity
(:func:`paddle_tpu.tuning.program_content_fingerprint` — the PR 13
content hash that survives restarts) plus everything else that shapes
the compiled artifact:

* the execution kind (``run`` / ``chained`` + step count) and fetch list,
* the compiler configuration (xla_options, tuned GEMM blocks, the
  nan-check flag — all of which change the traced/compiled program),
* the abstract signature of every argument leaf (shape + dtype + tree
  structure): state shapes come from the live scope, so two scopes with
  different-shaped state can never share an executable,
* backend, jax version and framework version (an upgraded compiler's
  executables are invisible, the cost-database staleness rule).

Safety posture (the cost-database discipline): loads NEVER raise — a
missing/corrupt/version-mismatched entry is a miss with one warning, and
the executor compiles as if the cache did not exist. Saves are atomic
(temp sibling + fsync + rename) so a killed replica can never publish a
torn entry. Counters: ``aot_cache_hits_total`` / ``aot_cache_misses_total``
/ ``aot_cache_saves_total`` / ``aot_cache_errors_total{op}``
(docs/OBSERVABILITY.md).
"""
from __future__ import annotations

import hashlib
import logging
import os
import pickle
import threading
from typing import Any, Optional, Tuple

from .monitor.lockwitness import make_lock

__all__ = ["executable_key", "load_executable", "save_executable",
           "cache_dir_flag", "cache_stats"]

logger = logging.getLogger("paddle_tpu.aot_cache")

_SCHEMA = 1
_SUFFIX = ".aotx"

# one warning per failure class per process — a broken cache dir must not
# spam a serving replica's log at request rate
_warned = set()
_warned_lock = make_lock("aot_cache._warned_lock")


def _warn_once(kind: str, msg: str, *args) -> None:
    with _warned_lock:
        if kind in _warned:
            return
        _warned.add(kind)
    logger.warning(msg, *args)


def _versions() -> Tuple[str, str]:
    import jax

    from . import __version__

    return str(__version__), str(jax.__version__)


def cache_dir_flag() -> str:
    """``FLAGS_aot_cache_dir`` (empty = cache disabled)."""
    from .flags import flag

    return str(flag("aot_cache_dir")).strip()


def _count(name: str, help_: str, **labels) -> None:
    from . import monitor

    if monitor.enabled():
        c = monitor.counter(name, help_)
        (c.labels(**labels) if labels else c).inc()


def executable_key(parts: tuple, args) -> str:
    """Durable identity of one compiled executable.

    ``parts`` is the executor-stamped tuple
    ``(kind, program, fetch_names, xla_opts, gemm_blocks, extra...)``;
    the program element is replaced by its content fingerprint (the
    autotuner's restart-stable hash — one identity shared by the cost
    database and this cache). ``args`` are the exact call arguments the
    executable will be lowered with; only their abstract signature
    (tree structure + per-leaf shape/dtype) enters the key.
    """
    import jax

    from .tuning import program_content_fingerprint

    kind, program, *rest = parts
    fp = program_content_fingerprint(program)
    leaves, treedef = jax.tree_util.tree_flatten(args)
    leaf_sig = "|".join(
        f"{getattr(v, 'shape', None)}:{getattr(v, 'dtype', None)}"
        for v in leaves)
    fw, jx = _versions()
    material = repr((kind, fp, tuple(rest), leaf_sig, str(treedef),
                     jax.default_backend(), fw, jx))
    return hashlib.sha256(material.encode()).hexdigest()[:32]


def _path(cache_dir: str, key: str) -> str:
    return os.path.join(cache_dir, key + _SUFFIX)


def load_executable(cache_dir: str, key: str):
    """The deserialized-and-loaded executable for ``key``, or None.
    Counts a hit or a miss; never raises (corrupt/alien entries degrade
    to a miss with one warning)."""
    path = _path(cache_dir, key)
    try:
        if not os.path.exists(path):
            _count("aot_cache_misses_total",
                   "AOT executable cache lookups that had to compile")
            return None
        with open(path, "rb") as f:
            blob = pickle.load(f)
        import jax
        from jax.experimental.serialize_executable import \
            deserialize_and_load

        fw, jx = _versions()
        if (not isinstance(blob, dict) or blob.get("schema") != _SCHEMA
                or blob.get("jax") != jx or blob.get("framework") != fw
                or blob.get("backend") != jax.default_backend()):
            # a different compiler's executable is not a corrupt file —
            # it is simply not ours to load (staleness rule)
            _count("aot_cache_misses_total",
                   "AOT executable cache lookups that had to compile")
            _warn_once("stale",
                       "aot cache entry %s was written by a different "
                       "framework/jax/backend — ignoring (recompiling)",
                       path)
            return None
        loaded = deserialize_and_load(blob["payload"], blob["in_tree"],
                                      blob["out_tree"])
        _count("aot_cache_hits_total",
               "compiles skipped by loading a serialized AOT executable")
        return loaded
    except Exception as e:
        _count("aot_cache_errors_total",
               "AOT executable cache operations that failed "
               "(non-fatal; the executor compiles instead)", op="load")
        _warn_once("load",
                   "aot cache load failed for %s (%s: %s) — compiling "
                   "instead", path, type(e).__name__, e)
        return None


def save_executable(cache_dir: str, key: str, compiled) -> bool:
    """Serialize ``compiled`` under ``key`` (atomic publish). Returns
    whether the entry was written; failures warn once and return False —
    a replica that cannot persist executables still serves."""
    try:
        import jax
        from jax.experimental.serialize_executable import (
            deserialize_and_load, serialize)

        payload, in_tree, out_tree = serialize(compiled)
        # validate BEFORE publishing: an executable that itself came out
        # of jax's persistent compilation cache serializes to a blob
        # that cannot load back ("Symbols not found" on XLA:CPU, jax
        # 0.4.x) — publishing it would poison every future warm start.
        # One deserialize costs milliseconds against the seconds the
        # entry saves; an unloadable blob is simply never published.
        try:
            deserialize_and_load(payload, in_tree, out_tree)
        except Exception as e:
            _count("aot_cache_errors_total",
                   "AOT executable cache operations that failed "
                   "(non-fatal; the executor compiles instead)",
                   op="validate")
            _warn_once("validate",
                       "aot cache: freshly serialized executable does "
                       "not load back (%s: %s) — not publishing it "
                       "(typical cause: the compile was served from "
                       "jax's own persistent compilation cache)",
                       type(e).__name__, e)
            return False
        fw, jx = _versions()
        blob = {"schema": _SCHEMA, "framework": fw, "jax": jx,
                "backend": jax.default_backend(), "payload": payload,
                "in_tree": in_tree, "out_tree": out_tree}
        os.makedirs(cache_dir, exist_ok=True)
        path = _path(cache_dir, key)
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            pickle.dump(blob, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _count("aot_cache_saves_total",
               "AOT executables serialized into the warm-start cache")
        return True
    except Exception as e:
        _count("aot_cache_errors_total",
               "AOT executable cache operations that failed "
               "(non-fatal; the executor compiles instead)", op="save")
        _warn_once("save",
                   "aot cache save failed under %s (%s: %s) — executable "
                   "stays in-memory only", cache_dir, type(e).__name__, e)
        return False


def cache_stats() -> dict:
    """Monitor-counter snapshot for reports (replica startup lines,
    ci_fleet_report.json)."""
    from . import monitor

    return {
        "hits": monitor.metric_value("aot_cache_hits_total", 0.0),
        "misses": monitor.metric_value("aot_cache_misses_total", 0.0),
        "saves": monitor.metric_value("aot_cache_saves_total", 0.0),
        "errors": sum(
            monitor.metric_value("aot_cache_errors_total", 0.0, op=op)
            for op in ("load", "save", "validate")),
    }
