"""Control-flow layers: While, Switch, IfElse, StaticRNN, tensor arrays.

Reference: python/paddle/fluid/layers/control_flow.py (While :697, Switch
:1052, IfElse :1327, StaticRNN :282, array_write/read :893/:1013,
lod_rank_table et al). Build-time only — each construct opens a sub-block,
records the user's ops there, then appends ONE structured op (while /
conditional_block / recurrent) to the parent; lowering maps those onto
lax.while_loop / lax.cond / lax.scan (see ops/control_flow.py for the
XLA-semantics deltas, e.g. bounded tensor arrays inside While).
"""
from __future__ import annotations

import contextlib
from typing import List, Optional

from .. import unique_name
from ..core.types import VarType
from ..framework import Variable, default_main_program
from ..layer_helper import LayerHelper
from .tensor import fill_constant

__all__ = ["While", "Switch", "IfElse", "StaticRNN", "create_array",
           "array_write", "array_read", "array_length", "cond",
           "tensor_array_to_tensor"]


def _block_io(sub_block, parent_block):
    """Vars a sub-block reads from (resp. writes to) enclosing scopes."""
    written_local: set = set()
    reads: List[str] = []
    writes: List[str] = []
    for op in sub_block.ops:
        for n in op.input_arg_names:
            if n == "@EMPTY@" or n in written_local or n in reads:
                continue
            if n not in sub_block.vars and parent_block.has_var_recursive(n):
                reads.append(n)
        for n in op.output_arg_names:
            if n == "@EMPTY@":
                continue
            if n in sub_block.vars:
                written_local.add(n)
            elif parent_block.has_var_recursive(n) and n not in writes:
                writes.append(n)
    return reads, writes


class While:
    """reference control_flow.py:697. Usage:

        i = layers.fill_constant([1], 'int64', 0)
        cond = layers.less_than(i, n)
        w = While(cond)
        with w.block():
            ...
            layers.increment(i)
            layers.assign(layers.less_than(i, n), cond)   # refresh cond

    ``max_len`` bounds any tensor array carried through the loop (XLA needs
    static shapes; unbounded growth inside while has no TPU encoding)."""

    def __init__(self, cond: Variable, is_test: bool = False, name=None,
                 max_len: int = 0):
        self.helper = LayerHelper("while", name=name)
        self.cond_var = cond
        self.is_test = is_test
        self.max_len = max_len

    @contextlib.contextmanager
    def block(self):
        program = self.cond_var.block.program
        parent = program.current_block()
        sub = program._create_block()
        try:
            yield
        finally:
            program._rollback()
        reads, writes = _block_io(sub, parent)
        if self.cond_var.name not in writes:
            raise ValueError(
                "While body never updates the condition variable "
                f"'{self.cond_var.name}' — the loop cannot terminate. "
                "Assign a fresh comparison to it inside the block.")
        parent.append_op(
            "while",
            inputs={"X": reads, "Condition": [self.cond_var.name]},
            outputs={"Out": writes},
            attrs={"sub_block": sub.idx, "is_test": self.is_test,
                   "max_len": self.max_len})


def cond(pred: Variable, true_fn=None, false_fn=None):
    """Functional if-else (the 2.x API, provided for convenience): both
    branches run under lax.cond; their return vars must match in shape."""
    program = pred.block.program
    parent = program.current_block()
    helper = LayerHelper("cond")

    def run_branch(fn):
        sub = program._create_block()
        try:
            res = fn() if fn is not None else None
        finally:
            program._rollback()
        res_list = list(res) if isinstance(res, (list, tuple)) else (
            [] if res is None else [res])
        return sub, res_list

    true_sub, true_out = run_branch(true_fn)
    false_sub, false_out = run_branch(false_fn)
    true_reads, true_writes = _block_io(true_sub, parent)
    false_reads, false_writes = _block_io(false_sub, parent)
    if len(true_out) != len(false_out):
        raise ValueError("cond branches must return the same structure")

    # ONE conditional_block per branch. Out = branch return vars PLUS every
    # outer var the branch writes, so side effects (assigns to enclosing-scope
    # vars) survive lowering — the reference tracks all sub-block writes the
    # same way. Emitted even when the branch returns nothing: the writes are
    # the observable effect.
    t_outs = list(dict.fromkeys([v.name for v in true_out] + true_writes))
    f_outs = list(dict.fromkeys([v.name for v in false_out] + false_writes))
    if t_outs or true_sub.ops:
        parent.append_op(
            "conditional_block",
            inputs={"Cond": [pred.name], "Input": true_reads},
            outputs={"Out": t_outs},
            attrs={"sub_block": true_sub.idx})
    if f_outs or false_sub.ops:
        notp = helper.create_variable_for_type_inference("bool")
        parent.append_op("logical_not", inputs={"X": pred},
                         outputs={"Out": notp})
        parent.append_op(
            "conditional_block",
            inputs={"Cond": [notp.name], "Input": false_reads},
            outputs={"Out": f_outs},
            attrs={"sub_block": false_sub.idx})
    outs = []
    for tv, fv in zip(true_out, false_out):
        out = helper.create_variable_for_type_inference(tv.dtype)
        out.shape = tv.shape
        outs.append(out)
        parent.append_op("where", inputs={"Condition": pred, "X": tv,
                                          "Y": fv},
                         outputs={"Out": out})
    if not outs:
        return None
    return outs[0] if len(outs) == 1 else outs


class Switch:
    """reference control_flow.py:1052 — case chain, used by LR schedules.

        with Switch() as switch:
            with switch.case(cond1): assign(a, out)
            with switch.default():   assign(b, out)

    Build-time: each case body becomes a conditional_block gated on
    (its cond) AND (no earlier cond fired)."""

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self._prior: Optional[Variable] = None  # any earlier case matched

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    @contextlib.contextmanager
    def case(self, condition: Variable):
        from . import nn as _nn

        if self._prior is None:
            eff = condition
            new_prior = condition
        else:
            notp = self.helper.create_variable_for_type_inference("bool")
            self.helper.append_op("logical_not", inputs={"X": self._prior},
                                  outputs={"Out": notp})
            eff = self.helper.create_variable_for_type_inference("bool")
            self.helper.append_op("logical_and",
                                  inputs={"X": condition, "Y": notp},
                                  outputs={"Out": eff})
            new_prior = self.helper.create_variable_for_type_inference("bool")
            self.helper.append_op("logical_or",
                                  inputs={"X": self._prior, "Y": condition},
                                  outputs={"Out": new_prior})
        program = eff.block.program
        parent = program.current_block()
        sub = program._create_block()
        try:
            yield
        finally:
            program._rollback()
        reads, writes = _block_io(sub, parent)
        parent.append_op("conditional_block",
                         inputs={"Cond": [eff.name], "Input": reads},
                         outputs={"Out": writes},
                         attrs={"sub_block": sub.idx})
        self._prior = new_prior

    @contextlib.contextmanager
    def default(self):
        if self._prior is None:
            raise ValueError("Switch.default() before any case()")
        notp = self.helper.create_variable_for_type_inference("bool")
        self.helper.append_op("logical_not", inputs={"X": self._prior},
                              outputs={"Out": notp})
        program = notp.block.program
        parent = program.current_block()
        sub = program._create_block()
        try:
            yield
        finally:
            program._rollback()
        reads, writes = _block_io(sub, parent)
        parent.append_op("conditional_block",
                         inputs={"Cond": [notp.name], "Input": reads},
                         outputs={"Out": writes},
                         attrs={"sub_block": sub.idx})


class IfElse:
    """reference control_flow.py:1327. true_block/false_block write output
    vars; ifelse() returns the merged outputs."""

    def __init__(self, cond: Variable, name=None):
        self.cond = cond
        self.helper = LayerHelper("ifelse", name=name)
        self._true_out: List[Variable] = []
        self._false_out: List[Variable] = []
        self._blocks = {}

    def input(self, x: Variable) -> Variable:
        return x  # dense tensors: no LoD split needed

    @contextlib.contextmanager
    def true_block(self):
        with self._branch(True):
            yield

    @contextlib.contextmanager
    def false_block(self):
        with self._branch(False):
            yield

    @contextlib.contextmanager
    def _branch(self, is_true: bool):
        program = self.cond.block.program
        parent = program.current_block()
        sub = program._create_block()
        self._current = (is_true, sub, parent)
        try:
            yield
        finally:
            program._rollback()
            del self._current
        self._blocks[is_true] = sub

    def output(self, *outs: Variable):
        is_true, _, _ = self._current
        (self._true_out if is_true else self._false_out).extend(outs)

    def __call__(self) -> List[Variable]:
        if len(self._true_out) != len(self._false_out):
            raise ValueError("IfElse branches produced different outputs")
        parent = self.cond.block.program.current_block()
        merged = []
        for tv, fv in zip(self._true_out, self._false_out):
            t_reads, _ = _block_io(self._blocks[True], parent)
            parent.append_op("conditional_block",
                             inputs={"Cond": [self.cond.name],
                                     "Input": t_reads},
                             outputs={"Out": [tv.name]},
                             attrs={"sub_block": self._blocks[True].idx})
            notp = self.helper.create_variable_for_type_inference("bool")
            parent.append_op("logical_not", inputs={"X": self.cond},
                             outputs={"Out": notp})
            f_reads, _ = _block_io(self._blocks[False], parent)
            parent.append_op("conditional_block",
                             inputs={"Cond": [notp.name],
                                     "Input": f_reads},
                             outputs={"Out": [fv.name]},
                             attrs={"sub_block": self._blocks[False].idx})
            out = self.helper.create_variable_for_type_inference(tv.dtype)
            out.shape = tv.shape
            parent.append_op("where",
                             inputs={"Condition": self.cond, "X": tv, "Y": fv},
                             outputs={"Out": out})
            merged.append(out)
        return merged


class StaticRNN:
    """reference control_flow.py:282 — RNN unrolled over the SEQUENCE axis.

    Inputs are TIME-MAJOR [seq, batch, ...] (reference convention);
    step_input yields the per-step slice [batch, ...]. Lowered to ONE
    lax.scan, so the whole RNN is a single fused XLA loop, differentiable
    end to end — the reference's recurrent_op + recurrent_grad in one
    primitive.
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self._sub = None
        self._parent = None
        self._seq_len = None
        self._step_inputs = []   # (source_name, step_var)
        self._memories = []      # (pre_var, init_name, new_name_or_None)
        self._outputs = []       # step output vars
        self._status = "outside"

    @contextlib.contextmanager
    def step(self):
        program = default_main_program()
        self._parent = program.current_block()
        self._sub = program._create_block()
        self._status = "inside"
        try:
            yield
        finally:
            program._rollback()
            self._status = "done"
        self._append_recurrent_op()

    def _require_inside(self):
        if self._status != "inside":
            raise RuntimeError("StaticRNN ops must be inside rnn.step()")

    def step_input(self, x: Variable) -> Variable:
        self._require_inside()
        if x.shape is None or len(x.shape) < 2:
            raise ValueError("step_input needs [seq, batch, ...] input")
        seq = x.shape[0]
        if self._seq_len is None:
            self._seq_len = seq
        step = self._sub.create_var(
            name=unique_name.generate("rnn_step_in"),
            shape=tuple(x.shape[1:]), dtype=x.dtype)
        self._step_inputs.append((x.name, step))
        return step

    def memory(self, init: Optional[Variable] = None, shape=None,
               batch_ref: Optional[Variable] = None, init_value=0.0,
               dtype="float32") -> Variable:
        self._require_inside()
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError("memory needs init or (shape, batch_ref)")
            # constant init of [batch, *shape] built OUTSIDE the loop;
            # batch_ref is time-major so the batch is its dim 1 (reference
            # StaticRNN.memory also uses fill_constant_batch_size_like,
            # which keeps the shape inferable when batch is dynamic)
            from .tensor import fill_constant_batch_size_like

            program = default_main_program()
            cur = program.current_block_idx
            program.current_block_idx = self._parent.idx
            try:
                init = fill_constant_batch_size_like(
                    input=batch_ref, shape=[-1] + list(shape), dtype=dtype,
                    value=init_value, input_dim_idx=1, output_dim_idx=0)
            finally:
                program.current_block_idx = cur
        pre = self._sub.create_var(
            name=unique_name.generate("rnn_mem_pre"),
            shape=init.shape, dtype=init.dtype)
        self._memories.append([pre, init.name, None])
        return pre

    def update_memory(self, mem: Variable, new: Variable):
        self._require_inside()
        for m in self._memories:
            if m[0].name == mem.name:
                m[2] = new.name
                return
        raise ValueError(f"update_memory: '{mem.name}' is not a memory")

    def step_output(self, out: Variable):
        self._require_inside()
        self._outputs.append(out)

    def output(self, *outs: Variable):
        for o in outs:
            self.step_output(o)

    def _append_recurrent_op(self):
        if not self._outputs:
            raise ValueError("StaticRNN produced no step_output")
        for m in self._memories:
            if m[2] is None:
                raise ValueError(
                    f"memory '{m[0].name}' never update_memory'd")
        reads, _ = _block_io(self._sub, self._parent)
        source_names = [s for s, _ in self._step_inputs]
        init_names = [m[1] for m in self._memories]
        inner = {v.name for _, v in self._step_inputs}
        inner |= {m[0].name for m in self._memories}
        param_names = [n for n in reads
                       if n not in source_names and n not in init_names]
        out_vars = []
        for o in self._outputs:
            ov = self._parent.create_var(
                name=unique_name.generate("rnn_out"),
                shape=(self._seq_len,) + tuple(o.shape)
                if o.shape else None,
                dtype=o.dtype)
            out_vars.append(ov)
        self._out_vars = out_vars
        self._parent.append_op(
            "recurrent",
            inputs={"Inputs": source_names, "InitStates": init_names,
                    "Params": param_names},
            outputs={"Outputs": [v.name for v in out_vars]},
            attrs={"sub_block": self._sub.idx,
                   "step_input_names": [v.name for _, v in self._step_inputs],
                   "pre_memory_names": [m[0].name for m in self._memories],
                   "new_memory_names": [m[2] for m in self._memories],
                   "step_output_names": [o.name for o in self._outputs]})

    def __call__(self):
        outs = self._out_vars
        return outs[0] if len(outs) == 1 else outs


# ---------------------------------------------------------------------------
# tensor arrays (reference array_write :893 / array_read :1013)
# ---------------------------------------------------------------------------

def create_array(dtype="float32") -> Variable:
    helper = LayerHelper("create_array")
    arr = helper.block.create_var(
        name=unique_name.generate("array"), dtype=dtype,
        type=VarType.LOD_TENSOR_ARRAY, shape=(0,), stop_gradient=True)
    helper.append_op("create_array", outputs={"Out": arr},
                     attrs={"dtype": dtype})
    return arr


def array_write(x: Variable, i: Variable, array: Optional[Variable] = None
                ) -> Variable:
    helper = LayerHelper("array_write")
    if array is None:
        array = create_array(x.dtype)
    helper.append_op("write_to_array",
                     inputs={"X": x, "I": i, "Array": array},
                     outputs={"Out": array})
    return array


def array_read(array: Variable, i: Variable) -> Variable:
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference(array.dtype)
    helper.append_op("read_from_array", inputs={"X": array, "I": i},
                     outputs={"Out": out})
    return out


def array_length(array: Variable) -> Variable:
    helper = LayerHelper("array_length")
    out = helper.create_variable_for_type_inference("int64", True)
    out.shape = (1,)
    helper.append_op("lod_array_length", inputs={"X": array},
                     outputs={"Out": out})
    return out


def tensor_array_to_tensor(input: Variable, axis=0, name=None):
    helper = LayerHelper("tensor_array_to_tensor", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("tensor_array_to_tensor", inputs={"X": input},
                     outputs={"Out": out}, attrs={"axis": axis})
    return out
