"""Data layers (reference: python/paddle/fluid/layers/io.py `data`)."""
from __future__ import annotations

from ..core.types import canonical_dtype
from ..framework import default_main_program

__all__ = ["data"]


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         stop_gradient=True, type=None):
    """Declare an input variable. With append_batch_size (reference default),
    a -1 batch dim is prepended; shapes with explicit -1 are taken as-is."""
    shape = list(shape)
    if append_batch_size:
        if any(s == -1 for s in shape):
            append_batch_size = False
        elif lod_level >= 1:
            # padded+lengths encoding: reference shape is per-token, so the
            # padded var gains BOTH a batch and a (bucketed) time dim
            shape = [-1, -1] + shape
        else:
            shape = [-1] + shape
    block = default_main_program().current_block()
    v = block.create_var(name=name, shape=shape,
                         dtype=canonical_dtype(dtype), lod_level=lod_level,
                         stop_gradient=stop_gradient, is_data=True)
    if lod_level >= 1:
        # padded+lengths LoD encoding (SURVEY §5): the per-sequence lengths
        # arrive in a companion feed '<name>@LOD' (int32 [batch]), produced
        # by the DataFeeder/DataLoader varlen path and consumed by the
        # sequence ops' SeqLen slots (ops/sequence_ops.py)
        block.create_var(name=name + "@LOD", shape=(-1,), dtype="int32",
                         stop_gradient=True, is_data=True)
    return v
