"""Extended fluid.layers surface — the long tail of reference
python/paddle/fluid/layers/nn.py functions whose ops already exist in the
registry but had no layer-building wrapper, plus reference pure-python
composites (dice_loss, mse_loss, npair_loss, image_resize_short,
fsp_matrix). Signatures mirror the reference; each wrapper is the standard
LayerHelper -> append_op -> Variable pattern."""
from __future__ import annotations

import numpy as np

from ..initializer import Constant, Normal, Xavier
from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr
from . import nn as _nn

__all__ = [
    "conv3d", "pool3d", "conv3d_transpose", "adaptive_pool2d", "lrn",
    "pad_constant_like", "label_smooth", "gather_nd", "scatter_nd_add",
    "scatter_nd", "crop", "crop_tensor", "affine_grid", "rank_loss",
    "margin_rank_loss", "pad2d", "sampling_id", "strided_slice", "maxout",
    "space_to_depth", "affine_channel", "hash", "grid_sampler",
    "add_position_encoding", "shuffle_channel", "temporal_shift",
    "kldiv_loss", "pixel_shuffle", "unique", "unique_with_counts",
    "unfold", "shard_index", "bpr_loss", "cross_entropy2", "random_crop",
    "similarity_focus", "teacher_student_sigmoid_loss", "roi_pool",
    "roi_align", "mean_iou", "bilinear_tensor_product", "multiplex",
    "im2sequence", "row_conv", "selu", "stanh", "brelu", "sign",
    "elementwise_mod", "elementwise_floordiv", "sum", "rank", "size",
    "dice_loss", "mse_loss", "npair_loss", "image_resize_short",
    "fsp_matrix", "uniform_random_batch_size_like",
    "gaussian_random_batch_size_like", "maxout", "center_loss",
    "data_norm", "spectral_norm", "deformable_conv", "deformable_roi_pooling",
    "psroi_pool", "prroi_pool", "merge_selected_rows",
    "get_tensor_from_selected_rows", "continuous_value_model",
    "sampled_softmax_with_cross_entropy", "py_func", "resize_trilinear",
    "lstm_unit", "autoincreased_step_counter", "adaptive_pool3d",
    "beam_search", "beam_search_decode", "filter_by_instag",
    "fused_decode_attention", "kv_cache_append", "sequence_gather",
    "sample_token", "spec_accept",
]


def _one(op_type, inputs, attrs=None, dtype=None, n_out=1, out_slot="Out",
         extra_outs=(), name=None):
    """Generic single-main-output wrapper."""
    helper = LayerHelper(op_type, name=name)
    first = next(v for v in inputs.values()
                 if v is not None and not isinstance(v, (list, tuple)))
    dtype = dtype or first.dtype
    out = helper.create_variable_for_type_inference(dtype)
    outs = {out_slot: out}
    extras = []
    for slot, dt in extra_outs:
        ev = helper.create_variable_for_type_inference(dt or dtype,
                                                       stop_gradient=True)
        outs[slot] = ev
        extras.append(ev)
    helper.append_op(op_type,
                     inputs={k: v for k, v in inputs.items()
                             if v is not None},
                     outputs=outs, attrs=attrs or {})
    return (out, *extras) if extras else out


def _triple(v):
    return list(v) if isinstance(v, (list, tuple)) else [v] * 3


# -- 3D conv/pool -----------------------------------------------------------

def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None):
    helper = LayerHelper("conv3d", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    k = _triple(filter_size)
    num_channels = input.shape[1]
    std = (2.0 / (k[0] * k[1] * k[2] * num_channels)) ** 0.5
    w = helper.create_parameter(
        helper.param_attr, shape=[num_filters, num_channels // groups] + k,
        dtype=input.dtype, default_initializer=Normal(0.0, std))
    pre_bias = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("conv3d", inputs={"Input": input, "Filter": w},
                     outputs={"Output": pre_bias},
                     attrs={"strides": _triple(stride),
                            "paddings": _triple(padding),
                            "dilations": _triple(dilation),
                            "groups": groups})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, ceil_mode=False,
           exclusive=True, name=None):
    return _one("pool3d", {"X": input},
                {"pooling_type": pool_type, "ksize": _triple(pool_size),
                 "strides": _triple(pool_stride),
                 "paddings": _triple(pool_padding),
                 "global_pooling": global_pooling, "ceil_mode": ceil_mode,
                 "exclusive": exclusive}, name=name)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("conv3d_transpose", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    k = _triple(filter_size)
    num_channels = input.shape[1]
    w = helper.create_parameter(
        helper.param_attr, shape=[num_channels, num_filters // groups] + k,
        dtype=input.dtype, default_initializer=Xavier())
    pre_bias = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("conv3d_transpose",
                     inputs={"Input": input, "Filter": w},
                     outputs={"Output": pre_bias},
                     attrs={"strides": _triple(stride),
                            "paddings": _triple(padding),
                            "dilations": _triple(dilation),
                            "groups": groups})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def adaptive_pool2d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    if require_index:
        raise NotImplementedError(
            "adaptive_pool2d(require_index=True): XLA has no argmax-index "
            "pooling output; take argmax over unfold-ed windows instead")
    ps = pool_size if isinstance(pool_size, (list, tuple)) \
        else [pool_size, pool_size]
    return _one("pool2d", {"X": input},
                {"pooling_type": pool_type, "ksize": list(ps),
                 "adaptive": True}, name=name)


# -- image / tensor rearrangement ------------------------------------------

def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    out, _ = _one("lrn", {"X": input}, {"n": n, "k": k, "alpha": alpha,
                                        "beta": beta},
                  extra_outs=[("MidOut", None)], name=name)
    return out


def pad_constant_like(x, y, pad_value=0.0, name=None):
    return _one("pad_constant_like", {"X": x, "Y": y},
                {"pad_value": float(pad_value)}, name=name)


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    return _one("label_smooth", {"X": label, "PriorDist": prior_dist},
                {"epsilon": float(epsilon)}, name=name)


def gather_nd(input, index, name=None):
    return _one("gather_nd", {"X": input, "Index": index}, name=name)


def scatter_nd_add(ref, index, updates, name=None):
    return _one("scatter_nd_add",
                {"X": ref, "Index": index, "Updates": updates}, name=name)


def scatter_nd(index, updates, shape, name=None):
    """Composite (reference nn.py scatter_nd): scatter_nd_add onto zeros."""
    from .tensor import fill_constant

    zero = fill_constant(shape=list(shape), dtype=updates.dtype, value=0.0)
    return scatter_nd_add(zero, index, updates, name=name)


def crop(x, shape=None, offsets=None, name=None):
    attrs = {}
    ins = {"X": x}
    if isinstance(shape, (list, tuple)):
        attrs["shape"] = list(shape)
    elif shape is not None:
        ins["Y"] = shape
    if isinstance(offsets, (list, tuple)):
        attrs["offsets"] = list(offsets)
    elif offsets is not None:
        ins["Offsets"] = offsets
    return _one("crop", ins, attrs, name=name)


def crop_tensor(x, shape=None, offsets=None, name=None):
    attrs = {}
    ins = {"X": x}
    if isinstance(shape, (list, tuple)):
        attrs["shape"] = list(shape)
    elif shape is not None:
        ins["Shape"] = shape
    if isinstance(offsets, (list, tuple)):
        attrs["offsets"] = list(offsets)
    elif offsets is not None:
        ins["Offsets"] = offsets
    return _one("crop_tensor", ins, attrs, name=name)


def affine_grid(theta, out_shape=None, name=None):
    attrs = {}
    ins = {"Theta": theta}
    if isinstance(out_shape, (list, tuple)):
        attrs["output_shape"] = [int(v) for v in out_shape]
    elif out_shape is not None:
        ins["OutputShape"] = out_shape
    return _one("affine_grid", ins, attrs, out_slot="Output", name=name)


def pad2d(input, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    return _one("pad2d", {"X": input},
                {"paddings": list(paddings), "mode": mode,
                 "pad_value": float(pad_value), "data_format": data_format},
                name=name)


def strided_slice(input, axes, starts, ends, strides, name=None):
    return _one("strided_slice", {"Input": input},
                {"axes": list(axes), "starts": list(starts),
                 "ends": list(ends), "strides": list(strides)}, name=name)


def maxout(x, groups, axis=1, name=None):
    return _one("maxout", {"X": x}, {"groups": groups, "axis": axis},
                name=name)


def space_to_depth(x, blocksize, name=None):
    return _one("space_to_depth", {"X": x}, {"blocksize": blocksize},
                name=name)


def affine_channel(x, scale=None, bias=None, data_layout="NCHW", name=None):
    return _one("affine_channel", {"X": x, "Scale": scale, "Bias": bias},
                {"data_layout": data_layout}, name=name)


def hash(input, hash_size, num_hash=1, name=None):
    return _one("hash", {"X": input},
                {"num_hash": num_hash, "mod_by": hash_size}, dtype="int64",
                name=name)


def grid_sampler(x, grid, name=None):
    return _one("grid_sampler", {"X": x, "Grid": grid},
                out_slot="Output", name=name)


def add_position_encoding(input, alpha=1.0, beta=1.0, name=None):
    return _one("add_position_encoding", {"X": input},
                {"alpha": float(alpha), "beta": float(beta)}, name=name)


def shuffle_channel(x, group, name=None):
    return _one("shuffle_channel", {"X": x}, {"group": group}, name=name)


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    return _one("temporal_shift", {"X": x},
                {"seg_num": seg_num, "shift_ratio": shift_ratio}, name=name)


def pixel_shuffle(x, upscale_factor, name=None):
    return _one("pixel_shuffle", {"X": x},
                {"upscale_factor": upscale_factor}, name=name)


def unique(x, dtype="int32", name=None):
    return _one("unique", {"X": x}, {"dtype": dtype},
                extra_outs=[("Index", dtype)], name=name)


def unique_with_counts(x, dtype="int32", name=None):
    return _one("unique_with_counts", {"X": x}, {"dtype": dtype},
                extra_outs=[("Index", dtype), ("Count", dtype)], name=name)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    pair = lambda v: list(v) if isinstance(v, (list, tuple)) else [v, v]
    return _one("unfold", {"X": x},
                {"kernel_sizes": pair(kernel_sizes),
                 "strides": pair(strides),
                 "paddings": pair(paddings) if not isinstance(
                     paddings, (list, tuple)) or len(paddings) != 4
                 else list(paddings),
                 "dilations": pair(dilations)}, out_slot="Y", name=name)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1,
                name=None):
    return _one("shard_index", {"X": input},
                {"index_num": index_num, "nshards": nshards,
                 "shard_id": shard_id, "ignore_value": ignore_value},
                name=name)


def random_crop(x, shape, seed=None, name=None):
    out, _ = _one("random_crop", {"X": x},
                  {"shape": list(shape),
                   "startup_seed": int(seed) if seed else 0},
                  extra_outs=[("SeedOut", "int64")], name=name)
    return out


def similarity_focus(input, axis, indexes, name=None):
    return _one("similarity_focus", {"X": input},
                {"axis": axis, "indexes": list(indexes)}, name=name)


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="int64", name=None):
    return _one("sampling_id", {"X": x},
                {"min": min, "max": max, "seed": seed}, dtype=dtype,
                name=name)


# -- losses -----------------------------------------------------------------

def rank_loss(label, left, right, name=None):
    return _one("rank_loss", {"Label": label, "Left": left, "Right": right},
                name=name)


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    out, _ = _one("margin_rank_loss",
                  {"Label": label, "X1": left, "X2": right},
                  {"margin": float(margin)},
                  extra_outs=[("Activated", None)], name=name)
    return out


def kldiv_loss(x, target, reduction="mean", name=None):
    return _one("kldiv_loss", {"X": x, "Target": target},
                {"reduction": reduction}, out_slot="Loss", name=name)


def bpr_loss(input, label, name=None):
    return _one("bpr_loss", {"X": input, "Label": label}, out_slot="Y",
                name=name)


def cross_entropy2(input, label, ignore_index=-100, name=None):
    out, _, _ = _one("cross_entropy2", {"X": input, "Label": label},
                     {"ignore_index": ignore_index}, out_slot="Y",
                     extra_outs=[("XShape", None), ("MatchX", None)],
                     name=name)
    return out


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    return _one("teacher_student_sigmoid_loss",
                {"X": input, "Label": label},
                {"soft_max_up_bound": soft_max_up_bound,
                 "soft_max_lower_bound": soft_max_lower_bound},
                out_slot="Y")


def dice_loss(input, label, epsilon=1e-5):
    """Composite, reference nn.py dice_loss: 1 - 2|X*Y| / (|X|+|Y|)."""
    label = _nn.one_hot(label, input.shape[-1])
    reduce_dims = list(range(1, len(input.shape)))
    inse = _nn.reduce_sum(_nn.elementwise_mul(input, label),
                          dim=reduce_dims)
    dice_denominator = _nn.elementwise_add(
        _nn.reduce_sum(input, dim=reduce_dims),
        _nn.reduce_sum(label, dim=reduce_dims))
    dice_score = _nn.scale(
        _nn.elementwise_div(
            inse, _nn.scale(dice_denominator, scale=1.0, bias=epsilon)),
        scale=-2.0, bias=1.0)
    return _nn.reduce_mean(dice_score)


def mse_loss(input, label):
    """Composite, reference nn.py mse_loss."""
    return _nn.reduce_mean(_nn.square_error_cost(input, label))


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """Composite, reference nn.py npair_loss (multi-class N-pair loss)."""
    batch = anchor.shape[0]
    labels = _nn.reshape(_nn.cast(labels, "float32"), [-1, 1])
    same = _nn.cast(_nn.equal(labels, _nn.transpose(labels, [1, 0])),
                    "float32")
    targets = _nn.elementwise_div(
        same, _nn.reduce_sum(same, dim=1, keep_dim=True))
    logits = _nn.matmul(anchor, positive, transpose_y=True)
    softmax_ce = _nn.reduce_mean(_nn.reduce_sum(
        _nn.elementwise_mul(_nn.scale(targets, scale=-1.0),
                            _nn.log_softmax(logits)), dim=1))
    reg = _nn.scale(
        _nn.elementwise_add(_nn.reduce_mean(_nn.reduce_sum(
            _nn.square(anchor), dim=1)),
            _nn.reduce_mean(_nn.reduce_sum(_nn.square(positive), dim=1))),
        scale=float(l2_reg) * 0.25)
    return _nn.elementwise_add(softmax_ce, reg)


def center_loss(input, label, num_classes, alpha, param_attr=None,
                update_center=True):
    helper = LayerHelper("center_loss", param_attr=param_attr)
    centers = helper.create_parameter(
        helper.param_attr, shape=[num_classes, input.shape[-1]],
        dtype=input.dtype, default_initializer=Constant(0.0))
    rate = helper.create_variable_for_type_inference("float32",
                                                     stop_gradient=True)
    helper.append_op("fill_constant", outputs={"Out": rate},
                     attrs={"shape": [1], "dtype": "float32",
                            "value": float(alpha)})
    c_out = helper.create_variable_for_type_inference(input.dtype,
                                                      stop_gradient=True)
    diff = helper.create_variable_for_type_inference(input.dtype,
                                                     stop_gradient=True)
    loss = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("center_loss",
                     inputs={"X": input, "Label": label,
                             "Centers": centers, "CenterUpdateRate": rate},
                     outputs={"CentersOut": c_out, "SampleCenterDiff": diff,
                              "Loss": loss},
                     attrs={"cluster_num": num_classes,
                            "need_update": update_center})
    return loss


# -- misc surface -----------------------------------------------------------

def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, rois_batch_idx=None, name=None):
    out, _ = _one("roi_pool",
                  {"X": input, "ROIs": rois,
                   "RoisBatchIdx": rois_batch_idx},
                  {"pooled_height": pooled_height,
                   "pooled_width": pooled_width,
                   "spatial_scale": spatial_scale},
                  extra_outs=[("Argmax", "int64")], name=name)
    return out


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, rois_batch_idx=None,
              name=None):
    return _one("roi_align",
                {"X": input, "ROIs": rois, "RoisBatchIdx": rois_batch_idx},
                {"pooled_height": pooled_height,
                 "pooled_width": pooled_width,
                 "spatial_scale": spatial_scale,
                 "sampling_ratio": sampling_ratio}, name=name)


def mean_iou(input, label, num_classes):
    out, wrong, correct = _one(
        "mean_iou", {"Predictions": input, "Labels": label},
        {"num_classes": num_classes}, dtype="float32",
        out_slot="OutMeanIou",
        extra_outs=[("OutWrong", "int32"), ("OutCorrect", "int32")])
    return out, wrong, correct


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    helper = LayerHelper("bilinear_tensor_product", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    w = helper.create_parameter(
        helper.param_attr, shape=[size, x.shape[-1], y.shape[-1]],
        dtype=x.dtype)
    out = helper.create_variable_for_type_inference(x.dtype)
    ins = {"X": x, "Y": y, "Weight": w}
    if bias_attr is not False:
        b = helper.create_parameter(helper.bias_attr or ParamAttr(),
                                    shape=[1, size], dtype=x.dtype,
                                    is_bias=True)
        ins["Bias"] = b
    helper.append_op("bilinear_tensor_product", inputs=ins,
                     outputs={"Out": out})
    return helper.append_activation(out)


def multiplex(inputs, index):
    return _one("multiplex", {"Ids": index, "X": list(inputs)})


def im2sequence(input, filter_size=1, stride=1, padding=0, input_image_size=None,
                out_stride=1, name=None):
    pair = lambda v: list(v) if isinstance(v, (list, tuple)) else [v, v]
    pads = pair(padding)
    if len(pads) == 2:
        pads = pads + pads
    return _one("im2sequence", {"X": input, "Y": input_image_size},
                {"kernels": pair(filter_size), "strides": pair(stride),
                 "paddings": pads, "out_stride": pair(out_stride)},
                name=name)


def row_conv(input, future_context_size, param_attr=None, act=None):
    helper = LayerHelper("row_conv", param_attr=param_attr, act=act)
    w = helper.create_parameter(
        helper.param_attr,
        shape=[future_context_size + 1, input.shape[-1]],
        dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("row_conv", inputs={"X": input, "Filter": w},
                     outputs={"Out": out})
    return helper.append_activation(out)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None, name=None):
    helper = LayerHelper("data_norm", param_attr=param_attr, act=act,
                         name=name)
    c = input.shape[-1]
    mk = lambda n, v: helper.create_parameter(
        ParamAttr(name=None), shape=[c], dtype=input.dtype,
        default_initializer=Constant(v))
    batch_size, batch_sum, batch_sq = mk("bs", 1e4), mk("bsum", 0.0), \
        mk("bsq", 1e4)
    y = helper.create_variable_for_type_inference(input.dtype)
    means = helper.create_variable_for_type_inference(input.dtype, True)
    scales = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("data_norm",
                     inputs={"X": input, "BatchSize": batch_size,
                             "BatchSum": batch_sum,
                             "BatchSquareSum": batch_sq},
                     outputs={"Y": y, "Means": means, "Scales": scales},
                     attrs={"epsilon": epsilon})
    return helper.append_activation(y)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    helper = LayerHelper("spectral_norm", name=name)
    h = int(weight.shape[dim])
    w = int(np.prod([weight.shape[i] for i in range(len(weight.shape))
                     if i != dim]))
    import paddle_tpu.unique_name as un

    mk = lambda n, size: helper.create_parameter(
        ParamAttr(name=un.generate(n), trainable=False), shape=[size],
        dtype=weight.dtype, default_initializer=Normal(0.0, 1.0))
    u, v = mk("spectral_norm_u", h), mk("spectral_norm_v", w)
    out = helper.create_variable_for_type_inference(weight.dtype)
    helper.append_op("spectral_norm",
                     inputs={"Weight": weight, "U": u, "V": v},
                     outputs={"Out": out},
                     attrs={"dim": dim, "power_iters": power_iters,
                            "eps": eps})
    return out


def selu(x, scale=None, alpha=None, name=None):
    attrs = {}
    if scale is not None:
        attrs["scale"] = scale
    if alpha is not None:
        attrs["alpha"] = alpha
    return _one("selu", {"X": x}, attrs, name=name)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _one("stanh", {"X": x},
                {"scale_a": scale_a, "scale_b": scale_b}, name=name)


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return _one("brelu", {"X": x}, {"t_min": t_min, "t_max": t_max},
                name=name)


def sign(x, name=None):
    return _one("sign", {"X": x}, name=name)


def elementwise_mod(x, y, axis=-1, name=None):
    return _one("elementwise_mod", {"X": x, "Y": y}, {"axis": axis},
                name=name)


def elementwise_floordiv(x, y, axis=-1, name=None):
    return _one("elementwise_floordiv", {"X": x, "Y": y}, {"axis": axis},
                name=name)


def sum(x):
    ins = list(x) if isinstance(x, (list, tuple)) else [x]
    return _one("sum", {"X": ins})


def rank(input):
    """Static rank as a constant tensor (reference nn.py rank)."""
    from .tensor import fill_constant

    return fill_constant(shape=[1], dtype="int32", value=len(input.shape))


def size(input):
    from .tensor import fill_constant

    return fill_constant(shape=[1], dtype="int64",
                         value=int(np.prod(
                             [d for d in input.shape if d != -1])))


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """Composite, reference nn.py image_resize_short: scale so the SHORT
    side equals out_short_len (static shapes at build time)."""
    h, w = int(input.shape[2]), int(input.shape[3])
    short = min(h, w)
    out_h = int(round(h * out_short_len / short))
    out_w = int(round(w * out_short_len / short))
    return _nn.image_resize(input, [out_h, out_w], resample)


def fsp_matrix(x, y):
    from ..contrib.slim.distillation import fsp_matrix as _fsp

    return _fsp(x, y)


def uniform_random_batch_size_like(input, shape, dtype="float32", min=-1.0,
                                   max=1.0, seed=0, input_dim_idx=0,
                                   output_dim_idx=0, name=None):
    helper = LayerHelper("uniform_random_batch_size_like", name=name)
    out = helper.create_variable_for_type_inference(dtype,
                                                    stop_gradient=True)
    sh = list(shape)
    sh[output_dim_idx] = -1  # batch-sized at runtime
    helper.append_op("uniform_random_batch_size_like",
                     inputs={"Input": input}, outputs={"Out": out},
                     attrs={"shape": sh, "min": min, "max": max,
                            "seed": seed, "dtype": dtype,
                            "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx})
    return out


def gaussian_random_batch_size_like(input, shape, dtype="float32",
                                    mean=0.0, std=1.0, seed=0,
                                    input_dim_idx=0, output_dim_idx=0,
                                    name=None):
    helper = LayerHelper("gaussian_random_batch_size_like", name=name)
    out = helper.create_variable_for_type_inference(dtype,
                                                    stop_gradient=True)
    sh = list(shape)
    sh[output_dim_idx] = -1
    helper.append_op("gaussian_random_batch_size_like",
                     inputs={"Input": input}, outputs={"Out": out},
                     attrs={"shape": sh, "mean": mean, "std": std,
                            "seed": seed, "dtype": dtype,
                            "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx})
    return out


# -- round-5 tail: deformable family, sequence tail, host callback ----------

def deformable_conv(input, offset, mask, num_filters, filter_size, stride=1,
                    padding=0, dilation=1, groups=1, deformable_groups=1,
                    im2col_step=64, param_attr=None, bias_attr=None,
                    modulated=True, name=None):
    """reference nn.py deformable_conv (v2 when modulated/mask given)."""
    helper = LayerHelper("deformable_conv", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    pair = lambda v: list(v) if isinstance(v, (list, tuple)) else [v, v]
    k = pair(filter_size)
    num_channels = input.shape[1]
    std = (2.0 / (k[0] * k[1] * num_channels)) ** 0.5
    w = helper.create_parameter(
        helper.param_attr,
        shape=[num_filters, num_channels // groups] + k,
        dtype=input.dtype, default_initializer=Normal(0.0, std))
    pre_bias = helper.create_variable_for_type_inference(input.dtype)
    ins = {"Input": input, "Offset": offset, "Filter": w}
    if modulated and mask is not None:
        ins["Mask"] = mask
    helper.append_op("deformable_conv", inputs=ins,
                     outputs={"Output": pre_bias},
                     attrs={"strides": pair(stride),
                            "paddings": pair(padding),
                            "dilations": pair(dilation), "groups": groups,
                            "deformable_groups": deformable_groups,
                            "im2col_step": im2col_step})
    return helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)


def deformable_roi_pooling(input, rois, trans, no_trans=False,
                           spatial_scale=1.0, group_size=(1, 1),
                           pooled_height=1, pooled_width=1,
                           part_size=None, sample_per_part=1, trans_std=0.1,
                           position_sensitive=True, rois_batch_idx=None,
                           name=None):
    helper = LayerHelper("deformable_psroi_pooling", name=name)
    if not position_sensitive:
        raise NotImplementedError(
            "deformable_roi_pooling(position_sensitive=False): use "
            "roi_align + trans offsets; the PS path is the deformable "
            "detectors' configuration")
    gs = list(group_size)
    out_dim = input.shape[1] // (gs[0] * gs[1])
    ps = list(part_size) if part_size is not None \
        else [pooled_height, pooled_width]
    o = helper.create_variable_for_type_inference(input.dtype)
    cnt = helper.create_variable_for_type_inference(input.dtype,
                                                    stop_gradient=True)
    ins = {"Input": input, "ROIs": rois, "Trans": trans}
    if rois_batch_idx is not None:
        ins["RoisBatchIdx"] = rois_batch_idx
    helper.append_op("deformable_psroi_pooling",
                     inputs=ins,
                     outputs={"Output": o, "TopCount": cnt},
                     attrs={"no_trans": no_trans,
                            "spatial_scale": spatial_scale,
                            "output_dim": int(out_dim), "group_size": gs,
                            "pooled_height": pooled_height,
                            "pooled_width": pooled_width, "part_size": ps,
                            "sample_per_part": sample_per_part,
                            "trans_std": trans_std})
    return o


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, rois_batch_idx=None, name=None):
    """``rois_batch_idx``: int tensor [R] mapping each ROI to its image in
    the batch (as roi_pool/roi_align accept); required when batch > 1."""
    return _one("psroi_pool", {"X": input, "ROIs": rois,
                               "RoisBatchIdx": rois_batch_idx},
                {"output_channels": output_channels,
                 "spatial_scale": spatial_scale,
                 "pooled_height": pooled_height,
                 "pooled_width": pooled_width}, name=name)


def prroi_pool(input, rois, output_channels=None, spatial_scale=1.0,
               pooled_height=1, pooled_width=1, batch_roi_nums=None,
               rois_batch_idx=None, name=None):
    """``batch_roi_nums``: int tensor [B] of ROI counts per image (the
    reference's prroi_pool signature) — counts must sum to the ROI count R,
    or trailing ROIs are silently mis-assigned (runtime data: unverifiable
    at trace time); ``rois_batch_idx``: int tensor [R] of per-ROI image
    indices. One of the two is required when batch > 1."""
    if batch_roi_nums is not None and rois_batch_idx is not None:
        raise ValueError(
            "prroi_pool: pass either batch_roi_nums or rois_batch_idx, "
            "not both — with conflicting values the op would silently "
            "follow rois_batch_idx")
    return _one("prroi_pool", {"X": input, "ROIs": rois,
                               "BatchRoINums": batch_roi_nums,
                               "RoisBatchIdx": rois_batch_idx},
                {"spatial_scale": spatial_scale,
                 "pooled_height": pooled_height,
                 "pooled_width": pooled_width}, name=name)


def merge_selected_rows(x, name=None):
    return _one("merge_selected_rows", {"X": x}, name=name)


def get_tensor_from_selected_rows(x, name=None):
    return _one("get_tensor_from_selected_rows", {"X": x}, name=name)


def continuous_value_model(input, cvm, use_cvm=True):
    return _one("cvm", {"X": input, "CVM": cvm}, {"use_cvm": use_cvm},
                out_slot="Y")


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1, remove_accidental_hits=True,
                                       use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None,
                                       seed=0):
    if use_customized_samples:
        raise NotImplementedError(
            "sampled_softmax_with_cross_entropy(use_customized_samples): "
            "host-side alias tables; use the log-uniform sampler")
    out_loss, _, _ = _one(
        "sampled_softmax_with_cross_entropy",
        {"Logits": logits, "Label": label},
        {"num_samples": num_samples, "seed": seed,
         "remove_accidental_hits": remove_accidental_hits},
        out_slot="Loss",
        extra_outs=[("Samples", "int64"), ("Probabilities", None)])
    return out_loss


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """reference nn.py py_func: host python inside the graph, via
    jax.pure_callback. ``out`` vars carry the result shapes/dtypes (they
    must be created with concrete shapes). backward_func is unsupported —
    the callback is opaque to autodiff."""
    from ..ops.misc2 import register_py_func

    if backward_func is not None:
        raise NotImplementedError(
            "py_func(backward_func=...): the host callback is opaque to "
            "vjp; compute the backward inside the program instead")
    helper = LayerHelper("py_func")
    xs = list(x) if isinstance(x, (list, tuple)) else [x]
    outs = list(out) if isinstance(out, (list, tuple)) else [out]
    fid = register_py_func(func)
    helper.append_op(
        "py_func", inputs={"X": xs}, outputs={"Out": outs},
        attrs={"func_id": fid,
               "out_shapes": [[int(d) for d in v.shape] for v in outs],
               "out_dtypes": [str(v.dtype) for v in outs]})
    return out


def resize_trilinear(input, out_shape=None, scale=None, name=None,
                     align_corners=True):
    if out_shape is None:
        d, h, w = [int(s * scale) for s in input.shape[2:]]
    else:
        d, h, w = [int(v) for v in out_shape]
    return _one("trilinear_interp", {"X": input},
                {"out_d": d, "out_h": h, "out_w": w,
                 "align_corners": align_corners}, name=name)


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """Composite (reference nn.py lstm_unit): one LSTM cell step built from
    fc over [x_t, h_prev] + the gate math."""
    concat_in = _nn.concat([x_t, hidden_t_prev], axis=1)
    hidden = hidden_t_prev.shape[-1]
    gates = _nn.fc(concat_in, 4 * hidden, param_attr=param_attr,
                   bias_attr=bias_attr)
    i, f, c_hat, o = _nn.split(gates, 4, dim=-1)
    f_act = _nn.sigmoid(_nn.scale(f, scale=1.0, bias=float(forget_bias)))
    new_cell = _nn.elementwise_add(
        _nn.elementwise_mul(f_act, cell_t_prev),
        _nn.elementwise_mul(_nn.sigmoid(i), _nn.tanh(c_hat)))
    new_hidden = _nn.elementwise_mul(_nn.sigmoid(o), _nn.tanh(new_cell))
    return new_hidden, new_cell


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """reference nn.py autoincreased_step_counter: a persistable counter
    advanced by ``step`` each iteration, one counter per name."""
    from ..framework import default_main_program, default_startup_program

    name = counter_name or "@STEP_COUNTER@"
    main = default_main_program().global_block
    startup = default_startup_program().global_block
    if not main.has_var(name):
        main.create_var(name=name, shape=(1,), dtype="int64",
                        persistable=True, stop_gradient=True)
        startup.create_var(name=name, shape=(1,), dtype="int64",
                           persistable=True)
        startup.append_op("fill_constant", outputs={"Out": name},
                          attrs={"shape": [1], "dtype": "int64",
                                 "value": float(begin) - float(step)})
        main.prepend_op("increment", inputs={"X": name},
                        outputs={"Out": name},
                        attrs={"step": float(step),
                               "__op_role__": "lr_sched"})
    return main.var(name)


def adaptive_pool3d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    if require_index:
        raise NotImplementedError("adaptive_pool3d(require_index=True)")
    d, h, w = [int(v) for v in input.shape[2:]]
    ps = _triple(pool_size)
    if d % ps[0] or h % ps[1] or w % ps[2]:
        raise NotImplementedError(
            "adaptive_pool3d: input spatial dims must divide pool_size on "
            "TPU (static windows); pad the input or pick a divisor size")
    k = [d // ps[0], h // ps[1], w // ps[2]]
    return pool3d(input, pool_size=k, pool_type=pool_type, pool_stride=k)


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, name=None,
                return_parent_idx=False):
    """reference nn.py beam_search — wrapper over the beam_search op the
    seq2seq model drives inside While (models/seq2seq.py)."""
    helper = LayerHelper("beam_search", name=name)
    sel_ids = helper.create_variable_for_type_inference("int64")
    sel_scores = helper.create_variable_for_type_inference(
        pre_scores.dtype)
    parent = helper.create_variable_for_type_inference("int64",
                                                       stop_gradient=True)
    ins = {"pre_ids": pre_ids, "pre_scores": pre_scores, "scores": scores}
    if ids is not None:
        ins["ids"] = ids
    helper.append_op("beam_search", inputs=ins,
                     outputs={"selected_ids": sel_ids,
                              "selected_scores": sel_scores,
                              "parent_idx": parent},
                     attrs={"beam_size": beam_size, "end_id": end_id,
                            "level": level,
                            "is_accumulated": is_accumulated})
    if return_parent_idx:
        return sel_ids, sel_scores, parent
    return sel_ids, sel_scores


def beam_search_decode(ids, scores, beam_size, end_id, name=None):
    """reference nn.py beam_search_decode: backtrack the per-step beam
    arrays into full sentences."""
    helper = LayerHelper("beam_search_decode", name=name)
    s_ids = helper.create_variable_for_type_inference("int64")
    s_scores = helper.create_variable_for_type_inference("float32")
    helper.append_op("beam_search_decode",
                     inputs={"Ids": ids, "Scores": scores},
                     outputs={"SentenceIds": s_ids,
                              "SentenceScores": s_scores},
                     attrs={"beam_size": beam_size, "end_id": end_id})
    return s_ids, s_scores


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=True):
    raise NotImplementedError(
        "filter_by_instag selects variable-size row subsets at runtime — "
        "dynamic shapes XLA cannot compile. Filter in the data pipeline "
        "(reader decorators) or mask rows with sequence_mask instead.")


def fused_decode_attention(q, k_new, v_new, cache_k, cache_v, positions,
                           scale=0.0, page_size=128, slot_mask=None,
                           name=None):
    """One autoregressive decode/verify chunk with the KV append fused in
    (ops/generation.py). q/k_new/v_new: [B, H, C, D] (C == 1 is the
    classic decode step; C <= 8 rides the chunk kernel); cache_k/cache_v:
    persistable paged caches [B, H, S_max, D]; positions: [B, 1] int —
    each sequence's length before this chunk. Query row i attends keys at
    positions < pos + i + 1 (causal within the chunk). ``slot_mask``
    [B, 1] (optional) keeps un-masked sequences' caches bit-untouched —
    the chunked-prefill / speculative dispatches run a subset of slots.
    The updated caches are written BACK INTO the cache vars (the single
    read+write op shape the donation proof needs), and the attended
    context [B, H, C, D] is returned. scale=0.0 means 1/sqrt(D)."""
    helper = LayerHelper("fused_decode_attention", name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    inputs = {"Q": q, "KNew": k_new, "VNew": v_new,
              "CacheK": cache_k, "CacheV": cache_v,
              "Positions": positions}
    if slot_mask is not None:
        inputs["SlotMask"] = slot_mask
    helper.append_op(
        "fused_decode_attention",
        inputs=inputs,
        outputs={"Out": out, "CacheKOut": cache_k, "CacheVOut": cache_v},
        attrs={"scale": float(scale), "page_size": int(page_size)})
    return out


def kv_cache_append(cache, new, positions, slot_mask=None, name=None):
    """Bulk KV write into a paged cache var (ops/generation.py): ``new``
    [B, H, L, D] lands at per-sequence ``positions`` [B, 1]; with
    ``slot_mask`` [B, 1] only masked sequences' rows change (the
    continuous-batching refill). Writes in place into ``cache`` (returns
    the same var)."""
    helper = LayerHelper("kv_cache_append", name=name)
    inputs = {"Cache": cache, "New": new, "Positions": positions}
    if slot_mask is not None:
        inputs["SlotMask"] = slot_mask
    helper.append_op("kv_cache_append", inputs=inputs,
                     outputs={"Out": cache})
    return cache


def sequence_gather(x, index, name=None):
    """Out[b] = x[b, index[b]] — gather one position per sequence along
    axis 1 (x: [B, S, ...], index: [B, 1] int, clamped into range)."""
    helper = LayerHelper("sequence_gather", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("sequence_gather", inputs={"X": x, "Index": index},
                     outputs={"Out": out})
    return out


def spec_accept(sampled, drafts, start, name=None):
    """Speculative-decoding accept rule (ops/generation.py): from the
    target's per-position tokens ``sampled`` [B, k] and the draft's
    proposals ``drafts`` [B, k-1], accept the longest agreeing prefix m
    plus the target's bonus token. Returns ``(accept_len [B,1],
    new_tok [B,1], new_pos [B,1])`` — all int64; ``new_pos = start + m +
    1`` is the committed sequence length."""
    helper = LayerHelper("spec_accept", name=name)
    accept = helper.create_variable_for_type_inference("int64")
    new_tok = helper.create_variable_for_type_inference("int64")
    new_pos = helper.create_variable_for_type_inference("int64")
    helper.append_op("spec_accept",
                     inputs={"Sampled": sampled, "Drafts": drafts,
                             "Start": start},
                     outputs={"AcceptLen": accept, "NewTok": new_tok,
                              "NewPos": new_pos})
    return accept, new_tok, new_pos


def sample_token(logits, strategy="greedy", temperature=1.0, top_k=0,
                 name=None):
    """Next-token selection from [B, V] logits -> [B, 1] int64
    (ops/generation.py): 'greedy' argmax, or seeded 'sample' with
    temperature and optional top-k truncation — deterministic for a fixed
    program.random_seed."""
    helper = LayerHelper("sample_token", name=name)
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op("sample_token", inputs={"Logits": logits},
                     outputs={"Out": out},
                     attrs={"strategy": strategy,
                            "temperature": float(temperature),
                            "top_k": int(top_k)})
    return out
