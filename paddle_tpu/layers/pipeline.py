"""layers.PipelineRegion — author a pipeline stage once, run it P times.

Reference counterpart: PipelineOptimizer's cut-list sections placed on
devices and fed through scope queues (reference optimizer.py:2781,
trainer.h:110, device_worker.h:267). The TPU-native shape of the same idea
(praxis/MaxText-style "repeat" pipelining): the user writes the repeated
stage ONCE as a sub-block; its parameters become [num_stages, ...]-stacked
persistable vars (named ``*.pp_stacked`` so the sharding rules place one
slice per 'pp' rank), and the `pipeline` op runs the GPipe microbatch
schedule over the mesh's 'pp' axis — or an equivalent lax.scan when there
is no pipeline axis (ops/pipeline_op.py).

Usage::

    pipe = layers.PipelineRegion(num_stages=4, num_microbatches=8)
    with pipe.stage(x) as s:
        w = s.param("w", [d, d])
        b = s.param("b", [d], is_bias=True)
        h = layers.gelu(layers.elementwise_add(layers.matmul(s.input, w), b))
        s.set_output(h)
    y = pipe.output          # [B, ...] — x's shape

Stage bodies use explicit s.param(...) + math layers; layers that create
their own parameters (fc, conv2d) would create per-call params instead of
stacked ones and cannot be used inside the region.
"""
from __future__ import annotations

import contextlib

from .. import unique_name
from ..framework import Variable
from ..initializer import Constant, Xavier
from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr

__all__ = ["PipelineRegion"]


class _StageHandle:
    def __init__(self, region):
        self._r = region

    @property
    def input(self) -> Variable:
        return self._r._in_var

    def param(self, name, shape, dtype="float32", initializer=None,
              is_bias=False):
        return self._r._make_param(name, shape, dtype, initializer, is_bias)

    def set_output(self, var: Variable):
        if tuple(var.shape) != tuple(self._r._in_var.shape):
            raise ValueError(
                f"pipeline stages must be shape-preserving (stage output "
                f"feeds the next stage's input): in {self._r._in_var.shape}"
                f" out {var.shape}")
        self._r._out_var = var


class PipelineRegion:
    def __init__(self, num_stages: int, num_microbatches: int = None,
                 name: str = None):
        self.num_stages = int(num_stages)
        self.num_microbatches = int(num_microbatches or num_stages)
        self._name = name or unique_name.generate("pipeline")
        self._stacked_names = []
        self._slice_names = []
        self._in_var = None
        self._out_var = None
        self.output = None

    @contextlib.contextmanager
    def stage(self, x: Variable):
        program = x.block.program
        parent = program.current_block()
        self._helper = LayerHelper(self._name)
        sub = program._create_block()
        self._sub = sub
        self._in_var = sub.create_var(
            name=unique_name.generate(f"{self._name}.in"),
            shape=x.shape, dtype=x.dtype, stop_gradient=False)
        try:
            yield _StageHandle(self)
        finally:
            program._rollback()
        if self._out_var is None:
            raise ValueError("pipeline stage never called set_output()")
        out = parent.create_var(
            name=unique_name.generate(f"{self._name}.out"),
            dtype=x.dtype, stop_gradient=False)
        parent.append_op(
            "pipeline",
            inputs={"X": [x.name], "StackedParams": list(self._stacked_names)},
            outputs={"Out": [out.name]},
            attrs={"sub_block": sub.idx,
                   "num_stages": self.num_stages,
                   "num_microbatches": self.num_microbatches,
                   "in_name": self._in_var.name,
                   "out_name": self._out_var.name,
                   "param_slices": list(self._slice_names)})
        self.output = out

    def _make_param(self, name, shape, dtype, initializer, is_bias):
        if initializer is None:
            initializer = Constant(0.0) if is_bias else Xavier()
        pname = f"{self._name}.{name}.pp_stacked"
        # the stacked parameter lives in the PARENT program (global block);
        # fan-in/out initializers see the per-stage trailing dims, not the
        # leading stage count, because Xavier on [P, d_in, d_out] treats
        # dim0 as a batch of receptive fields — acceptable: variance shifts
        # by 1/sqrt(P) only for rank-1 stacks
        stacked = self._helper.create_parameter(
            ParamAttr(name=pname), shape=[self.num_stages] + list(shape),
            dtype=dtype, default_initializer=initializer)
        self._stacked_names.append(stacked.name)
        sl = self._sub.create_var(
            name=unique_name.generate(f"{pname}.slice"),
            shape=list(shape), dtype=dtype, stop_gradient=False)
        self._slice_names.append(sl.name)
        return sl
