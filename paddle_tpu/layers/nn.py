"""Layer-building functions (reference: python/paddle/fluid/layers/nn.py —
190 functions; this module covers the core set, growing toward parity).

Every function follows the reference pattern: LayerHelper -> create params ->
append op(s) -> return out Variable. Nothing executes here; execution happens
when the Executor compiles the block to XLA.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..framework import Variable
from ..initializer import Constant, Normal, Xavier
from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr

__all__ = [
    "fc", "embedding", "conv2d", "conv2d_transpose", "pool2d", "batch_norm",
    "layer_norm", "group_norm", "instance_norm", "dropout", "softmax", "matmul",
    "relu", "cross_entropy", "softmax_with_cross_entropy", "mean", "mul",
    "reduce_sum", "reduce_mean", "reduce_max", "reduce_min", "reduce_prod",
    "concat", "reshape", "transpose", "split", "cast", "topk", "accuracy",
    "one_hot", "flatten", "squeeze", "unsqueeze", "stack", "expand", "gather",
    "scatter", "l2_normalize", "clip", "clip_by_norm", "elementwise_add",
    "elementwise_sub", "elementwise_mul", "elementwise_div", "elementwise_max",
    "elementwise_min", "elementwise_pow", "scale", "sums", "slice", "shape",
    "pad", "where", "arg_max", "arg_min", "argsort", "cumsum",
    "square_error_cost", "sigmoid_cross_entropy_with_logits", "huber_loss",
    "smooth_l1", "log_loss", "prelu", "leaky_relu", "relu6", "elu", "swish",
    "hard_swish", "hard_sigmoid", "soft_relu", "log", "sqrt", "square", "pow",
    "exp", "tanh", "sigmoid", "abs", "ceil", "floor", "cos", "sin", "round",
    "reciprocal", "reduce_all", "reduce_any", "increment", "equal", "not_equal",
    "less_than", "less_equal", "greater_than", "greater_equal", "logical_and",
    "logical_or", "logical_not", "logical_xor", "gelu", "erf", "log_softmax",
    "unstack", "resize_bilinear", "resize_nearest", "image_resize",
    "fused_multihead_attention", "linear_chain_crf", "crf_decoding",
    "nce", "hsigmoid", "edit_distance", "ctc_greedy_decoder", "chunk_eval",
    "cos_sim",
]


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None):
    """Fully-connected layer (reference nn.py:231): out = act(X W + b)."""
    helper = LayerHelper("fc", param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    inputs = input if isinstance(input, (list, tuple)) else [input]
    param_attrs = helper.multiple_param_attr(len(inputs))
    mul_results = []
    for inp, pa in zip(inputs, param_attrs):
        input_shape = inp.shape
        param_shape = [int(np.prod(input_shape[num_flatten_dims:]))] + [size]
        w = helper.create_parameter(pa, shape=param_shape, dtype=inp.dtype)
        tmp = helper.create_variable_for_type_inference(inp.dtype)
        helper.append_op("mul", inputs={"X": inp, "Y": w},
                         outputs={"Out": tmp},
                         attrs={"x_num_col_dims": num_flatten_dims,
                                "y_num_col_dims": 1})
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(inputs[0].dtype)
        helper.append_op("sum", inputs={"X": mul_results},
                         outputs={"Out": pre_bias})
    pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    return helper.append_activation(pre_act)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32", name=None):
    """reference nn.py embedding -> lookup_table op."""
    helper = LayerHelper("embedding", param_attr=param_attr, name=name)
    w = helper.create_parameter(helper.param_attr, shape=list(size),
                                dtype=dtype, is_bias=False,
                                default_initializer=Xavier())
    if is_distributed or is_sparse:
        # the PS-table / SelectedRows replacement (SURVEY §7): tag the table
        # so CompiledProgram row-shards it over the mesh — lookups become
        # XLA gathers with collectives (the all-to-all design) and the grad
        # arrives at each shard as a reduce-scatter instead of a dense
        # allreduce (reference parameter_prefetch.cc remote lookup)
        w.is_distributed = True
    out = helper.create_variable_for_type_inference(dtype)
    pad = -1 if padding_idx is None else (
        padding_idx if padding_idx >= 0 else size[0] + padding_idx)
    helper.append_op("lookup_table", inputs={"W": w, "Ids": input},
                     outputs={"Out": out},
                     attrs={"is_sparse": is_sparse,
                            "is_distributed": is_distributed,
                            "padding_idx": pad})
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCHW"):
    helper = LayerHelper("conv2d", param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    num_channels = input.shape[1]
    filter_size = _pair(filter_size)
    stride, padding, dilation = _pair(stride), _pair(padding), _pair(dilation)
    filter_shape = [num_filters, num_channels // groups] + list(filter_size)
    std = (2.0 / (filter_size[0] * filter_size[1] * num_channels)) ** 0.5
    w = helper.create_parameter(helper.param_attr, shape=filter_shape,
                                dtype=input.dtype,
                                default_initializer=Normal(0.0, std))
    pre_bias = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("conv2d", inputs={"Input": input, "Filter": w},
                     outputs={"Output": pre_bias},
                     attrs={"strides": list(stride), "paddings": list(padding),
                            "dilations": list(dilation), "groups": groups,
                            "use_cudnn": use_cudnn, "data_format": data_format})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("conv2d_transpose", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    num_channels = input.shape[1]
    filter_size = _pair(filter_size)
    stride, padding, dilation = _pair(stride), _pair(padding), _pair(dilation)
    filter_shape = [num_channels, num_filters // groups] + list(filter_size)
    w = helper.create_parameter(helper.param_attr, shape=filter_shape,
                                dtype=input.dtype,
                                default_initializer=Xavier())
    pre_bias = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("conv2d_transpose", inputs={"Input": input, "Filter": w},
                     outputs={"Output": pre_bias},
                     attrs={"strides": list(stride), "paddings": list(padding),
                            "dilations": list(dilation), "groups": groups})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, exclusive=True, name=None):
    helper = LayerHelper("pool2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("pool2d", inputs={"X": input}, outputs={"Out": out},
                     attrs={"pooling_type": pool_type,
                            "ksize": list(_pair(pool_size)),
                            "strides": list(_pair(pool_stride)),
                            "paddings": list(_pair(pool_padding)),
                            "global_pooling": global_pooling,
                            "ceil_mode": ceil_mode, "exclusive": exclusive,
                            "use_cudnn": use_cudnn})
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               name=None, moving_mean_name=None, moving_variance_name=None,
               use_global_stats=False):
    helper = LayerHelper("batch_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    c_axis = 1 if data_layout == "NCHW" else len(input.shape) - 1
    channels = input.shape[c_axis]
    dtype = input.dtype
    scale = helper.create_parameter(helper.param_attr, shape=[channels],
                                    dtype=dtype,
                                    default_initializer=Constant(1.0))
    from ..param_attr import ParamAttr

    bias_at = helper.bias_attr if helper.bias_attr is not False else ParamAttr()
    bias = helper.create_parameter(bias_at or ParamAttr(), shape=[channels],
                                   dtype=dtype, is_bias=True,
                                   default_initializer=Constant(0.0))
    mean = helper.create_parameter(
        ParamAttr(name=moving_mean_name, trainable=False),
        shape=[channels], dtype=dtype, default_initializer=Constant(0.0))
    mean.stop_gradient = True
    variance = helper.create_parameter(
        ParamAttr(name=moving_variance_name, trainable=False),
        shape=[channels], dtype=dtype, default_initializer=Constant(1.0))
    variance.stop_gradient = True
    y = helper.create_variable_for_type_inference(dtype)
    saved_mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    saved_var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op(
        "batch_norm",
        inputs={"X": input, "Scale": scale, "Bias": bias, "Mean": mean,
                "Variance": variance},
        outputs={"Y": y, "MeanOut": mean, "VarianceOut": variance,
                 "SavedMean": saved_mean, "SavedVariance": saved_var},
        attrs={"momentum": momentum, "epsilon": epsilon, "is_test": is_test,
               "data_layout": data_layout, "use_global_stats": use_global_stats})
    return helper.append_activation(y)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    helper = LayerHelper("layer_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    norm_shape = [int(np.prod(input.shape[begin_norm_axis:]))]
    inputs = {"X": input}
    if scale:
        s = helper.create_parameter(helper.param_attr, shape=norm_shape,
                                    dtype=dtype,
                                    default_initializer=Constant(1.0))
        inputs["Scale"] = s
    if shift:
        b = helper.create_parameter(
            helper.bias_attr if helper.bias_attr is not False else None,
            shape=norm_shape, dtype=dtype, is_bias=True,
            default_initializer=Constant(0.0))
        inputs["Bias"] = b
    y = helper.create_variable_for_type_inference(dtype)
    mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op("layer_norm", inputs=inputs,
                     outputs={"Y": y, "Mean": mean, "Variance": var},
                     attrs={"epsilon": epsilon,
                            "begin_norm_axis": begin_norm_axis})
    return helper.append_activation(y)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, name=None):
    helper = LayerHelper("group_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    channels = input.shape[1]
    inputs = {"X": input}
    s = helper.create_parameter(helper.param_attr, shape=[channels],
                                dtype=input.dtype,
                                default_initializer=Constant(1.0))
    b = helper.create_parameter(
        helper.bias_attr if helper.bias_attr is not False else None,
        shape=[channels], dtype=input.dtype, is_bias=True,
        default_initializer=Constant(0.0))
    inputs["Scale"], inputs["Bias"] = s, b
    y = helper.create_variable_for_type_inference(input.dtype)
    mean = helper.create_variable_for_type_inference(input.dtype, True)
    var = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("group_norm", inputs=inputs,
                     outputs={"Y": y, "Mean": mean, "Variance": var},
                     attrs={"epsilon": epsilon, "groups": groups})
    return helper.append_activation(y)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    helper = LayerHelper("instance_norm", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    channels = input.shape[1]
    s = helper.create_parameter(helper.param_attr, shape=[channels],
                                dtype=input.dtype,
                                default_initializer=Constant(1.0))
    b = helper.create_parameter(
        helper.bias_attr if helper.bias_attr is not False else None,
        shape=[channels], dtype=input.dtype, is_bias=True,
        default_initializer=Constant(0.0))
    y = helper.create_variable_for_type_inference(input.dtype)
    sm = helper.create_variable_for_type_inference(input.dtype, True)
    sv = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("instance_norm",
                     inputs={"X": input, "Scale": s, "Bias": b},
                     outputs={"Y": y, "SavedMean": sm, "SavedVariance": sv},
                     attrs={"epsilon": epsilon})
    return y


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    mask = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op("dropout", inputs={"X": x},
                     outputs={"Out": out, "Mask": mask},
                     attrs={"dropout_prob": dropout_prob, "is_test": is_test,
                            "fix_seed": seed is not None, "seed": seed or 0,
                            "dropout_implementation": dropout_implementation})
    return out


# -- simple wrappers --------------------------------------------------------

def _simple(op_type, x_slot="X", out_slot="Out", **attrs):
    def fn(x, name=None, **kw):
        helper = LayerHelper(op_type, name=name)
        a = dict(attrs)
        a.update(kw)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(op_type, inputs={x_slot: x}, outputs={out_slot: out},
                         attrs=a)
        return out

    fn.__name__ = op_type
    return fn


relu = _simple("relu")
sigmoid = _simple("sigmoid")
tanh = _simple("tanh")
exp = _simple("exp")
log = _simple("log")
sqrt = _simple("sqrt")
square = _simple("square")
abs = _simple("abs")
ceil = _simple("ceil")
floor = _simple("floor")
cos = _simple("cos")
sin = _simple("sin")
round = _simple("round")
reciprocal = _simple("reciprocal")
erf = _simple("erf")
gelu = _simple("gelu")
logical_not = _simple("logical_not")


def soft_relu(x, threshold=40.0, name=None):
    helper = LayerHelper("soft_relu", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("softplus", inputs={"X": x}, outputs={"Out": out})
    return out


def leaky_relu(x, alpha=0.02, name=None):
    return _simple("leaky_relu")(x, name=name, alpha=alpha)


def relu6(x, threshold=6.0, name=None):
    return _simple("relu6")(x, name=name, threshold=threshold)


def elu(x, alpha=1.0, name=None):
    return _simple("elu")(x, name=name, alpha=alpha)


def swish(x, beta=1.0, name=None):
    return _simple("swish")(x, name=name, beta=beta)


hard_swish = _simple("hard_swish")
hard_sigmoid = _simple("hard_sigmoid")


def pow(x, factor=1.0, name=None):
    return _simple("pow")(x, name=name, factor=factor)


def prelu(x, mode="all", param_attr=None, name=None):
    helper = LayerHelper("prelu", param_attr=param_attr, name=name)
    alpha_shape = [1] if mode == "all" else (
        [x.shape[1]] if mode == "channel" else list(x.shape[1:]))
    alpha = helper.create_parameter(helper.param_attr, shape=alpha_shape,
                                    dtype=x.dtype,
                                    default_initializer=Constant(0.25))
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("prelu", inputs={"X": x, "Alpha": alpha},
                     outputs={"Out": out}, attrs={"mode": mode})
    return out


def softmax(input, axis=-1, use_cudnn=False, name=None):
    return _simple("softmax")(input, name=name, axis=axis)


def log_softmax(input, axis=-1, name=None):
    return _simple("log_softmax")(input, name=name, axis=axis)


def mean(x, name=None):
    return _simple("mean")(x, name=name)


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("matmul", inputs={"X": x, "Y": y}, outputs={"Out": out},
                     attrs={"transpose_X": transpose_x,
                            "transpose_Y": transpose_y, "alpha": alpha})
    return out


def fused_multihead_attention(q, k, v, bias_qk=None, causal=False,
                              scale=0.0, attn_dropout=0.0, is_test=False,
                              sequence_parallel=False, name=None):
    """Fused multi-head attention (the reference `operators/fused/` role,
    here a Pallas flash kernel on TPU — ops/fused_attention.py).

    q/k/v: [B, num_heads, S, head_dim]; bias_qk: optional additive key bias
    [B, S] or [B, 1, 1, S] (padding-mask encoding). Returns the same shape
    as q. scale=0.0 means 1/sqrt(head_dim).

    sequence_parallel=True: when the program runs under a mesh with an
    'sp' axis (CompiledProgram places=mesh), attention runs as ring
    attention over that axis — sequence/context parallelism for sequences
    too long for one chip. bias_qk/attn_dropout are unsupported on that
    path; without an sp axis it degrades to the plain fused path."""
    helper = LayerHelper("fused_multihead_attention", name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    inputs = {"Q": q, "K": k, "V": v}
    if bias_qk is not None:
        inputs["BiasQK"] = bias_qk
    helper.append_op("fused_multihead_attention", inputs=inputs,
                     outputs={"Out": out},
                     attrs={"causal": causal, "scale": scale,
                            "attn_dropout": attn_dropout,
                            "is_test": is_test,
                            "sequence_parallel": sequence_parallel})
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("mul", inputs={"X": x, "Y": y}, outputs={"Out": out},
                     attrs={"x_num_col_dims": x_num_col_dims,
                            "y_num_col_dims": y_num_col_dims})
    return out


def _elementwise(op_type):
    def fn(x, y, axis=-1, act=None, name=None):
        helper = LayerHelper(op_type, act=act, name=name)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(op_type, inputs={"X": x, "Y": y},
                         outputs={"Out": out}, attrs={"axis": axis})
        return helper.append_activation(out)

    fn.__name__ = op_type
    return fn


elementwise_add = _elementwise("elementwise_add")
elementwise_sub = _elementwise("elementwise_sub")
elementwise_mul = _elementwise("elementwise_mul")
elementwise_div = _elementwise("elementwise_div")
elementwise_max = _elementwise("elementwise_max")
elementwise_min = _elementwise("elementwise_min")
elementwise_pow = _elementwise("elementwise_pow")
equal = _elementwise("equal")
not_equal = _elementwise("not_equal")
less_than = _elementwise("less_than")
less_equal = _elementwise("less_equal")
greater_than = _elementwise("greater_than")
greater_equal = _elementwise("greater_equal")
logical_and = _elementwise("logical_and")
logical_or = _elementwise("logical_or")
logical_xor = _elementwise("logical_xor")


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", act=act, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("scale", inputs={"X": x}, outputs={"Out": out},
                     attrs={"scale": float(scale), "bias": float(bias),
                            "bias_after_scale": bias_after_scale})
    return helper.append_activation(out)


def _reduce(op_type):
    def fn(input, dim=None, keep_dim=False, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(input.dtype)
        if dim is None:
            attrs = {"dim": [0], "keep_dim": keep_dim, "reduce_all": True}
        else:
            dims = dim if isinstance(dim, (list, tuple)) else [dim]
            attrs = {"dim": list(dims), "keep_dim": keep_dim,
                     "reduce_all": False}
        helper.append_op(op_type, inputs={"X": input}, outputs={"Out": out},
                         attrs=attrs)
        return out

    fn.__name__ = op_type
    return fn


reduce_sum = _reduce("reduce_sum")
reduce_mean = _reduce("reduce_mean")
reduce_max = _reduce("reduce_max")
reduce_min = _reduce("reduce_min")
reduce_prod = _reduce("reduce_prod")
reduce_all = _reduce("reduce_all")
reduce_any = _reduce("reduce_any")


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("cross_entropy", inputs={"X": input, "Label": label},
                     outputs={"Y": out},
                     attrs={"soft_label": soft_label,
                            "ignore_index": ignore_index})
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax_out = helper.create_variable_for_type_inference(logits.dtype)
    loss = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op("softmax_with_cross_entropy",
                     inputs={"Logits": logits, "Label": label},
                     outputs={"Softmax": softmax_out, "Loss": loss},
                     attrs={"soft_label": soft_label,
                            "ignore_index": ignore_index, "axis": axis,
                            "numeric_stable_mode": numeric_stable_mode})
    if return_softmax:
        return loss, softmax_out
    return loss


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("square_error_cost", inputs={"X": input, "Y": label},
                     outputs={"Out": out})
    return out


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None,
                                      normalize=False):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("sigmoid_cross_entropy_with_logits",
                     inputs={"X": x, "Label": label}, outputs={"Out": out},
                     attrs={"ignore_index": ignore_index,
                            "normalize": normalize})
    return out


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss")
    out = helper.create_variable_for_type_inference(input.dtype)
    residual = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("huber_loss", inputs={"X": input, "Y": label},
                     outputs={"Out": out, "Residual": residual},
                     attrs={"delta": delta})
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1_loss")
    out = helper.create_variable_for_type_inference(x.dtype)
    diff = helper.create_variable_for_type_inference(x.dtype, True)
    inputs = {"X": x, "Y": y}
    if inside_weight is not None:
        inputs["InsideWeight"] = inside_weight
    if outside_weight is not None:
        inputs["OutsideWeight"] = outside_weight
    helper.append_op("smooth_l1_loss", inputs=inputs,
                     outputs={"Out": out, "Diff": diff},
                     attrs={"sigma": sigma or 1.0})
    return out


def log_loss(input, label, epsilon=1e-4, name=None):
    helper = LayerHelper("log_loss", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("log_loss", inputs={"Predicted": input, "Labels": label},
                     outputs={"Loss": out}, attrs={"epsilon": epsilon})
    return out


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    values = helper.create_variable_for_type_inference(input.dtype)
    indices = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("top_k", inputs={"X": input},
                     outputs={"Out": values, "Indices": indices},
                     attrs={"k": k})
    return values, indices


def accuracy(input, label, k=1, correct=None, total=None):
    """reference layers/metric_op.py:accuracy."""
    helper = LayerHelper("accuracy")
    topk_out, topk_indices = topk(input, k=k)
    acc_out = helper.create_variable_for_type_inference("float32", True)
    correct = correct or helper.create_variable_for_type_inference("int32", True)
    total = total or helper.create_variable_for_type_inference("int32", True)
    helper.append_op("accuracy",
                     inputs={"Out": topk_out, "Indices": topk_indices,
                             "Label": label},
                     outputs={"Accuracy": acc_out, "Correct": correct,
                              "Total": total})
    return acc_out


def one_hot(input, depth, allow_out_of_range=False):
    helper = LayerHelper("one_hot")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op("one_hot", inputs={"X": input}, outputs={"Out": out},
                     attrs={"depth": depth, "dtype": "float32"})
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op("concat", inputs={"X": input}, outputs={"Out": out},
                     attrs={"axis": axis})
    return out


def sums(input, out=None):
    helper = LayerHelper("sum")
    out = out or helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op("sum", inputs={"X": input}, outputs={"Out": out})
    return out


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape2", act=act, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op("reshape2", inputs={"X": x},
                     outputs={"Out": out, "XShape": xshape},
                     attrs={"shape": list(shape)})
    return helper.append_activation(out)


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op("transpose2", inputs={"X": x},
                     outputs={"Out": out, "XShape": xshape},
                     attrs={"axis": list(perm)})
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    dim = dim if dim >= 0 else dim + len(input.shape)
    if isinstance(num_or_sections, int):
        num = num_or_sections
        attrs = {"num": num, "sections": [], "axis": dim}
        n_out = num
    else:
        attrs = {"num": 0, "sections": list(num_or_sections), "axis": dim}
        n_out = len(num_or_sections)
    outs = [helper.create_variable_for_type_inference(input.dtype)
            for _ in range(n_out)]
    helper.append_op("split", inputs={"X": input}, outputs={"Out": outs},
                     attrs=attrs)
    return outs


def cast(x, dtype):
    from ..core.types import canonical_dtype

    helper = LayerHelper("cast")
    dtype = canonical_dtype(dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("cast", inputs={"X": x}, outputs={"Out": out},
                     attrs={"in_dtype": x.dtype, "out_dtype": dtype})
    return out


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op("flatten2", inputs={"X": x},
                     outputs={"Out": out, "XShape": xshape},
                     attrs={"axis": axis})
    return out


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze2", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("squeeze2", inputs={"X": input},
                     outputs={"Out": out, "XShape": xshape},
                     attrs={"axes": list(axes)})
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze2", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("unsqueeze2", inputs={"X": input},
                     outputs={"Out": out, "XShape": xshape},
                     attrs={"axes": list(axes)})
    return out


def stack(x, axis=0):
    helper = LayerHelper("stack")
    out = helper.create_variable_for_type_inference(x[0].dtype)
    helper.append_op("stack", inputs={"X": x}, outputs={"Y": out},
                     attrs={"axis": axis})
    return out


def unstack(x, axis=0, num=None):
    helper = LayerHelper("unstack")
    if num is None:
        num = x.shape[axis]
    outs = [helper.create_variable_for_type_inference(x.dtype)
            for _ in range(num)]
    helper.append_op("unstack", inputs={"X": x}, outputs={"Y": outs},
                     attrs={"axis": axis, "num": num})
    return outs


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("expand", inputs={"X": x}, outputs={"Out": out},
                     attrs={"expand_times": list(expand_times)})
    return out


def gather(input, index):
    helper = LayerHelper("gather")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("gather", inputs={"X": input, "Index": index},
                     outputs={"Out": out})
    return out


def scatter(input, index, updates, overwrite=True, name=None):
    helper = LayerHelper("scatter", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("scatter",
                     inputs={"X": input, "Ids": index, "Updates": updates},
                     outputs={"Out": out}, attrs={"overwrite": overwrite})
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    norm = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op("l2_normalize", inputs={"X": x},
                     outputs={"Out": out, "Norm": norm},
                     attrs={"axis": axis, "epsilon": epsilon})
    return out


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("clip", inputs={"X": x}, outputs={"Out": out},
                     attrs={"min": min, "max": max})
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("clip_by_norm", inputs={"X": x}, outputs={"Out": out},
                     attrs={"max_norm": max_norm})
    return out


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("slice", inputs={"Input": input}, outputs={"Out": out},
                     attrs={"axes": list(axes), "starts": list(starts),
                            "ends": list(ends), "decrease_axis": []})
    return out


def shape(input):
    helper = LayerHelper("shape")
    out = helper.create_variable_for_type_inference("int32", True)
    helper.append_op("shape", inputs={"Input": input}, outputs={"Out": out})
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("pad", inputs={"X": x}, outputs={"Out": out},
                     attrs={"paddings": list(paddings),
                            "pad_value": pad_value})
    return out


def where(condition, x, y):
    helper = LayerHelper("where")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("where", inputs={"Condition": condition, "X": x, "Y": y},
                     outputs={"Out": out})
    return out


def arg_max(x, axis=0, name=None):
    helper = LayerHelper("arg_max", name=name)
    out = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("arg_max", inputs={"X": x}, outputs={"Out": out},
                     attrs={"axis": axis})
    return out


def arg_min(x, axis=0, name=None):
    helper = LayerHelper("arg_min", name=name)
    out = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("arg_min", inputs={"X": x}, outputs={"Out": out},
                     attrs={"axis": axis})
    return out


def argsort(input, axis=-1, descending=False, name=None):
    helper = LayerHelper("argsort", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    idx = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("argsort", inputs={"X": input},
                     outputs={"Out": out, "Indices": idx},
                     attrs={"axis": axis, "descending": descending})
    return out, idx


def cumsum(x, axis=-1, exclusive=False, reverse=False):
    helper = LayerHelper("cumsum")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("cumsum", inputs={"X": x}, outputs={"Out": out},
                     attrs={"axis": axis, "exclusive": exclusive,
                            "reverse": reverse})
    return out


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    out = x if in_place else helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("increment", inputs={"X": x}, outputs={"Out": out},
                     attrs={"step": float(value)})
    return out


def image_resize(input, out_shape, resample="BILINEAR", name=None):
    op = "bilinear_interp" if resample.upper() == "BILINEAR" else "interpolate_nearest"
    helper = LayerHelper(op, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(op, inputs={"X": input}, outputs={"Out": out},
                     attrs={"out_h": int(out_shape[0]),
                            "out_w": int(out_shape[1])})
    return out


def resize_bilinear(input, out_shape=None, name=None, align_corners=True):
    return image_resize(input, out_shape, "BILINEAR", name)


def resize_nearest(input, out_shape=None, name=None, align_corners=False):
    return image_resize(input, out_shape, "NEAREST", name)


def _pair(v):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v, v)


# -- structured prediction / candidate sampling ----------------------------
# reference nn.py:1412 linear_chain_crf, :1528 crf_decoding, :5080 nce,
# :5216 hsigmoid, :4689 edit_distance, :4816 ctc_greedy_decoder,
# layers/metric_op chunk_eval. Sequence inputs ride the padded + @LOD
# lengths encoding; the Length op input is wired from the companion var.


def _seq_len_or_none(v):
    from .sequence import seq_len_var

    try:
        return seq_len_var(v)
    except ValueError:
        return None


def linear_chain_crf(input, label, param_attr=None, length=None):
    """CRF negative log-likelihood (reference nn.py:1412). ``input`` is the
    padded [batch, time, tags] emission; the transition parameter is
    [tags+2, tags] (row 0 start, row 1 end). Returns the per-sequence cost
    ([batch, 1]) the reference calls log_likelihood."""
    helper = LayerHelper("linear_chain_crf", param_attr=param_attr)
    size = input.shape[-1]
    transition = helper.create_parameter(
        helper.param_attr, shape=[size + 2, size], dtype=input.dtype)
    alpha = helper.create_variable_for_type_inference(input.dtype)
    e_exps = helper.create_variable_for_type_inference(input.dtype)
    t_exps = helper.create_variable_for_type_inference(input.dtype)
    ll = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"Emission": input, "Transition": transition, "Label": label}
    length = length or _seq_len_or_none(input) or _seq_len_or_none(label)
    if length is not None:
        inputs["Length"] = length
    helper.append_op("linear_chain_crf", inputs=inputs,
                     outputs={"Alpha": alpha, "EmissionExps": e_exps,
                              "TransitionExps": t_exps,
                              "LogLikelihood": ll})
    return ll


def crf_decoding(input, param_attr, label=None, length=None):
    """Viterbi decode with the trained transition parameter (reference
    nn.py:1528). With ``label``, returns the 0/1 correctness mask."""
    helper = LayerHelper("crf_decoding")
    transition = helper.main_program.global_block.var(param_attr.name)
    path = helper.create_variable_for_type_inference("int64")
    inputs = {"Emission": input, "Transition": transition}
    if label is not None:
        inputs["Label"] = label
    length = length or _seq_len_or_none(input)
    if length is not None:
        inputs["Length"] = length
    helper.append_op("crf_decoding", inputs=inputs,
                     outputs={"ViterbiPath": path})
    return path


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (reference nn.py:5080)."""
    helper = LayerHelper("nce", param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    dim = input.shape[-1]
    w = helper.create_parameter(helper.param_attr,
                                shape=[num_total_classes, dim],
                                dtype=input.dtype)
    sampler_id = {"uniform": 0, "log_uniform": 1, "custom_dist": 2}[sampler]
    if custom_dist is not None:
        raise NotImplementedError(
            "nce(custom_dist=...): alias-table sampling is host-side; use "
            "sampler='uniform' or 'log_uniform' on TPU")
    cost = helper.create_variable_for_type_inference(input.dtype)
    s_logits = helper.create_variable_for_type_inference(input.dtype)
    s_labels = helper.create_variable_for_type_inference("int64")
    inputs = {"Input": input, "Label": label, "Weight": w}
    if sample_weight is not None:
        inputs["SampleWeight"] = sample_weight
    if bias_attr is not False:
        b = helper.create_parameter(helper.bias_attr or ParamAttr(),
                                    shape=[num_total_classes],
                                    dtype=input.dtype,
                                    default_initializer=Constant(0.0))
        inputs["Bias"] = b
    helper.append_op(
        "nce", inputs=inputs,
        outputs={"Cost": cost, "SampleLogits": s_logits,
                 "SampleLabels": s_labels},
        attrs={"num_total_classes": int(num_total_classes),
               "num_neg_samples": int(num_neg_samples or 10),
               "sampler": sampler_id, "seed": seed, "is_sparse": is_sparse})
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None, is_custom=False,
             is_sparse=False):
    """Hierarchical sigmoid (reference nn.py:5216): complete-binary-tree
    softmax factorization, or a custom tree via path_table/path_code."""
    helper = LayerHelper("hierarchical_sigmoid", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dim = input.shape[-1]
    if is_custom and (path_table is None or path_code is None):
        raise ValueError("is_custom=True needs path_table AND path_code")
    num_w = num_classes - 1 if not is_custom else num_classes
    w = helper.create_parameter(helper.param_attr, shape=[num_w, dim],
                                dtype=input.dtype)
    cost = helper.create_variable_for_type_inference(input.dtype)
    pre_out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": input, "W": w, "Label": label}
    if path_table is not None:
        inputs["PathTable"] = path_table
        inputs["PathCode"] = path_code
    if bias_attr is not False:
        b = helper.create_parameter(helper.bias_attr or ParamAttr(),
                                    shape=[num_w], dtype=input.dtype,
                                    default_initializer=Constant(0.0))
        inputs["Bias"] = b
    helper.append_op("hierarchical_sigmoid", inputs=inputs,
                     outputs={"Out": cost, "PreOut": pre_out},
                     attrs={"num_classes": int(num_classes),
                            "is_sparse": is_sparse})
    return cost


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    """Levenshtein distance per sequence pair (reference nn.py:4689).
    Returns (distance [batch, 1], sequence_num [1])."""
    helper = LayerHelper("edit_distance")
    if ignored_tokens:
        raise NotImplementedError(
            "edit_distance(ignored_tokens=...): pre-filter with "
            "layers.sequence_erase, the reference composes the same way")
    out = helper.create_variable_for_type_inference("float32")
    seq_num = helper.create_variable_for_type_inference("int64")
    inputs = {"Hyps": input, "Refs": label}
    input_length = input_length or _seq_len_or_none(input)
    label_length = label_length or _seq_len_or_none(label)
    if input_length is not None:
        inputs["HypsLength"] = input_length
    if label_length is not None:
        inputs["RefsLength"] = label_length
    helper.append_op("edit_distance", inputs=inputs,
                     outputs={"Out": out, "SequenceNum": seq_num},
                     attrs={"normalized": normalized})
    return out, seq_num


def ctc_greedy_decoder(input, blank, input_length=None, name=None):
    """Greedy CTC decode (reference nn.py:4816): argmax per frame, then
    merge repeats + drop blanks. Returns (decoded [batch, time] padded,
    lengths [batch]) — the padded form of the reference's LoD output."""
    helper = LayerHelper("ctc_greedy_decoder", name=name)
    argmax = helper.create_variable_for_type_inference("int64")
    helper.append_op("arg_max", inputs={"X": input}, outputs={"Out": argmax},
                     attrs={"axis": -1})
    decoded = helper.create_variable_for_type_inference("int64")
    out_len = helper.create_variable_for_type_inference("int32")
    inputs = {"Input": argmax}
    input_length = input_length or _seq_len_or_none(input)
    if input_length is not None:
        inputs["InputLength"] = input_length
    helper.append_op("ctc_align", inputs=inputs,
                     outputs={"Output": decoded, "OutputLength": out_len},
                     attrs={"blank": int(blank), "merge_repeated": True})
    from .sequence import _make_lod_out

    lod = _make_lod_out(helper, decoded)
    helper.append_op("assign", inputs={"X": out_len}, outputs={"Out": lod})
    return decoded, out_len


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    """Chunk-level precision/recall/F1 for tagging (reference
    layers/nn.py chunk_eval). Returns the reference's 6-tuple."""
    helper = LayerHelper("chunk_eval")
    precision = helper.create_variable_for_type_inference("float32")
    recall = helper.create_variable_for_type_inference("float32")
    f1 = helper.create_variable_for_type_inference("float32")
    n_infer = helper.create_variable_for_type_inference("int64")
    n_label = helper.create_variable_for_type_inference("int64")
    n_correct = helper.create_variable_for_type_inference("int64")
    inputs = {"Inference": input, "Label": label}
    seq_length = seq_length or _seq_len_or_none(input) \
        or _seq_len_or_none(label)
    if seq_length is not None:
        inputs["SeqLength"] = seq_length
    helper.append_op(
        "chunk_eval", inputs=inputs,
        outputs={"Precision": precision, "Recall": recall, "F1-Score": f1,
                 "NumInferChunks": n_infer, "NumLabelChunks": n_label,
                 "NumCorrectChunks": n_correct},
        attrs={"num_chunk_types": int(num_chunk_types),
               "chunk_scheme": chunk_scheme,
               "excluded_chunk_types": excluded_chunk_types or []})
    return precision, recall, f1, n_infer, n_label, n_correct


def cos_sim(X, Y):
    """Cosine similarity along dim 1 (reference nn.py:1360)."""
    helper = LayerHelper("cos_sim")
    out_v = helper.create_variable_for_type_inference(X.dtype)
    xnorm = helper.create_variable_for_type_inference(X.dtype)
    ynorm = helper.create_variable_for_type_inference(X.dtype)
    helper.append_op("cos_sim", inputs={"X": X, "Y": Y},
                     outputs={"Out": out_v, "XNorm": xnorm, "YNorm": ynorm})
    return out_v
