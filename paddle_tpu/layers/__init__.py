"""fluid.layers-compatible namespace."""
from . import math_op_patch  # noqa: F401
from .io import data  # noqa: F401
from .nn import *  # noqa: F401,F403
from .nn2 import *  # noqa: F401,F403
from .tensor import (assign, create_global_var, create_tensor,  # noqa: F401
                     fill_constant, fill_constant_batch_size_like,
                     gaussian_random, linspace, ones, ones_like,
                     uniform_random, zeros, zeros_like)
from . import nn  # noqa: F401
from . import nn2  # noqa: F401
from .control_flow import (While, Switch, IfElse, StaticRNN,  # noqa: F401
                           array_length, array_read, array_write, cond,
                           create_array, tensor_array_to_tensor)
from . import control_flow  # noqa: F401
from . import tensor  # noqa: F401
from .sequence import (sequence_pool, sequence_softmax,  # noqa: F401
                       sequence_reverse, sequence_expand, sequence_concat,
                       sequence_reshape, sequence_expand_as,
                       sequence_scatter, lod_reset, lod_append,
                       sequence_pad, sequence_unpad, sequence_slice,
                       sequence_erase, sequence_enumerate, sequence_conv,
                       sequence_first_step, sequence_last_step, sequence_mask)
from . import sequence  # noqa: F401
from .rnn import (DynamicRNN, dynamic_lstm, dynamic_lstmp,  # noqa: F401
                  dynamic_gru, gru_unit, lstm, warpctc)
from . import rnn  # noqa: F401
from . import detection  # noqa: F401
from .pipeline import PipelineRegion  # noqa: F401
from . import distributions  # noqa: F401
from .learning_rate_scheduler import (cosine_decay, exponential_decay,  # noqa: F401
                                      inverse_time_decay, linear_lr_warmup,
                                      natural_exp_decay, noam_decay,
                                      piecewise_decay, polynomial_decay)
