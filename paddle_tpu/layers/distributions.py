"""Probability distributions (reference python/paddle/fluid/layers/
distributions.py: Uniform, Normal, Categorical, MultivariateNormalDiag).

Graph-building classes: every method appends ops, so samples ride the
executor's per-op PRNG keys and log_prob/entropy/kl are differentiable
graph expressions like any layer output."""
from __future__ import annotations

import math

import numpy as np

from . import nn, tensor
from ..framework import Variable

__all__ = ["Uniform", "Normal", "Categorical"]


def _as_var(v, like=None):
    if isinstance(v, Variable):
        return v
    arr = np.asarray(v, np.float32)
    return tensor.assign(arr.reshape(arr.shape or (1,)))


class Uniform:
    """U(low, high) elementwise."""

    def __init__(self, low, high):
        self.low = _as_var(low)
        self.high = _as_var(high)

    def sample(self, shape, seed=0):
        u = tensor.uniform_random(shape, min=0.0, max=1.0, seed=seed)
        return nn.elementwise_add(
            nn.elementwise_mul(u, nn.elementwise_sub(self.high, self.low)),
            self.low)

    def log_prob(self, value):
        """-log(high-low) in support, -inf-ish (log 0) outside (reference
        Uniform.log_prob gates with lb*ub indicator)."""
        rng = nn.elementwise_sub(self.high, self.low)
        inside_lo = nn.cast(nn.greater_equal(value, self.low), "float32")
        inside_hi = nn.cast(nn.less_than(value, self.high), "float32")
        ind = nn.elementwise_mul(inside_lo, inside_hi)
        return nn.log(nn.elementwise_div(ind, rng))

    def entropy(self):
        return nn.log(nn.elementwise_sub(self.high, self.low))


class Normal:
    """N(loc, scale) elementwise."""

    def __init__(self, loc, scale):
        self.loc = _as_var(loc)
        self.scale = _as_var(scale)

    def sample(self, shape, seed=0):
        z = tensor.gaussian_random(shape, mean=0.0, std=1.0, seed=seed)
        return nn.elementwise_add(nn.elementwise_mul(z, self.scale),
                                  self.loc)

    def log_prob(self, value):
        var = nn.elementwise_mul(self.scale, self.scale)
        d = nn.elementwise_sub(value, self.loc)
        quad = nn.elementwise_div(nn.elementwise_mul(d, d),
                                  nn.scale(var, scale=2.0))
        log_z = nn.elementwise_add(
            nn.log(self.scale),
            tensor.assign(np.array([0.5 * math.log(2 * math.pi)],
                                   np.float32)))
        return nn.scale(nn.elementwise_add(quad, log_z), scale=-1.0)

    def entropy(self):
        return nn.elementwise_add(
            nn.log(self.scale),
            tensor.assign(np.array([0.5 + 0.5 * math.log(2 * math.pi)],
                                   np.float32)))

    def kl_divergence(self, other: "Normal"):
        """KL(self || other), the closed form."""
        var_ratio = nn.elementwise_div(self.scale, other.scale)
        var_ratio = nn.elementwise_mul(var_ratio, var_ratio)
        d = nn.elementwise_sub(self.loc, other.loc)
        t1 = nn.elementwise_div(
            nn.elementwise_mul(d, d),
            nn.elementwise_mul(other.scale, other.scale))
        inner = nn.elementwise_sub(
            nn.elementwise_add(var_ratio, t1),
            tensor.assign(np.array([1.0], np.float32)))
        return nn.scale(
            nn.elementwise_sub(inner, nn.log(var_ratio)), scale=0.5)


class Categorical:
    """Categorical over the last axis of ``logits``."""

    def __init__(self, logits):
        self.logits = logits

    def _log_p(self):
        return nn.log_softmax(self.logits)

    def entropy(self):
        logp = self._log_p()
        p = nn.softmax(self.logits)
        return nn.scale(nn.reduce_sum(nn.elementwise_mul(p, logp),
                                      dim=[-1]), scale=-1.0)

    def log_prob(self, value):
        """value: int ids [..., 1] or [...]."""
        logp = self._log_p()
        oh = nn.one_hot(value, depth=int(self.logits.shape[-1]))
        return nn.reduce_sum(nn.elementwise_mul(logp, oh), dim=[-1])

    def kl_divergence(self, other: "Categorical"):
        p = nn.softmax(self.logits)
        diff = nn.elementwise_sub(self._log_p(), other._log_p())
        return nn.reduce_sum(nn.elementwise_mul(p, diff), dim=[-1])
