"""RNN layers: dynamic_lstm, dynamic_gru, gru_unit, lstm (cudnn), warpctc.

Reference: python/paddle/fluid/layers/nn.py dynamic_lstm (:443),
dynamic_gru (:743), gru_unit (:846), lstm (cudnn_lstm wrapper, :475 in
later trees), warpctc (:4324). Sequence inputs follow the padded+lengths
encoding (layers/sequence.py)."""
from __future__ import annotations

import numpy as np

from ..layer_helper import LayerHelper
from .sequence import _make_lod_out, lod_suffix, seq_len_var

__all__ = ["dynamic_lstm", "dynamic_lstmp", "dynamic_gru", "gru_unit",
           "lstm", "warpctc"]


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None):
    """input: [B, T, 4H] pre-projected (reference contract); size = 4H."""
    helper = LayerHelper("dynamic_lstm", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    hidden_dim = size // 4
    w = helper.create_parameter(helper.param_attr,
                                shape=[hidden_dim, 4 * hidden_dim],
                                dtype=dtype)
    bias_size = 7 * hidden_dim if use_peepholes else 4 * hidden_dim
    b = helper.create_parameter(helper.bias_attr, shape=[1, bias_size],
                                dtype=dtype, is_bias=True)
    hidden = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    lod = _make_lod_out(helper, hidden)
    ins = {"Input": input, "Weight": w, "Bias": b,
           "SeqLen": seq_len_var(input)}
    if h_0 is not None:
        ins["H0"] = h_0
    if c_0 is not None:
        ins["C0"] = c_0
    helper.append_op("lstm", inputs=ins,
                     outputs={"Hidden": hidden, "Cell": cell},
                     attrs={"use_peepholes": use_peepholes,
                            "is_reverse": is_reverse,
                            "gate_activation": gate_activation,
                            "cell_activation": cell_activation,
                            "candidate_activation": candidate_activation})
    helper.append_op("assign", inputs={"X": seq_len_var(input)},
                     outputs={"Out": lod})
    return hidden, cell


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=True, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  dtype="float32", name=None, h_0=None, c_0=None,
                  cell_clip=None, proj_clip=None):
    """LSTM with recurrent projection (reference nn.py:583 dynamic_lstmp):
    input [B, T, 4H] pre-projected; size = 4H; returns (projection [B,T,P],
    cell [B,H])."""
    helper = LayerHelper("dynamic_lstmp", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    hidden_dim = size // 4
    from ..param_attr import ParamAttr

    attrs = ParamAttr._to_attr(param_attr)
    if not isinstance(attrs, list):
        # one attr supplied (or none): the projection weight gets its own
        # derived name — reusing the attr verbatim would silently alias the
        # two differently-shaped parameters under one var name
        proj_attr = ParamAttr(
            name=(attrs.name + "_proj") if attrs.name else None,
            initializer=attrs.initializer,
            learning_rate=attrs.learning_rate,
            regularizer=attrs.regularizer, trainable=attrs.trainable)
        attrs = [attrs, proj_attr]
    elif len(attrs) != 2:
        raise ValueError("dynamic_lstmp takes 1 or 2 param_attr entries "
                         "(Weight, ProjWeight)")
    w = helper.create_parameter(attrs[0],
                                shape=[proj_size, 4 * hidden_dim],
                                dtype=dtype)
    w_proj = helper.create_parameter(attrs[1],
                                     shape=[hidden_dim, proj_size],
                                     dtype=dtype)
    bias_size = 7 * hidden_dim if use_peepholes else 4 * hidden_dim
    b = helper.create_parameter(helper.bias_attr, shape=[1, bias_size],
                                dtype=dtype, is_bias=True)
    proj = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    _make_lod_out(helper, proj)
    ins = {"Input": input, "Weight": w, "ProjWeight": w_proj, "Bias": b,
           "SeqLen": seq_len_var(input)}
    if h_0 is not None:
        ins["H0"] = h_0
    if c_0 is not None:
        ins["C0"] = c_0
    helper.append_op("lstmp", inputs=ins,
                     outputs={"Projection": proj, "Cell": cell},
                     attrs={"use_peepholes": use_peepholes,
                            "is_reverse": is_reverse,
                            "gate_activation": gate_activation,
                            "cell_activation": cell_activation,
                            "candidate_activation": candidate_activation,
                            "proj_activation": proj_activation,
                            "cell_clip": float(cell_clip or 0.0),
                            "proj_clip": float(proj_clip or 0.0)})
    helper.append_op("assign", inputs={"X": seq_len_var(input)},
                     outputs={"Out": helper.block.var(
                         proj.name + lod_suffix)})
    return proj, cell


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, origin_mode=False,
                dtype="float32", name=None):
    """input: [B, T, 3H] pre-projected; size = H."""
    helper = LayerHelper("dynamic_gru", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    w = helper.create_parameter(helper.param_attr, shape=[size, 3 * size],
                                dtype=dtype)
    b = helper.create_parameter(helper.bias_attr, shape=[1, 3 * size],
                                dtype=dtype, is_bias=True)
    hidden = helper.create_variable_for_type_inference(dtype)
    lod = _make_lod_out(helper, hidden)
    ins = {"Input": input, "Weight": w, "Bias": b,
           "SeqLen": seq_len_var(input)}
    if h_0 is not None:
        ins["H0"] = h_0
    helper.append_op("gru", inputs=ins, outputs={"Hidden": hidden},
                     attrs={"is_reverse": is_reverse,
                            "origin_mode": origin_mode,
                            "gate_activation": gate_activation,
                            "activation": candidate_activation})
    helper.append_op("assign", inputs={"X": seq_len_var(input)},
                     outputs={"Out": lod})
    return hidden


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid",
             origin_mode=False, dtype="float32", name=None):
    """One step; size = 3H (reference nn.py gru_unit contract)."""
    helper = LayerHelper("gru_unit", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    h = size // 3
    w = helper.create_parameter(helper.param_attr, shape=[h, 3 * h],
                                dtype=dtype)
    b = helper.create_parameter(helper.bias_attr, shape=[1, 3 * h],
                                dtype=dtype, is_bias=True)
    gate = helper.create_variable_for_type_inference(dtype)
    reset_h = helper.create_variable_for_type_inference(dtype)
    updated = helper.create_variable_for_type_inference(dtype)
    ins = {"Input": input, "HiddenPrev": hidden, "Weight": w, "Bias": b}
    helper.append_op("gru_unit", inputs=ins,
                     outputs={"Gate": gate, "ResetHiddenPrev": reset_h,
                              "Hidden": updated},
                     attrs={"activation": activation,
                            "gate_activation": gate_activation,
                            "origin_mode": origin_mode})
    return updated, reset_h, gate


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    """Multi-layer LSTM (reference fluid.layers.lstm -> cudnn_lstm).
    input: [B, T, D] with a lengths companion. is_bidirec unsupported."""
    if is_bidirec:
        raise NotImplementedError("bidirectional cudnn_lstm: use two "
                                  "dynamic_lstm passes (is_reverse=True)")
    helper = LayerHelper("lstm", name=name)
    in_dim = int(input.shape[-1])
    n = 0
    for layer in range(num_layers):
        d = in_dim if layer == 0 else hidden_size
        n += 4 * hidden_size * d + 4 * hidden_size * hidden_size \
            + 8 * hidden_size
    w = helper.create_parameter(None, shape=[n], dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    last_h = helper.create_variable_for_type_inference(input.dtype)
    last_c = helper.create_variable_for_type_inference(input.dtype)
    lod = _make_lod_out(helper, out)
    ins = {"Input": input, "W": w, "SeqLen": seq_len_var(input)}
    if init_h is not None:
        ins["InitH"] = init_h
    if init_c is not None:
        ins["InitC"] = init_c
    helper.append_op("cudnn_lstm", inputs=ins,
                     outputs={"Out": out, "LastH": last_h, "LastC": last_c},
                     attrs={"hidden_size": int(hidden_size),
                            "num_layers": int(num_layers),
                            "dropout_prob": float(dropout_prob),
                            "is_test": is_test})
    helper.append_op("assign", inputs={"X": seq_len_var(input)},
                     outputs={"Out": lod})
    return out, last_h, last_c


def warpctc(input, label, blank=0, norm_by_times=False, input_length=None,
            label_length=None):
    """CTC loss. input: [B, T, C] logits; label: [B, L] padded int ids.
    Lengths come from explicit args or the @LOD companions."""
    helper = LayerHelper("warpctc")
    loss = helper.create_variable_for_type_inference(input.dtype)
    in_len = input_length if input_length is not None else seq_len_var(input)
    lb_len = label_length if label_length is not None else seq_len_var(label)
    helper.append_op("warpctc",
                     inputs={"Logits": input, "Label": label,
                             "LogitsLength": in_len, "LabelLength": lb_len},
                     outputs={"Loss": loss},
                     attrs={"blank": int(blank),
                            "norm_by_times": norm_by_times})
    return loss


class DynamicRNN:
    """reference layers/control_flow.py DynamicRNN: step over a LoD input.

    Padded-encoding mapping: the loop is StaticRNN (one lax.scan) over the
    padded time axis; per-step outputs are re-masked by the sequence
    lengths, so every VALID position equals the reference's packed
    computation (invalid steps never feed back into valid ones — step t
    only consumes memory from t-1). Usage mirrors the reference:

        drnn = layers.DynamicRNN()
        with drnn.block():
            w = drnn.step_input(emb)         # [B, T, E] lod_level-1
            prev = drnn.memory(shape=[H])
            h = layers.fc(layers.concat([w, prev], 1), H, act="tanh")
            drnn.update_memory(prev, h)
            drnn.output(h)
        hidden = drnn()                      # [B, T, H] + lengths companion
    """

    def __init__(self, name=None):
        from . import control_flow as _cf

        self._rnn = _cf.StaticRNN(name=name)
        self._lod_source = None

    def block(self):
        return self._rnn.step()

    def step_input(self, x, level=0):
        from . import nn as _nn

        if self._lod_source is None:
            self._lod_source = x
        # StaticRNN wants time-major; build the transpose OUTSIDE the block
        program = x.block.program
        cur = program.current_block_idx
        program.current_block_idx = self._rnn._parent.idx
        try:
            tm = _nn.transpose(x, [1, 0] + list(range(2, len(x.shape))))
        finally:
            program.current_block_idx = cur
        return self._rnn.step_input(tm)

    def memory(self, init=None, shape=None, value=0.0, need_reorder=False,
               dtype="float32"):
        if init is not None:
            return self._rnn.memory(init=init)
        if self._lod_source is None:
            raise ValueError("call step_input before memory(shape=...) so "
                             "the batch size is known (reference order)")
        batch_ref = self._tm_of_source()
        return self._rnn.memory(shape=shape, batch_ref=batch_ref,
                                init_value=value, dtype=dtype)

    def _tm_of_source(self):
        # the first step input is time-major with the right batch dim
        src_name = self._rnn._step_inputs[0][0]
        return self._rnn._parent._var_recursive(src_name)

    def update_memory(self, mem, new):
        self._rnn.update_memory(mem, new)

    def output(self, *outs):
        self._rnn.output(*outs)

    def __call__(self):
        from . import nn as _nn
        from .sequence import seq_len_var, sequence_unpad

        outs_tm = self._rnn()
        outs_tm = outs_tm if isinstance(outs_tm, list) else [outs_tm]
        ln = seq_len_var(self._lod_source)
        results = []
        for o in outs_tm:
            bm = _nn.transpose(o, [1, 0] + list(range(2, len(o.shape))))
            results.append(sequence_unpad(bm, ln))  # mask + @LOD companion
        return results[0] if len(results) == 1 else results


__all__.append("DynamicRNN")
