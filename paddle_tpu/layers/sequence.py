"""fluid.layers sequence functions on the padded + lengths LoD encoding.

Reference: python/paddle/fluid/layers/sequence_lod ops inside nn.py
(sequence_pool :2470, sequence_softmax, sequence_expand :4885, sequence_pad,
sequence_conv :2277, ...) over packed LoDTensors.

Encoding contract (SURVEY §5 plan): a lod_level>=1 variable ``x`` is padded
``[batch, max_len, ...]`` and its per-sequence lengths live in the companion
variable ``<x.name>@LOD`` (int32 ``[batch]``), created by ``layers.data`` and
fed by the DataFeeder/DataLoader varlen path (which also buckets max_len to
bound the compile cache). Ops producing new sequences create the companion
for their outputs, so lengths flow through the graph like any other var.
"""
from __future__ import annotations

from ..framework import Variable
from ..layer_helper import LayerHelper

__all__ = ["sequence_pool", "sequence_softmax", "sequence_reverse",
           "sequence_expand", "sequence_concat", "sequence_pad",
           "sequence_unpad", "sequence_slice", "sequence_erase",
           "sequence_enumerate", "sequence_conv", "sequence_first_step",
           "sequence_last_step", "sequence_mask", "lod_suffix", "seq_len_var"]

lod_suffix = "@LOD"


def seq_len_var(x: Variable) -> Variable:
    """The companion lengths variable of a lod_level>=1 var. When ``x`` has
    no direct companion, lengths are inferred through the dataflow: ops like
    embedding/elementwise/activation preserve the time axis, so the producer
    chain is walked until a var with a companion is found (the reference
    propagates LoD in each op's InferShape; here it is derived on demand).

    When the producer graph reaches MORE THAN ONE distinct companion (an
    op mixing tensors from different sequences), the first in input order
    is used and a RuntimeWarning names all candidates — pass the intended
    sequence explicitly (produce the tensor with a sequence op, or declare
    the input with lod_level=1) to silence it."""
    block = x.block
    # one exhaustive walk serves both purposes: found[0] is exactly what
    # the old short-circuiting walk returned (same DFS order), the rest
    # detects ambiguity
    all_names: list = []
    _collect_lod_names(block, x.name, set(), all_names)
    if not all_names:
        raise ValueError(
            f"'{x.name}' has no sequence lengths companion "
            f"'{x.name}{lod_suffix}' and none could be inferred from its "
            f"producers — declare the input with layers.data(..., "
            f"lod_level=1) or produce '{x.name}' with a sequence op")
    name = all_names[0]
    if len(set(all_names)) > 1:
        import warnings

        warnings.warn(
            f"seq_len_var('{x.name}'): multiple sequence-length companions"
            f" are reachable through its producers ({sorted(set(all_names))}"
            f"); using '{name}'. If that is the wrong sequence, pass "
            f"lengths explicitly.", RuntimeWarning, stacklevel=3)
    return block._var_recursive(name)


def _collect_lod_names(block, name, seen, found):
    """Producer-graph walk gathering EVERY reachable companion (DFS,
    input order): found[0] is the binding, the rest flag ambiguity."""
    if block.has_var_recursive(name + lod_suffix):
        found.append(name + lod_suffix)
        return
    if name in seen:
        return
    seen.add(name)
    for op in reversed(block.ops):
        if name in op.output_arg_names:
            for n in op.input_arg_names:
                if n != name and n != "@EMPTY@":
                    _collect_lod_names(block, n, seen, found)
            return


def _make_lod_out(helper: LayerHelper, out: Variable) -> Variable:
    lod = helper.block.create_var(name=out.name + lod_suffix, shape=(-1,),
                                  dtype="int32", stop_gradient=True)
    out.lod_level = 1
    return lod


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0):
    helper = LayerHelper("sequence_pool")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("sequence_pool",
                     inputs={"X": input, "SeqLen": seq_len_var(input)},
                     outputs={"Out": out},
                     attrs={"pooltype": pool_type.upper(),
                            "pad_value": float(pad_value)})
    return out


def sequence_first_step(input):
    return sequence_pool(input, "first")


def sequence_last_step(input):
    return sequence_pool(input, "last")


def sequence_softmax(input, use_cudnn=False, name=None):
    helper = LayerHelper("sequence_softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    lod = _make_lod_out(helper, out)
    helper.append_op("sequence_softmax",
                     inputs={"X": input, "SeqLen": seq_len_var(input)},
                     outputs={"Out": out})
    helper.append_op("assign", inputs={"X": seq_len_var(input)},
                     outputs={"Out": lod})
    return out


def sequence_reverse(x, name=None):
    helper = LayerHelper("sequence_reverse", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    lod = _make_lod_out(helper, out)
    helper.append_op("sequence_reverse",
                     inputs={"X": x, "SeqLen": seq_len_var(x)},
                     outputs={"Y": out})
    helper.append_op("assign", inputs={"X": seq_len_var(x)},
                     outputs={"Out": lod})
    return out


def sequence_expand(x, y, ref_level=-1, name=None):
    helper = LayerHelper("sequence_expand", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    lod = _make_lod_out(helper, out)
    helper.append_op("sequence_expand",
                     inputs={"X": x, "Y": y, "SeqLen": seq_len_var(y)},
                     outputs={"Out": out}, attrs={"ref_level": ref_level})
    helper.append_op("assign", inputs={"X": seq_len_var(y)},
                     outputs={"Out": lod})
    return out


def sequence_concat(input, name=None):
    helper = LayerHelper("sequence_concat", name=name)
    out = helper.create_variable_for_type_inference(input[0].dtype)
    lod = _make_lod_out(helper, out)
    helper.append_op("sequence_concat",
                     inputs={"X": input,
                             "SeqLen": [seq_len_var(v) for v in input]},
                     outputs={"Out": out, "OutLen": lod})
    return out


def sequence_pad(x, pad_value, maxlen=None, name=None):
    helper = LayerHelper("sequence_pad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    length = helper.create_variable_for_type_inference("int32",
                                                       stop_gradient=True)
    helper.append_op("sequence_pad",
                     inputs={"X": x, "SeqLen": seq_len_var(x),
                             "PadValue": pad_value},
                     outputs={"Out": out, "Length": length},
                     attrs={"padded_length": int(maxlen or -1)})
    return out, length


def sequence_unpad(x, length, name=None):
    helper = LayerHelper("sequence_unpad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    lod = _make_lod_out(helper, out)
    helper.append_op("sequence_unpad",
                     inputs={"X": x, "Length": length},
                     outputs={"Out": out, "OutLen": lod})
    return out


def sequence_slice(input, offset, length, name=None):
    helper = LayerHelper("sequence_slice", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    lod = _make_lod_out(helper, out)
    helper.append_op("sequence_slice",
                     inputs={"X": input, "SeqLen": seq_len_var(input),
                             "Offset": offset, "Length": length},
                     outputs={"Out": out, "OutLen": lod})
    return out


def sequence_erase(input, tokens, name=None):
    helper = LayerHelper("sequence_erase", name=name)
    out = helper.create_variable_for_type_inference(input.dtype,
                                                    stop_gradient=True)
    lod = _make_lod_out(helper, out)
    helper.append_op("sequence_erase",
                     inputs={"X": input, "SeqLen": seq_len_var(input)},
                     outputs={"Out": out, "OutLen": lod},
                     attrs={"tokens": list(tokens)})
    return out


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    helper = LayerHelper("sequence_enumerate", name=name)
    out = helper.create_variable_for_type_inference(input.dtype,
                                                    stop_gradient=True)
    lod = _make_lod_out(helper, out)
    helper.append_op("sequence_enumerate",
                     inputs={"X": input, "SeqLen": seq_len_var(input)},
                     outputs={"Out": out},
                     attrs={"win_size": int(win_size),
                            "pad_value": int(pad_value)})
    helper.append_op("assign", inputs={"X": seq_len_var(input)},
                     outputs={"Out": lod})
    return out


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, name=None):
    """reference nn.py:2277 sequence_conv: context-window projection."""
    helper = LayerHelper("sequence_conv", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    feat = int(input.shape[-1])
    w = helper.create_parameter(helper.param_attr,
                                shape=[filter_size * feat, num_filters],
                                dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    lod = _make_lod_out(helper, out)
    start = (-(filter_size // 2) if padding_start is None
             else int(padding_start))
    helper.append_op("sequence_conv",
                     inputs={"X": input, "Filter": w,
                             "SeqLen": seq_len_var(input)},
                     outputs={"Out": out},
                     attrs={"contextLength": int(filter_size),
                            "contextStart": start,
                            "contextStride": int(filter_stride)})
    helper.append_op("assign", inputs={"X": seq_len_var(input)},
                     outputs={"Out": lod})
    out = helper.append_bias_op(out, dim_start=2)
    out = helper.append_activation(out)
    # bias/activation un-zero the padded rows (act(bias) != 0); re-mask so
    # the module's zero-padding contract holds for non-length-aware consumers
    masked = helper.create_variable_for_type_inference(out.dtype)
    mlod = helper.block.create_var(name=masked.name + lod_suffix, shape=(-1,),
                                   dtype="int32", stop_gradient=True)
    masked.lod_level = 1
    helper.append_op("sequence_unpad",
                     inputs={"X": out, "Length": seq_len_var(input)},
                     outputs={"Out": masked, "OutLen": mlod})
    return masked


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    if maxlen is None or int(maxlen) <= 0:
        raise ValueError(
            "sequence_mask on TPU needs a static maxlen (XLA static shapes);"
            " the reference's dynamic max-length default has no encoding")
    helper = LayerHelper("sequence_mask", name=name)
    out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op("sequence_mask", inputs={"X": x}, outputs={"Y": out},
                     attrs={"maxlen": int(maxlen or -1), "out_dtype": dtype})
    return out


def sequence_reshape(input, new_dim):
    """reference nn.py sequence_reshape: redistribute timesteps so the
    feature dim becomes new_dim; lengths scale by old_dim/new_dim."""
    helper = LayerHelper("sequence_reshape")
    out = helper.create_variable_for_type_inference(input.dtype)
    lod = _make_lod_out(helper, out)
    helper.append_op("sequence_reshape",
                     inputs={"X": input, "SeqLen": seq_len_var(input)},
                     outputs={"Out": out, "OutLen": lod},
                     attrs={"new_dim": int(new_dim)})
    return out


def sequence_expand_as(x, y, name=None):
    """reference nn.py sequence_expand_as: row i of x fills sequence i of
    y (padded encoding: broadcast over y's time axis, masked by lengths)."""
    helper = LayerHelper("sequence_expand_as", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    lod = _make_lod_out(helper, out)
    helper.append_op("sequence_expand_as",
                     inputs={"X": x, "Y": y, "SeqLen": seq_len_var(y)},
                     outputs={"Out": out})
    helper.append_op("assign", inputs={"X": seq_len_var(y)},
                     outputs={"Out": lod})
    return out


def sequence_scatter(input, index, updates, name=None):
    helper = LayerHelper("sequence_scatter", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("sequence_scatter",
                     inputs={"X": input, "Ids": index, "Updates": updates,
                             "SeqLen": seq_len_var(index)},
                     outputs={"Out": out})
    return out


def lod_reset(x, y=None, target_lod=None):
    """reference nn.py lod_reset: re-bind x's sequence lengths. With the
    padded+lengths encoding this is a companion-var rebind: lengths come
    from y's companion (or y itself when y is int32 [batch]) or from the
    static target_lod offsets."""
    helper = LayerHelper("lod_reset")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("assign", inputs={"X": x}, outputs={"Out": out})
    lod = _make_lod_out(helper, out)
    if y is not None:
        src = y if getattr(y, "lod_level", 0) == 0 and \
            str(y.dtype).startswith("int") else seq_len_var(y)
        helper.append_op("assign", inputs={"X": src}, outputs={"Out": lod})
    elif target_lod is not None:
        lens = [int(b) - int(a) for a, b in
                zip(target_lod[:-1], target_lod[1:])]
        helper.append_op("assign_value", outputs={"Out": lod},
                         attrs={"shape": [len(lens)], "dtype": "int32",
                                "values": [float(v) for v in lens]})
    else:
        raise ValueError("lod_reset needs y or target_lod")
    return out


def lod_append(x, level):
    raise NotImplementedError(
        "lod_append: the padded+lengths encoding carries ONE sequence "
        "level (layers/sequence.py module docstring); nested levels "
        "flatten at the data layer — reshape the batch instead")


__all__ += ["sequence_reshape", "sequence_expand_as", "sequence_scatter",
            "lod_reset", "lod_append"]
